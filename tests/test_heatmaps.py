"""rw-heatmaps analog: mixed read/write sweep + CSV in the reference
plotter's schema + text heatmap rendering (tools/rw-heatmaps)."""
import pytest

from etcd_tpu import heatmaps
from etcd_tpu.server.kvserver import EtcdCluster


@pytest.fixture(scope="module")
def ec():
    c = EtcdCluster(n_members=3)
    c.ensure_leader()
    return c


@pytest.fixture(scope="module")
def rows(ec):
    return heatmaps.run_sweep(
        ec, ratios=(0.5, 2.0), value_sizes=(64,), conn_counts=(2,),
        repeats=2, ops=8)


def test_sweep_shape(rows):
    assert len(rows) == 2  # 2 ratios x 1 conn x 1 value size
    for r in rows:
        assert len(r["iters"]) == 2
        for rd, wr in r["iters"]:
            assert rd >= 0 and wr > 0


def test_ratio_controls_mix(ec):
    """ratio=8 must do ~8x more reads than writes; ratio=1/8 inverted."""
    rows = heatmaps.run_sweep(ec, ratios=(8.0,), value_sizes=(64,),
                              conn_counts=(2,), ops=18)
    rd, wr = rows[0]["iters"][0]
    assert rd > wr * 4
    rows = heatmaps.run_sweep(ec, ratios=(0.125,), value_sizes=(64,),
                              conn_counts=(2,), ops=18)
    rd, wr = rows[0]["iters"][0]
    assert wr > rd * 4


def test_csv_schema(rows, tmp_path):
    path = str(tmp_path / "rw.csv")
    heatmaps.write_csv(rows, path, comment="test sweep")
    lines = open(path).read().strip().split("\n")
    hdr = lines[0].split(",")
    assert hdr[:4] == ["type", "ratio", "conn_size", "value_size"]
    assert "iter0" in hdr and "iter1" in hdr and hdr[-1] == "comment"
    assert lines[1].startswith("PARAM")
    assert "test sweep" in lines[1]
    data = [ln for ln in lines if ln.startswith("DATA")]
    assert len(data) == len(rows)
    # iter cells are read:write pairs, the plot_data.py contract
    cell = data[0].split(",")[4]
    rd, wr = cell.split(":")
    float(rd), float(wr)


def test_render_ascii(rows):
    txt = heatmaps.render_ascii(rows, "read")
    assert "value_size=64" in txt
    assert "ratio\\conn" in txt
    txt_w = heatmaps.render_ascii(rows, "write")
    assert txt != txt_w


def test_cli(tmp_path, capsys, monkeypatch):
    out = str(tmp_path / "cli.csv")
    rc = heatmaps.main(["--output", out, "--ops", "6", "--members", "3",
                        "--ratios", "2", "--value-sizes", "64",
                        "--conns", "2"])
    assert rc == 0
    assert open(out).readline().startswith("type,")
    assert "cells" in capsys.readouterr().out
