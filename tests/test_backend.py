"""Durable backend + member restart tests.

Covers the bbolt-analog contract (etcd_tpu/storage/backend.py:
batched transactional appends, torn-tail recovery, defrag) and the
WAL+backend member restart path with consistent-index dedup
(VERDICT item 7; reference: server/storage/backend/backend.go:88-118,
cindex/cindex.go:30-38, server.go:1879-1885 skip-if-applied).
"""
import os

import pytest

from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.storage.backend import Backend
from etcd_tpu.storage import schema


# -- Backend contract --------------------------------------------------------
def test_backend_put_get_persist(tmp_path):
    p = str(tmp_path / "b.db")
    be = Backend(p, batch_limit=4)
    be.put("key", b"a", b"1")
    be.put("key", b"b", b"2")
    be.delete("key", b"a")
    be.commit()
    be.close()
    be2 = Backend(p)
    assert be2.get("key", b"a") is None
    assert be2.get("key", b"b") == b"2"
    assert be2.range("key", b"", b"\x00") == [(b"b", b"2")]


def test_backend_uncommitted_batch_lost(tmp_path):
    p = str(tmp_path / "b.db")
    be = Backend(p, batch_limit=1000)
    be.put("key", b"a", b"1")
    be.commit()
    be.put("key", b"b", b"2")  # stays in the batch buffer
    be._f.close()  # crash without commit
    be2 = Backend(p)
    assert be2.get("key", b"a") == b"1"
    assert be2.get("key", b"b") is None


def test_backend_torn_tail_truncated(tmp_path):
    p = str(tmp_path / "b.db")
    be = Backend(p, batch_limit=1)
    be.put("key", b"a", b"1")
    be.put("key", b"b", b"2")
    be.close()
    good = os.path.getsize(p)
    with open(p, "ab") as f:  # simulate a torn partial frame
        f.write(b"\x40\x00\x00\x00\x0bgarbage")
    be2 = Backend(p)
    assert be2.get("key", b"a") == b"1" and be2.get("key", b"b") == b"2"
    assert os.path.getsize(p) == good  # tail truncated at the last frame


def test_backend_defrag_shrinks(tmp_path):
    p = str(tmp_path / "b.db")
    be = Backend(p, batch_limit=1)
    for i in range(50):
        be.put("key", b"k", b"v%d" % i)  # history accumulates
    size_before = be.size()
    be.defrag()
    assert be.size() < size_before
    assert be.get("key", b"k") == b"v49"
    be.put("key", b"k2", b"x")  # appends still work after defrag
    be.close()
    be2 = Backend(p)
    assert be2.get("key", b"k") == b"v49" and be2.get("key", b"k2") == b"x"


# -- member restart from disk ------------------------------------------------
@pytest.fixture(scope="module")
def served(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("fleet"))
    srv = EtcdCluster(n_members=3, data_dir=data_dir)
    srv.ensure_leader()
    for i in range(6):
        srv.put(b"k%d" % i, b"v%d" % i)
    return srv


def test_backend_tracks_applied_state(served):
    srv = served
    for m, ms in enumerate(srv.members):
        assert ms.backend is not None
        meta = schema.load_applied_meta(ms.backend)
        assert meta["consistent_index"] == ms.applied_index
        assert meta["current_rev"] == ms.store.kv.current_rev


def test_member_restart_from_disk(served):
    srv = served
    hash_before = srv.hash_kv(0)
    # follower 2's host process dies; its backend keeps only committed state
    srv.crash_member(2)
    # traffic continues while it is down
    for i in range(4):
        srv.put(b"down%d" % i, b"x%d" % i)
    # restart from disk: backend state + ring replay from consistent index
    srv.restart_member_from_disk(2)
    srv.stabilize()
    ms = srv.members[2]
    assert not ms.crashed
    assert ms.applied_index == srv.members[0].applied_index
    # hashKV agreement across all members at the same revision: replay
    # after restart deduplicated (no double-applied revisions)
    h0 = srv.hash_kv(0)
    assert srv.hash_kv(2) == h0
    assert srv.hash_kv(1) == h0
    assert h0 != hash_before  # traffic really advanced state
    # the restarted member serves reads with the new data
    resp = srv.range(b"down0", member=2, serializable=True)
    assert resp["kvs"] and resp["kvs"][0].value == b"x0"


def test_member_restart_sees_own_writes_only_to_cindex(served):
    """The atomic applied-meta record governs recovery: a member whose
    crash lost the uncommitted batch tail comes back at its consistent
    index and replays forward (no gaps, no duplicates)."""
    srv = served
    srv.put(b"tail", b"t1")
    # crash member 1 (pending batch beyond the last commit is dropped)
    srv.crash_member(1)
    srv.put(b"tail", b"t2")
    srv.restart_member_from_disk(1)
    srv.stabilize()
    h0, h1 = srv.hash_kv(0), srv.hash_kv(1)
    assert h0 == h1
    resp = srv.range(b"tail", member=1, serializable=True)
    assert resp["kvs"][0].value == b"t2"
    assert resp["kvs"][0].version == 2
