"""Level-2 auditors: trace/HLO contracts checked on the canonical programs.

Where :mod:`etcd_tpu.analysis.lint` reads source text, the auditors here
lower the registry's real entry programs (:mod:`etcd_tpu.analysis.programs`)
and assert the contracts the repo's performance story rests on:

  one_trace    the lowered program is BIT-IDENTICAL across runtime-operand
               value variants — fault probabilities, palettes and mode
               switches must be operands, not closure constants, or every
               mix pays its own multi-second trace (and a baked
               numpy-array constant shows up as a dense<...> literal in
               exactly one variant's StableHLO)
  donation     every fleet-scaled ([..., C]) carried argument is donated
               (or carries a written justification), no buffer sits at
               two donated positions (the PR-9 shared-zeros crash class),
               no donated buffer is passed live elsewhere unless
               allowlisted, and every donated leaf has a shape/dtype-
               compatible output slot to alias
  transfers    the compiled round body contains no host callbacks /
               infeed / outfeed, and the program returns exactly its
               declared output arity (the counted device-to-host bound)
  collectives  the steady-state sharded round's post-SPMD HLO contains
               ZERO cross-shard collectives — the machine check for
               MULTICHIP_SCALING_r05 (clusters are independent; only the
               invariant psum may cross the mesh, and it is not in the
               round program)
  widths       the packed-state bit widths, the i16 narrow-plane range
               class and the wire split registry cross-check against the
               durability tables in models/state.py

Auditors return :class:`etcd_tpu.analysis.lint.Finding` rows (path =
``<program-name>``), so the CLI reports both levels uniformly.

A note on cost: tracing is the expensive step (the full chaos epoch is
~12 s of single-core time even at probe shapes), so each program is
traced ONCE per operand set and the trace is shared by every auditor —
lowered text derives from the cached trace without retracing.
"""
from __future__ import annotations

import re
from typing import Iterable

from etcd_tpu.analysis.lint import Finding
from etcd_tpu.analysis.programs import (
    PROGRAM_NAMES,
    ProgramInstance,
    get_program,
)

__all__ = [
    "AUDITOR_NAMES", "TracedProgram", "jaxpr_fingerprint",
    "audit_one_trace", "audit_donation", "audit_transfers",
    "audit_collectives", "audit_widths", "run_audits", "run_preflight",
]

AUDITOR_NAMES = ("widths", "donation", "one_trace", "transfers",
                 "collectives")


# ---------------------------------------------------------------------------
# shared trace cache
# ---------------------------------------------------------------------------

class TracedProgram:
    """One program's traces, computed lazily and shared across auditors
    (label None = the base operand set)."""

    def __init__(self, prog: ProgramInstance):
        self.prog = prog
        self._traced: dict = {}

    def args(self, label: str | None):
        if label is None:
            return self.prog.base
        return dict(self.prog.variants)[label]

    def trace(self, label: str | None = None):
        if label not in self._traced:
            self._traced[label] = self.prog.jitted.trace(*self.args(label))
        return self._traced[label]

    def lowered_text(self, label: str | None = None) -> str:
        return self.trace(label).lower().as_text()


def _subjaxprs(v):
    if hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
        yield v.jaxpr               # ClosedJaxpr
    elif hasattr(v, "eqns"):
        yield v                     # bare Jaxpr
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _subjaxprs(x)


def jaxpr_fingerprint(closed) -> tuple:
    """Structural fingerprint of a (closed) jaxpr: the recursive
    primitive histogram. Cheap against multi-MB jaxpr text, and enough
    to localise a structure divergence to the primitives that changed."""
    counts: dict[str, int] = {}

    def walk(j):
        for eqn in j.eqns:
            counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub)

    walk(closed.jaxpr if hasattr(closed, "jaxpr") else closed)
    return tuple(sorted(counts.items()))


def _find(prog: ProgramInstance, rule: str, msg: str) -> Finding:
    return Finding(rule=rule, path=f"<{prog.name}>", line=0, message=msg)


# ---------------------------------------------------------------------------
# one-trace
# ---------------------------------------------------------------------------

def _first_diff(a: str, b: str) -> str:
    la, lb = a.splitlines(), b.splitlines()
    for i, (x, y) in enumerate(zip(la, lb)):
        if x != y:
            return (f"first divergence at lowered line {i + 1}: "
                    f"{x.strip()[:120]!r} vs {y.strip()[:120]!r}")
    return (f"lowered programs differ in length: "
            f"{len(la)} vs {len(lb)} lines")


def audit_one_trace(tp: TracedProgram) -> list[Finding]:
    """The lowered program must not depend on operand VALUES. Compares
    the jaxpr primitive histogram (fast, localises the divergence) and
    the full lowered StableHLO text (catches value leaks the histogram
    cannot — e.g. an operand baked to a ``dense<...>`` constant)."""
    prog = tp.prog
    out = []
    if len(prog.variants) < 2:
        out.append(_find(prog, "audit-one-trace",
                         "program declares fewer than 3 operand sets; "
                         "the one-trace contract cannot be checked"))
        return out
    base_fp = jaxpr_fingerprint(tp.trace().jaxpr)
    base_txt = tp.lowered_text()
    for label, _ in prog.variants:
        fp = jaxpr_fingerprint(tp.trace(label).jaxpr)
        if fp != base_fp:
            b, v = dict(base_fp), dict(fp)
            delta = sorted(k for k in set(b) | set(v)
                           if b.get(k, 0) != v.get(k, 0))
            out.append(_find(
                prog, "audit-one-trace",
                f"jaxpr structure diverged for variant {label!r}: "
                f"primitive counts changed for {delta[:8]}"))
            continue
        txt = tp.trace(label).lower().as_text()
        if txt != base_txt:
            out.append(_find(
                prog, "audit-one-trace",
                f"lowered program is not bit-identical for variant "
                f"{label!r} ({_first_diff(base_txt, txt)}) — an operand "
                f"value leaked into the trace"))
    return out


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

def _out_list(tr) -> list:
    """Top-level output elements of a Traced: out_info is the output
    pytree, which is a bare OutInfo (not a 1-tuple) for single-output
    programs."""
    info = tr.out_info
    return list(info) if isinstance(info, (tuple, list)) else [info]


def _tree_sig(tree):
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    return (str(treedef),
            tuple((tuple(l.shape), str(l.dtype)) for l in leaves))


def _leaf_pointers(argnum, tree):
    """(pointer, argnum, leaf-path) rows; leaves whose runtime does not
    expose a buffer pointer (sharded arrays, committed multi-device)
    are skipped — pointer identity is only meaningful single-device."""
    import jax

    rows = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        try:
            ptr = leaf.unsafe_buffer_pointer()
        except Exception:
            continue
        rows.append((ptr, argnum, jax.tree_util.keystr(path)))
    return rows


def audit_donation(tp: TracedProgram) -> list[Finding]:
    prog = tp.prog
    import jax

    out: list[Finding] = []
    tr = tp.trace()
    out_sigs = [_tree_sig(o) for o in _out_list(tr)]
    arg_sigs = [_tree_sig(a) for a in prog.base]
    carried = {i for i, s in enumerate(arg_sigs) if s in out_sigs}

    def fleet_scaled(arg) -> bool:
        return any(l.ndim >= 1 and l.shape[-1] == prog.C
                   for l in jax.tree.leaves(arg))

    # completeness: every fleet-scaled carry is donated or justified
    for i in sorted(carried):
        if i in prog.donate or not fleet_scaled(prog.base[i]):
            continue
        if i in prog.undonated_ok:
            continue
        out.append(_find(
            prog, "audit-donation",
            f"arg {i} is a fleet-scaled carry (trailing C={prog.C} "
            f"leaves, aval-identical output) but is not donated and "
            f"carries no justification — at fleet C this double-buffers "
            f"the resident state"))
    # a donated arg that is not carried has no output to alias into
    for i in prog.donate:
        if i not in carried:
            out.append(_find(
                prog, "audit-donation",
                f"arg {i} is donated but no output element matches its "
                f"structure — the donation can never alias and XLA will "
                f"warn (or reject) at runtime"))

    # double-donation (the PR-9 crash class) + donated-live aliases
    donated_rows = []
    for i in prog.donate:
        donated_rows += _leaf_pointers(i, prog.base[i])
    by_ptr: dict[int, list] = {}
    for ptr, argnum, path in donated_rows:
        by_ptr.setdefault(ptr, []).append((argnum, path))
    for ptr, sites in by_ptr.items():
        if len(sites) > 1:
            locs = ", ".join(f"arg {a}{p}" for a, p in sites)
            out.append(_find(
                prog, "audit-donation",
                f"one buffer sits at {len(sites)} donated positions "
                f"({locs}) — donating it twice aliases two live results "
                f"into one allocation"))
    live_rows = []
    for i, arg in enumerate(prog.base):
        if i not in prog.donate:
            live_rows += _leaf_pointers(i, arg)
    donated_ptrs = {ptr: (a, p) for ptr, a, p in donated_rows}
    flagged = set()
    for ptr, argnum, path in live_rows:
        hit = donated_ptrs.get(ptr)
        if hit is None:
            continue
        d_arg, d_path = hit
        key = (d_arg, argnum)
        if key in prog.live_alias_ok or key in flagged:
            continue
        flagged.add(key)
        out.append(_find(
            prog, "audit-donation",
            f"donated arg {d_arg}{d_path} shares a buffer with live "
            f"arg {argnum}{path} — the runtime may reuse the buffer "
            f"while the live operand still reads it (allowlist via "
            f"live_alias_ok with a reason if the backend tolerates it)"))

    # alias validity: every donated leaf needs a compatible output slot
    out_leaf_counts: dict[tuple, int] = {}
    for o in _out_list(tr):
        for l in jax.tree.leaves(o):
            k = (tuple(l.shape), str(l.dtype))
            out_leaf_counts[k] = out_leaf_counts.get(k, 0) + 1
    for i in prog.donate:
        for l in jax.tree.leaves(prog.base[i]):
            k = (tuple(l.shape), str(l.dtype))
            if out_leaf_counts.get(k, 0) > 0:
                out_leaf_counts[k] -= 1
            else:
                out.append(_find(
                    prog, "audit-donation",
                    f"donated leaf of arg {i} with shape/dtype "
                    f"{k} has no remaining output slot to alias — the "
                    f"donation is unusable"))
    return out


# ---------------------------------------------------------------------------
# transfers
# ---------------------------------------------------------------------------

_HOST_PRIMITIVES = frozenset({
    "debug_callback", "pure_callback", "io_callback", "host_callback",
    "outside_call", "infeed", "outfeed", "debug_print",
})
_CALLBACK_TARGET_RE = re.compile(r'call_target_name\s*=\s*"([^"]+)"')


def audit_transfers(tp: TracedProgram) -> list[Finding]:
    """No host round-trips inside the compiled round body, and the
    program returns exactly its declared output arity — the counted
    bound on what can cross device-to-host per call."""
    prog = tp.prog
    out = []
    tr = tp.trace()
    fp = dict(jaxpr_fingerprint(tr.jaxpr))
    for name in sorted(_HOST_PRIMITIVES & set(fp)):
        out.append(_find(
            prog, "audit-transfers",
            f"traced program contains host primitive {name!r} (x{fp[name]})"
            f" — a synchronous host round-trip inside the round body"))
    txt = tp.lowered_text()
    for m in _CALLBACK_TARGET_RE.finditer(txt):
        target = m.group(1)
        if "callback" in target or target.startswith("xla_python"):
            out.append(_find(
                prog, "audit-transfers",
                f"lowered program custom_call targets {target!r} — a "
                f"host callback in the compiled body"))
    for op in ("stablehlo.infeed", "stablehlo.outfeed"):
        if op in txt:
            out.append(_find(prog, "audit-transfers",
                             f"lowered program contains {op}"))
    n_out = len(_out_list(tr))
    if n_out != prog.expected_outputs:
        out.append(_find(
            prog, "audit-transfers",
            f"program returns {n_out} top-level results, declared bound "
            f"is {prog.expected_outputs} — an undeclared result widens "
            f"the per-call device-to-host surface"))
    return out


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

_COLLECTIVE_RE = re.compile(
    r"=\s*\S*\s*(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)\b")
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(.*?)\}\s*[,)]")
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{(.*?)\}")


def _crosses_shards(line: str, op: str) -> bool:
    """Refine a collective-op match: only groups spanning >= 2 shards
    count as cross-shard traffic (XLA emits degenerate single-member
    groups for some rewrites)."""
    if op == "collective-permute":
        m = _SOURCE_TARGET_RE.search(line)
        return bool(m and m.group(1).strip())
    m = _REPLICA_GROUPS_RE.search(line)
    if not m:
        return True   # no groups attribute = one flat group over all shards
    body = m.group(1)
    groups = re.findall(r"\{([^{}]*)\}", body) or [body]
    return any(len([t for t in g.split(",") if t.strip()]) >= 2
               for g in groups)


def audit_collectives(tp: TracedProgram) -> list[Finding]:
    """Zero cross-shard collectives in the steady-state sharded round's
    post-SPMD HLO: clusters are independent, so any collective here is
    sharding-rule drift paying ICI/DCN bandwidth every round
    (MULTICHIP_SCALING_r05, machine-checked)."""
    prog = tp.prog
    if prog.mesh is None:
        return []
    out = []
    hlo = tp.trace().lower().compile().as_text()
    for line in hlo.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m and _crosses_shards(line, m.group(1)):
            out.append(_find(
                prog, "audit-collectives",
                f"cross-shard {m.group(1)} in the compiled round: "
                f"{line.strip()[:140]}"))
    return out


# ---------------------------------------------------------------------------
# widths
# ---------------------------------------------------------------------------

def audit_widths(spec=None, election_tick: int = 10, *,
                 durable=None, capped=None, replay=None, volatile=None,
                 wide_expected=("applied_hash", "snap_hash", "log_data"),
                 wire_split=None) -> list[Finding]:
    """Cross-check the packed-state plan and wire registries against the
    durability tables (models/state.py). The keyword overrides exist for
    the seeded-violation tests — production callers pass nothing and the
    real tables are audited."""
    from etcd_tpu.models import state as st
    from etcd_tpu.types import MSG_SNAP, Msg, Spec, WIRE_SPLIT

    spec = spec or Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    durable = tuple(durable if durable is not None else st.DURABLE_FIELDS)
    capped = tuple(capped if capped is not None else st.CAPPED_FIELDS)
    replay = tuple(replay if replay is not None else st.REPLAY_FIELDS)
    volatile = tuple(volatile if volatile is not None else st.VOLATILE_FIELDS)
    wire_split = wire_split if wire_split is not None else WIRE_SPLIT

    def find(msg):
        return Finding(rule="audit-widths", path="<state-tables>", line=0,
                       message=msg)

    out: list[Finding] = []
    fields = set(st.NodeState.__dataclass_fields__)
    tables = {"DURABLE": durable, "CAPPED": capped, "REPLAY": replay,
              "VOLATILE": volatile}
    seen: dict[str, str] = {}
    for tname, tbl in tables.items():
        for f in tbl:
            if f not in fields:
                out.append(find(f"{tname}_FIELDS names {f!r}, not a "
                                f"NodeState field"))
            if f in seen:
                out.append(find(f"{f!r} is classified both {seen[f]} and "
                                f"{tname} — the durability partition must "
                                f"be disjoint"))
            seen[f] = tname
    missing = fields - set(seen)
    if missing:
        out.append(find(f"NodeState fields with no durability class: "
                        f"{sorted(missing)} — a crash would silently "
                        f"preserve-or-wipe them by accident"))

    try:
        bit_rows, _n_lanes, narrow_rows, _n_narrow, wide_rows, _n_wide = \
            st.pack_plan(spec)
    except ValueError as e:
        out.append(find(f"pack_plan coverage check failed: {e}"))
        return out

    # id-valued rows must hold 0..M-1 (+bias for the NONE_ID shift)
    id_rows = {"nid", "lead", "vote", "lead_transferee", "ro_from",
               "ro_pend_from"}
    for name, bits, bias, _slots in bit_rows:
        if name in id_rows and spec.M - 1 + bias >= (1 << bits):
            out.append(find(
                f"packed row {name!r} has {bits} bits (bias {bias}) but "
                f"must store ids up to {spec.M - 1 + bias} at M={spec.M}"))
        if name in st._PACK_SATURATING and bits != st.PACK_TIMER_BITS:
            out.append(find(
                f"saturating timer {name!r} packs at {bits} bits, not "
                f"PACK_TIMER_BITS={st.PACK_TIMER_BITS}"))
    if 2 * election_tick >= (1 << st.PACK_TIMER_BITS):
        out.append(find(
            f"2*election_tick={2 * election_tick} does not fit the "
            f"{st.PACK_TIMER_BITS}-bit packed timer lane — the "
            f"randomized timeout draw in [T, 2T) would corrupt"))

    narrow_names = {r[0] for r in narrow_rows}
    bool_in_narrow = narrow_names & set(st._PACK_BOOL_FIELDS)
    if bool_in_narrow:
        out.append(find(
            f"bool fields {sorted(bool_in_narrow)} sit in the i16 narrow "
            f"plane — they belong in the bit plane (16x denser)"))

    wide_names = tuple(r[0] for r in wide_rows)
    if set(wide_names) != set(wide_expected):
        out.append(find(
            f"wide (full-i32) plane holds {sorted(wide_names)}, expected "
            f"{sorted(wide_expected)} — a field moved across the "
            f"int16-range contract boundary without review"))
    persistent = set(durable) | set(replay)
    for name in wide_names:
        if name not in persistent:
            out.append(find(
                f"wide field {name!r} is not DURABLE/REPLAY — full-width "
                f"volatile state contradicts the diet rationale"))

    msg_fields = set(Msg.__dataclass_fields__)
    for (f, t) in wire_split:
        if f not in msg_fields:
            out.append(find(f"WIRE_SPLIT names {f!r}, not a Msg field"))
    if ("commit", MSG_SNAP) not in wire_split:
        out.append(find(
            "WIRE_SPLIT lost ('commit', MSG_SNAP) — the MsgSnap applied "
            "hash would silently truncate on the int16 wire (the 81d0b1e "
            "bug class)"))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def run_audits(programs: Iterable[str] = PROGRAM_NAMES,
               auditors: Iterable[str] = AUDITOR_NAMES,
               progress=None) -> list[Finding]:
    """Run the selected auditors over the selected registry programs.
    `progress` (optional callable) receives one line per step."""
    auditors = tuple(auditors)
    say = progress or (lambda _msg: None)
    findings: list[Finding] = []
    if "widths" in auditors:
        say("audit: widths <state-tables>")
        findings += audit_widths()
    per_program = [a for a in ("donation", "one_trace", "transfers",
                               "collectives") if a in auditors]
    if not per_program:
        return findings
    for name in programs:
        say(f"audit: tracing {name}")
        tp = TracedProgram(get_program(name))
        for a in per_program:
            if a == "collectives" and tp.prog.mesh is None:
                continue
            say(f"audit: {a} {name}")
            findings += globals()[f"audit_{a}"](tp)
    return findings


def run_preflight(prog: ProgramInstance, progress=None) -> list[Finding]:
    """Driver preflight (bench/chaos_run --preflight): donation and
    one-trace auditors over the exact program the driver is about to
    execute, at probe operand shapes."""
    say = progress or (lambda _msg: None)
    tp = TracedProgram(prog)
    say(f"preflight: donation {prog.name}")
    findings = audit_donation(tp)
    say(f"preflight: one-trace {prog.name} "
        f"({1 + len(prog.variants)} operand sets)")
    findings += audit_one_trace(tp)
    return findings
