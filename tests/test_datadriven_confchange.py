"""Replay the reference's confchange golden files against the host Changer.

Source: raft/confchange/testdata/*.txt via confchange/datadriven_test.go.
Commands: simple / enter-joint [autoleave=] / leave-joint, with input tokens
vN/lN/rN/uN. Expected output's first line encodes the resulting config
("voters=(1 2 3)&&(1) autoleave learners=(4) learners_next=(5)") or an error
message; we compare the parsed sets and exact error strings. The per-id
Progress lines (match/next) track the reference's probe bootstrapping
cursor, which the device engine derives from next_idx directly — skipped.
"""
import re

import pytest

from etcd_tpu.harness import datadriven as dd
from etcd_tpu.models.changer import Changer, Config, ConfChangeError
from etcd_tpu.types import CC_ADD_LEARNER, CC_ADD_NODE, CC_REMOVE_NODE, CC_UPDATE_NODE

pytestmark = pytest.mark.skipif(
    not dd.reference_available(), reason="reference testdata not mounted"
)

FILES = [
    "joint_autoleave.txt",
    "joint_idempotency.txt",
    "joint_learners_next.txt",
    "joint_safety.txt",
    "simple_idempotency.txt",
    "simple_promote_demote.txt",
    "simple_safety.txt",
    "update.txt",
    "zero.txt",
]

_OPS = {"v": CC_ADD_NODE, "l": CC_ADD_LEARNER, "r": CC_REMOVE_NODE, "u": CC_UPDATE_NODE}


def parse_ccs(input_lines):
    toks = " ".join(input_lines).split()
    return [(_OPS[t[0]], int(t[1:])) for t in toks]


def parse_expected_config(line):
    """voters=(1 2 3)&&(4 5) [learners=(..)] [autoleave] [learners_next=(..)]"""
    m = re.match(r"voters=\(([\d ]*)\)(?:&&\(([\d ]*)\))?", line)
    if not m:
        return None
    ids = lambda s: set(int(x) for x in s.split()) if s else set()
    voters = ids(m.group(1))
    outgoing = ids(m.group(2)) if m.group(2) is not None else set()
    lm = re.search(r"learners=\(([\d ]*)\)", line)
    lnm = re.search(r"learners_next=\(([\d ]*)\)", line)
    return {
        "voters": voters,
        "outgoing": outgoing,
        "learners": ids(lm.group(1)) if lm else set(),
        "learners_next": ids(lnm.group(1)) if lnm else set(),
        "auto_leave": " autoleave" in line or line.endswith("autoleave"),
    }


@pytest.mark.parametrize("fname", FILES)
def test_confchange_goldens(fname):
    cases = dd.parse_file(dd.testdata("confchange", "testdata", fname))
    assert cases, fname
    cfg = Config()
    for case in cases:
        where = f"{fname}:{case.line}"
        try:
            ccs = parse_ccs(case.input)
        except (KeyError, ValueError):
            continue  # "unknown input" probe cases
        ch = Changer(cfg)
        err = None
        try:
            if case.cmd == "simple":
                new = ch.simple(ccs)
            elif case.cmd == "enter-joint":
                auto = case.args.get("autoleave", ["false"])[0] == "true"
                new = ch.enter_joint(auto, ccs)
            elif case.cmd == "leave-joint":
                new = ch.leave_joint()
            else:
                continue
        except ConfChangeError as e:
            err = str(e)
        first = case.expected[0].strip() if case.expected else ""
        want = parse_expected_config(first)
        if want is None:
            # golden expects an error
            assert err is not None, f"{where}: expected error {first!r}, got success"
            assert err == first, f"{where}: error mismatch: {err!r} != {first!r}"
            continue
        assert err is None, f"{where}: unexpected error {err!r}"
        cfg = new
        assert cfg.voters == want["voters"], where
        assert cfg.voters_outgoing == want["outgoing"], where
        assert cfg.learners == want["learners"], where
        assert cfg.learners_next == want["learners_next"], where
        assert cfg.auto_leave == want["auto_leave"], where


def test_restore_roundtrip():
    """Restore (confchange/restore.go) rebuilds the doc-comment example:
    voters=(1 2 3) learners=(5) outgoing=(1 2 4 6) learners_next=(4)."""
    import dataclasses

    @dataclasses.dataclass
    class CS:
        voters: list
        voters_outgoing: list
        learners: list
        learners_next: list
        auto_leave: bool

    from etcd_tpu.models.changer import restore

    cfg = restore(CS([1, 2, 3], [1, 2, 4, 6], [5], [4], True))
    assert cfg.voters == {1, 2, 3}
    assert cfg.voters_outgoing == {1, 2, 4, 6}
    assert cfg.learners == {5}
    assert cfg.learners_next == {4}
    assert cfg.auto_leave is True

    cfg = restore(CS([1, 2, 3], [], [4], [], False))
    assert cfg.voters == {1, 2, 3}
    assert cfg.learners == {4}
    assert not cfg.joint
