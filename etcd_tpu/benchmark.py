"""Benchmark CLI: the tools/benchmark analog.

The reference ships a cobra load generator (tools/benchmark/cmd: put,
range, txn-put, txn-mixed, lease, watch, watch-latency, ...) reporting
latency histograms and throughput via pkg/report. This drives the same
workloads over the v3 JSON/HTTP wire against any endpoint (a live
etcd_tpu.etcdmain process or the reference's gateway) and prints a
pkg/report-style summary.

Usage:
    python -m etcd_tpu.benchmark --endpoint http://127.0.0.1:2379 \
        put --total 1000 --key-size 8 --val-size 32
    python -m etcd_tpu.benchmark range --total 500 --serializable
    python -m etcd_tpu.benchmark txn-put --total 200
    python -m etcd_tpu.benchmark watch-latency --total 100
"""
from __future__ import annotations

import argparse
import base64
import json
import math
import os
import sys
import time
import urllib.request


def b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class Wire:
    def __init__(self, endpoint: str):
        self.endpoint = endpoint.rstrip("/")

    def call(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.endpoint + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())


class Report:
    """pkg/report analog: latency summary + histogram."""

    def __init__(self):
        self.lat: list[float] = []

    def add(self, seconds: float) -> None:
        self.lat.append(seconds)

    def render(self, total_s: float) -> str:
        n = len(self.lat)
        if not n:
            return "no samples"
        lat = sorted(self.lat)
        pct = lambda p: lat[min(n - 1, int(math.ceil(p * n)) - 1)] * 1000
        lines = [
            "",
            "Summary:",
            f"  Total:\t{total_s:.4f} secs.",
            f"  Slowest:\t{lat[-1] * 1000:.4f} ms.",
            f"  Fastest:\t{lat[0] * 1000:.4f} ms.",
            f"  Average:\t{sum(lat) / n * 1000:.4f} ms.",
            f"  Requests/sec:\t{n / total_s:.4f}",
            "",
            "Latency distribution:",
        ]
        for p in (0.10, 0.25, 0.50, 0.75, 0.90, 0.95, 0.99):
            lines.append(f"  {int(p * 100)}% in {pct(p):.4f} ms.")
        # coarse histogram (pkg/report prints one too)
        lo, hi = lat[0], lat[-1]
        buckets = 8
        width = (hi - lo) / buckets or 1e-9
        counts = [0] * buckets
        for v in lat:
            counts[min(buckets - 1, int((v - lo) / width))] += 1
        lines.append("")
        lines.append("Response time histogram:")
        peak = max(counts)
        for i, c in enumerate(counts):
            bar = "|" + "-" * int(40 * c / peak) if peak else "|"
            lines.append(f"  {(lo + i * width) * 1000:8.4f} ms [{c}]\t{bar}")
        return "\n".join(lines)


def _timed(rep: Report, fn) -> None:
    t0 = time.perf_counter()
    fn()
    rep.add(time.perf_counter() - t0)


def run_put(w: Wire, args) -> Report:
    rep = Report()
    for i in range(args.total):
        key = os.urandom(max(args.key_size // 2, 1)).hex().encode()
        val = b"v" * args.val_size
        _timed(rep, lambda: w.call(
            "/v3/kv/put", {"key": b64(b"bench/" + key), "value": b64(val)}
        ))
    return rep


def run_range(w: Wire, args) -> Report:
    w.call("/v3/kv/put", {"key": b64(b"bench/r"), "value": b64(b"x")})
    rep = Report()
    body = {"key": b64(b"bench/r")}
    if args.serializable:
        body["serializable"] = True
    for _ in range(args.total):
        _timed(rep, lambda: w.call("/v3/kv/range", dict(body)))
    return rep


def run_txn_put(w: Wire, args) -> Report:
    rep = Report()
    for i in range(args.total):
        key = b64(b"bench/t%d" % (i % 64))
        body = {
            "compare": [],
            "success": [{"request_put": {"key": key,
                                         "value": b64(b"v" * args.val_size)}}],
            "failure": [],
        }
        _timed(rep, lambda: w.call("/v3/kv/txn", body))
    return rep


def run_watch_latency(w: Wire, args) -> Report:
    """Time from put to the event arriving at a watcher
    (tools/benchmark/cmd/watch_latency.go)."""
    res = w.call("/v3/watch", {"create_request": {"key": b64(b"bench/w")}})
    wid = res["watch_id"]
    rep = Report()
    for i in range(args.total):
        t0 = time.perf_counter()
        w.call("/v3/kv/put", {"key": b64(b"bench/w"),
                              "value": b64(b"%d" % i)})
        while True:
            evs = w.call("/v3/watch",
                         {"poll_request": {"watch_id": wid}})["events"]
            if evs:
                break
        rep.add(time.perf_counter() - t0)
    w.call("/v3/watch", {"cancel_request": {"watch_id": wid}})
    return rep


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="benchmark-tpu")
    p.add_argument("--endpoint", default="http://127.0.0.1:2379")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("put", "range", "txn-put", "watch-latency"):
        s = sub.add_parser(name)
        s.add_argument("--total", type=int, default=100)
        s.add_argument("--key-size", type=int, default=8)
        s.add_argument("--val-size", type=int, default=32)
        if name == "range":
            s.add_argument("--serializable", action="store_true")
    args = p.parse_args(argv)
    w = Wire(args.endpoint)
    runner = {
        "put": run_put, "range": run_range, "txn-put": run_txn_put,
        "watch-latency": run_watch_latency,
    }[args.cmd]
    t0 = time.perf_counter()
    rep = runner(w, args)
    print(rep.render(time.perf_counter() - t0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
