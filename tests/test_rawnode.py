"""RawNode Ready/Advance contract tests — transliterations of the key
cases in raft/rawnode_test.go (Step guards, propose + conf change,
Start/Restart Ready sequences, read index, snapshot restart), driven
against the device-lane kernels.
"""
import pytest

from etcd_tpu.models.rawnode import (
    DeviceLaneStorage,
    ErrStepLocalMsg,
    ErrStepPeerNotFound,
    HostMsg,
    RawNode,
)
from etcd_tpu.models import confchange as ccmod
from etcd_tpu.storage.raftstorage import (
    ConfState,
    Entry,
    HardState,
    MemoryStorage,
    Snapshot,
    SnapshotMeta,
)
from etcd_tpu.types import (
    CC_ADD_NODE,
    ENTRY_CONF_CHANGE,
    MSG_APP_RESP,
    MSG_HEARTBEAT,
    MSG_HUP,
    MSG_PROP,
    MSG_READ_INDEX_RESP,
    NONE_ID,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

# one (cfg, spec) for the whole module so the lane kernels compile once
SPEC = Spec(M=8, L=64, E=16, K=8, W=8, R=4, A=8)
CFG = RaftConfig(election_tick=3, heartbeat_tick=1, max_inflight=8)


def boot(nid=0, voters=(0, 1, 2), index=2):
    s = MemoryStorage()
    s.apply_snapshot(
        Snapshot(
            meta=SnapshotMeta(
                index=index, term=1, conf_state=ConfState(voters=voters)
            )
        )
    )
    return RawNode(CFG, SPEC, s, nid, applied=index), s


def drive_to_leader(rn, s, peers=(1, 2)):
    """Campaign and fake the quorum of vote responses."""
    rn.campaign()
    rd = rn.ready()
    s.set_hard_state(rd.hard_state)
    rn.advance(rd)
    term = int(rn.n.term)
    for p in peers:
        rn.step(HostMsg(type=4, to=rn.nid, frm=p, term=term))  # MsgVoteResp
        if int(rn.n.role) == ROLE_LEADER:
            break
    assert int(rn.n.role) == ROLE_LEADER


# -- TestRawNodeStep ---------------------------------------------------------
def test_step_refuses_local_messages():
    rn, _ = boot()
    with pytest.raises(ErrStepLocalMsg):
        rn.step(HostMsg(type=MSG_HUP, to=0, frm=0))
    with pytest.raises(ErrStepLocalMsg):
        rn.step(HostMsg(type=MSG_PROP, to=0, frm=0))


def test_step_refuses_response_from_unknown_peer():
    rn, _ = boot(voters=(0, 1))
    # member 5 is not in the config: response messages bounce
    with pytest.raises(ErrStepPeerNotFound):
        rn.step(HostMsg(type=MSG_APP_RESP, to=0, frm=5, term=1))
    # non-response messages from unknown peers are fine (pre-config MsgApp)
    rn.step(HostMsg(type=MSG_HEARTBEAT, to=0, frm=5, term=1))


# -- TestRawNodeProposeAndConfChange (core variant) --------------------------
def test_propose_and_conf_change():
    rn, s = boot()
    drive_to_leader(rn, s)
    rd = rn.ready()  # leader's empty entry
    s.set_hard_state(rd.hard_state) if rd.hard_state else None
    s.append(rd.entries)
    rn.advance(rd)

    assert rn.propose(41)
    word = ccmod.encode([(CC_ADD_NODE, 3)])
    assert rn.propose_conf_change(word)
    # commit via acks from the quorum
    last = int(rn.n.last_index)
    term = int(rn.n.term)
    for p in (1, 2):
        rn.step(HostMsg(type=MSG_APP_RESP, to=0, frm=p, term=term, index=last))
    rd = rn.ready()
    s.set_hard_state(rd.hard_state) if rd.hard_state else None
    s.append(rd.entries)
    types = [e.type for e in rd.committed_entries]
    assert ENTRY_CONF_CHANGE in types
    rn.advance(rd)
    # the conf change took effect and was reported
    assert rn.last_conf_states, "conf switch not reported by Advance"
    assert 3 in rn.conf_state().voters
    # pending_conf_index guard cleared: a second conf change is accepted
    assert rn.propose_conf_change(ccmod.encode([(CC_ADD_NODE, 4)]))


# -- TestRawNodeStart --------------------------------------------------------
def test_ready_sequence_from_boot():
    rn, s = boot()
    assert not rn.has_ready()
    rn.campaign()
    assert rn.has_ready()
    rd = rn.ready()
    # campaign: hard state (term+vote) changed, must sync
    assert rd.must_sync and rd.hard_state.term == 1
    assert rd.soft_state is not None
    assert int(rd.hard_state.vote) == 0
    s.set_hard_state(rd.hard_state)
    rn.advance(rd)
    assert not rn.has_ready()


def test_commit_only_ready_is_not_sync():
    rn, s = boot()
    drive_to_leader(rn, s)
    rd = rn.ready()
    s.append(rd.entries)
    if rd.hard_state:
        s.set_hard_state(rd.hard_state)
    rn.advance(rd)
    # acks commit the empty entry: the next Ready carries only a commit
    # bump (and the committed entry), which MustSync=false
    last, term = int(rn.n.last_index), int(rn.n.term)
    for p in (1, 2):
        rn.step(HostMsg(type=MSG_APP_RESP, to=0, frm=p, term=term, index=last))
    rd = rn.ready()
    assert rd.hard_state is not None and rd.hard_state.commit == last
    assert not rd.must_sync
    assert [e.index for e in rd.committed_entries] == [last]
    rn.advance(rd)


# -- TestRawNodeRestart ------------------------------------------------------
def test_restart_from_storage():
    s = MemoryStorage()
    s.apply_snapshot(
        Snapshot(
            meta=SnapshotMeta(
                index=2, term=1, conf_state=ConfState(voters=(0, 1, 2))
            )
        )
    )
    s.append([Entry(index=3, term=1, data=7)])
    s.set_hard_state(HardState(term=1, vote=NONE_ID, commit=3))
    rn = RawNode(CFG, SPEC, s, 0, applied=2)
    # restart surfaces the committed-but-unapplied entry, nothing else
    rd = rn.ready()
    assert rd.hard_state is None  # unchanged vs storage
    assert rd.entries == []
    assert [e.index for e in rd.committed_entries] == [3]
    assert not rd.must_sync
    rn.advance(rd)
    assert not rn.has_ready()
    assert int(rn.n.applied) == 3


# -- TestRawNodeRestartFromSnapshot -----------------------------------------
def test_restart_from_snapshot():
    s = MemoryStorage()
    s.apply_snapshot(
        Snapshot(
            meta=SnapshotMeta(
                index=5, term=2, conf_state=ConfState(voters=(0, 1)),
                app_hash=99,
            )
        )
    )
    s.set_hard_state(HardState(term=2, vote=NONE_ID, commit=5))
    rn = RawNode(CFG, SPEC, s, 0, applied=5)
    assert not rn.has_ready()
    assert int(rn.n.commit) == 5
    assert int(rn.n.applied_hash) == 99
    assert rn.conf_state().voters == (0, 1)


# -- TestRawNodeReadIndex ----------------------------------------------------
def test_read_index_leader():
    rn, s = boot()
    drive_to_leader(rn, s)
    rd = rn.ready()
    s.append(rd.entries)
    if rd.hard_state:
        s.set_hard_state(rd.hard_state)
    rn.advance(rd)
    last, term = int(rn.n.last_index), int(rn.n.term)
    for p in (1, 2):
        rn.step(HostMsg(type=MSG_APP_RESP, to=0, frm=p, term=term, index=last))
    rd = rn.ready()
    rn.advance(rd)  # commit in current term established

    rn.read_index(ctx=7)
    rd = rn.ready()
    # ReadOnlySafe: a heartbeat round with the ctx goes out
    hb = [m for m in rd.messages if m.type == MSG_HEARTBEAT]
    assert len(hb) == 2 and all(m.context == 7 for m in hb)
    rn.advance(rd)
    for p in (1, 2):
        rn.step(
            HostMsg(type=7, to=0, frm=p, term=term, context=7)
        )  # MsgHeartbeatResp
    rd = rn.ready()
    assert [ (r.request_ctx, r.index) for r in rd.read_states ] == [(7, last)]
    rn.advance(rd)


# -- DeviceLaneStorage -------------------------------------------------------
def test_device_lane_storage_contract():
    from etcd_tpu.storage.raftstorage import ErrCompacted, ErrUnavailable

    rn, s = boot()
    drive_to_leader(rn, s)
    rd = rn.ready()
    s.append(rd.entries)
    rn.advance(rd)
    lane = DeviceLaneStorage(rn)
    assert lane.first_index() == 3
    assert lane.last_index() == int(rn.n.last_index)
    assert lane.term(2) == 1  # snapshot boundary
    with pytest.raises(ErrCompacted):
        lane.entries(1, 3)
    with pytest.raises(ErrUnavailable):
        lane.entries(3, lane.last_index() + 2)
    ents = lane.entries(3, lane.last_index() + 1)
    assert [e.index for e in ents] == [3]
    hs, cs = lane.initial_state()
    assert hs.term == int(rn.n.term) and cs.voters == (0, 1, 2)
    snap = lane.snapshot()
    assert snap.meta.index == 2 and snap.meta.term == 1
