"""Test doubles: the server/mock package analogs.

``RecordingStorage`` (mockstorage/storage_recorder.go) wraps a real
Storage, records every call as (action, args), and injects configured
errors — for driving RawNode/KVServer error paths deterministically.
``RecordingWait`` (mockwait/wait_recorder.go) does the same over
utils.wait.Wait. The v2-store mock (mockstore) has no analog because the
v2 API is deliberately omitted.
"""
from __future__ import annotations

from etcd_tpu.storage.raftstorage import MemoryStorage, Storage
from etcd_tpu.utils.wait import Wait


class RecordingStorage(Storage):
    """Wraps a Storage; records actions; raises injected failures.

    ``fail``: {method_name: exception} — the next call of that method
    raises the exception (one-shot, then cleared), modeling the
    reference's error-injecting storage doubles."""

    def __init__(self, inner: Storage | None = None):
        self.inner = inner or MemoryStorage()
        self.actions: list[tuple] = []
        self.fail: dict[str, Exception] = {}

    def _do(self, name: str, *args, **kw):
        self.actions.append((name,) + args)
        exc = self.fail.pop(name, None)
        if exc is not None:
            raise exc
        return getattr(self.inner, name)(*args, **kw)

    # -- Storage contract -------------------------------------------------
    def initial_state(self):
        return self._do("initial_state")

    def entries(self, lo, hi, max_entries=None):
        # forward the limit: a wrapped storage's size-limited reads must
        # behave identically under recording
        return self._do("entries", lo, hi, max_entries=max_entries)

    def term(self, i):
        return self._do("term", i)

    def first_index(self):
        return self._do("first_index")

    def last_index(self):
        return self._do("last_index")

    def snapshot(self):
        return self._do("snapshot")

    # -- MemoryStorage write surface (storage_recorder.go Save/SaveSnap) --
    def append(self, entries):
        return self._do("append", entries)

    def set_hard_state(self, hs):
        return self._do("set_hard_state", hs)

    def apply_snapshot(self, snap):
        return self._do("apply_snapshot", snap)

    def compact(self, index):
        return self._do("compact", index)

    def names(self) -> list[str]:
        """Recorded action names in order (testutil.Recorder.Wait analog)."""
        return [a[0] for a in self.actions]


class RecordingWait(Wait):
    """mockwait.WaitRecorder: record register/trigger traffic."""

    def __init__(self):
        super().__init__()
        self.actions: list[tuple] = []

    def register(self, id: int):
        self.actions.append(("Register", id))
        return super().register(id)

    def trigger(self, id: int, value) -> None:
        self.actions.append(("Trigger", id))
        super().trigger(id, value)
