"""v2 requests through consensus — the applyV2Request path
(apply_v2.go:124-148 + v2_server.go): every member's v2 tree is driven
only by committed entries, so trees stay bit-identical across members,
survive restart-from-disk, and ride peer snapshots."""
import pytest

from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.v2store import (
    EcodeKeyNotFound,
    EcodeNodeExist,
    EcodeTestFailed,
    V2Error,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def ec():
    c = EtcdCluster(n_members=3)
    c.ensure_leader()
    clk = FakeClock()
    c.v2_now = clk
    for ms in c.members:
        ms.v2store.clock = clk
    c._v2_clk = clk
    return c


def trees(ec):
    return [ms.v2store.save() for ms in ec.members]


def test_v2_put_replicates(ec):
    e = ec.v2_request("PUT", "/foo", val="bar")
    assert e.action == "set"
    assert e.node["value"] == "bar"
    ec.stabilize()
    t = trees(ec)
    assert t[0] == t[1] == t[2]
    g = ec.v2_get("/foo")
    assert g.node["value"] == "bar"
    # serializable read from a follower sees the same applied tree
    follower = next(m for m in range(3) if m != ec.ensure_leader())
    assert ec.v2_get("/foo", member=follower).node["value"] == "bar"


def test_v2_quorum_get(ec):
    ec.v2_request("PUT", "/foo", val="bar")
    e = ec.v2_request("QGET", "/foo")
    assert e.action == "get"
    assert e.node["value"] == "bar"


def test_v2_post_in_order(ec):
    e1 = ec.v2_request("POST", "/queue", val="a")
    e2 = ec.v2_request("POST", "/queue", val="b")
    assert e1.node["key"] < e2.node["key"]
    g = ec.v2_get("/queue", recursive=True, sorted_=True)
    assert [n["value"] for n in g.node["nodes"]] == ["a", "b"]


def test_v2_cas_cad_errors_propagate(ec):
    ec.v2_request("PUT", "/foo", val="v1")
    with pytest.raises(V2Error) as ei:
        ec.v2_request("PUT", "/foo", val="x", prev_value="bad")
    assert ei.value.code == EcodeTestFailed
    e = ec.v2_request("PUT", "/foo", val="v2", prev_value="v1")
    assert e.action == "compareAndSwap"
    with pytest.raises(V2Error) as ei:
        ec.v2_request("DELETE", "/foo", prev_index=999)
    assert ei.value.code == EcodeTestFailed
    e = ec.v2_request("DELETE", "/foo", prev_value="v2")
    assert e.action == "compareAndDelete"
    ec.stabilize()
    t = trees(ec)
    assert t[0] == t[1] == t[2]


def test_v2_prev_exist_semantics(ec):
    with pytest.raises(V2Error) as ei:
        ec.v2_request("PUT", "/foo", val="v", prev_exist=True)
    assert ei.value.code == EcodeKeyNotFound
    ec.v2_request("PUT", "/foo", val="v1", prev_exist=False)
    with pytest.raises(V2Error) as ei:
        ec.v2_request("PUT", "/foo", val="v2", prev_exist=False)
    assert ei.value.code == EcodeNodeExist
    e = ec.v2_request("PUT", "/foo", val="v2", prev_exist=True)
    assert e.action == "update"


def test_v2_ttl_sync_expires_on_all_members(ec):
    clk = ec._v2_clk
    ec.v2_request("PUT", "/tmp", val="v", ttl=5)
    ec.v2_request("PUT", "/keep", val="v")
    clk.advance(10)
    ec.v2_sync()
    ec.stabilize()
    for m in range(3):
        with pytest.raises(V2Error):
            ec.v2_get("/tmp", member=m)
        assert ec.v2_get("/keep", member=m).node["value"] == "v"
    t = trees(ec)
    assert t[0] == t[1] == t[2]


def test_v2_watch_sees_committed_changes(ec):
    w = ec.v2_watch("/foo")
    ec.v2_request("PUT", "/foo", val="v")
    ev = w.poll()
    assert ev is not None and ev.action == "set"


def test_v2_survives_restart_from_disk(tmp_path):
    ec = EtcdCluster(n_members=3, data_dir=str(tmp_path / "d"))
    ec.ensure_leader()
    ec.v2_request("PUT", "/a/b", val="v1")
    ec.v2_request("POST", "/q", val="item")
    ec.put(b"v3key", b"v3val")  # interleave v3 traffic
    ec.v2_request("PUT", "/a/b", val="v2", prev_value="v1")
    ec.stabilize()
    victim = ec.ensure_leader()
    want = ec.members[victim].v2store.save()
    ec.crash_member(victim)
    ec.stabilize()
    ec.restart_member_from_disk(victim)
    ec.stabilize()
    assert ec.members[victim].v2store.save() == want
    assert ec.v2_get("/a/b", member=victim).node["value"] == "v2"


def test_v2_rides_peer_snapshot(ec):
    """A memory-only member that falls behind the compacted ring gets the
    v2 tree via the peer state-machine snapshot."""
    ec.v2_request("PUT", "/snap/me", val="v")
    victim = (ec.ensure_leader() + 1) % 3
    ec.crash_member(victim)
    # push enough entries to force ring compaction past the victim
    L = ec.cl.spec.L
    for i in range(L + 4):
        ec.put(b"fill%d" % i, b"x")
    ec.v2_request("PUT", "/snap/late", val="w")
    ec.stabilize()
    ec.restart_member_from_disk(victim)
    ec.stabilize()
    assert ec.v2_get("/snap/me", member=victim).node["value"] == "v"
    assert ec.v2_get("/snap/late", member=victim).node["value"] == "w"
    assert ec.members[victim].v2store.save() == \
        ec.members[ec.ensure_leader()].v2store.save()


def test_v2_stats_count_ops(ec):
    ec.v2_request("PUT", "/foo", val="v")
    st = ec.v2_stats()
    assert st["setsSuccess"] >= 1
