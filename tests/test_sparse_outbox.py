"""RaftConfig.sparse_outbox: the dense outbox leaves the scan carry.

This completes PROFILE.md's emission restructure: under the steady
message classes every in-scan handler records PendingWire intents, so
the message scan carries only (NodeState, PendingWire) and the K-slot
outbox is packed ONCE by the post-scan merge. The equivalence contract
mirrors tests/test_deferred_emit.py: on live steady traffic the sparse
program reproduces the immediate-emission steady program bit-for-bit in
both fleet state and the wire — and the full diet stack (sparse outbox
+ packed state + compacted wire) holds the same bar.
"""
import dataclasses

import numpy as np
import jax
import pytest

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.models.state import pack_fleet, unpack_fleet
from etcd_tpu.types import (
    ENTRY_NORMAL,
    MSG_APP,
    MSG_APP_RESP,
    MSG_HEARTBEAT,
    MSG_PROP,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
FULL = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                  inbox_bound=4, coalesce_commit_refresh=True)
STEADY = dataclasses.replace(
    FULL, local_steps=("prop",),
    message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP))
SPARSE = dataclasses.replace(STEADY, deferred_emit=True, sparse_outbox=True)
DIET = dataclasses.replace(SPARSE, compact_wire=True, packed_state=True)
C = 4


def test_sparse_outbox_requires_deferred_emit():
    with pytest.raises(ValueError, match="deferred_emit"):
        dataclasses.replace(STEADY, sparse_outbox=True)


def test_sparse_outbox_requires_steady_classes():
    """Any class with an in-scan emit site must be rejected — its writes
    would be silently discarded from the carried PendingWire."""
    with pytest.raises(ValueError, match="message_classes"):
        dataclasses.replace(
            FULL, local_steps=("prop",), deferred_emit=True,
            sparse_outbox=True,
            message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP,
                             MSG_HEARTBEAT))
    with pytest.raises(ValueError, match="message_classes"):
        dataclasses.replace(FULL, deferred_emit=True, sparse_outbox=True)


def test_compact_wire_requires_inbox_bound():
    with pytest.raises(ValueError, match="inbox_bound"):
        RaftConfig(compact_wire=True)


@pytest.fixture(scope="module")
def elected():
    full = jax.jit(build_round(FULL, SPEC))
    M, E = SPEC.M, SPEC.E
    state = init_fleet(SPEC, C, seed=0, election_tick=FULL.election_tick)
    inbox = empty_inbox(SPEC, C)
    z2 = np.zeros((M, C), np.int32)
    zp = np.zeros((M, E, C), np.int32)
    no = np.zeros((M, C), bool)
    keep = np.ones((M, M, C), bool)
    hup = no.copy()
    hup[0, :] = True
    state, inbox = full(state, inbox, z2, zp, zp, z2, hup, no, keep)
    for _ in range(12):
        state, inbox = full(state, inbox, z2, zp, zp, z2, no, no, keep)
    assert (np.asarray(state.role)[0] == ROLE_LEADER).all()
    # quiescent entry point: the diet program boots from an EMPTY compact
    # inbox, so the comparison must start with no in-flight messages
    assert int((np.asarray(inbox.type) != 0).sum()) == 0
    return state, inbox, (z2, zp, no, keep)


def _props(z2, zp):
    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = 7
    ptype = zp.copy()
    ptype[0, 0, :] = ENTRY_NORMAL
    return plen, pdata, ptype


def test_sparse_program_is_bit_identical_in_steady_state(elected):
    """Sparse (carry-free) vs immediate emission: state AND wire equal
    over 10 live replicating rounds."""
    steady = jax.jit(build_round(STEADY, SPEC))
    sparse = jax.jit(build_round(SPARSE, SPEC))
    state0, inbox0, (z2, zp, no, keep) = elected
    plen, pdata, ptype = _props(z2, zp)

    sa, ia = state0, inbox0
    sb, ib = state0, inbox0
    for _ in range(10):
        sa, ia = steady(sa, ia, plen, pdata, ptype, z2, no, no, keep)
        sb, ib = sparse(sb, ib, plen, pdata, ptype, z2, no, no, keep)
    assert int(np.asarray(sa.commit).min()) >= 8  # really replicating
    for name in sa.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        ), f"state.{name}"
    for name in ia.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(ia, name)), np.asarray(getattr(ib, name))
        ), f"inbox.{name}"


def test_full_diet_program_is_bit_identical_in_steady_state(elected):
    """The whole stack at once — sparse outbox + packed state + compacted
    int16-free wire — against the immediate-emission steady program."""
    steady = jax.jit(build_round(STEADY, SPEC))
    diet = jax.jit(build_round(DIET, SPEC))
    state0, _, (z2, zp, no, keep) = elected
    plen, pdata, ptype = _props(z2, zp)

    sa = state0
    ia = empty_inbox(SPEC, C)
    pb = pack_fleet(SPEC, state0)
    ib = empty_inbox(SPEC, C, compact_bound=DIET.inbox_bound)
    for _ in range(10):
        sa, ia = steady(sa, ia, plen, pdata, ptype, z2, no, no, keep)
        pb, ib = diet(pb, ib, plen, pdata, ptype, z2, no, no, keep)
    sb = unpack_fleet(SPEC, pb)
    assert int(np.asarray(sa.commit).min()) >= 8
    for name in sa.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        ), f"state.{name}"


def test_sparse_program_heals_a_dropped_append(elected):
    """Past bit-exactness: the sparse program still converges when a
    follower's inbound append is dropped for a round (reject/probe
    path), like the deferred program it specializes."""
    sparse = jax.jit(build_round(SPARSE, SPEC))
    state, inbox, (z2, zp, no, keep) = elected
    plen, pdata, ptype = _props(z2, zp)

    drop = keep.copy()
    drop[:, 2, :] = False  # member 2 receives nothing this round
    state, inbox = sparse(state, inbox, plen, pdata, ptype, z2, no, no,
                          drop)
    for _ in range(6):
        state, inbox = sparse(state, inbox, z2, zp, zp, z2, no, no, keep)
    commits = np.asarray(state.commit)
    assert (commits[2] == commits[0]).all()  # the dropped member caught up
