"""Distributed coordination recipes — clientv3/concurrency analogs.

Mirrors ``client/v3/concurrency``: Session (lease-scoped liveness), Mutex
(lock by lowest create-revision under a prefix, mutex.go), Election
(campaign/proclaim/resign/observe, election.go) and STM (software
transactional memory retry loop, stm.go). These are *client-side recipes*
over KV+lease+watch — identical strategy to the reference, and the
substrate the server-side v3lock/v3election services expose.
"""
from __future__ import annotations

import dataclasses

from etcd_tpu.client import Client, prefix_range_end
from etcd_tpu.server.kvserver import Compare, Op


class ConcurrencyError(Exception):
    pass


class Session:
    """concurrency.Session: a lease kept alive on tick; dropping it releases
    every lock/candidacy owned by the session."""

    _next_id = 1000

    def __init__(self, client: Client, ttl: int = 60):
        self.client = client
        Session._next_id += 1
        self.lease_id = Session._next_id
        client.lease_grant(self.lease_id, ttl)

    def keepalive(self) -> None:
        self.client.lease_keepalive(self.lease_id)

    def close(self) -> None:
        self.client.lease_revoke(self.lease_id)


class Mutex:
    """concurrency.Mutex (mutex.go): my key = <prefix>/<lease-id>; acquire
    by putting it iff absent (create-rev 0 compare) and owning the lock when
    no earlier create-revision exists under the prefix."""

    def __init__(self, session: Session, prefix: bytes):
        self.s = session
        self.prefix = prefix.rstrip(b"/") + b"/"
        self.my_key = self.prefix + str(session.lease_id).encode()
        self.my_rev = 0

    def try_lock(self) -> bool:
        c = self.s.client
        res = (
            c.txn()
            .if_(c.compare_create(self.my_key, "=", 0))
            .then(Op("put", self.my_key, b"", lease=self.s.lease_id))
            .else_(Op("range", self.my_key))
            .commit()
        )
        if res["succeeded"]:
            self.my_rev = res["rev"]
        else:
            self.my_rev = res["responses"][0][1][0].create_revision
        owner = self._owner()
        if owner == self.my_rev:
            return True
        return False

    def lock(self, max_rounds: int = 200) -> None:
        """Block (stepping the cluster) until owned — waitDeletes on earlier
        keys in the reference becomes step-and-recheck here."""
        for _ in range(max_rounds):
            if self.try_lock():
                return
            # step, don't tick: the wait loop only needs raft rounds to
            # flush; advancing the raft timers here would fast-forward
            # lease TTLs (wall-clock seconds) by hundreds of seconds in
            # milliseconds and expire other sessions' locks
            self.s.client.ec.step()
        raise ConcurrencyError("lock: timed out")

    def unlock(self) -> None:
        self.s.client.delete(self.my_key)
        self.my_rev = 0

    def _owner(self) -> int:
        """Lowest create-revision under the prefix (the lock holder)."""
        res = self.s.client.get_prefix(self.prefix)
        revs = [kv.create_revision for kv in res["kvs"]]
        return min(revs) if revs else 0

    def is_owner(self) -> bool:
        return self.my_rev != 0 and self._owner() == self.my_rev


class Election:
    """concurrency.Election (election.go): leadership = owning the lowest
    create-revision key under the election prefix; proclaim rewrites the
    value guarded by that ownership."""

    def __init__(self, session: Session, prefix: bytes):
        self.s = session
        self.prefix = prefix.rstrip(b"/") + b"/"
        self.my_key = self.prefix + str(session.lease_id).encode()
        self.my_rev = 0

    def campaign(self, value: bytes, max_rounds: int = 200) -> None:
        c = self.s.client
        res = (
            c.txn()
            .if_(c.compare_create(self.my_key, "=", 0))
            .then(Op("put", self.my_key, value, lease=self.s.lease_id))
            .else_(Op("range", self.my_key))
            .commit()
        )
        if res["succeeded"]:
            self.my_rev = res["rev"]
        else:
            self.my_rev = res["responses"][0][1][0].create_revision
            c.put(self.my_key, value, lease=self.s.lease_id)
        for _ in range(max_rounds):
            if self.is_leader():
                return
            c.ec.step()  # see Mutex.lock: no lease-clock fast-forward
        raise ConcurrencyError("campaign: timed out")

    def proclaim(self, value: bytes) -> None:
        c = self.s.client
        res = (
            c.txn()
            .if_(c.compare_create(self.my_key, "=", self.my_rev))
            .then(Op("put", self.my_key, value, lease=self.s.lease_id))
            .commit()
        )
        if not res["succeeded"]:
            raise ConcurrencyError("proclaim: not leader (session expired)")

    def resign(self) -> None:
        self.s.client.delete(self.my_key)
        self.my_rev = 0

    def leader(self):
        """(key, value) of the current leader — earliest create-revision."""
        res = self.s.client.get_prefix(self.prefix)
        if not res["kvs"]:
            return None
        kv = min(res["kvs"], key=lambda kv: kv.create_revision)
        return kv

    def is_leader(self) -> bool:
        kv = self.leader()
        return kv is not None and kv.create_revision == self.my_rev


class STM:
    """concurrency.NewSTM (stm.go, SerializableSnapshot flavor): buffer
    reads/writes, commit with mod-revision compares over the read set,
    retry on conflict."""

    def __init__(self, client: Client, max_retries: int = 16):
        self.client = client
        self.max_retries = max_retries

    def run(self, apply_fn) -> dict:
        for _ in range(self.max_retries):
            txn = _STMTxn(self.client)
            apply_fn(txn)
            res = txn.commit()
            if res is not None:
                return res
        raise ConcurrencyError("STM: too many retries")


class _STMTxn:
    def __init__(self, client: Client):
        self.c = client
        self.rset: dict[bytes, int] = {}   # key -> mod_revision seen (0=absent)
        self.wset: dict[bytes, bytes] = {}

    def get(self, key: bytes) -> bytes | None:
        if key in self.wset:
            return self.wset[key]
        kv = self.c.get(key, serializable=True)
        self.rset[key] = kv.mod_revision if kv else 0
        return kv.value if kv else None

    def put(self, key: bytes, value: bytes) -> None:
        self.wset[key] = value

    def commit(self) -> dict | None:
        cmps = [
            self.c.compare_mod(k, "=", rev) for k, rev in self.rset.items()
        ]
        puts = [Op("put", k, v) for k, v in self.wset.items()]
        res = self.c.txn().if_(*cmps).then(*puts).commit()
        return res if res["succeeded"] else None
