"""Config-aware chaos tier (ISSUE 5): membership-change faults, the
joint-quorum recovery checkers, and targeted snapshot-install crash
scheduling.

The reference's functional tester exercises member add/remove cases
(tester/case_member_*.go) against a live cluster; here the same fault
class runs on-device — encoded conf-change words injected into the epoch
scan — and the crash-recovery checkers count durable holders against the
group's live (possibly joint) configuration instead of a static
full-member majority.

The default tests run tiny fleets on CPU (<=16 groups — the
run_smoke.sh configuration); the 4096-group acceptance shape rides
behind the `slow` marker and chaos_run.py (CHAOS_MEMBER=0.05
CHAOS_CRASH=0.01).
"""
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from etcd_tpu.harness.chaos import (
    VIOLATION_KEYS,
    check_recovery_invariants,
    empty_crash_state,
    member_palette,
    run_chaos,
    summarize_chaos,
    targeted_crash_probs,
    zero_violations,
)
from etcd_tpu.models.engine import (
    empty_inbox,
    init_fleet,
    member_window_mask,
    snapshot_window_mask,
)
from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    ENTRY_CONF_CHANGE,
    MSG_SNAP,
    PR_SNAPSHOT,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import (
    CrashConfig,
    MemberChaosConfig,
    RaftConfig,
)

SPEC = Spec(M=5, L=32, E=2, K=4, W=2, R=2, A=4)
CFG = RaftConfig(pre_vote=True, check_quorum=True)
# the two run_chaos tests use the lean bench-like geometry: the smoke
# tier's wall-clock is dominated by tracing the epoch programs, and the
# serial message-slot count (K*M) is the trace-cost multiplier — K=2/E=1
# halves it vs SPEC while exercising identical member-chaos structure
# (SPEC stays for the mask/checker unit tests, which trace nothing big)
RUN_SPEC = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)


def assert_safe(rep):
    for k in VIOLATION_KEYS:
        assert rep[k] == 0, rep


# ------------------------------------------------------------ end to end

def test_member_chaos_small_fleet():
    """Seeded small-fleet run with conf-change proposals stacked on the
    crash + network mix: all six checkers stay zero, the fleet recovers,
    and membership actually churned (proposals injected, configs applied,
    joint configs entered and left — the fault class is live, not
    vacuously safe)."""
    rep = run_chaos(
        RUN_SPEC, CFG, C=16, rounds=50, epoch_len=25, heal_len=25, seed=2,
        drop_p=0.02, delay_p=0.05, partition_p=0.1,
        crash_p=0.03, crash=CrashConfig(down_rounds=2),
        member_p=0.15, member=MemberChaosConfig(initial_voters=3),
    )
    assert_safe(rep)
    assert rep["crashes_injected"] > 0
    assert rep["member_changes_proposed"] > 0
    assert rep["conf_changes_applied"] > 0
    assert rep["joint_entered"] > 0
    # guard outcomes were recorded for leader-direct proposals
    assert rep["cc_guard_refusals"] + rep["cc_guard_admits"] > 0
    # conscious liveness floor (summarize_chaos contract): membership
    # churn legally starves fault epochs harder than the standard mix —
    # joint configs need BOTH halves to commit, and partial-voter boots
    # leave partitioned minorities smaller — so the floor drops from the
    # standard-mix default 0.2 to 0.1 of fault-free throughput
    summary = summarize_chaos(rep, rounds=50, epoch_len=25, heal_len=25,
                              liveness_frac=0.1)
    assert summary["safe"] and summary["recovered"] and summary["lively"], (
        rep, summary)


def test_config_blind_checker_fires_on_remove_voter():
    """The deliberately config-blind checker variant (the pre-ISSUE-5
    static full-member majority) must fire on a remove-voter + crash
    schedule that the config-aware checker accepts: once a group shrinks
    to voters {0, 1}, new commits are durably held by 2 members — every
    quorum of the LIVE config, but fewer than the static M//2+1 bar.
    Proves the rework is live, the same way persist-nothing proves the
    leader-completeness checker fires.

    Deliberately the SAME cfg/spec/epoch geometry as the honest test
    above: config_aware is a runtime operand, so both runs reuse the
    epoch programs already traced in this session."""
    kw = dict(
        C=16, rounds=25, epoch_len=25, heal_len=25, seed=5,
        drop_p=0.0, delay_p=0.05, partition_p=0.0,
        crash_p=0.02, crash=CrashConfig(down_rounds=2),
        member_p=0.25,
        member=MemberChaosConfig(mix="shrink", initial_voters=3),
    )
    honest = run_chaos(RUN_SPEC, CFG, config_aware=True, **kw)
    assert_safe(honest)
    assert honest["conf_changes_applied"] > 0
    blind = run_chaos(RUN_SPEC, CFG, config_aware=False, **kw)
    assert blind["lost_commit"] > 0, blind


# ------------------------------------------------------ palette / knobs

def _decode_deltas(w: int):
    out = []
    if w & (1 << 16):
        out.append((w & 7, (w >> 3) & 31))
    if w & (1 << 17):
        out.append(((w >> 8) & 7, (w >> 11) & 31))
    return out


@pytest.mark.parametrize("mix", ["standard", "simple", "shrink"])
def test_member_palette_never_drains_voter_floor(mix):
    """No palette word removes or demotes members 0/1 — the >= 2 voter
    floor the fsync-lag crash model requires (the device applies
    committed changes unconditionally, so the palette is where the floor
    is enforced)."""
    words = np.asarray(member_palette(SPEC, mix))
    assert words.size > 0
    for w in words:
        deltas = _decode_deltas(int(w))
        assert deltas, hex(int(w))
        for op, nid in deltas:
            if op in (CC_REMOVE_NODE, CC_ADD_LEARNER):
                assert nid >= 2, (mix, hex(int(w)))
    if mix == "shrink":
        assert all(op == CC_REMOVE_NODE
                   for w in words for op, _ in _decode_deltas(int(w)))
    if mix == "standard":
        # auto-joint two-delta words present
        assert any(len(_decode_deltas(int(w))) == 2 for w in words)


def test_member_config_validation():
    with pytest.raises(ValueError, match="unknown member mix"):
        MemberChaosConfig(mix="nope")
    with pytest.raises(ValueError, match="initial_voters"):
        MemberChaosConfig(initial_voters=1)
    with pytest.raises(ValueError, match="boosts"):
        MemberChaosConfig(snap_crash_boost=0.5)
    with pytest.raises(ValueError, match="M >= 3"):
        member_palette(Spec(M=2, L=8, E=1, K=1, W=2, R=2, A=2))
    # conf-change words use bits 16-20: the int16 wire would truncate
    # them silently, so the combination is rejected up front
    with pytest.raises(ValueError, match="int16 wire"):
        run_chaos(SPEC, RaftConfig(wire_int16=True), C=4, rounds=10,
                  member_p=0.1, member=MemberChaosConfig(initial_voters=3))


# ------------------------------------------------- targeted scheduling

def test_targeted_crash_probs_preserves_budget():
    """In-window lanes get boost * crash_p, the leftover budget spreads
    uniformly, and the round's expected crash count is exactly
    crash_p * lanes — the equal-budget property the acceptance compares
    against Bernoulli scheduling."""
    snap = jnp.zeros((5, 64), jnp.bool_).at[0, :8].set(True)
    mem = jnp.zeros((5, 64), jnp.bool_).at[1, :16].set(True)
    p = targeted_crash_probs(jnp.float32(0.01), snap, mem,
                             jnp.float32(20.0), jnp.float32(5.0))
    np.testing.assert_allclose(np.asarray(p[0, 0]), 0.2, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p[1, 0]), 0.05, rtol=1e-5)
    # budget = 0.01 * 320 = 3.2 expected crashes, preserved exactly
    np.testing.assert_allclose(float(p.sum()), 3.2, rtol=1e-5)
    # base lanes share the remainder uniformly
    np.testing.assert_allclose(
        np.asarray(p[4, 0]), (3.2 - 8 * 0.2 - 16 * 0.05) / (320 - 24),
        rtol=1e-5)

    # boosts of 1 reproduce the uniform Bernoulli schedule
    p1 = targeted_crash_probs(jnp.float32(0.01), snap, mem,
                              jnp.float32(1.0), jnp.float32(1.0))
    np.testing.assert_allclose(np.asarray(p1), 0.01, rtol=1e-5)

    # overspending windows scale down rather than exceed the budget
    p2 = targeted_crash_probs(jnp.float32(0.01), snap, mem,
                              jnp.float32(1e4), jnp.float32(1e4))
    np.testing.assert_allclose(float(p2.sum()), 3.2, rtol=1e-4)
    assert float(p2[4, 0]) == 0.0  # window lanes consumed everything

    # a snapshot-window lane wins over an overlapping member window:
    # mark the same [0, :8] lanes member-sensitive too
    both = snap
    p3 = targeted_crash_probs(jnp.float32(0.01), snap, both,
                              jnp.float32(30.0), jnp.float32(2.0))
    np.testing.assert_allclose(np.asarray(p3[0, 0]), 0.3, rtol=1e-5)


def test_snapshot_window_mask_detects_both_sides():
    C = 2
    state = init_fleet(SPEC, C, seed=0)
    inbox = empty_inbox(SPEC, C)
    # MsgSnap in flight from node 0 (slot k=0) to node 2 in group 1
    t = inbox.type.at[0, 0 * SPEC.M + 2, 1].set(MSG_SNAP)
    inbox = inbox.replace(type=t)
    # node 1 leads group 0 with peer 3 in PR_SNAPSHOT (sent, un-acked)
    state = state.replace(
        role=state.role.at[1, 0].set(ROLE_LEADER),
        pr_state=state.pr_state.at[1, 3, 0].set(PR_SNAPSHOT),
    )
    win = np.asarray(snapshot_window_mask(SPEC, state, inbox))
    expect = np.zeros((SPEC.M, C), bool)
    expect[2, 1] = True   # install-side: MsgSnap addressed to it
    expect[1, 0] = True   # leader-side: between send and ack
    np.testing.assert_array_equal(win, expect)


def test_member_window_mask_joint_and_pending_cc():
    C = 2
    state = init_fleet(SPEC, C, seed=0)
    # node 2 of group 1 sits in a joint config
    state = state.replace(
        voters_out=state.voters_out.at[2, 0, 1].set(True))
    # node 0 of group 0 has a committed-but-unapplied conf change at
    # index 3 (slot (3-1) % L): applied 2 < 3 <= commit 4
    ones = jnp.ones((), jnp.int32)
    state = state.replace(
        log_type=state.log_type.at[0, 2, 0].set(ENTRY_CONF_CHANGE),
        last_index=state.last_index.at[0, 0].set(5),
        commit=state.commit.at[0, 0].set(4 * ones),
        applied=state.applied.at[0, 0].set(2 * ones),
    )
    win = np.asarray(member_window_mask(SPEC, state))
    expect = np.zeros((SPEC.M, C), bool)
    expect[2, 1] = True
    expect[0, 0] = True
    np.testing.assert_array_equal(win, expect)
    # once applied catches up past the cc entry the window closes
    state2 = state.replace(applied=state.applied.at[0, 0].set(4 * ones))
    assert not np.asarray(member_window_mask(SPEC, state2))[0, 0]


# ------------------------------------------- checker unit semantics

def _fleet_with(voters_mask, C=2, **overrides):
    state = init_fleet(SPEC, C, voters=jnp.asarray(voters_mask, jnp.bool_),
                       seed=0)
    return state.replace(**overrides)


def _check(state, config_aware=True):
    crash = empty_crash_state(state)
    viol, crash = check_recovery_invariants(
        SPEC, state, crash, zero_violations(), jnp.bool_(config_aware))
    return int(viol.lost_commit), int(viol.log_divergence)


def _li(per_member, C=2):
    v = jnp.asarray(per_member, jnp.int32)[:, None]
    return jnp.broadcast_to(v, (SPEC.M, C))


def test_checker_removed_voters_abstain():
    """Two-voter config, both holding the watermark: every live quorum
    intersects the holders (safe), while the config-blind static
    majority (3 of 5 slots) fires — the exact remove-voter regime that
    blocked membership chaos (ROADMAP)."""
    state = _fleet_with([True, True, False, False, False],
                        last_index=_li([5, 5, 0, 0, 0]),
                        commit=_li([5, 5, 0, 0, 0]))
    lost, div = _check(state, config_aware=True)
    assert lost == 0 and div == 0
    lost_blind, _ = _check(state, config_aware=False)
    assert lost_blind == 2  # both groups, static majority never held


def test_checker_joint_config_needs_both_halves():
    """Joint consensus protection: a candidate missing the watermark
    must win BOTH halves. Incoming {0..4} with holders {0,1} is
    electable-without on its own, but outgoing {0,1,2} still pins the
    entry (non-holder 2 alone is no quorum) — a config-NAIVE checker
    evaluating only the incoming half would false-positive here."""
    vo = jnp.zeros((SPEC.M, SPEC.M, 2), jnp.bool_)
    vo = vo.at[:, 0].set(True).at[:, 1].set(True).at[:, 2].set(True)
    state = _fleet_with([True] * 5,
                        voters_out=vo,
                        last_index=_li([11, 11, 9, 0, 0]),
                        commit=_li([11, 11, 9, 0, 0]))
    lost, _ = _check(state)
    assert lost == 0

    # drop holder 1: outgoing non-holders {1, 2} now form a quorum of
    # that half too — the committed index is genuinely erasable
    state2 = state.replace(last_index=_li([11, 9, 9, 0, 0]))
    lost2, _ = _check(state2)
    assert lost2 == 2  # both groups


def test_checker_even_half_intersection_bar():
    """Even-sized halves use the quorum-intersection bar, not majority
    holdership: 2 holders of 4 voters already intersect every 3-vote
    quorum (safe); 1 holder leaves a 3-voter non-holder quorum (lost)."""
    state = _fleet_with([True, True, True, True, False],
                        last_index=_li([7, 7, 0, 0, 0]),
                        commit=_li([7, 7, 0, 0, 0]))
    lost, _ = _check(state)
    assert lost == 0
    state2 = state.replace(last_index=_li([7, 0, 0, 0, 0]))
    lost2, _ = _check(state2)
    assert lost2 == 2


# ---------------------------------------------- chaos_run.py validation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("env_extra,needle", [
    ({"CHAOS_CRASH": "1.5"}, "CHAOS_CRASH"),
    # name validation is delegated to MemberChaosConfig.__post_init__
    # (single source of truth), so the message names the mix, not the var
    ({"CHAOS_MEMBER": "0.1", "CHAOS_MEMBER_MIX": "nope"},
     "unknown member mix"),
])
def test_chaos_run_rejects_bad_knobs(env_extra, needle):
    """Knob validation exits 2 with a pointed message before any device
    work (no JSON line, no long run)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **env_extra}
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "chaos_run.py")],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 2, (out.returncode, out.stdout, out.stderr)
    assert needle in out.stderr
    assert not out.stdout.strip()


# ------------------------------------------------------ acceptance scale

@pytest.mark.slow
def test_member_chaos_4096_groups_targeted():
    """The acceptance-scale membership run (bench geometry minus the
    int16 wire, conf changes + crashes + snapshot-window targeting) —
    exercised on CPU/TPU via chaos_run.py (CHAOS_C=4096
    CHAOS_MEMBER=0.05 CHAOS_CRASH=0.005 CHAOS_SNAP_BOOST=200
    CHAOS_WIRE16=0); here behind the slow marker. The crash budget sits
    below the window-generation rate so the targeted scheduler's hit
    rate is window-limited, not budget-limited — the measured operating
    point for the >= 10x acceptance bar (16.5x at C=64)."""
    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=4, coalesce_commit_refresh=True)
    kw = dict(
        C=4096, rounds=200, epoch_len=50, heal_len=25, seed=0,
        drop_p=0.02, delay_p=0.05, partition_p=0.1,
        crash_p=0.005, crash=CrashConfig(down_rounds=3), member_p=0.05,
    )
    tgt = run_chaos(spec, cfg, member=MemberChaosConfig(
        initial_voters=3, snap_crash_boost=200.0,
        member_crash_boost=4.0), **kw)
    assert_safe(tgt)
    assert tgt["conf_changes_applied"] > 0
    # liveness_frac=0.1: the membership mix's conscious floor (see
    # test_member_chaos_small_fleet)
    s = summarize_chaos(tgt, rounds=200, epoch_len=50, heal_len=25,
                        liveness_frac=0.1)
    assert s["recovered"] and s["lively"], (tgt, s)
    uni = run_chaos(spec, cfg, member=MemberChaosConfig(
        initial_voters=3), **kw)
    assert_safe(uni)
    # >= 10x the Bernoulli window-hit rate at equal crash budget
    assert tgt["snap_window_hit_rate"] >= 10 * uni["snap_window_hit_rate"], (
        tgt["snap_window_hit_rate"], uni["snap_window_hit_rate"])
