"""Watch layer over the MVCC store.

Mirrors ``server/storage/mvcc/watchable_store.go``: watchers live in a
*synced* group (caught up; notified inline at write-txn end,
watchable_store_txn.go:22) or an *unsynced* group (start revision in the
past; drained by a catch-up pass reading history — syncWatchersLoop,
watchable_store.go:211,331). Slow receivers move to a *victims* list and are
retried (watchable_store.go:47-67). Range membership uses simple interval
checks (the reference's adt.IntervalTree in watcher_group.go:293 — at host
scale a linear scan over active watchers is the right-sized structure).
"""
from __future__ import annotations

import dataclasses

from etcd_tpu.server.mvcc import KeyValue, MVCCStore


@dataclasses.dataclass
class Event:
    """mvccpb.Event."""

    type: str  # "put" | "delete"
    kv: KeyValue
    prev_kv: KeyValue | None = None


def events_from_delta(delta, c: int) -> list:
    """Fan one group's device-extracted watch delta
    (etcd_tpu/device_mvcc/apply.py:extract_deltas) out as
    ``(type, KeyValue, prev_kv)`` tuples — the exact shape a host write
    txn's ``events`` list has, so ``WatchableStore.notify`` consumes the
    return value directly:

        ws.notify(events_from_delta(delta, c))

    Device deltas are revision-coalesced (one event per key per round,
    carrying the newest record; prev_kv is always None — history below
    the latest record does not exist on device); see the apply-plane
    README section for the delivery contract."""
    import numpy as np

    from etcd_tpu.device_mvcc import scheme

    mask = np.asarray(delta.mask[..., c])
    if not mask.any():
        return []
    tomb = np.asarray(delta.tomb[..., c])
    mod = np.asarray(delta.mod[..., c])
    create = np.asarray(delta.create[..., c])
    version = np.asarray(delta.version[..., c])
    vword = np.asarray(delta.vword[..., c])
    lease = np.asarray(delta.lease[..., c])
    out = []
    for kid in np.nonzero(mask)[0]:
        kid = int(kid)
        if tomb[kid]:
            kv = KeyValue(scheme.key_bytes(kid), b"", 0, int(mod[kid]), 0)
            out.append(("delete", kv, None))
        else:
            kv = KeyValue(
                scheme.key_bytes(kid), scheme.encode_value(int(vword[kid])),
                int(create[kid]), int(mod[kid]), int(version[kid]),
                int(lease[kid]),
            )
            out.append(("put", kv, None))
    return out


@dataclasses.dataclass
class Watcher:
    id: int
    key: bytes
    range_end: bytes | None
    start_rev: int  # next revision this watcher needs
    prev_kv: bool = False
    # fragment: client opted into split delivery of oversized event
    # batches (WatchCreateRequest.Fragment, api/v3rpc/watch.go:303-305)
    fragment: bool = False
    # progress_notify: client wants periodic empty revision headers when
    # idle (WatchCreateRequest.ProgressNotify, watch.go:296-298)
    progress_notify: bool = False
    # event-type filters (WatchCreateRequest.Filters NOPUT/NODELETE,
    # watch.go FiltersFromRequest:570-583); lowercase event type names
    filters: tuple = ()
    buffer: list[Event] = dataclasses.field(default_factory=list)
    # victim: buffer overflowed; excluded from synced until retried
    victim: bool = False
    compacted: bool = False

    MAX_BUFFER = 1024  # chanBufLen analog (watcher.go)

    def matches(self, key: bytes) -> bool:
        if self.range_end is None:
            return key == self.key
        if self.range_end == b"\x00":
            return key >= self.key
        return self.key <= key < self.range_end

    def filtered(self, typ: str) -> bool:
        """True if events of this type are dropped for this watcher
        (filterNoPut/filterNoDelete, watch.go:565-568)."""
        return typ in self.filters


class WatchableStore:
    """One member's watchable MVCC store."""

    def __init__(self, store: MVCCStore | None = None):
        self.kv = store or MVCCStore()
        self.synced: dict[int, Watcher] = {}
        self.unsynced: dict[int, Watcher] = {}
        self._next_id = 1

    # -- watch lifecycle (watcher.go watchStream.Watch) ----------------------
    def watch(
        self,
        key: bytes,
        range_end: bytes | None = None,
        start_rev: int = 0,
        prev_kv: bool = False,
        watch_id: int = 0,
        fragment: bool = False,
        progress_notify: bool = False,
        filters: tuple = (),
    ) -> Watcher:
        if watch_id == 0:
            watch_id = self._next_id
        self._next_id = max(self._next_id, watch_id) + 1
        cur = self.kv.current_rev
        if start_rev == 0:
            start_rev = cur + 1
        w = Watcher(watch_id, key, range_end, start_rev, prev_kv,
                    fragment=fragment, progress_notify=progress_notify,
                    filters=tuple(filters))
        if start_rev > cur:
            self.synced[watch_id] = w  # watchable_store.go:47-63
        else:
            self.unsynced[watch_id] = w
        return w

    def cancel(self, watch_id: int) -> bool:
        return (
            self.synced.pop(watch_id, None) is not None
            or self.unsynced.pop(watch_id, None) is not None
        )

    def restore(self, store: MVCCStore) -> None:
        """Install a snapshot store (the applySnapshot path,
        server.go:925-1061: the state machine jumps to the snapshot and
        every watcher re-syncs from history). Watchers whose start_rev was
        compacted away are cancelled with `compacted` by the next
        sync_watchers pass."""
        self.kv = store
        cur = store.current_rev
        for wid, w in list(self.synced.items()):
            if w.start_rev <= cur:  # future-rev watchers stay synced
                del self.synced[wid]
                self.unsynced[wid] = w

    # -- write-path publication (watchable_store_txn.go:22) ------------------
    def notify(self, events: list[tuple[str, KeyValue, KeyValue | None]]):
        for typ, kv, prev in events:
            for w in self.synced.values():
                if w.victim or not w.matches(kv.key):
                    continue
                if w.filtered(typ):
                    # filtered events are consumed, not deferred: the
                    # watcher stays current past them
                    w.start_rev = kv.mod_revision + 1
                    continue
                if len(w.buffer) >= Watcher.MAX_BUFFER:
                    # slow watcher becomes a victim; it will be re-synced
                    # from history later (victims queue). The catch-up path
                    # replays whole revisions, so roll back to the start of
                    # this (possibly multi-op) revision and drop its
                    # already-buffered prefix — otherwise those events would
                    # be delivered twice (sync_watchers' split-at-main-
                    # revision rule, applied to the victim path).
                    w.victim = True
                    rev = kv.mod_revision
                    while w.buffer and w.buffer[-1].kv.mod_revision == rev:
                        w.buffer.pop()
                    w.start_rev = rev
                    continue
                w.buffer.append(
                    Event(typ, kv, prev if w.prev_kv else None)
                )
                w.start_rev = kv.mod_revision + 1

    def apply_txn_events(self, txn_events) -> None:
        self.notify(txn_events)

    # -- catch-up (syncWatchersLoop, watchable_store.go:211-331) -------------
    def sync_watchers(self, batch: int = 512) -> int:
        """One catch-up pass: move ready unsynced/victim watchers to synced,
        emitting their missed history. Returns number synced."""
        moved = 0
        # victims rejoin the unsynced path
        for wid, w in list(self.synced.items()):
            if w.victim:
                del self.synced[wid]
                self.unsynced[wid] = w
        cur = self.kv.current_rev
        for wid, w in list(self.unsynced.items()):
            if w.start_rev <= self.kv.compact_rev:
                w.compacted = True  # client must restart (ErrCompacted)
                del self.unsynced[wid]
                moved += 1
                continue
            evs = self._history(w, w.start_rev, cur)
            room = Watcher.MAX_BUFFER - len(w.buffer)
            if len(evs) > room:
                # split only at a main-revision boundary: a multi-op txn's
                # events share one mod_revision, and resuming mid-revision
                # would re-emit the already-buffered part of it
                split = room
                while (
                    split > 0
                    and evs[split].kv.mod_revision
                    == evs[split - 1].kv.mod_revision
                ):
                    split -= 1
                if split == 0:
                    continue  # no room for a whole revision yet
                w.buffer.extend(evs[:split])
                w.start_rev = evs[split].kv.mod_revision
                continue  # still unsynced
            w.buffer.extend(evs)
            w.start_rev = cur + 1
            w.victim = False
            del self.unsynced[wid]
            self.synced[wid] = w
            moved += 1
        return moved

    def _history(self, w: Watcher, lo: int, hi: int) -> list[Event]:
        """Events for w in revision range [lo, hi] from the rev-keyed store
        (the kvsToEvents read of the backend, watchable_store.go:331)."""
        out = []
        for (main, sub), (kv, tomb) in sorted(self.kv.revs.items()):
            if main < lo or main > hi:
                continue
            if not w.matches(kv.key):
                continue
            typ = "delete" if tomb else "put"
            if w.filtered(typ):
                continue
            out.append(Event(typ, kv))
        return out

    # -- consumption (serverWatchStream sendLoop analog) ---------------------
    def take_events(self, watch_id: int, limit: int | None = None) -> list[Event]:
        """Drain up to `limit` buffered events (all if None). A fragmenting
        consumer passes a limit and re-polls; the remainder stays queued."""
        w = self.synced.get(watch_id) or self.unsynced.get(watch_id)
        if w is None:
            return []
        if limit is None or len(w.buffer) <= limit:
            evs, w.buffer = w.buffer, []
        else:
            evs, w.buffer = w.buffer[:limit], w.buffer[limit:]
        return evs

    def pending_events(self, watch_id: int) -> int:
        w = self.synced.get(watch_id) or self.unsynced.get(watch_id)
        return 0 if w is None else len(w.buffer)

    def get_watcher(self, watch_id: int) -> Watcher | None:
        return self.synced.get(watch_id) or self.unsynced.get(watch_id)

    def progress(self, watch_id: int) -> int | None:
        """Revision header for a progress notification: only a synced,
        fully-drained watcher may report progress (mvcc watchStream.
        RequestProgress: progress is sent iff the watcher is synced —
        otherwise the header would claim delivery through a revision whose
        events are still queued)."""
        w = self.synced.get(watch_id)
        if w is None or w.buffer or w.victim:
            return None
        return self.kv.current_rev
