"""Local tester (tools/local-tester analog): fault-injected live cluster
under client load — drops, isolation, partitions, crash+restart — with
post-heal verification of every acknowledged write."""
from etcd_tpu.localtester import run_local_tester


def test_local_tester_memory_cluster():
    rep = run_local_tester(cycles=3, seed=2, puts_per_phase=4)
    assert rep["healthy"], rep
    assert rep["puts_ok"] > 0
    assert set(rep["faults"]) <= {"drop_links", "isolate_member",
                                  "partition"}


def test_local_tester_crash_restart_cycle(tmp_path):
    rep = run_local_tester(cycles=4, seed=3, puts_per_phase=4,
                           data_dir=str(tmp_path))
    assert rep["healthy"], rep
    assert "crash_restart" in rep["faults"]
