"""Backend bucket layout + consistent-index persistence.

The reference's ``server/storage/schema`` defines the bbolt bucket names
(key/meta/lease/auth/alarm/members, schema/bucket.go:97) and the
consistent-index accessors (schema/cindex.go:85); ``cindex.Store``
(server/etcdserver/cindex/cindex.go:30-38) persists the applied
index+term inside the same backend transaction as the kv writes, so a
restarted member knows exactly which raft entries its backend reflects
and dedups replay.

Atomicity mapping: bbolt gives the reference multi-bucket transactional
commits. Our append-only backend's atomic unit is one CRC-framed record,
so the whole non-KV applied state rides in a single ``applied_meta``
record — (consistent_index, term, current_rev, compact_rev, lease, auth,
alarms) — written after each apply batch's revision records. Recovery
loads the last committed applied_meta and trims any revision records
beyond its ``current_rev``: a batch-commit boundary that splits a group
simply rolls the member back to the previous consistent point, exactly
the WAL+backend recovery contract (replay resumes at cindex).
"""
from __future__ import annotations

import pickle
import struct

from etcd_tpu.server.mvcc import KeyIndex, KeyValue, MVCCStore, Revision
from etcd_tpu.storage.backend import Backend

KEY_BUCKET = "key"
META_BUCKET = "meta"
MEMBERS_BUCKET = "members"

_REV = struct.Struct(">qi")  # main, sub — sorts correctly as bytes
_APPLIED_META_KEY = b"applied_meta"


def rev_to_bytes(main: int, sub: int) -> bytes:
    return _REV.pack(main, sub)


def bytes_to_rev(b: bytes) -> tuple[int, int]:
    return _REV.unpack(b)


def _enc_kv(kv: KeyValue, tomb: bool) -> bytes:
    return pickle.dumps(
        (kv.key, kv.value, kv.create_revision, kv.mod_revision, kv.version,
         kv.lease, tomb),
        protocol=4,
    )


def _dec_kv(blob: bytes) -> tuple[KeyValue, bool]:
    k, v, cr, mr, ver, lease, tomb = pickle.loads(blob)
    return KeyValue(k, v, cr, mr, ver, lease), tomb


# -- MVCC revision records ---------------------------------------------------
def persist_mvcc_delta(be: Backend, store: MVCCStore, last_rev: int) -> int:
    """Write every revision with main > last_rev to the key bucket;
    returns the new high-water main revision (storeTxnWrite.End ->
    batch_tx path, mvcc/kvstore_txn.go:182).

    ``store.revs`` is insertion-ordered (writes append chronologically;
    compaction only deletes), so the new tail is found by scanning from
    the end — O(delta), not O(history)."""
    new = []
    for key in reversed(store.revs):
        if key[0] <= last_rev:
            break
        new.append(key)
    for (main, sub) in reversed(new):
        kv, tomb = store.revs[(main, sub)]
        be.put(KEY_BUCKET, rev_to_bytes(main, sub), _enc_kv(kv, tomb))
    return store.current_rev


def persist_compaction(be: Backend, store: MVCCStore) -> None:
    """Drop revisions MVCC compaction removed (the scheduled-compaction
    delete pass, mvcc/kvstore_compaction.go)."""
    live = {rev_to_bytes(m, s) for (m, s) in store.revs}
    for k, _ in be.range(KEY_BUCKET, b"", b"\x00"):
        if k not in live:
            be.delete(KEY_BUCKET, k)


# -- the atomic applied-state record ----------------------------------------
def save_applied_meta(
    be: Backend, *, index: int, term: int, store: MVCCStore,
    lease_snap, auth_snap, alarms,
    cluster_version: str | None = None, downgrade: dict | None = None,
    v2: str | None = None,
) -> None:
    """One record = consistent index + MVCC cursors + the small applied
    sub-states (lease/auth/alarm buckets of the reference schema, plus
    the cluster-version / downgrade records of membership's backend
    buckets — cluster.go:263-269 recovers both on boot)."""
    be.put(
        META_BUCKET,
        _APPLIED_META_KEY,
        pickle.dumps(
            {
                "consistent_index": index,
                "term": term,
                "current_rev": store.current_rev,
                "compact_rev": store.compact_rev,
                "lease": lease_snap,
                "auth": auth_snap,
                "alarms": sorted(alarms),
                "cluster_version": cluster_version,
                "downgrade": downgrade,
                "v2": v2,
            },
            protocol=4,
        ),
    )


def load_applied_meta(be: Backend) -> dict | None:
    raw = be.get(META_BUCKET, _APPLIED_META_KEY)
    return pickle.loads(raw) if raw else None


def load_mvcc(be: Backend, max_rev: int | None = None,
              compact_rev: int = 0) -> MVCCStore:
    """Rebuild the MVCC store from the key bucket: replay revisions in
    (main, sub) order to reconstruct the keyIndex generations (the
    treeIndex rebuild on boot, mvcc/kvstore.go:59-113). Revisions past
    ``max_rev`` (a partially-committed batch) are dropped."""
    st = MVCCStore()
    for rk, blob in be.range(KEY_BUCKET, b"", b"\x00"):
        main, sub = bytes_to_rev(rk)
        if max_rev is not None and main > max_rev:
            continue
        kv, tomb = _dec_kv(blob)
        st.revs[(main, sub)] = (kv, tomb)
        st.size += len(kv.key) + len(kv.value)
        ki = st.index.get(kv.key)
        if ki is None:
            ki = KeyIndex(kv.key)
            st.index[kv.key] = ki
            st._sorted_dirty = True
        if tomb:
            ki.tombstone(Revision(main, sub))
        else:
            ki.put(Revision(main, sub))
    if max_rev is not None:
        st.current_rev = max(max_rev, 1)
    elif st.revs:
        st.current_rev = max(m for m, _ in st.revs)
    st.compact_rev = compact_rev
    return st


# ---- storage version (storage/schema/schema.go + version.go): the field
# was introduced "in 3.6" — its ABSENCE means the 3.5 layout. Migrate up
# writes it; migrate down removes it.
_STORAGE_VERSION_KEY = b"storage_version"
CURRENT_STORAGE_VERSION = "3.6"
MIN_STORAGE_VERSION = "3.5"


def set_storage_version(be: Backend, version: str | None) -> None:
    if version is None or version == MIN_STORAGE_VERSION:
        be.delete(META_BUCKET, _STORAGE_VERSION_KEY)
    else:
        be.put(META_BUCKET, _STORAGE_VERSION_KEY, version.encode())


def get_storage_version(be: Backend) -> str | None:
    """None = the pre-field (3.5-equivalent) layout."""
    raw = be.get(META_BUCKET, _STORAGE_VERSION_KEY)
    return raw.decode() if raw else None
