"""RaftConfig.deferred_emit: the emission restructure (PROFILE.md).

Equivalence contract: on live steady traffic (one append + one ack per
follower per round), the deferred-emission program — per-destination
PendingWire intents in the scan, one post-scan AppResp emit + merged
maybe_send_append — reproduces the immediate-emission steady program
bit-for-bit in both fleet state and the wire (inbox) tensors. The scan
body then writes no outbox planes at all, which is the point."""
import dataclasses

import numpy as np
import jax
import pytest

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.types import (
    ENTRY_NORMAL,
    MSG_APP,
    MSG_APP_RESP,
    MSG_PROP,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
FULL = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                  inbox_bound=4, coalesce_commit_refresh=True)
STEADY = dataclasses.replace(
    FULL, local_steps=("prop",),
    message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP))
DEFERRED = dataclasses.replace(STEADY, deferred_emit=True)
C = 4


def _elect(full):
    M, E = SPEC.M, SPEC.E
    state = init_fleet(SPEC, C, seed=0, election_tick=FULL.election_tick)
    inbox = empty_inbox(SPEC, C)
    z2 = np.zeros((M, C), np.int32)
    zp = np.zeros((M, E, C), np.int32)
    no = np.zeros((M, C), bool)
    keep = np.ones((M, M, C), bool)
    hup = no.copy()
    hup[0, :] = True
    state, inbox = full(state, inbox, z2, zp, zp, z2, hup, no, keep)
    for _ in range(12):
        state, inbox = full(state, inbox, z2, zp, zp, z2, no, no, keep)
    assert (np.asarray(state.role)[0] == ROLE_LEADER).all()
    return state, inbox, (z2, zp, no, keep)


def test_deferred_emit_requires_coalescing():
    with pytest.raises(ValueError, match="coalesce"):
        RaftConfig(deferred_emit=True)


def test_deferred_program_is_bit_identical_in_steady_state():
    full = jax.jit(build_round(FULL, SPEC))
    steady = jax.jit(build_round(STEADY, SPEC))
    deferred = jax.jit(build_round(DEFERRED, SPEC))
    state0, inbox0, (z2, zp, no, keep) = _elect(full)

    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = 7
    ptype = zp.copy()
    ptype[0, 0, :] = ENTRY_NORMAL

    sa, ia = state0, inbox0
    sb, ib = state0, inbox0
    for r in range(10):
        sa, ia = steady(sa, ia, plen, pdata, ptype, z2, no, no, keep)
        sb, ib = deferred(sb, ib, plen, pdata, ptype, z2, no, no, keep)
    assert int(np.asarray(sa.commit).min()) >= 8  # really replicating
    for name in sa.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        ), f"state.{name}"
    for name in ia.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(ia, name)), np.asarray(getattr(ib, name))
        ), f"inbox.{name}"


def test_deferred_program_heals_a_dropped_append():
    """Past bit-exactness: with one follower's inbound append dropped for
    a round (reject/probe path), the deferred program still converges all
    commits — the coalesced reply/send machinery heals like the immediate
    one."""
    deferred = jax.jit(build_round(DEFERRED, SPEC))
    full = jax.jit(build_round(FULL, SPEC))
    state, inbox, (z2, zp, no, keep) = _elect(full)

    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = 9
    ptype = zp.copy()
    ptype[0, 0, :] = ENTRY_NORMAL

    drop = keep.copy()
    drop[:, 2, :] = False  # member 2 receives nothing this round
    state, inbox = deferred(state, inbox, plen, pdata, ptype, z2, no, no,
                            drop)
    for _ in range(6):
        state, inbox = deferred(state, inbox, z2, zp, zp, z2, no, no,
                                keep)
    commits = np.asarray(state.commit)
    assert (commits[2] == commits[0]).all()  # the dropped member caught up
