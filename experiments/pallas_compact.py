"""Pallas TPU kernel experiment: fused inbox compaction.

`compact_inbox` (models/raft.py) squeezes each node's nonempty inbox
slots to the front: rank = cumsum(nonempty)-1 along the slot axis S,
then a [B, S] one-hot contraction per message field. In XLA this is ~17
separate fused reductions (one per field) sharing the recomputed rank;
the Pallas form does ONE pass: a C-tile of every field sits in VMEM,
rank is computed once, and all 17 outputs are written together —
a guaranteed single HBM read+write of the inbox per round instead of
whatever fusion split XLA picks.

Standalone experiment (SURVEY §7 step 4): run on the TPU with
    python experiments/pallas_compact.py
and compare against the XLA form at bench shapes. Results are recorded
in PROFILE.md; the engine adopts the kernel only if it wins.
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

S, B = 10, 4          # M*K slots in, inbox_bound out (bench geometry M=5)
N_FIELDS = 17         # Msg leaves


def _compact_kernel(*refs):
    """refs = (typ_ref, f1_ref..fN_ref, out_typ_ref, out_f1..out_fN).
    Block shapes [S, Ct] in, [B, Ct] out."""
    n = N_FIELDS
    typ_ref = refs[0]
    in_refs = refs[: n + 1]
    out_refs = refs[n + 1 :]
    typ = typ_ref[:]                                  # [S, Ct]
    nonempty = typ != 0
    # rank[s] = number of nonempty slots before s (cumsum isn't lowerable
    # on TPU Pallas yet; S is small and static, so unroll)
    count = jnp.zeros_like(typ[0])
    ranks = []
    for s in range(S):
        ranks.append(jnp.where(nonempty[s], count, -1))
        count = count + nonempty[s].astype(jnp.int32)
    sels = [
        [(ranks[s] == b).astype(jnp.int32) for s in range(S)]
        for b in range(B)
    ]
    for iref, oref in zip(in_refs, out_refs):
        x = iref[:]
        for b in range(B):
            acc = sels[b][0] * x[0]
            for s in range(1, S):
                acc = acc + sels[b][s] * x[s]
            oref[b, :] = acc


def pallas_compact(typ, fields, ct: int = 512):
    """typ [S, C] i32; fields: list of [S, C] i32. Returns ([B, C] typ,
    list of [B, C])."""
    C = typ.shape[1]
    grid = (C // ct,)
    in_specs = [
        pl.BlockSpec((S, ct), lambda i: (0, i)) for _ in range(N_FIELDS + 1)
    ]
    out_specs = [
        pl.BlockSpec((B, ct), lambda i: (0, i)) for _ in range(N_FIELDS + 1)
    ]
    out_shape = [
        jax.ShapeDtypeStruct((B, C), jnp.int32) for _ in range(N_FIELDS + 1)
    ]
    outs = pl.pallas_call(
        _compact_kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
    )(typ, *fields)
    return outs[0], list(outs[1:])


def xla_compact(typ, fields):
    """The engine's current form (models/raft.py compact_inbox)."""
    nonempty = typ != 0                                   # [S, C]
    rank = jnp.cumsum(nonempty.astype(jnp.int32), axis=0) - 1
    sel = (
        (rank[None] == jnp.arange(B, dtype=jnp.int32)[:, None, None])
        & nonempty[None]
    ).astype(jnp.int32)                                   # [B, S, C]
    out_t = (sel * typ[None]).sum(axis=1)
    outs = [(sel * f[None]).sum(axis=1) for f in fields]
    return out_t, outs


def main():
    C = 262_144
    key = jax.random.PRNGKey(0)
    typ = (jax.random.uniform(key, (S, C)) < 0.4).astype(jnp.int32) * 3
    fields = [
        jax.random.randint(jax.random.fold_in(key, i), (S, C), 0, 1000)
        for i in range(N_FIELDS)
    ]

    fx = jax.jit(xla_compact)

    def bench(f, n=50):
        f(typ, fields)
        t0 = time.perf_counter()
        for _ in range(n):
            out = f(typ, fields)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n * 1e3

    rx = fx(typ, fields)
    bytes_touched = (S + B) * C * 4 * (N_FIELDS + 1)
    tx = bench(fx)
    print(f"XLA          : {tx:.3f} ms  ({bytes_touched / tx / 1e6:.0f} GB/s)")
    for ct in (512, 1024, 2048):
        fp = jax.jit(functools.partial(pallas_compact, ct=ct))
        rp = fp(typ, fields)
        same = all(
            jnp.array_equal(a, b)
            for a, b in zip([rx[0]] + rx[1], [rp[0]] + rp[1])
        )
        tp = bench(fp)
        print(f"Pallas ct={ct:5d}: {tp:.3f} ms  "
              f"({bytes_touched / tp / 1e6:.0f} GB/s)  identical={same}  "
              f"speedup={tx / tp:.2f}x")


if __name__ == "__main__":
    main()
