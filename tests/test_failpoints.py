"""Deterministic failpoints in the host pipeline — gofail analogs
(markers at server/etcdserver/raft.go:221-302; tester trigger at
tests/functional/tester/case_failpoints.go:207): kill the 'process' at
each persist/commit/snapshot boundary and verify the member recovers from
disk to a state consistent with its peers.
"""
import pytest

from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.utils import failpoints
from etcd_tpu.utils.failpoints import FailpointPanic


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def test_failpoint_registry_semantics():
    failpoints.enable("raftBeforeSave")
    assert failpoints.enabled("raftBeforeSave")
    with pytest.raises(FailpointPanic):
        failpoints.fire("raftBeforeSave")
    # panic is one-shot (the process died); the site is disarmed
    failpoints.fire("raftBeforeSave")
    # count-armed: fires on the N-th passage
    failpoints.enable("backendBeforeCommit", count=3)
    failpoints.fire("backendBeforeCommit")
    failpoints.fire("backendBeforeCommit")
    with pytest.raises(FailpointPanic):
        failpoints.fire("backendBeforeCommit")
    # unknown actions are inert, off disables
    failpoints.enable("raftAfterSave", action="print")
    failpoints.fire("raftAfterSave")
    failpoints.disable("raftAfterSave")
    failpoints.fire("raftAfterSave")


def test_failpoint_env_wire_format(monkeypatch):
    monkeypatch.setenv("ETCD_TPU_FAILPOINTS",
                       "raftBeforeSave=panic;raftAfterSave=off")
    failpoints.clear()
    failpoints._load_env()
    assert failpoints.enabled("raftBeforeSave")
    assert not failpoints.enabled("raftAfterSave")


@pytest.mark.parametrize("point", [
    "raftBeforeSave", "raftAfterSave",
    "backendBeforeCommit", "backendAfterCommit",
])
def test_crash_at_persist_boundary_recovers(tmp_path, point):
    """Kill member 0 at each persist-path marker mid-write, restart it from
    disk, and require convergence with the surviving quorum (the
    FAILPOINTS functional case: inject -> recover -> check KV_HASH)."""
    ec = EtcdCluster(data_dir=str(tmp_path / point))
    ec.ensure_leader()
    for ms in ec.members:
        # shrink the batch-commit cadence so the commit-path markers fire
        # within a handful of puts (the 100ms batchInterval analog)
        ms.backend.batch_limit = 4
    for i in range(4):
        ec.put(b"pre/%d" % i, b"v%d" % i)
    ec.stabilize()

    failpoints.enable(point)
    died = False
    try:
        for i in range(6):  # enough passes to cross the commit cadence
            ec.put(b"during/%d" % i, b"x")
    except FailpointPanic as e:
        died = True
        assert e.name == point
    assert died, f"{point} never fired on the write path"

    # the 'process' that hit the failpoint dies mid-persist (members are
    # persisted in order, so member 0 was the one interrupted)
    ec.crash_member(0)
    ec.restart_member_from_disk(0)
    ec.stabilize()
    assert not ec.members[0].crashed
    # recovery invariant: all members converge to the same KV hash
    h = {ec.hash_kv(m) for m in range(3)}
    assert len(h) == 1, f"diverged after crash at {point}: {h}"
    ec.corruption_check()
    # the cluster remains live
    ec.put(b"post", b"alive")
    ec.stabilize()
    assert ec.range(b"post")["kvs"][0].value == b"alive"


def test_crash_at_snapshot_install_recovers(tmp_path):
    """Crash during peer-snapshot install (raftBeforeApplySnap): the member
    restarts and a second install completes."""
    ec = EtcdCluster(data_dir=str(tmp_path / "snap"))
    ec.ensure_leader()
    ec.put(b"k", b"v")
    ec.stabilize()
    # force a state where member 1 needs a peer snapshot: crash it, write
    # past the payload GC floor, then let _pump try to catch it up
    ec.crash_member(1)
    for i in range(8):
        ec.put(b"g/%d" % i, b"x")
    ec.stabilize()
    failpoints.enable("raftBeforeApplySnap")
    try:
        ec.restart_member_from_disk(1)
        fired = False
    except FailpointPanic:
        fired = True
    failpoints.clear()
    if fired:
        # died mid-install: restart again, clean
        ec.crash_member(1)
        ec.restart_member_from_disk(1)
    ec.stabilize()
    assert ec.hash_kv(1) == ec.hash_kv(0)
    ec.corruption_check()
