"""Integration tier — the analog of tests/integration/v3_grpc_test.go et al:
client-visible KV/Txn/Watch/Lease/Auth/Maintenance semantics served through
real consensus on the batched engine (multi-member in one process, like the
reference's in-process cluster over unix sockets, tests/integration/
cluster.go:126-205)."""
import numpy as np
import pytest

from etcd_tpu.server.kvserver import Compare, EtcdCluster, Op, ServerError
from etcd_tpu.server.mvcc import ErrCompacted


@pytest.fixture(scope="module")
def ec():
    cl = EtcdCluster(n_members=3)
    cl.ensure_leader()
    return cl


def test_put_range_linearizable(ec):
    res = ec.put(b"foo", b"bar")
    assert res["rev"] >= 2
    got = ec.range(b"foo")
    assert [kv.value for kv in got["kvs"]] == [b"bar"]
    assert got["kvs"][0].create_revision == res["rev"]
    assert got["kvs"][0].version == 1
    # overwrite bumps version + mod_revision, keeps create_revision
    res2 = ec.put(b"foo", b"baz", prev_kv=True)
    assert res2["prev_kv"].value == b"bar"
    got = ec.range(b"foo")
    assert got["kvs"][0].version == 2
    assert got["kvs"][0].create_revision == res["rev"]
    assert got["kvs"][0].mod_revision == res2["rev"]


def test_range_prefix_and_rev(ec):
    ec.put(b"k/a", b"1")
    r = ec.put(b"k/b", b"2")
    ec.put(b"k/c", b"3")
    got = ec.range(b"k/", b"k0")  # prefix scan
    assert [kv.key for kv in got["kvs"]] == [b"k/a", b"k/b", b"k/c"]
    # historical read at the revision where only a,b existed
    got = ec.range(b"k/", b"k0", rev=r["rev"])
    assert [kv.key for kv in got["kvs"]] == [b"k/a", b"k/b"]
    # limit + count
    got = ec.range(b"k/", b"k0", limit=2)
    assert len(got["kvs"]) == 2 and got["count"] == 3


def test_delete_range(ec):
    ec.put(b"d/1", b"x")
    ec.put(b"d/2", b"y")
    res = ec.delete_range(b"d/", b"d0", prev_kv=True)
    assert res["deleted"] == 2
    assert {kv.key for kv in res["prev_kvs"]} == {b"d/1", b"d/2"}
    assert ec.range(b"d/", b"d0")["count"] == 0


def test_txn_compare_and_ops(ec):
    ec.put(b"t", b"v1")
    res = ec.txn(
        compare=[Compare(b"t", "value", "=", b"v1")],
        success=[Op("put", b"t", b"v2"), Op("range", b"t")],
        failure=[Op("put", b"t", b"nope")],
    )
    assert res["succeeded"] is True
    assert ec.range(b"t")["kvs"][0].value == b"v2"
    # failed compare takes the failure branch
    res = ec.txn(
        compare=[Compare(b"t", "version", "=", 1)],
        success=[Op("put", b"t", b"x")],
        failure=[Op("delete", b"t")],
    )
    assert res["succeeded"] is False
    assert ec.range(b"t")["count"] == 0


def test_txn_intra_txn_visibility(ec):
    """Ops within one txn see earlier ops of the same txn (kvstore_txn.go
    read buffer): put+delete deletes, put+put bumps version, mid-txn range
    observes the put."""
    res = ec.txn(
        compare=[],
        success=[Op("put", b"iv", b"x"), Op("range", b"iv"), Op("delete", b"iv")],
    )
    assert res["responses"][1][2] == 1        # mid-txn range saw the put
    assert res["responses"][2][1] == 1        # delete found it
    assert ec.range(b"iv")["count"] == 0      # net effect: gone
    res = ec.txn(
        compare=[],
        success=[Op("put", b"iv2", b"a"), Op("put", b"iv2", b"b")],
    )
    got = ec.range(b"iv2")
    assert got["kvs"][0].version == 2 and got["kvs"][0].value == b"b"


def test_serializable_read_any_member(ec):
    ec.put(b"s", b"1")
    # serializable reads skip the ReadIndex barrier and may lag; after the
    # commit index propagates (next heartbeat round) every member serves it
    ec.tick()
    ec.stabilize()
    for m in range(3):
        got = ec.range(b"s", serializable=True, member=m)
        assert [kv.value for kv in got["kvs"]] == [b"1"]


def test_compact(ec):
    ec.put(b"c", b"1")
    r2 = ec.put(b"c", b"2")
    ec.put(b"c", b"3")
    ec.compact(r2["rev"])
    with pytest.raises(ErrCompacted):
        ec.range(b"c", rev=r2["rev"] - 1)
    assert ec.range(b"c")["kvs"][0].value == b"3"


def test_watch_current_and_historic(ec):
    lead = ec.ensure_leader()
    w = ec.watch(lead, b"w/", b"w0")
    ec.put(b"w/1", b"a")
    ec.delete_range(b"w/1")
    evs = ec.watch_events(lead, w.id)
    assert [(e.type, e.kv.key) for e in evs] == [
        ("put", b"w/1"), ("delete", b"w/1"),
    ]
    # historical watch: start_rev in the past replays from history
    start = ec.range(b"w/", b"w0")["rev"]
    ec.put(b"w/2", b"b")
    w2 = ec.watch(lead, b"w/", b"w0", start_rev=start)
    evs = ec.watch_events(lead, w2.id)
    assert ("put", b"w/2") in [(e.type, e.kv.key) for e in evs]
    assert ec.cancel_watch(lead, w2.id)


def test_lease_attach_and_revoke(ec):
    ec.lease_grant(100, ttl=50)
    ec.put(b"l/1", b"x", lease=100)
    ttl = ec.lease_time_to_live(100)
    assert ttl["keys"] == [b"l/1"]
    ec.lease_revoke(100)
    assert ec.range(b"l/1")["count"] == 0
    assert 100 not in ec.leases()


def test_lease_expiry_through_consensus(ec):
    ec.lease_grant(200, ttl=3)
    ec.put(b"l/2", b"y", lease=200)
    for _ in range(10):
        ec.tick()
        if 200 not in ec.leases():
            break
    assert 200 not in ec.leases()
    assert ec.range(b"l/2")["count"] == 0


def test_lease_keepalive(ec):
    ec.lease_grant(300, ttl=4)
    for _ in range(8):
        ec.tick()
        ec.lease_keepalive(300)
    assert 300 in ec.leases()  # survived well past its TTL
    ec.lease_revoke(300)


def test_membership_learner_promotion():
    ec = EtcdCluster(cluster=__import__(
        "etcd_tpu.harness.cluster", fromlist=["Cluster"]
    ).Cluster(n_members=4, voters=[True, True, True, False]))
    ec.ensure_leader()
    ec.put(b"m", b"1")
    ec.member_add(3, learner=True)
    cfg = ec.member_config()
    assert cfg.learners == {3}
    ec.stabilize()
    ec.member_promote(3)
    cfg = ec.member_config()
    assert cfg.voters == {0, 1, 2, 3}
    # remove again
    ec.member_remove(3)
    assert ec.member_config().voters == {0, 1, 2}
    # validation: removing a non-member fails host-side
    from etcd_tpu.models.changer import ConfChangeError

    with pytest.raises(Exception):
        ec.member_remove(3)
        ec.member_remove(3)


def test_auth_end_to_end(ec):
    ec.auth_request("auth_user_add", name="root", password="pw")
    ec.auth_request("auth_role_add", name="root")
    ec.auth_request("auth_user_grant_role", name="root", role="root")
    ec.auth_request("auth_user_add", name="alice", password="apw")
    ec.auth_request("auth_role_add", name="reader")
    from etcd_tpu.server.auth import Permission, READ, ErrPermissionDenied

    ec.auth_request(
        "auth_role_grant_permission", role="reader",
        perm=Permission(READ, b"a/", b"a0"),
    )
    ec.auth_request("auth_user_grant_role", name="alice", role="reader")
    ec.put(b"a/1", b"v")  # before enable: no token needed
    ec.auth_request("auth_enable")
    root_tok = ec.authenticate("root", "pw")
    alice_tok = ec.authenticate("alice", "apw")
    # root can write
    ec.put(b"a/2", b"v", token=root_tok)
    # alice can read her range but not write it
    got = ec.range(b"a/1", token=alice_tok)
    assert got["count"] == 1
    with pytest.raises(ErrPermissionDenied):
        ec.put(b"a/3", b"v", token=alice_tok)
    with pytest.raises(ErrPermissionDenied):
        ec.range(b"b", token=alice_tok)
    # ACL change invalidates old tokens (auth revision check)
    from etcd_tpu.server.auth import ErrAuthOldRevision

    ec.auth_request("auth_role_add", name="other")
    with pytest.raises(ErrAuthOldRevision):
        ec.range(b"a/1", token=alice_tok)
    ec.auth_request("auth_disable")


def test_maintenance_status_hash_corruption(ec):
    ec.put(b"z", b"1")
    st = ec.status(0)
    assert st["leader"] == ec.leader()
    assert st["raft_applied_index"] > 0
    ec.stabilize()
    # all members at same applied index agree on KV hash
    ec.corruption_check()
    snap = ec.snapshot(0)
    from etcd_tpu.server.mvcc import MVCCStore

    st2 = MVCCStore.from_snapshot(snap["kv"])
    kvs, cnt, _ = st2.range(b"z")
    assert cnt == 1 and kvs[0].value == b"1"


def test_quota_nospace_alarm():
    ec = EtcdCluster(n_members=3, quota_bytes=64)
    ec.ensure_leader()
    ec.put(b"q", b"x" * 100)  # exceeds quota; alarm activates
    from etcd_tpu.server.kvserver import ErrNoSpace

    with pytest.raises(ErrNoSpace):
        ec.put(b"q2", b"y")
    # alarm disarm restores writes
    ec.alarm("deactivate", "NOSPACE")
    ec.quota_bytes = 0
    ec.put(b"q2", b"y")
