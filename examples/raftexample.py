"""raftexample — a minimal replicated KV on the raw consensus core.

The ``contrib/raftexample`` analog (kvstore.go + raft.go + httpapi.go):
the canonical "how to drive RawNode" program. N nodes each own a
``RawNode`` over a ``MemoryStorage``; the driver loop mirrors the
reference's raft.go serveChannels Ready cycle —

    rd = node.ready()
    save rd.hard_state + rd.entries to storage   (wal.Save analog)
    apply rd.snapshot if set
    send rd.messages over the network            (transport.Send)
    apply rd.committed_entries to the kv store
    node.advance(rd)

— with the in-process message exchange standing in for rafthttp (drop
is legal, so the dict-based network may lose messages under test
faults). Proposals carry int32 words resolved through a shared payload
table, exactly like the server runtime's payloadRef scheme.

Run: ``python -m examples.raftexample`` (3-node demo: elect, replicate
a few puts, print each node's store).
"""
from __future__ import annotations

import dataclasses

from etcd_tpu.models.rawnode import RawNode, Ready
from etcd_tpu.storage.raftstorage import (
    ConfState,
    MemoryStorage,
    Snapshot,
    SnapshotMeta,
)
from etcd_tpu.types import ENTRY_NORMAL, ROLE_LEADER, Spec
from etcd_tpu.utils.config import RaftConfig


@dataclasses.dataclass
class Proposal:
    key: str
    value: str


class KVStore:
    """kvstore.go: the applied state machine — a dict fed by committed
    entries; words resolve through the shared proposal table."""

    def __init__(self, proposals: dict[int, Proposal]):
        self.proposals = proposals
        self.data: dict[str, str] = {}
        self.applied_words: list[int] = []

    def apply(self, word: int) -> None:
        if word == 0:
            return  # empty (leader-election) entry
        p = self.proposals.get(word)
        if p is None:
            return  # foreign/unknown ref after a restart
        self.data[p.key] = p.value
        self.applied_words.append(word)

    def lookup(self, key: str) -> str | None:
        return self.data.get(key)


class RaftExampleNode:
    """raft.go raftNode: one member's RawNode + storage + kv bundle."""

    def __init__(self, cfg: RaftConfig, spec: Spec, nid: int,
                 proposals: dict[int, Proposal],
                 storage: MemoryStorage | None = None):
        if storage is None:
            # bootstrap a fresh member with the initial voter set
            # (raftexample boots via raft.StartNode(peers); here the
            # voter ConfState arrives as the bootstrap snapshot meta)
            storage = MemoryStorage()
            storage.apply_snapshot(Snapshot(meta=SnapshotMeta(
                index=1, term=1,
                conf_state=ConfState(voters=tuple(range(spec.M))))))
        self.storage = storage
        applied = storage.snapshot().meta.index
        self.node = RawNode(cfg, spec, self.storage, nid, applied=applied)
        self.kv = KVStore(proposals)
        self.nid = nid

    def process_ready(self, network: "Network") -> None:
        # serveChannels' Ready cycle (contrib/raftexample/raft.go)
        if not self.node.has_ready():
            return
        rd: Ready = self.node.ready()
        if rd.hard_state is not None:
            self.storage.set_hard_state(rd.hard_state)
        if rd.entries:
            self.storage.append(list(rd.entries))
        if rd.snapshot is not None:
            self.storage.apply_snapshot(rd.snapshot)
        for hm in rd.messages:
            network.send(hm)
        for e in rd.committed_entries:
            if e.type == ENTRY_NORMAL:
                self.kv.apply(e.data)
        self.node.advance(rd)


class Network:
    """The in-process rafthttp stand-in: per-node inboxes with optional
    drop masks (Send MUST NOT block / drop is OK)."""

    def __init__(self, nodes: dict[int, RaftExampleNode]):
        self.nodes = nodes
        self.inboxes: dict[int, list] = {n: [] for n in nodes}
        self.drop: set[tuple[int, int]] = set()  # (frm, to) pairs

    def send(self, hm) -> None:
        if (hm.frm, hm.to) in self.drop:
            return
        if hm.to in self.inboxes:
            self.inboxes[hm.to].append(hm)

    def deliver(self) -> int:
        moved = 0
        for nid, box in self.inboxes.items():
            msgs, self.inboxes[nid] = box, []
            for hm in msgs:
                self.nodes[nid].node.step(hm)
                moved += 1
        return moved


class Cluster:
    """The whole example: nodes + network + the httpapi-style front."""

    def __init__(self, n: int = 3, cfg: RaftConfig | None = None):
        spec = Spec(M=max(n, 3), L=32, E=1, K=2, W=4, R=2, A=4)
        cfg = cfg or RaftConfig()
        self.spec, self.cfg = spec, cfg
        self.proposals: dict[int, Proposal] = {}
        self._next_word = 1
        self.nodes = {
            i: RaftExampleNode(cfg, spec, i, self.proposals)
            for i in range(n)
        }
        self.network = Network(self.nodes)

    # -- driver
    def pump(self, rounds: int = 1) -> None:
        for _ in range(rounds):
            for node in self.nodes.values():
                node.process_ready(self.network)
            self.network.deliver()

    def settle(self, max_rounds: int = 64) -> None:
        for _ in range(max_rounds):
            self.pump()
            if not any(self.inflight()):
                return

    def inflight(self):
        return [len(b) for b in self.network.inboxes.values()] + \
            [1 for n in self.nodes.values() if n.node.has_ready()]

    def elect(self, nid: int = 0) -> int:
        self.nodes[nid].node.campaign()
        self.settle()
        return self.leader()

    def leader(self) -> int:
        for i, n in self.nodes.items():
            if n.node.status().soft_state.role == ROLE_LEADER:
                return i
        return -1

    # -- httpapi.go front: PUT proposes, GET serves the local store
    def put(self, key: str, value: str) -> None:
        lead = self.leader()
        if lead < 0:
            raise RuntimeError("no leader")
        word = self._next_word
        self._next_word += 1
        self.proposals[word] = Proposal(key, value)
        self.nodes[lead].node.propose(word)
        self.settle()

    def get(self, key: str, nid: int = 0) -> str | None:
        return self.nodes[nid].kv.lookup(key)


def main() -> int:
    c = Cluster(3)
    lead = c.elect(0)
    print(f"leader: node {lead}")
    for k, v in (("hello", "world"), ("foo", "bar"), ("x", "42")):
        c.put(k, v)
    for nid, node in sorted(c.nodes.items()):
        print(f"node {nid}: {dict(sorted(node.kv.data.items()))}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
