"""raftpb conf-change value types + wire codec (raft/raftpb/confchange.go).

The device fleet runs conf changes as packed int32 words (at most two
changes — models/confchange.py), which covers every replicated-path use.
This module is the HOST-side raftpb analog for everything around that
core: full ``ConfChangeV2`` values with arbitrary change lists and
context bytes, the v1 type, ``as_v1``/``as_v2`` conversion,
``marshal_conf_change`` → (entry type, bytes), the EnterJoint/LeaveJoint
classification (confchange.go:70-107), and the ``v1 l2 r3 u4`` string
grammar (confchange.go:112-168) used by tests and tooling.

The byte format is a little-endian varint TLV, not gogo-protobuf — the
reference's generated marshalling is an implementation detail; what
matters is a stable, self-describing round trip.
"""
from __future__ import annotations

import dataclasses

from etcd_tpu.models import confchange as ccmod
from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    CC_UPDATE_NODE,
    ENTRY_CONF_CHANGE,
)

# ConfChangeTransition (raft.pb.go): how joint consensus is entered/left
TRANSITION_AUTO = 0
TRANSITION_JOINT_IMPLICIT = 1
TRANSITION_JOINT_EXPLICIT = 2

_TYPE_CHARS = {
    "v": CC_ADD_NODE,
    "l": CC_ADD_LEARNER,
    "r": CC_REMOVE_NODE,
    "u": CC_UPDATE_NODE,
}
_CHAR_TYPES = {v: k for k, v in _TYPE_CHARS.items()}


@dataclasses.dataclass(frozen=True)
class ConfChangeSingle:
    """raftpb.ConfChangeSingle: one (type, node) operation."""

    type: int
    node_id: int


@dataclasses.dataclass(frozen=True)
class ConfChange:
    """Legacy v1 conf change (one operation, EntryConfChange)."""

    type: int
    node_id: int
    context: bytes = b""

    def as_v2(self) -> "ConfChangeV2":
        return ConfChangeV2(
            changes=(ConfChangeSingle(self.type, self.node_id),),
            context=self.context,
        )

    def as_v1(self) -> "ConfChange | None":
        return self

    def marshal(self) -> bytes:
        return b"\x01" + _enc_varint(self.type) + _enc_varint(
            self.node_id
        ) + _enc_bytes(self.context)


@dataclasses.dataclass(frozen=True)
class ConfChangeV2:
    """raftpb.ConfChangeV2: N operations + transition + context."""

    changes: tuple[ConfChangeSingle, ...] = ()
    transition: int = TRANSITION_AUTO
    context: bytes = b""

    def as_v2(self) -> "ConfChangeV2":
        return self

    def as_v1(self) -> ConfChange | None:
        return None

    def enter_joint(self) -> tuple[bool, bool]:
        """(autoLeave, useJoint) — confchange.go:70-99: joint consensus is
        used for multi-change batches or any explicit transition."""
        if self.transition != TRANSITION_AUTO or len(self.changes) > 1:
            if self.transition in (TRANSITION_AUTO,
                                   TRANSITION_JOINT_IMPLICIT):
                return True, True
            if self.transition == TRANSITION_JOINT_EXPLICIT:
                return False, True
            raise ValueError(f"unknown transition {self.transition}")
        return False, False

    def leave_joint(self) -> bool:
        """confchange.go:101-107: zero value (context aside) = leave."""
        return not self.changes and self.transition == TRANSITION_AUTO

    def marshal(self) -> bytes:
        out = [b"\x02", _enc_varint(self.transition),
               _enc_varint(len(self.changes))]
        for ch in self.changes:
            out.append(_enc_varint(ch.type))
            out.append(_enc_varint(ch.node_id))
        out.append(_enc_bytes(self.context))
        return b"".join(out)


def marshal_conf_change(cc) -> tuple[int, bytes]:
    """MarshalConfChange (confchange.go:34-47): v1 values keep the legacy
    entry type; everything else marshals as v2."""
    from etcd_tpu.types import ENTRY_CONF_CHANGE_V2

    v1 = cc.as_v1()
    if v1 is not None:
        return ENTRY_CONF_CHANGE, v1.marshal()
    return ENTRY_CONF_CHANGE_V2, cc.as_v2().marshal()


def unmarshal_conf_change(data: bytes):
    """Inverse of ConfChange/ConfChangeV2.marshal (tag byte selects)."""
    if not data:
        raise ValueError("empty conf-change payload")
    tag, pos = data[0], 1
    if tag == 1:
        typ, pos = _dec_varint(data, pos)
        nid, pos = _dec_varint(data, pos)
        ctx, pos = _dec_bytes(data, pos)
        return ConfChange(typ, nid, ctx)
    if tag == 2:
        tr, pos = _dec_varint(data, pos)
        n, pos = _dec_varint(data, pos)
        chs = []
        for _ in range(n):
            typ, pos = _dec_varint(data, pos)
            nid, pos = _dec_varint(data, pos)
            chs.append(ConfChangeSingle(typ, nid))
        ctx, pos = _dec_bytes(data, pos)
        return ConfChangeV2(tuple(chs), tr, ctx)
    raise ValueError(f"bad conf-change tag {tag}")


# -- string grammar (confchange.go:112-168) ---------------------------------
def conf_changes_from_string(s: str) -> tuple[ConfChangeSingle, ...]:
    """Parse "v1 l2 r3 u4" (0-based ids are the caller's concern; this
    keeps the reference's 1-based surface verbatim)."""
    out = []
    for tok in s.split():
        if tok[0] not in _TYPE_CHARS:
            raise ValueError(f"unknown input: {tok}")
        out.append(ConfChangeSingle(_TYPE_CHARS[tok[0]], int(tok[1:])))
    return tuple(out)


def conf_changes_to_string(ccs) -> str:
    return " ".join(f"{_CHAR_TYPES[c.type]}{c.node_id}" for c in ccs)


# -- device-word bridge ------------------------------------------------------
def to_word(cc) -> int:
    """Pack for the device fleet (models/confchange.py layout). Only
    batches of <= 2 changes exist on the replicated device path; larger
    batches stay host-side (the leader's joint guard demotes them before
    they ever reach a device entry)."""
    v2 = cc.as_v2()
    if v2.leave_joint():
        return ccmod.encode_leave_joint()
    if len(v2.changes) > 2:
        raise ValueError(
            "device conf-change words carry at most 2 changes; "
            f"got {len(v2.changes)}"
        )
    auto, joint = v2.enter_joint()
    return ccmod.encode(
        [(c.type, c.node_id) for c in v2.changes],
        enter_joint=joint, auto_leave=auto,
    )


def _enc_varint(v: int) -> bytes:
    if v < 0:
        raise ValueError("varint must be non-negative")
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _dec_varint(data: bytes, pos: int) -> tuple[int, int]:
    shift = v = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


def _enc_bytes(b: bytes) -> bytes:
    return _enc_varint(len(b)) + b


def _dec_bytes(data: bytes, pos: int) -> tuple[bytes, int]:
    n, pos = _dec_varint(data, pos)
    if pos + n > len(data):
        raise ValueError("truncated bytes field")
    return data[pos:pos + n], pos + n
