"""Mirror syncer — clientv3/mirror parity (client/v3/mirror/syncer.go).

``Syncer.sync_base()`` streams the source's key-value state pinned at one
revision in paginated batches (syncer.go:49-104: WithLimit(batchLimit) +
WithRev, advancing past the last key of each page); ``sync_updates()``
returns a watch handle on the prefix starting at rev+1 (syncer.go:106-111).
``make_mirror`` is the etcdctl make-mirror loop built on them: replay the
base state then apply watch events to the destination.
"""
from __future__ import annotations

from etcd_tpu.client import Client, prefix_range_end

BATCH_LIMIT = 1000  # syncer.go:25


class Syncer:
    def __init__(self, client: Client, prefix: bytes = b"", rev: int = 0):
        self.c = client
        self.prefix = prefix
        self.rev = rev

    def sync_base(self, batch_limit: int = BATCH_LIMIT):
        """Yield pages (lists of KeyValue) of the source state at one fixed
        revision. Sets self.rev to that revision (syncer.go:53-60)."""
        if self.rev == 0:
            # pin the revision with a cheap read, like syncer.go's Get("foo")
            res = self.c.get_range(self.prefix or b"\x00", b"\x00", limit=1)
            self.rev = int(res["header"].revision)
        if self.prefix:
            key, end = self.prefix, prefix_range_end(self.prefix)
        else:
            key, end = b"\x00", b"\x00"  # whole keyspace, WithFromKey
        while True:
            res = self.c.get_range(
                key, end, rev=self.rev, limit=batch_limit, serializable=True,
            )
            kvs = res["kvs"]
            if kvs:
                yield kvs
            if len(kvs) < batch_limit or not kvs:
                return
            key = kvs[-1].key + b"\x00"  # move past the last key

    def sync_updates(self):
        """Watch handle for updates after the base revision
        (syncer.go:106-111). sync_base must have pinned the revision."""
        if self.rev == 0:
            raise RuntimeError(
                "unexpected revision = 0. Calling sync_updates before "
                "sync_base finishes?"
            )
        if self.prefix:
            return self.c.watch(self.prefix, prefix_range_end(self.prefix),
                                start_rev=self.rev + 1)
        return self.c.watch(b"\x00", b"\x00", start_rev=self.rev + 1)


def make_mirror(src: Client, dst: Client, prefix: bytes = b"",
                batch_limit: int = BATCH_LIMIT) -> "Mirror":
    """etcdctl make-mirror analog (etcdctl/ctlv3/command/make_mirror_command
    .go): full base copy, then an incremental pump the caller drives."""
    s = Syncer(src, prefix)
    n = 0
    for page in s.sync_base(batch_limit):
        for kv in page:
            dst.put(kv.key, kv.value)
            n += 1
    return Mirror(s.sync_updates(), dst, base_keys=n)


class Mirror:
    """The update pump: apply watched source events to the destination."""

    def __init__(self, watch_handle, dst: Client, base_keys: int = 0):
        self.watch = watch_handle
        self.dst = dst
        self.base_keys = base_keys
        self.applied = 0

    def pump(self) -> int:
        """Apply all currently-available update events; returns how many."""
        evs = self.watch.events()
        for e in evs:
            if e.type == "put":
                self.dst.put(e.kv.key, e.kv.value)
            else:
                self.dst.delete(e.kv.key)
        self.applied += len(evs)
        return len(evs)
