"""Run-level Raft knobs — parity with the reference's ``raft.Config``
(raft/raft.go:116-199), minus the Go-runtime-specific fields (Storage/Logger)
and with byte limits re-expressed as entry counts (payloads are fixed-width
words on device).

These are *static* (trace-time) parameters: they select code paths and
bounds inside the jitted step, so changing them recompiles.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    # tick counts (raft.Config.ElectionTick/HeartbeatTick)
    election_tick: int = 10
    heartbeat_tick: int = 1
    # flow control: raft.Config.MaxInflightMsgs; must be <= Spec.W
    max_inflight: int = 4
    # raft.Config.MaxUncommittedEntriesSize, in entries (0 disables like ref)
    max_uncommitted: int = 0
    # raft.Config.PreVote (thesis §9.6)
    pre_vote: bool = False
    # raft.Config.CheckQuorum (leader steps down without quorum contact)
    check_quorum: bool = False
    # raft.Config.ReadOnlyOption: False=ReadOnlySafe, True=ReadOnlyLeaseBased
    read_only_lease_based: bool = False
    # raft.Config.DisableProposalForwarding
    disable_proposal_forwarding: bool = False
    # Unroll the per-round message loop into straight-line XLA instead of a
    # lax.scan. On TPU each while-loop iteration carries a large fixed
    # runtime cost, so unrolling is ~20x faster per round at fleet shapes;
    # the price is a ~(M*K)x larger graph and correspondingly slower first
    # compile, which is wrong for the (CPU, many-Spec) test suite. Perf
    # paths (bench, entry) turn this on.
    unroll_messages: bool = False

    def __post_init__(self):
        if self.heartbeat_tick <= 0:
            raise ValueError("heartbeat tick must be greater than 0")
        if self.election_tick <= self.heartbeat_tick:
            raise ValueError("election tick must be greater than heartbeat tick")
        if self.read_only_lease_based and not self.check_quorum:
            raise ValueError("CheckQuorum must be enabled for lease-based reads")

    @property
    def max_uncommitted_entries(self) -> int:
        return self.max_uncommitted if self.max_uncommitted > 0 else (1 << 30)
