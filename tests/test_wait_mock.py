"""pkg/wait + server/mock analogs (utils/wait.py, harness/mock.py):
register/trigger matching, logical-deadline waits, duplicate-id refusal
(wait_test.go), and the recording/error-injecting storage double driving
a real RawNode error path.
"""
import pytest

from etcd_tpu.harness.mock import RecordingStorage, RecordingWait
from etcd_tpu.storage.raftstorage import Entry, ErrCompacted, MemoryStorage
from etcd_tpu.utils.wait import Wait, WaitTime


def test_wait_register_trigger():
    w = Wait()
    a = w.register(1)
    b = w.register(2)
    assert w.is_registered(1) and w.is_registered(2)
    w.trigger(1, "one")
    assert a.done and a.value == "one"
    assert not b.done
    assert not w.is_registered(1)
    w.trigger(2, "two")
    assert b.wait(timeout=1) == "two"


def test_wait_duplicate_id_refused():
    w = Wait()
    w.register(7)
    with pytest.raises(ValueError, match="duplicate id"):
        w.register(7)


def test_wait_trigger_unregistered_is_noop():
    Wait().trigger(99, "x")  # wait.go Trigger on empty id: nothing


def test_wait_time_deadlines():
    wt = WaitTime()
    w1 = wt.wait(1)
    w2 = wt.wait(2)
    w4 = wt.wait(4)
    wt.trigger(2)
    assert w1.done and w2.done and not w4.done
    # deadlines at or before the last trigger complete immediately
    assert wt.wait(2).done
    assert not wt.wait(5).done
    wt.trigger(10)
    assert w4.done


def test_recording_storage_records_and_injects():
    rs = RecordingStorage(MemoryStorage())
    rs.append([Entry(index=1, term=1)])
    rs.last_index()
    assert rs.names() == ["append", "last_index"]
    rs.fail["entries"] = ErrCompacted()
    with pytest.raises(ErrCompacted):
        rs.entries(1, 2)
    # one-shot: the next call goes through to the real storage
    assert [e.index for e in rs.entries(1, 2)] == [1]


def test_recording_storage_drives_rawnode():
    from etcd_tpu.models.rawnode import RawNode
    from etcd_tpu.types import Spec
    from etcd_tpu.utils.config import RaftConfig

    spec = Spec(M=1, L=16, E=2, K=2, W=4, R=2, A=4)
    rs = RecordingStorage(MemoryStorage())
    rn = RawNode(RaftConfig(), spec, rs, nid=0)
    rn.campaign()
    rd = rn.ready()
    if rd.hard_state is not None:
        rs.set_hard_state(rd.hard_state)
    rs.append(rd.entries)
    rn.advance(rd)
    names = rs.names()
    # boot reads the contract, then the harness persists the Ready
    assert "initial_state" in names
    assert names[-1] == "append"


def test_recording_wait():
    rw = RecordingWait()
    rw.register(3)
    rw.trigger(3, "v")
    assert rw.actions == [("Register", 3), ("Trigger", 3)]
