"""Black-box forensics plane (ISSUE 15): ring bit-identity, host
word-replay cross-checks, on-violation extraction, the Chrome trace
export and the forensics knob contract.

The load-bearing contract mirrors the telemetry plane's: the event ring
RIDES BESIDE the fleet state and never feeds back, so a ring-on round
must reproduce the ring-off round BIT-FOR-BIT in state and wire — over
the rich full-program scenario and under the PR-8 diet forms
(packed_state, sparse_outbox) and the crash-chaos epoch program. The
ring's bit-packed WORDS are then cross-checked against an independent
numpy replay of the recorded trajectory, and the extraction path is
proven end-to-end: a persist-nothing chaos run must pinpoint the
lost-commit round while only the offending groups' rings cross PCIe.
"""
import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from etcd_tpu.models.blackbox import (
    HOST_PID,
    MSG_CLASSES,
    ROLE_NAMES,
    VIOLATION_BIT_NAMES,
    decode_word,
    first_k_offenders,
    gather_forensics,
    init_blackbox,
    ring_capture,
    to_chrome_trace,
    violation_names,
)
from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.models.metrics import build_metered_round, zero_metrics
from etcd_tpu.models.state import NodeState, pack_fleet, unpack_fleet
from etcd_tpu.types import (
    ENTRY_NORMAL,
    MSG_APP,
    MSG_APP_RESP,
    MSG_HEARTBEAT,
    MSG_HEARTBEAT_RESP,
    MSG_HUP,
    MSG_PRE_VOTE,
    MSG_PRE_VOTE_RESP,
    MSG_PROP,
    MSG_SNAP,
    MSG_SNAP_STATUS,
    MSG_TIMEOUT_NOW,
    MSG_TRANSFER_LEADER,
    MSG_VOTE,
    MSG_VOTE_RESP,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig
from etcd_tpu.utils.trace import Field, Trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the test_packed_state / test_telemetry rich-scenario geometry:
# elections, a partition window long enough for snapshot fallback, a
# read-index wave, ticks
SPEC = Spec(M=3, L=16, E=1, K=2, W=2, R=2, A=2)
CFG = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2,
                 inbox_bound=4)
C = 16
ROUNDS = 48
# window >= ROUNDS: the whole trajectory stays resident (partial fill,
# no slot reuse), so the replay can address slot r directly
WINDOW = 64


def _inputs(r: int):
    M, E = SPEC.M, SPEC.E
    hup = np.zeros((M, C), bool)
    if r == 0:
        for c in range(C):
            hup[c % M, c] = True
    plen = np.zeros((M, C), np.int32)
    pdata = np.zeros((M, E, C), np.int32)
    ptype = np.zeros((M, E, C), np.int32)
    if 2 <= r < ROUNDS - 10:
        plen[0, :] = 1
        pdata[0, 0, :] = r * 64 + np.arange(C)
        ptype[0, 0, :] = ENTRY_NORMAL
    ri = np.zeros((M, C), np.int32)
    if r == 24:
        ri[0, :] = 7
    keep = np.ones((M, M, C), bool)
    if 8 <= r < 18:
        keep[1, :, 4:8] = False
        keep[:, 1, 4:8] = False
    tick = np.full((M, C), r % 3 == 0 or r >= ROUNDS - 8, bool)
    return plen, pdata, ptype, ri, hup, tick, keep


def _assert_states_equal(a: NodeState, b: NodeState, label: str, r: int):
    for name in NodeState.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        ), f"{label}: state.{name} diverged at round {r}"


@pytest.fixture(scope="module")
def plain_run():
    """Reference trajectory, recording the consumed wire of every round
    (inbox r-1) alongside the emitted wire — the replay needs both."""
    round_fn = jax.jit(build_round(CFG, SPEC))
    init = init_fleet(SPEC, C, seed=0, election_tick=CFG.election_tick)
    init_inbox = empty_inbox(SPEC, C)
    state, inbox = init, init_inbox
    states, inboxes = [], []
    for r in range(ROUNDS):
        state, inbox = round_fn(state, inbox, *_inputs(r))
        states.append(state)
        inboxes.append(inbox)
    assert int((np.asarray(state.role) == ROLE_LEADER).sum()) == C
    return init, init_inbox, states, inboxes


def _ring_run(cfg, window=WINDOW):
    step = jax.jit(build_metered_round(cfg, SPEC, with_blackbox=True))
    state = init_fleet(SPEC, C, seed=0, election_tick=cfg.election_tick)
    base = state
    if cfg.packed_state:
        state = pack_fleet(SPEC, state)
    inbox = empty_inbox(
        SPEC, C, compact_bound=cfg.inbox_bound if cfg.compact_wire else 0)
    metrics = zero_metrics()
    bb = init_blackbox(SPEC, base, window=window)
    states, inboxes = [], []
    for r in range(ROUNDS):
        state, inbox, metrics, bb = step(state, inbox, *_inputs(r),
                                         metrics, blackbox=bb)
        states.append(unpack_fleet(SPEC, state) if cfg.packed_state
                      else state)
        inboxes.append(inbox)
    return states, inboxes, bb


@pytest.fixture(scope="module")
def ring_run_dense():
    return _ring_run(CFG)


def test_ring_round_state_bit_identity(plain_run, ring_run_dense):
    """The tentpole's proof: the fused ring reduction leaves the state
    AND wire trajectories bit-identical over the rich scenario."""
    _, _, ref_states, ref_inboxes = plain_run
    states, inboxes, bb = ring_run_dense
    for r, (a, b) in enumerate(zip(ref_states, states)):
        _assert_states_equal(a, b, "ring", r)
    for r, (a, b) in enumerate(zip(ref_inboxes, inboxes)):
        assert np.array_equal(np.asarray(a.type), np.asarray(b.type)), \
            f"wire diverged at round {r}"
    assert int(np.asarray(bb.round)) == ROUNDS


def test_ring_packed_state_bit_identity(plain_run, ring_run_dense):
    """The ring composes with the PR-8 diet: packed carry in,
    bit-identical unpacked trajectory out, and the SAME ring words as
    the dense run (the words read the unpacked view)."""
    _, _, ref_states, _ = plain_run
    pcfg = dataclasses.replace(CFG, packed_state=True)
    states, _, bb_p = _ring_run(pcfg)
    for r, (a, b) in enumerate(zip(ref_states, states)):
        _assert_states_equal(a, b, "packed+ring", r)
    _, _, bb_d = ring_run_dense
    assert np.array_equal(np.asarray(bb_p.ring), np.asarray(bb_d.ring))


def test_ring_sparse_outbox_bit_identity():
    """Steady-traffic bit-identity under the diet's sparse_outbox form
    (same contract split as tests/test_sparse_outbox.py)."""
    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    full = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                      inbox_bound=4, coalesce_commit_refresh=True)
    sparse = dataclasses.replace(
        full, local_steps=("prop",),
        message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP),
        deferred_emit=True, sparse_outbox=True)
    Cs = 4
    M, E = spec.M, spec.E
    boot = jax.jit(build_round(full, spec))
    state = init_fleet(spec, Cs, seed=0, election_tick=full.election_tick)
    inbox = empty_inbox(spec, Cs)
    z2 = np.zeros((M, Cs), np.int32)
    zp = np.zeros((M, E, Cs), np.int32)
    no = np.zeros((M, Cs), bool)
    keep = np.ones((M, M, Cs), bool)
    hup = no.copy()
    hup[0, :] = True
    state, inbox = boot(state, inbox, z2, zp, zp, z2, hup, no, keep)
    for _ in range(12):
        state, inbox = boot(state, inbox, z2, zp, zp, z2, no, no, keep)
    assert (np.asarray(state.role)[0] == ROLE_LEADER).all()

    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = 9
    args = (plen, pdata, zp, z2, no, no, keep)
    bare = jax.jit(build_round(sparse, spec))
    met = jax.jit(build_metered_round(sparse, spec, with_blackbox=True))
    s_a, i_a = state, inbox
    s_b, i_b = state, inbox
    metrics, bb = zero_metrics(), init_blackbox(spec, state, window=16)
    for r in range(12):
        s_a, i_a = bare(s_a, i_a, *args)
        s_b, i_b, metrics, bb = met(s_b, i_b, *args, metrics, blackbox=bb)
        _assert_states_equal(s_a, s_b, "sparse_outbox+ring", r)
        assert np.array_equal(np.asarray(i_a.type), np.asarray(i_b.type))
    # the leader's words show steady append traffic going out
    ring = np.asarray(bb.ring)
    last = decode_word(int(ring[(12 - 1) % 16, 0, 0]))
    assert "append" in last["sent"] and last["commit_delta"] > 0


# ---------------------------------------------------------------------------
# host replay cross-check: an independent numpy decode of the recorded
# trajectory, compared word by word against the device ring
# ---------------------------------------------------------------------------

_APPEND = {MSG_APP, MSG_APP_RESP, MSG_SNAP, MSG_SNAP_STATUS}
_ELECT = {MSG_VOTE, MSG_VOTE_RESP, MSG_PRE_VOTE, MSG_PRE_VOTE_RESP,
          MSG_TIMEOUT_NOW, MSG_TRANSFER_LEADER, MSG_HUP}
_HB = {MSG_HEARTBEAT, MSG_HEARTBEAT_RESP}


def _np_class(t: int) -> str:
    if t in _APPEND:
        return "append"
    if t in _ELECT:
        return "election"
    if t in _HB:
        return "heartbeat"
    return "other"


def _np_activity(M: int, msg, side: str):
    """(counts [M, C], {(m, c): sorted class names}) from a flat wire
    pytree — senders by the frm field, receivers by slot % M."""
    t = np.asarray(msg.type)
    frm = np.asarray(msg.frm)
    live = t != 0
    S = t.shape[1]
    Cn = t.shape[-1]
    counts = np.zeros((M, Cn), np.int64)
    classes = {}
    for m in range(M):
        if side == "send":
            mask = live & (frm == m)
        else:
            mask = live & ((np.arange(S) % M == m)[None, :, None])
        counts[m] = mask.sum(axis=(0, 1))
        for c in range(Cn):
            names = {_np_class(int(tt)) for tt in t[:, :, c][mask[:, :, c]]}
            classes[(m, c)] = [k for k in MSG_CLASSES if k in names]
    return counts, classes


def _replay_round(spec, pre, post, consumed, emitted):
    """Expected decode_word() dict for every (member, group) of one
    round — computed with plain numpy, independent of the bit packing."""
    role0 = np.asarray(pre.role)
    role = np.asarray(post.role)
    term_d = np.clip(np.asarray(post.term) - np.asarray(pre.term), 0, 7)
    com_d = np.clip(np.asarray(post.commit) - np.asarray(pre.commit), 0, 7)
    app = np.asarray(post.applied) - np.asarray(pre.applied)
    cc = np.zeros(role.shape, bool)
    for f in ("voters", "voters_out", "learners", "learners_next"):
        cc |= (np.asarray(getattr(pre, f))
               != np.asarray(getattr(post, f))).any(axis=1)
    sent_n, sent_cls = _np_activity(spec.M, emitted, "send")
    recv_n, recv_cls = _np_activity(spec.M, consumed, "recv")
    out = {}
    for m in range(spec.M):
        for c in range(role.shape[-1]):
            out[(m, c)] = {
                "role": ROLE_NAMES[int(role[m, c])],
                "role_change": bool(role[m, c] != role0[m, c]),
                "term_delta": int(term_d[m, c]),
                "commit_delta": int(com_d[m, c]),
                "applied_delta": int(np.clip(app[m, c], 0, 7)),
                "snapshot_install": bool(app[m, c] > spec.A),
                "conf_change": bool(cc[m, c]),
                "crashed": False,
                "restarted": False,
                "down": False,
                "sent": sent_cls[(m, c)],
                "recv": recv_cls[(m, c)],
                "sent_count": min(int(sent_n[m, c]), 7),
                "recv_count": min(int(recv_n[m, c]), 7),
            }
    return out


def test_ring_words_match_host_replay(plain_run, ring_run_dense):
    """Every word of the partially-filled ring decodes to exactly the
    fields an independent numpy replay derives from the recorded
    trajectory — roles, transitions, frontier deltas, the snapshot
    install the partition forces, and per-class wire activity."""
    init, init_inbox, states, inboxes = plain_run
    _, _, bb = ring_run_dense
    ring = np.asarray(bb.ring)
    assert ring.shape == (WINDOW, SPEC.M, C)
    # partial fill: rounds past the trajectory never got written
    assert not ring[ROUNDS:].any()
    pre_states = [init] + states[:-1]
    pre_inboxes = [init_inbox] + inboxes[:-1]
    snap_seen = False
    for r in range(ROUNDS):
        exp = _replay_round(SPEC, pre_states[r], states[r],
                            pre_inboxes[r], inboxes[r])
        for m in range(SPEC.M):
            for c in range(C):
                got = decode_word(int(ring[r, m, c]))
                assert got == exp[(m, c)], (r, m, c, got, exp[(m, c)])
                snap_seen |= got["snapshot_install"]
    # rich enough to prove anything: the partition forced a laggard
    # through snapshot fallback and the ring recorded it
    assert snap_seen


# ---------------------------------------------------------------------------
# chaos epoch composition + on-violation extraction
# ---------------------------------------------------------------------------


def test_chaos_epoch_bit_identity_with_blackbox():
    """The crash-chaos epoch program with the BlackBox carry produces
    the exact same state/wire/violations/key as the program without it
    (the per-group checker masks derive from the same intermediates the
    counters sum)."""
    from etcd_tpu.harness.chaos import (
        build_chaos_epoch,
        empty_blackbox,
        empty_crash_state,
        zero_violations,
    )

    Cs, rounds = 8, 8
    M = SPEC.M
    state = init_fleet(SPEC, Cs, seed=2, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, Cs)
    crash = empty_crash_state(state)
    key = jax.random.PRNGKey(7)
    prop_len = jnp.zeros((M, Cs), jnp.int32).at[0].set(1)
    prop_data = jnp.zeros((M, SPEC.E, Cs), jnp.int32).at[0, 0].set(7)
    pal = jnp.zeros((1,), jnp.int32)
    ops = (jnp.float32(0.05), jnp.float32(0.0), jnp.float32(0.1),
           jnp.float32(0.08), jnp.int32(2), jnp.bool_(True),
           jnp.bool_(True), jnp.float32(0.0), pal, jnp.float32(1.0),
           jnp.float32(1.0))
    plain = jax.jit(build_chaos_epoch(
        CFG, SPEC, rounds, with_delay=False, with_crash=True))
    boxed = jax.jit(build_chaos_epoch(
        CFG, SPEC, rounds, with_delay=False, with_crash=True,
        with_blackbox=True))
    bb = empty_blackbox(SPEC, state, window=16)
    out_a = plain(state, inbox, None, crash, key, prop_len, prop_data,
                  zero_violations(), None, None, *ops)
    out_b = boxed(state, inbox, None, crash, key, prop_len, prop_data,
                  zero_violations(), None, bb, *ops)
    _assert_states_equal(out_a[0], out_b[0], "chaos epoch", rounds)
    assert np.array_equal(np.asarray(out_a[1].type),
                          np.asarray(out_b[1].type))
    assert np.array_equal(np.asarray(out_a[4]), np.asarray(out_b[4]))
    for leaf_a, leaf_b in zip(jax.tree.leaves(out_a[5]),
                              jax.tree.leaves(out_b[5])):
        assert np.array_equal(np.asarray(leaf_a), np.asarray(leaf_b))
    assert int(np.asarray(out_a[8])) == int(np.asarray(out_b[8]))
    bb_out = out_b[7]
    assert bb_out is not None
    assert int(np.asarray(bb_out.ring.round)) == rounds


def test_violation_bit_order_pinned_to_chaos_keys():
    from etcd_tpu.harness import chaos

    assert tuple(chaos.VIOLATION_KEYS) == VIOLATION_BIT_NAMES


def test_first_k_offenders_device_reduction():
    mask = jnp.zeros((12,), bool).at[7].set(True).at[2].set(True)
    assert list(np.asarray(first_k_offenders(mask, 4))) == [2, 7, 12, 12]
    assert list(np.asarray(first_k_offenders(mask, 1))) == [2]
    none = jnp.zeros((12,), bool)
    assert list(np.asarray(first_k_offenders(none, 3))) == [12, 12, 12]


def test_gather_forensics_narrow_transfer():
    """Only the first-K offending groups' ring lanes cross PCIe: the
    gathered rings are [W, M, k], never fleet-width."""
    state = init_fleet(SPEC, C, seed=0)
    ring = init_blackbox(SPEC, state, window=8)
    viol_groups = (jnp.zeros((C,), jnp.int32)
                   .at[5].set(1 << 3)    # lost_commit
                   .at[11].set(1 << 0))  # multi_leader
    viol_round = (jnp.full((C,), -1, jnp.int32).at[5].set(9)
                  .at[11].set(12))
    g = gather_forensics(ring, viol_groups, viol_round, k=4)
    assert g["rings"].shape == (8, SPEC.M, 4)
    assert list(g["ids"]) == [5, 11, C, C]
    assert int(g["total"]) == 2
    assert violation_names(int(g["bits"][0])) == ["lost_commit"]
    assert violation_names(int(g["bits"][1])) == ["multi_leader"]
    assert int(g["viol_round"][0]) == 9


def test_persist_nothing_forensics_pinpoints_lost_commit():
    """The extraction acceptance end-to-end: a crash-chaos run under the
    deliberately-broken persist-nothing durability model violates
    lost-commit, and the forensics section pinpoints the offending
    round with the crash/down events leading into it."""
    from etcd_tpu.harness.chaos import run_chaos
    from etcd_tpu.utils.config import CrashConfig

    spec = Spec(M=5, L=32, E=2, K=4, W=2, R=2, A=4)
    cfg = RaftConfig(pre_vote=True, check_quorum=True)
    rep = run_chaos(
        spec, cfg, C=16, rounds=25, epoch_len=25, heal_len=25, seed=3,
        drop_p=0.0, delay_p=0.08, partition_p=0.0, crash_p=0.12,
        crash=CrashConfig(down_rounds=2, durability="none"),
        blackbox=True, blackbox_k=4,
    )
    assert rep["lost_commit"] > 0
    f = rep["forensics"]
    assert f["window"] >= 2
    assert f["groups_violating"] >= 1
    assert f["captured"], "violating groups but nothing captured"
    cap = f["captured"][0]
    assert "lost_commit" in cap["violations"]
    vr = cap["first_violation_round"]
    assert vr >= 0
    # the frozen ring's preserved window ENDS at the violation round
    assert cap["timeline"][-1]["round"] == vr
    # and the rounds leading in show the crash machinery at work
    events = {e for row in cap["timeline"] for mem in row["members"]
              for e in mem["events"]}
    assert events & {"crash", "down"}, events
    # the whole report (forensics included) is strict JSON
    json.loads(json.dumps(rep))


# ---------------------------------------------------------------------------
# Chrome trace export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------


def test_chrome_trace_schema(ring_run_dense):
    """Device tracks from a live ring capture + host spans from traced
    requests land in one loadable Chrome trace: every event carries
    ph/pid/tid, device tracks use group/member ids, host spans sit on
    their own synthetic process with one child slice per trace step."""
    _, _, bb = ring_run_dense
    caps = ring_capture(bb, [0, 3])
    t1 = Trace("put", Field("rpc", "kv_put"))
    t1.step("proposed through raft")
    t1.step("applied; result ready")
    t2 = Trace("range", Field("serializable", False))
    t2.step("read-index settled")
    doc = to_chrome_trace(captures=caps, spans=[t1.to_span(), t2.to_span()])
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    assert all({"ph", "name", "pid", "tid"} <= set(e) for e in evs)
    xs = [e for e in evs if e["ph"] == "X"]
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    device = [e for e in xs if e["cat"] == "device"]
    host = [e for e in xs if e["cat"] == "host"]
    assert {e["pid"] for e in device} == {0, 3}
    assert {e["pid"] for e in host} == {HOST_PID}
    # the live window covers rounds [round - W + 1, round - 1] clipped
    # at 0: W=64 >= 48 rounds -> the full 48-round history, per member
    assert len(device) == 2 * ROUNDS * SPEC.M
    # host: one span slice per request + one child slice per step
    assert len(host) == 2 + 3
    # process metadata for both groups and the host track
    procs = [e for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert {p["pid"] for p in procs} == {0, 3, HOST_PID}
    json.loads(json.dumps(doc))


# ---------------------------------------------------------------------------
# init hygiene + knob contract
# ---------------------------------------------------------------------------


def test_init_blackbox_leaves_share_no_buffers():
    """Every EventRing leaf owns its buffer: the chaos epoch programs
    donate the whole carry on accelerators, and XLA rejects one buffer
    appearing at two donated positions in a single Execute (the
    empty_crash_state alias hazard class)."""
    state = init_fleet(SPEC, 4, seed=0)
    bb = init_blackbox(SPEC, state)
    ptrs = [leaf.unsafe_buffer_pointer() for leaf in jax.tree.leaves(bb)]
    assert len(ptrs) == len(set(ptrs)), "aliased ring leaves"
    state_ptrs = {leaf.unsafe_buffer_pointer()
                  for leaf in jax.tree.leaves(state)}
    assert not state_ptrs & set(ptrs), "ring leaf aliases state"


def test_init_blackbox_rejects_bad_window():
    state = init_fleet(SPEC, 2, seed=0)
    with pytest.raises(ValueError, match="window"):
        init_blackbox(SPEC, state, window=1)
    with pytest.raises(ValueError, match="window"):
        init_blackbox(SPEC, state, window=257)


@pytest.mark.parametrize("script,env_extra,needle", [
    ("chaos_run.py", {"TELEM_EVERY": "0"}, "TELEM_EVERY"),
    ("chaos_run.py", {"CHAOS_BLACKBOX": "2"}, "CHAOS_BLACKBOX"),
    ("chaos_run.py", {"CHAOS_BLACKBOX_WINDOW": "1"},
     "CHAOS_BLACKBOX_WINDOW"),
    ("bench.py", {"BENCH_BLACKBOX": "x"}, "BENCH_BLACKBOX"),
])
def test_forensics_knob_validation_exits_2(script, env_extra, needle):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **env_extra}
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, script)],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 2, (out.returncode, out.stdout, out.stderr)
    assert needle in out.stderr
    assert not out.stdout.strip()


def test_drivers_read_env_through_knob_helpers_only():
    """Knob hygiene: the scale drivers route every env knob through
    utils/knobs (one validation idiom, one exit-2 contract). The check
    itself moved into the static-analysis plane (the ``env-knob`` rule,
    etcd_tpu/analysis/lint.py, AST-based so presence checks and
    child-env construction stay legal); this wrapper keeps the PR-10
    contract pinned to the two drivers from the telemetry suite that
    introduced it."""
    from pathlib import Path

    from etcd_tpu.analysis.lint import run_lint

    findings = run_lint(Path(REPO), targets=("bench.py", "chaos_run.py"),
                        rules=("env-knob",))
    assert not findings, "\n".join(
        str(f) + "; route new knobs through etcd_tpu.utils.knobs"
        for f in findings)
