"""v2 auth — basic-auth users/roles guarding the /v2/keys surface.

Re-design of ``server/etcdserver/api/v2auth/auth.go`` + the guard logic
of ``api/v2http/client_auth.go``: users (password hash + role list) and
roles (key-pattern read/write permission lists with trailing-``*``
globs, auth.go:574-614 simpleMatch/prefixMatch) live in the replicated
v2 tree itself under a hidden ``/_security`` subtree — every mutation
is a committed v2 request, so all members agree on who may do what.
``root`` user + implicit root role gate admin operations; the ``guest``
role (auto-created full-access on enable, auth.go:368-398) covers
unauthenticated requests.
"""
from __future__ import annotations

import hashlib
import json

from etcd_tpu.server.v2store import EcodeKeyNotFound, V2Error

PREFIX = "/_security"  # StorePermsPrefix analog (hidden subtree)
GUEST_ROLE = "guest"
ROOT_ROLE = "root"

GUEST_PERMISSIONS = {"kv": {"read": ["/*"], "write": ["/*"]}}


class AuthError(Exception):
    """v2auth.Error: message + HTTP status."""

    def __init__(self, status: int, msg: str):
        self.status = status
        super().__init__(f"auth: {msg}")


def hash_password(password: str) -> str:
    # passwordStore.HashPassword (bcrypt in the reference; a keyed
    # sha256 here — the contract is deterministic verify, not KDF parity)
    return hashlib.sha256(b"etcd-tpu-v2auth:" +
                          password.encode()).hexdigest()


def simple_match(pattern: str, key: str) -> bool:
    if pattern.endswith("*"):
        return key.startswith(pattern[:-1])
    return key == pattern


def prefix_match(pattern: str, key: str) -> bool:
    if not pattern.endswith("*"):
        return False
    return key.startswith(pattern[:-1])


def has_access(perms: dict, key: str, write: bool,
               recursive: bool = False) -> bool:
    """RWPermission.HasAccess / HasRecursiveAccess (auth.go:574-602)."""
    pats = perms.get("kv", {}).get("write" if write else "read", [])
    match = prefix_match if recursive else simple_match
    return any(match(p, key) for p in pats)


class V2AuthStore:
    """auth.go store: CRUD over replicated /_security records."""

    def __init__(self, ec):
        self.ec = ec

    # ---- raw record access (auth_requests.go path scheme)
    def _get(self, path: str) -> dict | None:
        try:
            e = self.ec.v2_get(PREFIX + path)
        except V2Error as err:
            if err.code == EcodeKeyNotFound:
                return None
            raise
        return json.loads(e.node["value"])

    def _put(self, path: str, value: dict) -> None:
        self.ec.v2_request("PUT", PREFIX + path, val=json.dumps(value))

    def _delete(self, path: str) -> None:
        self.ec.v2_request("DELETE", PREFIX + path)

    def _list(self, path: str) -> list[str]:
        try:
            e = self.ec.v2_get(PREFIX + path)
        except V2Error as err:
            if err.code == EcodeKeyNotFound:
                return []
            raise
        return sorted(n["key"].rsplit("/", 1)[-1]
                      for n in e.node.get("nodes", []))

    # ---- users
    def create_user(self, name: str, password: str,
                    roles: list[str] | None = None) -> dict:
        if self._get(f"/users/{name}") is not None:
            raise AuthError(409, f"user {name} already exists")
        u = {"user": name, "password": hash_password(password),
             "roles": sorted(roles or [])}
        self._put(f"/users/{name}", u)
        return {"user": name, "roles": u["roles"]}

    def get_user(self, name: str) -> dict:
        u = self._get(f"/users/{name}")
        if u is None:
            raise AuthError(404, f"user {name} does not exist")
        return u

    def all_users(self) -> list[str]:
        return self._list("/users")

    def delete_user(self, name: str) -> None:
        if self.auth_enabled() and name == "root":
            raise AuthError(403, "cannot delete root user while "
                            "auth is enabled")
        self.get_user(name)
        self._delete(f"/users/{name}")

    def update_user(self, name: str, password: str | None = None,
                    grant: list[str] | None = None,
                    revoke: list[str] | None = None) -> dict:
        # User.merge (auth.go:418-461)
        u = self.get_user(name)
        if password is not None:
            u["password"] = hash_password(password)
        roles = set(u.get("roles", []))
        for r in grant or []:
            if r in roles:
                raise AuthError(409,
                                f"duplicate role {r} for user {name}")
            roles.add(r)
        for r in revoke or []:
            if r not in roles:
                raise AuthError(409,
                                f"revoking ungranted role {r} from "
                                f"user {name}")
            roles.discard(r)
        u["roles"] = sorted(roles)
        self._put(f"/users/{name}", u)
        return {"user": name, "roles": u["roles"]}

    # ---- roles
    def create_role(self, name: str,
                    permissions: dict | None = None) -> dict:
        if name == ROOT_ROLE:
            raise AuthError(403, f"invalid role name {name}")
        if self._get(f"/roles/{name}") is not None:
            raise AuthError(409, f"role {name} already exists")
        r = {"role": name,
             "permissions": permissions or {"kv": {"read": [],
                                                   "write": []}}}
        self._put(f"/roles/{name}", r)
        return r

    def get_role(self, name: str) -> dict:
        if name == ROOT_ROLE:
            # the implicit root role: full access everywhere
            return {"role": ROOT_ROLE,
                    "permissions": {"kv": {"read": ["/*"],
                                           "write": ["/*"]}}}
        r = self._get(f"/roles/{name}")
        if r is None:
            raise AuthError(404, f"role {name} does not exist")
        return r

    def all_roles(self) -> list[str]:
        return sorted(self._list("/roles") + [ROOT_ROLE])

    def delete_role(self, name: str) -> None:
        self.get_role(name)
        self._delete(f"/roles/{name}")

    def update_role(self, name: str, grant: dict | None = None,
                    revoke: dict | None = None) -> dict:
        # Role.merge / Permissions.Grant/Revoke (auth.go:463-572)
        r = self.get_role(name)
        perms = r["permissions"]["kv"]
        for mode in ("read", "write"):
            for pat in (grant or {}).get("kv", {}).get(mode, []):
                if pat in perms[mode]:
                    raise AuthError(409, f"duplicate permission {pat}")
                perms[mode].append(pat)
            for pat in (revoke or {}).get("kv", {}).get(mode, []):
                if pat not in perms[mode]:
                    raise AuthError(409,
                                    f"revoking ungranted permission "
                                    f"{pat}")
                perms[mode].remove(pat)
            perms[mode].sort()
        self._put(f"/roles/{name}", r)
        return r

    # ---- enable/disable (auth.go:364-416)
    def auth_enabled(self) -> bool:
        return bool(self._get("/enabled"))

    def enable_auth(self) -> None:
        if self.auth_enabled():
            raise AuthError(409, "already enabled")
        if self._get("/users/root") is None:
            raise AuthError(409, "No root user available, please "
                            "create one")
        if self._get(f"/roles/{GUEST_ROLE}") is None:
            self.create_role(GUEST_ROLE, dict(GUEST_PERMISSIONS))
        self._put("/enabled", True)

    def disable_auth(self) -> None:
        if not self.auth_enabled():
            raise AuthError(409, "already disabled")
        self._put("/enabled", False)

    # ---- the guard (client_auth.go userFromBasicAuth +
    # hasKeyPrefixAccess)
    def check_password(self, name: str, password: str) -> dict:
        u = self._get(f"/users/{name}")
        if u is None or u["password"] != hash_password(password):
            raise AuthError(401, "incorrect password")
        return u

    def is_root(self, creds: tuple[str, str] | None) -> bool:
        if not self.auth_enabled():
            return True  # no auth: everyone is admin
        if creds is None:
            return False
        try:
            u = self.check_password(*creds)
        except AuthError:
            return False
        return ROOT_ROLE in u.get("roles", []) or u["user"] == "root"

    def check_key_access(self, creds: tuple[str, str] | None, key: str,
                         write: bool, recursive: bool = False) -> None:
        """Raise AuthError unless creds may touch `key`."""
        if not self.auth_enabled():
            return
        if key.startswith(PREFIX):
            raise AuthError(403, "the security subtree is internal")
        if creds is None:
            roles = [GUEST_ROLE]
        else:
            u = self.check_password(*creds)
            if ROOT_ROLE in u.get("roles", []) or u["user"] == "root":
                return
            roles = u.get("roles", [])
        for rname in roles:
            try:
                r = self.get_role(rname)
            except AuthError:
                continue
            if has_access(r["permissions"], key, write, recursive):
                return
        who = creds[0] if creds else "guest"
        raise AuthError(401 if creds else 403,
                        f"no {'write' if write else 'read'} access to "
                        f"{key} for {who}")
