"""Host-storage mirror of the crash–restart fault class (ISSUE 3):
crash-restart round-trips through the segmented WAL + MemoryStorage,
including torn-final-record truncation in WAL.read_all (wal/repair.go
behavior) and the crash-during-cut debris case.

The device-tier analogs (volatile-state wipe, fsync-lag loss, recovery
checkers) live in tests/test_recovery_crash.py; this file proves the same
durability contract on the byte-level storage path.
"""
import os
import random

import pytest

from etcd_tpu.storage.raftstorage import bootstrap_from_wal
from etcd_tpu.storage.wal import WAL, WALError


def _fill(w: WAL, n: int, term: int = 1, commit_lag: int = 1):
    """n save() batches: entry i + hardstate committing i - commit_lag."""
    for i in range(1, n + 1):
        w.save({"term": term, "vote": 0, "commit": max(i - commit_lag, 0)},
               [{"index": i, "term": term, "data": i * 11, "type": 0}])


def test_wal_torn_final_record_corrupt_in_place(tmp_path):
    """Corrupting the tail BYTES of the last segment (not appending
    garbage): read_all truncates the now-unverifiable final record and
    replays the durable prefix instead of raising."""
    d = str(tmp_path / "wal")
    w = WAL(d)
    _fill(w, 3)
    w.close()
    seg = os.path.join(d, sorted(os.listdir(d))[-1])
    size = os.path.getsize(seg)
    data = bytearray(open(seg, "rb").read())
    # smash the last record's payload bytes (the final record is the
    # hardstate of batch 3; its frame is > 16 bytes, so offset -12 is
    # inside the payload, not the pad)
    for off in range(size - 12, size - 8):
        data[off] ^= 0xFF
    open(seg, "wb").write(bytes(data))

    w2 = WAL(d)
    _, hs, ents, _ = w2.read_all()
    # entries 1..3 survive (written before the smashed hardstate);
    # hardstate falls back to batch 2's
    assert [e["index"] for e in ents] == [1, 2, 3]
    assert hs == {"term": 1, "vote": 0, "commit": 1}
    assert os.path.getsize(seg) < size  # torn tail truncated in place
    # the repaired WAL appends cleanly
    w2.save(None, [{"index": 4, "term": 1, "data": 44, "type": 0}])
    w2.close()
    _, _, ents, _ = WAL(d).read_all()
    assert [e["index"] for e in ents] == [1, 2, 3, 4]


def test_wal_truncated_final_record(tmp_path):
    """fsync lag: the file loses its tail mid-record. Replay returns the
    durable prefix."""
    d = str(tmp_path / "wal")
    w = WAL(d)
    _fill(w, 5)
    w.close()
    seg = os.path.join(d, sorted(os.listdir(d))[-1])
    with open(seg, "ab") as f:
        f.truncate(os.path.getsize(seg) - 7)
    _, hs, ents, _ = WAL(d).read_all()
    # the tear lands mid-hardstate-of-batch-5: entries survive through 5,
    # the newest surviving hardstate is batch 4's
    assert [e["index"] for e in ents] == [1, 2, 3, 4, 5]
    assert hs["commit"] == 3


def test_wal_crash_during_cut_drops_debris(tmp_path):
    """A tear at the tail of the penultimate segment with nothing but
    record-free debris after it (the crash-inside-cut window): repair
    truncates the tear and unlinks the debris segment instead of
    raising."""
    import etcd_tpu.storage.wal as walmod

    d = str(tmp_path / "wal")
    old = walmod.SEGMENT_BYTES
    walmod.SEGMENT_BYTES = 256
    try:
        w = WAL(d)
        for i in range(1, 20):
            w.save(None, [{"index": i, "term": 1, "data": i, "type": 0}])
        w.close()
        segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
        assert len(segs) > 2
        # tear the tail of the penultimate segment and reduce the last
        # one to a record-free stub (its first frame torn too)
        pen = os.path.join(d, segs[-2])
        with open(pen, "ab") as f:
            f.truncate(os.path.getsize(pen) - 5)
        last = os.path.join(d, segs[-1])
        with open(last, "r+b") as f:
            f.truncate(3)

        w2 = WAL(d)
        _, _, ents, _ = w2.read_all()
        assert ents, "durable prefix must replay"
        assert ents[-1]["index"] < 19
        assert not os.path.exists(last), "debris segment must be unlinked"
        # appends continue on the repaired tail
        nxt = ents[-1]["index"] + 1
        w2.save(None, [{"index": nxt, "term": 1, "data": 0, "type": 0}])
        w2.close()
        _, _, ents2, _ = WAL(d).read_all()
        assert ents2[-1]["index"] == nxt
    finally:
        walmod.SEGMENT_BYTES = old


def test_wal_bitrot_in_durable_segment_refuses(tmp_path):
    """A COMPLETE frame failing its crc in a non-final segment is bit rot
    on fsync'd bytes (cut() synced the whole segment before opening the
    next), not a torn append — repair must refuse even when everything
    after it is record-free debris, or it would silently drop durable
    records. Only an INCOMPLETE trailing frame is a tear there."""
    import etcd_tpu.storage.wal as walmod
    from etcd_tpu.storage.walcodec import get_codec

    d = str(tmp_path / "wal")
    old = walmod.SEGMENT_BYTES
    walmod.SEGMENT_BYTES = 256
    try:
        w = WAL(d)
        for i in range(1, 20):
            w.save(None, [{"index": i, "term": 1, "data": i, "type": 0}])
        w.close()
        segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
        assert len(segs) > 2
        pen = os.path.join(d, segs[-2])
        buf = open(pen, "rb").read()
        # flip a payload byte of the segment's SECOND frame: a complete
        # mid-segment record, well clear of the trailing-append window
        first_len = get_codec().decode(buf, 0, 0)[0]
        data = bytearray(buf)
        data[first_len + 12] ^= 0xFF
        open(pen, "wb").write(bytes(data))
        # reduce the last segment to a record-free stub, the shape that
        # WOULD make an incomplete tail repairable
        last = os.path.join(d, segs[-1])
        with open(last, "r+b") as f:
            f.truncate(3)
        with pytest.raises(WALError, match="durable"):
            WAL(d).read_all()
    finally:
        walmod.SEGMENT_BYTES = old


def test_wal_bitrot_mid_final_segment_refuses(tmp_path):
    """A complete-but-crc-broken frame with MORE records after it in the
    final segment is bit rot on fsync'd bytes, not a torn tail — the
    records behind it (later hardstates carrying vote/term) must not be
    silently truncated away. Only the log's very last frame (ending at
    EOF) gets the lenient tail treatment."""
    from etcd_tpu.storage.walcodec import get_codec

    d = str(tmp_path / "wal")
    w = WAL(d)
    _fill(w, 5)
    w.close()
    seg = os.path.join(d, sorted(os.listdir(d))[-1])
    buf = open(seg, "rb").read()
    first_len = get_codec().decode(buf, 0, 0)[0]
    data = bytearray(buf)
    data[first_len + 12] ^= 0xFF  # payload byte of frame 2 of 10
    open(seg, "wb").write(bytes(data))
    with pytest.raises(WALError, match="durable"):
        WAL(d).read_all()


def test_bootstrap_from_wal_initial_snapshot_marker(tmp_path):
    """A WAL that opens with the initial empty-snapshot marker
    (index 0, term 0) must still bootstrap — apply_snapshot would
    reject index 0 as out of date on a fresh MemoryStorage."""
    d = str(tmp_path / "wal")
    w = WAL(d)
    w.save_snapshot(index=0, term=0)
    w.save({"term": 1, "vote": 0, "commit": 1},
           [{"index": 1, "term": 1, "data": 11, "type": 0}])
    w.close()
    ms, _ = bootstrap_from_wal(WAL(d))
    assert ms.first_index() == 1 and ms.last_index() == 1
    assert ms.hard_state.commit == 1


def test_wal_bitrot_in_debris_segment_refuses(tmp_path):
    """The bit-rot rule applies to the segments repair would UNLINK too:
    a tear in the penultimate segment followed by a last segment whose
    first frame is complete but crc-broken must refuse — unlinking it
    would silently delete durable records."""
    import etcd_tpu.storage.wal as walmod
    from etcd_tpu.storage.walcodec import HEADER_SIZE

    d = str(tmp_path / "wal")
    old = walmod.SEGMENT_BYTES
    walmod.SEGMENT_BYTES = 256
    try:
        w = WAL(d)
        for i in range(1, 20):
            w.save(None, [{"index": i, "term": 1, "data": i, "type": 0}])
        w.close()
        segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
        pen = os.path.join(d, segs[-2])
        with open(pen, "ab") as f:
            f.truncate(os.path.getsize(pen) - 5)
        last = os.path.join(d, segs[-1])
        data = bytearray(open(last, "rb").read())
        data[HEADER_SIZE + 1] ^= 0xFF  # first frame's payload: crc breaks
        open(last, "wb").write(bytes(data))
        with pytest.raises(WALError, match="durable"):
            WAL(d).read_all()
        assert os.path.exists(last)  # nothing was unlinked
    finally:
        walmod.SEGMENT_BYTES = old


def test_wal_mid_log_corruption_still_refuses(tmp_path):
    """Valid records AFTER a tear make it mid-log corruption, which must
    stay loud (repair would create a silent hole) — the widened repair
    path must not regress this."""
    import etcd_tpu.storage.wal as walmod

    d = str(tmp_path / "wal")
    old = walmod.SEGMENT_BYTES
    walmod.SEGMENT_BYTES = 256
    try:
        w = WAL(d)
        for i in range(1, 20):
            w.save(None, [{"index": i, "term": 1, "data": i, "type": 0}])
        w.close()
        segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
        pen = os.path.join(d, segs[-2])
        with open(pen, "ab") as f:
            f.truncate(os.path.getsize(pen) - 5)
        with pytest.raises(WALError):
            WAL(d).read_all()
    finally:
        walmod.SEGMENT_BYTES = old


def test_crash_restart_roundtrip_through_storage(tmp_path):
    """The full host-side crash loop: write through the WAL, crash with
    a torn tail, bootstrap a MemoryStorage from the repaired replay, and
    check the recovery invariants the device checkers enforce — the
    durable prefix is intact, commit never exceeds the surviving log,
    and the persisted term never regresses across restarts."""
    d = str(tmp_path / "wal")
    w = WAL(d, metadata=b"group-7")
    _fill(w, 6, term=1)
    w.save_snapshot(index=2, term=1)
    w.save({"term": 2, "vote": 1, "commit": 5},
           [{"index": 7, "term": 2, "data": 77, "type": 0}])
    w.close()

    # term monotonicity on the PERSISTED HardState: each recovery may see
    # a torn-off (never-durable) newest batch fall away, but never a term
    # below what an earlier recovery already read back — tears only reach
    # the freshly appended tail, so once recovered, always recovered
    prev_recovered_term = 0
    rng = random.Random(5)
    for crash in range(4):
        seg = os.path.join(d, sorted(
            f for f in os.listdir(d) if f.endswith(".wal"))[-1])
        # fsync lag: lose a random sliver of the tail
        with open(seg, "ab") as f:
            f.truncate(max(os.path.getsize(seg) - rng.randrange(1, 30), 0))
        w = WAL(d)
        ms, metadata = bootstrap_from_wal(w)
        assert metadata == b"group-7"
        hs, _ = ms.initial_state()
        assert hs.commit <= ms.last_index()
        assert hs.term >= prev_recovered_term, "persisted term regressed"
        prev_recovered_term = hs.term
        assert ms.snapshot().meta.index == 2
        assert ms.first_index() == 3  # replay starts past the snapshot
        # log matching across restart: entry terms stay non-decreasing
        terms = [ms.term(i)
                 for i in range(ms.first_index(), ms.last_index() + 1)]
        assert terms == sorted(terms)
        # the restarted node keeps writing at a strictly higher term
        t = hs.term + 1
        nxt = ms.last_index() + 1
        w.save({"term": t, "vote": 0, "commit": hs.commit},
               [{"index": nxt, "term": t, "data": nxt, "type": 0}])
        w.close()

    # final intact replay still bootstraps
    ms, _ = bootstrap_from_wal(WAL(d))
    assert ms.last_index() >= ms.hard_state.commit
