"""Transport security: TLS configuration, self-signed cert generation,
and certificate identities for the gateway wire surfaces.

The analog of the reference's ``client/pkg/transport`` package
(listener.go:120-180 TLSInfo, listener.go:185 SelfCert,
listener_tls.go:43 NewTLSListener's post-handshake CN/SAN gate) and
``pkg/tlsutil``, re-designed for this framework's HTTP gateway: instead
of Go's crypto/tls listener wrappers, a :class:`TLSInfo` builds
``ssl.SSLContext`` objects for the server socket and for client dials,
and the per-connection identity (client-cert CN) is read off the
handshaked socket by the request handler.

Scope note: in this framework consensus traffic between members of a
group is an on-device tensor exchange (outbox→inbox transpose), not a
socket — so "peer TLS" has no raft wire to protect inside one process.
The TLS surfaces are the client-facing gateway (this module + v3rpc),
the proxies, and any multi-process deployment of those.
"""
from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import os
import ssl

__all__ = [
    "TLSInfo", "self_cert", "generate_ca", "issue_cert",
    "peer_common_name", "check_cert_constraints",
]


@dataclasses.dataclass
class TLSInfo:
    """TLSInfo (client/pkg/transport/listener.go:120-180): file paths +
    policy knobs, from which server/client SSL contexts are built."""

    cert_file: str = ""
    key_file: str = ""
    # separate client-side keypair for dials; falls back to cert_file
    # (listener.go:131-133 ClientCertFile semantics)
    client_cert_file: str = ""
    client_key_file: str = ""
    trusted_ca_file: str = ""
    client_cert_auth: bool = False
    # client dials: skip server-cert verification entirely
    insecure_skip_verify: bool = False
    # post-handshake constraints (listener.go:161-166): a CN the client
    # cert must carry, or a hostname/IP its SANs must cover. (The
    # reference's SkipClientSANVerify has no analog: client-cert SANs
    # are never verified here unless allowed_hostname opts in, so there
    # is nothing to skip.)
    allowed_cn: str = ""
    allowed_hostname: str = ""

    def empty(self) -> bool:
        return not self.cert_file and not self.key_file

    def __str__(self) -> str:
        return (f"cert = {self.cert_file}, key = {self.key_file}, "
                f"trusted-ca = {self.trusted_ca_file}, "
                f"client-cert-auth = {self.client_cert_auth}")

    # ---------------------------------------------------------- contexts
    def server_context(self) -> ssl.SSLContext:
        """ServerConfig (listener.go:345-380): server cert + optional
        required-and-verified client certs."""
        if not self.cert_file or not self.key_file:
            raise ValueError(
                "KeyFile and CertFile must both be present "
                f"[key: {self.key_file!r}, cert: {self.cert_file!r}]")
        wants_client_certs = self.client_cert_auth or self.allowed_cn \
            or self.allowed_hostname
        if wants_client_certs and not self.trusted_ca_file:
            raise ValueError("client cert auth requires a trusted CA file")
        if self.allowed_cn and self.allowed_hostname:
            # mutually exclusive like the reference's ServerConfig
            # (listener.go:354): silently preferring one would void the
            # other constraint the operator thinks is enforced
            raise ValueError(
                "AllowedCN and AllowedHostname are mutually exclusive")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if wants_client_certs:
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(self.trusted_ca_file)
        elif self.trusted_ca_file:
            # CA without required certs: verify one when presented
            ctx.verify_mode = ssl.CERT_OPTIONAL
            ctx.load_verify_locations(self.trusted_ca_file)
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """ClientConfig (listener.go:382-403): CA verification for the
        server cert + optional client keypair for mutual TLS."""
        if self.insecure_skip_verify:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.trusted_ca_file:
            ctx = ssl.create_default_context(
                cafile=self.trusted_ca_file)
        else:
            ctx = ssl.create_default_context()
        cert = self.client_cert_file or self.cert_file
        key = self.client_key_file or self.key_file
        if bool(cert) != bool(key):
            # a half-configured keypair must error here, not surface
            # later as an opaque handshake rejection (listener.go:358)
            raise ValueError(
                "ClientCertFile and ClientKeyFile must both be present "
                f"or both absent [cert: {cert!r}, key: {key!r}]")
        if cert and key:
            ctx.load_cert_chain(cert, key)
        return ctx


def resolve_client_context(tls) -> "ssl.SSLContext | None":
    """One resolution rule for every client transport: a TLSInfo builds
    its client context; a prebuilt ssl.SSLContext passes through; None
    stays None (plain http)."""
    if tls is None:
        return None
    if hasattr(tls, "client_context"):
        return tls.client_context()
    return tls


# ------------------------------------------------------- cert generation

_ONE_DAY = datetime.timedelta(days=1)


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import ec

    return ec.generate_private_key(ec.SECP256R1())


def _write_pem(cert, key, cert_path: str, key_path: str) -> None:
    """Write key THEN cert, each via tmp-file + rename: the reuse guard
    checks for both files, so writing the cert last means 'cert.pem
    exists' implies a complete keypair — a crash mid-generation can
    never leave a permanently broken pair behind."""
    from cryptography.hazmat.primitives import serialization

    tmp_key = key_path + ".tmp"
    fd = os.open(tmp_key, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp_key, key_path)
    tmp_cert = cert_path + ".tmp"
    with open(tmp_cert, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp_cert, cert_path)
    # fsync the directory so the renames themselves survive power loss
    dfd = os.open(os.path.dirname(cert_path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _san_entries(hosts):
    from cryptography import x509

    out = []
    for host in hosts or ():
        h = host.rsplit(":", 1)[0] if ":" in host and \
            host.count(":") == 1 else host
        try:
            out.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            out.append(x509.DNSName(h))
    return out


def _build_cert(subject_cn: str, hosts, issuer_cert, issuer_key, key,
                is_ca: bool, validity_days: int, server_auth: bool,
                client_auth: bool):
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    subject = x509.Name([
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, "etcd-tpu"),
        x509.NameAttribute(NameOID.COMMON_NAME, subject_cn),
    ])
    issuer = subject if issuer_cert is None else issuer_cert.subject
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (x509.CertificateBuilder()
         .subject_name(subject)
         .issuer_name(issuer)
         .public_key(key.public_key())
         .serial_number(x509.random_serial_number())
         .not_valid_before(now - _ONE_DAY)
         .not_valid_after(now + datetime.timedelta(days=validity_days))
         .add_extension(x509.BasicConstraints(ca=is_ca, path_length=None),
                        critical=True)
         .add_extension(x509.KeyUsage(
             digital_signature=True, key_encipherment=not is_ca,
             content_commitment=False, data_encipherment=False,
             key_agreement=False, key_cert_sign=is_ca, crl_sign=is_ca,
             encipher_only=False, decipher_only=False), critical=True))
    if not is_ca:
        ekus = []
        if server_auth:
            ekus.append(ExtendedKeyUsageOID.SERVER_AUTH)
        if client_auth:
            ekus.append(ExtendedKeyUsageOID.CLIENT_AUTH)
        b = b.add_extension(x509.ExtendedKeyUsage(ekus), critical=False)
    san = _san_entries(hosts)
    if san:
        b = b.add_extension(x509.SubjectAlternativeName(san),
                            critical=False)
    signer = issuer_key if issuer_key is not None else key
    return b.sign(signer, hashes.SHA256())


def self_cert(dirpath: str, hosts, validity_days: int = 365,
              common_name: str = "etcd-tpu-self") -> TLSInfo:
    """SelfCert (listener.go:185-280): generate (or reuse) a self-signed
    keypair under `dirpath` covering `hosts` as SANs; the same keypair
    serves as server cert and client cert, like the reference's
    auto-TLS. Returns the TLSInfo pointing at cert.pem/key.pem with the
    cert itself as the trust root (self-signed ⇒ it is its own CA)."""
    os.makedirs(dirpath, exist_ok=True)
    cert_path = os.path.abspath(os.path.join(dirpath, "cert.pem"))
    key_path = os.path.abspath(os.path.join(dirpath, "key.pem"))
    if not (os.path.exists(cert_path) and os.path.exists(key_path)):
        key = _new_key()
        cert = _build_cert(common_name, hosts, None, None, key,
                           is_ca=True, validity_days=validity_days,
                           server_auth=True, client_auth=True)
        _write_pem(cert, key, cert_path, key_path)
    return TLSInfo(cert_file=cert_path, key_file=key_path,
                   client_cert_file=cert_path, client_key_file=key_path,
                   trusted_ca_file=cert_path)


def generate_ca(dirpath: str, validity_days: int = 365,
                common_name: str = "etcd-tpu-ca") -> TLSInfo:
    """A private CA for issuing server/client certs (the analog of the
    reference test fixtures' CA; no direct reference function — SelfCert
    only does self-signed). Returns a TLSInfo whose trusted_ca_file is
    the CA cert; cert/key are the CA's own (for issue_cert)."""
    os.makedirs(dirpath, exist_ok=True)
    cert_path = os.path.abspath(os.path.join(dirpath, "ca.pem"))
    key_path = os.path.abspath(os.path.join(dirpath, "ca-key.pem"))
    if not (os.path.exists(cert_path) and os.path.exists(key_path)):
        key = _new_key()
        cert = _build_cert(common_name, (), None, None, key, is_ca=True,
                           validity_days=validity_days,
                           server_auth=False, client_auth=False)
        _write_pem(cert, key, cert_path, key_path)
    return TLSInfo(cert_file=cert_path, key_file=key_path,
                   trusted_ca_file=cert_path)


def _load_ca(ca: TLSInfo):
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization

    with open(ca.cert_file, "rb") as f:
        cert = x509.load_pem_x509_certificate(f.read())
    with open(ca.key_file, "rb") as f:
        key = serialization.load_pem_private_key(f.read(), password=None)
    return cert, key


def issue_cert(dirpath: str, ca: TLSInfo, common_name: str,
               hosts=(), validity_days: int = 365,
               server_auth: bool = True,
               client_auth: bool = True) -> TLSInfo:
    """Issue a leaf cert signed by `ca` with the given CN and SANs —
    the identity carrier for cert-CN auth (server/auth/store.go:985
    AuthInfoFromTLS takes the verified chain's CommonName as the user)."""
    os.makedirs(dirpath, exist_ok=True)
    base = common_name.replace("/", "_")
    cert_path = os.path.abspath(os.path.join(dirpath, f"{base}.pem"))
    key_path = os.path.abspath(
        os.path.join(dirpath, f"{base}-key.pem"))
    if not (os.path.exists(cert_path) and os.path.exists(key_path)):
        ca_cert, ca_key = _load_ca(ca)
        key = _new_key()
        cert = _build_cert(common_name, hosts, ca_cert, ca_key, key,
                           is_ca=False, validity_days=validity_days,
                           server_auth=server_auth,
                           client_auth=client_auth)
        _write_pem(cert, key, cert_path, key_path)
    return TLSInfo(cert_file=cert_path, key_file=key_path,
                   trusted_ca_file=ca.trusted_ca_file or ca.cert_file)


# ------------------------------------------------- connection identities

def peer_common_name(conn) -> str | None:
    """The verified client cert's CN off a handshaked SSL socket, or
    None (plain socket / no client cert / unverified). Only verified
    certs carry identity — ssl only exposes getpeercert() content when
    verify_mode required/optional verification succeeded, mirroring the
    reference's use of VerifiedChains (store.go:992)."""
    getpeercert = getattr(conn, "getpeercert", None)
    if getpeercert is None:
        return None
    cert = getpeercert()
    if not cert:
        return None
    for rdn in cert.get("subject", ()):
        for k, v in rdn:
            if k == "commonName":
                return v
    return None


def check_cert_constraints(conn, allowed_cn: str = "",
                           allowed_hostname: str = "") -> bool:
    """The post-handshake gate of NewTLSListener (listener_tls.go:43,
    check 'allowed CN'/'allowed hostname'): True iff the peer cert
    satisfies the configured constraint. No constraints ⇒ pass."""
    if not allowed_cn and not allowed_hostname:
        return True
    cert = conn.getpeercert() if hasattr(conn, "getpeercert") else None
    if not cert:
        return False
    if allowed_cn:
        return peer_common_name(conn) == allowed_cn
    # hostname constraint: the cert's SANs must cover it (wildcard
    # matching via ssl's private helper, exact match if it ever moves)
    for typ, val in cert.get("subjectAltName", ()):
        if typ == "IP Address" and val == allowed_hostname:
            return True
        if typ == "DNS":
            try:
                if ssl._dnsname_match(val, allowed_hostname):
                    return True
            except AttributeError:  # pragma: no cover
                if val == allowed_hostname:
                    return True
    return False
