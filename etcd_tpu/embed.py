"""Process assembly: embed.Config + start_etcd.

The reference's ``embed`` package is the library form of the server
process: one Config struct carrying every flag (server/embed/config.go),
``StartEtcd(cfg)`` wiring listeners + EtcdServer + v3rpc together
(server/embed/etcd.go:104), and etcdmain as the CLI shell around it.

Here ``start_etcd(Config)`` boots the batched fleet (one simulated
multi-member cluster), serves the v3 JSON/HTTP API on the client URL,
and runs the tick loop (heartbeats, lease expiry, auto-compaction) on a
background thread — the process-level analog of raftNode's ticker +
the compactor + lessor runLoop goroutines.
"""
from __future__ import annotations

import dataclasses
import threading
import time

from etcd_tpu.server.compactor import Compactor
from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.v3rpc import V3Server


@dataclasses.dataclass
class Config:
    """The embed.Config analog (server/embed/config.go), trimmed to the
    knobs the TPU runtime honors."""

    name: str = "default"
    data_dir: str | None = None
    listen_client_host: str = "127.0.0.1"
    listen_client_port: int = 0  # 0 = ephemeral
    cluster_size: int = 3
    tick_ms: int = 100                  # --heartbeat-interval
    election_ticks: int = 10            # --election-timeout / tick
    quota_backend_bytes: int = 0        # --quota-backend-bytes
    auto_compaction_mode: str = "off"   # --auto-compaction-mode
    auto_compaction_retention: int = 0  # --auto-compaction-retention
    pre_vote: bool = True               # --pre-vote
    check_quorum: bool = True
    auto_tick: bool = True              # background ticker on/off
    # --auth-token (embed/config.go AuthToken): "simple" or
    # "jwt[,sign-method=HS256][,ttl=SECONDS]"; jwt needs auth_jwt_key
    # (the priv-key= file contents of the reference flag)
    auth_token: str = "simple"
    auth_jwt_key: bytes | None = None
    # --initial-cluster-state (config.go ClusterState): "new" boots a
    # fresh cluster; "existing" joins one that already has data
    initial_cluster_state: str = "new"
    # --force-new-cluster (config.go ForceNewCluster): disaster recovery —
    # restart from this data dir as a ONE-member cluster, discarding the
    # other members (bootstrap.go:327-341)
    force_new_cluster: bool = False
    # cluster-version monitor cadence in ticks (monitorVersionInterval =
    # 5s at the reference's 100ms tick, server.go:2160); 0 disables.
    # The manual tick() path (tests) leaves monitoring to explicit
    # monitor_versions() calls so tick counts stay deterministic.
    monitor_version_ticks: int = 50
    # Transport security (embed/config.go ClientTLSInfo + ClientAutoTLS).
    # client_tls serves the gateway over HTTPS; client_auto_tls
    # generates a self-signed cert under data_dir/fixtures/client
    # (config.go:677 self-signed path). The reference's PeerTLSInfo has
    # NO analog on purpose: member-to-member consensus inside one fleet
    # is an on-device tensor exchange — there is no peer socket to
    # encrypt, and offering a knob that protects nothing would mislead.
    client_tls: "object | None" = None   # transport.TLSInfo
    client_auto_tls: bool = False
    # --unsafe-no-fsync (embed/config.go UnsafeNoFsync): skip the
    # fsync-before-ack durability barrier. Faster, loses acknowledged
    # writes on kill -9.
    unsafe_no_fsync: bool = False
    # --metrics extensive analog: attach the fleet telemetry plane
    # (models/telemetry.py) so /metrics serves latency-histogram
    # families (commit latency, election duration) beside the gauges.
    # One extra small fused dispatch per raft step.
    telemetry: bool = False
    # black-box event ring (models/blackbox.py): per-round packed event
    # words per member, exportable with the host request spans as a
    # Chrome/Perfetto trace (blackbox.to_chrome_trace). Same
    # one-extra-dispatch cost profile as telemetry.
    blackbox: bool = False

    def validate(self) -> None:
        if self.cluster_size < 1:
            raise ValueError("cluster size must be >= 1")
        if self.tick_ms <= 0:
            raise ValueError("tick interval must be positive")
        if self.auto_compaction_mode not in ("off", "periodic", "revision"):
            raise ValueError(
                f"unknown auto-compaction mode {self.auto_compaction_mode}"
            )
        if self.auth_token.split(",")[0] not in ("simple", "jwt"):
            raise ValueError(f"unknown auth token provider {self.auth_token}")
        if self.auth_token.split(",")[0] == "jwt" and not self.auth_jwt_key:
            raise ValueError("auth_token=jwt requires auth_jwt_key")
        if self.initial_cluster_state not in ("new", "existing"):
            raise ValueError(
                "initial cluster state must be 'new' or 'existing', got "
                f"{self.initial_cluster_state!r}"
            )
        if self.force_new_cluster and not self.data_dir:
            raise ValueError("force_new_cluster requires a data_dir")
        if self.client_tls is not None and self.client_auto_tls:
            raise ValueError(
                "client_tls and client_auto_tls are mutually exclusive")
        if self.client_auto_tls and not self.data_dir:
            # the self-signed keypair lives under data_dir/fixtures
            # like the reference's auto-TLS (embed/config.go:677)
            raise ValueError("auto TLS requires a data_dir")


class Etcd:
    """A running embedded server (embed.Etcd analog)."""

    def __init__(self, cfg: Config):
        cfg.validate()
        self.config = cfg
        from etcd_tpu.harness.cluster import Cluster
        from etcd_tpu.utils.config import RaftConfig

        raft_cfg = RaftConfig(
            election_tick=max(cfg.election_ticks, 2),
            heartbeat_tick=1,
            pre_vote=cfg.pre_vote,
            check_quorum=cfg.check_quorum,
        )
        self.server = self._bootstrap(cfg, raft_cfg)
        self.server.ensure_leader()
        self.compactor = Compactor(
            self.server, cfg.auto_compaction_mode,
            cfg.auto_compaction_retention,
        )
        self.client_tls = self._resolve_tls(cfg)
        self.http = V3Server(
            self.server, cfg.listen_client_host, cfg.listen_client_port,
            tls_info=self.client_tls,
        ).start()
        # contention detector over the tick cadence (pkg/contention armed
        # at 2x the interval, etcdserver/raft.go:133)
        from etcd_tpu.utils.contention import TimeoutDetector

        self.contention = TimeoutDetector(2 * cfg.tick_ms / 1000.0)
        self.server.contention = self.contention
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None
        if cfg.auto_tick:
            self._ticker = threading.Thread(target=self._tick_loop,
                                            daemon=True)
            self._ticker.start()

    @staticmethod
    def _resolve_tls(cfg: Config):
        """ClientTLSInfo resolution incl. the auto-TLS self-signed path
        (embed/config.go:677): the generated keypair lives under
        data_dir/fixtures/client and is reused across restarts."""
        import os

        from etcd_tpu.transport import self_cert

        client = cfg.client_tls
        if client is None and cfg.client_auto_tls:
            hosts = [cfg.listen_client_host, "localhost", "127.0.0.1"]
            if cfg.listen_client_host in ("0.0.0.0", "::", ""):
                # a wildcard listen address is never what clients dial:
                # cover this machine's name + addresses in the SANs
                import socket

                name = socket.gethostname()
                hosts.append(name)
                try:
                    hosts.extend({ai[4][0] for ai in
                                  socket.getaddrinfo(name, None)})
                except OSError:
                    pass
            client = self_cert(
                os.path.join(cfg.data_dir, "fixtures", "client"), hosts)
        return client

    @staticmethod
    def _bootstrap(cfg: Config, raft_cfg) -> EtcdCluster:
        """The cold-start selection tree (bootstrap.go:51-99): data on
        disk (haveWAL) always wins and restarts the cluster from it;
        otherwise initial_cluster_state picks between bootstrapping a new
        cluster and joining an existing one.

        | disk state        | new            | existing               |
        |-------------------|----------------|------------------------|
        | no data_dir       | fresh (memory) | error: nothing to join |
        | empty dir         | fresh (wipes)  | error: nothing to join |
        | any member data   | restart from disk; absent members catch  |
        |                   | up from peers (missing_ok)               |
        | + force_new_...   | 1-member cluster from member 0's data    |
        """
        import os

        from etcd_tpu.harness.cluster import Cluster
        from etcd_tpu.utils.logging import get_logger

        kw = dict(
            quota_bytes=cfg.quota_backend_bytes,
            auth_token=cfg.auth_token,
            auth_jwt_key=cfg.auth_jwt_key,
            # a server process must not lose acknowledged writes to
            # kill -9 (--unsafe-no-fsync is the reference's opt-out)
            durable_proposes=not cfg.unsafe_no_fsync,
        )
        n = cfg.cluster_size
        have = [
            os.path.exists(EtcdCluster.member_db_path(cfg.data_dir, m))
            for m in range(n)
        ] if cfg.data_dir else []
        if any(have):
            # bootstrap.go:91 bootstrapWithWAL: on-disk state wins over
            # the initial-cluster-state flag
            if cfg.force_new_cluster:
                # recover from the first member whose data survived —
                # never silently start empty while peer data exists
                src = have.index(True)
                get_logger().warning(
                    "forcing new cluster from member %d of %s",
                    src, cfg.data_dir,
                )
                return EtcdCluster.boot_from_disk(
                    cfg.data_dir, n_members=1, members=[src],
                    cluster=Cluster(n_members=1, cfg=raft_cfg,
                        telemetry=cfg.telemetry,
                        blackbox=cfg.blackbox), **kw,
                )
            return EtcdCluster.boot_from_disk(
                cfg.data_dir, n_members=n, missing_ok=True, uniform=False,
                cluster=Cluster(n_members=n, cfg=raft_cfg,
                        telemetry=cfg.telemetry,
                        blackbox=cfg.blackbox), **kw,
            )
        if cfg.initial_cluster_state == "existing":
            # bootstrapExistingClusterNoWAL (bootstrap.go:182) fails the
            # same way when the named cluster cannot be reached
            raise ValueError(
                "initial_cluster_state='existing' but no member data "
                f"exists under {cfg.data_dir!r}; nothing to join"
            )
        return EtcdCluster(
            n_members=n,
            cluster=Cluster(n_members=n, cfg=raft_cfg,
                        telemetry=cfg.telemetry,
                        blackbox=cfg.blackbox),
            data_dir=cfg.data_dir,
            **kw,
        )

    @property
    def client_url(self) -> str:
        return (f"{self.http.scheme}://"
                f"{self.config.listen_client_host}:{self.http.port}")

    def _tick_loop(self) -> None:
        period = self.config.tick_ms / 1000.0
        # lease TTLs are seconds (lease/lessor.go): accumulate wall time
        # and advance the lease clock once per elapsed second, whatever
        # the raft tick rate (sub-second or multi-second) is
        owed = 0.0
        ticks = 0
        mon_every = self.config.monitor_version_ticks
        # v2 TTL expiry is driven by committed SYNC proposals (the
        # reference's syncer fires every 500ms, etcdserver/server.go);
        # without this, expired v2 keys stay visible forever on a
        # running server
        sync_every = max(1, round(0.5 / period))
        sync_failed = False
        while not self._stop.wait(period):
            owed += period
            advance = int(owed)
            owed -= advance
            ticks += 1
            on_time, exceed = self.contention.observe("tick")
            if not on_time:
                from etcd_tpu.utils.logging import get_logger

                get_logger().warning(
                    "ticker took %.3fs longer than expected; host loop "
                    "contended (disk/CPU starvation)", exceed,
                )
            with self.http.api.lock:
                self.server.tick(lease_clock=advance >= 1)
                for _ in range(advance - 1):  # tick_ms > 1000: catch up
                    self.server.advance_lease_clock()
                self.compactor.tick()
                if ticks % sync_every == 0:
                    from etcd_tpu.types import NONE_ID
                    from etcd_tpu.utils.logging import get_logger

                    try:
                        # leader() is a pure probe: ensure_leader()'s
                        # forced ticks would fast-forward the lease
                        # clock during leaderless windows
                        lead = self.server.leader()
                        if lead != NONE_ID and self.server.members[lead] \
                                .v2store.has_ttl_keys():
                            self.server.v2_sync()
                        sync_failed = False
                    except Exception as e:
                        # lost leadership, backpressure, or an apply
                        # error — the next pass retries; NOTHING may
                        # escape and kill the ticker thread (raft
                        # ticks, lease clock and compaction all ride
                        # it). Say so once per failure streak.
                        if not sync_failed:
                            get_logger().warning(
                                "v2 SYNC proposal failed: %s", e)
                        sync_failed = True
                if mon_every and ticks % mon_every == 0:
                    # monitorVersions + monitorDowngrade passes (leader
                    # only; no-ops otherwise). Proposal failures (lost
                    # leadership mid-pass) are the next pass's problem.
                    try:
                        self.server.monitor_versions()
                        self.server.monitor_downgrade()
                    except Exception:
                        pass

    def tick(self, n: int = 1) -> None:
        """Manual clock (auto_tick=False mode, for tests): each call is
        one raft tick AND one lease-clock second."""
        with self.http.api.lock:
            for _ in range(n):
                self.server.tick()
                self.compactor.tick()

    def close(self) -> None:
        from etcd_tpu.utils.logging import get_logger

        self._stop.set()
        if self._ticker:
            self._ticker.join(timeout=2)
        self.http.stop()
        try:
            # clean shutdown leaves every member at the committed front
            self.server.sync_for_shutdown()
        except Exception:
            pass  # crashy members can't block close
        for ms in self.server.members:
            if ms.backend is not None:
                ms.backend.close()
        get_logger().info("etcd %r stopped", self.config.name)


def start_etcd(cfg: Config) -> Etcd:
    """embed.StartEtcd (server/embed/etcd.go:104)."""
    from etcd_tpu.utils.logging import get_logger

    e = Etcd(cfg)
    get_logger().info(
        "etcd %r serving %d members at %s", cfg.name, cfg.cluster_size,
        e.client_url,
    )
    return e
