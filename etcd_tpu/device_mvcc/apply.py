"""Vmapped txn apply, compaction scatter, digest and watch-delta scan.

One committed int32 entry word (scheme.py codec) is one MVCC operation;
``apply_word`` applies one word across the whole ``[keys, C]`` fleet as
straight-line masked tensor updates — the device twin of
``MVCCStore.WriteTxn`` (etcd_tpu/server/mvcc.py):

  * revisions ``{main, sub}`` are preserved semantically: a word with the
    CONT bit continues the previous word's txn (same main, next sub —
    intra-txn op order), a word without it opens a new txn at
    ``current_rev + 1``; ``current_rev`` advances only when the txn wrote
    (WriteTxn.end()).  The latest record stores main exactly as
    mvccpb.KeyValue.mod_revision does; sub never escapes the host store's
    rev-keyed index either.
  * put: read-your-writes against the live store (earlier words of the
    same txn already landed), version bump iff the key is live, fresh
    generation (create=main, version=1) after a tombstone — key_index.go
    semantics without the generation lists.
  * delete-range: one masked interval tombstone write; only live keys
    count toward deleted (and toward the txn's wrote flag).
  * compact: ``ErrCompacted``/``ErrFutureRev`` become per-group status
    lanes (counters — the batched form of the host's raised exceptions),
    then a masked scatter clears keys whose latest record is a tombstone
    at or below the compaction floor (kvstore_compaction.go's
    "drop whole keys whose latest is a tombstone").

``kv_digest`` is the device half of the shared canonical digest
(scheme.latest_digest); ``extract_deltas`` is the per-round watch delta
scan — keys whose mod_revision moved past the previous round's revision
cursor, revision-coalesced (one event per key per round, carrying the
newest record; the host watch facade fans these out,
server/watch.py:events_from_delta).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from etcd_tpu.device_mvcc import scheme
from etcd_tpu.device_mvcc.state import KVSpec, KVState


def _i32c(x: int) -> jnp.ndarray:
    return jnp.int32(scheme.i32(x))


def _value_hash32(val: jnp.ndarray) -> jnp.ndarray:
    """jnp twin of scheme.value_hash32 (int32 wrap == u32 congruence)."""
    val = val.astype(jnp.int32)
    return (val * _i32c(scheme.MIX_A)) ^ (val + _i32c(scheme.MIX_B))


def _record_mix(key, mod, create, version, vword, lease, tomb):
    """jnp twin of scheme.record_mix — keep line-for-line congruent."""
    h = key * _i32c(scheme.MIX_A) + mod * _i32c(scheme.MIX_B)
    h = h ^ (create * _i32c(scheme.MIX_C) + version * _i32c(scheme.MIX_D) + 7)
    h = h * _i32c(scheme.MIX_C) + (
        _value_hash32(vword) ^ (lease * _i32c(scheme.MIX_E))
    )
    return h + tomb.astype(jnp.int32) * _i32c(scheme.MIX_D)


def apply_word(kvspec: KVSpec, st: KVState, word: jnp.ndarray,
               active: jnp.ndarray) -> KVState:
    """Apply one op word per group. ``word``/``active`` are [C] (scalar
    broadcasts fine); inactive lanes (and NOP/unparseable kinds) are
    identity. Pure elementwise over [keys, C] — no gathers: the key axis
    is small, so one-hot masks beat scatter lowering on TPU."""
    K = kvspec.keys
    word = jnp.asarray(word, jnp.int32)
    active = jnp.asarray(active, jnp.bool_)
    word, active = jnp.broadcast_arrays(word, active.astype(jnp.bool_))

    kind = word & 3
    cont = (word & scheme.CONT_BIT) != 0
    key = (word >> scheme.KEY_SHIFT) & scheme.MAX_KEYS
    val = (word >> scheme.VAL_SHIFT) & scheme.MAX_VAL
    lease = (word >> scheme.LEASE_SHIFT) & scheme.MAX_LEASE
    hi = (word >> scheme.HI_SHIFT) & ((1 << scheme.HI_BITS) - 1)
    crev = (word >> scheme.REV_SHIFT) & scheme.MAX_COMPACT_REV

    is_put = active & (kind == scheme.KIND_PUT)
    is_del = active & (kind == scheme.KIND_DELETE)
    is_cmp = active & (kind == scheme.KIND_COMPACT)

    # txn main: a CONT word shares the OPEN txn's main (WriteTxn.main);
    # anything else — including a CONT with no txn open (first word, or
    # right after a compact closed it) — opens a fresh txn at
    # current_rev + 1, exactly like the host replay reopening after
    # end(). txn_main == 0 means "no open txn".
    has_txn = cont & (st.txn_main > 0)
    main = jnp.where(has_txn, st.txn_main, st.current_rev + 1)    # [C]

    ids = jnp.arange(K, dtype=jnp.int32)[:, None]                  # [K, 1]
    live = st.present & ~st.tomb                                   # [K, C]

    # ---- put --------------------------------------------------------------
    pmask = is_put[None, :] & (ids == key[None, :])                # [K, C]
    new_present = st.present | pmask
    new_tomb = st.tomb & ~pmask
    new_mod = jnp.where(pmask, main[None, :], st.mod)
    # fresh generation after absence/tombstone: create=main, version=1;
    # live key: create kept, version + 1 (key_index.go created_version)
    new_create = jnp.where(pmask, jnp.where(live, st.create, main[None, :]),
                           st.create)
    new_version = jnp.where(pmask, jnp.where(live, st.version + 1, 1),
                            st.version)
    new_vword = jnp.where(pmask, val[None, :], st.vword)
    new_lease = jnp.where(pmask, lease[None, :], st.lease)

    # ---- delete-range -----------------------------------------------------
    dmask = (
        is_del[None, :] & live
        & (ids >= key[None, :]) & (ids < hi[None, :])
    )                                                              # [K, C]
    deleted_any = dmask.any(axis=0)                                # [C]
    # tombstoned keys stay present (in the index) until compaction
    new_tomb = new_tomb | dmask
    new_mod = jnp.where(dmask, main[None, :], new_mod)
    # host tombstone KeyValue: (key, b"", create=0, mod=rev, version=0,
    # lease=0) — mirror the zeroed fields exactly or digests diverge
    new_create = jnp.where(dmask, 0, new_create)
    new_version = jnp.where(dmask, 0, new_version)
    new_vword = jnp.where(dmask, 0, new_vword)
    new_lease = jnp.where(dmask, 0, new_lease)

    wrote = is_put | (is_del & deleted_any)
    new_current = jnp.where(wrote, main, st.current_rev)
    # a compact CLOSES the open txn (host replay ends it before
    # compacting), so a later CONT word cannot bind to a stale main
    new_txn_main = jnp.where(
        is_put | is_del, main,
        jnp.where(is_cmp, 0, st.txn_main),
    )

    # ---- compact ----------------------------------------------------------
    bad_c = is_cmp & (crev <= st.compact_rev)   # mvcc.ErrCompacted
    bad_f = is_cmp & (crev > st.current_rev)    # mvcc.ErrFutureRev
    ok_cmp = is_cmp & ~bad_c & ~bad_f
    new_compact = jnp.where(ok_cmp, crev, st.compact_rev)
    # masked scatter: whole keys whose latest is a tombstone at/below the
    # floor drop out of the index (kvstore_compaction.go); live keys keep
    # their latest record, exactly like KeyIndex.compact keeps it
    gone = ok_cmp[None, :] & st.tomb & (st.mod <= crev[None, :])
    new_present = new_present & ~gone
    new_tomb = new_tomb & ~gone
    new_mod = jnp.where(gone, 0, new_mod)

    return st.replace(
        present=new_present, tomb=new_tomb, mod=new_mod, create=new_create,
        version=new_version, vword=new_vword, lease=new_lease,
        current_rev=new_current, compact_rev=new_compact,
        txn_main=new_txn_main,
        err_compacted=st.err_compacted + bad_c.astype(jnp.int32),
        err_future=st.err_future + bad_f.astype(jnp.int32),
    )


def apply_words(kvspec: KVSpec, st: KVState, words: jnp.ndarray,
                active: jnp.ndarray | None = None) -> KVState:
    """Apply a word stream [N, C] (each group its own schedule down axis 0
    — the differential fuzz layout). ``active`` [N, C] masks individual
    ops; None = all on."""
    words = jnp.asarray(words, jnp.int32)
    if active is None:
        active = jnp.ones(words.shape, jnp.bool_)

    def body(carry, wa):
        w, a = wa
        return apply_word(kvspec, carry, w, a), None

    st, _ = jax.lax.scan(body, st, (words, jnp.asarray(active, jnp.bool_)))
    return st


# ---------------------------------------------------------------------------
# reads
# ---------------------------------------------------------------------------


def check_rev(st: KVState, rev: jnp.ndarray):
    """The host's _check_rev window test as status lanes:
    (err_future, err_compacted, at) for a requested read revision
    (rev <= 0 means current). ``at`` is the served revision."""
    rev = jnp.broadcast_to(jnp.asarray(rev, jnp.int32), st.current_rev.shape)
    cur = jnp.where(rev <= 0, st.current_rev, rev)
    err_f = cur > st.current_rev
    at = jnp.where(err_f, st.current_rev, cur)
    err_c = at < st.compact_rev
    return err_f, err_c, at


def read_at(kvspec: KVSpec, st: KVState, rev: jnp.ndarray = 0):
    """Visibility mask at a revision: (visible [keys, C], unservable
    [keys, C], err_future [C], err_compacted [C]).

    The latest-only store serves a key at ``rev`` exactly when its latest
    record is at or below ``rev`` (nothing newer exists to mask) — always
    true at the current revision.  A matching key whose mod_revision is
    ABOVE ``rev`` is flagged ``unservable``: its state at ``rev`` was
    compacted-to-latest by construction, and the honest etcd-shaped
    answer is ErrCompacted (the plane's per-key compaction floor is the
    latest record).  The host facade (server/mvcc.py DeviceBackedStore)
    raises on any unservable hit rather than returning wrong data."""
    err_f, err_c, at = check_rev(st, rev)
    reach = st.present & (st.mod <= at[None, :])
    visible = reach & ~st.tomb
    unservable = st.present & (st.mod > at[None, :])
    return visible, unservable, err_f, err_c


# ---------------------------------------------------------------------------
# digest (device half of the shared canonical fold)
# ---------------------------------------------------------------------------


def kv_digest(kvspec: KVSpec, st: KVState) -> jnp.ndarray:
    """[C] i32 — bit-equal to scheme.store_latest_digest of a host store
    that applied the same words (the equivalence gate of
    tests/test_device_mvcc.py)."""
    K = kvspec.keys
    ids = jnp.arange(K, dtype=jnp.int32)[:, None]
    mix = _record_mix(ids, st.mod, st.create, st.version, st.vword,
                      st.lease, st.tomb)
    s = (mix * st.present.astype(jnp.int32)).sum(
        axis=0, dtype=jnp.int32
    )
    h = s * _i32c(scheme.MIX_C) + st.current_rev * _i32c(scheme.MIX_A)
    return h ^ (st.compact_rev * _i32c(scheme.MIX_E) + _i32c(scheme.MIX_B))


# ---------------------------------------------------------------------------
# watch deltas (device-side delta scan)
# ---------------------------------------------------------------------------


class WatchDelta(struct.PyTreeNode):
    """Per-round [keys, C] delta tensors the host watch facade fans out.
    ``mask`` selects keys whose latest record moved past ``rev_floor``
    this round; ``tomb`` distinguishes delete events. Coalesced by
    revision: one event per key per round, carrying the newest record."""

    mask: jnp.ndarray      # bool[K, C]
    tomb: jnp.ndarray      # bool[K, C]
    mod: jnp.ndarray       # i32[K, C]
    create: jnp.ndarray    # i32[K, C]
    version: jnp.ndarray   # i32[K, C]
    vword: jnp.ndarray     # i32[K, C]
    lease: jnp.ndarray     # i32[K, C]
    rev_floor: jnp.ndarray  # i32[C] — deltas cover (rev_floor, current_rev]


def extract_deltas(kvspec: KVSpec, rev_floor: jnp.ndarray,
                   st: KVState) -> WatchDelta:
    """Keys whose latest record landed after ``rev_floor`` (usually the
    previous round's current_rev). Compaction never fires a delta (it
    clears mod to 0, below any floor)."""
    rev_floor = jnp.broadcast_to(
        jnp.asarray(rev_floor, jnp.int32), st.current_rev.shape
    )
    mask = st.present & (st.mod > rev_floor[None, :])
    return WatchDelta(
        mask=mask, tomb=st.tomb & mask, mod=st.mod, create=st.create,
        version=st.version, vword=st.vword, lease=st.lease,
        rev_floor=rev_floor,
    )
