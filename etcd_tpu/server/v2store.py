"""The legacy v2 store — an in-memory hierarchical key tree.

Re-design of ``server/etcdserver/api/v2store`` (store.go, node.go,
event.go, event_history.go, watcher.go, watcher_hub.go, ttl_key_heap.go)
for this framework: the store is the *applied state machine* behind the
batched device consensus engine — every mutation arrives as a committed
v2 request (see kvserver's ``kind == "v2"`` dispatch, the applyV2Request
analog of apply_v2.go:124-148) so all members hold bit-identical trees.

Host-side by design: like MVCC, the v2 tree is irregular pointer-chasing
state that belongs on the host; the device fleet carries the replicated
log that orders its mutations (SURVEY §2.4 — apply is host work).

Differences from the reference, all deliberate:
- Nodes are plain Python objects; NodeExtern reprs are JSON-ready dicts.
- Watchers buffer events in a deque (capacity 100, overflow removes the
  watcher — watcher.go:63-72's closed-channel rule) instead of channels;
  the gateway long-polls them like the v3 watch façade.
- Time is a float-seconds clock injected by the server so TTL math stays
  deterministic under test clocks; proposed requests carry an absolute
  expiration, exactly like RequestV2.Expiration (apply_v2.go:150-157).
"""
from __future__ import annotations

import heapq
import json
import math
import time as _time
from collections import deque
from typing import Any, Callable

# ---------------------------------------------------------------- errors
# v2error/error.go:83-106 code points + :27-63 messages

EcodeKeyNotFound = 100
EcodeTestFailed = 101
EcodeNotFile = 102
EcodeNotDir = 104
EcodeNodeExist = 105
EcodeRootROnly = 107
EcodeDirNotEmpty = 108
EcodeUnauthorized = 110
EcodePrevValueRequired = 201
EcodeTTLNaN = 202
EcodeIndexNaN = 203
EcodeInvalidField = 209
EcodeInvalidForm = 210
EcodeRefreshValue = 211
EcodeRefreshTTLRequired = 212
EcodeRaftInternal = 300
EcodeLeaderElect = 301
EcodeWatcherCleared = 400
EcodeEventIndexCleared = 401

_MESSAGES = {
    EcodeKeyNotFound: "Key not found",
    EcodeTestFailed: "Compare failed",
    EcodeNotFile: "Not a file",
    EcodeNotDir: "Not a directory",
    EcodeNodeExist: "Key already exists",
    EcodeRootROnly: "Root is read only",
    EcodeDirNotEmpty: "Directory not empty",
    EcodeUnauthorized: "The request requires user authentication",
    EcodePrevValueRequired: "PrevValue is Required in POST form",
    EcodeTTLNaN: "The given TTL in POST form is not a number",
    EcodeIndexNaN: "The given index in POST form is not a number",
    EcodeInvalidField: "Invalid field",
    EcodeInvalidForm: "Invalid POST form",
    EcodeRefreshValue: "Value provided on refresh",
    EcodeRefreshTTLRequired: "A TTL must be provided on refresh",
    EcodeRaftInternal: "Raft Internal Error",
    EcodeLeaderElect: "During Leader Election",
    EcodeWatcherCleared: "watcher is cleared due to etcd recovery",
    EcodeEventIndexCleared:
        "The event in requested index is outdated and cleared",
}

# HTTP status mapping (v2error/error.go:71-80; default 400)
_HTTP_STATUS = {
    EcodeKeyNotFound: 404,
    EcodeNotFile: 403,
    EcodeDirNotEmpty: 403,
    EcodeUnauthorized: 401,
    EcodeTestFailed: 412,
    EcodeNodeExist: 412,
    EcodeRaftInternal: 500,
    EcodeLeaderElect: 500,
}


class V2Error(Exception):
    """v2error.Error: code + cause + the store index at raise time."""

    def __init__(self, code: int, cause: str = "", index: int = 0):
        self.code = code
        self.cause = cause
        self.index = index
        super().__init__(f"{_MESSAGES.get(code, f'code {code}')} ({cause})"
                         f" [{index}]")

    @property
    def message(self) -> str:
        return _MESSAGES.get(self.code, f"code {self.code}")

    def status_code(self) -> int:
        return _HTTP_STATUS.get(self.code, 400)

    def to_json(self) -> dict:
        return {"errorCode": self.code, "message": self.message,
                "cause": self.cause, "index": self.index}


# ---------------------------------------------------------------- events
# event.go:17-26 action names

GET = "get"
CREATE = "create"
SET = "set"
UPDATE = "update"
DELETE = "delete"
COMPARE_AND_SWAP = "compareAndSwap"
COMPARE_AND_DELETE = "compareAndDelete"
EXPIRE = "expire"

PERMANENT = None  # node.ExpireTime zero-value analog


def _clean_path(p: str) -> str:
    """path.Clean(path.Join("/", p)) — collapse //, resolve ., .., root it."""
    parts: list[str] = []
    for comp in p.split("/"):
        if comp in ("", "."):
            continue
        if comp == "..":
            if parts:
                parts.pop()
            continue
        parts.append(comp)
    return "/" + "/".join(parts)


def _split_path(p: str) -> tuple[str, str]:
    """path.Split: (dir with trailing slash semantics collapsed, base)."""
    p = _clean_path(p)
    if p == "/":
        return "/", ""
    i = p.rfind("/")
    return (p[:i] or "/"), p[i + 1:]


class Event:
    """event.go Event: action + node repr + optional prevNode repr."""

    __slots__ = ("action", "node", "prev_node", "etcd_index", "refresh")

    def __init__(self, action: str, node: dict,
                 prev_node: dict | None = None, etcd_index: int = 0,
                 refresh: bool = False):
        self.action = action
        self.node = node
        self.prev_node = prev_node
        self.etcd_index = etcd_index
        self.refresh = refresh

    def index(self) -> int:
        return self.node.get("modifiedIndex", 0)

    def is_created(self) -> bool:
        # event.go:49-54
        if self.action == CREATE:
            return True
        return self.action == SET and self.prev_node is None

    def clone(self) -> "Event":
        return Event(self.action, dict(self.node),
                     dict(self.prev_node) if self.prev_node else None,
                     self.etcd_index, self.refresh)

    def to_json(self) -> dict:
        out = {"action": self.action, "node": self.node}
        if self.prev_node is not None:
            out["prevNode"] = self.prev_node
        return out


class Node:
    """node.go node: one tree vertex — KV (children is None) or dir."""

    __slots__ = ("path", "value", "children", "created_index",
                 "modified_index", "expire_time", "parent", "store")

    def __init__(self, store: "V2Store", path: str, created: int,
                 parent: "Node | None", expire_time: float | None,
                 value: str | None = None, is_dir: bool = False):
        self.store = store
        self.path = path
        self.created_index = created
        self.modified_index = created
        self.parent = parent
        self.expire_time = expire_time
        if is_dir:
            self.children: dict[str, Node] | None = {}
            self.value = ""
        else:
            self.children = None
            self.value = value or ""

    # ---- predicates (node.go:87-108)
    def is_dir(self) -> bool:
        return self.children is not None

    def is_permanent(self) -> bool:
        return self.expire_time is None

    def is_hidden(self) -> bool:
        _, name = _split_path(self.path)
        return name.startswith("_")

    # ---- accessors
    def write(self, value: str, index: int) -> None:
        if self.is_dir():
            raise V2Error(EcodeNotFile, "", self.store.current_index)
        self.value = value
        self.modified_index = index

    def expiration_and_ttl(self, now: float) -> tuple[str | None, int]:
        """node.go:131-151 — ttl = ceil(expire - now), floor 1s range."""
        if self.is_permanent():
            return None, 0
        ttl = math.ceil(self.expire_time - now)
        iso = _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                             _time.gmtime(self.expire_time))
        return iso, int(ttl)

    def get_child(self, name: str) -> "Node | None":
        if not self.is_dir():
            raise V2Error(EcodeNotDir, self.path, self.store.current_index)
        return self.children.get(name)

    def add(self, child: "Node") -> None:
        if not self.is_dir():
            raise V2Error(EcodeNotDir, "", self.store.current_index)
        _, name = _split_path(child.path)
        if name in self.children:
            raise V2Error(EcodeNodeExist, "", self.store.current_index)
        self.children[name] = child

    def remove(self, dir: bool, recursive: bool,
               callback: Callable[[str], None] | None) -> None:
        """node.go:206-256 — delete self (and children when recursive)."""
        if not self.is_dir():
            _, name = _split_path(self.path)
            if self.parent is not None and \
                    self.parent.children.get(name) is self:
                del self.parent.children[name]
            if callback:
                callback(self.path)
            if not self.is_permanent():
                self.store._ttl_heap_remove(self)
            return
        if not dir:
            raise V2Error(EcodeNotFile, self.path, self.store.current_index)
        if self.children and not recursive:
            raise V2Error(EcodeDirNotEmpty, self.path,
                          self.store.current_index)
        for child in list(self.children.values()):
            child.remove(True, True, callback)
        _, name = _split_path(self.path)
        if self.parent is not None and \
                self.parent.children.get(name) is self:
            del self.parent.children[name]
            if callback:
                callback(self.path)
            if not self.is_permanent():
                self.store._ttl_heap_remove(self)

    def update_ttl(self, expire_time: float | None) -> None:
        """node.go:311-338 — move between permanent and TTL'd."""
        if not self.is_permanent():
            if expire_time is None:
                self.expire_time = None
                self.store._ttl_heap_remove(self)
            else:
                self.expire_time = expire_time
                self.store._ttl_heap_push(self)  # re-key (lazy heap)
            return
        if expire_time is None:
            return
        self.expire_time = expire_time
        self.store._ttl_heap_push(self)

    def compare(self, prev_value: str, prev_index: int) -> tuple[bool, int]:
        """node.go:340-358 — '' / 0 are wildcards; returns (ok, which)."""
        index_match = prev_index == 0 or self.modified_index == prev_index
        value_match = prev_value == "" or self.value == prev_value
        if value_match and index_match:
            return True, 0
        if value_match and not index_match:
            return False, 1  # CompareIndexNotMatch
        if index_match and not value_match:
            return False, 2  # CompareValueNotMatch
        return False, 3  # CompareNotMatch

    def extern(self, recursive: bool, sorted_: bool, now: float) -> dict:
        """loadInternalNode (node_extern.go:38-70): the GET top-level
        repr — a dir ALWAYS lists its direct children (hidden skipped);
        `recursive` only controls whether those children recurse."""
        if not self.is_dir():
            return self.repr(False, False, now)
        out: dict[str, Any] = {
            "key": self.path, "dir": True,
            "modifiedIndex": self.modified_index,
            "createdIndex": self.created_index,
        }
        exp, ttl = self.expiration_and_ttl(now)
        if exp is not None:
            out["expiration"], out["ttl"] = exp, ttl
        nodes = [c.repr(recursive, sorted_, now)
                 for c in self.children.values() if not c.is_hidden()]
        if sorted_:
            nodes.sort(key=lambda n: n["key"])
        out["nodes"] = nodes
        return out

    # ---- repr (node.go:258-310)
    def repr(self, recursive: bool, sorted_: bool, now: float) -> dict:
        if self.is_dir():
            out: dict[str, Any] = {
                "key": self.path, "dir": True,
                "modifiedIndex": self.modified_index,
                "createdIndex": self.created_index,
            }
            exp, ttl = self.expiration_and_ttl(now)
            if exp is not None:
                out["expiration"], out["ttl"] = exp, ttl
            if not recursive:
                return out
            nodes = [c.repr(recursive, sorted_, now)
                     for c in self.children.values() if not c.is_hidden()]
            if sorted_:
                nodes.sort(key=lambda n: n["key"])
            out["nodes"] = nodes
            return out
        out = {
            "key": self.path, "value": self.value,
            "modifiedIndex": self.modified_index,
            "createdIndex": self.created_index,
        }
        exp, ttl = self.expiration_and_ttl(now)
        if exp is not None:
            out["expiration"], out["ttl"] = exp, ttl
        return out

    # ---- save/recover (store.go:739-789)
    def to_save(self) -> dict:
        out: dict[str, Any] = {
            "path": self.path, "createdIndex": self.created_index,
            "modifiedIndex": self.modified_index,
        }
        if self.expire_time is not None:
            out["expireTime"] = self.expire_time
        if self.is_dir():
            out["dir"] = True
            out["children"] = [c.to_save() for c in self.children.values()]
        else:
            out["value"] = self.value
        return out

    @classmethod
    def from_save(cls, store: "V2Store", d: dict,
                  parent: "Node | None") -> "Node":
        n = cls(store, d["path"], d["createdIndex"], parent,
                d.get("expireTime"), d.get("value"), d.get("dir", False))
        n.modified_index = d["modifiedIndex"]
        if n.is_dir():
            for c in d.get("children", []):
                child = cls.from_save(store, c, n)
                _, name = _split_path(child.path)
                n.children[name] = child
        return n


def _compare_fail_cause(n: Node, which: int, prev_value: str,
                        prev_index: int) -> str:
    # store.go:246-256 getCompareFailCause
    if which == 1:
        return f"[{prev_index} != {n.modified_index}]"
    if which == 2:
        return f"[{prev_value} != {n.value}]"
    return (f"[{prev_value} != {n.value}]"
            f" [{prev_index} != {n.modified_index}]")


# --------------------------------------------------------- event history

class EventHistory:
    """event_history.go: ring of the last `capacity` events so watchers
    can resume from a past index (EcodeEventIndexCleared past the ring)."""

    def __init__(self, capacity: int = 1000):
        self.capacity = capacity
        self.events: deque[Event] = deque(maxlen=capacity)
        self.start_index = 0
        self.last_index = 0

    def add(self, e: Event) -> Event:
        self.events.append(e)
        self.last_index = e.index()
        self.start_index = self.events[0].index()
        return e

    def scan(self, key: str, recursive: bool,
             index: int) -> Event | None:
        """event_history.go:57-107 — first event ≥ index touching key."""
        if index < self.start_index:
            raise V2Error(
                EcodeEventIndexCleared,
                f"the requested history has been cleared "
                f"[{self.start_index}/{index}]", 0)
        if index > self.last_index:  # future index
            return None
        for e in self.events:
            if e.index() < index or e.refresh:
                continue
            ok = e.node["key"] == key
            if recursive:
                nkey = key if key.endswith("/") else key + "/"
                ok = ok or e.node["key"].startswith(nkey)
            if e.action in (DELETE, EXPIRE) and e.prev_node is not None \
                    and e.prev_node.get("dir"):
                ok = ok or key.startswith(e.prev_node["key"])
            if ok:
                return e
        return None

    def clone(self) -> "EventHistory":
        eh = EventHistory(self.capacity)
        eh.events = deque(self.events, maxlen=self.capacity)
        eh.start_index = self.start_index
        eh.last_index = self.last_index
        return eh


# --------------------------------------------------------------- watcher

class Watcher:
    """watcher.go watcher — deque-buffered (capacity = channel size 100;
    overflow removes the watcher, the closed-channel rule)."""

    CAPACITY = 100

    def __init__(self, hub: "WatcherHub", key: str, recursive: bool,
                 stream: bool, since_index: int, start_index: int):
        self.hub = hub
        self.key = key
        self.recursive = recursive
        self.stream = stream
        self.since_index = since_index
        self.start_index = start_index  # EtcdIndex at creation
        self.events: deque[Event] = deque()
        self.removed = False
        self.cleared = False  # poisoned by recovery(); next poll errors

    def notify(self, e: Event, original_path: bool, deleted: bool) -> bool:
        # watcher.go:43-75 interest predicate
        if (self.recursive or original_path or deleted) \
                and e.index() >= self.since_index:
            if len(self.events) >= self.CAPACITY:
                # missed a notification: drop the watcher, and poison it
                # so a client still polling gets EcodeWatcherCleared once
                # the buffer drains instead of silent empty polls forever
                # (the reference closes the event channel here)
                self.cleared = True
                self.remove()
                return True
            self.events.append(e)
            return True
        return False

    def poll(self) -> Event | None:
        """Drain one event (the gateway's long-poll read)."""
        if self.events:
            return self.events.popleft()
        if self.cleared:
            # store.go WatcherHub.clone/recovery drops the hub; clients
            # get EcodeWatcherCleared so they know to re-watch
            raise V2Error(EcodeWatcherCleared,
                          "the watcher is cleared on store recovery")
        return None

    def remove(self) -> None:
        if not self.removed:
            self.removed = True
            self.hub._detach(self)


def _is_hidden(watch_path: str, key_path: str) -> bool:
    """watcher_hub.go isHidden: ANY component of keyPath below watchPath
    starting with '_' hides the event (watching /a recursively must not
    see /a/b/_h, not just /a/_h)."""
    if len(watch_path) > len(key_path):
        return False
    after = key_path[len(watch_path):].lstrip("/")
    return any(seg.startswith("_") for seg in after.split("/") if seg)


class WatcherHub:
    """watcher_hub.go — path → watcher list + shared event history."""

    def __init__(self, capacity: int = 1000):
        self.watchers: dict[str, list[Watcher]] = {}
        self.history = EventHistory(capacity)
        self.count = 0

    def watch(self, key: str, recursive: bool, stream: bool,
              index: int, store_index: int) -> Watcher:
        event = self.history.scan(key, recursive, index)  # may raise 401
        w = Watcher(self, key, recursive, stream, index, store_index)
        if event is not None:
            ne = event.clone()
            ne.etcd_index = store_index
            w.events.append(ne)
            return w
        self.watchers.setdefault(key, []).append(w)
        self.count += 1
        return w

    def _detach(self, w: Watcher) -> None:
        lst = self.watchers.get(w.key)
        if lst and w in lst:
            lst.remove(w)
            self.count -= 1
            if not lst:
                del self.watchers[w.key]

    def add(self, e: Event) -> None:
        """Refresh events enter history but notify nobody
        (watcher_hub.go:118-120 + store.go refresh branches)."""
        self.history.add(e)

    def notify(self, e: Event) -> None:
        # watcher_hub.go:122-141: notify every ancestor path
        e = self.history.add(e)
        segments = [s for s in e.node["key"].split("/") if s]
        curr = "/"
        self.notify_watchers(e, curr, False)
        for seg in segments:
            curr = curr.rstrip("/") + "/" + seg
            self.notify_watchers(e, curr, False)

    def notify_watchers(self, e: Event, node_path: str,
                        deleted: bool) -> None:
        lst = self.watchers.get(node_path)
        if not lst:
            return
        for w in list(lst):
            original_path = e.node["key"] == node_path
            if (original_path or not _is_hidden(node_path, e.node["key"])) \
                    and w.notify(e, original_path, deleted):
                if not w.stream:
                    w.removed = True
                    if w in lst:
                        lst.remove(w)
                        self.count -= 1
        if node_path in self.watchers and not self.watchers[node_path]:
            del self.watchers[node_path]

    def clone(self) -> "WatcherHub":
        wh = WatcherHub(self.history.capacity)
        wh.history = self.history.clone()
        return wh


# ----------------------------------------------------------------- stats

_STAT_NAMES = (
    "getsSuccess", "getsFail", "setsSuccess", "setsFail",
    "deleteSuccess", "deleteFail", "updateSuccess", "updateFail",
    "createSuccess", "createFail", "compareAndSwapSuccess",
    "compareAndSwapFail", "compareAndDeleteSuccess",
    "compareAndDeleteFail", "expireCount",
)


class Stats:
    """stats.go Stats — per-op success/fail counters."""

    def __init__(self):
        self.counters = {k: 0 for k in _STAT_NAMES}

    def inc(self, name: str) -> None:
        self.counters[name] += 1

    def to_json(self) -> dict:
        return dict(self.counters)


# ----------------------------------------------------------------- store

class V2Store:
    """store.go store — the v2 tree with a stop-the-world apply model
    (our applies are already serialized by the consensus log, so there is
    no lock: one committed entry at a time mutates the tree)."""

    def __init__(self, namespaces: tuple[str, ...] = (),
                 clock: Callable[[], float] | None = None):
        self.current_version = 2  # defaultVersion (store.go:33)
        self.current_index = 0
        self.clock = clock or _time.time
        self.root = Node(self, "/", self.current_index, None,
                         PERMANENT, is_dir=True)
        for ns in namespaces:
            self.root.add(Node(self, _clean_path(ns), self.current_index,
                               self.root, PERMANENT, is_dir=True))
        self.readonly_set = {"/"} | {_clean_path(ns) for ns in namespaces}
        self.hub = WatcherHub(1000)
        self.stats = Stats()
        # TTL min-heap with lazy invalidation: (expire, seq, node); an
        # entry is live iff the node still carries that expire time and
        # is still attached (ttl_key_heap.go, keyed update collapsed to
        # push-and-skip-stale)
        self._ttl_heap: list[tuple[float, int, Node]] = []
        self._ttl_seq = 0

    # ---- ttl heap helpers
    def _ttl_heap_push(self, n: Node) -> None:
        self._ttl_seq += 1
        heapq.heappush(self._ttl_heap, (n.expire_time, self._ttl_seq, n))

    def _ttl_heap_remove(self, n: Node) -> None:
        pass  # lazy: stale entries are skipped at pop time

    def _ttl_top(self) -> Node | None:
        while self._ttl_heap:
            exp, _, n = self._ttl_heap[0]
            if n.is_permanent() or n.expire_time != exp or self._detached(n):
                heapq.heappop(self._ttl_heap)
                continue
            return n
        return None

    def _detached(self, n: Node) -> bool:
        while n.parent is not None:
            _, name = _split_path(n.path)
            if n.parent.children is None or \
                    n.parent.children.get(name) is not n:
                return True
            n = n.parent
        return n.path != "/"

    # ---- public surface (Store interface, store.go:41-68)
    def version(self) -> int:
        return self.current_version

    def index(self) -> int:
        return self.current_index

    def get(self, node_path: str, recursive: bool = False,
            sorted_: bool = False) -> Event:
        try:
            n = self._internal_get(node_path)
        except V2Error:
            self.stats.inc("getsFail")
            raise
        now = self.clock()
        e = Event(GET, n.extern(recursive, sorted_, now),
                  etcd_index=self.current_index)
        self.stats.inc("getsSuccess")
        return e

    def create(self, node_path: str, dir: bool = False, value: str = "",
               unique: bool = False,
               expire_time: float | None = None) -> Event:
        try:
            e = self._internal_create(node_path, dir, value, unique,
                                      False, expire_time, CREATE)
        except V2Error:
            self.stats.inc("createFail")
            raise
        e.etcd_index = self.current_index
        self.hub.notify(e)
        self.stats.inc("createSuccess")
        return e

    def set(self, node_path: str, dir: bool = False, value: str = "",
            expire_time: float | None = None,
            refresh: bool = False) -> Event:
        try:
            n = None
            try:
                n = self._internal_get(node_path)
            except V2Error as ge:
                if ge.code != EcodeKeyNotFound:
                    raise
                if refresh:
                    raise  # refresh requires an existing node
            if refresh:
                value = n.value
            prev_repr = n.repr(False, False, self.clock()) if n else None
            e = self._internal_create(node_path, dir, value, False, True,
                                      expire_time, SET)
        except V2Error:
            self.stats.inc("setsFail")
            raise
        e.etcd_index = self.current_index
        if prev_repr is not None:
            e.prev_node = prev_repr
        if not refresh:
            self.hub.notify(e)
        else:
            e.refresh = True
            self.hub.add(e)
        self.stats.inc("setsSuccess")
        return e

    def update(self, node_path: str, new_value: str = "",
               expire_time: float | None = None,
               refresh: bool = False) -> Event:
        try:
            node_path = _clean_path(node_path)
            if node_path in self.readonly_set:
                raise V2Error(EcodeRootROnly, "/", self.current_index)
            n = self._internal_get(node_path)
            if n.is_dir():
                # the n.Write call inside Update rejects directories
                # (node.go:120-124), so dir updates always fail NotFile
                raise V2Error(EcodeNotFile, node_path, self.current_index)
            if refresh:
                new_value = n.value
            next_index = self.current_index + 1
            now = self.clock()
            prev = n.repr(False, False, now)
            n.write(new_value, next_index)
            n.update_ttl(expire_time)
            node_repr = {"key": node_path,
                         "modifiedIndex": next_index,
                         "createdIndex": n.created_index,
                         "value": new_value}
            exp, ttl = n.expiration_and_ttl(now)
            if exp is not None:
                node_repr["expiration"], node_repr["ttl"] = exp, ttl
            e = Event(UPDATE, node_repr, prev, next_index)
        except V2Error:
            self.stats.inc("updateFail")
            raise
        if not refresh:
            self.hub.notify(e)
        else:
            e.refresh = True
            self.hub.add(e)
        self.current_index = next_index
        self.stats.inc("updateSuccess")
        return e

    def compare_and_swap(self, node_path: str, prev_value: str,
                         prev_index: int, value: str,
                         expire_time: float | None = None,
                         refresh: bool = False) -> Event:
        try:
            node_path = _clean_path(node_path)
            if node_path in self.readonly_set:
                raise V2Error(EcodeRootROnly, "/", self.current_index)
            n = self._internal_get(node_path)
            if n.is_dir():
                raise V2Error(EcodeNotFile, node_path, self.current_index)
            ok, which = n.compare(prev_value, prev_index)
            if not ok:
                cause = _compare_fail_cause(n, which, prev_value,
                                            prev_index)
                raise V2Error(EcodeTestFailed, cause, self.current_index)
            if refresh:
                value = n.value
            self.current_index += 1
            now = self.clock()
            prev = n.repr(False, False, now)
            n.write(value, self.current_index)
            n.update_ttl(expire_time)
            node_repr = {"key": node_path, "value": value,
                         "modifiedIndex": self.current_index,
                         "createdIndex": n.created_index}
            exp, ttl = n.expiration_and_ttl(now)
            if exp is not None:
                node_repr["expiration"], node_repr["ttl"] = exp, ttl
            e = Event(COMPARE_AND_SWAP, node_repr, prev,
                      self.current_index)
        except V2Error:
            self.stats.inc("compareAndSwapFail")
            raise
        if not refresh:
            self.hub.notify(e)
        else:
            e.refresh = True
            self.hub.add(e)
        self.stats.inc("compareAndSwapSuccess")
        return e

    def delete(self, node_path: str, dir: bool = False,
               recursive: bool = False) -> Event:
        try:
            node_path = _clean_path(node_path)
            if node_path in self.readonly_set:
                raise V2Error(EcodeRootROnly, "/", self.current_index)
            if recursive:  # recursive implies dir
                dir = True
            n = self._internal_get(node_path)
            next_index = self.current_index + 1
            now = self.clock()
            prev = n.repr(False, False, now)
            node_repr = {"key": node_path, "modifiedIndex": next_index,
                         "createdIndex": n.created_index}
            if n.is_dir():
                node_repr["dir"] = True
            e = Event(DELETE, node_repr, prev, next_index)

            def callback(path: str) -> None:
                self.hub.notify_watchers(e, path, True)

            n.remove(dir, recursive, callback)
        except V2Error:
            self.stats.inc("deleteFail")
            raise
        self.current_index = next_index
        self.hub.notify(e)
        self.stats.inc("deleteSuccess")
        return e

    def compare_and_delete(self, node_path: str, prev_value: str,
                           prev_index: int) -> Event:
        try:
            node_path = _clean_path(node_path)
            n = self._internal_get(node_path)
            if n.is_dir():
                raise V2Error(EcodeNotFile, node_path, self.current_index)
            ok, which = n.compare(prev_value, prev_index)
            if not ok:
                cause = _compare_fail_cause(n, which, prev_value,
                                            prev_index)
                raise V2Error(EcodeTestFailed, cause, self.current_index)
            self.current_index += 1
            now = self.clock()
            prev = n.repr(False, False, now)
            e = Event(COMPARE_AND_DELETE,
                      {"key": node_path,
                       "modifiedIndex": self.current_index,
                       "createdIndex": n.created_index},
                      prev, self.current_index)

            def callback(path: str) -> None:
                self.hub.notify_watchers(e, path, True)

            n.remove(False, False, callback)
        except V2Error:
            self.stats.inc("compareAndDeleteFail")
            raise
        self.hub.notify(e)
        self.stats.inc("compareAndDeleteSuccess")
        return e

    def watch(self, key: str, recursive: bool = False,
              stream: bool = False, since_index: int = 0) -> Watcher:
        key = _clean_path(key)
        if since_index == 0:
            since_index = self.current_index + 1
        try:
            return self.hub.watch(key, recursive, stream, since_index,
                                  self.current_index)
        except V2Error as e:
            e.index = self.current_index
            raise

    def delete_expired_keys(self, cutoff: float) -> None:
        """store.go:679-711 — pop TTL heap up to cutoff, emit expire
        events. Driven by committed SYNC requests so all members expire
        identically (v2_server SYNC / apply_v2.go:113-116)."""
        while True:
            n = self._ttl_top()
            if n is None or n.expire_time > cutoff:
                break
            self.current_index += 1
            prev = n.repr(False, False, self.clock())
            node_repr = {"key": n.path,
                         "modifiedIndex": self.current_index,
                         "createdIndex": n.created_index}
            if n.is_dir():
                node_repr["dir"] = True
            e = Event(EXPIRE, node_repr, prev, self.current_index)

            def callback(path: str) -> None:
                self.hub.notify_watchers(e, path, True)

            heapq.heappop(self._ttl_heap)
            n.remove(True, True, callback)
            self.stats.inc("expireCount")
            self.hub.notify(e)

    def has_ttl_keys(self) -> bool:
        return self._ttl_top() is not None

    # ---- persistence (store.go:739-789)
    def save(self) -> str:
        return json.dumps({
            "version": self.current_version,
            "currentIndex": self.current_index,
            "root": self.root.to_save(),
            "readonly": sorted(self.readonly_set),
        })

    def recovery(self, state: str) -> None:
        d = json.loads(state)
        self.current_version = d["version"]
        self.current_index = d["currentIndex"]
        self.readonly_set = set(d.get("readonly", ["/"]))
        self.root = Node.from_save(self, d["root"], None)
        self._ttl_heap = []
        self._ttl_seq = 0
        # Poison live watchers before discarding the hub: their next
        # poll raises EcodeWatcherCleared (the reference's recovery
        # path returns 400 so clients know to re-watch) instead of
        # silently never firing again.
        for ws in self.hub.watchers.values():
            for w in list(ws):
                w.cleared = True
        self.hub = WatcherHub(self.hub.history.capacity)
        self._rebuild_ttl(self.root)

    def _rebuild_ttl(self, n: Node) -> None:
        if not n.is_permanent():
            self._ttl_heap_push(n)
        if n.is_dir():
            for c in n.children.values():
                self._rebuild_ttl(c)

    def clone(self) -> "V2Store":
        s = V2Store(clock=self.clock)
        s.recovery(self.save())
        s.stats.counters = dict(self.stats.counters)
        return s

    def json_stats(self) -> dict:
        out = self.stats.to_json()
        out["watchers"] = self.hub.count
        return out

    # ---- internals
    def _walk(self, node_path: str, walk_fn) -> Node:
        # store.go:471-489
        curr = self.root
        for comp in node_path.split("/"):
            if not comp:
                continue
            curr = walk_fn(curr, comp)
        return curr

    def _internal_get(self, node_path: str) -> Node:
        node_path = _clean_path(node_path)

        def walk_fn(parent: Node, name: str) -> Node:
            if not parent.is_dir():
                raise V2Error(EcodeNotDir, parent.path, self.current_index)
            child = parent.children.get(name)
            if child is None:
                raise V2Error(EcodeKeyNotFound,
                              _clean_path(parent.path + "/" + name),
                              self.current_index)
            return child

        return self._walk(node_path, walk_fn)

    def _check_dir(self, parent: Node, dir_name: str) -> Node:
        # store.go:717-733 — auto-create intermediate permanent dirs
        node = parent.children.get(dir_name)
        if node is not None:
            if node.is_dir():
                return node
            raise V2Error(EcodeNotDir, node.path, self.current_index)
        n = Node(self, _clean_path(parent.path + "/" + dir_name),
                 self.current_index + 1, parent, PERMANENT, is_dir=True)
        parent.children[dir_name] = n
        return n

    def _internal_create(self, node_path: str, dir: bool, value: str,
                         unique: bool, replace: bool,
                         expire_time: float | None,
                         action: str) -> Event:
        # store.go:566-648
        curr_index, next_index = self.current_index, self.current_index + 1
        if unique:  # POST in-order key: zero-padded next index
            node_path += "/" + format(next_index, "020d")
        node_path = _clean_path(node_path)
        if node_path in self.readonly_set:
            raise V2Error(EcodeRootROnly, "/", curr_index)

        dir_name, node_name = _split_path(node_path)
        d = self._walk(dir_name, self._check_dir)

        node_repr: dict[str, Any] = {"key": node_path,
                                     "modifiedIndex": next_index,
                                     "createdIndex": next_index}
        e = Event(action, node_repr)
        n = d.get_child(node_name)
        if n is not None:
            if replace:
                if n.is_dir():
                    raise V2Error(EcodeNotFile, node_path, curr_index)
                e.prev_node = n.repr(False, False, self.clock())
                n.remove(False, False, None)
            else:
                raise V2Error(EcodeNodeExist, node_path, curr_index)

        if not dir:
            node_repr["value"] = value
            n = Node(self, node_path, next_index, d, expire_time,
                     value=value)
        else:
            node_repr["dir"] = True
            n = Node(self, node_path, next_index, d, expire_time,
                     is_dir=True)
        d.add(n)
        if not n.is_permanent():
            self._ttl_heap_push(n)
            exp, ttl = n.expiration_and_ttl(self.clock())
            node_repr["expiration"], node_repr["ttl"] = exp, ttl
        self.current_index = next_index
        return e
