"""Streamed member-snapshot transfer — the merged-db snapshot channel.

Re-design of ``server/etcdserver/api/rafthttp/snapshot_sender.go`` +
``api/snap/message.go`` + ``api/snap/db.go``: the reference ships a
raft snapshot as a long-running side-channel POST whose body is the
snap message followed by the whole bbolt file, trailed by a size/CRC
check before the receiver renames it into place (db.go:52-79 writes
to a temp file and verifies). Here the member snapshot (MVCC + lease +
auth + v2 tree) streams as fixed-size chunks, each carrying its own
CRC32 and offset; the receiver verifies every chunk and the total
length before the snapshot becomes visible — a torn or corrupted
transfer never reaches ``restore_member``.

Chunks are plain dicts so any transport that moves JSON/pickle frames
(the gateway, a pipe, a file) can carry them.
"""
from __future__ import annotations

import pickle
import zlib
from typing import Iterator

DEFAULT_CHUNK = 64 * 1024  # snapshotSendBufSize-ish granularity


class SnapStreamError(Exception):
    """Chunk CRC/offset/length mismatch: the transfer is corrupt."""


def send_snapshot(snap: dict, chunk_size: int = DEFAULT_CHUNK
                  ) -> Iterator[dict]:
    """Serialize a member snapshot into self-verifying chunks.

    First frame is the header (total length + whole-payload CRC —
    the snap.Message size/CRC trailer moved up front); each following
    frame carries (seq, offset, data, crc)."""
    blob = pickle.dumps(snap, protocol=4)
    total_crc = zlib.crc32(blob)
    yield {"kind": "header", "total_len": len(blob),
           "total_crc": total_crc, "chunk_size": chunk_size}
    for seq, off in enumerate(range(0, len(blob), chunk_size)):
        data = blob[off:off + chunk_size]
        yield {"kind": "chunk", "seq": seq, "offset": off,
               "data": data, "crc": zlib.crc32(data)}


class SnapshotReceiver:
    """Reassemble and verify a chunk stream (snap/db.go SaveDBFrom:
    write to a staging buffer, verify, only then expose)."""

    def __init__(self):
        self._header: dict | None = None
        self._parts: list[bytes] = []
        self._next_seq = 0
        self._got = 0

    def feed(self, frame: dict) -> None:
        if frame["kind"] == "header":
            if self._header is not None:
                raise SnapStreamError("duplicate header")
            self._header = frame
            return
        if self._header is None:
            raise SnapStreamError("chunk before header")
        if frame["seq"] != self._next_seq:
            raise SnapStreamError(
                f"out-of-order chunk {frame['seq']} != {self._next_seq}")
        if frame["offset"] != self._got:
            raise SnapStreamError("offset mismatch")
        if zlib.crc32(frame["data"]) != frame["crc"]:
            raise SnapStreamError(f"chunk {frame['seq']} CRC mismatch")
        self._parts.append(frame["data"])
        self._got += len(frame["data"])
        self._next_seq += 1

    def close(self) -> dict:
        """Verify totals and yield the snapshot (the rename-into-place
        moment: nothing partial ever escapes)."""
        if self._header is None:
            raise SnapStreamError("no header received")
        if self._got != self._header["total_len"]:
            raise SnapStreamError(
                f"short transfer: {self._got}/{self._header['total_len']}")
        blob = b"".join(self._parts)
        if zlib.crc32(blob) != self._header["total_crc"]:
            raise SnapStreamError("total CRC mismatch")
        return pickle.loads(blob)


def transfer(snap: dict, chunk_size: int = DEFAULT_CHUNK,
             corrupt_frame: int | None = None) -> dict:
    """One in-process transfer: sender -> receiver, optionally flipping
    a byte of frame `corrupt_frame` (fault injection for tests and the
    chaos harness)."""
    rx = SnapshotReceiver()
    for i, frame in enumerate(send_snapshot(snap, chunk_size)):
        if corrupt_frame is not None and i == corrupt_frame \
                and frame["kind"] == "chunk" and frame["data"]:
            data = bytearray(frame["data"])
            data[0] ^= 0xFF
            frame = dict(frame, data=bytes(data))
        rx.feed(frame)
    return rx.close()
