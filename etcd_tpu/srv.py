"""DNS SRV discovery — client/pkg/srv parity.

The reference bootstraps clusters and client endpoint lists from DNS SRV
records (`client/pkg/srv/srv.go:35-91` GetCluster, :96-140 GetClient;
service names composed by GetSRVService). The resolver is pluggable
(srv.go:26-31 swaps lookupSRV in tests) — this build has no live DNS
(zero-egress environment), so the default resolver uses the stdlib-free
hook point and tests/embedders inject records.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SRVRecord:
    """net.SRV."""

    target: str
    port: int
    priority: int = 0
    weight: int = 0


class Resolver:
    """lookup_srv(service, proto, domain) -> [SRVRecord]; the lookupSRV
    seam (srv.go:26-31)."""

    def lookup_srv(self, service: str, proto: str, domain: str):
        raise NotImplementedError(
            "no live DNS in this environment; inject a resolver with "
            "SRV records (StaticResolver)"
        )


class StaticResolver(Resolver):
    """Test/embedder resolver: records keyed by (service, proto, domain)."""

    def __init__(self, records: dict[tuple[str, str, str], list[SRVRecord]]):
        self.records = records

    def lookup_srv(self, service, proto, domain):
        return self.records.get((service, proto, domain), [])


def get_srv_service(service: str, service_name: str, scheme: str) -> str:
    """GetSRVService (srv.go GetSRVService): https gets an -ssl suffix."""
    suffix = "-ssl" if scheme == "https" else ""
    if service_name:
        return f"{service}-{service_name}{suffix}"
    return f"{service}{suffix}"


def get_cluster(resolver: Resolver, scheme: str, service: str, name: str,
                domain: str, apurls: list[str]) -> list[str]:
    """GetCluster (srv.go:35-91): resolve the service's SRV records into
    `name=scheme://host:port` initial-cluster parts; the record matching
    one of our advertised peer urls gets OUR name, others get ordinals."""
    temp = 0
    own = set()
    for u in apurls:
        hostport = u.split("://", 1)[-1]
        own.add(hostport)
    parts = []
    addrs = resolver.lookup_srv(service, "tcp", domain)
    if not addrs:
        raise LookupError(
            f"error querying DNS SRV records for _{service}._tcp.{domain}"
        )
    for srv in addrs:
        short = srv.target.rstrip(".")
        hostport = f"{short}:{srv.port}"
        n = name if hostport in own else str(temp)
        if hostport not in own:
            temp += 1
        parts.append(f"{n}={scheme}://{hostport}")
    return parts


def get_client(resolver: Resolver, service: str, domain: str,
               service_name: str = "") -> dict:
    """GetClient (srv.go:96-140): try the https (-ssl) service then the
    http one; returns {"endpoints": [...], "srvs": [...]}."""
    endpoints, srvs = [], []
    for scheme in ("https", "http"):
        svc = get_srv_service(service, service_name, scheme)
        for srv in resolver.lookup_srv(svc, "tcp", domain):
            short = srv.target.rstrip(".")
            endpoints.append(f"{scheme}://{short}:{srv.port}")
            srvs.append(srv)
    if not endpoints:
        raise LookupError(
            f"error querying DNS SRV records for _{service}._tcp.{domain}"
        )
    return {"endpoints": endpoints, "srvs": srvs}
