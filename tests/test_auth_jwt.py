"""JWT token provider — parity with the reference's tokenJWT
(server/auth/jwt.go:28 assign/info, jwt options parsing at
jwt.go:152-176): stateless HS256 tokens carrying {username, revision,
exp}; verification rejects bad signatures, foreign algorithms and
expired tokens; stale-ACL revocation happens via the auth-revision
check, not token state (tokenJWT.invalidateUser is a no-op, jwt.go:38).
"""
import pytest

from etcd_tpu.server.auth import (
    AuthError,
    AuthStore,
    ErrAuthOldRevision,
    ErrInvalidAuthToken,
    ErrPermissionDenied,
    JWTTokenProvider,
    Permission,
    READ,
)

KEY = b"0123456789abcdef0123456789abcdef"


def test_jwt_assign_info_roundtrip():
    p = JWTTokenProvider(KEY, ttl=300)
    tok = p.assign("alice", 7, now=100)
    assert tok.count(".") == 2
    assert p.info(tok, now=100) == ("alice", 7)
    assert p.info(tok, now=399) == ("alice", 7)


def test_jwt_expiry():
    p = JWTTokenProvider(KEY, ttl=10)
    tok = p.assign("bob", 1, now=0)
    assert p.info(tok, now=9) == ("bob", 1)
    with pytest.raises(ErrInvalidAuthToken):
        p.info(tok, now=10)  # exp is exclusive, like jwt exp semantics


def test_jwt_tamper_rejected():
    p = JWTTokenProvider(KEY)
    tok = p.assign("alice", 3, now=0)
    h, c, s = tok.split(".")
    # claims swapped for another user's but signature kept
    other = p.assign("mallory", 3, now=0)
    _, c2, _ = other.split(".")
    with pytest.raises(ErrInvalidAuthToken):
        p.info(f"{h}.{c2}.{s}", now=0)
    # truncated / garbage forms
    for bad in ("", "a.b", f"{h}.{c}.", tok + "x"):
        with pytest.raises(ErrInvalidAuthToken):
            p.info(bad, now=0)


def test_jwt_wrong_key_rejected():
    tok = JWTTokenProvider(KEY).assign("alice", 1, now=0)
    with pytest.raises(ErrInvalidAuthToken):
        JWTTokenProvider(b"another-key-entirely").info(tok, now=0)


def test_jwt_alg_confusion_rejected():
    """A token claiming alg=none (or anything but the provider's method)
    is rejected before signature use (jwt.go:49-51 checks Method.Alg())."""
    import base64
    import json

    p = JWTTokenProvider(KEY)
    tok = p.assign("alice", 1, now=0)
    _, c, s = tok.split(".")
    h_none = base64.urlsafe_b64encode(
        json.dumps({"alg": "none", "typ": "JWT"}).encode()
    ).rstrip(b"=").decode()
    with pytest.raises(ErrInvalidAuthToken):
        p.info(f"{h_none}.{c}.", now=0)
    with pytest.raises(ErrInvalidAuthToken):
        p.info(f"{h_none}.{c}.{s}", now=0)


def test_jwt_provider_requires_key_and_known_method():
    with pytest.raises(AuthError):
        JWTTokenProvider(b"")
    with pytest.raises(AuthError):
        JWTTokenProvider(KEY, sign_method="none")
    with pytest.raises(AuthError):
        JWTTokenProvider(KEY, sign_method="XX256")
    with pytest.raises(AuthError):
        # an HMAC secret is not a PEM keypair
        JWTTokenProvider(KEY, sign_method="RS256")


# ---------------------------------------------- asymmetric sign methods
# (auth/jwt.go:152-156 + options.go:88-103: RSA / RSA-PSS / ECDSA)

def _rsa_pem() -> bytes:
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    k = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    return k.private_bytes(serialization.Encoding.PEM,
                           serialization.PrivateFormat.PKCS8,
                           serialization.NoEncryption())


def _ec_pem(curve=None) -> bytes:
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ec

    k = ec.generate_private_key(curve or ec.SECP256R1())
    return k.private_bytes(serialization.Encoding.PEM,
                           serialization.PrivateFormat.PKCS8,
                           serialization.NoEncryption())


def _pub_of(pem: bytes) -> bytes:
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization

    k = serialization.load_pem_private_key(pem, password=None)
    return k.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo)


def _ec384_pem() -> bytes:
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.asymmetric import ec

    return _ec_pem(ec.SECP384R1())


def _ec521_pem() -> bytes:
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.asymmetric import ec

    return _ec_pem(ec.SECP521R1())


@pytest.mark.parametrize("method,keyfn", [
    ("RS256", _rsa_pem), ("RS384", _rsa_pem), ("RS512", _rsa_pem),
    ("PS256", _rsa_pem), ("PS384", _rsa_pem), ("PS512", _rsa_pem),
    ("ES256", _ec_pem), ("ES384", _ec384_pem), ("ES512", _ec521_pem),
    ("HS384", lambda: KEY), ("HS512", lambda: KEY),
])
def test_jwt_asymmetric_roundtrip(method, keyfn):
    p = JWTTokenProvider(keyfn(), sign_method=method, ttl=100)
    tok = p.assign("alice", 7, now=0)
    assert p.info(tok, now=50) == ("alice", 7)
    with pytest.raises(ErrInvalidAuthToken):
        p.info(tok, now=100)  # expired
    with pytest.raises(ErrInvalidAuthToken):
        p.info(tok[:-6] + "AAAAAA", now=0)  # corrupted signature


def test_jwt_asymmetric_wrong_key_rejected():
    a = JWTTokenProvider(_rsa_pem(), sign_method="RS256")
    b = JWTTokenProvider(_rsa_pem(), sign_method="RS256")
    with pytest.raises(ErrInvalidAuthToken):
        b.info(a.assign("alice", 1, now=0), now=0)


def test_jwt_public_key_is_verify_only():
    """jwt.go:150-160: a public key can verify tokens minted by the
    private-key holder but cannot assign (verifyOnly)."""
    priv_pem = _rsa_pem()
    signer = JWTTokenProvider(priv_pem, sign_method="RS256")
    verifier = JWTTokenProvider(_pub_of(priv_pem), sign_method="RS256")
    assert verifier.verify_only
    tok = signer.assign("alice", 3, now=0)
    assert verifier.info(tok, now=0) == ("alice", 3)
    with pytest.raises(ErrInvalidAuthToken):
        verifier.assign("alice", 3, now=0)


def test_jwt_es_curve_mismatch_rejected():
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.asymmetric import ec

    with pytest.raises(AuthError, match="curve"):
        JWTTokenProvider(_ec_pem(ec.SECP384R1()), sign_method="ES256")
    with pytest.raises(AuthError, match="ECDSA"):
        JWTTokenProvider(_rsa_pem(), sign_method="ES256")
    with pytest.raises(AuthError, match="RSA"):
        JWTTokenProvider(_ec_pem(), sign_method="RS256")


def test_jwt_cross_alg_confusion_rejected():
    """An RS256 token presented to an HS256 provider (and vice versa)
    dies at the alg check, never reaching key material."""
    rsa_p = JWTTokenProvider(_rsa_pem(), sign_method="RS256")
    hs_p = JWTTokenProvider(KEY)
    with pytest.raises(ErrInvalidAuthToken):
        hs_p.info(rsa_p.assign("alice", 1, now=0), now=0)
    with pytest.raises(ErrInvalidAuthToken):
        rsa_p.info(hs_p.assign("alice", 1, now=0), now=0)


def test_authstore_rs256_end_to_end():
    a = AuthStore(token="jwt,sign-method=RS256,ttl=50",
                  jwt_key=_rsa_pem())
    a.user_add("root", "rpw")
    a.role_add("root")
    a.user_grant_role("root", "root")
    a.auth_enable()
    tok = a.authenticate("root", "rpw")
    assert tok.count(".") == 2
    a.check(tok, b"anything", write=True)  # root passes authz


def test_authstore_token_spec_parsing():
    a = AuthStore(token="jwt,sign-method=HS256,ttl=60", jwt_key=KEY)
    assert a.jwt is not None and a.jwt.ttl == 60
    assert AuthStore().jwt is None  # simple default
    with pytest.raises(AuthError):
        AuthStore(token="oauth2")


def _enabled_jwt_store() -> AuthStore:
    a = AuthStore(token="jwt,ttl=50", jwt_key=KEY)
    a.user_add("root", "rpw")
    a.role_add("root")
    a.user_grant_role("root", "root")
    a.user_add("alice", "apw")
    a.role_add("reader")
    a.role_grant_permission("reader", Permission(READ, b"a/", b"a0"))
    a.user_grant_role("alice", "reader")
    a.auth_enable()
    return a


def test_authstore_jwt_mint_verify_and_perms():
    a = _enabled_jwt_store()
    tok = a.authenticate("alice", "apw")
    assert tok.count(".") == 2  # a real JWT, not a simple token
    assert a.tokens == {}  # stateless: nothing server-side
    a.check(tok, b"a/x")  # read within grant
    with pytest.raises(ErrPermissionDenied):
        a.check(tok, b"a/x", write=True)


def test_authstore_jwt_stale_revision_rejected():
    """Reference semantics: the jwt carries the mint-time auth revision;
    any ACL change bumps the store revision and outstanding tokens fail
    the rev check (store.go ErrAuthOldRevision)."""
    a = _enabled_jwt_store()
    tok = a.authenticate("alice", "apw")
    a.role_add("other")  # ACL mutation
    with pytest.raises(ErrAuthOldRevision):
        a.check(tok, b"a/x")
    # re-authentication under the new revision works again
    assert a.check(a.authenticate("alice", "apw"), b"a/x") is None


def test_authstore_jwt_expiry_via_tick():
    a = _enabled_jwt_store()
    tok = a.authenticate("alice", "apw")
    a.tick(49)
    a.check(tok, b"a/x")
    a.tick(1)
    with pytest.raises(ErrInvalidAuthToken):
        a.check(tok, b"a/x")


def test_embed_config_validates_jwt_key():
    from etcd_tpu.embed import Config

    with pytest.raises(ValueError):
        Config(auth_token="jwt").validate()
    Config(auth_token="jwt", auth_jwt_key=KEY).validate()
    Config(auth_token="simple").validate()


def test_etcdcluster_jwt_end_to_end():
    """test_auth_end_to_end with the jwt provider: tokens mint at any
    member, verify statelessly, and honor RBAC + revision semantics."""
    from etcd_tpu.server.kvserver import EtcdCluster

    ec = EtcdCluster(auth_token="jwt,ttl=300", auth_jwt_key=KEY)
    ec.ensure_leader()
    ec.auth_request("auth_user_add", name="root", password="pw")
    ec.auth_request("auth_role_add", name="root")
    ec.auth_request("auth_user_grant_role", name="root", role="root")
    ec.auth_request("auth_user_add", name="alice", password="apw")
    ec.auth_request("auth_role_add", name="reader")
    ec.auth_request(
        "auth_role_grant_permission", role="reader",
        perm=Permission(READ, b"a/", b"a0"),
    )
    ec.auth_request("auth_user_grant_role", name="alice", role="reader")
    ec.auth_request("auth_enable")
    root_tok = ec.authenticate("root", "pw")
    alice_tok = ec.authenticate("alice", "apw")
    assert root_tok.count(".") == 2 and alice_tok.count(".") == 2
    ec.put(b"a/2", b"v", token=root_tok)
    assert ec.range(b"a/2", token=alice_tok)["count"] == 1
    with pytest.raises(ErrPermissionDenied):
        ec.put(b"a/3", b"v", token=alice_tok)
    ec.auth_request("auth_role_add", name="other")
    with pytest.raises(ErrAuthOldRevision):
        ec.range(b"a/2", token=alice_tok)
