"""Fleet observability: on-device metrics + status snapshots.

The reference instruments everything with Prometheus counters
(server/etcdserver/metrics.go — proposals committed/applied/pending,
leader changes, heartbeat failures) and exports per-node Status snapshots
(raft/status.go:26-76). A batched fleet cannot afford a host read per
group per round, so the TPU-native design keeps a small
:class:`FleetMetrics` pytree ON DEVICE, updated by pure tensor reductions
fused into the round program; the host reads a handful of scalars
whenever it wants a report (one tiny transfer, no sync in the hot loop).

Status comes in two granularities:
  * :func:`fleet_summary` — whole-fleet aggregates (roles histogram,
    term/commit spread, commit-apply lag) from one device reduction.
  * :func:`basic_status` — one group's per-node Status dict, the analog
    of raft.Status for lane (m, c).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from etcd_tpu.models.engine import build_round
from etcd_tpu.models.state import NodeState, unpack_fleet
from etcd_tpu.types import (
    NONE_ID,
    PR_PROBE,
    PR_REPLICATE,
    PR_SNAPSHOT,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

# commit-apply lag histogram bucket upper bounds (entries); last is +inf
LAG_BUCKETS = (0, 1, 2, 4, 8, 16, 32)


class FleetMetrics(struct.PyTreeNode):
    """Device-resident counters.

    Counters are i32 under the default JAX config (i64 only with
    jax_enable_x64): reset per measurement window (``zero_metrics()``)
    rather than accumulating for a whole soak — at 1M groups the message
    counter crosses 2^31 after ~100 rounds. ``metrics_report`` raises if
    a counter has wrapped.
    """

    rounds: jnp.ndarray          # lockstep rounds executed
    elections_won: jnp.ndarray   # nodes that newly became leader
    leader_losses: jnp.ndarray   # nodes that stopped being leader
    commits: jnp.ndarray         # sum of per-node commit advances
    applies: jnp.ndarray         # sum of per-node applied advances
    msgs_delivered: jnp.ndarray  # slots surviving the fault mask
    msgs_dropped: jnp.ndarray    # emitted slots killed by the keep-mask
    lag_hist: jnp.ndarray        # [len(LAG_BUCKETS)+1] cumulative lag counts


def zero_metrics() -> FleetMetrics:
    z = jnp.int64(0) if jax.config.jax_enable_x64 else jnp.int32(0)
    return FleetMetrics(
        rounds=z, elections_won=z, leader_losses=z, commits=z, applies=z,
        msgs_delivered=z, msgs_dropped=z,
        lag_hist=jnp.zeros((len(LAG_BUCKETS) + 1,), z.dtype),
    )


class CrashMetrics(struct.PyTreeNode):
    """Device-resident crash/restart + membership-chaos event counters for
    the chaos tier (harness/chaos.py). Kept separate from
    :class:`FleetMetrics` because they ride the chaos epoch's scan carry,
    not the metered round: the chaos program accumulates them as the same
    kind of fused i32 reductions as its Violations counters and the host
    reads them once per report.

    The ``cc_guard_*`` counters record the leader-side proposal-guard
    outcome (stepLeader refuses a conf change while one is pending or the
    config is joint) evaluated against the group's CURRENT leader at
    injection time; when node 0 is not the leader the proposal forwards
    and the real guard runs a round later, so these are exact for
    leader-direct proposals and one-round-skewed estimates otherwise.
    ``conf_changes_applied`` counts (node, round) lanes whose applied
    config masks changed inside the round step — conf-change applies plus
    snapshot-install config adoptions, never crash rewinds (the wipe
    happens before the round and is excluded by construction).

    The ``*_window_*`` counters feed the targeted-crash-scheduler
    acceptance math: ``snap_window_crashes / crashes_injected`` is the
    snapshot-window hit rate, compared at equal crash budget against a
    Bernoulli run (both counted at crash-sampling instants only, so heal
    rounds don't dilute the rates)."""

    crashes_injected: jnp.ndarray     # nodes killed by the crash mask
    entries_lost_fsync: jnp.ndarray   # log entries dropped past `stable`
    restarts_completed: jnp.ndarray   # down-timers that reached 0
    # membership-change chaos (ISSUE 5)
    member_changes_proposed: jnp.ndarray  # conf-change proposals injected
    cc_guard_refusals: jnp.ndarray    # guard outcome at injection: refuse
    cc_guard_admits: jnp.ndarray      # guard outcome at injection: admit
    conf_changes_applied: jnp.ndarray # lanes whose applied config changed
    joint_entered: jnp.ndarray        # lanes entering a joint config
    joint_left: jnp.ndarray           # lanes leaving a joint config
    # targeted crash scheduling (snapshot-install / membership windows)
    snap_window_lanes: jnp.ndarray    # lanes in-window at sampling time
    snap_window_crashes: jnp.ndarray  # crashes that landed in-window
    member_window_lanes: jnp.ndarray
    member_window_crashes: jnp.ndarray


def zero_crash_metrics() -> CrashMetrics:
    z = jnp.int32(0)
    return CrashMetrics(
        crashes_injected=z, entries_lost_fsync=z, restarts_completed=z,
        member_changes_proposed=z, cc_guard_refusals=z, cc_guard_admits=z,
        conf_changes_applied=z, joint_entered=z, joint_left=z,
        snap_window_lanes=z, snap_window_crashes=z,
        member_window_lanes=z, member_window_crashes=z,
    )


# lint: allow-def(host-sync) -- host-side report path; one narrow device_get per report window
def crash_metrics_report(m: CrashMetrics) -> dict:
    """One host transfer -> plain-dict counters for the chaos report JSON,
    plus the derived window-hit rates the targeting acceptance compares."""
    m = jax.device_get(m)
    out = {k: int(getattr(m, k)) for k in CrashMetrics.__dataclass_fields__}
    if any(v < 0 for v in out.values()):
        raise OverflowError(
            "CrashMetrics counter wrapped (i32); shorten the run or shard "
            "the report window"
        )
    crashes = max(out["crashes_injected"], 1)
    out["snap_window_hit_rate"] = round(out["snap_window_crashes"] / crashes, 6)
    out["member_window_hit_rate"] = round(
        out["member_window_crashes"] / crashes, 6)
    return out


def build_metered_round(cfg: RaftConfig, spec: Spec,
                        with_telemetry: bool = False,
                        with_blackbox: bool = False):
    """Round program with fused metric (and optional telemetry /
    black-box ring) updates — the ONE instrumented-round builder every
    observability consumer shares (ISSUE 9 unification).

    Returns fn(state, inbox, prop_len, prop_data, prop_type, ri_ctx,
    do_hup, do_tick, keep_mask, metrics) -> (state, inbox, metrics);
    with_telemetry adds a trailing FleetTelemetry argument and result
    (models/telemetry.py — per-group lanes + latency histograms), fused
    into the same program by the same read-only reductions.
    with_blackbox adds a trailing EventRing argument and result after it
    (models/blackbox.py — per-round bit-packed event words over the
    same pre/post views plus the consumed/emitted wire).

    The metric math is a handful of elementwise reductions over state
    the round already touches — XLA fuses them into the same program, so
    the marginal cost is one small add per counter. The whole PR-8 diet
    composes: under compact_wire `delivered` counts post-compaction
    slots (messages that can still be consumed), and under packed_state
    the counters read a read-only UNPACKED VIEW at the round boundary
    while the carried state stays packed — note the view materializes
    the dense fleet as a temporary, so metering a fleet_chunks program
    at huge C pays a full-fleet temp (observability passes run at
    bounded C or bounded rounds; the timed hot loop stays unmetered).
    Telemetry only reads, never feeds back: state/inbox out of the
    metered program are bit-identical to the bare round's
    (tests/test_telemetry.py).
    """
    round_fn = build_round(cfg, spec, with_drop_count=True)
    unp = ((lambda s: unpack_fleet(spec, s)) if cfg.packed_state
           else (lambda s: s))

    def metered(state: NodeState, inbox, prop_len, prop_data, prop_type,
                ri_ctx, do_hup, do_tick, keep_mask, metrics: FleetMetrics,
                telemetry=None, blackbox=None):
        pre = unp(state)
        was_leader = pre.role == ROLE_LEADER
        commit0, applied0 = pre.commit, pre.applied
        state, next_inbox, dropped = round_fn(
            state, inbox, prop_len, prop_data, prop_type, ri_ctx, do_hup,
            do_tick, keep_mask,
        )
        post = unp(state)
        is_leader = post.role == ROLE_LEADER
        dt = metrics.rounds.dtype
        delivered = (next_inbox.type != 0).sum().astype(dt)
        lag = (post.commit - post.applied).astype(jnp.int32)
        edges = jnp.asarray(LAG_BUCKETS, jnp.int32)
        # Prometheus-style cumulative buckets: hist[b] counts lag <=
        # edges[b]; the final slot counts every sample (+inf bucket)
        cum = (lag[..., None] <= edges).sum(axis=tuple(range(lag.ndim)))
        total = jnp.asarray(lag.size, cum.dtype)
        hist = jnp.concatenate([cum, total[None]]).astype(dt)
        metrics = FleetMetrics(
            rounds=metrics.rounds + 1,
            elections_won=metrics.elections_won
            + (is_leader & ~was_leader).sum().astype(dt),
            leader_losses=metrics.leader_losses
            + (was_leader & ~is_leader).sum().astype(dt),
            commits=metrics.commits
            + (post.commit - commit0).sum().astype(dt),
            applies=metrics.applies
            + (post.applied - applied0).sum().astype(dt),
            msgs_delivered=metrics.msgs_delivered + delivered,
            msgs_dropped=metrics.msgs_dropped + dropped.astype(dt),
            lag_hist=metrics.lag_hist + hist,
        )
        if with_telemetry:
            from etcd_tpu.models.telemetry import telemetry_update

            telemetry = telemetry_update(spec, telemetry, pre, post)
        if with_blackbox:
            from etcd_tpu.models.blackbox import blackbox_update

            # the consumed wire is this round's receive side, the fresh
            # wire its send side — both read-only views the round
            # already produced
            blackbox = blackbox_update(spec, blackbox, pre, post,
                                       inbox=inbox, outbox=next_inbox)
        if with_telemetry and with_blackbox:
            return state, next_inbox, metrics, telemetry, blackbox
        if with_telemetry:
            return state, next_inbox, metrics, telemetry
        if with_blackbox:
            return state, next_inbox, metrics, blackbox
        return state, next_inbox, metrics

    return metered


# lint: allow-def(host-sync) -- host-side report path; one narrow device_get per report window
def metrics_report(metrics: FleetMetrics, elapsed_s: float | None = None,
                   n_groups: int | None = None,
                   n_members: int | None = None) -> dict:
    """One host transfer -> a plain dict (the /metrics endpoint analog)."""
    m = jax.device_get(metrics)
    if int(m.msgs_delivered) < 0 or int(m.commits) < 0 or int(m.applies) < 0:
        raise OverflowError(
            "FleetMetrics counter wrapped (i32); reset metrics per window "
            "with zero_metrics()"
        )
    out = {
        "rounds": int(m.rounds),
        "elections_won": int(m.elections_won),
        "leader_losses": int(m.leader_losses),
        "commits_total": int(m.commits),
        "applies_total": int(m.applies),
        "msgs_delivered": int(m.msgs_delivered),
        "msgs_dropped": int(m.msgs_dropped),
        "commit_apply_lag_hist": {
            **{f"le_{b}": int(v) for b, v in zip(LAG_BUCKETS, m.lag_hist)},
            "inf": int(m.lag_hist[-1]),
        },
    }
    if elapsed_s and elapsed_s > 0:
        out["commits_per_sec"] = round(int(m.commits) / elapsed_s, 1)
        out["rounds_per_sec"] = round(int(m.rounds) / elapsed_s, 1)
    if n_groups:
        # `commits` sums per-REPLICA commit-cursor advances; normalizing
        # by the replica count gives committed entries per group per round
        nodes = n_groups * (n_members or 1)
        key = (
            "commits_per_group_per_round" if n_members
            else "commit_advances_per_node_per_round"
        )
        out[key] = round(int(m.commits) / max(int(m.rounds), 1) / nodes, 4)
    return out


# ---------------------------------------------------------------------------
# status snapshots (raft/status.go:26-76)
# ---------------------------------------------------------------------------

_ROLE_NAMES = {0: "StateFollower", 1: "StatePreCandidate",
               2: "StateCandidate", 3: "StateLeader"}
_PR_NAMES = {PR_PROBE: "StateProbe", PR_REPLICATE: "StateReplicate",
             PR_SNAPSHOT: "StateSnapshot"}


# lint: allow-def(host-sync) -- host-side summary; reductions run on device, scalars cross
def fleet_summary(state: NodeState) -> dict:
    """Whole-fleet aggregate status: one jitted reduction, one transfer."""

    @jax.jit
    def agg(s: NodeState):
        roles = jnp.stack([(s.role == r).sum() for r in range(4)])
        lag = s.commit - s.applied
        per_group_leaders = (s.role == ROLE_LEADER).sum(axis=0)
        edges = jnp.asarray(LAG_BUCKETS, jnp.int32)
        lag_cum = (lag[..., None] <= edges).sum(axis=(0, 1))
        return dict(
            roles=roles,
            term_max=s.term.max(),
            commit_min=s.commit.min(), commit_max=s.commit.max(),
            applied_max=s.applied.max(),
            lag_max=lag.max(), lag_sum=lag.sum(), lag_cum=lag_cum,
            groups_with_leader=(per_group_leaders > 0).sum(),
            groups_multi_leader=(per_group_leaders > 1).sum(),
        )

    r = jax.device_get(agg(state))
    M, C = state.role.shape[0], state.role.shape[-1]
    return {
        "nodes": int(M * C),
        "groups": int(C),
        "roles": {
            name: int(r["roles"][i]) for i, name in _ROLE_NAMES.items()
        },
        "term_max": int(r["term_max"]),
        "commit_min": int(r["commit_min"]),
        "commit_max": int(r["commit_max"]),
        "applied_max": int(r["applied_max"]),
        "commit_apply_lag_max": int(r["lag_max"]),
        "commit_apply_lag_mean": float(r["lag_sum"]) / (M * C),
        "lag_sum": int(r["lag_sum"]),
        # instantaneous lag distribution across all fleet nodes at the
        # scrape instant — the /metrics histogram family's source
        "commit_apply_lag_hist": {
            **{f"le_{b}": int(v)
               for b, v in zip(LAG_BUCKETS, r["lag_cum"])},
            "inf": M * C,
        },
        "groups_with_leader": int(r["groups_with_leader"]),
        "groups_multi_leader": int(r["groups_multi_leader"]),
    }


# lint: allow-def(host-sync) -- host-side status probe for the serving facade
def basic_status(state: NodeState, spec: Spec, m: int, c: int = 0) -> dict:
    """raft.Status for one lane (m, c) of the fleet: BasicStatus fields
    plus the leader's progress map (status.go:26-76)."""
    g = lambda leaf: np.asarray(leaf[m, ..., c])
    role = int(g(state.role))
    out = {
        "id": m,
        "term": int(g(state.term)),
        "vote": int(g(state.vote)),
        "commit": int(g(state.commit)),
        "applied": int(g(state.applied)),
        "lead": int(g(state.lead)),
        "raft_state": _ROLE_NAMES[role],
    }
    if role == ROLE_LEADER:
        tracked = g(state.voters) | g(state.voters_out) | g(state.learners) \
            | g(state.learners_next)
        match, nxt = g(state.match), g(state.next_idx)
        prs, ract = g(state.pr_state), g(state.recent_active)
        psnap, icnt = g(state.pending_snapshot), g(state.infl_count)
        lrn = g(state.learners) | g(state.learners_next)
        out["progress"] = {
            int(i): {
                "match": int(match[i]),
                "next": int(nxt[i]),
                "state": _PR_NAMES[int(prs[i])],
                "is_learner": bool(lrn[i]),
                "recent_active": bool(ract[i]),
                "pending_snapshot": int(psnap[i]),
                "inflight": int(icnt[i]),
            }
            for i in range(spec.M) if tracked[i]
        }
    return out
