"""Quorum kernels: commit-index and vote tallies over majority & joint configs.

TPU-native re-expression of the reference's ``raft/quorum`` package:
  * ``MajorityConfig.CommittedIndex`` (quorum/majority.go:126-172): sort the
    match indexes of the voters, take ``srt[n-(n/2+1)]``. Here the config is a
    bool[M] mask and the sort is a fixed-size ``jnp.sort`` — unacked voters
    report 0, non-voters sort to +inf so the quantile lands on voters only.
  * ``MajorityConfig.VoteResult`` (quorum/majority.go:178-210): won iff a
    quorum of yes, lost iff yes can no longer reach quorum, else pending.
  * ``JointConfig`` variants (quorum/joint.go:49-75): min / combine of the
    two majority halves, an empty half behaving like the other half.

All functions are written for a single group (1-D [M] inputs) and batched by
``jax.vmap``; they are the #1 hot kernel per SURVEY.md §3 hot-loop ranking.
"""
from __future__ import annotations

import jax.numpy as jnp

from etcd_tpu.types import INT32_MAX, VOTE_LOST, VOTE_PENDING, VOTE_WON


def committed_index(voters: jnp.ndarray, acked: jnp.ndarray) -> jnp.ndarray:
    """Largest index acked by a quorum of `voters`.

    voters: bool[M] membership mask; acked: i32[M] per-member acked index
    (0 for voters that have not reported). Empty config -> INT32_MAX, which
    makes joint quorums behave like the populated half (majority.go:128-132).
    """
    n = voters.sum().astype(jnp.int32)
    vals = jnp.where(voters, acked, INT32_MAX)
    pos = jnp.maximum(n - (n // 2 + 1), 0)
    # k-th smallest by rank counting instead of jnp.sort + dynamic index:
    # HLO sort and gather both fall off the vector path on TPU (measured
    # ~100x the cost of this [M, M] comparison triangle at M<=7, the same
    # size the reference bounds its stack-allocated insertion sort to,
    # majority.go:126-172). Ties break by member id, making `rank` a
    # permutation, so exactly one element holds rank == pos.
    M = vals.shape[0]
    ids = jnp.arange(M, dtype=jnp.int32)
    lt = (vals[None, :] < vals[:, None]) | (
        (vals[None, :] == vals[:, None]) & (ids[None, :] < ids[:, None])
    )
    rank = lt.sum(axis=-1).astype(jnp.int32)
    kth = jnp.where(rank == pos, vals, 0).sum().astype(jnp.int32)
    return jnp.where(n == 0, INT32_MAX, kth).astype(jnp.int32)


def joint_committed_index(
    voters_incoming: jnp.ndarray,
    voters_outgoing: jnp.ndarray,
    acked: jnp.ndarray,
) -> jnp.ndarray:
    """min of both halves' committed indexes (quorum/joint.go:70-75)."""
    return jnp.minimum(
        committed_index(voters_incoming, acked),
        committed_index(voters_outgoing, acked),
    )


def vote_result(
    voters: jnp.ndarray, responded: jnp.ndarray, granted: jnp.ndarray
) -> jnp.ndarray:
    """VOTE_WON / VOTE_LOST / VOTE_PENDING for one majority config.

    voters/responded/granted: bool[M]. Empty config wins by convention
    (majority.go:179-184).
    """
    n = voters.sum().astype(jnp.int32)
    q = n // 2 + 1
    yes = (voters & responded & granted).sum().astype(jnp.int32)
    no = (voters & responded & ~granted).sum().astype(jnp.int32)
    missing = n - yes - no
    won = (yes >= q) | (n == 0)
    pending = ~won & (yes + missing >= q)
    return jnp.where(won, VOTE_WON, jnp.where(pending, VOTE_PENDING, VOTE_LOST)).astype(
        jnp.int32
    )


def joint_vote_result(
    voters_incoming: jnp.ndarray,
    voters_outgoing: jnp.ndarray,
    responded: jnp.ndarray,
    granted: jnp.ndarray,
) -> jnp.ndarray:
    """Combine both halves (quorum/joint.go:49-68): if either half lost the
    joint vote is lost; won only if both halves won; else pending."""
    r1 = vote_result(voters_incoming, responded, granted)
    r2 = vote_result(voters_outgoing, responded, granted)
    lost = (r1 == VOTE_LOST) | (r2 == VOTE_LOST)
    won = (r1 == VOTE_WON) & (r2 == VOTE_WON)
    return jnp.where(lost, VOTE_LOST, jnp.where(won, VOTE_WON, VOTE_PENDING)).astype(
        jnp.int32
    )
