"""Inbox compaction (RaftConfig.inbox_bound): the perf path processes only
the first B nonempty inbox slots per round. Drops past the bound are legal
transport behavior (etcdserver/raft.go:107-110); in the replication steady
state B = M-1 is lossless, so a bounded fleet must produce bit-identical
trajectories there.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.models.raft import compact_inbox
from etcd_tpu.types import MSG_APP, MSG_APP_RESP, MSG_VOTE, Spec, empty_msg
from etcd_tpu.utils.config import RaftConfig


def test_compact_inbox_unit():
    """Order preserved, empties squeezed out, tail dropped."""
    spec = Spec(M=5, K=2, E=1)
    S = spec.M * spec.K
    m = empty_msg(spec)
    # slots: 1:VOTE(frm 1), 4:APP(frm 2, index 7), 9:APP_RESP(frm 3)
    typ = np.zeros(S, np.int32)
    frm = np.zeros(S, np.int32)
    idx = np.zeros(S, np.int32)
    typ[1], frm[1] = MSG_VOTE, 1
    typ[4], frm[4], idx[4] = MSG_APP, 2, 7
    typ[9], frm[9] = MSG_APP_RESP, 3
    flat = m.replace(
        type=jnp.asarray(typ), frm=jnp.asarray(frm), index=jnp.asarray(idx),
        term=jnp.zeros(S, jnp.int32), log_term=jnp.zeros(S, jnp.int32),
        commit=jnp.zeros(S, jnp.int32), reject=jnp.zeros(S, bool),
        reject_hint=jnp.zeros(S, jnp.int32), context=jnp.zeros(S, jnp.int32),
        ent_len=jnp.zeros(S, jnp.int32),
        ent_term=jnp.zeros((S, 1), jnp.int32),
        ent_data=jnp.zeros((S, 1), jnp.int32),
        ent_type=jnp.zeros((S, 1), jnp.int32),
        c_voters=jnp.zeros(S, jnp.int32), c_voters_out=jnp.zeros(S, jnp.int32),
        c_learners=jnp.zeros(S, jnp.int32),
        c_learners_next=jnp.zeros(S, jnp.int32),
    )
    out = compact_inbox(spec, flat, 4)
    assert out.type.shape[0] == 4
    assert out.type.tolist() == [MSG_VOTE, MSG_APP, MSG_APP_RESP, 0]
    assert out.frm.tolist()[:3] == [1, 2, 3]
    assert int(out.index[1]) == 7
    # bound smaller than live messages: tail dropped
    out2 = compact_inbox(spec, flat, 2)
    assert out2.type.tolist() == [MSG_VOTE, MSG_APP]


def _run_steady(bound: int, rounds: int = 12, coalesce: bool = False):
    spec = Spec(M=5, L=32, E=1, K=2, W=4, R=2, A=2)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=bound, coalesce_commit_refresh=coalesce)
    cl = Cluster(n_members=5, C=4, spec=spec, cfg=cfg)
    for c in range(4):
        cl.campaign(0, c=c)
    cl.stabilize()
    commits = []
    for _ in range(rounds):
        for c in range(4):
            cl.propose(0, 7, c=c)
        cl.step()
        commits.append(np.asarray(cl.s.commit).copy())
    return cl, commits


def test_steady_state_bound_is_lossless():
    """With commit-refresh coalescing the steady state is one append + one
    ack per follower per round, so bound=M-1 reproduces the unbounded
    trajectory bit-for-bit."""
    a, _ = _run_steady(0, coalesce=True)
    b, _ = _run_steady(4, coalesce=True)
    for field in ("term", "commit", "applied", "last_index", "applied_hash",
                  "role", "lead", "match", "next_idx"):
        assert np.array_equal(
            np.asarray(getattr(a.s, field)), np.asarray(getattr(b.s, field))
        ), field
    assert int(a.s.commit.min()) >= 10  # real replication happened


def test_fleet_chunking_is_exact():
    """RaftConfig.fleet_chunks: clusters are independent, so the chunked
    round must produce bit-identical fleets (and identical drop counts on
    the metered path)."""
    spec = Spec(M=5, L=32, E=1, K=2, W=4, R=2, A=2)

    def run(chunks):
        cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                         inbox_bound=4, coalesce_commit_refresh=True,
                         fleet_chunks=chunks)
        cl = Cluster(n_members=5, C=8, spec=spec, cfg=cfg)
        for c in range(8):
            cl.campaign(c % 5, c=c)
        cl.stabilize()
        for _ in range(6):
            for c in range(8):
                cl.propose(0, 7, c=c)
            cl.step()
        return cl

    a, b, d = run(1), run(2), run(4)
    for field in ("term", "commit", "applied", "last_index", "applied_hash",
                  "role", "lead", "match", "next_idx"):
        fa = np.asarray(getattr(a.s, field))
        assert np.array_equal(fa, np.asarray(getattr(b.s, field))), field
        assert np.array_equal(fa, np.asarray(getattr(d.s, field))), field
    assert np.array_equal(np.asarray(a.eng.inbox.type),
                          np.asarray(b.eng.inbox.type))


def test_wire_int16_is_exact_at_small_horizon():
    """RaftConfig.wire_int16: at horizons where every wire value fits
    int16 (the scale-mode contract), the i16 wire reproduces the i32
    trajectories bit-for-bit."""
    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)

    def run(wire16):
        cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                         inbox_bound=4, coalesce_commit_refresh=True,
                         wire_int16=wire16)
        cl = Cluster(n_members=5, C=4, spec=spec, cfg=cfg)
        for c in range(4):
            cl.campaign(c % 5, c=c)
        cl.stabilize()
        for r in range(8):
            for c in range(4):
                cl.propose(0, 100 + r, c=c)
            cl.step()
        return cl

    a, b = run(False), run(True)
    assert b.eng.inbox.term.dtype == jnp.int16
    for field in ("term", "commit", "applied", "last_index", "applied_hash",
                  "role", "lead", "match", "next_idx", "log_data"):
        assert np.array_equal(
            np.asarray(getattr(a.s, field)), np.asarray(getattr(b.s, field))
        ), field


def test_coalesced_refresh_preserves_commit_schedule():
    """Coalescing halves message traffic but must not delay commits: the
    per-round commit trajectory matches the uncoalesced engine exactly."""
    a, ca = _run_steady(0, coalesce=False)
    b, cb = _run_steady(0, coalesce=True)
    for r, (x, y) in enumerate(zip(ca, cb)):
        assert np.array_equal(x, y), f"commit schedule diverged at round {r}"
    # and the coalesced engine really does send fewer messages
    assert a.eng.pending_messages() > b.eng.pending_messages()


def test_bounded_election_still_converges():
    """Vote-resp drops past the bound may slow an election but never wedge
    it: re-campaign on timeout wins eventually."""
    spec = Spec(M=5, L=32, E=1, K=2, W=4, R=2, A=2)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=2)  # aggressively tight
    cl = Cluster(n_members=5, C=2, spec=spec, cfg=cfg)
    ok = False
    for _ in range(120):
        cl.step(tick=True)
        if all(cl.leader(c) != -1 for c in range(2)):
            ok = True
            break
    assert ok, "bounded inbox wedged leader election"


# NOTE: the straight-line `unroll_messages` round variant was deleted in
# round 4 — its XLA CPU compile was pathological (>6GB RSS / SIGSEGV even at
# C=1) and the TPU bench had already abandoned it for the scan program
# (models/raft.py node_round). Bound semantics under the scan path are
# covered by the tests above.
