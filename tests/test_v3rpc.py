"""Serving-layer tests: embed + v3 JSON/HTTP API + etcdctl/etcdutl/verify.

The reference covers this tier with tests/e2e (real binaries over real
sockets driven by etcdctl); here an embedded server (etcd_tpu.embed)
serves real HTTP on localhost and the CLI tools drive it through the
wire, then the offline tools check the data dir it wrote.
"""
import base64
import io
import json
import sys
import urllib.request

import pytest

from etcd_tpu import etcdctl, etcdutl, verify
from etcd_tpu.embed import Config, start_etcd


def b64(s: bytes | str) -> str:
    if isinstance(s, str):
        s = s.encode()
    return base64.b64encode(s).decode()


@pytest.fixture(scope="module")
def etcd(tmp_path_factory):
    cfg = Config(
        cluster_size=3,
        data_dir=str(tmp_path_factory.mktemp("embed")),
        auto_tick=False,
        telemetry=True,  # /metrics histogram families ride the plane
        blackbox=True,   # event ring behind the Chrome trace export
    )
    e = start_etcd(cfg)
    yield e
    e.close()


def call(etcd, path, body):
    req = urllib.request.Request(
        etcd.client_url + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def run_ctl(etcd, *argv) -> str:
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        rc = etcdctl.main(["--endpoint", etcd.client_url, *argv])
    finally:
        sys.stdout = old
    assert rc == 0
    return out.getvalue()


def test_http_kv_roundtrip(etcd):
    res = call(etcd, "/v3/kv/put", {"key": b64("wire/k"), "value": b64("v1")})
    assert "header" in res
    res = call(etcd, "/v3/kv/range", {"key": b64("wire/k")})
    assert base64.b64decode(res["kvs"][0]["value"]) == b"v1"
    assert res["count"] == "1"


def test_http_txn_and_compaction(etcd):
    call(etcd, "/v3/kv/put", {"key": b64("wire/t"), "value": b64("a")})
    res = call(etcd, "/v3/kv/txn", {
        "compare": [{"key": b64("wire/t"), "target": "VALUE",
                     "result": "EQUAL", "value": b64("a")}],
        "success": [{"request_put": {"key": b64("wire/t"),
                                     "value": b64("b")}}],
        "failure": [{"request_range": {"key": b64("wire/t")}}],
    })
    assert res["succeeded"] is True
    res = call(etcd, "/v3/kv/range", {"key": b64("wire/t")})
    assert base64.b64decode(res["kvs"][0]["value"]) == b"b"
    rev = int(res["kvs"][0]["mod_revision"])
    call(etcd, "/v3/kv/compaction", {"revision": rev - 1})


def test_http_watch_longpoll(etcd):
    res = call(etcd, "/v3/watch",
               {"create_request": {"key": b64("wire/w"),
                                   "range_end": b64("wire/w\xff")}})
    wid = res["watch_id"]
    call(etcd, "/v3/kv/put", {"key": b64("wire/w1"), "value": b64("x")})
    # watched range is wire/w .. wire/w\xff: w1 is inside
    res = call(etcd, "/v3/watch", {"poll_request": {"watch_id": wid}})
    assert [e["type"] for e in res["events"]] == ["PUT"]
    res = call(etcd, "/v3/watch", {"cancel_request": {"watch_id": wid}})
    assert res["canceled"] is True


def test_http_lease_cycle(etcd):
    call(etcd, "/v3/lease/grant", {"ID": 501, "TTL": 30})
    call(etcd, "/v3/kv/put", {"key": b64("wire/l"), "value": b64("x"),
                              "lease": 501})
    res = call(etcd, "/v3/lease/timetolive", {"ID": 501})
    assert int(res["TTL"]) > 0
    res = call(etcd, "/v3/lease/leases", {})
    assert {"ID": "501"} in res["leases"]
    call(etcd, "/v3/lease/revoke", {"ID": 501})
    res = call(etcd, "/v3/kv/range", {"key": b64("wire/l")})
    assert res.get("kvs", []) == []  # revoke deleted the attached key


def test_http_health_version_metrics_status(etcd):
    with urllib.request.urlopen(etcd.client_url + "/health") as r:
        assert json.loads(r.read())["health"] == "true"
    with urllib.request.urlopen(etcd.client_url + "/version") as r:
        assert "etcdserver" in json.loads(r.read())
    with urllib.request.urlopen(etcd.client_url + "/metrics") as r:
        text = r.read().decode()
    assert "etcd_tpu_groups_with_leader 1" in text
    res = call(etcd, "/v3/maintenance/status", {})
    assert int(res["raft_term"]) >= 1
    res = call(etcd, "/v3/maintenance/hash", {})
    assert int(res["hash"]) != 0


def test_metrics_prometheus_conformance(etcd):
    """/metrics speaks exposition format: every sample under a # TYPE
    declaration, histogram triplets cumulative with +Inf == _count, and
    the text survives a parse -> re-render -> parse round trip."""
    from etcd_tpu.models.telemetry import prometheus_parse, prometheus_render

    call(etcd, "/v3/kv/put", {"key": b64("prom/k"), "value": b64("v")})
    with urllib.request.urlopen(etcd.client_url + "/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    fams = prometheus_parse(text)  # validates conformance internally
    assert fams["etcd_server_has_leader"]["type"] == "gauge"
    assert fams["etcd_server_leader_changes_seen_total"]["type"] == "counter"
    committed = fams["etcd_server_proposals_committed_total"]["samples"][
        ("etcd_server_proposals_committed_total", ())]
    assert committed >= 1
    # the telemetry-backed histogram families (fixture runs telemetry=True)
    for name in ("etcd_tpu_commit_apply_lag_entries",
                 "etcd_tpu_commit_latency_rounds",
                 "etcd_tpu_election_duration_rounds"):
        fam = fams[name]
        assert fam["type"] == "histogram"
        assert (name + "_sum", ()) in fam["samples"]
    # the server cluster elected once and commits flow: the latency
    # histogram actually accumulated samples
    lat = fams["etcd_tpu_commit_latency_rounds"]["samples"]
    assert lat[("etcd_tpu_commit_latency_rounds_count", ())] >= 1
    # round trip: re-render the parsed families and parse again — the
    # sample sets must be identical
    fams2 = prometheus_parse(prometheus_render([
        (name, f["type"], f.get("help", name),
         [(k[0][len(name):], dict(k[1]), v)
          for k, v in f["samples"].items()])
        for name, f in fams.items()
    ]))
    assert {n: f["samples"] for n, f in fams2.items()} == \
        {n: f["samples"] for n, f in fams.items()}
    # the slow-request counter families (ISSUE 15) ride the same scrape
    for name in ("etcd_server_slow_apply_total",
                 "etcd_server_slow_read_indexes_total"):
        assert fams[name]["type"] == "counter"


def test_prometheus_parse_rejects_counter_missing_type():
    """A counter family whose samples precede any # TYPE declaration is
    nonconformant — the parser must refuse it, not guess."""
    from etcd_tpu.models.telemetry import prometheus_parse

    with pytest.raises(ValueError, match="TYPE"):
        prometheus_parse(
            "# HELP etcd_server_slow_apply_total The total.\n"
            "etcd_server_slow_apply_total 3\n")


def test_slow_request_counters_and_chrome_trace(etcd):
    """The tracing tentpole end-to-end over real HTTP: force the slow
    thresholds to zero, drive a put and a linearizable range, and the
    new counter families increment on re-scrape; the recorded request
    spans plus the live device ring export to one loadable Chrome
    trace with both host and device tracks."""
    from etcd_tpu.models.blackbox import (
        HOST_PID,
        ring_capture,
        to_chrome_trace,
    )
    from etcd_tpu.models.telemetry import prometheus_parse

    def scrape():
        with urllib.request.urlopen(etcd.client_url + "/metrics") as r:
            return prometheus_parse(r.read().decode())

    def counter(fams, name):
        return fams[name]["samples"][(name, ())]

    srv = etcd.server
    before = scrape()
    # instance-attribute overrides; the class defaults stay intact for
    # the other module tests
    srv.SLOW_APPLY_THRESHOLD_S = 0.0
    srv.SLOW_READ_INDEX_THRESHOLD_S = 0.0
    try:
        call(etcd, "/v3/kv/put", {"key": b64("slow/k"), "value": b64("v")})
        res = call(etcd, "/v3/kv/range", {"key": b64("slow/k")})
        assert res["count"] == "1"
    finally:
        del srv.SLOW_APPLY_THRESHOLD_S
        del srv.SLOW_READ_INDEX_THRESHOLD_S
    after = scrape()
    assert counter(after, "etcd_server_slow_apply_total") > \
        counter(before, "etcd_server_slow_apply_total")
    assert counter(after, "etcd_server_slow_read_indexes_total") > \
        counter(before, "etcd_server_slow_read_indexes_total")
    # the traced put/range left spans with steps behind
    spans = list(srv.req_spans)
    ops = {s["op"] for s in spans}
    assert {"put", "range"} <= ops
    put_span = next(s for s in spans if s["op"] == "put")
    assert any("raft" in st["msg"] for st in put_span["steps"])
    # correlated export: device tracks from the serving fleet's ring,
    # host tracks from the request spans, one Perfetto-loadable doc
    assert srv.cl.bb is not None
    caps = ring_capture(srv.cl.bb, [0])
    doc = to_chrome_trace(captures=caps, spans=spans[-8:])
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert pids == {0, HOST_PID}
    json.loads(json.dumps(doc))


def test_http_election_and_lock(etcd):
    call(etcd, "/v3/lease/grant", {"ID": 601, "TTL": 60})
    res = call(etcd, "/v3/election/campaign",
               {"name": b64("wire/elec"), "value": b64("cand-1"),
                "lease": 601})
    leader = res["leader"]
    res = call(etcd, "/v3/election/leader", {"name": b64("wire/elec")})
    assert base64.b64decode(res["kv"]["value"]) == b"cand-1"
    call(etcd, "/v3/election/resign", {"leader": leader})

    call(etcd, "/v3/lease/grant", {"ID": 602, "TTL": 60})
    res = call(etcd, "/v3/lock/lock", {"name": b64("wire/lock"),
                                       "lease": 602})
    call(etcd, "/v3/lock/unlock", {"key": res["key"]})


def test_etcdctl_surface(etcd, tmp_path):
    assert run_ctl(etcd, "put", "ctl/a", "1") == "OK\n"
    assert run_ctl(etcd, "get", "ctl/a") == "ctl/a\n1\n"
    run_ctl(etcd, "put", "ctl/b", "2")
    out = run_ctl(etcd, "get", "ctl", "--prefix", "--count-only")
    assert out.strip() == "2"
    assert run_ctl(etcd, "del", "ctl/b").strip() == "1"
    out = run_ctl(etcd, "lease", "grant", "701", "60")
    assert "granted" in out
    out = run_ctl(etcd, "member", "list")
    assert out.count("voter") == 3
    out = run_ctl(etcd, "endpoint", "health")
    assert "true" in out
    out = run_ctl(etcd, "alarm", "list")
    assert out == ""
    snap_path = str(tmp_path / "snap.json")
    run_ctl(etcd, "snapshot", "save", snap_path)
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        assert etcdutl.main(["snapshot", "status", snap_path]) == 0
    finally:
        sys.stdout = old
    assert json.loads(out.getvalue())["revision"] >= 1


def test_offline_tools_on_data_dir(etcd):
    # flush whatever is pending so the offline view is current
    for ms in etcd.server.members:
        if ms.backend is not None:
            ms.backend.commit()
    data_dir = etcd.config.data_dir
    reports = verify.verify_data_dir(data_dir)
    assert len(reports) == 3
    assert all(r["consistent_index"] > 0 for r in reports)
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        assert etcdutl.main(["status", "--data-dir", data_dir]) == 0
        assert etcdutl.main(["hashkv", "--data-dir", data_dir,
                             "--member", "0"]) == 0
        assert etcdutl.main(["defrag", "--data-dir", data_dir]) == 0
    finally:
        sys.stdout = old
    assert "consistent_index" in out.getvalue()


def test_auto_compaction_revision_mode(tmp_path):
    e = start_etcd(Config(cluster_size=3, auto_tick=False,
                          auto_compaction_mode="revision",
                          auto_compaction_retention=5))
    try:
        for i in range(12):
            call(e, "/v3/kv/put", {"key": b64("c/k"), "value": b64(str(i))})
        for _ in range(12):
            e.tick()
        lead = e.server.ensure_leader()
        kv = e.server.members[lead].store.kv
        assert kv.compact_rev > 0
        assert kv.current_rev - kv.compact_rev >= 5
    finally:
        e.close()


def test_ticker_thread_mode():
    import time

    e = start_etcd(Config(cluster_size=3, tick_ms=20, auto_tick=True))
    try:
        call(e, "/v3/kv/put", {"key": b64("t/k"), "value": b64("v")})
        time.sleep(0.3)  # a few background ticks with concurrent serving
        res = call(e, "/v3/kv/range", {"key": b64("t/k")})
        assert base64.b64decode(res["kvs"][0]["value"]) == b"v"
    finally:
        e.close()
