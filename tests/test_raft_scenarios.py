"""raft_test.go scenario parity: dueling candidates, stale messages,
leadership-transfer edge cases, lease-based reads, proposal-forwarding
knobs, and stale-leader convergence — driven through the batched Cluster
harness the way the reference drives its fake network
(raft/raft_test.go:4633-4760).
"""
import numpy as np
import pytest

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.types import (
    CAMPAIGN_TRANSFER,
    MSG_TIMEOUT_NOW,
    NONE_ID,
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig


def elect(cl: Cluster, m: int = 0) -> int:
    cl.campaign(m)
    cl.stabilize()
    lead = cl.leader()
    assert lead == m
    return lead


# -- TestDuelingCandidates ---------------------------------------------------
def test_dueling_candidates():
    cl = Cluster(3)
    cl.cut(0, 2)  # 0 and 2 can't talk; both campaign
    cl.campaign(0)
    cl.campaign(2)
    cl.stabilize()
    roles = cl.roles()
    # node 1 is the tiebreaker: exactly one of {0,2} won its quorum
    assert (roles == ROLE_LEADER).sum() == 1
    winner = cl.leader()
    cl.recover()
    cl.propose(winner, 7)
    # ticked stabilize: the paused probe toward the cut-off node resumes
    # on the next heartbeat exchange (IsPaused, tracker/progress.go:201)
    cl.stabilize(tick=True)
    cl.stabilize(tick=True)
    cl.stabilize()
    assert min(cl.commits()) == max(cl.commits()) >= 1


# -- TestOldMessages ---------------------------------------------------------
def test_old_messages_ignored():
    from etcd_tpu.types import MSG_APP

    cl = Cluster(3)
    elect(cl, 0)
    # term moves on: node 1 takes over
    cl.campaign(1)
    cl.stabilize()
    assert cl.leader() == 1
    t_new = cl.get("term", 1)
    commit_before = cl.commits().copy()
    # inject a stale MsgApp at the old term into node 2
    cl.inject(to=2, frm=0, type=MSG_APP, term=t_new - 1, index=0,
              log_term=0, commit=5)
    cl.stabilize()
    # the stale leader's commit hint must not move node 2
    assert cl.get("term", 2) == t_new
    assert (cl.commits() >= commit_before).all()
    assert cl.get("commit", 2) == commit_before[2]


# -- leadership transfer (raft.go:1339-1369) ---------------------------------
def test_transfer_to_up_to_date_follower():
    cl = Cluster(3)
    elect(cl, 0)
    cl.propose(0, 5)
    cl.stabilize()
    cl.inject(to=0, frm=1, type=10, term=cl.get("term", 0))  # MsgTransferLeader
    cl.stabilize()
    assert cl.leader() == 1
    assert cl.get("role", 0) == ROLE_FOLLOWER


def test_transfer_to_lagging_follower_waits_for_catchup():
    cl = Cluster(3)
    elect(cl, 0)
    cl.isolate(2)
    for d in (5, 6, 7):
        cl.propose(0, d)
        cl.stabilize()
    assert cl.get("match", 0)[2] < cl.get("last_index", 0)
    cl.recover()
    # transfer request while 2 is behind: leader first catches it up, then
    # sends MsgTimeoutNow once match == lastIndex
    cl.inject(to=0, frm=2, type=10, term=cl.get("term", 0))
    cl.stabilize()
    assert cl.leader() == 2
    assert cl.get("last_index", 2) >= 4


def test_transfer_aborts_on_election_timeout():
    cl = Cluster(3)
    elect(cl, 0)
    cl.isolate(2)
    cl.inject(to=0, frm=2, type=10, term=cl.get("term", 0))
    cl.step()
    assert cl.get("lead_transferee", 0) == 2
    # the transfer target never catches up; a full election timeout at the
    # leader abandons the transfer (raft.go:668-671)
    for _ in range(cl.cfg.election_tick + 1):
        cl.step(tick=True)
    assert cl.get("lead_transferee", 0) == NONE_ID
    assert cl.leader() == 0 or cl.get("role", 0) == ROLE_LEADER


def test_transfer_to_self_and_learner_ignored():
    cl = Cluster(
        4, voters=[True, True, True, False],
        learners=[False, False, False, True], spec=Spec(M=4),
    )
    elect(cl, 0)
    t = cl.get("term", 0)
    cl.inject(to=0, frm=0, type=10, term=t)  # self-transfer: no-op
    cl.stabilize()
    assert cl.leader() == 0
    cl.inject(to=0, frm=3, type=10, term=t)  # learner: ignored
    cl.stabilize()
    assert cl.leader() == 0
    assert cl.get("lead_transferee", 0) == NONE_ID


def test_timeout_now_forces_election_past_lease():
    """MsgTimeoutNow campaigns with CAMPAIGN_TRANSFER, overriding the
    check-quorum leader lease that normally rejects the vote
    (raft.go:855-881 force flag)."""
    cfg = RaftConfig(pre_vote=True, check_quorum=True)
    cl = Cluster(3, cfg=cfg)
    elect(cl, 0)
    t = cl.get("term", 0)
    cl.inject(to=1, frm=0, type=MSG_TIMEOUT_NOW, term=t)
    cl.stabilize()
    assert cl.leader() == 1
    assert cl.get("term", 1) == t + 1


# -- proposal forwarding knobs ----------------------------------------------
def test_disable_proposal_forwarding():
    cfg = RaftConfig(disable_proposal_forwarding=True)
    cl = Cluster(3, cfg=cfg)
    elect(cl, 0)
    last = cl.get("last_index", 0)
    cl.propose(1, 9)  # follower proposal: dropped, not forwarded
    cl.stabilize()
    assert cl.get("last_index", 0) == last


# -- ReadOnlyLeaseBased (raft.go:53-58, read_only.go) ------------------------
def test_read_index_lease_based():
    cfg = RaftConfig(check_quorum=True, read_only_lease_based=True)
    cl = Cluster(3, cfg=cfg)
    elect(cl, 0)
    cl.propose(0, 5)
    cl.stabilize()
    commit = cl.get("commit", 0)
    ctx = cl.read_index(0)
    cl.step()  # lease-based: answered locally, no heartbeat round needed
    rs_count = cl.get("rs_count", 0)
    assert rs_count >= 1
    ctxs = cl.get("rs_ctx", 0)
    idxs = cl.get("rs_index", 0)
    assert ctxs[0] == ctx and idxs[0] == commit


# -- candidate concedes to a live leader -------------------------------------
def test_candidate_steps_down_on_leader_heartbeat():
    from etcd_tpu.types import MSG_HEARTBEAT

    cl = Cluster(3)
    elect(cl, 0)
    t = cl.get("term", 0)
    # drive node 2 into candidacy at t+1 while partitioned
    cl.isolate(2)
    cl.campaign(2)
    cl.stabilize()
    assert cl.get("role", 2) == ROLE_CANDIDATE
    cl.recover()
    # a heartbeat from the (re-elected at t+?) leader at the candidate's
    # term makes it concede (raft.go:1390-1398)
    cl.inject(to=2, frm=0, type=MSG_HEARTBEAT, term=cl.get("term", 2))
    cl.stabilize()
    assert cl.get("role", 2) == ROLE_FOLLOWER


# -- stale minority leader converges after heal ------------------------------
def test_stale_leader_steps_down_after_heal():
    cl = Cluster(5, spec=Spec(M=5))
    elect(cl, 0)
    # leader 0 keeps only follower 1; nodes 2,3,4 elect a new leader
    cl.partition([[0, 1], [2, 3, 4]])
    cl.campaign(2)
    cl.stabilize()
    leaders = set(cl.leaders())
    assert 2 in leaders  # majority side elected
    assert cl.get("term", 2) > cl.get("term", 0) or 0 not in leaders
    cl.recover()
    cl.propose(2, 9)
    # heartbeats carry the new term to the stale minority leader
    cl.stabilize(tick=True)
    cl.stabilize(tick=True)
    cl.stabilize()
    assert cl.leaders() == [2]  # the stale leader stepped down
    assert cl.get("role", 0) == ROLE_FOLLOWER
    assert min(cl.commits()) == max(cl.commits())
