import os

# Tests run on a virtual 8-device CPU mesh: sharding paths are exercised
# without TPU hardware and unit tests stay fast and hermetic.
#
# NOTE: this environment's sitecustomize registers an "axon" TPU backend and
# *explicitly* sets jax_platforms="axon,cpu" via jax.config.update at
# interpreter start, which overrides JAX_PLATFORMS from the environment. We
# must override it back AFTER importing jax, or every eager op dispatches
# over the TPU tunnel (~5ms/op, and hangs when the tunnel is down).
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the round program is large; re-running the
# suite should not re-pay XLA compile time.
#
# NOTE: cache entries are machine-specific XLA:CPU AOT code, and in
# this environment CPU compiles run through the axon host compiler,
# whose feature flags (+prefer-no-scatter/+prefer-no-gather) differ
# from the execution host — so cpu_aot_loader machine-feature warnings
# are CHRONIC here, even on freshly-built entries. The round-3/4 suite
# SIGSEGVs happened in the cache-read path at high process RSS; a cache
# wipe + the periodic clear_caches below produced a green 346-test run.
# If the suite dies in compilation_cache.get_executable_and_time again:
# wipe .jax_cache, keep SUITE_CLEAR_EVERY enabled, and re-run.
from etcd_tpu.utils.cache import configure_compile_cache  # noqa: E402

configure_compile_cache(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))

import gc

import pytest

# The single pytest process accumulates one live XLA executable per
# compiled program (hundreds over the suite, ~10s of GB RSS). Dropping
# them periodically bounds that growth; the persistent cache makes the
# re-load cheap. SUITE_CLEAR_EVERY=0 disables.
_CLEAR_EVERY = int(os.environ.get("SUITE_CLEAR_EVERY", "100"))
_test_count = [0]


@pytest.fixture(autouse=True)
def _bound_executable_accumulation():
    yield
    _test_count[0] += 1
    if _CLEAR_EVERY and _test_count[0] % _CLEAR_EVERY == 0:
        jax.clear_caches()
        gc.collect()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "e2e: out-of-process tier — spawns etcdmain subprocesses")
    config.addinivalue_line(
        "markers",
        "smoke: fast core-correctness tier (-m smoke for quick "
        "iteration on models/raft.py edits)")
    config.addinivalue_line(
        "markers",
        "slow: full-scale tiers excluded from the tier-1 run "
        "(-m 'not slow'); e.g. the 262k-group crash-chaos run and the "
        "4096-group device-MVCC acceptance fuzz (no new marker needed "
        "for the apply plane — its scale shapes ride this one; the "
        "fleet-memory-diet equivalence suites keep their fast C<=16 "
        "shapes unmarked and any future large-C variant rides this "
        "marker too)")


def bootstrap_cert_cn_auth(call):
    """Shared admin bootstrap for the cert-CN auth scenarios (test_tls
    mtls fixture + the e2e subprocess variant): root with the root
    role, alice scoped READWRITE to /app/*, auth enabled. `call` is a
    RemoteClient.call-shaped callable."""
    from etcd_tpu.client import RemoteClient

    b64 = RemoteClient._b64
    call("/v3/auth/user/add", {"name": "root", "password": "rpw"})
    call("/v3/auth/role/add", {"name": "root"})
    call("/v3/auth/user/grant", {"name": "root", "role": "root"})
    call("/v3/auth/user/add", {"name": "alice", "password": "apw"})
    call("/v3/auth/role/add", {"name": "app"})
    call("/v3/auth/role/grant", {
        "name": "app",
        "perm": {"permType": "READWRITE", "key": b64(b"/app/"),
                 "range_end": b64(b"/app0")}})
    call("/v3/auth/user/grant", {"name": "alice", "role": "app"})
    call("/v3/auth/enable", {})
