#!/bin/bash
# Smoke tier (~15 min warm on this 1-core VM; measured): the core-correctness subset to run between
# models/raft.py edits, when the full suite's cold-compile cost
# (~2h after any raft.py change invalidates the fleet-program cache)
# would stall iteration. Covers: the raft state machines against the
# reference datadriven goldens, the ring/quorum kernels, the trace-specialization
# equivalence proofs (every perf rung), replication + election
# scenarios. NOT a substitute for the full
# suite before a commit milestone — wire façades, the network/lease
# chaos tiers, tools and e2e only run there. The crash-chaos tier's
# fast configuration (tests/test_recovery_crash.py: <=64 groups, <=2 fault
# epochs; the 262k variant stays behind -m slow) runs HERE because
# crash recovery exercises the raft state machines this tier guards —
# as does the membership-chaos tier's fast configuration
# (tests/test_recovery_member.py: <=16 groups, conf-change injection +
# config-aware checkers; the 4096-group shape stays behind -m slow), and
# the device-MVCC apply plane's fast tier (tests/test_device_mvcc.py:
# differential fuzz at <=128 groups, engine/kvserver integration; the
# 4096-group acceptance fuzz stays behind -m slow) — the apply plane
# consumes the frontier these state machines produce. The fleet-memory-
# diet equivalence tiers run here too: packed-state/compact-wire
# full-program bit-identity (tests/test_packed_state.py, C=16),
# sparse-outbox steady bit-identity (tests/test_sparse_outbox.py) and
# fleet-carry donation safety (tests/test_donation.py) — they guard the
# same round program this tier exists for. The telemetry tier
# (tests/test_telemetry.py) runs here too: round-program bit-identity
# with the telemetry plane fused in (dense + diet forms), a host-replay
# histogram cross-check, and the small-C chaos flight-recorder run
# asserting the per-epoch timeline is present and monotone. The fast
# forensics tier (tests/test_telemetry_blackbox.py, tests/test_trace.py) rides
# along: black-box ring bit-identity over the same round programs, the
# numpy word-replay cross-check, the persist-nothing post-mortem at
# C=16, and the host Trace unit tests — all small-C, no slow marks.
#
# The static-analysis fast tier runs FIRST: source lint + the widths
# table cross-check (etcd_tpu/analysis — milliseconds, no tracing).
# A lint finding here is a real defect or an unjustified suppression;
# fix it before burning pytest time. The trace/HLO auditors
# (ANALYSIS_AUDIT=1, the default CLI mode) stay out of the smoke loop —
# they re-trace every registry program (minutes); run the full CLI
# before a commit milestone instead.
cd "$(dirname "$0")"
ANALYSIS_AUDIT=0 python -m etcd_tpu.analysis || exit 1
JAX_PLATFORMS=cpu ANALYSIS_LINT=0 ANALYSIS_AUDITORS=widths \
  ANALYSIS_PROGRAMS=bare_round python -m etcd_tpu.analysis || exit 1
exec python -m pytest -q -m 'not slow' \
  tests/test_datadriven_quorum.py \
  tests/test_datadriven_confchange.py \
  tests/test_paper.py \
  tests/test_quorum.py \
  tests/test_log.py \
  tests/test_raftpb.py \
  tests/test_confchange.py \
  tests/test_election.py \
  tests/test_replication.py \
  tests/test_local_steps.py \
  tests/test_deferred_emit.py \
  tests/test_apply_specialization.py \
  tests/test_packed_state.py \
  tests/test_sparse_outbox.py \
  tests/test_donation.py \
  tests/test_sparse_held.py \
  tests/test_recovery_crash.py \
  tests/test_recovery_member.py \
  tests/test_device_mvcc.py \
  tests/test_telemetry.py \
  tests/test_trace.py \
  tests/test_telemetry_blackbox.py \
  "$@"
