"""Pluggable logger — the raft.Logger analog.

The reference exposes a small logging interface with default / discard
implementations and a process-wide ``SetLogger`` hook
(raft/logger.go:24-142), bridged to zap by the server
(server/etcdserver/zap_raft.go:102). The TPU engine's hot path is pure
tensor math and never logs (by design — a log call per node per round
would serialize the fleet), so this logger serves the HOST layers: the
server runtime, storage recovery, harnesses and CLIs.

``Logger`` mirrors the reference surface (debug/info/warning/error/
fatal/panic, printf-style); ``set_logger`` swaps the process-wide
instance; ``DiscardLogger`` silences everything (raft/logger.go:90).
The default adapts to the stdlib ``logging`` module so embedders can
route through their own handlers.
"""
from __future__ import annotations

import logging as _pylog
import sys


class Logger:
    """raft.Logger (raft/logger.go:24-40)."""

    def debug(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def info(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def warning(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def error(self, fmt: str, *args) -> None:
        raise NotImplementedError

    def fatal(self, fmt: str, *args) -> None:
        self.error(fmt, *args)
        sys.exit(1)

    def panic(self, fmt: str, *args) -> None:
        raise RuntimeError(fmt % args if args else fmt)


class DefaultLogger(Logger):
    """Bridges to the stdlib logging module (the zap bridge analog)."""

    def __init__(self, name: str = "etcd_tpu"):
        self._log = _pylog.getLogger(name)

    def debug(self, fmt, *args):
        self._log.debug(fmt, *args)

    def info(self, fmt, *args):
        self._log.info(fmt, *args)

    def warning(self, fmt, *args):
        self._log.warning(fmt, *args)

    def error(self, fmt, *args):
        self._log.error(fmt, *args)


class DiscardLogger(Logger):
    """Drops everything (raft/logger.go:90-100)."""

    def debug(self, fmt, *args):
        pass

    def info(self, fmt, *args):
        pass

    def warning(self, fmt, *args):
        pass

    def error(self, fmt, *args):
        pass


_logger: Logger = DefaultLogger()


def set_logger(logger: Logger) -> None:
    """raft.SetLogger (raft/logger.go:60-66)."""
    global _logger
    _logger = logger


def get_logger() -> Logger:
    return _logger
