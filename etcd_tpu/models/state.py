"""Per-node Raft state as a struct-of-arrays pytree.

This is the TPU-native re-layout of the reference's ``raft`` struct
(raft/raft.go:243-316) fused with its ``raftLog`` (raft/log.go:24-45),
``tracker.ProgressTracker`` (tracker/tracker.go) and config masks
(tracker.Config / confchange): one node's state is a bundle of scalars,
[M] peer-arrays and an [L] log ring; a whole fleet is the same pytree with
leading ``[clusters, members]`` axes produced by ``jax.vmap``.

Design notes vs the reference:
  * stable/unstable log split (raft/log_unstable.go) collapses to cursor
    arithmetic — the device ring IS the log; host checkpointing reads any
    suffix it wants. `first_index = snap_index + 1`, valid range
    (snap_index, last_index], capacity L.
  * Snapshots are applied eagerly on restore (the reference stages them in
    `unstable.snapshot` until the app applies them; our "application" is
    fused into the round step), so `promotable()`'s pending-snapshot check
    (raft/raft.go:1618-1621) is vacuously satisfied.
  * The applied state machine is a rolling hash chain (`applied_hash`) —
    the batched analog of the functional tester's KV_HASH checker
    (tests/functional/tester/checker_kv_hash.go): two nodes with equal
    `applied` must have equal `applied_hash`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from etcd_tpu.types import (
    NONE_ID,
    PR_PROBE,
    ROLE_FOLLOWER,
    Spec,
)


class NodeState(struct.PyTreeNode):
    # --- identity -----------------------------------------------------------
    nid: jnp.ndarray          # i32, this node's member id (constant)

    # --- HardState (raftpb.HardState, raft.proto:102-106) -------------------
    term: jnp.ndarray         # i32
    vote: jnp.ndarray         # i32, NONE_ID if none
    commit: jnp.ndarray       # i32

    # --- SoftState ----------------------------------------------------------
    lead: jnp.ndarray         # i32, NONE_ID if unknown
    role: jnp.ndarray         # i32 ROLE_*

    # --- log ring (raftLog + unstable fused) --------------------------------
    log_term: jnp.ndarray     # i32[L]
    log_data: jnp.ndarray     # i32[L]
    log_type: jnp.ndarray     # i32[L] ENTRY_*
    last_index: jnp.ndarray   # i32
    applied: jnp.ndarray      # i32
    applied_hash: jnp.ndarray # i32 rolling hash chain of applied entries

    # --- snapshot (raftpb.SnapshotMetadata analog) --------------------------
    snap_index: jnp.ndarray   # i32; log holds (snap_index, last_index]
    snap_term: jnp.ndarray    # i32
    snap_hash: jnp.ndarray    # i32 applied_hash at snap_index
    snap_voters: jnp.ndarray        # bool[M] ConfState at snapshot
    snap_voters_out: jnp.ndarray    # bool[M]
    snap_learners: jnp.ndarray      # bool[M]
    snap_learners_next: jnp.ndarray # bool[M]
    snap_auto_leave: jnp.ndarray    # bool

    # --- timers (raft.go:285-303) -------------------------------------------
    election_elapsed: jnp.ndarray    # i32
    heartbeat_elapsed: jnp.ndarray   # i32
    randomized_timeout: jnp.ndarray  # i32
    rng_key: jnp.ndarray             # u32[2] per-node PRNG key

    # --- leader replication tracker (tracker/progress.go:30-80) -------------
    match: jnp.ndarray        # i32[M]
    next_idx: jnp.ndarray     # i32[M]
    pr_state: jnp.ndarray     # i32[M] PR_*
    probe_sent: jnp.ndarray   # bool[M]
    pending_snapshot: jnp.ndarray  # i32[M]
    recent_active: jnp.ndarray     # bool[M]
    # inflights ring (tracker/inflights.go): ends of in-flight MsgApps.
    # Stored FLAT [M*W]: rank-2 per-node leaves with tiny minor dims get
    # tile-padded ~26x once batched to fleet shape (a 1.25GB HLO temp at
    # C=65536); ops view it as [M, W] via free reshapes.
    infl_ends: jnp.ndarray    # i32[M*W]
    infl_start: jnp.ndarray   # i32[M]
    infl_count: jnp.ndarray   # i32[M]

    # --- votes (tracker.ProgressTracker.Votes) ------------------------------
    votes_responded: jnp.ndarray  # bool[M]
    votes_granted: jnp.ndarray    # bool[M]

    # --- config: this node's applied view (tracker.Config) ------------------
    voters: jnp.ndarray           # bool[M] incoming voters
    voters_out: jnp.ndarray       # bool[M] outgoing voters (joint iff any)
    learners: jnp.ndarray         # bool[M]
    learners_next: jnp.ndarray    # bool[M]
    auto_leave: jnp.ndarray       # bool

    # --- leader bookkeeping -------------------------------------------------
    pending_conf_index: jnp.ndarray  # i32
    uncommitted_size: jnp.ndarray    # i32 (entry count stand-in for bytes)
    lead_transferee: jnp.ndarray     # i32

    # --- read-only queue (raft/read_only.go), re-keyed by int ctx -----------
    ro_ctx: jnp.ndarray       # i32[R] request ctx ids (0 = empty)
    ro_index: jnp.ndarray     # i32[R] commit index captured at enqueue
    ro_from: jnp.ndarray      # i32[R] requester id (NONE_ID/self => local)
    ro_acks: jnp.ndarray      # bool[R*M] (flat; see infl_ends note)
    ro_count: jnp.ndarray     # i32 number of queued requests
    # pending MsgReadIndex deferred until first commit in term
    # (raft.go:311-315 pendingReadIndexMessages)
    ro_pend_ctx: jnp.ndarray  # i32[R]
    ro_pend_from: jnp.ndarray # i32[R]
    ro_pend_count: jnp.ndarray  # i32
    # ReadStates surfaced to the local application (raft.go:249)
    rs_ctx: jnp.ndarray       # i32[R]
    rs_index: jnp.ndarray     # i32[R]
    rs_count: jnp.ndarray     # i32


# ---------------------------------------------------------------------------
# Crash-durability classification (harness/chaos.py crash faults).
#
# Every NodeState field belongs to exactly one class; the chaos tier's
# crash–restart wipe (models/engine.py crash_restart_fleet) implements this
# table, and tests/test_recovery_crash.py proves the two agree — a new field
# added here without a classification fails the suite instead of silently
# surviving (or losing) a simulated crash.
#
#  * DURABLE: survives a crash as-is. HardState term/vote (MustSync forces
#    an fsync before any message reflecting them is sent,
#    raft/node.go:586-593), the snapshot metadata (snapshots fsync
#    synchronously before use), the node id, and the log ring ARRAYS
#    (slots past the durable last_index are dead by the last_index gate —
#    the window (snap_index, last_index] defines validity, so lost-suffix
#    slots need no scrub).
#  * CAPPED: survives up to the durable floor. last_index drops to the
#    fsync'd prefix (max(min(last_index, stable), snap_index)); commit is
#    additionally capped by it (commit-only advances don't fsync, so a
#    restart may legally REGRESS commit — the chaos commit-monotonicity
#    checker exempts crash rounds).
#  * REPLAY: re-derived by replaying the durable log from the snapshot:
#    applied/applied_hash rewind to the snapshot cursor (the fused apply
#    loop then re-applies committed entries, reproducing the identical
#    hash chain — which the KV_HASH checker verifies), and the applied
#    config masks rewind to the snapshot's ConfState. The chaos tier's
#    config-aware recovery checkers key on this: a crash may regress a
#    node's applied config VIEW, but never the durable conf entries, so
#    the checkers carry the newest-ever applied config across outages
#    (harness/chaos.py refresh_ref_config) instead of re-reading the
#    possibly-rewound masks.
#  * VOLATILE: reset to fresh-follower boot values (raft.go:318-370
#    newRaft on restart): role/lead/timers/tracker/votes/queues. The
#    randomized election timeout is re-drawn; rng_key is carried through
#    (PRNG state has no semantic content — any value is a valid restart).
# ---------------------------------------------------------------------------

DURABLE_FIELDS = (
    "nid", "term", "vote",
    "log_term", "log_data", "log_type",
    "snap_index", "snap_term", "snap_hash",
    "snap_voters", "snap_voters_out", "snap_learners", "snap_learners_next",
    "snap_auto_leave",
    "rng_key",
)
CAPPED_FIELDS = ("last_index", "commit")
REPLAY_FIELDS = (
    "applied", "applied_hash",
    "voters", "voters_out", "learners", "learners_next", "auto_leave",
)
VOLATILE_FIELDS = (
    "lead", "role",
    "election_elapsed", "heartbeat_elapsed", "randomized_timeout",
    "match", "next_idx", "pr_state", "probe_sent", "pending_snapshot",
    "recent_active",
    "infl_ends", "infl_start", "infl_count",
    "votes_responded", "votes_granted",
    "pending_conf_index", "uncommitted_size", "lead_transferee",
    "ro_ctx", "ro_index", "ro_from", "ro_acks", "ro_count",
    "ro_pend_ctx", "ro_pend_from", "ro_pend_count",
    "rs_ctx", "rs_index", "rs_count",
)


def init_node(
    spec: Spec,
    nid: int | jnp.ndarray,
    voters: jnp.ndarray,
    learners: jnp.ndarray | None = None,
    seed: int | jnp.ndarray = 0,
    election_tick: int = 10,
) -> NodeState:
    """A fresh follower at term 0 with the given applied config.

    Equivalent to newRaft on a MemoryStorage whose ConfState is already set
    (the way raft_test.go's newTestRaft boots; raft/raft.go:318-370) — the
    log is empty, commit/applied = 0, and like becomeFollower at boot a
    randomized election timeout in [T, 2T) is drawn.
    """
    M, L, W, R = spec.M, spec.L, spec.W, spec.R
    if learners is None:
        learners = jnp.zeros((M,), jnp.bool_)
    fM = jnp.zeros((M,), jnp.bool_)
    z = jnp.int32(0)
    nid = jnp.asarray(nid, jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(0), jnp.asarray(seed, jnp.int32))
    key = jax.random.fold_in(key, nid)
    key, sub = jax.random.split(key)
    rand_to = election_tick + jax.random.randint(
        sub, (), 0, election_tick, dtype=jnp.int32
    )
    return NodeState(
        nid=nid,
        term=z, vote=jnp.int32(NONE_ID), commit=z,
        lead=jnp.int32(NONE_ID), role=jnp.int32(ROLE_FOLLOWER),
        log_term=jnp.zeros((L,), jnp.int32),
        log_data=jnp.zeros((L,), jnp.int32),
        log_type=jnp.zeros((L,), jnp.int32),
        last_index=z, applied=z, applied_hash=z,
        snap_index=z, snap_term=z, snap_hash=z,
        snap_voters=voters, snap_voters_out=fM,
        snap_learners=learners, snap_learners_next=fM,
        snap_auto_leave=jnp.bool_(False),
        election_elapsed=z, heartbeat_elapsed=z,
        randomized_timeout=rand_to,
        rng_key=key,
        match=jnp.zeros((M,), jnp.int32),
        next_idx=jnp.ones((M,), jnp.int32),
        pr_state=jnp.full((M,), PR_PROBE, jnp.int32),
        probe_sent=fM,
        pending_snapshot=jnp.zeros((M,), jnp.int32),
        recent_active=fM,
        infl_ends=jnp.zeros((M * W,), jnp.int32),
        infl_start=jnp.zeros((M,), jnp.int32),
        infl_count=jnp.zeros((M,), jnp.int32),
        votes_responded=fM, votes_granted=fM,
        voters=voters, voters_out=fM,
        learners=learners, learners_next=fM,
        auto_leave=jnp.bool_(False),
        pending_conf_index=z, uncommitted_size=z,
        lead_transferee=jnp.int32(NONE_ID),
        ro_ctx=jnp.zeros((R,), jnp.int32),
        ro_index=jnp.zeros((R,), jnp.int32),
        ro_from=jnp.full((R,), NONE_ID, jnp.int32),
        ro_acks=jnp.zeros((R * M,), jnp.bool_),
        ro_count=z,
        ro_pend_ctx=jnp.zeros((R,), jnp.int32),
        ro_pend_from=jnp.full((R,), NONE_ID, jnp.int32),
        ro_pend_count=z,
        rs_ctx=jnp.zeros((R,), jnp.int32),
        rs_index=jnp.zeros((R,), jnp.int32),
        rs_count=z,
    )


def is_joint(n: NodeState) -> jnp.ndarray:
    return n.voters_out.any()


def is_learner_self(n: NodeState) -> jnp.ndarray:
    self_hot = jnp.arange(n.voters.shape[0], dtype=jnp.int32) == n.nid
    return (self_hot & n.learners).any()


def in_config_self(n: NodeState) -> jnp.ndarray:
    """Whether this node has a Progress entry, i.e. is voter/outgoing/learner."""
    self_hot = jnp.arange(n.voters.shape[0], dtype=jnp.int32) == n.nid
    return (self_hot & (n.voters | n.voters_out | n.learners)).any()


# ---------------------------------------------------------------------------
# Packed fleet storage — the "fleet memory diet" (RaftConfig.packed_state)
#
# The resident fleet's bytes/group, not its FLOPs, is what forces the
# fleet-chunk loop above ~131k groups/shard (PROFILE.md roofline): most
# NodeState leaves are bools, 2-bit enums, node ids, or small counters
# stored as int32/bool arrays. The packed form carries the SAME information
# in three dense planes per node:
#
#   bits    u32[NB]  every narrow field (roles, ids, vote bitmaps, guard
#                    flags, timers, counters, pr_state, log_type) bit-packed
#                    into 32-bit lanes
#   narrow  i16[NI]  every index/term-valued field (ring terms, match/next,
#                    inflight ends, cursors) under the wire_int16-class
#                    range contract (values < 32768 at bench/chaos horizons)
#   wide    i32[NW]  full-width fields: the two rolling hashes and the
#                    log_data payload words (device-MVCC words use 28 bits)
#   rng     u32[2]   the per-node PRNG key, passthrough
#
# ~2.4x smaller than NodeState at the bench geometry. pack/unpack are pure
# elementwise shift/mask chains that XLA fuses into the neighboring round
# program; with fleet_chunks they run INSIDE the chunk loop so the unpacked
# temps stay chunk-local. The crash-durability machinery is untouched: it
# operates on the unpacked NodeState between unpack and repack, so the
# classification table above stays the single source of truth.
#
# A NodeState field added without a row in the pack plan fails
# tests/test_packed_state.py (same enforcement pattern as the durability
# table), and the bytes budget there keeps a new leaf from silently
# re-inflating the fleet.
# ---------------------------------------------------------------------------

# Packed timer lanes: election_elapsed / heartbeat_elapsed / randomized_
# timeout each get this many bits. Requires 2 * election_tick <
# 2**PACK_TIMER_BITS (models/engine.py validates at build time); the two
# elapsed counters SATURATE at the cap, which is exact for promotable nodes
# (elapsed resets at the timeout) and semantically equivalent for
# non-promotable ones (any elapsed >= the randomized timeout behaves the
# same: the fire/lease comparisons are already past their thresholds).
PACK_TIMER_BITS = 10
_PACK_SATURATING = ("election_elapsed", "heartbeat_elapsed")

_PACK_BOOL_FIELDS = frozenset({
    "snap_auto_leave", "auto_leave",
    "probe_sent", "recent_active", "votes_responded", "votes_granted",
    "voters", "voters_out", "learners", "learners_next",
    "snap_voters", "snap_voters_out", "snap_learners", "snap_learners_next",
    "ro_acks",
})


class PackedFleet(struct.PyTreeNode):
    """A NodeState fleet in packed storage (leaves keep the engine's
    members-leading / clusters-minor convention: [M, lanes, C])."""

    bits: jnp.ndarray    # u32[M, NB, C]
    narrow: jnp.ndarray  # i16[M, NI, C]
    wide: jnp.ndarray    # i32[M, NW, C]
    rng_key: jnp.ndarray # u32[M, 2, C] passthrough


@functools.lru_cache(maxsize=16)
def pack_plan(spec: Spec):
    """The static packing layout for one Spec: (bit_rows, bit_lanes,
    narrow_rows, wide_rows) where bit_rows maps every narrow field to
    per-element (lane, offset) slots, and narrow/wide rows are
    (name, count, offset) runs in the i16/i32 planes."""
    M, L, R, W = spec.M, spec.L, spec.R, spec.W
    idb = max(M.bit_length(), 1)          # ids stored with +1 bias: 0..M
    cnt = max(R.bit_length(), 1)          # queue counters: 0..R
    tb = PACK_TIMER_BITS
    bit_fields = (
        # (name, bits/element, elements, bias)
        ("nid", idb, 1, 0),
        ("role", 2, 1, 0),
        ("lead", idb, 1, 1),
        ("vote", idb, 1, 1),
        ("lead_transferee", idb, 1, 1),
        ("snap_auto_leave", 1, 1, 0),
        ("auto_leave", 1, 1, 0),
        ("election_elapsed", tb, 1, 0),
        ("heartbeat_elapsed", tb, 1, 0),
        ("randomized_timeout", tb, 1, 0),
        ("ro_count", cnt, 1, 0),
        ("ro_pend_count", cnt, 1, 0),
        ("rs_count", cnt, 1, 0),
        ("pr_state", 2, M, 0),
        ("probe_sent", 1, M, 0),
        ("recent_active", 1, M, 0),
        ("votes_responded", 1, M, 0),
        ("votes_granted", 1, M, 0),
        ("voters", 1, M, 0),
        ("voters_out", 1, M, 0),
        ("learners", 1, M, 0),
        ("learners_next", 1, M, 0),
        ("snap_voters", 1, M, 0),
        ("snap_voters_out", 1, M, 0),
        ("snap_learners", 1, M, 0),
        ("snap_learners_next", 1, M, 0),
        ("infl_start", max((W - 1).bit_length(), 1), M, 0),
        ("infl_count", max(W.bit_length(), 1), M, 0),
        ("ro_acks", 1, R * M, 0),
        ("ro_from", idb, R, 1),
        ("ro_pend_from", idb, R, 1),
        ("log_type", 2, L, 0),
    )
    # greedy lane fill; an element never straddles two lanes
    bit_rows, lane, off = [], 0, 0
    for name, bits, count, bias in bit_fields:
        slots = []
        for _ in range(count):
            if off + bits > 32:
                lane, off = lane + 1, 0
            slots.append((lane, off))
            off += bits
        bit_rows.append((name, bits, bias, tuple(slots)))
    n_lanes = lane + 1

    def runs(fields):
        rows, o = [], 0
        for name, count in fields:
            rows.append((name, count, o))
            o += count
        return tuple(rows), o

    narrow_rows, n_narrow = runs((
        ("term", 1), ("commit", 1), ("last_index", 1), ("applied", 1),
        ("snap_index", 1), ("snap_term", 1), ("pending_conf_index", 1),
        ("uncommitted_size", 1),
        ("match", M), ("next_idx", M), ("pending_snapshot", M),
        ("infl_ends", M * W),
        ("log_term", L),
        ("ro_ctx", R), ("ro_index", R), ("ro_pend_ctx", R),
        ("rs_ctx", R), ("rs_index", R),
    ))
    wide_rows, n_wide = runs((
        ("applied_hash", 1), ("snap_hash", 1), ("log_data", L),
    ))
    covered = ({r[0] for r in bit_rows}
               | {r[0] for r in narrow_rows}
               | {r[0] for r in wide_rows} | {"rng_key"})
    missing = set(NodeState.__dataclass_fields__) - covered
    extra = covered - set(NodeState.__dataclass_fields__)
    if missing or extra:
        # a new NodeState leaf MUST be classified here, exactly like the
        # durability table — an unpacked stray would silently vanish
        # across a packed round
        raise ValueError(
            f"pack_plan out of sync with NodeState: missing={sorted(missing)} "
            f"extra={sorted(extra)}")
    return bit_rows, n_lanes, narrow_rows, n_narrow, wide_rows, n_wide


def _rows3(x: jnp.ndarray) -> jnp.ndarray:
    """Fleet leaf [M, C] or [M, count, C] -> [M, count, C]."""
    return x[:, None, :] if x.ndim == 2 else x


def pack_fleet(spec: Spec, state: NodeState) -> PackedFleet:
    """NodeState fleet ([M, ..., C] leaves) -> packed storage. Values are
    masked to their declared widths (the wire_int16-style range contract;
    the two elapsed timers saturate instead — see PACK_TIMER_BITS)."""
    bit_rows, n_lanes, narrow_rows, _, wide_rows, _ = pack_plan(spec)
    M = spec.M
    C = state.term.shape[-1]
    lanes = [jnp.zeros((M, C), jnp.uint32) for _ in range(n_lanes)]
    for name, bits, bias, slots in bit_rows:
        x = _rows3(getattr(state, name))
        if name in _PACK_BOOL_FIELDS:
            v = x.astype(jnp.uint32)
        else:
            v = x.astype(jnp.int32) + bias
            if name in _PACK_SATURATING:
                v = jnp.minimum(v, (1 << bits) - 1)
            v = (v & ((1 << bits) - 1)).astype(jnp.uint32)
        for k, (lane, off) in enumerate(slots):
            lanes[lane] = lanes[lane] | (v[:, k, :] << jnp.uint32(off))
    bits_plane = jnp.stack(lanes, axis=1)
    narrow = jnp.concatenate(
        [_rows3(getattr(state, name)).astype(jnp.int16)
         for name, _, _ in narrow_rows], axis=1)
    wide = jnp.concatenate(
        [_rows3(getattr(state, name)).astype(jnp.int32)
         for name, _, _ in wide_rows], axis=1)
    return PackedFleet(bits=bits_plane, narrow=narrow, wide=wide,
                       rng_key=state.rng_key)


def _unpack_bits_row(packed: PackedFleet, name, bits, bias, slots):
    mask = jnp.uint32((1 << bits) - 1)
    cols = [
        (packed.bits[:, lane, :] >> jnp.uint32(off)) & mask
        for (lane, off) in slots
    ]
    v = jnp.stack(cols, axis=1)
    x = (v != 0) if name in _PACK_BOOL_FIELDS \
        else v.astype(jnp.int32) - bias
    return x[:, 0, :] if len(slots) == 1 else x


def _unpack_plane_row(plane: jnp.ndarray, count, off):
    x = plane[:, off:off + count, :].astype(jnp.int32)
    return x[:, 0, :] if count == 1 else x


def unpack_fleet(spec: Spec, packed: PackedFleet) -> NodeState:
    """Packed storage -> NodeState fleet; exact inverse of pack_fleet on
    every in-contract value (int16 sign-extension round-trips everything
    below 32768, including the NONE_ID sentinels)."""
    bit_rows, _, narrow_rows, _, wide_rows, _ = pack_plan(spec)
    out = {"rng_key": packed.rng_key}
    for name, bits, bias, slots in bit_rows:
        out[name] = _unpack_bits_row(packed, name, bits, bias, slots)
    for rows, plane in ((narrow_rows, packed.narrow),
                        (wide_rows, packed.wide)):
        for name, count, off in rows:
            out[name] = _unpack_plane_row(plane, count, off)
    return NodeState(**out)


def unpack_field(spec: Spec, packed: PackedFleet, name: str) -> jnp.ndarray:
    """ONE NodeState field off the packed storage, without materializing
    the whole unpacked fleet — the probe drivers use between timed
    dispatches (e.g. bench.py reading `commit` at 1M groups, where a
    full unpack is a multi-GB transient)."""
    if name == "rng_key":
        return packed.rng_key
    bit_rows, _, narrow_rows, _, wide_rows, _ = pack_plan(spec)
    for fname, bits, bias, slots in bit_rows:
        if fname == name:
            return _unpack_bits_row(packed, name, bits, bias, slots)
    for rows, plane in ((narrow_rows, packed.narrow),
                        (wide_rows, packed.wide)):
        for fname, count, off in rows:
            if fname == name:
                return _unpack_plane_row(plane, count, off)
    raise KeyError(name)


def state_bytes_per_group(spec: Spec, packed: bool = False) -> int:
    """Resident bytes per group (M nodes) of the fleet state in the given
    storage form, computed from the actual leaf dtypes/shapes — the number
    bench.py reports and the regression budget guards."""
    if packed:
        _, nb, _, ni, _, nw = pack_plan(spec)
        return spec.M * (nb * 4 + ni * 2 + nw * 4 + 2 * 4)
    import math

    sh = jax.eval_shape(
        lambda: init_node(spec, 0, jnp.zeros((spec.M,), jnp.bool_)))
    return spec.M * sum(
        math.prod(leaf.shape) * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(sh))
