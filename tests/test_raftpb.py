"""Host-side raftpb conf-change surface (raft/raftpb/confchange.go):
v1/v2 conversion, EnterJoint/LeaveJoint classification, marshalling round
trips, the string grammar, and the device-word bridge.
"""
import pytest

from etcd_tpu import raftpb as pb
from etcd_tpu.models import confchange as ccmod
from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    ENTRY_CONF_CHANGE,
    ENTRY_CONF_CHANGE_V2,
)


def test_v1_as_v2_and_marshal_type():
    cc = pb.ConfChange(CC_ADD_NODE, 3, b"ctx")
    v2 = cc.as_v2()
    assert v2.changes == (pb.ConfChangeSingle(CC_ADD_NODE, 3),)
    assert v2.context == b"ctx"
    typ, data = pb.marshal_conf_change(cc)
    assert typ == ENTRY_CONF_CHANGE
    rt = pb.unmarshal_conf_change(data)
    assert rt == cc


def test_v2_marshal_round_trip():
    v2 = pb.ConfChangeV2(
        changes=(
            pb.ConfChangeSingle(CC_ADD_NODE, 2),
            pb.ConfChangeSingle(CC_ADD_LEARNER, 3),
            pb.ConfChangeSingle(CC_REMOVE_NODE, 300),  # multi-byte varint
        ),
        transition=pb.TRANSITION_JOINT_EXPLICIT,
        context=b"\x00\xff payload",
    )
    typ, data = pb.marshal_conf_change(v2)
    assert typ == ENTRY_CONF_CHANGE_V2
    assert pb.unmarshal_conf_change(data) == v2


def test_enter_leave_joint_classification():
    one = pb.ConfChangeV2((pb.ConfChangeSingle(CC_ADD_NODE, 1),))
    assert one.enter_joint() == (False, False)  # simple protocol
    two = pb.ConfChangeV2(
        (pb.ConfChangeSingle(CC_ADD_NODE, 1),
         pb.ConfChangeSingle(CC_ADD_NODE, 2)),
    )
    assert two.enter_joint() == (True, True)  # auto -> autoleave joint
    explicit = pb.ConfChangeV2(
        one.changes, transition=pb.TRANSITION_JOINT_EXPLICIT
    )
    assert explicit.enter_joint() == (False, True)
    implicit = pb.ConfChangeV2(
        one.changes, transition=pb.TRANSITION_JOINT_IMPLICIT
    )
    assert implicit.enter_joint() == (True, True)
    assert pb.ConfChangeV2().leave_joint()
    assert pb.ConfChangeV2(context=b"x").leave_joint()  # context ignored
    assert not one.leave_joint()


def test_string_grammar_round_trip():
    ccs = pb.conf_changes_from_string("v1 l2 r3 u4")
    assert [c.node_id for c in ccs] == [1, 2, 3, 4]
    assert pb.conf_changes_to_string(ccs) == "v1 l2 r3 u4"
    with pytest.raises(ValueError, match="unknown input"):
        pb.conf_changes_from_string("x9")


def test_device_word_bridge():
    v2 = pb.ConfChangeV2(
        (pb.ConfChangeSingle(CC_ADD_NODE, 1),
         pb.ConfChangeSingle(CC_ADD_LEARNER, 2)),
    )
    w = pb.to_word(v2)
    assert w == ccmod.encode(
        [(CC_ADD_NODE, 1), (CC_ADD_LEARNER, 2)],
        enter_joint=True, auto_leave=True,
    )
    assert pb.to_word(pb.ConfChangeV2()) == ccmod.encode_leave_joint()
    three = pb.ConfChangeV2(
        tuple(pb.ConfChangeSingle(CC_ADD_NODE, i) for i in range(3))
    )
    with pytest.raises(ValueError, match="at most 2"):
        pb.to_word(three)
