"""Cluster version negotiation + downgrade machinery.

Host-side control plane, redesigned from the reference's:
  * server/etcdserver/version/monitor.go — Monitor (UpdateClusterVersionIfNeeded,
    CancelDowngradeIfNeeded, decideClusterVersion, versionsMatchTarget)
  * server/etcdserver/api/membership/downgrade.go — DowngradeInfo,
    isValidDowngrade, mustDetectDowngrade, AllowedDowngradeVersion
  * server/etcdserver/api/membership/cluster.go:709-724 — IsValidVersionChange
  * server/etcdserver/v3_server.go:901-990 — Downgrade VALIDATE/ENABLE/CANCEL

The decided cluster version and the downgrade record are REPLICATED state:
the leader proposes them through consensus ("cluster_version_set" /
"downgrade_info_set" request kinds, the ClusterVersionSetRequest /
DowngradeInfoSetRequest analogs) and every member applies them to its
MemberState, so mixed-version behavior survives crash/restart via the
applied_meta record. Only parsing/compare logic lives here; proposal and
apply live in kvserver.py.
"""
from __future__ import annotations

import dataclasses

# The local build's server version (version.Version analog). v3rpc's
# /version reports this as "etcdserver" and the negotiated cluster
# version as "etcdcluster".
SERVER_VERSION = "3.6.0-tpu.4"
# version.MinClusterVersion: the version a cluster starts at while member
# versions are still unknown.
MIN_CLUSTER_VERSION = "3.0.0"


def parse(v: str) -> tuple[int, int, int]:
    """\"major.minor.patch[-extra]\" -> (major, minor, patch). Raises
    ValueError on garbage (semver.NewVersion analog, no dependency)."""
    core = v.split("-", 1)[0].split("+", 1)[0]
    parts = core.split(".")
    if len(parts) != 3:
        raise ValueError(f"invalid semver {v!r}")
    return tuple(int(p) for p in parts)  # type: ignore[return-value]


def fmt(t: tuple[int, int, int]) -> str:
    return f"{t[0]}.{t[1]}.{t[2]}"


def major_minor(v: str) -> tuple[int, int, int]:
    """Truncate to major.minor (cluster versions always carry patch 0 —
    version.Cluster analog)."""
    ma, mi, _ = parse(v)
    return (ma, mi, 0)


def cluster_version_str(v: str) -> str:
    return fmt(major_minor(v))


@dataclasses.dataclass
class DowngradeInfo:
    """membership.DowngradeInfo: target version while a downgrade job is
    live; enabled=False <=> target_version == \"\"."""

    target_version: str = ""
    enabled: bool = False

    def to_dict(self) -> dict:
        return {"target-version": self.target_version, "enabled": self.enabled}

    @classmethod
    def from_dict(cls, d: dict | None) -> "DowngradeInfo":
        if not d:
            return cls()
        return cls(d.get("target-version", ""), bool(d.get("enabled", False)))


def allowed_downgrade_version(ver: str) -> str:
    """One minor below (AllowedDowngradeVersion, downgrade.go:77-80)."""
    ma, mi, _ = major_minor(ver)
    return fmt((ma, mi - 1, 0))


def is_valid_downgrade(ver_from: str, ver_to: str) -> bool:
    ma, mi, _ = major_minor(ver_from)
    if mi < 1:
        return False  # x.0 has no one-minor-down target
    return major_minor(ver_to) == (ma, mi - 1, 0)


def is_valid_version_change(cluster_ver: str, new_ver: str) -> bool:
    """IsValidVersionChange (cluster.go:709-724): the cluster version may
    move DOWN by exactly one minor (a live downgrade) or UP toward the
    min member version (normal negotiation at cluster start/upgrade)."""
    cv, nv = major_minor(cluster_ver), major_minor(new_ver)
    if is_valid_downgrade(fmt(cv), fmt(nv)):
        return True
    return cv[0] == nv[0] and cv < nv


class InvalidDowngrade(Exception):
    """mustDetectDowngrade's Fatal, surfaced as an exception: the member
    process must refuse to serve (downgrade.go:41-75)."""


def detect_downgrade(server_ver: str, cluster_ver: str | None,
                     d: DowngradeInfo | None) -> None:
    """Run at member boot/restart (mustDetectDowngrade): with a downgrade
    job live only target-version servers may join; without one a server
    older than the cluster version may not."""
    lv = major_minor(server_ver)
    if d is not None and d.enabled and d.target_version:
        if lv == major_minor(d.target_version):
            return
        raise InvalidDowngrade(
            f"server {server_ver} is not allowed to join while the cluster "
            f"downgrades to {d.target_version}"
        )
    if cluster_ver is not None and lv < major_minor(cluster_ver):
        raise InvalidDowngrade(
            f"server version {server_ver} is lower than the determined "
            f"cluster version {cluster_ver}"
        )


class VersionMonitor:
    """Leader-side monitor (monitor.go). ``server`` duck-types:
    get_cluster_version() -> str|None, get_downgrade_info() -> DowngradeInfo,
    get_versions() -> dict[member, {"server": str, "cluster": str}|None],
    update_cluster_version(str), downgrade_cancel(). The host driver calls
    update_cluster_version_if_needed()/cancel_downgrade_if_needed() on its
    monitor interval (the monitorVersions/monitorDowngrade goroutines'
    synchronous analog)."""

    def __init__(self, server):
        self.s = server

    def decide_cluster_version(self) -> str | None:
        """Min member server version, or None while any member's version
        is unknown (decideClusterVersion, monitor.go:91-126)."""
        vers = self.s.get_versions()
        cv: tuple[int, int, int] | None = None
        for _, ver in sorted(vers.items()):
            if ver is None:
                return None
            try:
                v = parse(ver["server"])
            except (ValueError, KeyError):
                return None
            if cv is None or v < cv:
                cv = v
        return fmt(cv) if cv is not None else None

    def update_cluster_version_if_needed(self) -> str | None:
        """Returns the version string it decided to propose (or None)."""
        v = self.decide_cluster_version()
        if v is not None:
            v = fmt(major_minor(v))
        cur = self.s.get_cluster_version()
        if cur is None:
            target = v if v is not None else MIN_CLUSTER_VERSION
            self.s.update_cluster_version(target)
            return target
        if v is not None and is_valid_version_change(cur, v):
            self.s.update_cluster_version(v)
            return v
        return None

    def versions_match_target(self, target: str) -> bool:
        """All members' CLUSTER versions equal the target (monitor.go:
        130-160) — the signal that the downgrade job finished."""
        want = major_minor(target)
        for _, ver in self.s.get_versions().items():
            if ver is None:
                return False
            try:
                if major_minor(ver["cluster"]) != want:
                    return False
            except (ValueError, KeyError):
                return False
        return True

    def cancel_downgrade_if_needed(self) -> bool:
        d = self.s.get_downgrade_info()
        if not d.enabled:
            return False
        if self.versions_match_target(d.target_version):
            self.s.downgrade_cancel()
            return True
        return False
