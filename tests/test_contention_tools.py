"""Contention detector (pkg/contention analog), proxy lease fan-in
(grpcproxy/lease.go), and the etcd-dump-metrics tool analog."""
from etcd_tpu.proxy import LeaseCoalescer
from etcd_tpu.utils.contention import TimeoutDetector


def test_timeout_detector_reports_late_observations():
    t = [0.0]
    td = TimeoutDetector(max_duration=1.0, clock=lambda: t[0])
    assert td.observe("tick") == (True, 0.0)   # first: no baseline
    t[0] = 0.9
    assert td.observe("tick") == (True, 0.0)   # on time
    t[0] = 3.0
    ok, exceeded = td.observe("tick")          # 2.1s gap, 1.1s late
    assert not ok and abs(exceeded - 1.1) < 1e-9
    assert td.late_total == 1 and abs(td.max_exceeded - 1.1) < 1e-9
    td.reset()
    t[0] = 10.0
    assert td.observe("tick") == (True, 0.0)   # history forgotten
    # independent keys don't blame each other (per-follower records,
    # raft.go:357 observes per ms[i].To)
    t[0] = 10.5
    assert td.observe("other") == (True, 0.0)


def test_lease_coalescer_one_upstream_per_interval():
    calls = []
    t = [0.0]

    def fake_call(path, q):
        calls.append((path, int(q["ID"])))
        return {"ID": q["ID"], "TTL": 30}

    lc = LeaseCoalescer(fake_call, clock=lambda: t[0])
    # 5 clients keep the same lease alive inside TTL/3 = 10s: ONE upstream
    for _ in range(5):
        r = lc.keepalive({"ID": 7})
        assert r["TTL"] == 30
    assert lc.upstream_sent == 1 and lc.coalesced == 4
    assert calls == [("/v3/lease/keepalive", 7)]
    # a different lease is its own stream
    lc.keepalive({"ID": 8})
    assert lc.upstream_sent == 2
    # past the refresh interval the upstream is refreshed again
    t[0] = 10.5
    lc.keepalive({"ID": 7})
    assert lc.upstream_sent == 3
    # revoke forgets the cache: next keepalive must hit upstream even
    # inside the window (no stale TTL for a dead lease)
    lc.forget(7)
    lc.keepalive({"ID": 7})
    assert lc.upstream_sent == 4


def test_dump_metrics_enumerates_registry():
    from etcd_tpu.dump import dump_metrics
    from etcd_tpu.server.kvserver import EtcdCluster

    ec = EtcdCluster(n_members=1)
    lines = dump_metrics(ec)
    names = {ln.split()[0] for ln in lines}
    assert "etcd_tpu_groups" in names
    assert "etcd_tpu_ticker_late_total" in names
    assert "etcd_tpu_ticker_late_max_seconds" in names
    assert all(len(ln.split()) == 2 for ln in lines)
