"""CLI shell: the etcdmain analog (server/etcdmain/main.go:25,
etcd.go:52) — parse flags into an embed.Config, start the server, serve
until interrupted.

Usage:
    python -m etcd_tpu.etcdmain --listen-client-port 2379 \
        --data-dir /tmp/etcd-tpu --cluster-size 3
"""
from __future__ import annotations

import argparse
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="etcd-tpu",
        description="TPU-native batched etcd: serve the v3 JSON/HTTP API "
        "over one simulated multi-member cluster",
    )
    p.add_argument("--name", default="default")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--listen-client-host", default="127.0.0.1")
    p.add_argument("--listen-client-port", type=int, default=2379)
    p.add_argument("--cluster-size", type=int, default=3)
    p.add_argument("--heartbeat-interval", type=int, default=100,
                   metavar="MS", dest="tick_ms")
    p.add_argument("--election-timeout", type=int, default=1000,
                   metavar="MS")
    p.add_argument("--quota-backend-bytes", type=int, default=0)
    p.add_argument("--auto-compaction-mode", default="off",
                   choices=("off", "periodic", "revision"))
    p.add_argument("--auto-compaction-retention", type=int, default=0)
    p.add_argument("--pre-vote", action=argparse.BooleanOptionalAction,
                   default=True)
    # transport security (etcdmain --cert-file family, config.go
    # ClientTLSInfo + ClientAutoTLS)
    p.add_argument("--cert-file", default=None,
                   help="server TLS cert; enables HTTPS")
    p.add_argument("--key-file", default=None,
                   help="key for --cert-file")
    p.add_argument("--trusted-ca-file", default=None,
                   help="CA bundle for verifying client certs")
    p.add_argument("--client-cert-auth", action="store_true",
                   help="require CA-verified client certs; the cert CN "
                   "is accepted as the user identity")
    p.add_argument("--auto-tls", action="store_true",
                   help="self-signed TLS under data-dir/fixtures/client")
    p.add_argument("--unsafe-no-fsync", action="store_true",
                   help="skip fsync-before-ack (may lose acknowledged "
                   "writes on crash)")
    # cluster bootstrap via a discovery service (etcdmain --discovery):
    # "<gateway-url>/<token>"; cluster size comes from the token's
    # _config/size record (v2discovery)
    p.add_argument("--discovery", default=None)
    # v2 proxy mode (startEtcdOrProxyV2's startProxy branch): serve a
    # failover reverse proxy over the listed endpoints instead of a
    # cluster
    p.add_argument("--proxy", choices=["off", "on"], default="off")
    p.add_argument("--proxy-endpoints", default="",
                   help="comma list of gateway URLs to proxy")
    p.add_argument("--proxy-cacert", default=None,
                   help="CA bundle for verifying HTTPS proxy upstreams")
    p.add_argument("--proxy-failure-wait", type=float, default=5.0)
    p.add_argument("--proxy-refresh-interval", type=float, default=30.0)
    return p


def run_proxy(args) -> int:
    """httpproxy mode: forward every request to the first available
    endpoint (proxy/httpproxy NewHandler + etcdmain startProxy)."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from etcd_tpu.httpproxy import Director, HTTPProxy, make_urllib_transport

    tls = None
    if args.proxy_cacert:
        from etcd_tpu.transport import TLSInfo

        tls = TLSInfo(trusted_ca_file=args.proxy_cacert)
    urls = [u for u in args.proxy_endpoints.split(",") if u]
    if args.discovery and not urls:
        base, token = args.discovery.rsplit("/", 1)
        from etcd_tpu import clientv2, discovery

        # the discovery bootstrap dial trusts the same CA as the
        # upstream forwards — an HTTPS discovery service behind a
        # private CA must not fall back to the system trust store
        keys = clientv2.new(base, tls=tls).keys
        cluster = discovery.Discovery(keys, token, "proxy").get_cluster()
        urls = [part.split("=", 1)[1] for part in cluster.split(",")]
    d = Director(lambda: urls, args.proxy_failure_wait,
                 args.proxy_refresh_interval)
    proxy = HTTPProxy(d, make_urllib_transport(tls))

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _handle(self):
            from urllib.parse import parse_qsl, urlsplit

            form = dict(parse_qsl(urlsplit(self.path).query,
                                  keep_blank_values=True))
            n = int(self.headers.get("Content-Length", "0") or 0)
            if n:
                form.update(parse_qsl(self.rfile.read(n).decode(),
                                      keep_blank_values=True))
            st, body, hdr = proxy.handle(
                self.command, urlsplit(self.path).path, form)
            blob = json.dumps(body).encode()
            self.send_response(st)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            for k, v in hdr.items():
                if k.lower().startswith("x-etcd"):
                    self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(blob)

        do_GET = do_PUT = do_POST = do_DELETE = _handle

    httpd = ThreadingHTTPServer(
        (args.listen_client_host, args.listen_client_port), Handler)
    print(f"etcd-tpu proxy serving "
          f"http://{args.listen_client_host}:"
          f"{httpd.server_address[1]} -> {urls}", file=sys.stderr)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return 0


def main(argv=None) -> int:
    # honor an explicit JAX_PLATFORMS request (this environment's
    # sitecustomize re-pins the accelerator platform at interpreter
    # start, so the env var alone is not enough) and reuse the repo's
    # persistent compile cache for fast process starts
    from etcd_tpu.utils.cache import entrypoint_platform_setup

    entrypoint_platform_setup()

    from etcd_tpu.embed import Config, start_etcd

    args = build_parser().parse_args(argv)
    if args.proxy == "on":
        return run_proxy(args)
    cluster_size = args.cluster_size
    if args.discovery:
        # join the discovery token before boot (etcd.go startEtcd's
        # discovery branch): the token's size record decides the
        # cluster size every joiner agrees on
        from etcd_tpu import clientv2, discovery

        base, token = args.discovery.rsplit("/", 1)
        keys = clientv2.new(base).keys
        d = discovery.Discovery(keys, token, args.name)
        cluster_str = d.join_cluster(
            f"{args.name}=http://{args.listen_client_host}:"
            f"{args.listen_client_port}")
        cluster_size = len(cluster_str.split(","))
        print(f"discovery: joined cluster [{cluster_str}]",
              file=sys.stderr)
    client_tls = None
    if args.cert_file or args.key_file or args.trusted_ca_file or \
            args.client_cert_auth:
        # ANY tls flag builds the TLSInfo so half-configurations fail
        # startup loudly instead of silently serving plaintext
        from etcd_tpu.transport import TLSInfo

        client_tls = TLSInfo(
            cert_file=args.cert_file or "",
            key_file=args.key_file or "",
            trusted_ca_file=args.trusted_ca_file or "",
            client_cert_auth=args.client_cert_auth,
        )
    cfg = Config(
        name=args.name,
        data_dir=args.data_dir,
        listen_client_host=args.listen_client_host,
        listen_client_port=args.listen_client_port,
        cluster_size=cluster_size,
        tick_ms=args.tick_ms,
        election_ticks=max(args.election_timeout // max(args.tick_ms, 1), 2),
        quota_backend_bytes=args.quota_backend_bytes,
        auto_compaction_mode=args.auto_compaction_mode,
        auto_compaction_retention=args.auto_compaction_retention,
        pre_vote=args.pre_vote,
        client_tls=client_tls,
        client_auto_tls=args.auto_tls,
        unsafe_no_fsync=args.unsafe_no_fsync,
    )
    etcd = start_etcd(cfg)
    print(f"etcd-tpu '{cfg.name}' serving {etcd.client_url} "
          f"({cfg.cluster_size} members)", file=sys.stderr)
    try:
        # race-free: sigwait atomically blocks for either signal
        signal.sigwait({signal.SIGINT, signal.SIGTERM})
    finally:
        etcd.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
