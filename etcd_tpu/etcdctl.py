"""etcdctl analog: a user CLI speaking the v3 JSON/HTTP API.

Mirrors the reference's etcdctl command surface (etcdctl/ctlv3/command)
over the gateway endpoints served by etcd_tpu.server.v3rpc: get / put /
del / txn / watch / lease / member / endpoint status / alarm / compaction
/ snapshot save / elect / lock / auth / user / role.

Usage:
    python -m etcd_tpu.etcdctl --endpoint http://127.0.0.1:2379 put k v
    python -m etcd_tpu.etcdctl get k --prefix
"""
from __future__ import annotations

import argparse
import base64
import json
import sys


class Ctl:
    """Thin CLI boundary over client.RemoteClient: one wire transport,
    with gateway errors translated to exit-code-1 SystemExit the way a
    CLI reports them."""

    def __init__(self, endpoint: str, token: str | None = None,
                 tls=None):
        from etcd_tpu.client import RemoteClient

        # transport.TLSInfo (or a prebuilt ssl.SSLContext) for https
        # endpoints — --cacert/--cert/--key (ctlv3 global flags).
        # timeout=None: CLI ops (snapshot save, long txns) block like
        # the reference ctl rather than dying at an arbitrary 10s.
        self._rc = RemoteClient(endpoint, token=token, tls=tls,
                                timeout=None)

    @property
    def token(self):
        return self._rc.token

    @token.setter
    def token(self, tok):
        self._rc.token = tok

    def call(self, path: str, body: dict) -> dict:
        import urllib.error

        from etcd_tpu.client import RemoteError

        try:
            return self._rc.call(path, body)
        except RemoteError as e:
            raise SystemExit(f"Error: {e}") from None
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            # connection failures are CLI errors, not tracebacks
            raise SystemExit(f"Error: {e}") from None

    def get_http(self, path: str) -> bytes:
        import urllib.error

        try:
            return self._rc.get_raw(path)
        except urllib.error.HTTPError as e:
            # /health answers 503 with {"health":"false",...} when
            # leaderless — that body IS the answer, not a traceback
            return e.read()
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            raise SystemExit(f"Error: {e}") from None


def b64(s: str | bytes) -> str:
    if isinstance(s, str):
        s = s.encode()
    return base64.b64encode(s).decode()


def unb64(s: str | None) -> str:
    return base64.b64decode(s).decode(errors="replace") if s else ""


def _print_kvs(res: dict, write=print) -> None:
    for kv in res.get("kvs", []):
        write(unb64(kv["key"]))
        write(unb64(kv.get("value")))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etcdctl-tpu")
    p.add_argument("--endpoint", default="http://127.0.0.1:2379")
    p.add_argument("--user", default=None, help="name:password")
    # TLS global flags (ctlv3 --cacert/--cert/--key/
    # --insecure-skip-tls-verify)
    p.add_argument("--cacert", default=None,
                   help="verify the server cert against this CA bundle")
    p.add_argument("--cert", default=None, dest="tls_cert",
                   help="client TLS cert (mutual TLS / cert-CN auth)")
    # dest must NOT be "key": nearly every subcommand has a `key`
    # positional that would clobber the path
    p.add_argument("--key", default=None, dest="tls_key",
                   help="key for --cert")
    p.add_argument("--insecure-skip-tls-verify", action="store_true",
                   help="skip server cert verification (testing only)")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get")
    g.add_argument("key")
    g.add_argument("range_end", nargs="?")
    g.add_argument("--prefix", action="store_true")
    g.add_argument("--rev", type=int, default=0)
    g.add_argument("--limit", type=int, default=0)
    g.add_argument("--count-only", action="store_true")

    pu = sub.add_parser("put")
    pu.add_argument("key")
    pu.add_argument("value")
    pu.add_argument("--lease", type=int, default=0)

    d = sub.add_parser("del")
    d.add_argument("key")
    d.add_argument("range_end", nargs="?")
    d.add_argument("--prefix", action="store_true")

    t = sub.add_parser("txn", help="JSON txn body on stdin")

    w = sub.add_parser("watch")
    w.add_argument("key")
    w.add_argument("--prefix", action="store_true")
    w.add_argument("--rev", type=int, default=0)
    w.add_argument("--polls", type=int, default=1)

    lease = sub.add_parser("lease")
    lsub = lease.add_subparsers(dest="lease_cmd", required=True)
    lg = lsub.add_parser("grant"); lg.add_argument("id", type=int); lg.add_argument("ttl", type=int)
    lr = lsub.add_parser("revoke"); lr.add_argument("id", type=int)
    lk = lsub.add_parser("keep-alive"); lk.add_argument("id", type=int)
    lt = lsub.add_parser("timetolive"); lt.add_argument("id", type=int)
    lsub.add_parser("list")

    mem = sub.add_parser("member")
    msub = mem.add_subparsers(dest="member_cmd", required=True)
    ma = msub.add_parser("add"); ma.add_argument("id", type=int); ma.add_argument("--learner", action="store_true")
    mr = msub.add_parser("remove"); mr.add_argument("id", type=int)
    mp = msub.add_parser("promote"); mp.add_argument("id", type=int)
    msub.add_parser("list")

    ep = sub.add_parser("endpoint")
    esub = ep.add_subparsers(dest="ep_cmd", required=True)
    esub.add_parser("status")
    esub.add_parser("health")
    esub.add_parser("hashkv")

    al = sub.add_parser("alarm")
    al.add_argument("alarm_cmd", choices=("list", "disarm"))

    cp = sub.add_parser("compaction")
    cp.add_argument("rev", type=int)

    sn = sub.add_parser("snapshot")
    ssub = sn.add_subparsers(dest="snap_cmd", required=True)
    sv = ssub.add_parser("save"); sv.add_argument("path")

    el = sub.add_parser("elect")
    el.add_argument("name")
    el.add_argument("value", nargs="?")
    el.add_argument("--lease", type=int, default=0)
    el.add_argument("--listen", action="store_true", help="print the leader")

    lk2 = sub.add_parser("lock")
    lk2.add_argument("name")
    lk2.add_argument("--lease", type=int, default=0)

    au = sub.add_parser("auth")
    au.add_argument("auth_cmd", choices=("enable", "disable"))

    # etcdctl downgrade validate/enable/cancel (ctlv3/command/downgrade.go)
    dg = sub.add_parser("downgrade")
    dg.add_argument("downgrade_cmd", choices=("validate", "enable", "cancel"))
    dg.add_argument("target_version", nargs="?")

    us = sub.add_parser("user")
    usub = us.add_subparsers(dest="user_cmd", required=True)
    ua = usub.add_parser("add"); ua.add_argument("name"); ua.add_argument("password")
    ud = usub.add_parser("delete"); ud.add_argument("name")
    ug = usub.add_parser("grant-role"); ug.add_argument("name"); ug.add_argument("role")

    ro = sub.add_parser("role")
    rsub = ro.add_subparsers(dest="role_cmd", required=True)
    ra = rsub.add_parser("add"); ra.add_argument("name")
    rg = rsub.add_parser("grant-permission")
    rg.add_argument("name"); rg.add_argument("perm_type",
                                             choices=("read", "write", "readwrite"))
    rg.add_argument("key"); rg.add_argument("range_end", nargs="?")

    # legacy v2 family (etcdctl/ctlv2 command surface)
    v2 = sub.add_parser("v2", help="legacy v2 commands over /v2/keys")
    v2sub = v2.add_subparsers(dest="v2_cmd", required=True)
    v2g = v2sub.add_parser("get"); v2g.add_argument("key")
    v2s = v2sub.add_parser("set"); v2s.add_argument("key")
    v2s.add_argument("value"); v2s.add_argument("--ttl", type=int)
    v2mk = v2sub.add_parser("mk"); v2mk.add_argument("key")
    v2mk.add_argument("value")
    v2md = v2sub.add_parser("mkdir"); v2md.add_argument("key")
    v2ls = v2sub.add_parser("ls"); v2ls.add_argument("key", nargs="?",
                                                    default="/")
    v2ls.add_argument("--recursive", action="store_true")
    v2rm = v2sub.add_parser("rm"); v2rm.add_argument("key")
    v2rm.add_argument("--recursive", action="store_true")
    v2rd = v2sub.add_parser("rmdir"); v2rd.add_argument("key")
    v2u = v2sub.add_parser("update"); v2u.add_argument("key")
    v2u.add_argument("value")

    args = p.parse_args(argv)
    tls = None
    if args.cacert or args.tls_cert or args.tls_key or \
            args.insecure_skip_tls_verify:
        from etcd_tpu.transport import TLSInfo

        tls = TLSInfo(
            trusted_ca_file=args.cacert or "",
            client_cert_file=args.tls_cert or "",
            client_key_file=args.tls_key or "",
            insecure_skip_verify=args.insecure_skip_tls_verify,
        )
    ctl = Ctl(args.endpoint, tls=tls)
    if args.user:
        name, _, pw = args.user.partition(":")
        ctl.token = ctl.call("/v3/auth/authenticate",
                             {"name": name, "password": pw})["token"]

    def range_end_of(key: str, range_end, prefix: bool):
        if range_end:
            return b64(range_end)
        if prefix:
            k = key.encode()
            end = bytearray(k)
            for i in reversed(range(len(end))):
                if end[i] < 0xFF:
                    end[i] += 1
                    return b64(bytes(end[: i + 1]))
            return b64(b"\x00")
        return None

    c = args.cmd
    if c == "get":
        body = {"key": b64(args.key), "revision": args.rev,
                "limit": args.limit, "count_only": args.count_only}
        re_ = range_end_of(args.key, args.range_end, args.prefix)
        if re_:
            body["range_end"] = re_
        res = ctl.call("/v3/kv/range", body)
        if args.count_only:
            print(res.get("count", "0"))
        else:
            _print_kvs(res)
    elif c == "put":
        ctl.call("/v3/kv/put", {"key": b64(args.key), "value": b64(args.value),
                                "lease": args.lease})
        print("OK")
    elif c == "del":
        body = {"key": b64(args.key)}
        re_ = range_end_of(args.key, args.range_end, args.prefix)
        if re_:
            body["range_end"] = re_
        res = ctl.call("/v3/kv/deleterange", body)
        print(res.get("deleted", "0"))
    elif c == "txn":
        print(json.dumps(ctl.call("/v3/kv/txn", json.load(sys.stdin))))
    elif c == "watch":
        body = {"create_request": {"key": b64(args.key),
                                   "start_revision": args.rev}}
        re_ = range_end_of(args.key, None, args.prefix)
        if re_:
            body["create_request"]["range_end"] = re_
        wid = ctl.call("/v3/watch", body)["watch_id"]
        for _ in range(args.polls):
            res = ctl.call("/v3/watch", {"poll_request": {"watch_id": wid}})
            for ev in res.get("events", []):
                print(ev["type"])
                print(unb64(ev["kv"]["key"]))
                print(unb64(ev["kv"].get("value")))
        ctl.call("/v3/watch", {"cancel_request": {"watch_id": wid}})
    elif c == "lease":
        lc = args.lease_cmd
        if lc == "grant":
            res = ctl.call("/v3/lease/grant", {"ID": args.id, "TTL": args.ttl})
            print(f"lease {res['ID']} granted with TTL({res['TTL']}s)")
        elif lc == "revoke":
            ctl.call("/v3/lease/revoke", {"ID": args.id})
            print(f"lease {args.id} revoked")
        elif lc == "keep-alive":
            res = ctl.call("/v3/lease/keepalive", {"ID": args.id})
            print(f"lease {res['ID']} keepalived with TTL({res['TTL']})")
        elif lc == "timetolive":
            res = ctl.call("/v3/lease/timetolive", {"ID": args.id})
            print(f"lease {res['ID']} remaining ttl {res['TTL']}")
        else:
            for l in ctl.call("/v3/lease/leases", {}).get("leases", []):
                print(l["ID"])
    elif c == "downgrade":
        body = {"action": args.downgrade_cmd.upper()}
        if args.target_version:
            body["version"] = args.target_version
        res = ctl.call("/v3/maintenance/downgrade", body)
        print(f"cluster version {res['version']}; "
              f"downgrade {args.downgrade_cmd} OK")
    elif c == "member":
        mc = args.member_cmd
        if mc == "add":
            ctl.call("/v3/cluster/member/add",
                     {"ID": args.id, "is_learner": args.learner})
            print(f"Member {args.id} added")
        elif mc == "remove":
            ctl.call("/v3/cluster/member/remove", {"ID": args.id})
            print(f"Member {args.id} removed")
        elif mc == "promote":
            ctl.call("/v3/cluster/member/promote", {"ID": args.id})
            print(f"Member {args.id} promoted")
        else:
            for m in ctl.call("/v3/cluster/member/list", {}).get("members", []):
                kind = "learner" if m.get("is_learner") else "voter"
                print(f"{m['ID']}: {kind}")
    elif c == "endpoint":
        if args.ep_cmd == "status":
            print(json.dumps(ctl.call("/v3/maintenance/status", {})))
        elif args.ep_cmd == "health":
            body = ctl.get_http("/health").decode().strip()
            print(body)
            try:
                parsed = json.loads(body)
                healthy = isinstance(parsed, dict) and \
                    parsed.get("health") == "true"
            except json.JSONDecodeError:
                healthy = False
            if not healthy:
                # scripts gate on the exit code (`endpoint health &&
                # deploy`), like the reference ctl
                return 1
        else:
            print(ctl.call("/v3/maintenance/hash", {})["hash"])
    elif c == "alarm":
        if args.alarm_cmd == "list":
            res = ctl.call("/v3/maintenance/alarm", {"action": "GET"})
        else:
            res = ctl.call("/v3/maintenance/alarm", {"action": "DEACTIVATE"})
        for a in res.get("alarms", []):
            print(a["alarm"])
    elif c == "compaction":
        ctl.call("/v3/kv/compaction", {"revision": args.rev})
        print(f"compacted revision {args.rev}")
    elif c == "snapshot":
        blob = ctl.call("/v3/maintenance/snapshot", {})["blob"]
        with open(args.path, "wb") as f:
            f.write(base64.b64decode(blob))
        print(f"Snapshot saved at {args.path}")
    elif c == "elect":
        if args.listen or args.value is None:
            res = ctl.call("/v3/election/leader", {"name": b64(args.name)})
            print(unb64(res["kv"]["value"]))
        else:
            res = ctl.call(
                "/v3/election/campaign",
                {"name": b64(args.name), "value": b64(args.value),
                 "lease": args.lease},
            )
            print(unb64(res["leader"]["key"]))
    elif c == "lock":
        res = ctl.call("/v3/lock/lock",
                       {"name": b64(args.name), "lease": args.lease})
        print(unb64(res["key"]))
    elif c == "auth":
        ctl.call(f"/v3/auth/{args.auth_cmd}", {})
        print(f"Authentication {'Enabled' if args.auth_cmd == 'enable' else 'Disabled'}")
    elif c == "user":
        uc = args.user_cmd
        if uc == "add":
            ctl.call("/v3/auth/user/add",
                     {"name": args.name, "password": args.password})
            print(f"User {args.name} created")
        elif uc == "delete":
            ctl.call("/v3/auth/user/delete", {"name": args.name})
            print(f"User {args.name} deleted")
        else:
            ctl.call("/v3/auth/user/grant",
                     {"name": args.name, "role": args.role})
            print(f"Role {args.role} is granted to user {args.name}")
    elif c == "role":
        rc = args.role_cmd
        if rc == "add":
            ctl.call("/v3/auth/role/add", {"name": args.name})
            print(f"Role {args.name} created")
        else:
            perm = {"permType": args.perm_type.upper(), "key": b64(args.key)}
            if args.range_end:
                perm["range_end"] = b64(args.range_end)
            ctl.call("/v3/auth/role/grant", {"name": args.name, "perm": perm})
            print(f"Role {args.name} updated")
    elif c == "v2":
        from etcd_tpu import clientv2

        cli = clientv2.new(args.endpoint, tls=tls)
        vc = args.v2_cmd
        try:
            if vc == "get":
                print(cli.keys.get(args.key).node.get("value", ""))
            elif vc == "set":
                r = cli.keys.set(args.key, args.value, ttl=args.ttl)
                print(r.node.get("value", ""))
            elif vc == "mk":
                r = cli.keys.create(args.key, args.value)
                print(r.node.get("value", ""))
            elif vc == "mkdir":
                cli.keys.set(args.key, None, dir=True,
                             prev_exist=clientv2.PREV_NO_EXIST)
                print("")
            elif vc == "ls":
                r = cli.keys.get(args.key, recursive=args.recursive,
                                 sort=True)
                def walk(n):
                    for ch in n.get("nodes", []):
                        print(ch["key"] + ("/" if ch.get("dir") else ""))
                        walk(ch)
                walk(r.node)
            elif vc == "rm":
                cli.keys.delete(args.key, recursive=args.recursive)
                print(f"PrevNode.Value: deleted {args.key}")
            elif vc == "rmdir":
                cli.keys.delete(args.key, dir=True)
                print("")
            elif vc == "update":
                print(cli.keys.update(args.key, args.value)
                      .node.get("value", ""))
        except clientv2.Error as e:
            print(f"Error: {e.code}: {e.message} ({e.cause}) "
                  f"[{e.index}]", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    from etcd_tpu.utils.cache import entrypoint_platform_setup

    entrypoint_platform_setup()
    sys.exit(main())
