"""Deterministic failpoints — the gofail analog.

The reference compiles crash markers into the hot path
(`// gofail: var raftBeforeSave struct{}` at
server/etcdserver/raft.go:221,228,235,242,256,301, enabled by
FAILPOINTS=1 builds) and the functional tester trips them over HTTP
(tests/functional/tester/case_failpoints.go:207). Here a failpoint is a
named site in the host pipeline; enabling it with the "panic" action makes
the next passage raise :class:`FailpointPanic`, which tests treat as the
process dying at exactly that boundary. Activation comes from the
programmatic API or the ``ETCD_TPU_FAILPOINTS`` env var
(``name=panic;other=off`` — gofail's GOFAIL_FAILPOINTS wire format).

Registered sites (kvserver/backend analogs of the reference markers):
  raftBeforeSave      before the apply batch's MVCC delta hits the backend
  raftAfterSave       after the atomic applied-meta record is staged
  backendBeforeCommit before the backend's fsync'd batch commit
  backendAfterCommit  after it
  raftBeforeApplySnap before installing a peer state snapshot
  raftAfterApplySnap  after it
"""
from __future__ import annotations

import threading


class FailpointPanic(Exception):
    """The 'process' died at a failpoint (gofail panic action)."""

    def __init__(self, name: str):
        super().__init__(f"failpoint {name} triggered")
        self.name = name


_lock = threading.Lock()
_active: dict[str, str] = {}
_hits: dict[str, int] = {}

KNOWN = (
    "raftBeforeSave",
    "raftAfterSave",
    "backendBeforeCommit",
    "backendAfterCommit",
    "raftBeforeApplySnap",
    "raftAfterApplySnap",
)


def _load_env() -> None:
    from etcd_tpu.utils.knobs import env_str

    spec = env_str("failpoints", "ETCD_TPU_FAILPOINTS", "")
    for part in spec.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, action = part.split("=", 1)
        if action != "off":
            _active[name] = action


_load_env()


def enable(name: str, action: str = "panic", count: int = 0) -> None:
    """Arm a failpoint. `count` > 0 = trigger only on the count-th passage
    (gofail's `N*panic` terms collapse to this)."""
    with _lock:
        _active[name] = action
        _hits[name] = -(count - 1) if count > 0 else 0


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)
        _hits.pop(name, None)


def clear() -> None:
    with _lock:
        _active.clear()
        _hits.clear()


def enabled(name: str) -> bool:
    return name in _active


def fire(name: str) -> None:
    """Marker call placed at the instrumented site. No-op unless armed."""
    with _lock:
        action = _active.get(name)
        if action is None:
            return
        hits = _hits.get(name, 0) + 1
        _hits[name] = hits
        if hits <= 0:  # armed with a count that hasn't elapsed yet
            return
        if action == "panic":
            # one-shot, like a dead process: re-arm explicitly to fire again
            _active.pop(name, None)
            _hits.pop(name, None)
            raise FailpointPanic(name)
        # other actions (e.g. "sleep(...)"/"print") are accepted but inert
