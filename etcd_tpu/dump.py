"""Offline inspection tools — tools/etcd-dump-db and tools/etcd-dump-logs
analogs.

`dump-db` walks a backend file's buckets/keys (the bbolt inspector:
tools/etcd-dump-db/backend.go — list buckets, iterate a bucket, decode the
key bucket's revision records); `dump-logs` prints a WAL directory's
records in order (tools/etcd-dump-logs/main.go — metadata, hardstates,
snapshots, entries with type/term/index).

Usage:
    python -m etcd_tpu.dump db list-bucket <file.db>
    python -m etcd_tpu.dump db iterate-bucket <file.db> <bucket> [--decode]
    python -m etcd_tpu.dump logs <wal-dir>
"""
from __future__ import annotations

import argparse
import json
import sys


def dump_db_buckets(path: str) -> list[str]:
    from etcd_tpu.storage.backend import Backend

    be = Backend(path)
    try:
        return sorted(be.data.keys())
    finally:
        be.close()


def dump_db_bucket(path: str, bucket: str, decode: bool = False):
    """Yield (key, value-summary) pairs; with decode, revision records in the
    key bucket pretty-print like dump-db's --decode keyDecoder."""
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    be = Backend(path)
    try:
        for k, v in sorted(be.data.get(bucket, {}).items()):
            if decode and bucket == schema.KEY_BUCKET:
                main, sub = schema.bytes_to_rev(k)
                kv, tomb = schema._dec_kv(v)
                yield (
                    f"rev={{{main}/{sub}}}",
                    {
                        "key": kv.key.decode("latin1"),
                        "value": kv.value.decode("latin1"),
                        "create_revision": kv.create_revision,
                        "mod_revision": kv.mod_revision,
                        "version": kv.version,
                        "lease": kv.lease,
                        "tombstone": tomb,
                    },
                )
            else:
                yield (repr(k), f"{len(v)} bytes")
    finally:
        be.close()


def dump_logs(wal_dir: str) -> dict:
    """Replay a WAL directory and summarize its records
    (etcd-dump-logs: WAL metadata + snapshot + hardstate + entries)."""
    from etcd_tpu.storage.wal import WAL

    w = WAL(wal_dir)
    metadata, hardstate, entries, snapshot = w.read_all()
    w.close()
    return {
        "metadata": metadata.decode("latin1") if metadata else "",
        "snapshot": snapshot,
        "hardstate": hardstate,
        "entry_count": len(entries),
        "entries": [
            {
                "index": e["index"],
                "term": e["term"],
                "type": "conf-change" if e.get("type") else "normal",
                "data": e["data"],
            }
            for e in entries
        ],
    }


def dump_metrics(ec=None) -> list[str]:
    """tools/etcd-dump-metrics analog: enumerate the full metrics
    exposition of a (fresh, if none given) cluster — the reference tool
    boots an etcd instance and scrapes /metrics to document every metric
    name with a default value."""
    from etcd_tpu.models.metrics import fleet_summary

    if ec is None:
        from etcd_tpu.server.kvserver import EtcdCluster

        ec = EtcdCluster(n_members=1)  # in-process; no teardown needed
    s = fleet_summary(ec.cl.s)
    flat: dict = {}
    for k, v in s.items():
        if isinstance(v, dict):  # e.g. roles -> roles_follower etc.
            for k2, v2 in v.items():
                flat[f"{k}_{k2}"] = v2
        else:
            flat[k] = v
    lines = [f"etcd_tpu_{k} {v}" for k, v in sorted(flat.items())]
    td = getattr(ec, "contention", None)
    lines.append(
        f"etcd_tpu_ticker_late_total {td.late_total if td else 0}"
    )
    lines.append(
        "etcd_tpu_ticker_late_max_seconds "
        f"{td.max_exceeded if td else 0.0:.6f}"
    )
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etcd-dump-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    db = sub.add_parser("db")
    dsub = db.add_subparsers(dest="db_cmd", required=True)
    lb = dsub.add_parser("list-bucket")
    lb.add_argument("path")
    ib = dsub.add_parser("iterate-bucket")
    ib.add_argument("path")
    ib.add_argument("bucket")
    ib.add_argument("--decode", action="store_true")

    lg = sub.add_parser("logs")
    lg.add_argument("wal_dir")

    sub.add_parser("metrics")  # etcd-dump-metrics analog

    args = p.parse_args(argv)
    if args.cmd == "metrics":
        for line in dump_metrics():
            print(line)
        return 0
    if args.cmd == "db":
        if args.db_cmd == "list-bucket":
            for b in dump_db_buckets(args.path):
                print(b)
        else:
            for k, v in dump_db_bucket(args.path, args.bucket, args.decode):
                print(f"{k} -> {json.dumps(v) if isinstance(v, dict) else v}")
    else:
        print(json.dumps(dump_logs(args.wal_dir), indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
