"""Fleet telemetry plane (ISSUE 9): bit-identity, host-replay
cross-checks, the chaos flight recorder, knob validation and the
Prometheus exposition round trip.

The load-bearing contract is the first one: telemetry RIDES BESIDE the
fleet state and never feeds back, so a telemetry-on round must
reproduce the telemetry-off round BIT-FOR-BIT in state and wire — over
the rich full-program scenario (elections / partitions / snapshot
fallback / read-index / ticks, the test_packed_state scenario) and
under the PR-8 diet forms (packed_state, sparse_outbox). The histogram
MATH is then cross-checked against an independent numpy replay of the
recorded state trajectory at small C.
"""
import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import jax
import pytest

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.models.metrics import build_metered_round, zero_metrics
from etcd_tpu.models.state import NodeState, unpack_fleet, pack_fleet
from etcd_tpu.models.telemetry import (
    FleetTelemetry,
    flight_record,
    hist_percentile,
    init_telemetry,
    pow2_edges,
    prometheus_parse,
    prometheus_render,
    telemetry_report,
    telemetry_update,
)
from etcd_tpu.types import (
    ENTRY_NORMAL,
    MSG_APP,
    MSG_APP_RESP,
    MSG_PROP,
    ROLE_CANDIDATE,
    ROLE_LEADER,
    ROLE_PRE_CANDIDATE,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the test_packed_state rich-scenario geometry: elections, a partition
# window long enough for snapshot fallback, a read-index wave, ticks
SPEC = Spec(M=3, L=16, E=1, K=2, W=2, R=2, A=2)
CFG = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2,
                 inbox_bound=4)
C = 16
ROUNDS = 48


def _inputs(r: int):
    M, E = SPEC.M, SPEC.E
    hup = np.zeros((M, C), bool)
    if r == 0:
        for c in range(C):
            hup[c % M, c] = True
    plen = np.zeros((M, C), np.int32)
    pdata = np.zeros((M, E, C), np.int32)
    ptype = np.zeros((M, E, C), np.int32)
    if 2 <= r < ROUNDS - 10:
        plen[0, :] = 1
        pdata[0, 0, :] = r * 64 + np.arange(C)
        ptype[0, 0, :] = ENTRY_NORMAL
    ri = np.zeros((M, C), np.int32)
    if r == 24:
        ri[0, :] = 7
    keep = np.ones((M, M, C), bool)
    if 8 <= r < 18:
        keep[1, :, 4:8] = False
        keep[:, 1, 4:8] = False
    tick = np.full((M, C), r % 3 == 0 or r >= ROUNDS - 8, bool)
    return plen, pdata, ptype, ri, hup, tick, keep


def _assert_states_equal(a: NodeState, b: NodeState, label: str, r: int):
    for name in NodeState.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        ), f"{label}: state.{name} diverged at round {r}"


@pytest.fixture(scope="module")
def plain_run():
    """Reference trajectory: the bare round program, plus the recorded
    per-round states the replay cross-check consumes."""
    round_fn = jax.jit(build_round(CFG, SPEC))
    init = init_fleet(SPEC, C, seed=0, election_tick=CFG.election_tick)
    state, inbox = init, empty_inbox(SPEC, C)
    states, inboxes = [], []
    for r in range(ROUNDS):
        state, inbox = round_fn(state, inbox, *_inputs(r))
        states.append(state)
        inboxes.append(inbox)
    # rich enough to prove anything: elections happened, the partition
    # forced a snapshot fallback (laggard re-synced via MsgSnap)
    assert int((np.asarray(state.role) == ROLE_LEADER).sum()) == C
    return init, states, inboxes


def _telemetered_run(cfg, init_tele_state=None):
    step = jax.jit(build_metered_round(cfg, SPEC, with_telemetry=True))
    state = init_fleet(SPEC, C, seed=0, election_tick=cfg.election_tick)
    base = state
    if cfg.packed_state:
        state = pack_fleet(SPEC, state)
    inbox = empty_inbox(
        SPEC, C, compact_bound=cfg.inbox_bound if cfg.compact_wire else 0)
    metrics = zero_metrics()
    tele = init_telemetry(SPEC, base)
    states, inboxes = [], []
    for r in range(ROUNDS):
        state, inbox, metrics, tele = step(state, inbox, *_inputs(r),
                                           metrics, tele)
        states.append(unpack_fleet(SPEC, state) if cfg.packed_state
                      else state)
        inboxes.append(inbox)
    return states, inboxes, tele


def test_telemetered_round_state_bit_identity(plain_run):
    """The tentpole's proof: fused telemetry reductions leave the state
    AND wire trajectories bit-identical over the rich scenario."""
    _, ref_states, ref_inboxes = plain_run
    states, inboxes, tele = _telemetered_run(CFG)
    for r, (a, b) in enumerate(zip(ref_states, states)):
        _assert_states_equal(a, b, "telemetered", r)
    for r, (a, b) in enumerate(zip(ref_inboxes, inboxes)):
        assert np.array_equal(np.asarray(a.type), np.asarray(b.type)), \
            f"wire diverged at round {r}"
    rep = telemetry_report(tele)
    assert rep["rounds"] == ROUNDS
    # the scenario elected one leader per group at round ~0 and the
    # partition cost nothing fleet-wide lasting: lanes saw >= 1 change
    assert rep["leader_changes_total"] >= C
    assert rep["commit_latency_rounds"]["count"] > 0


def test_telemetered_packed_state_bit_identity(plain_run):
    """The metered/telemetered round now composes with the PR-8 diet:
    packed carry in, bit-identical unpacked trajectory out, and the
    SAME telemetry as the dense telemetered run."""
    _, ref_states, _ = plain_run
    pcfg = dataclasses.replace(CFG, packed_state=True)
    states, _, tele_p = _telemetered_run(pcfg)
    for r, (a, b) in enumerate(zip(ref_states, states)):
        _assert_states_equal(a, b, "packed+telemetered", r)
    _, _, tele_d = _telemetered_run(CFG)
    for name in FleetTelemetry.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(tele_p, name)),
            np.asarray(getattr(tele_d, name))
        ), f"telemetry.{name} diverged between packed and dense"


def test_telemetered_sparse_outbox_bit_identity():
    """Steady-traffic bit-identity under the diet's sparse_outbox form
    (the rich scenario is out of scope for the steady message classes —
    same contract split as tests/test_sparse_outbox.py)."""
    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    full = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                      inbox_bound=4, coalesce_commit_refresh=True)
    sparse = dataclasses.replace(
        full, local_steps=("prop",),
        message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP),
        deferred_emit=True, sparse_outbox=True)
    Cs = 4
    M, E = spec.M, spec.E
    boot = jax.jit(build_round(full, spec))
    state = init_fleet(spec, Cs, seed=0, election_tick=full.election_tick)
    inbox = empty_inbox(spec, Cs)
    z2 = np.zeros((M, Cs), np.int32)
    zp = np.zeros((M, E, Cs), np.int32)
    no = np.zeros((M, Cs), bool)
    keep = np.ones((M, M, Cs), bool)
    hup = no.copy()
    hup[0, :] = True
    state, inbox = boot(state, inbox, z2, zp, zp, z2, hup, no, keep)
    for _ in range(12):
        state, inbox = boot(state, inbox, z2, zp, zp, z2, no, no, keep)
    assert (np.asarray(state.role)[0] == ROLE_LEADER).all()
    assert int((np.asarray(inbox.type) != 0).sum()) == 0

    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = 9
    args = (plen, pdata, zp, z2, no, no, keep)
    bare = jax.jit(build_round(sparse, spec))
    met = jax.jit(build_metered_round(sparse, spec, with_telemetry=True))
    s_a, i_a = state, inbox
    s_b, i_b = state, inbox
    metrics, tele = zero_metrics(), init_telemetry(spec, state)
    for r in range(12):
        s_a, i_a = bare(s_a, i_a, *args)
        s_b, i_b, metrics, tele = met(s_b, i_b, *args, metrics, tele)
        _assert_states_equal(s_a, s_b, "sparse_outbox+telemetered", r)
        assert np.array_equal(np.asarray(i_a.type), np.asarray(i_b.type))
    rep = telemetry_report(tele)
    # steady commits: every round samples C entries at the pipeline lat
    assert rep["commit_latency_rounds"]["count"] >= 8 * Cs
    assert rep["commit_latency_rounds"]["p99"] <= 4


# ---------------------------------------------------------------------------
# host replay cross-check: an independent numpy reimplementation of the
# telemetry definitions over the recorded state trajectory
# ---------------------------------------------------------------------------


def _replay(spec, init, states, buckets=8):
    M, L = spec.M, spec.L
    Cn = np.asarray(init.term).shape[-1]
    nb1 = buckets + 1
    edges = np.asarray(pow2_edges(buckets))
    hists = {k: np.zeros(nb1, np.int64) for k in ("commit", "elect")}
    lanes = {"leader_changes": np.zeros(Cn, np.int64),
             "snapshot_installs": np.zeros(Cn, np.int64)}
    birth = np.zeros((L, Cn), np.int64)
    prev_last = np.asarray(init.last_index).max(axis=0).astype(np.int64)
    prev_commit = np.asarray(init.commit).max(axis=0).astype(np.int64)
    cand_since = np.full((M, Cn), -1, np.int64)

    def sample(key, lat):
        hists[key][:-1] += lat <= edges
        hists[key][-1] += 1

    pre = init
    for r, post in enumerate(states):
        role_pre = np.asarray(pre.role)
        role = np.asarray(post.role)
        li = np.asarray(post.last_index).max(axis=0)
        cm = np.asarray(post.commit).max(axis=0)
        for c in range(Cn):
            for slot in range(L):
                idx = li[c] - ((li[c] - 1 - slot) % L)
                if idx > prev_last[c] and idx > 0:
                    birth[slot, c] = r
            for slot in range(L):
                idx = li[c] - ((li[c] - 1 - slot) % L)
                if prev_commit[c] < idx <= cm[c] and idx > 0:
                    sample("commit", max(r - birth[slot, c], 0))
        is_cand = (role == ROLE_PRE_CANDIDATE) | (role == ROLE_CANDIDATE)
        cand_since = np.where(is_cand & (cand_since < 0), r, cand_since)
        new_lead = (role == ROLE_LEADER) & (role_pre != ROLE_LEADER)
        for m, c in zip(*np.nonzero(new_lead)):
            sample("elect",
                   r - cand_since[m, c] if cand_since[m, c] >= 0 else 0)
        cand_since = np.where(is_cand, cand_since, -1)
        lanes["leader_changes"] += new_lead.any(axis=0)
        inst = (np.asarray(post.applied) - np.asarray(pre.applied)) > spec.A
        lanes["snapshot_installs"] += inst.any(axis=0)
        prev_last = li
        prev_commit = np.maximum(prev_commit, cm)
        pre = post
    return hists, lanes


def test_histograms_match_host_replay(plain_run):
    """The device histograms/lanes equal an independent numpy replay of
    the same definitions over the recorded trajectory — including the
    snapshot-install lane the partition window provokes."""
    init, ref_states, _ = plain_run
    _, _, tele = _telemetered_run(CFG)
    hists, lanes = _replay(SPEC, init, ref_states)
    assert np.array_equal(np.asarray(tele.commit_hist), hists["commit"])
    assert np.array_equal(np.asarray(tele.elect_hist), hists["elect"])
    assert np.array_equal(np.asarray(tele.leader_changes),
                          lanes["leader_changes"])
    assert np.array_equal(np.asarray(tele.snapshot_installs),
                          lanes["snapshot_installs"])
    # the partition window really forced a snapshot fallback somewhere
    assert lanes["snapshot_installs"].sum() > 0
    # heal machinery is compiled out without crash masks: all zero
    assert int(np.asarray(tele.heal_hist)[-1]) == 0
    assert int(np.asarray(tele.heal_rounds).sum()) == 0


# ---------------------------------------------------------------------------
# chaos flight recorder
# ---------------------------------------------------------------------------


def test_chaos_epoch_bit_identity_with_telemetry():
    """The chaos epoch program with the telemetry carry produces the
    exact same state/wire/violations/key as the program without it."""
    from etcd_tpu.harness.chaos import (
        build_chaos_epoch,
        empty_crash_state,
        zero_violations,
    )
    import jax.numpy as jnp

    Cs, rounds = 8, 8
    M = SPEC.M
    state = init_fleet(SPEC, Cs, seed=2, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, Cs)
    crash = empty_crash_state(state)
    key = jax.random.PRNGKey(7)
    prop_len = jnp.zeros((M, Cs), jnp.int32).at[0].set(1)
    prop_data = jnp.zeros((M, SPEC.E, Cs), jnp.int32).at[0, 0].set(7)
    pal = jnp.zeros((1,), jnp.int32)
    ops = (jnp.float32(0.05), jnp.float32(0.0), jnp.float32(0.1),
           jnp.float32(0.08), jnp.int32(2), jnp.bool_(True),
           jnp.bool_(True), jnp.float32(0.0), pal, jnp.float32(1.0),
           jnp.float32(1.0))
    plain = jax.jit(build_chaos_epoch(
        CFG, SPEC, rounds, with_delay=False, with_crash=True))
    telem = jax.jit(build_chaos_epoch(
        CFG, SPEC, rounds, with_delay=False, with_crash=True,
        with_telemetry=True))
    tele = init_telemetry(SPEC, state)
    out_a = plain(state, inbox, None, crash, key, prop_len, prop_data,
                  zero_violations(), None, None, *ops)
    out_b = telem(state, inbox, None, crash, key, prop_len, prop_data,
                  zero_violations(), tele, None, *ops)
    _assert_states_equal(out_a[0], out_b[0], "chaos epoch", rounds)
    assert np.array_equal(np.asarray(out_a[1].type),
                          np.asarray(out_b[1].type))
    for leaf_a, leaf_b in zip(jax.tree.leaves(out_a[5]),
                              jax.tree.leaves(out_b[5])):
        assert np.array_equal(np.asarray(leaf_a), np.asarray(leaf_b))
    assert np.array_equal(np.asarray(out_a[4]), np.asarray(out_b[4]))
    assert out_b[6] is not None  # telemetry came back
    assert int(np.asarray(out_b[6].round)) == rounds


def test_chaos_flight_recorder_timeline():
    """run_chaos(telemetry=True) emits one cumulative flight-recorder
    row per epoch: rounds advance, every counter is monotone
    non-decreasing, and the crash tier's heal machinery feeds the
    heal histogram."""
    from etcd_tpu.harness.chaos import run_chaos
    from etcd_tpu.utils.config import CrashConfig

    rep = run_chaos(
        SPEC, CFG, C=8, rounds=50, epoch_len=25, heal_len=25, seed=1,
        drop_p=0.03, delay_p=0.08, partition_p=0.2,
        crash_p=0.05, crash=CrashConfig(down_rounds=2), telemetry=True,
    )
    tl = rep["timeline"]
    assert len(tl) >= 2
    assert [row["kind"] for row in tl[:2]] == ["fault", "heal"]
    mono_keys = ("round", "commit_sum", "elect_sum", "heal_sum",
                 "leader_changes", "snapshot_installs", "heal_rounds",
                 "crashes_injected", "entries_lost_fsync")
    for a, b in zip(tl, tl[1:]):
        assert b["round"] > a["round"]
        for k in mono_keys:
            assert b[k] >= a[k], (k, a, b)
        for hk in ("commit_hist", "elect_hist", "heal_hist"):
            assert all(y >= x for x, y in zip(a[hk], b[hk])), (hk, a, b)
        assert all(b["violations"][k] >= a["violations"][k]
                   for k in b["violations"])
    t = rep["telemetry"]
    assert t["rounds"] == tl[-1]["round"]
    assert t["commit_latency_rounds"]["count"] > 0
    assert t["election_duration_rounds"]["count"] >= 8  # fleet elected
    if rep["crashes_injected"] > 0:
        # down rounds count toward some group's heal lane
        assert t["heal_rounds_total"] > 0
    # flight_record rows and the final report agree on the totals
    assert t["leader_changes_total"] == tl[-1]["leader_changes"]
    assert t["commit_latency_rounds"]["count"] == tl[-1]["commit_hist"][-1]


# ---------------------------------------------------------------------------
# TELEM_* knob validation (the exit-2-before-device-work contract)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("script,env_extra,needle", [
    ("bench.py", {"TELEM": "2"}, "TELEM"),
    ("bench.py", {"TELEM_BUCKETS": "1"}, "TELEM_BUCKETS"),
    ("chaos_run.py", {"TELEM": "maybe"}, "TELEM"),
    ("chaos_run.py", {"TELEM_BUCKETS": "99"}, "TELEM_BUCKETS"),
])
def test_telem_knob_validation_exits_2(script, env_extra, needle):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **env_extra}
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, script)],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 2, (out.returncode, out.stdout, out.stderr)
    assert needle in out.stderr
    assert not out.stdout.strip()


# ---------------------------------------------------------------------------
# reporting primitives + Prometheus exposition round trip
# ---------------------------------------------------------------------------


def test_hist_percentile():
    # 10 samples: 6 at <=2, 9 at <=4, all at <=8 (cumulative form)
    h = np.array([0, 6, 9, 10, 10], np.int64)
    assert hist_percentile(h, 0.5) == 2
    assert hist_percentile(h, 0.9) == 4
    assert hist_percentile(h, 0.99) == 8
    assert hist_percentile(np.zeros(5, np.int64), 0.5) is None
    # samples past the largest edge land in +Inf
    h2 = np.array([0, 0, 0, 0, 10], np.int64)
    assert hist_percentile(h2, 0.5) == float("inf")


def test_prometheus_render_parse_roundtrip():
    from etcd_tpu.models.telemetry import histogram_samples

    fams = [
        ("etcd_server_has_leader", "gauge", "Whether a leader exists.",
         [("", {}, 1)]),
        ("etcd_server_leader_changes_seen_total", "counter",
         "Leader changes seen.", [("", {}, 3)]),
        ("etcd_tpu_commit_latency_rounds", "histogram",
         "Commit latency.",
         histogram_samples((1, 2, 4), (5, 11, 12), 13, 37)),
    ]
    text = prometheus_render(fams)
    parsed = prometheus_parse(text)
    assert parsed["etcd_server_has_leader"]["type"] == "gauge"
    s = parsed["etcd_tpu_commit_latency_rounds"]["samples"]
    assert s[("etcd_tpu_commit_latency_rounds_bucket",
              (("le", "2"),))] == 11
    assert s[("etcd_tpu_commit_latency_rounds_bucket",
              (("le", "+Inf"),))] == 13
    assert s[("etcd_tpu_commit_latency_rounds_count", ())] == 13
    assert s[("etcd_tpu_commit_latency_rounds_sum", ())] == 37
    # a second render/parse cycle is stable
    assert prometheus_parse(text) == parsed


def test_prometheus_parse_rejects_nonconformant():
    with pytest.raises(ValueError, match="TYPE"):
        prometheus_parse("etcd_orphan_metric 1\n")
    bad_hist = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 4\n'
        "h_sum 9\nh_count 4\n"
    )
    with pytest.raises(ValueError, match="cumulative"):
        prometheus_parse(bad_hist)
    no_inf = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_sum 9\nh_count 5\n'
    )
    with pytest.raises(ValueError, match="Inf"):
        prometheus_parse(no_inf)


def test_report_percentiles_stay_json_strict():
    """A percentile past the top finite edge serializes as the string
    "inf", never float('inf') — json.dumps would emit the bare token
    Infinity, which strict parsers reject."""
    state = init_fleet(SPEC, 2, seed=0)
    tele = init_telemetry(SPEC, state, buckets=2)
    # force samples past the top edge (2): fake a large latency by
    # driving the hist directly through the report path
    import jax.numpy as jnp

    tele = tele.replace(commit_hist=jnp.asarray([0, 0, 10], jnp.int32),
                        commit_sum=jnp.int32(1000))
    rep = telemetry_report(tele)
    assert rep["commit_latency_rounds"]["p99"] == "inf"
    json.loads(json.dumps(rep))  # strict round trip


def test_cluster_telemetry_rejects_packed_state():
    from etcd_tpu.harness.cluster import Cluster

    with pytest.raises(ValueError, match="packed_state"):
        Cluster(n_members=3, spec=SPEC,
                cfg=dataclasses.replace(CFG, packed_state=True),
                telemetry=True)


def test_cluster_reset_telemetry_opens_fresh_window():
    from etcd_tpu.harness.cluster import Cluster

    cl = Cluster(n_members=3, spec=SPEC, cfg=CFG, telemetry=True)
    cl.campaign(0)
    cl.stabilize()
    assert int(np.asarray(cl.tele.round)) > 0
    cl.reset_telemetry()
    assert int(np.asarray(cl.tele.round)) == 0
    rep = telemetry_report(cl.tele, groups=cl.C)
    assert rep["commit_latency_rounds"]["count"] == 0


def test_init_telemetry_leaves_share_no_buffers():
    """Every FleetTelemetry leaf owns its buffer: the chaos epoch
    programs donate the whole carry on accelerators, and XLA rejects
    one buffer appearing at two donated positions in a single Execute
    (the empty_crash_state alias hazard class)."""
    state = init_fleet(SPEC, 4, seed=0)
    tele = init_telemetry(SPEC, state)
    ptrs = [leaf.unsafe_buffer_pointer() for leaf in jax.tree.leaves(tele)]
    assert len(ptrs) == len(set(ptrs)), "aliased telemetry leaves"
    state_ptrs = {leaf.unsafe_buffer_pointer()
                  for leaf in jax.tree.leaves(state)}
    assert not state_ptrs & set(ptrs), "telemetry leaf aliases state"


def test_init_telemetry_rejects_bad_buckets():
    state = init_fleet(SPEC, 2, seed=0)
    with pytest.raises(ValueError, match="buckets"):
        init_telemetry(SPEC, state, buckets=1)
    with pytest.raises(ValueError, match="buckets"):
        init_telemetry(SPEC, state, buckets=17)


def test_flight_record_shape():
    state = init_fleet(SPEC, 2, seed=0)
    tele = init_telemetry(SPEC, state)
    tele = telemetry_update(SPEC, tele, state, state)
    rec = flight_record(tele, kind="heal")
    assert rec["kind"] == "heal" and rec["round"] == 1
    assert len(rec["commit_hist"]) == 9  # 8 pow2 buckets + inf
    assert rec["wrapped"] is False
    assert json.dumps(rec)  # JSON-serializable as-is


def test_flight_record_flags_i32_wrap():
    """A wrapped (negative) i32 counter flags the row instead of
    silently breaking the timeline's monotone property."""
    import jax.numpy as jnp

    state = init_fleet(SPEC, 2, seed=0)
    tele = init_telemetry(SPEC, state)
    tele = tele.replace(commit_sum=jnp.int32(-5))
    rec = flight_record(tele)
    assert rec["wrapped"] is True


def test_run_chaos_survives_wrapped_telemetry_window(monkeypatch):
    """An i32 wrap at the end of a long soak must degrade the summary
    ({wrapped: true}) rather than discard the whole run's report."""
    from etcd_tpu.harness import chaos as chaos_mod
    from etcd_tpu.utils.config import CrashConfig

    def raiser(tele, groups=None):
        raise OverflowError("forced wrap")

    monkeypatch.setattr(chaos_mod, "telemetry_report", raiser)
    # same shape/fault mix as test_chaos_flight_recorder_timeline so the
    # lru-cached epoch programs are reused instead of re-traced
    rep = chaos_mod.run_chaos(
        SPEC, CFG, C=8, rounds=50, epoch_len=25, heal_len=25, seed=1,
        drop_p=0.03, delay_p=0.08, partition_p=0.2,
        crash_p=0.05, crash=CrashConfig(down_rounds=2), telemetry=True,
    )
    assert rep["telemetry"]["wrapped"] is True
    assert rep["telemetry"]["rounds"] == rep["timeline"][-1]["round"]
    assert len(rep["timeline"]) >= 2  # the timeline still made it out
