"""Benchmark-tool tests: the tools/benchmark analog drives a live
embedded server over the wire and reports pkg/report-style summaries."""
import io
import sys

import pytest

from etcd_tpu import benchmark
from etcd_tpu.embed import Config, start_etcd


@pytest.fixture(scope="module")
def etcd():
    e = start_etcd(Config(cluster_size=3, auto_tick=False))
    yield e
    e.close()


def run(etcd, *argv) -> str:
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        ep = ["--endpoint", etcd.client_url] if etcd else []
        rc = benchmark.main([*ep, *argv])
    finally:
        sys.stdout = old
    assert rc == 0
    return out.getvalue()


def test_benchmark_put_and_range(etcd):
    out = run(etcd, "put", "--total", "20", "--val-size", "16")
    assert "Requests/sec:" in out and "99% in" in out
    out = run(etcd, "range", "--total", "20", "--serializable")
    assert "Latency distribution:" in out


def test_benchmark_txn_and_watch_latency(etcd):
    out = run(etcd, "txn-put", "--total", "10")
    assert "Summary:" in out
    out = run(etcd, "watch-latency", "--total", "5")
    assert "Requests/sec:" in out


def test_benchmark_txn_mixed_and_stm(etcd):
    out = run(etcd, "txn-mixed", "--total", "10", "--rw-ratio", "2")
    assert "Summary:" in out
    out = run(etcd, "stm", "--total", "8", "--stm-keys", "3")
    assert "Requests/sec:" in out
    # STM actually incremented: each txn is one read-modify-write
    from etcd_tpu.client import RemoteClient

    c = RemoteClient(etcd.client_url)
    total = sum(int(c.get(b"stm/%d" % i) or b"0") for i in range(3))
    assert total == 8


def test_benchmark_lease(etcd):
    out = run(etcd, "lease", "--total", "10")
    assert "Requests/sec:" in out


def test_benchmark_watch_shapes(etcd):
    out = run(etcd, "watch", "--total", "6", "--watchers", "3")
    assert "events delivered: " in out and "Summary:" in out
    out = run(etcd, "watch-get", "--total", "5", "--watchers", "2",
              "--watch-events", "6")
    assert "catch-up events: " in out


def test_benchmark_mvcc_put():
    """The direct-storage shape needs no server at all."""
    out = run(None, "mvcc-put", "--total", "50", "--val-size", "16")
    assert "Requests/sec:" in out
