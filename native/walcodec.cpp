// WAL record codec — the native hot path of the host durability ring.
//
// Plays the role of the reference's encoder/decoder pair
// (server/storage/wal/encoder.go:124, decoder.go:196): length-prefixed
// records with a running CRC32 chain so a torn tail is detected at the
// first bad frame (wal/repair.go's openAtTail contract). Layout per record:
//
//   u32 payload_len | u8 type | u32 crc | payload bytes | pad to 8
//
// crc = crc32(prev_crc, payload) — chained, so records can't be reordered.
// Exposed as a C ABI for ctypes (pybind11 is not in this image).
#include <cstdint>
#include <cstring>

namespace {

// CRC32 (IEEE, reflected) — table-driven, same polynomial as Go's
// hash/crc32.IEEETable used by the reference WAL.
uint32_t crc_table[256];
bool table_init = false;

void init_table() {
  if (table_init) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  table_init = true;
}

uint32_t crc32_update(uint32_t crc, const uint8_t* buf, size_t len) {
  init_table();
  crc = ~crc;
  for (size_t i = 0; i < len; i++) crc = crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

constexpr size_t kHeader = 9;  // u32 len + u8 type + u32 crc

inline size_t padded(size_t n) { return (n + 7) & ~size_t(7); }

}  // namespace

extern "C" {

uint32_t wal_crc32(uint32_t crc, const uint8_t* buf, uint64_t len) {
  return crc32_update(crc, buf, len);
}

// Frame one record into out (caller sizes out >= wal_frame_size(len)).
// Returns bytes written; *crc_io is the running chain crc (in/out).
uint64_t wal_encode(uint8_t type, const uint8_t* payload, uint64_t len,
                    uint32_t* crc_io, uint8_t* out) {
  uint32_t crc = crc32_update(*crc_io, payload, len);
  *crc_io = crc;
  uint32_t l32 = (uint32_t)len;
  std::memcpy(out, &l32, 4);
  out[4] = type;
  std::memcpy(out + 5, &crc, 4);
  std::memcpy(out + kHeader, payload, len);
  size_t total = kHeader + len;
  size_t want = kHeader + padded(len);
  for (size_t i = total; i < want; i++) out[i] = 0;
  return want;
}

uint64_t wal_frame_size(uint64_t len) { return kHeader + padded(len); }

// Decode one record at buf[0..len). On success returns bytes consumed and
// fills *type/*payload_off/*payload_len, advancing *crc_io. Returns 0 when
// the frame is truncated or the CRC chain breaks (torn tail: caller
// truncates here, wal/repair.go semantics).
uint64_t wal_decode(const uint8_t* buf, uint64_t len, uint32_t* crc_io,
                    uint8_t* type, uint64_t* payload_off, uint64_t* payload_len) {
  if (len < kHeader) return 0;
  uint32_t l32;
  std::memcpy(&l32, buf, 4);
  uint8_t ty = buf[4];
  uint32_t crc;
  std::memcpy(&crc, buf + 5, 4);
  uint64_t want = kHeader + padded(l32);
  if (len < want) return 0;
  uint32_t got = crc32_update(*crc_io, buf + kHeader, l32);
  if (got != crc) return 0;
  *crc_io = got;
  *type = ty;
  *payload_off = kHeader;
  *payload_len = l32;
  return want;
}

// Batch append: frame n records (concatenated payloads with a length table)
// into out. Returns total bytes. Used for group-commit batches so one
// Python->C call frames a whole fsync batch (the reference batches fsyncs
// per Ready, wal/wal.go MustSync).
uint64_t wal_encode_batch(const uint8_t* types, const uint64_t* lens,
                          const uint8_t* payloads, uint64_t n,
                          uint32_t* crc_io, uint8_t* out) {
  uint64_t in_off = 0, out_off = 0;
  for (uint64_t i = 0; i < n; i++) {
    out_off += wal_encode(types[i], payloads + in_off, lens[i], crc_io, out + out_off);
    in_off += lens[i];
  }
  return out_off;
}

}  // extern "C"
