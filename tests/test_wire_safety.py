"""Mechanical int16-wire safety (engine.wire_overflow_count + types.WIRE_SPLIT).

The 81d0b1e bug class: MsgSnap carried the 32-bit applied hash in `commit`,
and RaftConfig.wire_int16 silently truncated it — every restored follower
diverged until the chaos KV_HASH checker caught it. The guard here audits
the PRE-cast int32 wire every round of a scenario that produces every
message class (election, replication, read index, conf change, snapshot
catch-up): any value that would not survive the int16 cast and is not a
registered split fails loudly. A new wide field on the wire breaks this
test, not a fleet."""
import numpy as np
import jax.numpy as jnp

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.models.engine import wire_overflow_count
from etcd_tpu.types import MSG_APP, MSG_SNAP, Spec
from etcd_tpu.utils.config import RaftConfig


def _audit(cl: Cluster) -> int:
    return int(wire_overflow_count(cl.spec, cl.eng.inbox))


def test_all_message_classes_fit_the_wire_or_are_split():
    # pre-vote + check-quorum: the healed laggard probes with a prevote
    # instead of disrupting the stable leader mid-scenario
    cl = Cluster(3, cfg=RaftConfig(pre_vote=True, check_quorum=True))
    saw_snap = False
    snap_commit_overflowed = False

    def step_audit(tick=False):
        nonlocal saw_snap, snap_commit_overflowed
        cl.step(tick=tick)
        assert _audit(cl) == 0, "non-split wire value exceeds int16"
        typ = np.asarray(cl.eng.inbox.type)
        com = np.asarray(cl.eng.inbox.commit)
        snaps = typ == MSG_SNAP
        if snaps.any():
            saw_snap = True
            if (np.abs(com[snaps]) > 2 ** 15 - 1).any():
                snap_commit_overflowed = True

    # election (vote/vote-resp)
    cl.campaign(0)
    for _ in range(6):
        step_audit()
    assert cl.leader() == 0

    # replication + heartbeats + read index + conf change
    cl.propose(0, 7)
    cl.read_index(0)
    step_audit(tick=True)
    for _ in range(4):
        step_audit()

    # snapshot catch-up: isolate a follower, push past the ring window so
    # the leader compacts, then heal — replication falls back to MsgSnap
    # whose `commit` carries the full 32-bit applied hash (the registered
    # split). The hash is a 32-bit mix, so it exercises the exemption.
    cl.isolate(2)
    for r in range(cl.spec.L // cl.spec.E + 4):
        for e in range(cl.spec.E):
            cl.propose(0, 1000 + r * cl.spec.E + e)
        step_audit()
    assert cl.get("snap_index", 0) > 0, "leader ring never compacted"
    cl.recover()
    for _ in range(12):
        step_audit(tick=True)
        if saw_snap:
            break
    assert saw_snap, "heal never produced a MsgSnap"
    for _ in range(8):
        step_audit()
    assert cl.get("commit", 2) == cl.get("commit", 0), "laggard not caught up"
    assert cl.get("applied_hash", 2) == cl.get("applied_hash", 0)
    # the exemption was actually exercised (a truncating value rode commit)
    assert snap_commit_overflowed, (
        "applied hash never exceeded int16 — scenario too small to prove "
        "the split registry matters"
    )


def test_checker_flags_unregistered_wide_field():
    cl = Cluster(3, cfg=RaftConfig(pre_vote=True, check_quorum=True))
    cl.campaign(0)
    cl.stabilize()
    # a 32-bit value in MsgApp.index is NOT a registered split: flag it
    cl.inject(to=1, frm=0, type=MSG_APP, index=1 << 20)
    assert _audit(cl) >= 1
    # the same value on a MSG_SNAP commit IS registered: clean
    cl2 = Cluster(3, cfg=RaftConfig(pre_vote=True, check_quorum=True))
    cl2.campaign(0)
    cl2.stabilize()
    cl2.inject(to=1, frm=0, type=MSG_SNAP, commit=-(1 << 20))
    assert _audit(cl2) == 0


def test_checker_rejects_int16_inbox():
    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=4, coalesce_commit_refresh=True,
                     wire_int16=True)
    cl = Cluster(n_members=5, C=4, spec=spec, cfg=cfg)
    assert cl.eng.inbox.term.dtype == jnp.int16
    try:
        wire_overflow_count(spec, cl.eng.inbox)
    except ValueError:
        return
    raise AssertionError("int16 inbox must be rejected (audit is pre-cast)")
