"""Transport security: self-signed cert generation, HTTPS gateway with
CA verification, handshake failures, mutual TLS, the allowed-CN gate,
and certificate-CN auth (client/pkg/transport listener.go:185 SelfCert,
listener_tls.go:43, server/auth/store.go:985 AuthInfoFromTLS)."""
import os
import ssl
import urllib.error

import pytest

from etcd_tpu import clientv2
from etcd_tpu.client import RemoteClient, RemoteError
from etcd_tpu.embed import Config, start_etcd
from etcd_tpu.transport import (
    TLSInfo,
    generate_ca,
    issue_cert,
    self_cert,
)


# ------------------------------------------------------- cert generation

def test_self_cert_generates_and_reuses(tmp_path):
    pytest.importorskip("cryptography")
    d = str(tmp_path / "sc")
    info = self_cert(d, ["127.0.0.1", "localhost"])
    assert os.path.exists(info.cert_file)
    assert os.path.exists(info.key_file)
    assert info.trusted_ca_file == info.cert_file  # its own trust root
    assert (os.stat(info.key_file).st_mode & 0o777) == 0o600
    before = open(info.cert_file, "rb").read()
    info2 = self_cert(d, ["10.0.0.1"])  # reused, NOT regenerated
    assert open(info2.cert_file, "rb").read() == before


def test_ca_issue_cert_cn(tmp_path):
    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.x509.oid import NameOID

    ca = generate_ca(str(tmp_path / "ca"))
    leaf = issue_cert(str(tmp_path / "ca"), ca, "alice")
    cert = x509.load_pem_x509_certificate(
        open(leaf.cert_file, "rb").read())
    cns = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
    assert cns[0].value == "alice"
    assert leaf.trusted_ca_file == ca.cert_file


def test_server_context_requires_keypair():
    with pytest.raises(ValueError, match="must both be present"):
        TLSInfo().server_context()
    with pytest.raises(ValueError, match="requires a trusted CA"):
        TLSInfo(cert_file="x", key_file="y",
                client_cert_auth=True).server_context()


# ------------------------------------------------- auto-TLS HTTPS server

@pytest.fixture(scope="module")
def https_etcd(tmp_path_factory):
    pytest.importorskip("cryptography")  # auto-TLS cert generation
    d = str(tmp_path_factory.mktemp("httpsd"))
    e = start_etcd(Config(cluster_size=1, data_dir=d,
                          client_auto_tls=True, auto_tick=False))
    yield e
    e.close()


def _ca_of(e) -> TLSInfo:
    return TLSInfo(trusted_ca_file=e.client_tls.cert_file)


def test_https_roundtrip_with_ca_verification(https_etcd):
    assert https_etcd.client_url.startswith("https://")
    cli = RemoteClient(https_etcd.client_url, tls=_ca_of(https_etcd))
    cli.put(b"/tls/a", b"v1")
    assert cli.get(b"/tls/a") == b"v1"
    assert cli.get_prefix(b"/tls/") == [(b"/tls/a", b"v1")]
    st = cli.status()
    assert "db_size" in st


def test_https_rejected_without_ca(https_etcd):
    """Default trust store doesn't contain the self-signed cert: the
    handshake must fail (no silent fallback to plaintext)."""
    cli = RemoteClient(https_etcd.client_url)
    with pytest.raises(urllib.error.URLError) as ei:
        cli.get(b"/tls/a")
    assert isinstance(ei.value.reason, ssl.SSLError)


def test_https_rejected_with_wrong_ca(https_etcd, tmp_path):
    other = generate_ca(str(tmp_path / "otherca"))
    cli = RemoteClient(
        https_etcd.client_url,
        tls=TLSInfo(trusted_ca_file=other.cert_file))
    with pytest.raises(urllib.error.URLError):
        cli.get(b"/tls/a")


def test_https_insecure_skip_verify(https_etcd):
    cli = RemoteClient(https_etcd.client_url,
                       tls=TLSInfo(insecure_skip_verify=True))
    cli.put(b"/tls/skip", b"ok")
    assert cli.get(b"/tls/skip") == b"ok"


def test_etcdctl_over_https(https_etcd, capsys):
    from etcd_tpu import etcdctl

    ep = ["--endpoint", https_etcd.client_url,
          "--cacert", https_etcd.client_tls.cert_file]
    assert etcdctl.main([*ep, "put", "/tls/ctl", "cv"]) == 0
    capsys.readouterr()
    assert etcdctl.main([*ep, "get", "/tls/ctl"]) == 0
    assert "cv" in capsys.readouterr().out


def test_clientv2_over_https(https_etcd):
    cli = clientv2.new(https_etcd.client_url, tls=_ca_of(https_etcd))
    assert cli.keys.set("/tlsv2/a", "v").action == "set"
    assert cli.keys.get("/tlsv2/a").node["value"] == "v"


def test_auto_tls_requires_data_dir():
    with pytest.raises(ValueError, match="auto TLS requires a data_dir"):
        Config(cluster_size=1, client_auto_tls=True).validate()


# -------------------------------------------- mutual TLS + cert-CN auth

@pytest.fixture(scope="module")
def mtls(tmp_path_factory):
    """CA + server/alice/bob certs + an embed server requiring client
    certs, with auth enabled and alice scoped to /app/*."""
    pytest.importorskip("cryptography")  # CA + cert issuance
    d = str(tmp_path_factory.mktemp("mtls"))
    ca = generate_ca(os.path.join(d, "certs"))
    server = issue_cert(os.path.join(d, "certs"), ca, "server",
                        hosts=["127.0.0.1", "localhost"])
    alice = issue_cert(os.path.join(d, "certs"), ca, "alice")
    bob = issue_cert(os.path.join(d, "certs"), ca, "bob")
    e = start_etcd(Config(
        cluster_size=1, data_dir=os.path.join(d, "data"),
        auto_tick=False,
        client_tls=TLSInfo(
            cert_file=server.cert_file, key_file=server.key_file,
            trusted_ca_file=ca.cert_file, client_cert_auth=True)))
    # admin bootstrap over the wire (any CA-signed cert may connect)
    from conftest import bootstrap_cert_cn_auth

    admin = RemoteClient(e.client_url, tls=TLSInfo(
        trusted_ca_file=ca.cert_file,
        client_cert_file=alice.cert_file,
        client_key_file=alice.key_file))
    bootstrap_cert_cn_auth(admin.call)
    yield {"e": e, "ca": ca, "alice": alice, "bob": bob}
    e.close()


def test_mtls_handshake_requires_client_cert(mtls):
    # TLS 1.3: the client may only see the certificate-required alert
    # on its first read, as a raw SSLError rather than a wrapped
    # URLError — either way the connection is refused
    cli = RemoteClient(
        mtls["e"].client_url,
        tls=TLSInfo(trusted_ca_file=mtls["ca"].cert_file))  # no cert
    with pytest.raises((urllib.error.URLError, ssl.SSLError,
                        ConnectionError)):
        cli.get(b"/app/x")


def test_cert_cn_authenticates_without_password(mtls):
    """AuthInfoFromTLS: the verified cert CN is the user — no token,
    no password, permissions enforced for that user."""
    alice = RemoteClient(mtls["e"].client_url, tls=TLSInfo(
        trusted_ca_file=mtls["ca"].cert_file,
        client_cert_file=mtls["alice"].cert_file,
        client_key_file=mtls["alice"].key_file))
    alice.put(b"/app/x", b"from-cert")
    assert alice.get(b"/app/x") == b"from-cert"
    with pytest.raises(RemoteError, match="[Pp]ermission"):
        alice.put(b"/outside", b"nope")


def test_cert_cn_unknown_user_rejected(mtls):
    """bob's cert verifies, but no 'bob' user exists: authz fails."""
    bob = RemoteClient(mtls["e"].client_url, tls=TLSInfo(
        trusted_ca_file=mtls["ca"].cert_file,
        client_cert_file=mtls["bob"].cert_file,
        client_key_file=mtls["bob"].key_file))
    with pytest.raises(RemoteError):
        bob.put(b"/app/x", b"nope")


def test_cert_token_not_spoofable_from_wire(mtls):
    """Authorization: cert:root from the wire must NOT become a cert
    identity — the transport strips it and the real cert CN wins."""
    alice = RemoteClient(mtls["e"].client_url, token="cert:root",
                         tls=TLSInfo(
                             trusted_ca_file=mtls["ca"].cert_file,
                             client_cert_file=mtls["alice"].cert_file,
                             client_key_file=mtls["alice"].key_file))
    with pytest.raises(RemoteError, match="[Pp]ermission"):
        alice.put(b"/outside", b"nope")  # root could; alice cannot
    alice.put(b"/app/spoof", b"still-alice")  # alice's scope still works


def test_cert_token_not_spoofable_via_body(mtls):
    """A "_token": "cert:root" smuggled in the JSON BODY (not the
    Authorization header) must be stripped before it can impersonate a
    TLS identity."""
    alice = RemoteClient(mtls["e"].client_url, tls=TLSInfo(
        trusted_ca_file=mtls["ca"].cert_file,
        client_cert_file=mtls["alice"].cert_file,
        client_key_file=mtls["alice"].key_file))
    with pytest.raises(RemoteError, match="[Pp]ermission"):
        alice.call("/v3/kv/put", {
            "key": RemoteClient._b64(b"/outside"),
            "value": RemoteClient._b64(b"x"),
            "_token": "cert:root",
        })


def test_password_token_still_works_over_mtls(mtls):
    """Token auth composes with mutual TLS: an explicit Authorization
    token outranks the cert CN (the reference prefers the token when
    both are present)."""
    root = RemoteClient(mtls["e"].client_url, tls=TLSInfo(
        trusted_ca_file=mtls["ca"].cert_file,
        client_cert_file=mtls["alice"].cert_file,
        client_key_file=mtls["alice"].key_file))
    root.login("root", "rpw")
    root.put(b"/outside", b"root-can")  # alice's cert alone could not
    assert root.get(b"/outside") == b"root-can"


def test_auth_admin_requires_root(mtls):
    """With auth enabled, /v3/auth admin ops need the root role —
    a valid non-root cert identity is not enough (AdminPermission)."""
    alice = RemoteClient(mtls["e"].client_url, tls=TLSInfo(
        trusted_ca_file=mtls["ca"].cert_file,
        client_cert_file=mtls["alice"].cert_file,
        client_key_file=mtls["alice"].key_file))
    with pytest.raises(RemoteError):
        alice.call("/v3/auth/disable", {})
    with pytest.raises(RemoteError):
        alice.call("/v3/auth/user/add",
                   {"name": "mallory", "password": "m"})
    # root (password token) still administers
    root = RemoteClient(mtls["e"].client_url, tls=TLSInfo(
        trusted_ca_file=mtls["ca"].cert_file,
        client_cert_file=mtls["alice"].cert_file,
        client_key_file=mtls["alice"].key_file)).login("root", "rpw")
    root.call("/v3/auth/user/add", {"name": "temp", "password": "t"})
    # a mutating admin op bumps the auth revision: the old token is
    # now ErrAuthOldRevision and the client must re-authenticate
    # (auth/store.go revision discipline)
    with pytest.raises(RemoteError, match="OldRevision"):
        root.call("/v3/auth/user/delete", {"name": "temp"})
    root.login("root", "rpw")
    root.call("/v3/auth/user/delete", {"name": "temp"})


def test_etcdctl_mutual_tls_key_flag(mtls):
    """--key must not collide with subcommand key positionals: mutual
    TLS through the full etcdctl argv path."""
    import contextlib
    import io

    from etcd_tpu import etcdctl

    ep = ["--endpoint", mtls["e"].client_url,
          "--cacert", mtls["ca"].cert_file,
          "--cert", mtls["alice"].cert_file,
          "--key", mtls["alice"].key_file]
    assert etcdctl.main([*ep, "put", "/app/ctl", "mv"]) == 0
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        assert etcdctl.main([*ep, "get", "/app/ctl"]) == 0
    assert "mv" in out.getvalue()


def test_half_configured_tls_fails_loudly(tmp_path):
    """CA-only server TLSInfo must fail startup, not silently serve
    plaintext; a client cert without its key must error at config."""
    with pytest.raises(ValueError, match="must both be present"):
        start_etcd(Config(
            cluster_size=1, data_dir=str(tmp_path / "d"),
            auto_tick=False,
            client_tls=TLSInfo(trusted_ca_file="ca.pem",
                               client_cert_auth=True)))
    with pytest.raises(ValueError, match="must both be present"):
        TLSInfo(client_cert_file="alice.pem").client_context()


def test_stalled_client_does_not_block_accepts(https_etcd):
    """A TCP client that connects and never handshakes must not stall
    other clients (handshakes are deferred to handler threads)."""
    import socket

    host, port = "127.0.0.1", https_etcd.http.port
    stalled = socket.create_connection((host, port))
    try:
        cli = RemoteClient(https_etcd.client_url,
                           tls=_ca_of(https_etcd), timeout=10)
        cli.put(b"/tls/notblocked", b"v")
        assert cli.get(b"/tls/notblocked") == b"v"
    finally:
        stalled.close()


# ------------------------------------------------------ allowed-CN gate

def test_allowed_cn_gate(tmp_path):
    pytest.importorskip("cryptography")
    d = str(tmp_path)
    ca = generate_ca(os.path.join(d, "certs"))
    server = issue_cert(os.path.join(d, "certs"), ca, "server",
                        hosts=["127.0.0.1", "localhost"])
    alice = issue_cert(os.path.join(d, "certs"), ca, "alice")
    bob = issue_cert(os.path.join(d, "certs"), ca, "bob")
    e = start_etcd(Config(
        cluster_size=1, data_dir=os.path.join(d, "data"),
        auto_tick=False,
        client_tls=TLSInfo(
            cert_file=server.cert_file, key_file=server.key_file,
            trusted_ca_file=ca.cert_file, client_cert_auth=True,
            allowed_cn="alice")))
    try:
        ok = RemoteClient(e.client_url, tls=TLSInfo(
            trusted_ca_file=ca.cert_file,
            client_cert_file=alice.cert_file,
            client_key_file=alice.key_file))
        ok.put(b"/cn/a", b"v")
        bad = RemoteClient(e.client_url, tls=TLSInfo(
            trusted_ca_file=ca.cert_file,
            client_cert_file=bob.cert_file,
            client_key_file=bob.key_file))
        with pytest.raises(RemoteError, match="constraint"):
            bad.put(b"/cn/b", b"v")
    finally:
        e.close()
