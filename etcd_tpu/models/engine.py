"""Batched engine: vmapped node rounds + message exchange.

The reference runs one goroutine per node and moves messages through
rafthttp streams (server/etcdserver/api/rafthttp/). Here a fleet of
``C x M`` nodes steps in lockstep: ``jax.vmap`` over members then clusters
turns the per-node round into one fused XLA program, and the "network" is a
transpose of the dense outbox tensor ``[from, K, to, C] -> [to, K, from, C]``
with a multiplicative keep-mask standing in for drop/partition faults
(rafttest/network.go:33-64's drop/disconnect semantics; dropping is legal
per the transport contract, etcdserver/raft.go:107-110).

Fleet layout: **clusters-minor** — every leaf is ``[M, feature..., C]``
with the huge batch axis LAST. TPU tiles the two minor dims to (8, 128)
sublanes x lanes; with clusters leading, a ``[C, 5, 5]`` leaf pads 41x and
the fleet OOMs at scale, while clusters-minor pads only the tiny member
axis (<=1.6x). The member axes stay leading and fully on-device, which is
where the per-round message transpose happens.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from etcd_tpu.models.raft import node_round
from etcd_tpu.models.state import (
    NodeState,
    PACK_TIMER_BITS,
    init_node,
    pack_fleet,
    state_bytes_per_group,
    unpack_fleet,
)
from etcd_tpu.ops.outbox import Outbox
from etcd_tpu.types import (
    ENT_FIELDS,
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    MSG_SNAP,
    Msg,
    NONE_ID,
    PR_PROBE,
    PR_SNAPSHOT,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig


_ENT_FIELDS = ENT_FIELDS


def _unflatten_inbox(spec: Spec, msgs: Msg) -> Msg:
    """[from, K*to(*E), C] -> [from, K, to, (E,) C]; a bitcast (row-major
    adjacent-axis split), no data movement."""
    M, K, E = spec.M, spec.K, spec.E

    def f(name, x):
        if name in _ENT_FIELDS:
            return x.reshape(M, K, M, E, x.shape[-1])
        return x.reshape(M, K, M, x.shape[-1])

    return Msg(**{k: f(k, getattr(msgs, k)) for k in Msg.__dataclass_fields__})


def _flatten_inbox(spec: Spec, msgs: Msg) -> Msg:
    """Inverse of :func:`_unflatten_inbox`."""
    M, K, E = spec.M, spec.K, spec.E

    def f(name, x):
        n = K * M * (E if name in _ENT_FIELDS else 1)
        return x.reshape(M, n, x.shape[-1])

    return Msg(**{k: f(k, getattr(msgs, k)) for k in Msg.__dataclass_fields__})


def to_wire(m: Msg) -> Msg:
    """int32 -> int16 at the round boundary (RaftConfig.wire_int16)."""
    return jax.tree.map(
        lambda x: x.astype(jnp.int16) if x.dtype == jnp.int32 else x, m
    )


def wire_overflow_count(spec: Spec, inbox: Msg) -> jnp.ndarray:
    """Mechanical int16-wire safety check: count values in a flat int32
    inbox ([from, K*to(*E), C] leaves) that would NOT survive the int16
    cast and are not covered by a registered split (types.WIRE_SPLIT).

    This is the test-time guard for the 81d0b1e bug class — MsgSnap's
    32-bit applied hash riding `commit` was silently truncated by
    RaftConfig.wire_int16 until the chaos KV_HASH checker caught the
    divergence. Any new wide field on the wire now fails
    tests/test_wire_safety.py instead of corrupting a fleet."""
    from etcd_tpu.types import WIRE_SPLIT

    if inbox.term.dtype == jnp.int16:
        raise ValueError(
            "wire_overflow_count audits the PRE-cast int32 wire; run the "
            "fleet with wire_int16=False and check each round's inbox"
        )
    lo, hi = -(2 ** 15), 2 ** 15 - 1
    t = inbox.type.astype(jnp.int32)  # [M, K*M, C]
    total = jnp.zeros((), jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    for name in Msg.__dataclass_fields__:
        x = getattr(inbox, name)
        if x.dtype != jnp.int32:
            continue
        tt = jnp.repeat(t, spec.E, axis=1) if name in _ENT_FIELDS else t
        bad = (x < lo) | (x > hi)
        for (f, msg_type) in WIRE_SPLIT:
            if f == name:
                bad = bad & (tt != msg_type)
        total = total + bad.sum()
    return total


def from_wire(m: Msg) -> Msg:
    return jax.tree.map(
        lambda x: x.astype(jnp.int32) if x.dtype == jnp.int16 else x, m
    )


def empty_inbox(spec: Spec, C: int, wire_int16: bool = False,
                compact_bound: int = 0) -> Msg:
    """Zeroed inbox, stored FLAT: leaves [from, K*to, C] (ent fields
    [from, K*to*E, C]).

    Two TPU layout hazards shape this format (measured in the C=65536
    compile reports): (a) any stored tensor whose minor-most logical dims
    are tiny (K=2, E=1) gets tile-padded 60-200x, so the flat middle axis
    keeps a medium dim next to C (<=1.6x pad); (b) delivery must not
    transpose, so the same tensor the senders write (axis 0 = from) is
    what receivers consume — build_round unflattens by free reshape and
    maps receivers over the `to` axis.

    ``compact_bound`` > 0 (RaftConfig.compact_wire, pass cfg.inbox_bound):
    the COMPACTED carry form instead — leaves [B(slot), to, C] (ent fields
    [B, to*E, C]), the first B nonempty delivery slots per receiver. Same
    minor-pair padding class ((to, C) instead of (K*to, C)); receivers are
    mapped over axis 1."""
    from etcd_tpu.types import empty_msg

    m = empty_msg(spec)
    B = min(compact_bound, spec.K * spec.M)

    def mk(name, x):
        e = spec.E if name in _ENT_FIELDS else 1
        dt = x.dtype
        if wire_int16 and dt == jnp.int32:
            dt = jnp.int16
        if B:
            return jnp.zeros((B, spec.M * e, C), dt)
        return jnp.zeros((spec.M, spec.K * spec.M * e, C), dt)

    return Msg(**{k: mk(k, getattr(m, k)) for k in Msg.__dataclass_fields__})


def inbox_bytes_per_group(spec: Spec, wire_int16: bool = False,
                          compact_bound: int = 0) -> int:
    """Resident wire bytes per group in the given storage form, from the
    actual leaf dtypes/shapes (bench.py's accounting + the regression
    budget in tests/test_packed_state.py).

    Built EAGERLY at C=1 (a few hundred bytes), not under
    jax.eval_shape: empty_inbox goes through the lru-cached
    types.empty_msg, and an eval_shape call would poison that cache
    with tracer leaves for this (spec, backend) key, crashing the next
    eager inbox construction with an UnexpectedTracerError."""
    sh = empty_inbox(spec, 1, wire_int16, compact_bound)
    return sum(int(leaf.size) * leaf.dtype.itemsize
               for leaf in jax.tree.leaves(sh))


def compact_wire_carry(spec: Spec, msgs: Msg, bound: int) -> Msg:
    """Per-receiver inbox compaction at the ROUND BOUNDARY
    (RaftConfig.compact_wire): the keep-masked delivery view
    [from, K, to, (E,) C] -> the first `bound` nonempty slots per
    (receiver, cluster) in delivery order, stored [B, to(*E), C].

    Identical math to models/raft.py compact_inbox (rank = cumsum of
    nonempty over the from-major slot axis, one-hot contraction rather
    than a gather — same reasons), run once fleet-wide instead of at the
    next round's scan entry, so the resident wire is B slots instead of
    K*M. Messages past the bound drop here, which is the same drop set
    the in-round compaction produced one round later."""
    M, K, E = spec.M, spec.K, spec.E
    S = M * K
    B = min(bound, S)
    C = msgs.type.shape[-1]
    t = msgs.type.reshape(S, M, C)
    nonempty = t != 0                                       # [S, to, C]
    rank = jnp.cumsum(nonempty.astype(jnp.int32), axis=0) - 1
    sel = (
        rank[None] == jnp.arange(B, dtype=jnp.int32)[:, None, None, None]
    ) & nonempty[None]                                      # [B, S, to, C]

    def take(name, x):
        e = E if name in _ENT_FIELDS else 1
        xs = x.reshape((S, M) + (() if e == 1 else (e,)) + (C,))
        s = sel if e == 1 else sel[:, :, :, None, :]
        if x.dtype == jnp.bool_:
            out = (s & xs[None]).any(axis=1)
        else:
            out = (s.astype(x.dtype) * xs[None]).sum(axis=1)
        return out.reshape(B, M * e, C)

    return Msg(**{k: take(k, getattr(msgs, k))
                  for k in Msg.__dataclass_fields__})


def _unflatten_compact(spec: Spec, msgs: Msg) -> Msg:
    """Compact storage [B, to(*E), C] -> receiver view [B, to, (E,) C]
    (free reshape); receivers are vmapped over axis 1."""
    M, E = spec.M, spec.E

    def f(name, x):
        if name in _ENT_FIELDS:
            return x.reshape(x.shape[0], M, E, x.shape[-1])
        return x

    return Msg(**{k: f(k, getattr(msgs, k)) for k in Msg.__dataclass_fields__})


def init_fleet(
    spec: Spec,
    C: int,
    voters: jnp.ndarray | None = None,
    learners: jnp.ndarray | None = None,
    seed: int = 0,
    election_tick: int = 10,
) -> NodeState:
    """State pytree with leading [C, M] axes. `voters`/`learners` may be
    [M] (shared) or [C, M] masks."""
    if voters is None:
        voters = jnp.ones((spec.M,), jnp.bool_)
    if voters.ndim == 1:
        voters = jnp.broadcast_to(voters, (C, spec.M))
    if learners is None:
        learners = jnp.zeros((C, spec.M), jnp.bool_)
    elif learners.ndim == 1:
        learners = jnp.broadcast_to(learners, (C, spec.M))

    return _init_fleet_core(
        spec, C, election_tick, voters, learners,
        jnp.asarray(seed, jnp.int32),
    )


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _init_fleet_core(spec: Spec, C: int, election_tick: int,
                     voters, learners, seed):
    """Jitted: an EAGER nested vmap here traced init_node through the
    batching interpreter on every cluster construction (~seconds each;
    at suite scale that tracing dominated wall time)."""

    def one(c, m):
        return init_node(
            spec, m, voters[c], learners[c], seed=c * 1_000_003 + seed,
            election_tick=election_tick,
        )

    # members leading (axis 0), clusters minor (axis -1)
    return jax.vmap(
        lambda m: jax.vmap(lambda c: one(c, m), out_axes=-1)(
            jnp.arange(C, dtype=jnp.int32)
        )
    )(jnp.arange(spec.M, dtype=jnp.int32))


def _node_mask(mask: jnp.ndarray, leaf: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a per-node [M, C] mask to a fleet leaf's [M, ..., C]
    rank by inserting singleton middle axes."""
    extra = leaf.ndim - 2
    return mask.reshape(mask.shape[0], *([1] * extra), mask.shape[-1])


def crash_restart_fleet(
    spec: Spec,
    state: NodeState,
    crashed: jnp.ndarray,
    stable: jnp.ndarray,
    rand_to: jnp.ndarray,
    keep_log: bool | jnp.ndarray = True,
) -> tuple[NodeState, jnp.ndarray]:
    """Crash and immediately restart the masked nodes, keeping only their
    modeled durable state (the classification table in models/state.py:
    DURABLE / CAPPED / REPLAY / VOLATILE).

    ``crashed``/``stable``/``rand_to`` are [M, C]: which nodes crash, each
    node's fsync'd log prefix (entries past it are lost — the fsync-lag
    window), and the restarted node's fresh randomized election timeout.
    ``keep_log=False`` (python bool or traced scalar — the chaos tier
    passes it as a runtime operand so one traced program serves both
    durability models) is the deliberately-broken "persist nothing past
    the snapshot" model (utils/config.py CrashConfig.durability="none")
    used to prove the leader-completeness checker fires.

    Ring slots past the durable last_index are NOT scrubbed: the valid
    window (snap_index, last_index] gates every log read, so the lost
    suffix is unreachable, and future appends overwrite it — same reason
    the reference truncates by cursor, not by zeroing pages.

    Returns (state, entries_lost) where entries_lost counts log entries
    dropped by the fsync-lag (or persist-nothing) wipe this call.
    """
    floor = state.snap_index                       # snapshots fsync eagerly
    durable_last = jnp.where(
        keep_log, jnp.maximum(jnp.minimum(state.last_index, stable), floor),
        floor,
    )
    # commit-only advances never force an fsync (MustSync,
    # raft/node.go:586-593): the persisted commit is capped by the
    # durable log and may legally regress across the crash
    durable_commit = jnp.maximum(jnp.minimum(state.commit, durable_last), floor)
    entries_lost = jnp.where(
        crashed, state.last_index - durable_last, 0
    ).sum().astype(jnp.int32)

    def sel(field: str, restarted: jnp.ndarray) -> jnp.ndarray:
        cur = getattr(state, field)
        return jnp.where(_node_mask(crashed, cur), restarted.astype(cur.dtype), cur)

    zM = jnp.zeros_like(state.match)               # [M, M, C] i32
    fMM = jnp.zeros_like(state.votes_responded)    # [M, M, C] bool
    z2 = jnp.zeros_like(state.commit)              # [M, C] i32
    state = state.replace(
        # CAPPED
        last_index=sel("last_index", durable_last),
        commit=sel("commit", durable_commit),
        # REPLAY: rewind the state machine + applied config to the
        # snapshot; the fused apply loop re-derives the identical hash
        applied=sel("applied", state.snap_index),
        applied_hash=sel("applied_hash", state.snap_hash),
        voters=sel("voters", state.snap_voters),
        voters_out=sel("voters_out", state.snap_voters_out),
        learners=sel("learners", state.snap_learners),
        learners_next=sel("learners_next", state.snap_learners_next),
        auto_leave=sel("auto_leave", state.snap_auto_leave),
        # VOLATILE: fresh-follower boot values
        lead=sel("lead", jnp.full_like(state.lead, NONE_ID)),
        role=sel("role", jnp.full_like(state.role, ROLE_FOLLOWER)),
        election_elapsed=sel("election_elapsed", z2),
        heartbeat_elapsed=sel("heartbeat_elapsed", z2),
        randomized_timeout=sel("randomized_timeout", rand_to),
        match=sel("match", zM),
        next_idx=sel("next_idx", durable_last[:, None, :] + 1),
        pr_state=sel("pr_state", jnp.full_like(state.pr_state, PR_PROBE)),
        probe_sent=sel("probe_sent", fMM),
        pending_snapshot=sel("pending_snapshot", zM),
        recent_active=sel("recent_active", fMM),
        infl_ends=sel("infl_ends", jnp.zeros_like(state.infl_ends)),
        infl_start=sel("infl_start", zM),
        infl_count=sel("infl_count", zM),
        votes_responded=sel("votes_responded", fMM),
        votes_granted=sel("votes_granted", fMM),
        pending_conf_index=sel("pending_conf_index", z2),
        uncommitted_size=sel("uncommitted_size", z2),
        lead_transferee=sel("lead_transferee",
                            jnp.full_like(state.lead_transferee, NONE_ID)),
        ro_ctx=sel("ro_ctx", jnp.zeros_like(state.ro_ctx)),
        ro_index=sel("ro_index", jnp.zeros_like(state.ro_index)),
        ro_from=sel("ro_from", jnp.full_like(state.ro_from, NONE_ID)),
        ro_acks=sel("ro_acks", jnp.zeros_like(state.ro_acks)),
        ro_count=sel("ro_count", z2),
        ro_pend_ctx=sel("ro_pend_ctx", jnp.zeros_like(state.ro_pend_ctx)),
        ro_pend_from=sel("ro_pend_from",
                         jnp.full_like(state.ro_pend_from, NONE_ID)),
        ro_pend_count=sel("ro_pend_count", z2),
        rs_ctx=sel("rs_ctx", jnp.zeros_like(state.rs_ctx)),
        rs_index=sel("rs_index", jnp.zeros_like(state.rs_index)),
        rs_count=sel("rs_count", z2),
        # DURABLE fields (term, vote, log ring, snap_*, nid, rng_key)
        # pass through untouched
    )
    return state, entries_lost


def wipe_crashed_traffic(spec: Spec, inbox: Msg, crashed: jnp.ndarray) -> Msg:
    """Drop every in-flight message FROM or TO a crashed node: its
    unsent/undelivered traffic dies with the process. The FROM wipe is
    load-bearing for the durability model — the engine emits a round's
    messages before the modeled fsync completes, so killing the crashed
    sender's in-flight row is what makes "entries past `stable` are lost"
    safe (no acknowledgement of an unsynced entry is ever delivered,
    the lockstep analog of the Ready contract's persist-before-send).
    The TO wipe is plain message loss, always legal by the transport
    contract (etcdserver/raft.go:107-110). Only the type leaf is zeroed —
    type 0 means "empty slot" and the other fields are never read."""
    M, K = spec.M, spec.K
    C = inbox.type.shape[-1]
    t5 = inbox.type.reshape(M, K, M, C)            # [from, K, to, C] view
    kill = crashed[:, None, None, :] | crashed[None, None, :, :]
    t5 = jnp.where(kill, 0, t5)
    return inbox.replace(type=t5.reshape(M, K * M, C).astype(inbox.type.dtype))


def snapshot_window_mask(spec: Spec, state: NodeState,
                         inbox: Msg) -> jnp.ndarray:
    """[M, C] bool: lanes inside the snapshot-install window this round —
    a MsgSnap is in flight TO the node (the follower is about to install),
    or the node is a leader with a peer in PR_SNAPSHOT (snapshot sent, ack
    not yet processed — which also covers the follower's installed-but-
    unacked round, since the leader stays PR_SNAPSHOT until the MsgAppResp
    lands). The chaos tier's targeted crash scheduler concentrates crash
    probability here instead of waiting for Bernoulli luck to land a kill
    in the (rare) window; ``inbox`` is the FLAT storage form
    ([from, K*to, C] type leaf, int16 or int32 wire)."""
    M, K = spec.M, spec.K
    C = inbox.type.shape[-1]
    t5 = inbox.type.reshape(M, K, M, C)                 # [from, K, to, C]
    snap_to = (t5 == MSG_SNAP).any(axis=(0, 1))         # [to, C]
    snap_from = (state.role == ROLE_LEADER) & (
        state.pr_state == PR_SNAPSHOT).any(axis=1)      # [M, C]
    return snap_to | snap_from


def member_window_mask(spec: Spec, state: NodeState) -> jnp.ndarray:
    """[M, C] bool: membership-sensitive lanes — the node's applied config
    is joint, or a committed-but-unapplied conf-change entry sits in its
    (applied, commit] window (the batched form of ops/log.py
    count_pending_conf). These are the regimes where reconfiguration bugs
    live — a leaving leader stepping down, a change committed under one
    quorum rule but not yet switched — so the chaos tier's targeted crash
    scheduler can concentrate kills on them."""
    L = spec.L
    li = state.last_index[:, None, :]                   # [M, 1, C]
    idxs = jnp.arange(L, dtype=jnp.int32)[None, :, None]
    ent_idx = li - (((li - 1) % L) - idxs) % L          # index living at slot
    pend_cc = (
        (ent_idx > state.applied[:, None, :])
        & (ent_idx <= state.commit[:, None, :])
        & (ent_idx > state.snap_index[:, None, :])
        & (state.log_type == ENTRY_CONF_CHANGE)
    ).any(axis=1)                                       # [M, C]
    return state.voters_out.any(axis=1) | pend_cc


def build_round(cfg: RaftConfig, spec: Spec, with_drop_count: bool = False):
    """Returns round_fn(state, inbox, prop_len, prop_data, prop_type,
    ri_ctx, do_hup, do_tick, keep_mask) -> (state, next_inbox).

    Shapes (clusters-minor): state/* leaves [M, ..., C]; inbox leaves
    FLAT [M(from), K*M(to)(*E), C] (see empty_inbox);
    prop_len/ri_ctx/do_hup/do_tick [M, C]; prop_data/prop_type [M, E, C];
    keep_mask [M(from), M(to), C] bool (True = deliver).

    Delivery is transpose-free: each node reads the fleet message tensor
    along its `to` axis (the outer vmap maps the inbox over axis 2) and
    writes its outbox with its own id on axis 0, so the masked outbox IS
    the next inbox. The old explicit swapaxes materialized multi-GB
    relayout copies at fleet C (XLA put the tiny K/E axes layout-minor).

    with_drop_count: also return the number of emitted messages the
    keep-mask killed this round (for the metrics pipeline). Under
    cfg.compact_wire the count additionally includes messages past the
    inbox bound — the same drop set the dense program realizes one round
    later at scan-entry compaction, counted at the boundary where it now
    happens.

    cfg.packed_state: the state argument/result is the PackedFleet
    storage form (models/state.py); unpack/repack run inside _core, so
    with fleet_chunks > 1 the unpacked temps are chunk-local and only
    the packed fleet stays resident.
    """
    if cfg.packed_state and 2 * cfg.election_tick >= (1 << PACK_TIMER_BITS):
        # the randomized timeout is drawn in [T, 2T); a draw that cannot
        # fit the packed timer lane would corrupt election timing
        raise ValueError(
            f"packed_state timer lanes hold {PACK_TIMER_BITS} bits; "
            f"election_tick={cfg.election_tick} needs 2*T < "
            f"{1 << PACK_TIMER_BITS}")
    node_fn = functools.partial(node_round, cfg, spec)
    # inner vmap: cluster axis (minor); outer vmap: member axis — state
    # and inputs on axis 0, the inbox on its `to` axis (2 dense,
    # 1 compact)
    inner = jax.vmap(node_fn, in_axes=-1, out_axes=-1)
    vmapped = jax.vmap(
        inner, in_axes=(0, 1 if cfg.compact_wire else 2, 0, 0, 0, 0, 0, 0)
    )

    def _core(
        state: NodeState,
        inbox: Msg,
        prop_len,
        prop_data,
        prop_type,
        ri_ctx,
        do_hup,
        do_tick,
        keep_mask,
    ):
        if cfg.packed_state:
            state = unpack_fleet(spec, state)
        if cfg.wire_int16:
            inbox = from_wire(inbox)
        if cfg.compact_wire:
            inbox_v = _unflatten_compact(spec, inbox)   # [B, to, (E,) C]
        else:
            inbox_v = _unflatten_inbox(spec, inbox)     # free reshape
        state, ob = vmapped(
            state, inbox_v, prop_len, prop_data, prop_type, ri_ctx, do_hup,
            do_tick,
        )
        # ob.msgs leaves are the per-node flat form batched:
        # [from, K*to(*E), C] — already the dense inbox storage format
        msgs = _unflatten_inbox(spec, ob.msgs)  # [from, K, to, (E,) C] view
        # self-loops (MsgHup-to-self etc.) are local, never subject to faults
        keep = keep_mask | jnp.eye(spec.M, dtype=jnp.bool_)[:, :, None]
        emitted = (msgs.type != 0).sum() if with_drop_count else None
        msgs = msgs.replace(type=jnp.where(keep[:, None, :, :], msgs.type, 0))
        if cfg.compact_wire:
            next_inbox = compact_wire_carry(spec, msgs, cfg.inbox_bound)
        else:
            next_inbox = _flatten_inbox(spec, msgs)  # flat storage form
        if cfg.wire_int16:
            next_inbox = to_wire(next_inbox)
        if cfg.packed_state:
            state = pack_fleet(spec, state)
        if with_drop_count:
            dropped = emitted - (next_inbox.type != 0).sum()
            return state, next_inbox, dropped
        return state, next_inbox

    if cfg.fleet_chunks <= 1:
        return _core

    def round_fn(*args):
        # Sequential chunking over the (trailing, independent) clusters
        # axis: bounds peak HLO-temp memory at ~1/chunks while the whole
        # fleet stays resident (see RaftConfig.fleet_chunks). Results are
        # written back with dynamic_update_slice on the carried state/inbox
        # values — the in-place idiom XLA aliases inside loop carries and
        # donated calls, so the fleet is single-buffered (a concatenate
        # stitch materialized a second full fleet and re-OOMed at 1M).
        # The chunk sweep is a fori_loop whose carry IS the fleet, updated
        # by dynamic_update_slice — the canonical XLA in-place loop-carry
        # idiom (KV-cache-style), so the fleet stays single-buffered while
        # only one chunk's temps are ever live. (A Python-level chunk loop
        # was tried first: with optimization_barrier sequencing, the
        # barrier's lowering defeated donation aliasing; without it, the
        # scheduler overlapped chunk temp sets. Both re-OOMed at 1M.)
        # Chunk i+1 slices from the updated carry: its region is untouched
        # by earlier writes, so per-cluster math is unchanged. (With
        # cfg.packed_state the sliced carry is the PackedFleet — the
        # unpacked form exists only inside _core, per chunk.)
        C = jax.tree.leaves(args[0])[0].shape[-1]
        chunks = cfg.fleet_chunks
        if C % chunks:
            return _core(*args)
        csz = C // chunks
        rest = args[2:]

        def body(i, carry):
            state, inbox, dropped = carry
            start = i * csz

            def sl(x):
                return jax.lax.dynamic_slice_in_dim(x, start, csz, -1)

            a_i = (
                jax.tree.map(sl, state),
                jax.tree.map(sl, inbox),
            ) + tuple(jax.tree.map(sl, r) for r in rest)
            out = _core(*a_i)

            def wr(full, part):
                return jax.lax.dynamic_update_slice_in_dim(
                    full, part, start, -1
                )

            state = jax.tree.map(wr, state, out[0])
            inbox = jax.tree.map(wr, inbox, out[1])
            if with_drop_count:
                dropped = dropped + out[2]
            return (state, inbox, dropped)

        state, inbox, dropped = jax.lax.fori_loop(
            0, chunks, body, (args[0], args[1], jnp.int32(0))
        )
        if with_drop_count:
            return state, inbox, dropped
        return state, inbox

    return round_fn


def build_kv_round(cfg: RaftConfig, spec: Spec, kvspec, member: int = 0):
    """Round step with the device-resident MVCC apply plane fused in:
    consensus round, then up to Spec.A committed entry words consumed
    straight from ``member``'s apply frontier into a
    ``device_mvcc.KVState`` fleet, then the watch-delta scan.

    Returns kv_round_fn(state, inbox, kv, do_apply, *round_args) ->
    (state, inbox, kv, delta). ``do_apply`` is a RUNTIME operand ([C]
    bool or scalar): False leaves the KV fleet untouched, so ONE traced
    program serves both apply modes (host-apply pulls the same words
    through numpy, exactly like kvserver._pump) — the same
    one-trace/many-operands discipline as the chaos knobs.

    The plane consumes entries in (kv.applied_idx, state.applied[member]]
    — the entries the node itself just applied to its hash chain
    (models/raft.py apply_round), so the KV store advances at the same
    <=A-per-round cadence and words can never outrun it.  Ring
    compaction only moves cursors (snap_index), never scrubs slots, so
    the plane may read below snap_index; a word is lost only once a
    newer entry physically overwrites its slot (idx <= last_index - L).
    Lost words are counted in kv.skipped and the cursor jumps
    (unreachable while the apply cadence A covers the per-round append
    rate, as every current caller does).

    PEER SNAPSHOTS: a member that installs MsgSnap (models/raft.py
    handle_snapshot) keeps its old ring bytes under new cursors, so its
    slots no longer index-match and replay would corrupt the lane.  The
    plane binds to a member that must not be a snapshot receiver (every
    current caller binds the leader lane); installs are DETECTED by the
    one sound signal available — ring apply advances `applied` by at
    most Spec.A per round, so a larger jump can only be an install —
    and the lane freezes with the sticky kv.desynced flag set rather
    than diverging silently.  An install whose jump happens to be <= A
    escapes this detector; recovering a desynced lane needs a KV-state
    snapshot transfer (ROADMAP apply-plane follow-ons).  Conf-change
    and empty (leader-election) entries decode as NOPs by construction.

    KV words exceed the int16 wire (scheme.py layout: up to 28 bits), so
    device-apply fleets require wire_int16=False — same rule, same
    reason as the membership chaos tier's conf-change words.
    """
    from etcd_tpu.device_mvcc.apply import apply_word, extract_deltas

    if cfg.wire_int16:
        raise ValueError(
            "build_kv_round needs the int32 wire (KV op words use bits "
            "0-27); construct the engine with wire_int16=False"
        )
    if cfg.packed_state or cfg.compact_wire:
        # the apply plane reads the bound member's log ring / applied
        # cursor straight off the round's NodeState result — it needs the
        # unpacked fleet and the dense wire (same class of restriction as
        # the int16 rule above)
        raise ValueError(
            "build_kv_round reads the unpacked fleet (log ring, applied "
            "cursor); construct it with packed_state=False and "
            "compact_wire=False"
        )
    base = build_round(cfg, spec)
    L = spec.L

    def kv_round_fn(state, inbox, kv, do_apply, *args):
        pre_applied = state.applied[member]            # [C]
        state, inbox = base(state, inbox, *args)
        do_apply = jnp.broadcast_to(
            jnp.asarray(do_apply, jnp.bool_), kv.current_rev.shape
        )
        rev_floor = kv.current_rev
        applied = state.applied[member]                # [C]
        ld = state.log_data[member]                    # [L, C]
        lt = state.log_type[member]                    # [L, C]
        # snapshot-install detector (see docstring): ring apply can
        # advance `applied` by at most A per round — a bigger jump means
        # handle_snapshot fired and the ring no longer index-matches
        kv = kv.replace(desynced=kv.desynced | (
            do_apply & (applied - pre_applied > spec.A)
        ))
        live = do_apply & ~kv.desynced
        # ring-overwrite overrun: a slot is gone only once a newer entry
        # physically lands on it — count the lost words, jump the cursor
        floor = jnp.maximum(state.last_index[member] - L, 0)
        lost = jnp.where(
            live, jnp.maximum(floor - kv.applied_idx, 0), 0
        )
        kv = kv.replace(
            skipped=kv.skipped + lost,
            applied_idx=jnp.where(live,
                                  jnp.maximum(kv.applied_idx, floor),
                                  kv.applied_idx),
        )

        def body(kvc, _):
            idx = kvc.applied_idx + 1
            can = live & (idx <= applied)
            slot = (idx - 1) % L                       # [C]
            word = jnp.take_along_axis(ld, slot[None, :], axis=0)[0]
            etype = jnp.take_along_axis(lt, slot[None, :], axis=0)[0]
            word = jnp.where(can & (etype == ENTRY_NORMAL), word, 0)
            kvc = apply_word(kvspec, kvc, word, can)
            kvc = kvc.replace(
                applied_idx=jnp.where(can, idx, kvc.applied_idx)
            )
            return kvc, None

        kv, _ = jax.lax.scan(body, kv, None, length=spec.A)
        delta = extract_deltas(kvspec, rev_floor, kv)
        return state, inbox, kv, delta

    return kv_round_fn


@functools.lru_cache(maxsize=64)
def _jitted_kv_round(cfg: RaftConfig, spec: Spec, kvspec, member: int = 0):
    """One traced+jitted KV round program per (cfg, spec, kvspec, member)
    — same sharing rationale as _jitted_round."""
    return jax.jit(build_kv_round(cfg, spec, kvspec, member))


@functools.lru_cache(maxsize=64)
def _jitted_round(cfg: RaftConfig, spec: Spec, donate: bool = False):
    """One traced+jitted round program per (cfg, spec, donate), shared by
    every RaftEngine. Re-jitting per engine instance re-traces the whole
    round (~seconds of pjit tracing each) — at suite scale that tracing,
    not execution, dominated wall time.

    ``donate=True`` donates the fleet carry (state + inbox): XLA aliases
    the output buffers onto the inputs, so a dispatch updates the fleet
    in place instead of holding two copies across it — the difference
    between chunk-free and chunk-forced at large C. The caller's old
    references are DELETED by the runtime after the call (reuse raises
    a deleted-buffer error; tests/test_donation.py); interactive/debug
    drivers that re-inspect a pre-round fleet must keep donate=False."""
    return jax.jit(build_round(cfg, spec),
                   donate_argnums=(0, 1) if donate else ())


class RaftEngine:
    """Jitted lockstep driver for a fleet of C x M-member Raft groups."""

    def __init__(
        self,
        spec: Spec = Spec(),
        cfg: RaftConfig = RaftConfig(),
        C: int = 1,
        voters=None,
        learners=None,
        seed: int = 0,
        donate: bool = False,
    ):
        """``donate=False`` (the default) is the interactive/debug path:
        every round's input buffers stay live, so callers may hold and
        re-inspect ``engine.state`` snapshots across steps. Perf drivers
        pass donate=True to single-buffer the fleet (step() reassigns
        the carry, so the engine itself never reuses a donated ref)."""
        self.spec, self.cfg, self.C = spec, cfg, C
        self.state = init_fleet(
            spec, C, voters, learners, seed, election_tick=cfg.election_tick
        )
        if cfg.packed_state:
            self.state = pack_fleet(spec, self.state)
        self.inbox = empty_inbox(
            spec, C, wire_int16=cfg.wire_int16,
            compact_bound=cfg.inbox_bound if cfg.compact_wire else 0,
        )
        self.keep_mask = jnp.ones((spec.M, spec.M, C), jnp.bool_)
        self._round = _jitted_round(cfg, spec, donate)

    # -- one lockstep round -------------------------------------------------
    def step(
        self,
        prop_len=None,
        prop_data=None,
        prop_type=None,
        ri_ctx=None,
        do_hup=None,
        do_tick=False,
    ):
        """All inputs use the device (clusters-minor) layout:
        prop_len/ri_ctx/do_hup/do_tick [M, C]; prop_data/prop_type
        [M, E, C]."""
        C, M, E = self.C, self.spec.M, self.spec.E
        z2 = jnp.zeros((M, C), jnp.int32)
        prop_len = z2 if prop_len is None else jnp.asarray(prop_len, jnp.int32)
        prop_data = (
            jnp.zeros((M, E, C), jnp.int32)
            if prop_data is None
            else jnp.asarray(prop_data, jnp.int32)
        )
        prop_type = (
            jnp.zeros((M, E, C), jnp.int32)
            if prop_type is None
            else jnp.asarray(prop_type, jnp.int32)
        )
        ri_ctx = z2 if ri_ctx is None else jnp.asarray(ri_ctx, jnp.int32)
        do_hup = (
            jnp.zeros((M, C), jnp.bool_)
            if do_hup is None
            else jnp.asarray(do_hup, jnp.bool_)
        )
        if isinstance(do_tick, bool):
            do_tick = jnp.full((M, C), do_tick, jnp.bool_)
        else:
            do_tick = jnp.asarray(do_tick, jnp.bool_)
        self.state, self.inbox = self._round(
            self.state,
            self.inbox,
            prop_len,
            prop_data,
            prop_type,
            ri_ctx,
            do_hup,
            do_tick,
            self.keep_mask,
        )
        return self.state

    # lint: allow-def(host-sync) -- host probe on the eager facade, not in the round program

    def pending_messages(self) -> int:
        return int((self.inbox.type != 0).sum())
