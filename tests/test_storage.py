"""Durability layer: WAL codec (C++ + fallback), segmented WAL replay and
torn-tail repair, fleet checkpoint/restore determinism — the analog of the
reference's wal/wal_test.go + repair_test.go + snap tests."""
import os
import struct

import numpy as np
import pytest

from etcd_tpu.storage import walcodec
from etcd_tpu.storage.wal import REC_ENTRIES, WAL
from etcd_tpu.storage.checkpoint import FleetCheckpointer, load_fleet, save_fleet


def test_codec_roundtrip_both_impls():
    py = walcodec._PyCodec()
    impls = [py]
    native = walcodec._build_native()
    if native is not None:
        impls.append(native)
    for codec in impls:
        crc = 0
        frames = []
        payloads = [b"hello", b"", b"x" * 1000, bytes(range(256))]
        for p in payloads:
            frame, crc = codec.encode(REC_ENTRIES, p, crc)
            assert len(frame) % 8 == 1  # header 9 + pad8(payload)
            frames.append(frame)
        buf = memoryview(b"".join(frames))
        crc = 0
        off = 0
        out = []
        while off < len(buf):
            hit = codec.decode(buf, off, crc)
            assert hit is not None
            consumed, rtype, payload, crc = hit
            off += consumed
            out.append(payload)
        assert out == payloads


def test_codec_native_matches_python():
    native = walcodec._build_native()
    if native is None:
        pytest.skip("g++ unavailable")
    py = walcodec._PyCodec()
    crc_n = crc_p = 0
    frames = []
    for p in [b"abc", b"", b"payload" * 99]:
        fn, crc_n = native.encode(7, p, crc_n)
        fp, crc_p = py.encode(7, p, crc_p)
        assert fn == fp and crc_n == crc_p
        frames.append(fn)
    # cross-decode: python reads what C++ framed
    buf = memoryview(b"".join(frames))
    crc = off = 0
    for want in [b"abc", b"", b"payload" * 99]:
        consumed, rtype, payload, crc = py.decode(buf, off, crc)
        assert rtype == 7 and payload == want
        off += consumed


def test_wal_save_and_replay(tmp_path):
    d = str(tmp_path / "wal")
    w = WAL(d, metadata=b"cluster-0")
    w.save({"term": 1, "vote": 0, "commit": 0},
           [{"index": 1, "term": 1, "data": 11, "type": 0}])
    w.save({"term": 1, "vote": 0, "commit": 1},
           [{"index": 2, "term": 1, "data": 22, "type": 0}])
    w.close()
    w2 = WAL(d)
    meta, hs, ents, snap = w2.read_all()
    assert meta == b"cluster-0"
    assert hs == {"term": 1, "vote": 0, "commit": 1}
    assert [e["index"] for e in ents] == [1, 2]
    assert snap is None
    w2.close()


def test_wal_truncate_and_append_semantics(tmp_path):
    """A rewritten suffix (leader change truncating uncommitted tail)
    supersedes earlier records at >= its index (log_unstable.go:121)."""
    d = str(tmp_path / "wal")
    w = WAL(d)
    w.save(None, [{"index": 1, "term": 1, "data": 1, "type": 0},
                  {"index": 2, "term": 1, "data": 2, "type": 0},
                  {"index": 3, "term": 1, "data": 3, "type": 0}])
    w.save({"term": 2, "vote": 1, "commit": 1},
           [{"index": 2, "term": 2, "data": 20, "type": 0}])
    w.close()
    _, hs, ents, _ = WAL(d).read_all()
    assert [(e["index"], e["term"]) for e in ents] == [(1, 1), (2, 2)]


def test_wal_torn_tail_repair(tmp_path):
    d = str(tmp_path / "wal")
    w = WAL(d)
    w.save({"term": 1, "vote": 0, "commit": 0},
           [{"index": 1, "term": 1, "data": 5, "type": 0}])
    w.close()
    seg = os.path.join(d, sorted(os.listdir(d))[0])
    good_size = os.path.getsize(seg)
    with open(seg, "ab") as f:
        f.write(b"\x07\x00\x00\x00garbage-torn-tail")
    w2 = WAL(d)
    _, hs, ents, _ = w2.read_all()
    assert [e["data"] for e in ents] == [5]
    assert os.path.getsize(seg) == good_size  # tail truncated in place
    # appends still work after repair
    w2.save(None, [{"index": 2, "term": 1, "data": 6, "type": 0}])
    w2.close()
    _, _, ents, _ = WAL(d).read_all()
    assert [e["data"] for e in ents] == [5, 6]


def test_wal_mid_log_corruption_refuses(tmp_path):
    """Corruption in a non-last segment must fail loudly, not become a
    silent hole (repair.go only tolerates a torn LAST file)."""
    import etcd_tpu.storage.wal as walmod

    d = str(tmp_path / "wal")
    old = walmod.SEGMENT_BYTES
    walmod.SEGMENT_BYTES = 256  # force multiple segments
    try:
        w = WAL(d)
        for i in range(1, 30):
            w.save(None, [{"index": i, "term": 1, "data": i, "type": 0}])
        w.close()
        segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
        assert len(segs) > 1
        first = os.path.join(d, segs[0])
        data = bytearray(open(first, "rb").read())
        data[len(data) // 2] ^= 0xFF  # flip a bit mid-first-segment
        open(first, "wb").write(bytes(data))
        from etcd_tpu.storage.wal import WALError

        with pytest.raises(WALError):
            WAL(d).read_all()
    finally:
        walmod.SEGMENT_BYTES = old


def test_wal_snapshot_marker_and_release(tmp_path):
    d = str(tmp_path / "wal")
    w = WAL(d)
    for i in range(1, 6):
        w.save(None, [{"index": i, "term": 1, "data": i, "type": 0}])
    w.save_snapshot(index=3, term=1)
    w.close()
    _, _, ents, snap = WAL(d).read_all()
    assert snap == {"index": 3, "term": 1}
    assert [e["index"] for e in ents] == [4, 5]  # replay from the snapshot


def test_fleet_checkpoint_roundtrip(tmp_path):
    from etcd_tpu.harness.cluster import Cluster

    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 42)
    cl.stabilize()
    path = str(tmp_path / "fleet.npz")
    save_fleet(path, cl.s, round_no=7)
    state, meta = load_fleet(path)
    assert meta["round"] == 7
    for name in ("term", "commit", "log_data", "match", "rng_key"):
        np.testing.assert_array_equal(
            np.asarray(getattr(state, name)), np.asarray(getattr(cl.s, name))
        )
    # restored state drives the engine identically (deterministic resume)
    cl.eng.state = state
    cl.propose(0, 43)
    cl.stabilize()
    # log: [empty@1, 42@2, 43@3] -> commit 3 everywhere
    assert cl.commits().tolist() == [3, 3, 3]


def test_checkpointer_rotation(tmp_path):
    from etcd_tpu.harness.cluster import Cluster

    cl = Cluster(n_members=3)
    ck = FleetCheckpointer(str(tmp_path / "ck"), every=2, keep=2)
    saved = sum(ck.maybe_save(cl.s) for _ in range(10))
    assert saved == 5
    snaps = [f for f in os.listdir(ck.dir) if f.endswith(".npz")]
    assert len(snaps) == 2  # retention
    st, meta = ck.restore()
    assert meta["round"] == 10
