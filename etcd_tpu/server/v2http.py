"""The v2 REST façade — /v2/keys, /v2/members, /v2/stats.

Re-design of ``server/etcdserver/api/v2http`` (client.go keysHandler +
parseKeyRequest:346-527, membersHandler, statsHandler) for this
framework's gateway: requests arrive as (method, path, form) triples —
from the JSON/query HTTP server or in-process from clientv2 — and are
parsed with the reference's exact validation ladder and error codes,
then routed through :class:`EtcdCluster`'s consensus front (writes and
quorum reads) or served from the applied tree (plain reads).

Watch (GET ?wait=true) follows this gateway's long-poll convention (see
server/v3rpc.py's watch): if the event is already in history it returns
immediately; otherwise the watcher parks in a registry and the client
polls ``watch_poll`` — the blocking-HTTP analog collapsed to polling,
like the v3 façade's JSON long-poll stands in for gRPC streams.
"""
from __future__ import annotations

import time
from typing import Any

from etcd_tpu.models.changer import ConfChangeError
from etcd_tpu.server.kvserver import EtcdCluster, ServerError
from etcd_tpu.server.v2store import (
    _clean_path,
    EcodeIndexNaN,
    EcodeInvalidField,
    EcodePrevValueRequired,
    EcodeRaftInternal,
    EcodeRefreshTTLRequired,
    EcodeRefreshValue,
    EcodeTTLNaN,
    EcodeUnauthorized,
    EcodeWatcherCleared,
    Event,
    V2Error,
)

KEYS_PREFIX = "/v2/keys"


def _strlist(v) -> list[str] | None:
    """Form lists arrive either as JSON lists or comma strings."""
    if v is None or v == "":
        return None
    if isinstance(v, list):
        return [str(x) for x in v]
    return [s for s in str(v).split(",") if s]


def _get_bool(form: dict, name: str) -> bool:
    """getBool (v2http/http.go): absent = false, 'true'/'false' only."""
    v = form.get(name)
    if v is None:
        return False
    if isinstance(v, bool):
        return v
    if v == "true":
        return True
    if v == "false":
        return False
    raise V2Error(EcodeInvalidField, f'invalid value for "{name}"')


def _get_uint(form: dict, name: str, code: int) -> int:
    v = form.get(name)
    if v is None or v == "":
        return 0
    try:
        i = int(v)
        if i < 0:
            raise ValueError
        return i
    except (TypeError, ValueError):
        raise V2Error(code, f'invalid value for "{name}"') from None


def parse_key_request(method: str, form: dict) -> dict:
    """parseKeyRequest (v2http/client.go:346-527): the validation ladder,
    same codes, same order. Returns the RequestV2-shaped dict."""
    prev_index = _get_uint(form, "prevIndex", EcodeIndexNaN)
    wait_index = _get_uint(form, "waitIndex", EcodeIndexNaN)
    recursive = _get_bool(form, "recursive")
    sorted_ = _get_bool(form, "sorted")
    wait = _get_bool(form, "wait")
    dir_ = _get_bool(form, "dir")
    quorum = _get_bool(form, "quorum")
    stream = _get_bool(form, "stream")
    if wait and method != "GET":
        raise V2Error(EcodeInvalidField,
                      '"wait" can only be used with GET requests')
    prev_value = form.get("prevValue", "")
    if "prevValue" in form and prev_value == "":
        raise V2Error(EcodePrevValueRequired,
                      '"prevValue" cannot be empty')
    no_value_on_success = _get_bool(form, "noValueOnSuccess")
    ttl = None
    if form.get("ttl") not in (None, ""):
        ttl = _get_uint(form, "ttl", EcodeTTLNaN)
    prev_exist = None
    if "prevExist" in form:
        prev_exist = _get_bool(form, "prevExist")
    refresh = None
    if "refresh" in form:
        refresh = _get_bool(form, "refresh")
        if refresh:
            if form.get("value"):
                raise V2Error(EcodeRefreshValue,
                              "A value was provided on a refresh")
            if ttl is None:
                raise V2Error(EcodeRefreshTTLRequired, "No TTL value set")
    return {
        "method": method, "value": form.get("value", ""), "dir": dir_,
        "prev_value": prev_value, "prev_index": prev_index,
        "prev_exist": prev_exist, "wait": wait, "wait_index": wait_index,
        "recursive": recursive, "sorted": sorted_, "quorum": quorum,
        "stream": stream, "refresh": bool(refresh), "ttl": ttl,
        "no_value_on_success": no_value_on_success,
    }


class V2Api:
    """keysHandler + membersHandler + statsHandler + the v2auth admin
    surface (client_auth.go) over EtcdCluster."""

    # Parked long-poll watchers that the client never polls again would
    # otherwise leak until their 100-event overflow: evict after
    # PARK_TTL seconds without a poll, and bound the registry size.
    # The TTL scan itself is throttled to SWEEP_EVERY (it is on the
    # long-poll hot path); the cap check runs every time.
    PARK_TTL = 300.0
    PARK_CAP = 1024
    SWEEP_EVERY = 1.0

    def __init__(self, ec: EtcdCluster):
        from etcd_tpu.server.v2auth import V2AuthStore

        self.ec = ec
        self.auth = V2AuthStore(ec)
        self._watches: dict[int, Any] = {}
        self._watch_seen: dict[int, float] = {}
        self._last_sweep = 0.0
        self._next_watch = 1

    @staticmethod
    def _creds(form: dict) -> tuple[str, str] | None:
        ba = form.get("_basic_auth")
        if not ba:
            return None
        user, _, pw = ba.partition(":")
        return (user, pw)

    # ------------------------------------------------------------- keys
    def keys(self, method: str, key: str,
             form: dict | None = None) -> tuple[int, dict, dict]:
        """One /v2/keys request. Returns (status, body, headers)."""
        from etcd_tpu.server.v2auth import AuthError

        form = form or {}
        # Canonicalize BEFORE the auth guard: the store cleans the path
        # at apply time, so guarding the raw string would let
        # //_security/... or /a/../_security/... slip past both the
        # /_security prefix check and pattern matching (the reference
        # gets this from Go's mux canonicalization + path.Join before
        # any store access).
        key = _clean_path(key)
        try:
            r = parse_key_request(method, form)
            # the basic-auth guard (client_auth.go hasKeyPrefixAccess)
            self.auth.check_key_access(
                self._creds(form), key, write=method != "GET",
                recursive=r["recursive"])
            if method == "GET":
                return self._get(key, r)
            if method in ("PUT", "POST", "DELETE"):
                ev = self.ec.v2_request(
                    method, key, val=r["value"], dir=r["dir"],
                    prev_value=r["prev_value"],
                    prev_index=r["prev_index"],
                    prev_exist=r["prev_exist"],
                    recursive=r["recursive"], sorted_=r["sorted"],
                    refresh=r["refresh"], ttl=r["ttl"])
                return self._key_event(ev, r)
            raise V2Error(EcodeInvalidField, f"bad method {method}")
        except AuthError as e:
            # writeNoAuth: surface as the 110 Unauthorized v2 error
            err = V2Error(EcodeUnauthorized, str(e),
                          self._store().current_index)
            return e.status, err.to_json(), self._headers()
        except V2Error as e:
            return e.status_code(), e.to_json(), self._headers()
        except ServerError as e:
            err = V2Error(EcodeRaftInternal, str(e),
                          self._store().current_index)
            return err.status_code(), err.to_json(), self._headers()

    def _store(self):
        return self.ec.members[self.ec.ensure_leader()].v2store

    def _headers(self) -> dict:
        st = self._store()
        return {"X-Etcd-Index": st.current_index}

    def _key_event(self, ev: Event, r: dict) -> tuple[int, dict, dict]:
        # writeKeyEvent: 201 on create, else 200; noValueOnSuccess trims
        status = 201 if ev.is_created() else 200
        body = ev.to_json()
        if r.get("no_value_on_success"):
            body = dict(body)
            node = dict(body["node"])
            node.pop("value", None)
            node.pop("nodes", None)
            body["node"] = node
            body.pop("prevNode", None)
        return status, body, self._headers()

    def _get(self, key: str, r: dict) -> tuple[int, dict, dict]:
        if r["wait"]:
            return self._watch(key, r)
        if r["quorum"]:
            ev = self.ec.v2_request("QGET", key, recursive=r["recursive"],
                                    sorted_=r["sorted"])
        else:
            ev = self.ec.v2_get(key, r["recursive"], r["sorted"])
        return 200, ev.to_json(), self._headers()

    def _watch(self, key: str, r: dict) -> tuple[int, dict, dict]:
        w = self.ec.v2_watch(key, recursive=r["recursive"],
                             stream=r["stream"],
                             since_index=r["wait_index"])
        ev = w.poll()
        if ev is not None and not r["stream"]:
            w.remove()
            return 200, ev.to_json(), self._headers()
        self._evict_stale_watches(reserve=1)
        wid = self._next_watch
        self._next_watch += 1
        self._watches[wid] = w
        self._watch_seen[wid] = time.monotonic()
        out: dict[str, Any] = {"watch_id": wid}
        if ev is not None:  # stream watcher with a ready history event
            out["event"] = ev.to_json()
        return 200, out, self._headers()

    def _evict_stale_watches(self, reserve: int = 0) -> None:
        """`reserve` slots are held back for an imminent registration;
        plain polls pass 0 so a registry sitting exactly at PARK_CAP is
        not trimmed by unrelated traffic."""
        now = time.monotonic()
        if now - self._last_sweep >= self.SWEEP_EVERY:
            self._last_sweep = now
            for wid, t in list(self._watch_seen.items()):
                if now - t <= self.PARK_TTL:
                    continue
                w = self._watches.get(wid)
                if w is None or w.cleared:
                    # poisoned tombstone outlived its grace window
                    # unclaimed: drop it for good
                    self.watch_cancel(wid)
                else:
                    # free the store-side watcher now, but keep a
                    # poisoned tombstone for one more TTL window so a
                    # returning client gets EcodeWatcherCleared (the
                    # re-watch signal) instead of a bare miss
                    w.cleared = True
                    w.remove()
                    self._watch_seen[wid] = now
        # over cap even after the TTL pass: shed dead tombstones first,
        # then oldest live watches
        excess = len(self._watches) - (self.PARK_CAP - reserve)
        if excess > 0:
            order = sorted(
                self._watch_seen,
                key=lambda i: (not self._watches[i].cleared,
                               self._watch_seen[i]))
            for wid in order[:excess]:
                self.watch_cancel(wid)

    def watch_poll(self, watch_id: int) -> tuple[int, dict, dict]:
        w = self._watches.get(watch_id)
        if w is not None:
            # refresh BEFORE the sweep so a poll always keeps its own
            # watch alive, even arriving just past PARK_TTL
            self._watch_seen[watch_id] = time.monotonic()
        self._evict_stale_watches()
        if w is None:
            # cap-shed, cancelled, or tombstone expired: same 400 +
            # cleared errorCode as the poisoned path, so every "this
            # watch is gone, re-watch" condition looks identical
            err = V2Error(EcodeWatcherCleared, "unknown or evicted watch",
                          self._store().current_index)
            return err.status_code(), err.to_json(), self._headers()
        try:
            ev = w.poll()
        except V2Error as e:
            # EcodeWatcherCleared after recovery/overflow/eviction:
            # surface the error once with the current store index (the
            # v2 re-watch recipe is waitIndex=index+1), then forget the
            # watch (store.go WatcherHub clear semantics)
            if not e.index:
                e.index = self._store().current_index
            self.watch_cancel(watch_id)
            return e.status_code(), e.to_json(), self._headers()
        if ev is None:
            return 200, {}, self._headers()
        if not w.stream:
            w.remove()
            del self._watches[watch_id]
            self._watch_seen.pop(watch_id, None)
        return 200, {"event": ev.to_json()}, self._headers()

    def watch_cancel(self, watch_id: int) -> None:
        w = self._watches.pop(watch_id, None)
        self._watch_seen.pop(watch_id, None)
        if w is not None:
            w.remove()

    # ---------------------------------------------------------- members
    def members(self, method: str, suffix: str = "",
                form: dict | None = None) -> tuple[int, dict, dict]:
        form = form or {}
        try:
            if method == "GET":
                cfg = self.ec.member_config()
                return 200, {"members": [
                    {"id": str(i), "name": f"member{i}",
                     "isLearner": i in cfg.learners}
                    for i in sorted(cfg.progress)
                ]}, self._headers()
            if method == "POST":
                mid = int(form["id"])
                self.ec.member_add(mid,
                                   learner=bool(form.get("isLearner")))
                return 201, {"id": str(mid)}, self._headers()
            if method == "DELETE":
                self.ec.member_remove(int(suffix.strip("/")))
                return 204, {}, self._headers()
            return 405, {"error": "method not allowed"}, self._headers()
        except (ServerError, ConfChangeError, ValueError, KeyError) as e:
            return 500, {"message": str(e)}, self._headers()

    # ------------------------------------------------------- auth admin
    def auth_admin(self, method: str, path: str,
                   form: dict | None = None) -> tuple[int, dict, dict]:
        """/v2/auth/{enable,users[/name],roles[/name]} — the
        client_auth.go handler surface. Admin ops require root once
        auth is enabled (hasRootAccess)."""
        from etcd_tpu.server.v2auth import AuthError

        form = form or {}
        creds = self._creds(form)
        a = self.auth
        try:
            if not a.is_root(creds):
                raise AuthError(401, "permission denied")
            parts = [p for p in path.strip("/").split("/") if p]
            kind = parts[0] if parts else ""
            name = parts[1] if len(parts) > 1 else None
            if kind == "enable":
                if method == "GET":
                    return 200, {"enabled": a.auth_enabled()}, \
                        self._headers()
                if method == "PUT":
                    a.enable_auth()
                    return 200, {"enabled": True}, self._headers()
                if method == "DELETE":
                    a.disable_auth()
                    return 200, {"enabled": False}, self._headers()
            if kind == "users":
                if method == "GET" and name is None:
                    return 200, {"users": a.all_users()}, self._headers()
                if method == "GET":
                    u = dict(a.get_user(name))
                    u.pop("password", None)
                    return 200, u, self._headers()
                if method == "PUT":
                    if form.get("grant") or form.get("revoke") or \
                            a._get(f"/users/{name}") is not None:
                        out = a.update_user(
                            name, password=form.get("password"),
                            grant=_strlist(form.get("grant")),
                            revoke=_strlist(form.get("revoke")))
                        return 200, out, self._headers()
                    out = a.create_user(
                        name, form.get("password", ""),
                        _strlist(form.get("roles")))
                    return 201, out, self._headers()
                if method == "DELETE":
                    a.delete_user(name)
                    return 200, {}, self._headers()
            if kind == "roles":
                if method == "GET" and name is None:
                    return 200, {"roles": a.all_roles()}, self._headers()
                if method == "GET":
                    return 200, a.get_role(name), self._headers()
                if method == "PUT":
                    if form.get("grant") or form.get("revoke"):
                        out = a.update_role(name,
                                            grant=form.get("grant"),
                                            revoke=form.get("revoke"))
                        return 200, out, self._headers()
                    out = a.create_role(name, form.get("permissions"))
                    return 201, out, self._headers()
                if method == "DELETE":
                    a.delete_role(name)
                    return 200, {}, self._headers()
            return 404, {"message": f"unknown auth path {path}"}, \
                self._headers()
        except AuthError as e:
            return e.status, {"message": str(e)}, self._headers()

    # ------------------------------------------------------------ stats
    def stats(self, which: str) -> tuple[int, dict, dict]:
        if which == "store":
            return 200, self.ec.v2_stats(), self._headers()
        if which == "self":
            lead = self.ec.ensure_leader()
            return 200, {"id": str(lead), "state": "StateLeader"}, \
                self._headers()
        if which == "leader":
            lead = self.ec.ensure_leader()
            return 200, {"leader": str(lead)}, self._headers()
        return 404, {"error": f"unknown stats {which}"}, self._headers()
