"""v2 API emulated on the v3 store — the api/v2v3 analog.

Re-design of ``server/etcdserver/api/v2v3/store.go``: serve the v2store
surface (Get/Set/Update/Create/CompareAndSwap/CompareAndDelete/Delete/
Watch) from the replicated **v3 MVCC** store instead of the legacy v2
tree. The key encoding is the reference's depth scheme
(store.go mkPathDepth): a v2 path at directory depth ``n`` lives at
``{pfx}/{n:03d}/k{path}`` so one prefix range lists a directory level;
directory markers are ``...{path}/`` keys; every mutation also writes
``{pfx}/act`` with the v2 action name inside the same txn so watchers
can recover the action (store.go mkActionKey + watcher.go); v2 indexes
are v3 revisions shifted by one (mkV2Rev/mkV3Rev, store.go:592-604).

Mutations ride v3 txns (Compare on create/mod revision stands in for
the reference's STM), so everything replicates through the same device
consensus path as any other v3 write.
"""
from __future__ import annotations

from typing import Any

from etcd_tpu.server.kvserver import Compare, EtcdCluster, Op
from etcd_tpu.server.v2store import (
    EcodeDirNotEmpty,
    EcodeKeyNotFound,
    EcodeNodeExist,
    EcodeNotDir,
    EcodeNotFile,
    EcodeRootROnly,
    EcodeTestFailed,
    Event,
    V2Error,
    _clean_path,
)

MAX_DEPTH = 64  # recursive-listing depth bound (v2 paths are shallow)


def mk_v2_rev(v3_rev: int) -> int:
    return 0 if v3_rev == 0 else v3_rev - 1


def mk_v3_rev(v2_rev: int) -> int:
    return 0 if v2_rev == 0 else v2_rev + 1


def _is_root(p: str) -> bool:
    return p in ("", "/", "/0", "/1")


class V2v3Store:
    """store.go v2v3Store over an in-process EtcdCluster."""

    def __init__(self, ec: EtcdCluster, pfx: str = "/__v2"):
        self.ec = ec
        self.pfx = pfx.rstrip("/")

    # ---- key encoding (store.go:566-590)
    def _depth(self, node_path: str) -> int:
        return _clean_path(node_path).count("/")

    def _mk_path(self, node_path: str, depth: int = 0) -> bytes:
        normal = _clean_path(node_path)
        n = normal.count("/") + depth
        return f"{self.pfx}/{n:03d}/k{normal}".encode()

    def _node_path(self, key: bytes) -> str:
        # strip "{pfx}/{ddd}/k" prefix
        s = key.decode()
        return _clean_path(s[len(self.pfx) + 5 + 1:])

    def _act_key(self) -> bytes:
        return (self.pfx + "/act").encode()

    # ---- small kv helpers
    def _get_kv(self, key: bytes):
        kvs = self.ec.range(key)["kvs"]
        return kvs[0] if kvs else None

    def _rev(self) -> int:
        m = self.ec.ensure_leader()
        return self.ec.members[m].store.kv.current_rev

    def _txn(self, compare, success, failure=()) -> dict:
        return self.ec.txn(list(compare), list(success), list(failure))

    def _dir_key(self, node_path: str) -> bytes:
        # a directory marker is the path with a trailing "/" at its depth
        normal = _clean_path(node_path)
        n = normal.count("/")
        return f"{self.pfx}/{n:03d}/k{normal}/".encode()

    def _is_dir(self, node_path: str) -> bool:
        if _is_root(node_path):
            return True
        if self._get_kv(self._dir_key(node_path)) is not None:
            return True
        # implicit dir: any child at depth+1 under the path
        lo = self._mk_path(node_path + "/x", 0)  # depth+1 prefix base
        pref = lo[: lo.rfind(b"/") + 1]
        return bool(self.ec.range(pref, _prefix_end(pref),
                                  limit=1)["kvs"])

    # ---- reads (store.go:51-136)
    def get(self, node_path: str, recursive: bool = False,
            sorted_: bool = False) -> Event:
        node_path = _clean_path(node_path)
        rev = self._rev()
        if not _is_root(node_path):
            kv = self._get_kv(self._mk_path(node_path))
            if kv is not None:
                node = {"key": node_path, "value": kv.value.decode(),
                        "modifiedIndex": mk_v2_rev(kv.mod_revision),
                        "createdIndex": mk_v2_rev(kv.create_revision)}
                return Event("get", node, etcd_index=mk_v2_rev(rev))
            if not self._is_dir(node_path):
                raise V2Error(EcodeKeyNotFound, node_path,
                              mk_v2_rev(rev))
        node = {"key": node_path, "dir": True,
                "nodes": self._get_dir(node_path, recursive, sorted_)}
        if not _is_root(node_path):
            dkv = self._get_kv(self._dir_key(node_path))
            if dkv is not None:
                node["modifiedIndex"] = mk_v2_rev(dkv.mod_revision)
                node["createdIndex"] = mk_v2_rev(dkv.create_revision)
        return Event("get", node, etcd_index=mk_v2_rev(rev))

    def _get_dir(self, node_path: str, recursive: bool,
                 sorted_: bool) -> list[dict]:
        out = self._get_dir_depth(node_path, 1)
        if recursive:
            # deeper levels fold under their parent dict
            by_path = {n["key"]: n for n in out}
            for d in range(2, MAX_DEPTH):
                level = self._get_dir_depth(node_path, d)
                if not level:
                    break
                for n in level:
                    parent = n["key"].rsplit("/", 1)[0]
                    p = by_path.get(parent)
                    if p is None or "value" in p:
                        continue  # orphan (parent hidden) — skip
                    p.setdefault("nodes", [])
                    p["nodes"].append(n)
                    by_path[n["key"]] = n
        if sorted_:
            def walk(ns):
                ns.sort(key=lambda n: n["key"])
                for n in ns:
                    if "nodes" in n:
                        walk(n["nodes"])
            walk(out)
        return out

    def _get_dir_depth(self, node_path: str, depth: int) -> list[dict]:
        base = "" if _is_root(node_path) else _clean_path(node_path)
        n = (base.count("/") if base else 0) + depth
        pref = f"{self.pfx}/{n:03d}/k{base}/".encode()
        kvs = self.ec.range(pref, _prefix_end(pref))["kvs"]
        out: dict[str, dict] = {}
        for kv in kvs:
            s = kv.key.decode()
            p = self._node_path(kv.key)
            name = p.rsplit("/", 1)[-1]
            if name.startswith("_"):
                continue  # hidden
            if s.endswith("/"):  # dir marker
                out.setdefault(p, {
                    "key": p, "dir": True,
                    "modifiedIndex": mk_v2_rev(kv.mod_revision),
                    "createdIndex": mk_v2_rev(kv.create_revision)})
            else:
                out[p] = {"key": p, "value": kv.value.decode(),
                          "modifiedIndex": mk_v2_rev(kv.mod_revision),
                          "createdIndex": mk_v2_rev(kv.create_revision)}
        # implicit dirs: children one level deeper with no marker
        n2 = n + 1
        pref2 = f"{self.pfx}/{n2:03d}/k{base}/".encode()
        kvs2 = self.ec.range(pref2, _prefix_end(pref2))["kvs"]
        for kv in kvs2:
            p = self._node_path(kv.key).rsplit("/", 1)[0]
            name = p.rsplit("/", 1)[-1]
            if not name.startswith("_"):
                out.setdefault(p, {"key": p, "dir": True})
        return list(out.values())

    # ---- writes (store.go:138-265,267-352)
    def set(self, node_path: str, dir: bool = False,
            value: str = "") -> Event:
        node_path = _clean_path(node_path)
        if _is_root(node_path):
            raise V2Error(EcodeRootROnly, "/", mk_v2_rev(self._rev()))
        if dir:
            return self._mkdir("set", node_path, must_create=False)
        if self._is_dir(node_path):
            raise V2Error(EcodeNotFile, node_path,
                          mk_v2_rev(self._rev()))
        key = self._mk_path(node_path)
        prev = self._get_kv(key)
        res = self._txn(
            [], [Op("put", key, value.encode())] +
            self._parent_dirs(node_path) +
            [Op("put", self._act_key(), b"set")])
        rev = res["rev"]
        node = {"key": node_path, "value": value,
                "modifiedIndex": mk_v2_rev(rev),
                "createdIndex": mk_v2_rev(
                    prev.create_revision if prev else rev)}
        e = Event("set", node, etcd_index=mk_v2_rev(rev))
        if prev is not None:
            e.prev_node = {"key": node_path,
                           "value": prev.value.decode(),
                           "modifiedIndex": mk_v2_rev(prev.mod_revision),
                           "createdIndex":
                               mk_v2_rev(prev.create_revision)}
        return e

    def _parent_dirs(self, node_path: str) -> list[Op]:
        # auto-create intermediate dir markers (store.go:154-160)
        ops = []
        parts = _clean_path(node_path).split("/")[1:-1]
        p = ""
        for comp in parts:
            p += "/" + comp
            if not self._is_dir(p):
                ops.append(Op("put", self._dir_key(p), b""))
        return ops

    def _mkdir(self, action: str, node_path: str,
               must_create: bool) -> Event:
        dkey = self._dir_key(node_path)
        if self._get_kv(self._mk_path(node_path)) is not None:
            raise V2Error(EcodeNotDir, node_path,
                          mk_v2_rev(self._rev()))
        if self._get_kv(dkey) is not None:
            if must_create:
                raise V2Error(EcodeNodeExist, node_path,
                              mk_v2_rev(self._rev()))
            rev = self._rev()
            return Event(action, {"key": node_path, "dir": True},
                         etcd_index=mk_v2_rev(rev))
        res = self._txn([], [Op("put", dkey, b"")] +
                        self._parent_dirs(node_path) +
                        [Op("put", self._act_key(), action.encode())])
        rev = res["rev"]
        return Event(action,
                     {"key": node_path, "dir": True,
                      "modifiedIndex": mk_v2_rev(rev),
                      "createdIndex": mk_v2_rev(rev)},
                     etcd_index=mk_v2_rev(rev))

    def create(self, node_path: str, dir: bool = False, value: str = "",
               unique: bool = False) -> Event:
        node_path = _clean_path(node_path)
        if unique:
            # in-order key from the next v2 index (store.go:283-290)
            node_path += "/" + format(mk_v2_rev(self._rev()) + 1, "020d")
        if _is_root(node_path):
            raise V2Error(EcodeRootROnly, "/", mk_v2_rev(self._rev()))
        if dir:
            return self._mkdir("create", node_path, must_create=True)
        if self._is_dir(node_path):
            raise V2Error(EcodeNotFile, node_path,
                          mk_v2_rev(self._rev()))
        key = self._mk_path(node_path)
        res = self._txn(
            [Compare(key, "create", "=", 0)],
            [Op("put", key, value.encode())] +
            self._parent_dirs(node_path) +
            [Op("put", self._act_key(), b"create")])
        if not res["succeeded"]:
            raise V2Error(EcodeNodeExist, node_path,
                          mk_v2_rev(self._rev()))
        rev = res["rev"]
        return Event("create",
                     {"key": node_path, "value": value,
                      "modifiedIndex": mk_v2_rev(rev),
                      "createdIndex": mk_v2_rev(rev)},
                     etcd_index=mk_v2_rev(rev))

    def update(self, node_path: str, new_value: str = "") -> Event:
        node_path = _clean_path(node_path)
        if _is_root(node_path):
            raise V2Error(EcodeRootROnly, "/", mk_v2_rev(self._rev()))
        if self._is_dir(node_path):
            raise V2Error(EcodeNotFile, node_path,
                          mk_v2_rev(self._rev()))
        key = self._mk_path(node_path)
        prev = self._get_kv(key)
        if prev is None:
            raise V2Error(EcodeKeyNotFound, node_path,
                          mk_v2_rev(self._rev()))
        res = self._txn(
            [Compare(key, "create", ">", 0)],
            [Op("put", key, new_value.encode()),
             Op("put", self._act_key(), b"update")])
        if not res["succeeded"]:
            raise V2Error(EcodeKeyNotFound, node_path,
                          mk_v2_rev(self._rev()))
        rev = res["rev"]
        e = Event("update",
                  {"key": node_path, "value": new_value,
                   "modifiedIndex": mk_v2_rev(rev),
                   "createdIndex": mk_v2_rev(prev.create_revision)},
                  etcd_index=mk_v2_rev(rev))
        e.prev_node = {"key": node_path, "value": prev.value.decode(),
                       "modifiedIndex": mk_v2_rev(prev.mod_revision),
                       "createdIndex": mk_v2_rev(prev.create_revision)}
        return e

    def compare_and_swap(self, node_path: str, prev_value: str,
                         prev_index: int, value: str) -> Event:
        node_path = _clean_path(node_path)
        if _is_root(node_path):
            raise V2Error(EcodeRootROnly, "/", mk_v2_rev(self._rev()))
        if self._is_dir(node_path):
            raise V2Error(EcodeNotFile, node_path,
                          mk_v2_rev(self._rev()))
        key = self._mk_path(node_path)
        prev = self._get_kv(key)
        if prev is None:
            raise V2Error(EcodeKeyNotFound, node_path,
                          mk_v2_rev(self._rev()))
        cmps = [Compare(key, "create", ">", 0)]
        if prev_value:
            cmps.append(Compare(key, "value", "=",
                                prev_value.encode()))
        if prev_index:
            cmps.append(Compare(key, "mod", "=",
                                mk_v3_rev(prev_index)))
        res = self._txn(cmps, [
            Op("put", key, value.encode()),
            Op("put", self._act_key(), b"compareAndSwap")])
        if not res["succeeded"]:
            raise V2Error(
                EcodeTestFailed,
                f"[{prev_value} != {prev.value.decode()}]"
                if prev_value else
                f"[{prev_index} != {mk_v2_rev(prev.mod_revision)}]",
                mk_v2_rev(self._rev()))
        rev = res["rev"]
        e = Event("compareAndSwap",
                  {"key": node_path, "value": value,
                   "modifiedIndex": mk_v2_rev(rev),
                   "createdIndex": mk_v2_rev(prev.create_revision)},
                  etcd_index=mk_v2_rev(rev))
        e.prev_node = {"key": node_path, "value": prev.value.decode(),
                       "modifiedIndex": mk_v2_rev(prev.mod_revision),
                       "createdIndex": mk_v2_rev(prev.create_revision)}
        return e

    def compare_and_delete(self, node_path: str, prev_value: str,
                           prev_index: int) -> Event:
        node_path = _clean_path(node_path)
        if self._is_dir(node_path):
            raise V2Error(EcodeNotFile, node_path,
                          mk_v2_rev(self._rev()))
        key = self._mk_path(node_path)
        prev = self._get_kv(key)
        if prev is None:
            raise V2Error(EcodeKeyNotFound, node_path,
                          mk_v2_rev(self._rev()))
        cmps = [Compare(key, "create", ">", 0)]
        if prev_value:
            cmps.append(Compare(key, "value", "=", prev_value.encode()))
        if prev_index:
            cmps.append(Compare(key, "mod", "=", mk_v3_rev(prev_index)))
        res = self._txn(cmps, [
            Op("delete", key),
            Op("put", self._act_key(), b"compareAndDelete")])
        if not res["succeeded"]:
            raise V2Error(
                EcodeTestFailed,
                f"[{prev_value} != {prev.value.decode()}]"
                if prev_value else
                f"[{prev_index} != {mk_v2_rev(prev.mod_revision)}]",
                mk_v2_rev(self._rev()))
        rev = res["rev"]
        e = Event("compareAndDelete",
                  {"key": node_path,
                   "modifiedIndex": mk_v2_rev(rev),
                   "createdIndex": mk_v2_rev(prev.create_revision)},
                  etcd_index=mk_v2_rev(rev))
        e.prev_node = {"key": node_path, "value": prev.value.decode(),
                       "modifiedIndex": mk_v2_rev(prev.mod_revision),
                       "createdIndex": mk_v2_rev(prev.create_revision)}
        return e

    def delete(self, node_path: str, dir: bool = False,
               recursive: bool = False) -> Event:
        node_path = _clean_path(node_path)
        if _is_root(node_path):
            raise V2Error(EcodeRootROnly, "/", mk_v2_rev(self._rev()))
        if recursive:
            dir = True
        if self._is_dir(node_path):
            if not dir:
                raise V2Error(EcodeNotFile, node_path,
                              mk_v2_rev(self._rev()))
            children = self._get_dir_depth(node_path, 1)
            if children and not recursive:
                raise V2Error(EcodeDirNotEmpty, node_path,
                              mk_v2_rev(self._rev()))
            ops = [Op("delete", self._dir_key(node_path))]
            base = _clean_path(node_path)
            for d in range(1, MAX_DEPTH):
                n = base.count("/") + d
                pref = f"{self.pfx}/{n:03d}/k{base}/".encode()
                kvs = self.ec.range(pref, _prefix_end(pref))["kvs"]
                if not kvs:
                    break
                ops.append(Op("delete", pref, range_end=_prefix_end(pref)))
            ops.append(Op("put", self._act_key(), b"delete"))
            res = self._txn([], ops)
            rev = res["rev"]
            return Event("delete",
                         {"key": node_path, "dir": True,
                          "modifiedIndex": mk_v2_rev(rev)},
                         etcd_index=mk_v2_rev(rev))
        key = self._mk_path(node_path)
        prev = self._get_kv(key)
        if prev is None:
            raise V2Error(EcodeKeyNotFound, node_path,
                          mk_v2_rev(self._rev()))
        res = self._txn([], [Op("delete", key),
                             Op("put", self._act_key(), b"delete")])
        rev = res["rev"]
        e = Event("delete",
                  {"key": node_path, "modifiedIndex": mk_v2_rev(rev),
                   "createdIndex": mk_v2_rev(prev.create_revision)},
                  etcd_index=mk_v2_rev(rev))
        e.prev_node = {"key": node_path, "value": prev.value.decode(),
                       "modifiedIndex": mk_v2_rev(prev.mod_revision),
                       "createdIndex": mk_v2_rev(prev.create_revision)}
        return e

    # ---- watch (watcher.go): a v3 watch over the key plane; the action
    # key written in the same txn recovers the v2 action per revision
    def watch(self, node_path: str, recursive: bool = False,
              since_index: int = 0) -> "V2v3Watcher":
        return V2v3Watcher(self, node_path, recursive, since_index)


class V2v3Watcher:
    def __init__(self, store: V2v3Store, node_path: str,
                 recursive: bool, since_index: int):
        self.store = store
        self.path = _clean_path(node_path)
        self.recursive = recursive
        ec = store.ec
        m = ec.ensure_leader()
        self.member = m
        pref = store.pfx.encode()
        start = mk_v3_rev(since_index) if since_index else 0
        self.watch_id = ec.watch(
            m, pref, _prefix_end(pref), start_rev=start, prev_kv=True).id

    def next(self) -> Event | None:
        ec = self.store.ec
        evs = ec.watch_events(self.member, self.watch_id)
        # group by mod_revision; find the action key + the node key
        act_key = self.store._act_key()
        by_rev: dict[int, dict] = {}
        for ev in evs:
            kv = ev.kv
            rev = kv.mod_revision
            g = by_rev.setdefault(rev, {"action": None, "kvs": []})
            if kv.key == act_key:
                g["action"] = kv.value.decode()
            elif b"/k" in kv.key:
                g["kvs"].append((ev.type, kv, ev.prev_kv))
        for rev in sorted(by_rev):
            g = by_rev[rev]
            for typ, kv, prev in g["kvs"]:
                s = kv.key.decode()
                if s.endswith("/"):
                    continue  # dir markers don't fire v2 watch events
                p = self.store._node_path(kv.key)
                interested = (p == self.path or
                              (self.recursive and
                               p.startswith(self.path.rstrip("/") + "/")))
                if not interested:
                    continue
                action = g["action"] or \
                    ("delete" if typ == "delete" else "set")
                node: dict[str, Any] = {
                    "key": p, "modifiedIndex": mk_v2_rev(rev)}
                if typ != "delete":
                    node["value"] = kv.value.decode()
                    node["createdIndex"] = mk_v2_rev(kv.create_revision)
                e = Event(action, node, etcd_index=mk_v2_rev(rev))
                if prev is not None:
                    e.prev_node = {
                        "key": p, "value": prev.value.decode(),
                        "modifiedIndex": mk_v2_rev(prev.mod_revision),
                        "createdIndex": mk_v2_rev(prev.create_revision)}
                return e
        return None

    def remove(self) -> None:
        self.store.ec.cancel_watch(self.member, self.watch_id)


def _prefix_end(prefix: bytes) -> bytes:
    end = bytearray(prefix)
    for i in range(len(end) - 1, -1, -1):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[: i + 1])
    return b"\x00"
