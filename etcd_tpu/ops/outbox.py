"""Per-node outbox: K message slots per destination with overflow-drop.

The reference accumulates outbound messages in ``r.msgs`` (raft/raft.go:264,
appended by send() at raft.go:386-419) and the transport may drop messages
("Send MUST NOT block / drop is OK", server/etcdserver/raft.go:107-110;
rafttest/network.go:106-108). Here the outbox is a dense ``[K, M]`` plane of
Msg slots plus a per-destination fill counter; emitting past K drops the
message, which is legal by the same contract.

Axis order matters on TPU: per-node leaves are [K, M(dest), ...] with the
member axis LAST so that, after the fleet vmap appends the clusters axis,
every materialized temp ends in (..., M, C) — a (5, big) minor pair that
tiles to (8, 128) with <=1.6x padding. The previous [M, K] order left the
tiny K/E axes minor-most and the TPU layout padded message temps 60-130x,
OOMing fleet-scale programs (see the C=65536 compile report: 100-200MB
temps for 1.6-3MB of data).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from etcd_tpu.types import ENT_FIELDS as _ENT_FIELDS, Msg, NONE_ID, Spec, empty_msg


class PendingWire(struct.PyTreeNode):
    """Deferred-emission accumulator (RaftConfig.deferred_emit): instead
    of writing [K, M] message planes inside the serial message scan, the
    steady-state handlers record per-destination reply/send intents in
    these [M]-vectors; node_round materializes them with ONE post-scan
    emit + ONE maybe_send_append (the emission restructure named in
    PROFILE.md). Last-writer-wins per destination — legal because the
    transport may drop messages, and exact in the steady state where
    each peer receives at most one reply-worthy message per round."""

    # MsgAppResp reply intent (handle_append_entries + the lower-term
    # commit push of process_message)
    rep_any: jnp.ndarray      # bool[M]
    rep_term: jnp.ndarray     # i32[M]
    rep_index: jnp.ndarray    # i32[M]
    rep_reject: jnp.ndarray   # bool[M]
    rep_hint: jnp.ndarray     # i32[M]
    rep_logterm: jnp.ndarray  # i32[M]
    # union of maybe_send_append destinations requested mid-scan
    # (stepLeader's ack/reject merged send + in-scan bcastAppend)
    send_dest: jnp.ndarray      # bool[M]
    send_nonempty: jnp.ndarray  # bool[M]
    # follower proposal forward intent (stepFollower raft.go:1423-1432)
    fwd_any: jnp.ndarray    # bool[M]
    fwd_len: jnp.ndarray    # i32[M]
    fwd_data: jnp.ndarray   # i32[M, E]
    fwd_type: jnp.ndarray   # i32[M, E]


def empty_pending(spec: Spec) -> PendingWire:
    z = jnp.zeros((spec.M,), jnp.int32)
    b = jnp.zeros((spec.M,), jnp.bool_)
    ze = jnp.zeros((spec.M, spec.E), jnp.int32)
    return PendingWire(rep_any=b, rep_term=z, rep_index=z, rep_reject=b,
                       rep_hint=z, rep_logterm=z, send_dest=b,
                       send_nonempty=b, fwd_any=b, fwd_len=z,
                       fwd_data=ze, fwd_type=ze)


class Outbox(struct.PyTreeNode):
    # msgs leaves are stored FLAT: [K*M(dest)] (ent fields [K*M*E]) —
    # the outbox is a lax.scan carry in node_round, and a carry leaf whose
    # minor logical dims are tiny (K=2, E=1) gets tile-padded up to 200x
    # once batched to fleet shape (observed: three 2.5GB HLO temps for
    # 13MB of data at C=65536). Rank-1 per-node leaves batch to
    # [member, C, K*M*E], whose minor pair includes a medium axis.
    # emit() views them as [K, M, (E)] via free reshapes.
    msgs: Msg
    counts: jnp.ndarray    # i32[M]
    # highest commit index carried by any message sent to each dest this
    # round (0 = none). Consumed by the coalesced end-of-round commit
    # flush (RaftConfig.coalesce_commit_refresh) to detect destinations
    # whose only messages this round predate a commit advance.
    sent_commit: jnp.ndarray  # i32[M]
    # deferred-emission accumulator; None unless cfg.deferred_emit
    pend: PendingWire | None = None


def _view(spec: Spec, name: str, x: jnp.ndarray) -> jnp.ndarray:
    if name in _ENT_FIELDS:
        return x.reshape(spec.K, spec.M, spec.E)
    return x.reshape(spec.K, spec.M)


def empty_outbox(spec: Spec, deferred: bool = False) -> Outbox:
    m = empty_msg(spec)

    def mk(name, x):
        n = spec.K * spec.M * (spec.E if name in _ENT_FIELDS else 1)
        return jnp.zeros((n,), x.dtype)

    msgs = Msg(**{k: mk(k, getattr(m, k)) for k in Msg.__dataclass_fields__})
    return Outbox(msgs=msgs, counts=jnp.zeros((spec.M,), jnp.int32),
                  sent_commit=jnp.zeros((spec.M,), jnp.int32),
                  pend=empty_pending(spec) if deferred else None)


def make_msg(spec: Spec, **kw) -> Msg:
    """A scalar Msg with given fields, rest defaulted."""
    base = empty_msg(spec)
    conv = {}
    for k, v in kw.items():
        ref = getattr(base, k)
        conv[k] = jnp.asarray(v, ref.dtype)
    return base.replace(**conv)


def bcast(spec: Spec, m: Msg) -> Msg:
    """Broadcast a scalar Msg to per-destination leaves [M, ...]."""
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (spec.M,) + x.shape), m)


# message-header fields read by type-generic receiver code for EVERY
# message (process_message's term/lease/vote plumbing): always written
HEADER_FIELDS = ("type", "term", "frm", "context", "reject")


def emit(spec: Spec, ob: Outbox, to_mask: jnp.ndarray, m: Msg,
         fields: tuple | None = None) -> Outbox:
    """Write per-destination message m (leaves [M, ...]) into the next free
    slot for every destination in `to_mask`; silently drop on overflow.

    `fields` (sparse emit): the non-header fields this message type
    actually sets. Unlisted fields are left untouched — slots start each
    round zeroed and no slot is written twice, so an unwritten field IS
    zero, bit-identical to dense emission of a defaulted Msg — and the
    skipped rewrites are the round program's dominant HBM traffic
    (PROFILE.md: ~22 emit sites x 17 leaves x [K, M] plane per step).
    None = write everything (callers that build full messages)."""
    slot_idx = ob.counts                       # [M]
    can = to_mask & (slot_idx < spec.K)        # [M]
    sel = can[None, :] & (
        jnp.arange(spec.K, dtype=jnp.int32)[:, None] == slot_idx[None, :]
    )  # [K, M]

    def upd(name):
        old = _view(spec, name, getattr(ob.msgs, name))
        new = getattr(m, name)
        extra = old.ndim - 2
        s = sel.reshape(sel.shape + (1,) * extra)
        return jnp.where(s, new[None], old).reshape(-1)

    names = (
        Msg.__dataclass_fields__
        if fields is None
        else tuple(dict.fromkeys(HEADER_FIELDS + tuple(fields)))
    )
    msgs = ob.msgs.replace(**{k: upd(k) for k in names})
    return ob.replace(msgs=msgs, counts=ob.counts + can.astype(jnp.int32))


def record_sent_commit(ob: Outbox, mask: jnp.ndarray, value) -> Outbox:
    """Note that destinations in `mask` just received a message carrying
    commit information `value` ([M] or scalar)."""
    return ob.replace(
        sent_commit=jnp.where(
            mask, jnp.maximum(ob.sent_commit, value), ob.sent_commit
        )
    )


def emit_one(
    spec: Spec, ob: Outbox, to: jnp.ndarray, m: Msg, enable: jnp.ndarray,
    fields: tuple | None = None,
) -> Outbox:
    """Emit a scalar Msg to a single destination id (gated by `enable`)."""
    to_mask = (jnp.arange(spec.M, dtype=jnp.int32) == to) & enable
    return emit(spec, ob, to_mask, bcast(spec, m), fields)
