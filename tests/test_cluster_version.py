"""Cluster version negotiation + downgrade machinery
(server/etcdserver/version/monitor.go, api/membership/downgrade.go,
v3_server.go:901-990 — see etcd_tpu/server/version.py)."""
import pytest

from etcd_tpu.server.kvserver import (
    ErrDowngradeInProcess,
    ErrInvalidDowngradeTargetVersion,
    ErrNoInflightDowngrade,
    EtcdCluster,
)
from etcd_tpu.server.version import (
    DowngradeInfo,
    InvalidDowngrade,
    SERVER_VERSION,
    allowed_downgrade_version,
    cluster_version_str,
    detect_downgrade,
    is_valid_version_change,
    parse,
)


# -- pure logic (no fleet) ---------------------------------------------------
def test_semver_logic():
    assert parse("3.6.0") == (3, 6, 0)
    assert parse("3.6.1-tpu.4") == (3, 6, 1)
    with pytest.raises(ValueError):
        parse("abc")
    assert allowed_downgrade_version("3.6.5") == "3.5.0"
    assert cluster_version_str(SERVER_VERSION) == "3.6.0"


def test_is_valid_version_change():
    # upgrade toward min member version (cluster start)
    assert is_valid_version_change("3.0.0", "3.6.0")
    # one-minor downgrade is the ONLY legal decrease
    assert is_valid_version_change("3.6.0", "3.5.0")
    assert not is_valid_version_change("3.6.0", "3.4.0")
    # cross-major moves are rejected either way
    assert not is_valid_version_change("3.6.0", "4.0.0")
    assert not is_valid_version_change("4.0.0", "3.6.0")
    assert not is_valid_version_change("3.6.0", "3.6.0")


def test_detect_downgrade_boot_check():
    # no downgrade job: older server than cluster version refuses to boot
    with pytest.raises(InvalidDowngrade):
        detect_downgrade("3.5.0", "3.6.0", None)
    detect_downgrade("3.6.0", "3.6.0", None)
    detect_downgrade("3.7.0", "3.6.0", None)
    # live downgrade job: ONLY target-version servers may join
    d = DowngradeInfo("3.5.0", True)
    detect_downgrade("3.5.9", "3.6.0", d)
    with pytest.raises(InvalidDowngrade):
        detect_downgrade("3.6.0", "3.6.0", d)


def _settle(ec, rounds: int = 6):
    """Drain apply on ALL members: _propose returns once the serving
    member applied; followers catch up on subsequent pumps."""
    for _ in range(rounds):
        ec.step()


# -- negotiation over a live fleet ------------------------------------------
def test_mixed_version_fleet_negotiates_min():
    ec = EtcdCluster(n_members=3)
    ec.ensure_leader()
    ec.set_server_version(1, "3.5.7")
    proposed = ec.monitor_versions()
    # cluster version was unset: first pass decides min(3.6, 3.5, 3.6)
    assert proposed == "3.5.0"
    _settle(ec)
    assert all(ms.cluster_version == "3.5.0" for ms in ec.members)
    # the laggard upgrades -> next pass raises the cluster version
    ec.set_server_version(1, SERVER_VERSION)
    assert ec.monitor_versions() == "3.6.0"
    assert ec.cluster_version() == "3.6.0"
    # steady state: nothing to change
    assert ec.monitor_versions() is None


def test_monitor_abstains_while_member_unreachable():
    ec = EtcdCluster(n_members=3)
    ec.ensure_leader()
    assert ec.monitor_versions() == "3.6.0"
    lead = ec.leader()
    victim = (lead + 1) % 3
    ec.members[victim].crashed = True
    # decideClusterVersion returns nil when any member's version is
    # unknown -> no change proposed (monitor.go:91-99)
    assert ec.monitor_versions() is None
    ec.members[victim].crashed = False
    assert ec.monitor_versions() is None  # still 3.6.0, nothing to do


def test_downgrade_validate_enable_cancel():
    ec = EtcdCluster(n_members=3)
    ec.ensure_leader()
    ec.monitor_versions()
    assert ec.downgrade("validate", "3.5.0")["version"] == "3.6.0"
    with pytest.raises(ErrInvalidDowngradeTargetVersion):
        ec.downgrade("validate", "3.4.0")
    with pytest.raises(ErrNoInflightDowngrade):
        ec.downgrade("cancel")
    ec.downgrade("enable", "3.5.0")
    _settle(ec)
    assert all(ms.downgrade.enabled for ms in ec.members)
    with pytest.raises(ErrDowngradeInProcess):
        ec.downgrade("validate", "3.5.0")
    ec.downgrade("cancel")
    _settle(ec)
    assert not any(ms.downgrade.enabled for ms in ec.members)


def test_full_downgrade_job_completes_and_cancels():
    """enable -> swap every member's binary to the target -> the monitor
    lowers the cluster version -> monitorDowngrade cancels the job."""
    ec = EtcdCluster(n_members=3)
    ec.ensure_leader()
    ec.monitor_versions()
    assert ec.cluster_version() == "3.6.0"
    ec.downgrade("enable", "3.5.0")
    # binaries swap one by one; min server version becomes 3.5
    for m in range(3):
        ec.set_server_version(m, "3.5.2")
    assert ec.monitor_versions() == "3.5.0"  # one-minor drop is legal
    _settle(ec)
    assert all(ms.cluster_version == "3.5.0" for ms in ec.members)
    assert ec.monitor_downgrade() is True    # every view matches target
    _settle(ec)
    assert not any(ms.downgrade.enabled for ms in ec.members)
    assert ec.monitor_downgrade() is False


def test_version_records_survive_restart(tmp_path):
    ec = EtcdCluster(n_members=3, data_dir=str(tmp_path))
    ec.ensure_leader()
    ec.monitor_versions()
    _settle(ec)
    assert ec.cluster_version() == "3.6.0"
    ec.put(b"k", b"v")
    ec.sync_for_shutdown()
    victim = (ec.leader() + 1) % 3
    ec.crash_member(victim)
    ec.restart_member_from_disk(victim)
    assert ec.members[victim].cluster_version == "3.6.0"


def test_restart_refused_mid_downgrade(tmp_path):
    """mustDetectDowngrade: with a downgrade job live, a member restarting
    on the OLD binary refuses to serve (downgrade.go:58-64)."""
    ec = EtcdCluster(n_members=3, data_dir=str(tmp_path))
    ec.ensure_leader()
    ec.monitor_versions()
    ec.downgrade("enable", "3.5.0")
    _settle(ec)
    ec.sync_for_shutdown()
    victim = (ec.leader() + 1) % 3
    ec.crash_member(victim)
    with pytest.raises(InvalidDowngrade):
        ec.restart_member_from_disk(victim)
    # the swapped binary (target version) is allowed in
    ec.set_server_version(victim, "3.5.2")
    ec.restart_member_from_disk(victim)
    assert ec.members[victim].downgrade.enabled
