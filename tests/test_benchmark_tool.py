"""Benchmark-tool tests: the tools/benchmark analog drives a live
embedded server over the wire and reports pkg/report-style summaries."""
import io
import sys

import pytest

from etcd_tpu import benchmark
from etcd_tpu.embed import Config, start_etcd


@pytest.fixture(scope="module")
def etcd():
    e = start_etcd(Config(cluster_size=3, auto_tick=False))
    yield e
    e.close()


def run(etcd, *argv) -> str:
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        rc = benchmark.main(["--endpoint", etcd.client_url, *argv])
    finally:
        sys.stdout = old
    assert rc == 0
    return out.getvalue()


def test_benchmark_put_and_range(etcd):
    out = run(etcd, "put", "--total", "20", "--val-size", "16")
    assert "Requests/sec:" in out and "99% in" in out
    out = run(etcd, "range", "--total", "20", "--serializable")
    assert "Latency distribution:" in out


def test_benchmark_txn_and_watch_latency(etcd):
    out = run(etcd, "txn-put", "--total", "10")
    assert "Summary:" in out
    out = run(etcd, "watch-latency", "--total", "5")
    assert "Requests/sec:" in out
