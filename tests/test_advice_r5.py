"""Regression tests for the round-4 advisor findings: v2 façade path
canonicalization before the auth guard, tick-loop-driven v2 SYNC expiry,
nested hidden-node watch suppression, parked-watch eviction, and
EcodeWatcherCleared on store recovery."""
import time

import pytest

from etcd_tpu.server.kvserver import EtcdCluster
from etcd_tpu.server.v2http import V2Api
from etcd_tpu.server.v2store import (
    EcodeKeyNotFound,
    EcodeUnauthorized,
    EcodeWatcherCleared,
    V2Error,
    V2Store,
    _is_hidden,
)


@pytest.fixture(scope="module")
def ec():
    c = EtcdCluster(n_members=3)
    c.ensure_leader()
    return c


@pytest.fixture()
def api(ec):
    return V2Api(ec)


# ------------------------------------------- high: path canonicalization

def test_security_subtree_unreachable_via_raw_paths(ec):
    """//_security/... and /a/../_security/... must hit the same guard
    as /_security/... (the store cleans paths at apply time, so the
    façade must clean them before the auth check too —
    v2http/client.go relies on Go's mux canonicalization)."""
    api = V2Api(ec)
    root = {"_basic_auth": "root:rpw"}
    api.auth_admin("PUT", "/users/root", {**root, "password": "rpw"})
    api.auth_admin("PUT", "/enable", root)
    try:
        for evil in ("//_security/users/mallory",
                     "/a/../_security/users/mallory",
                     "/ok/./../_security/enabled"):
            st, body, _ = api.keys("PUT", evil, {"value": "pwn"})
            assert st == 403, evil
            assert body["errorCode"] == EcodeUnauthorized, evil
            st, body, _ = api.keys("GET", evil, {})
            assert st == 403, evil
        # and the canonical form still guards (sanity)
        st, body, _ = api.keys("GET", "/_security/enabled", {})
        assert st == 403
        # permission matching also sees the cleaned path: a non-root
        # user scoped to /app/* may write //app/x (same key)
        api.auth_admin("PUT", "/roles/writer", {
            **root,
            "permissions": {"kv": {"read": ["/app/*"],
                                   "write": ["/app/*"]}}})
        api.auth_admin("PUT", "/users/bob",
                       {**root, "password": "bpw", "roles": "writer"})
        st, body, _ = api.keys(
            "PUT", "//app/x", {"value": "v", "_basic_auth": "bob:bpw"})
        assert st == 201 and body["node"]["key"] == "/app/x"
        # ...but not escape its scope via dot-dot
        st, body, _ = api.keys(
            "PUT", "/app/../other", {"value": "v",
                                     "_basic_auth": "bob:bpw"})
        assert st == 401
    finally:
        api.auth_admin("DELETE", "/enable", root)


# ----------------------------------------- medium: tick-loop v2 SYNC

def test_tick_loop_proposes_v2_sync(tmp_path):
    """A TTL key on a *running* server expires without any client
    calling sync: embed's ticker proposes SYNC every ~500ms
    (etcdserver's syncer cadence)."""
    from etcd_tpu.embed import Config, start_etcd

    e = start_etcd(Config(cluster_size=1, data_dir=str(tmp_path / "d"),
                          tick_ms=50, auto_tick=True))
    try:
        st, body, _ = e.http.v2api.keys(
            "PUT", "/ttl/auto", {"value": "v", "ttl": "1"})
        assert st == 201 and body["node"]["ttl"] == 1
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            st, body, _ = e.http.v2api.keys("GET", "/ttl/auto", {})
            if st == 404:
                break
            time.sleep(0.2)
        assert st == 404
        assert body["errorCode"] == EcodeKeyNotFound
    finally:
        e.close()


# ------------------------------------------- low: nested hidden nodes

def test_is_hidden_nested_components():
    assert _is_hidden("/a", "/a/_h")
    assert _is_hidden("/a", "/a/b/_h")         # the nested case
    assert _is_hidden("/", "/x/_deep/leaf")
    assert not _is_hidden("/a", "/a/b/c")
    # components *inside* the watch path don't hide (watching under a
    # hidden dir sees its own events — watcher_hub.go passes afterPath)
    assert not _is_hidden("/_h/sub", "/_h/sub/leaf")


def test_watcher_suppresses_nested_hidden_events():
    s = V2Store()
    w = s.watch("/a", recursive=True, stream=True)
    s.create("/a/b/_h", value="secret")
    assert w.poll() is None
    s.create("/a/b/c", value="visible")
    ev = w.poll()
    assert ev is not None and ev.node["key"] == "/a/b/c"


# ------------------------------------------- low: parked-watch eviction

def test_parked_watch_ttl_eviction(ec):
    api = V2Api(ec)
    st, body, _ = api.keys("GET", "/pw/none", {"wait": "true"})
    wid = body["watch_id"]
    assert wid in api._watches
    # a poll refreshes the clock; an idle park past PARK_TTL is poisoned
    api.watch_poll(wid)
    api._watch_seen[wid] -= V2Api.PARK_TTL + 1
    api._last_sweep = 0.0  # the sweep itself is throttled to 1/s
    api.keys("GET", "/pw/other", {"wait": "true"})  # triggers sweep
    w = api._watches[wid]
    assert w.cleared and w.removed  # store-side watcher freed
    # a returning client gets the re-watch signal once, with the index
    st, body, _ = api.watch_poll(wid)
    assert st == 400 and body["errorCode"] == EcodeWatcherCleared
    assert body["index"] > 0
    # ...and a bare miss afterwards looks identical (400 + errorCode)
    # so clientv2 raises instead of treating it as an empty poll
    st, body, _ = api.watch_poll(wid)
    assert st == 400 and body["errorCode"] == EcodeWatcherCleared
    # an unclaimed tombstone is dropped after a second TTL window
    st, body, _ = api.keys("GET", "/pw/third", {"wait": "true"})
    wid2 = body["watch_id"]
    api._watch_seen[wid2] -= 2 * (V2Api.PARK_TTL + 1)
    api._watches[wid2].cleared = True
    api._last_sweep = 0.0
    api.keys("GET", "/pw/fourth", {"wait": "true"})
    assert wid2 not in api._watches


def test_poll_keeps_own_watch_alive_and_sheds_tombstones_first(ec):
    """A poll arriving just past PARK_TTL refreshes its own watch before
    the sweep; cap pressure drops dead tombstones before live parks."""
    api = V2Api(ec)
    _, body, _ = api.keys("GET", "/ka/x", {"wait": "true"})
    wid = body["watch_id"]
    api._watch_seen[wid] -= V2Api.PARK_TTL + 1
    api._last_sweep = 0.0
    st, body, _ = api.watch_poll(wid)  # the late poll itself
    assert st == 200 and body == {}  # still alive, not poisoned
    assert not api._watches[wid].cleared
    # tombstones shed before live watches under cap pressure
    _, b2, _ = api.keys("GET", "/ka/y", {"wait": "true"})
    api._watches[b2["watch_id"]].cleared = True  # dead tombstone
    old_cap = V2Api.PARK_CAP
    V2Api.PARK_CAP = len(api._watches)
    try:
        _, b3, _ = api.keys("GET", "/ka/z", {"wait": "true"})
    finally:
        V2Api.PARK_CAP = old_cap
    assert b2["watch_id"] not in api._watches  # tombstone went first
    assert wid in api._watches  # live watch survived


def test_parked_watch_cap(ec, monkeypatch):
    monkeypatch.setattr(V2Api, "PARK_CAP", 4)
    api = V2Api(ec)
    wids = []
    for i in range(6):
        _, body, _ = api.keys("GET", f"/cap/{i}", {"wait": "true"})
        wids.append(body["watch_id"])
    assert len(api._watches) <= 4
    assert wids[0] not in api._watches  # oldest shed first
    assert wids[-1] in api._watches


# ------------------------------------- low: EcodeWatcherCleared on recovery

def test_recovery_poisons_store_watchers():
    s = V2Store()
    s.create("/r/a", value="1")
    w = s.watch("/r", recursive=True, stream=True)
    s.recovery(s.save())
    with pytest.raises(V2Error) as ei:
        w.poll()
    assert ei.value.code == EcodeWatcherCleared
    # the fresh hub serves new watchers normally
    w2 = s.watch("/r", recursive=True, stream=True)
    s.create("/r/b", value="2")
    assert w2.poll().node["key"] == "/r/b"


def test_overflowed_watcher_poisoned_not_silent():
    """A stream watcher that misses a notification (100-event overflow)
    raises EcodeWatcherCleared after draining, instead of returning
    empty polls forever (the reference closes the event channel)."""
    s = V2Store()
    w = s.watch("/of", recursive=True, stream=True)
    from etcd_tpu.server.v2store import Watcher

    for i in range(Watcher.CAPACITY + 1):
        s.set(f"/of/{i}", value=str(i))
    drained = 0
    while w.events:
        assert w.poll() is not None
        drained += 1
    assert drained == Watcher.CAPACITY
    with pytest.raises(V2Error) as ei:
        w.poll()
    assert ei.value.code == EcodeWatcherCleared


def test_facade_watch_poll_reports_cleared(ec):
    api = V2Api(ec)
    _, body, _ = api.keys("GET", "/rc/none", {"wait": "true"})
    wid = body["watch_id"]
    store = api._store()
    store.recovery(store.save())
    st, body, _ = api.watch_poll(wid)
    assert st == 400 and body["errorCode"] == EcodeWatcherCleared
    assert body["index"] > 0
    # the façade forgets the watch after surfacing the error once; the
    # miss looks identical (400 + cleared errorCode)
    st, body, _ = api.watch_poll(wid)
    assert st == 400 and body["errorCode"] == EcodeWatcherCleared
