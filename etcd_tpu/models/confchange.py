"""Config-change arithmetic as mask algebra.

Re-expression of the reference's ``confchange.Changer`` (raft/confchange/
confchange.go): Simple one-delta changes (confchange.go:130-147), joint
consensus EnterJoint/LeaveJoint (49-123) and LearnersNext staging (206-230),
operating on bool[M] masks instead of map-backed ProgressMaps. A conf change
is encoded into a single int32 entry-data word (up to two changes, which
covers the V2 auto-joint rule "more than one change => joint").

Word layout (low bits first):
  [0:3]   op1 (CC_*)        [3:8]   id1
  [8:11]  op2               [11:16] id2
  16: has1   17: has2   18: enter_joint   19: auto_leave   20: leave_joint

The validation the reference performs in Changer.checkInvariants is enforced
at proposal time by the leader-side guards in stepLeader (one unapplied
change at a time, no new change while joint, leave only while joint), so
application here is unconditional — matching applyConfChange's panic-on-
invalid contract (raft.go:1623-1643).
"""
from __future__ import annotations

import jax.numpy as jnp

from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    NONE_ID,
    PR_PROBE,
    ROLE_LEADER,
)
from etcd_tpu.utils.tree import tree_where

_HAS1 = 1 << 16
_HAS2 = 1 << 17
_ENTER = 1 << 18
_AUTO = 1 << 19
_LEAVE = 1 << 20


def encode(
    changes: list[tuple[int, int]],
    enter_joint: bool = False,
    auto_leave: bool = True,
    leave_joint: bool = False,
) -> int:
    """Host-side encoder: changes is [(op, id), ...] with at most 2 entries."""
    if leave_joint:
        return _LEAVE
    if len(changes) > 2:
        raise ValueError("at most 2 changes per conf-change word")
    w = 0
    if len(changes) >= 1:
        op, nid = changes[0]
        w |= (op & 7) | ((nid & 31) << 3) | _HAS1
    if len(changes) >= 2:
        op, nid = changes[1]
        w |= ((op & 7) << 8) | ((nid & 31) << 11) | _HAS2
    if enter_joint or len(changes) > 1:
        w |= _ENTER
        if auto_leave:
            w |= _AUTO
    return w


def encode_leave_joint() -> int:
    return _LEAVE


def is_leave_joint(data) -> jnp.ndarray:
    return (data & _LEAVE) != 0


def _apply_one(spec, v, vo, l, ln_, joint, op, nid, enable):
    """One change against the incoming config (confchange.go:152-230)."""
    hot = (jnp.arange(spec.M, dtype=jnp.int32) == nid) & enable
    add_v = hot & (op == CC_ADD_NODE)
    add_l = hot & (op == CC_ADD_LEARNER)
    rem = hot & (op == CC_REMOVE_NODE)
    # makeVoter (confchange.go:152-164)
    v = (v | add_v) & ~add_l & ~rem
    # makeLearner (confchange.go:166-230): a demoted voter still in the
    # outgoing config is staged in LearnersNext until LeaveJoint
    stage = add_l & joint & vo
    l = (l | (add_l & ~stage)) & ~add_v & ~rem
    ln_ = (ln_ | stage) & ~add_v & ~rem
    return v, vo, l, ln_


def apply_conf_change(cfg, spec, n, ob, data, enable):
    """applyConfChange + switchToConfig (raft/raft.go:1623-1700)."""
    from etcd_tpu.models import raft as raftmod  # cycle-free at call time

    op1 = data & 7
    id1 = (data >> 3) & 31
    op2 = (data >> 8) & 7
    id2 = (data >> 11) & 31
    has1 = (data & _HAS1) != 0
    has2 = (data & _HAS2) != 0
    enter = ((data & _ENTER) != 0) | (has1 & has2)
    auto = (data & _AUTO) != 0
    leave = (data & _LEAVE) != 0

    v, vo, l, ln_ = n.voters, n.voters_out, n.learners, n.learners_next

    # LeaveJoint (confchange.go:97-123)
    do_leave = enable & leave
    v_l = v
    l_l = l | ln_
    ln_l = jnp.zeros_like(ln_)
    vo_l = jnp.zeros_like(vo)

    # EnterJoint copies incoming -> outgoing first (confchange.go:49-95)
    do_change = enable & ~leave
    vo_c = jnp.where(do_change & enter, v, vo)
    joint_now = vo_c.any()
    v_c, vo_c, l_c, ln_c = _apply_one(
        spec, v, vo_c, l, ln_, joint_now, op1, id1, do_change & has1
    )
    v_c, vo_c, l_c, ln_c = _apply_one(
        spec, v_c, vo_c, l_c, ln_c, joint_now, op2, id2, do_change & has2
    )

    was_tracked = v | vo | l | ln_

    n = n.replace(
        voters=jnp.where(do_leave, v_l, jnp.where(do_change, v_c, n.voters)),
        voters_out=jnp.where(do_leave, vo_l, jnp.where(do_change, vo_c, n.voters_out)),
        learners=jnp.where(do_leave, l_l, jnp.where(do_change, l_c, n.learners)),
        learners_next=jnp.where(
            do_leave, ln_l, jnp.where(do_change, ln_c, n.learners_next)
        ),
        auto_leave=jnp.where(
            do_leave, False, jnp.where(do_change & enter, auto, n.auto_leave)
        ),
    )

    # Fresh Progress for members entering the tracked set
    # (confchange.go:249-272 initProgress): match=0, next=lastIndex (so the
    # new follower can be probed immediately), probe state, recently active
    # so CheckQuorum doesn't step the leader down before first contact.
    # Without this a removed-then-re-added member would keep its stale
    # match, which could falsely advance the commit index.
    now_tracked = n.voters | n.voters_out | n.learners | n.learners_next
    fresh = enable & now_tracked & ~was_tracked
    ends = n.infl_ends.reshape(spec.M, spec.W)
    n = n.replace(
        match=jnp.where(fresh, 0, n.match),
        next_idx=jnp.where(fresh, jnp.maximum(n.last_index, 1), n.next_idx),
        pr_state=jnp.where(fresh, PR_PROBE, n.pr_state),
        probe_sent=jnp.where(fresh, False, n.probe_sent),
        pending_snapshot=jnp.where(fresh, 0, n.pending_snapshot),
        recent_active=jnp.where(fresh, True, n.recent_active),
        infl_count=jnp.where(fresh, 0, n.infl_count),
        infl_start=jnp.where(fresh, 0, n.infl_start),
        infl_ends=jnp.where(fresh[:, None], 0, ends).reshape(-1),
    )

    # switchToConfig side effects (raft.go:1651-1700)
    from etcd_tpu.models.state import in_config_self, is_learner_self

    self_ok = in_config_self(n) & ~is_learner_self(n)
    active = (
        enable & (n.role == ROLE_LEADER) & self_ok & n.voters.any()
    )
    n2, adv = raftmod.maybe_commit_state(cfg, spec, n)
    n = tree_where(active & adv, n2, n)
    n, ob = raftmod.bcast_append(cfg, spec, n, ob, active & adv)
    n, ob = raftmod.maybe_send_append(
        cfg,
        spec,
        n,
        ob,
        raftmod._progress_ids(n) & jnp.broadcast_to(active & ~adv, (spec.M,)),
        False,
    )
    # abort a transfer to a peer no longer in the voter union (raft.go:1694-1697)
    tr = jnp.clip(n.lead_transferee, 0, spec.M - 1)
    gone = (n.lead_transferee != NONE_ID) & ~raftmod.onehot_sel(
        n.voters | n.voters_out, tr
    )
    n = n.replace(
        lead_transferee=jnp.where(enable & gone, NONE_ID, n.lead_transferee)
    )
    return n, ob
