"""Functional chaos tier: randomized faults + on-device invariant checkers.

The reference's functional tester (tests/functional/tester/cluster.go:43-65)
loops rounds of inject -> stress -> recover -> check over a live cluster,
with fault cases like BLACKHOLE/DELAY_PEER_PORT_TX_RX (rpcpb enum) injected
by an L4 proxy (pkg/proxy/server.go:92-127) and a KV_HASH checker
(tester/checker_kv_hash.go) asserting every member converges to the same
state hash.

The TPU-native equivalent runs the whole loop ON DEVICE at fleet scale:

  * drop faults: per-round Bernoulli keep-masks (the blackhole case);
  * partition faults: rolling per-group bisections re-sampled every epoch
    (SIGQUIT/blackhole-quorum analogs), healed between epochs;
  * delay/reorder faults (rafttest/network.go:122-144 delay semantics):
    messages divert into a held buffer with probability p and deliver a
    round late — arriving after younger messages, which exercises
    reordering;
  * checkers, evaluated every round as tensor reductions and accumulated
    as violation counters so only a handful of scalars ever cross to the
    host:
      - election safety: at most one leader per (group, term);
      - state-machine safety (KV_HASH): equal applied index => equal
        applied hash, for every member pair;
      - commit monotonicity: no node's commit index ever regresses.

Everything (fault sampling, stepping, checking) lives in one lax.scan —
no host round-trips during a chaos epoch.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.models.state import NodeState
from etcd_tpu.types import Msg, ROLE_LEADER, Spec
from etcd_tpu.utils.config import RaftConfig


class Violations(struct.PyTreeNode):
    """Safety-violation counters (i32 scalars)."""

    multi_leader: jnp.ndarray     # >1 leader at one (group, term)
    hash_mismatch: jnp.ndarray    # equal applied, different hash
    commit_regress: jnp.ndarray   # commit index moved backwards


def zero_violations() -> Violations:
    z = jnp.int32(0)
    return Violations(multi_leader=z, hash_mismatch=z, commit_regress=z)


def check_invariants(state: NodeState, prev_commit: jnp.ndarray,
                     viol: Violations) -> Violations:
    """One round's checker pass: pure reductions over [M, C] leaves."""
    M = state.role.shape[0]
    is_lead = state.role == ROLE_LEADER            # [M, C]
    term = state.term
    # pairwise i<j comparisons over the tiny member axis
    iu, ju = jnp.triu_indices(M, k=1)
    both_lead = is_lead[iu] & is_lead[ju] & (term[iu] == term[ju])
    same_applied = state.applied[iu] == state.applied[ju]
    diff_hash = state.applied_hash[iu] != state.applied_hash[ju]
    regress = state.commit < prev_commit
    return Violations(
        multi_leader=viol.multi_leader + both_lead.sum().astype(jnp.int32),
        hash_mismatch=viol.hash_mismatch
        + (same_applied & diff_hash).sum().astype(jnp.int32),
        commit_regress=viol.commit_regress + regress.sum().astype(jnp.int32),
    )


def _bc(spec: Spec, mask, leaf):
    """Broadcast a [from, K*to, C] slot mask to a leaf's shape (ent leaves
    repeat the middle axis per entry — the engine's FLAT storage form)."""
    if leaf.shape[1] != mask.shape[1]:
        return jnp.repeat(mask, spec.E, axis=1)
    return mask


# --------------------------------------------------------- sparse held
# The original held buffer was a SECOND FULL INBOX (17 x [M, K*M, C]
# leaves): at C=1M its while-loop double-buffering alone overflowed HBM
# (measured 17.01G vs the 15.75G budget), capping fault epochs at 524k
# groups. But delay faults are SPARSE — at delay_p=0.05 a sender row
# (K*M = 10 slots) holds ~0.1-0.5 delayed messages a round — so the
# buffer now packs each row's delayed messages into HELD_SLOTS compact
# slots (index + fields), ~3x smaller than the dense plane and with
# tiny [M, H, S, C] one-hot temporaries instead of full-inbox passes.
# Overflow past HELD_SLOTS per row per round DROPS the extra messages —
# legal by the transport contract (etcdserver/raft.go:107-110), and at
# the chaos mixes' traffic (<=2 live slots per row in steady state)
# P(>3 delayed in one row) is negligible.

HELD_SLOTS = 3


class HeldSparse(struct.PyTreeNode):
    """Per-sender-row packed delayed messages: `idx[m, h, c]` is the
    flat slot (0..K*M-1) the h-th held message came from (-1 = empty);
    `msgs` leaves are [M, H(,E packed into H*E), C] in the wire dtype."""

    idx: jnp.ndarray
    msgs: Msg


def empty_held(spec: Spec, C: int, wire_int16: bool) -> HeldSparse:
    # eval_shape: only leaf shapes/dtypes are needed — materializing a
    # real dense inbox here would transiently allocate the very
    # multi-GB buffer this sparse form exists to avoid
    inbox_sds = jax.eval_shape(
        lambda: empty_inbox(spec, C, wire_int16=wire_int16))
    H = HELD_SLOTS

    def shrink(x):
        S = spec.K * spec.M
        e = x.shape[1] // S  # 1, or E for ent leaves
        return jnp.zeros((spec.M, H * e, C), x.dtype)

    return HeldSparse(
        idx=jnp.full((spec.M, H, C), -1, jnp.int32),
        msgs=jax.tree.map(shrink, inbox_sds),
    )


def _pack_held(spec: Spec, out: Msg, dm) -> HeldSparse:
    """Compact this round's delayed slots (mask dm [M, S, C]) into the
    sparse form: per sender row, the h-th delayed slot lands in held
    slot h; extras past HELD_SLOTS drop."""
    S = spec.K * spec.M
    H = HELD_SLOTS
    rank = jnp.cumsum(dm.astype(jnp.int32), axis=1) - 1        # [M, S, C]
    sel = (
        rank[:, None, :, :] == jnp.arange(H, dtype=jnp.int32)[None, :, None, None]
    ) & dm[:, None]                                            # [M, H, S, C]
    taken = sel.any(axis=2)                                    # [M, H, C]
    slot_ids = jnp.arange(S, dtype=jnp.int32)[None, None, :, None]
    idx = jnp.where(taken, (sel * slot_ids).sum(axis=2), -1)

    def pack(x):
        e = x.shape[1] // S
        xr = x.reshape(spec.M, S, e, x.shape[-1])
        f = (sel[:, :, :, None, :] * xr[:, None]).sum(axis=2)  # [M, H, e, C]
        return f.reshape(spec.M, H * e, x.shape[-1]).astype(x.dtype)

    return HeldSparse(idx=idx, msgs=jax.tree.map(pack, out))


def _held_wins(spec: Spec, held: HeldSparse, fresh: Msg) -> Msg:
    """Scatter the sparse held messages back over fresh traffic: a held
    message wins a slot collision (the fresh one drops — legal per the
    transport contract, etcdserver/raft.go:107-110)."""
    S = spec.K * spec.M
    H = HELD_SLOTS
    sel = (
        held.idx[:, :, None, :]
        == jnp.arange(S, dtype=jnp.int32)[None, None, :, None]
    ) & (held.idx >= 0)[:, :, None, :]                         # [M, H, S, C]
    live = sel.any(axis=1)                                     # [M, S, C]

    def un(xh, f):
        e = f.shape[1] // S
        xr = xh.reshape(spec.M, H, e, xh.shape[-1])
        dense = (sel[:, :, :, None, :] * xr[:, :, None]).sum(axis=1)
        dense = dense.reshape(spec.M, S * e, xh.shape[-1]).astype(f.dtype)
        return jnp.where(_bc(spec, live, f), dense, f)

    return jax.tree.map(un, held.msgs, fresh)


def _merge_delayed(spec: Spec, out: Msg, held: HeldSparse,
                   delay_mask) -> tuple[Msg, HeldSparse]:
    """Split this round's traffic by the delay mask and merge in messages
    held from the previous round. Message leaves are in the engine's FLAT
    storage form [from, K*to(*E), C]; `delay_mask` is [from, K*to, C]."""
    new_held = _pack_held(spec, out, delay_mask)
    fresh = out.replace(type=jnp.where(delay_mask, 0, out.type))
    return _held_wins(spec, held, fresh), new_held


def build_chaos_epoch(
    cfg: RaftConfig,
    spec: Spec,
    rounds: int,
    faultless: bool = False,
    partition_period: int = 25,
    tick: bool = True,
    with_delay: bool = True,
):
    """One jitted chaos epoch: `rounds` lockstep rounds of faulted traffic
    with per-round invariant checks.

    Returns fn(state, inbox, held, key, prop_len, prop_data, viol,
    drop_p, delay_p, partition_p) -> (state, inbox, held, key, viol,
    commits_delta). The fault probabilities are RUNTIME operands, not
    closure constants — one traced program serves every fault mix (a
    full trace costs ~40s of single-core time; the suite's three chaos
    configurations used to pay it three times over). The regression
    baseline (prev_commit) starts at the entry state's own commit —
    nothing moves between epochs, so passing it across the boundary
    would merely alias a leaf of the donated state.

    Partitions re-sample every `partition_period` rounds: each group is
    partitioned with probability partition_p into two random sides (links
    across sides drop entirely); other faults stack on top. `faultless`
    selects the structurally-reduced heal program (no sampling, no held
    bookkeeping), which ignores the probability operands.

    `with_delay=False` removes the delay/reorder machinery AT TRACE TIME:
    no Bernoulli delay draws, no held-buffer merge, and no held pytree
    in the scan carry. The held buffer is SPARSE (HeldSparse: HELD_SLOTS
    packed messages per sender row) — the round-4 dense form was a full
    second inbox whose double-buffering overflowed HBM at the 1M-group
    configuration (measured 17.01G vs 15.75G), capping delay coverage
    at 524k groups. Callers pass held=None and get None back.
    """
    round_fn = build_round(cfg, spec)
    M = spec.M

    def epoch(state, inbox, held, key, prop_len, prop_data, viol,
              drop_p, delay_p, partition_p):
        prev_commit = state.commit
        C = state.term.shape[-1]
        zp = jnp.zeros((M, spec.E, C), jnp.int32)
        z2 = jnp.zeros((M, C), jnp.int32)
        no = jnp.zeros((M, C), jnp.bool_)
        do_tick = jnp.full((M, C), tick, jnp.bool_)
        commit0 = state.commit.sum()
        key, pkey = jax.random.split(key)

        if faultless:
            # heal program: no fault sampling, no delay bookkeeping. Drain
            # whatever the previous chaos epoch still held by merging it
            # into the entry inbox once (held wins a slot collision, as in
            # _merge_delayed), then run bare rounds with per-round checks.
            if with_delay:
                inbox = _held_wins(spec, held, inbox)
                held = held.replace(
                    idx=jnp.full_like(held.idx, -1),
                    msgs=jax.tree.map(jnp.zeros_like, held.msgs),
                )
            keep_all = jnp.ones((M, M, C), jnp.bool_)

            def heal_body(carry, r):
                state, inbox, viol, prev_commit = carry
                state, out = round_fn(
                    state, inbox, prop_len, prop_data, zp, z2, no,
                    do_tick, keep_all
                )
                viol = check_invariants(state, prev_commit, viol)
                return (state, out, viol, state.commit), None

            (state, inbox, viol, prev_commit), _ = jax.lax.scan(
                heal_body, (state, inbox, viol, prev_commit),
                jnp.arange(rounds, dtype=jnp.int32),
            )
            return (state, inbox, held, key, viol,
                    state.commit.sum() - commit0)

        def sample_keep(key, r):
            key, kd, kl = jax.random.split(key, 3)
            # rolling partition: drawn from the epoch-stable pkey folded
            # with the period index, so the cut holds for a whole period
            # and re-rolls at the next one
            period = r // partition_period
            kp = jax.random.fold_in(pkey, period)
            side = jax.random.bernoulli(kp, 0.5, (M, C))
            partitioned = jax.random.bernoulli(
                jax.random.fold_in(kp, 1), partition_p, (C,)
            )
            same_side = side[:, None, :] == side[None, :, :]  # [M, M, C]
            keep_part = same_side | ~partitioned[None, None, :]
            keep_drop = jax.random.bernoulli(kd, 1.0 - drop_p, (M, M, C))
            return key, kl, keep_part & keep_drop

        if with_delay:
            def body(carry, r):
                state, inbox, held, key, viol, prev_commit = carry
                key, kl, keep = sample_keep(key, r)
                state, out = round_fn(
                    state, inbox, prop_len, prop_data, zp, z2, no,
                    do_tick, keep
                )
                delay = jax.random.bernoulli(
                    kl, delay_p, (M, spec.K * M, C)
                ) & (out.type != 0)
                nxt, held2 = _merge_delayed(spec, out, held, delay)
                viol = check_invariants(state, prev_commit, viol)
                return (state, nxt, held2, key, viol, state.commit), None

            (state, inbox, held, key, viol, prev_commit), _ = jax.lax.scan(
                body, (state, inbox, held, key, viol, prev_commit),
                jnp.arange(rounds, dtype=jnp.int32),
            )
        else:
            def body(carry, r):
                state, inbox, key, viol, prev_commit = carry
                key, _, keep = sample_keep(key, r)
                state, out = round_fn(
                    state, inbox, prop_len, prop_data, zp, z2, no,
                    do_tick, keep
                )
                viol = check_invariants(state, prev_commit, viol)
                return (state, out, key, viol, state.commit), None

            (state, inbox, key, viol, prev_commit), _ = jax.lax.scan(
                body, (state, inbox, key, viol, prev_commit),
                jnp.arange(rounds, dtype=jnp.int32),
            )
        return state, inbox, held, key, viol, state.commit.sum() - commit0

    return epoch


@functools.lru_cache(maxsize=32)
def _epoch_program(cfg: RaftConfig, spec: Spec, rounds: int,
                   faultless: bool, with_delay: bool = True):
    """One jitted epoch program per (cfg, spec, rounds, structure),
    shared across every run_chaos call and fault mix (probabilities are
    operands). Donation of the fleet-sized carries (state/inbox/held) is
    accelerator-only: large-C runs that compile fine otherwise die at
    runtime allocation from double-buffering, while host runs don't need
    the memory and keep maximum runtime portability."""
    if jax.default_backend() != "cpu":
        # held (arg 2) is None (no buffers) when the delay machinery is
        # compiled out — donating it is at best a no-op and has crashed
        # the tunneled TPU worker at fleet scale
        donate = (0, 1, 2) if with_delay else (0, 1)
    else:
        donate = ()
    return jax.jit(
        build_chaos_epoch(cfg, spec, rounds, faultless=faultless,
                          with_delay=with_delay),
        donate_argnums=donate,
    )


def run_chaos(
    spec: Spec,
    cfg: RaftConfig,
    C: int,
    rounds: int = 200,
    epoch_len: int = 50,
    heal_len: int = 25,
    seed: int = 0,
    drop_p: float = 0.02,
    delay_p: float = 0.05,
    partition_p: float = 0.1,
    propose: bool = True,
    sync_dispatch: bool = False,
) -> dict:
    """The tester's round loop (tester/cluster_run.go): alternate fault
    epochs and heal epochs, then verify recovery — every group ends with
    a leader and fresh commits. Returns the violation counts + liveness
    stats; raises nothing (the caller asserts)."""
    state = init_fleet(spec, C, election_tick=cfg.election_tick, seed=seed)
    inbox = empty_inbox(spec, C, wire_int16=cfg.wire_int16)
    # delay/reorder faults carry a SPARSE held buffer (HELD_SLOTS packed
    # messages per sender row — see HeldSparse); delay_p=0 still drops
    # the whole machinery at trace time
    with_delay = delay_p > 0
    held = empty_held(spec, C, cfg.wire_int16) if with_delay else None
    key = jax.random.PRNGKey(seed)
    M = spec.M
    prop_len = jnp.zeros((M, C), jnp.int32)
    prop_data = jnp.zeros((M, spec.E, C), jnp.int32)
    if propose:
        # one proposal per group per round at node 0; when node 0 is not
        # the leader the proposal forwards to it (stepFollower MsgProp),
        # so stress keeps flowing wherever leadership lands
        prop_len = prop_len.at[0].set(1)
        prop_data = prop_data.at[0, 0].set(7)

    chaos = _epoch_program(cfg, spec, epoch_len, False, with_delay)
    heal = _epoch_program(cfg, spec, heal_len, True, with_delay)
    dp = jnp.float32(drop_p)
    lp = jnp.float32(delay_p)
    pp = jnp.float32(partition_p)
    z = jnp.float32(0.0)

    def _sync(x):
        # marginal-HBM probe (sync_dispatch): block between epoch
        # dispatches so the donated buffers of the finished program are
        # released before the next executable's workspace is allocated —
        # async dispatch enqueues both and the allocator sees the sum
        if sync_dispatch:
            jax.block_until_ready(x)

    viol = zero_violations()
    commits = []
    done = 0
    while done < rounds:
        state, inbox, held, key, viol, dc = chaos(
            state, inbox, held, key, prop_len, prop_data, viol, dp, lp, pp
        )
        _sync(viol.multi_leader)
        done += epoch_len
        state, inbox, held, key, viol, dh = heal(
            state, inbox, held, key, prop_len, prop_data, viol, z, z, z
        )
        _sync(viol.multi_leader)
        done += heal_len
        commits.append((int(dc), int(dh)))

    # recovery check (the tester's WaitHealth loop, tester/cluster.go):
    # keep healing in bounded increments until every group has a leader —
    # a group whose randomized election timeout just fired may need more
    # than one heal epoch to converge
    def leaders() -> int:
        return int(((state.role == ROLE_LEADER).sum(axis=0) > 0).sum())

    for _ in range(6):
        if leaders() == C:
            break
        state, inbox, held, key, viol, dh = heal(
            state, inbox, held, key, prop_len, prop_data, viol, z, z, z
        )
        done += heal_len
        commits.append((0, int(dh)))
    has_leader = leaders()
    v = jax.device_get(viol)
    return {
        "groups": C,
        "rounds": done,
        "multi_leader": int(v.multi_leader),
        "hash_mismatch": int(v.hash_mismatch),
        "commit_regress": int(v.commit_regress),
        "groups_with_leader_after_heal": has_leader,
        "heal_commits_last_epoch": commits[-1][1],
        "epoch_commits": commits,
    }
