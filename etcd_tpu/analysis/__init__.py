"""Trace-contract static analysis plane.

Two levels (see README "Static analysis"):

  * ``analysis.lint`` — pure-AST lint rules over the repo source
    (knob hygiene, host-sync discipline in traced modules, leftover
    debug prints, undefined names, dead knobs). Stdlib-only: importing
    it never touches jax.
  * ``analysis.audit`` — program auditors that lower the canonical
    entry programs and statically check the contracts the perf claims
    rest on: one-trace/many-operands, donation completeness with no
    double-donation, no host callbacks, zero cross-shard collectives in
    the steady-state sharded round, and pack/wire width contracts.

CLI: ``python -m etcd_tpu.analysis`` (exit 0 clean, 1 findings, 2 bad
knobs). Knobs: ANALYSIS_RULES / ANALYSIS_PATHS / ANALYSIS_AUDIT /
ANALYSIS_AUDITORS / ANALYSIS_PROGRAMS via utils/knobs.
"""
from etcd_tpu.analysis.lint import Finding, lint_paths, run_lint, RULES

__all__ = ["Finding", "lint_paths", "run_lint", "RULES"]
