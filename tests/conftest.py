import os

# Tests always run on a virtual 8-device CPU mesh so sharding paths are
# exercised without TPU hardware (and unit tests stay fast/deterministic).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
