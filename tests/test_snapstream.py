"""Streamed snapshot transfer: chunking, CRC verification, corruption
detection (rafthttp/snapshot_sender.go + api/snap/db.go analog)."""
import pytest

from etcd_tpu.storage.snapstream import (
    SnapshotReceiver,
    SnapStreamError,
    send_snapshot,
    transfer,
)


@pytest.fixture
def snap():
    return {"applied_index": 42, "kv": {"data": b"x" * 300_000},
            "lease": [1, 2, 3], "v2": "{}"}


def test_roundtrip(snap):
    assert transfer(snap, chunk_size=4096) == snap


def test_roundtrip_single_chunk(snap):
    assert transfer(snap, chunk_size=1 << 30) == snap


def test_chunk_corruption_detected(snap):
    with pytest.raises(SnapStreamError, match="CRC"):
        transfer(snap, chunk_size=4096, corrupt_frame=3)


def test_short_transfer_detected(snap):
    frames = list(send_snapshot(snap, chunk_size=4096))
    rx = SnapshotReceiver()
    for f in frames[:-1]:  # drop the tail chunk
        rx.feed(f)
    with pytest.raises(SnapStreamError, match="short"):
        rx.close()


def test_out_of_order_detected(snap):
    frames = list(send_snapshot(snap, chunk_size=4096))
    rx = SnapshotReceiver()
    rx.feed(frames[0])
    rx.feed(frames[1])
    with pytest.raises(SnapStreamError, match="out-of-order"):
        rx.feed(frames[3])


def test_chunk_before_header(snap):
    frames = list(send_snapshot(snap, chunk_size=4096))
    rx = SnapshotReceiver()
    with pytest.raises(SnapStreamError, match="before header"):
        rx.feed(frames[1])


def test_retransmit_after_failure_succeeds(snap):
    """The sender retries the whole transfer after a failed attempt
    (snapshot_sender.go retries via the pipeline) — a fresh receiver
    accepts the second pass."""
    with pytest.raises(SnapStreamError):
        transfer(snap, chunk_size=4096, corrupt_frame=2)
    assert transfer(snap, chunk_size=4096) == snap


def test_peer_snapshot_path_uses_stream(monkeypatch):
    """_install_peer_snapshot routes through the streamed channel."""
    from etcd_tpu.server import kvserver
    from etcd_tpu.server.kvserver import EtcdCluster

    ec = EtcdCluster(n_members=3)
    ec.ensure_leader()
    ec.put(b"k", b"v")
    calls = []
    import etcd_tpu.storage.snapstream as ss
    real = ss.transfer

    def spy(snap, *a, **kw):
        calls.append(1)
        return real(snap, *a, **kw)

    monkeypatch.setattr(ss, "transfer", spy)
    victim = (ec.ensure_leader() + 1) % 3
    ec._install_peer_snapshot(
        victim, ec.members[victim],
        ec.members[ec.ensure_leader()].applied_index)
    assert calls
    assert ec.members[victim].store.kv.range(b"k")[0]
