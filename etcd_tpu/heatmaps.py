"""Mixed read/write benchmark sweep — the tools/rw-heatmaps analog.

Re-design of ``tools/rw-heatmaps/rw-benchmark.sh`` + ``plot_data.py``:
sweep read/write ratio x value size x client concurrency over a live
cluster, record read & write throughput per cell in the same CSV shape
the reference's plotter consumes (``type,ratio,conn_size,value_size,
iterN`` with ``read:write`` cells, plus a PARAM comment row), and
render the heatmap grids as text (the zero-dependency stand-in for the
matplotlib images; the CSV remains loadable by the reference's
plot_data.py).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Sequence

# scaled-down defaults of rw-benchmark.sh's sweep axes
DEFAULT_RATIOS = (0.125, 0.5, 2.0, 8.0)   # reads per write
DEFAULT_VALUE_SIZES = (256, 1024)
DEFAULT_CONN_COUNTS = (4, 16)


def run_cell(ec, ratio: float, conn: int, value_size: int,
             ops: int) -> tuple[float, float]:
    """One sweep cell: `ops` operations split reads/writes by `ratio`
    across `conn` round-robin sessions. Returns (reads/s, writes/s)."""
    val = b"v" * value_size
    keys = [b"heat/%d" % i for i in range(conn)]
    for k in keys:
        ec.put(k, val)
    reads = writes = 0
    r_acc = ratio / (1.0 + ratio)  # fraction of ops that are reads
    acc = 0.0
    t0 = time.perf_counter()
    for i in range(ops):
        k = keys[i % conn]
        acc += r_acc
        if acc >= 1.0:
            acc -= 1.0
            ec.range(k)
            reads += 1
        else:
            ec.put(k, val)
            writes += 1
    dt = time.perf_counter() - t0 or 1e-9
    return reads / dt, writes / dt


def run_sweep(ec, ratios: Sequence[float] = DEFAULT_RATIOS,
              value_sizes: Sequence[int] = DEFAULT_VALUE_SIZES,
              conn_counts: Sequence[int] = DEFAULT_CONN_COUNTS,
              repeats: int = 1, ops: int = 64) -> list[dict]:
    rows = []
    for ratio in ratios:
        for conn in conn_counts:
            for vs in value_sizes:
                iters = [run_cell(ec, ratio, conn, vs, ops)
                         for _ in range(repeats)]
                rows.append({"type": "DATA", "ratio": ratio,
                             "conn_size": conn, "value_size": vs,
                             "iters": iters})
    return rows


def write_csv(rows: list[dict], path: str, comment: str = "") -> None:
    """rw-benchmark.sh CSV shape: iterN cells are 'read:write'."""
    repeats = max((len(r["iters"]) for r in rows), default=1)
    hdr = ["type", "ratio", "conn_size", "value_size"] + \
        [f"iter{i}" for i in range(repeats)] + ["comment"]
    lines = [",".join(hdr)]
    if comment:
        lines.append(",".join(
            ["PARAM", "0", "0", "0"] + [""] * repeats + [comment]))
    for r in rows:
        cells = [f"{rd:.1f}:{wr:.1f}" for rd, wr in r["iters"]]
        cells += [""] * (repeats - len(cells))
        lines.append(",".join(
            ["DATA", str(r["ratio"]), str(r["conn_size"]),
             str(r["value_size"])] + cells + [""]))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def render_ascii(rows: list[dict], metric: str = "read") -> str:
    """One text heatmap grid per value size: ratio rows x conn cols."""
    idx = 0 if metric == "read" else 1
    out = []
    for vs in sorted({r["value_size"] for r in rows}):
        sub = [r for r in rows if r["value_size"] == vs]
        conns = sorted({r["conn_size"] for r in sub})
        ratios = sorted({r["ratio"] for r in sub})
        out.append(f"== {metric}/s @ value_size={vs} ==")
        out.append("ratio\\conn " + " ".join(f"{c:>10}" for c in conns))
        for ratio in ratios:
            cells = []
            for c in conns:
                rs = [r for r in sub
                      if r["conn_size"] == c and r["ratio"] == ratio]
                best = max((it[idx] for r in rs for it in r["iters"]),
                           default=0.0)
                cells.append(f"{best:>10.0f}")
            out.append(f"{ratio:>10} " + " ".join(cells))
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rw-heatmaps")
    p.add_argument("--output", default="rw_result.csv")
    p.add_argument("--ops", type=int, default=64)
    p.add_argument("--repeats", type=int, default=1)
    p.add_argument("--members", type=int, default=3)
    p.add_argument("--comment", default="etcd_tpu rw sweep")
    p.add_argument("--ratios", default=None,
                   help="comma list of read/write ratios")
    p.add_argument("--value-sizes", default=None)
    p.add_argument("--conns", default=None)
    args = p.parse_args(argv)

    from etcd_tpu.server.kvserver import EtcdCluster

    ec = EtcdCluster(n_members=args.members)
    ec.ensure_leader()
    rows = run_sweep(
        ec,
        ratios=tuple(float(x) for x in args.ratios.split(","))
        if args.ratios else DEFAULT_RATIOS,
        value_sizes=tuple(int(x) for x in args.value_sizes.split(","))
        if args.value_sizes else DEFAULT_VALUE_SIZES,
        conn_counts=tuple(int(x) for x in args.conns.split(","))
        if args.conns else DEFAULT_CONN_COUNTS,
        repeats=args.repeats, ops=args.ops)
    write_csv(rows, args.output, comment=args.comment)
    print(render_ascii(rows, "read"))
    print(render_ascii(rows, "write"))
    print(json.dumps({"cells": len(rows), "csv": args.output}))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
