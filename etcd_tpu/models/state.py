"""Per-node Raft state as a struct-of-arrays pytree.

This is the TPU-native re-layout of the reference's ``raft`` struct
(raft/raft.go:243-316) fused with its ``raftLog`` (raft/log.go:24-45),
``tracker.ProgressTracker`` (tracker/tracker.go) and config masks
(tracker.Config / confchange): one node's state is a bundle of scalars,
[M] peer-arrays and an [L] log ring; a whole fleet is the same pytree with
leading ``[clusters, members]`` axes produced by ``jax.vmap``.

Design notes vs the reference:
  * stable/unstable log split (raft/log_unstable.go) collapses to cursor
    arithmetic — the device ring IS the log; host checkpointing reads any
    suffix it wants. `first_index = snap_index + 1`, valid range
    (snap_index, last_index], capacity L.
  * Snapshots are applied eagerly on restore (the reference stages them in
    `unstable.snapshot` until the app applies them; our "application" is
    fused into the round step), so `promotable()`'s pending-snapshot check
    (raft/raft.go:1618-1621) is vacuously satisfied.
  * The applied state machine is a rolling hash chain (`applied_hash`) —
    the batched analog of the functional tester's KV_HASH checker
    (tests/functional/tester/checker_kv_hash.go): two nodes with equal
    `applied` must have equal `applied_hash`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from etcd_tpu.types import (
    NONE_ID,
    PR_PROBE,
    ROLE_FOLLOWER,
    Spec,
)


class NodeState(struct.PyTreeNode):
    # --- identity -----------------------------------------------------------
    nid: jnp.ndarray          # i32, this node's member id (constant)

    # --- HardState (raftpb.HardState, raft.proto:102-106) -------------------
    term: jnp.ndarray         # i32
    vote: jnp.ndarray         # i32, NONE_ID if none
    commit: jnp.ndarray       # i32

    # --- SoftState ----------------------------------------------------------
    lead: jnp.ndarray         # i32, NONE_ID if unknown
    role: jnp.ndarray         # i32 ROLE_*

    # --- log ring (raftLog + unstable fused) --------------------------------
    log_term: jnp.ndarray     # i32[L]
    log_data: jnp.ndarray     # i32[L]
    log_type: jnp.ndarray     # i32[L] ENTRY_*
    last_index: jnp.ndarray   # i32
    applied: jnp.ndarray      # i32
    applied_hash: jnp.ndarray # i32 rolling hash chain of applied entries

    # --- snapshot (raftpb.SnapshotMetadata analog) --------------------------
    snap_index: jnp.ndarray   # i32; log holds (snap_index, last_index]
    snap_term: jnp.ndarray    # i32
    snap_hash: jnp.ndarray    # i32 applied_hash at snap_index
    snap_voters: jnp.ndarray        # bool[M] ConfState at snapshot
    snap_voters_out: jnp.ndarray    # bool[M]
    snap_learners: jnp.ndarray      # bool[M]
    snap_learners_next: jnp.ndarray # bool[M]
    snap_auto_leave: jnp.ndarray    # bool

    # --- timers (raft.go:285-303) -------------------------------------------
    election_elapsed: jnp.ndarray    # i32
    heartbeat_elapsed: jnp.ndarray   # i32
    randomized_timeout: jnp.ndarray  # i32
    rng_key: jnp.ndarray             # u32[2] per-node PRNG key

    # --- leader replication tracker (tracker/progress.go:30-80) -------------
    match: jnp.ndarray        # i32[M]
    next_idx: jnp.ndarray     # i32[M]
    pr_state: jnp.ndarray     # i32[M] PR_*
    probe_sent: jnp.ndarray   # bool[M]
    pending_snapshot: jnp.ndarray  # i32[M]
    recent_active: jnp.ndarray     # bool[M]
    # inflights ring (tracker/inflights.go): ends of in-flight MsgApps.
    # Stored FLAT [M*W]: rank-2 per-node leaves with tiny minor dims get
    # tile-padded ~26x once batched to fleet shape (a 1.25GB HLO temp at
    # C=65536); ops view it as [M, W] via free reshapes.
    infl_ends: jnp.ndarray    # i32[M*W]
    infl_start: jnp.ndarray   # i32[M]
    infl_count: jnp.ndarray   # i32[M]

    # --- votes (tracker.ProgressTracker.Votes) ------------------------------
    votes_responded: jnp.ndarray  # bool[M]
    votes_granted: jnp.ndarray    # bool[M]

    # --- config: this node's applied view (tracker.Config) ------------------
    voters: jnp.ndarray           # bool[M] incoming voters
    voters_out: jnp.ndarray       # bool[M] outgoing voters (joint iff any)
    learners: jnp.ndarray         # bool[M]
    learners_next: jnp.ndarray    # bool[M]
    auto_leave: jnp.ndarray       # bool

    # --- leader bookkeeping -------------------------------------------------
    pending_conf_index: jnp.ndarray  # i32
    uncommitted_size: jnp.ndarray    # i32 (entry count stand-in for bytes)
    lead_transferee: jnp.ndarray     # i32

    # --- read-only queue (raft/read_only.go), re-keyed by int ctx -----------
    ro_ctx: jnp.ndarray       # i32[R] request ctx ids (0 = empty)
    ro_index: jnp.ndarray     # i32[R] commit index captured at enqueue
    ro_from: jnp.ndarray      # i32[R] requester id (NONE_ID/self => local)
    ro_acks: jnp.ndarray      # bool[R*M] (flat; see infl_ends note)
    ro_count: jnp.ndarray     # i32 number of queued requests
    # pending MsgReadIndex deferred until first commit in term
    # (raft.go:311-315 pendingReadIndexMessages)
    ro_pend_ctx: jnp.ndarray  # i32[R]
    ro_pend_from: jnp.ndarray # i32[R]
    ro_pend_count: jnp.ndarray  # i32
    # ReadStates surfaced to the local application (raft.go:249)
    rs_ctx: jnp.ndarray       # i32[R]
    rs_index: jnp.ndarray     # i32[R]
    rs_count: jnp.ndarray     # i32


# ---------------------------------------------------------------------------
# Crash-durability classification (harness/chaos.py crash faults).
#
# Every NodeState field belongs to exactly one class; the chaos tier's
# crash–restart wipe (models/engine.py crash_restart_fleet) implements this
# table, and tests/test_recovery_crash.py proves the two agree — a new field
# added here without a classification fails the suite instead of silently
# surviving (or losing) a simulated crash.
#
#  * DURABLE: survives a crash as-is. HardState term/vote (MustSync forces
#    an fsync before any message reflecting them is sent,
#    raft/node.go:586-593), the snapshot metadata (snapshots fsync
#    synchronously before use), the node id, and the log ring ARRAYS
#    (slots past the durable last_index are dead by the last_index gate —
#    the window (snap_index, last_index] defines validity, so lost-suffix
#    slots need no scrub).
#  * CAPPED: survives up to the durable floor. last_index drops to the
#    fsync'd prefix (max(min(last_index, stable), snap_index)); commit is
#    additionally capped by it (commit-only advances don't fsync, so a
#    restart may legally REGRESS commit — the chaos commit-monotonicity
#    checker exempts crash rounds).
#  * REPLAY: re-derived by replaying the durable log from the snapshot:
#    applied/applied_hash rewind to the snapshot cursor (the fused apply
#    loop then re-applies committed entries, reproducing the identical
#    hash chain — which the KV_HASH checker verifies), and the applied
#    config masks rewind to the snapshot's ConfState. The chaos tier's
#    config-aware recovery checkers key on this: a crash may regress a
#    node's applied config VIEW, but never the durable conf entries, so
#    the checkers carry the newest-ever applied config across outages
#    (harness/chaos.py refresh_ref_config) instead of re-reading the
#    possibly-rewound masks.
#  * VOLATILE: reset to fresh-follower boot values (raft.go:318-370
#    newRaft on restart): role/lead/timers/tracker/votes/queues. The
#    randomized election timeout is re-drawn; rng_key is carried through
#    (PRNG state has no semantic content — any value is a valid restart).
# ---------------------------------------------------------------------------

DURABLE_FIELDS = (
    "nid", "term", "vote",
    "log_term", "log_data", "log_type",
    "snap_index", "snap_term", "snap_hash",
    "snap_voters", "snap_voters_out", "snap_learners", "snap_learners_next",
    "snap_auto_leave",
    "rng_key",
)
CAPPED_FIELDS = ("last_index", "commit")
REPLAY_FIELDS = (
    "applied", "applied_hash",
    "voters", "voters_out", "learners", "learners_next", "auto_leave",
)
VOLATILE_FIELDS = (
    "lead", "role",
    "election_elapsed", "heartbeat_elapsed", "randomized_timeout",
    "match", "next_idx", "pr_state", "probe_sent", "pending_snapshot",
    "recent_active",
    "infl_ends", "infl_start", "infl_count",
    "votes_responded", "votes_granted",
    "pending_conf_index", "uncommitted_size", "lead_transferee",
    "ro_ctx", "ro_index", "ro_from", "ro_acks", "ro_count",
    "ro_pend_ctx", "ro_pend_from", "ro_pend_count",
    "rs_ctx", "rs_index", "rs_count",
)


def init_node(
    spec: Spec,
    nid: int | jnp.ndarray,
    voters: jnp.ndarray,
    learners: jnp.ndarray | None = None,
    seed: int | jnp.ndarray = 0,
    election_tick: int = 10,
) -> NodeState:
    """A fresh follower at term 0 with the given applied config.

    Equivalent to newRaft on a MemoryStorage whose ConfState is already set
    (the way raft_test.go's newTestRaft boots; raft/raft.go:318-370) — the
    log is empty, commit/applied = 0, and like becomeFollower at boot a
    randomized election timeout in [T, 2T) is drawn.
    """
    M, L, W, R = spec.M, spec.L, spec.W, spec.R
    if learners is None:
        learners = jnp.zeros((M,), jnp.bool_)
    fM = jnp.zeros((M,), jnp.bool_)
    z = jnp.int32(0)
    nid = jnp.asarray(nid, jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(0), jnp.asarray(seed, jnp.int32))
    key = jax.random.fold_in(key, nid)
    key, sub = jax.random.split(key)
    rand_to = election_tick + jax.random.randint(
        sub, (), 0, election_tick, dtype=jnp.int32
    )
    return NodeState(
        nid=nid,
        term=z, vote=jnp.int32(NONE_ID), commit=z,
        lead=jnp.int32(NONE_ID), role=jnp.int32(ROLE_FOLLOWER),
        log_term=jnp.zeros((L,), jnp.int32),
        log_data=jnp.zeros((L,), jnp.int32),
        log_type=jnp.zeros((L,), jnp.int32),
        last_index=z, applied=z, applied_hash=z,
        snap_index=z, snap_term=z, snap_hash=z,
        snap_voters=voters, snap_voters_out=fM,
        snap_learners=learners, snap_learners_next=fM,
        snap_auto_leave=jnp.bool_(False),
        election_elapsed=z, heartbeat_elapsed=z,
        randomized_timeout=rand_to,
        rng_key=key,
        match=jnp.zeros((M,), jnp.int32),
        next_idx=jnp.ones((M,), jnp.int32),
        pr_state=jnp.full((M,), PR_PROBE, jnp.int32),
        probe_sent=fM,
        pending_snapshot=jnp.zeros((M,), jnp.int32),
        recent_active=fM,
        infl_ends=jnp.zeros((M * W,), jnp.int32),
        infl_start=jnp.zeros((M,), jnp.int32),
        infl_count=jnp.zeros((M,), jnp.int32),
        votes_responded=fM, votes_granted=fM,
        voters=voters, voters_out=fM,
        learners=learners, learners_next=fM,
        auto_leave=jnp.bool_(False),
        pending_conf_index=z, uncommitted_size=z,
        lead_transferee=jnp.int32(NONE_ID),
        ro_ctx=jnp.zeros((R,), jnp.int32),
        ro_index=jnp.zeros((R,), jnp.int32),
        ro_from=jnp.full((R,), NONE_ID, jnp.int32),
        ro_acks=jnp.zeros((R * M,), jnp.bool_),
        ro_count=z,
        ro_pend_ctx=jnp.zeros((R,), jnp.int32),
        ro_pend_from=jnp.full((R,), NONE_ID, jnp.int32),
        ro_pend_count=z,
        rs_ctx=jnp.zeros((R,), jnp.int32),
        rs_index=jnp.zeros((R,), jnp.int32),
        rs_count=z,
    )


def is_joint(n: NodeState) -> jnp.ndarray:
    return n.voters_out.any()


def is_learner_self(n: NodeState) -> jnp.ndarray:
    self_hot = jnp.arange(n.voters.shape[0], dtype=jnp.int32) == n.nid
    return (self_hot & n.learners).any()


def in_config_self(n: NodeState) -> jnp.ndarray:
    """Whether this node has a Progress entry, i.e. is voter/outgoing/learner."""
    self_hot = jnp.arange(n.voters.shape[0], dtype=jnp.int32) == n.nid
    return (self_hot & (n.voters | n.voters_out | n.learners)).any()
