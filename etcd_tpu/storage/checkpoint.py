"""Fleet checkpoint/restore — the WAL+snapshot pair at device scale.

The reference persists per-node HardState+entries in the WAL on every Ready
(server/etcdserver/raft.go:236) and cuts full snapshots every SnapshotCount
applied entries (server.go:1088-1104). At fleet scale the equivalent is:

  * full-state device->host snapshots every N rounds (one npz of the whole
    [C, M] pytree — HardState, log ring, trackers, RNG keys), and
  * per-round HardState/entry *deltas* appended to a WAL for the clusters
    the host is actively serving (EtcdCluster integration tier).

Restore rebuilds the exact NodeState pytree; because the engine is
deterministic given (state, inputs), replaying the same proposal schedule
reproduces the same fleet — the deterministic-replay contract of
SURVEY.md §5 checkpoint/resume.
"""
from __future__ import annotations

import json
import os

import jax
import numpy as np

from etcd_tpu.models.state import NodeState
from etcd_tpu.types import Spec


def _leaf_names(state: NodeState) -> list[str]:
    return [f.name for f in state.__dataclass_fields__.values()]


def save_fleet(path: str, state: NodeState, round_no: int = 0,
               extra: dict | None = None) -> None:
    """Atomic full-fleet snapshot (write-temp + rename, like the reference's
    snap file discipline in api/snap/snapshotter.go)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays = {
        name: np.asarray(getattr(state, name)) for name in _leaf_names(state)
    }
    meta = {"round": round_no, "extra": extra or {}}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, __meta__=json.dumps(meta), **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_fleet(path: str) -> tuple[NodeState, dict]:
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        kw = {k: jax.numpy.asarray(z[k]) for k in z.files if k != "__meta__"}
    return NodeState(**kw), meta


class FleetCheckpointer:
    """Every-N-rounds snapshot rotation with retention (the triggerSnapshot
    cadence, server.go:72 DefaultSnapshotCount)."""

    def __init__(self, dirpath: str, every: int = 1000, keep: int = 3):
        self.dir = dirpath
        self.every = every
        self.keep = keep
        self.round = 0
        os.makedirs(dirpath, exist_ok=True)

    def maybe_save(self, state: NodeState, rounds_advanced: int = 1) -> bool:
        self.round += rounds_advanced
        if self.round % self.every:
            return False
        self.save(state)
        return True

    def save(self, state: NodeState) -> str:
        path = os.path.join(self.dir, f"fleet-{self.round:012d}.npz")
        save_fleet(path, state, self.round)
        self._gc()
        return path

    def latest(self) -> str | None:
        snaps = sorted(
            f for f in os.listdir(self.dir)
            if f.startswith("fleet-") and f.endswith(".npz")
        )
        return os.path.join(self.dir, snaps[-1]) if snaps else None

    def restore(self) -> tuple[NodeState, dict] | None:
        p = self.latest()
        if p is None:
            return None
        state, meta = load_fleet(p)
        self.round = meta["round"]
        return state, meta

    def _gc(self) -> None:
        snaps = sorted(
            f for f in os.listdir(self.dir)
            if f.startswith("fleet-") and f.endswith(".npz")
        )
        for f in snaps[: -self.keep]:
            os.remove(os.path.join(self.dir, f))
