"""Raft paper invariants — transliteration of raft/raft_paper_test.go
(header at raft_paper_test.go:15-26): each test pins a sentence of the raft
paper, §5.1-§5.4. Tests drive single nodes with hand-crafted messages via
Cluster.inject/set_node, the batched analog of r.Step(pb.Message{...}).

Replication-path members of the suite (TestLeaderStartReplication,
TestLeaderCommitEntry, TestLeaderAcknowledgeCommit,
TestLeaderCommitPrecedingEntries, TestFollowerCommitEntry,
TestLeaderSyncFollowerLog, TestLeaderOnlyCommitsLogFromCurrentTerm) live in
tests/test_replication.py; election-path members overlap tests/
test_election.py. This file covers the rest.
"""
import numpy as np
import pytest

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.types import (
    MSG_APP,
    MSG_APP_RESP,
    MSG_HEARTBEAT,
    MSG_VOTE,
    MSG_VOTE_RESP,
    NONE_ID,
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    Spec,
)


# ---------------------------------------------------------------------------
# §5.1 terms
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("role", [ROLE_FOLLOWER, ROLE_CANDIDATE, ROLE_LEADER])
def test_update_term_from_message(role):
    """TestFollower/Candidate/LeaderUpdateTermFromMessage (§5.1): any node
    seeing a higher term adopts it and becomes follower."""
    cl = Cluster(n_members=3)
    if role == ROLE_CANDIDATE:
        cl.campaign(0)
        cl.step()
        cl.drain()
    elif role == ROLE_LEADER:
        cl.campaign(0)
        cl.stabilize()
    assert cl.get("role", 0) == role
    cl.inject(to=0, frm=1, type=MSG_APP, term=5, index=0, log_term=0)
    cl.step()
    assert cl.get("term", 0) == 5
    assert cl.get("role", 0) == ROLE_FOLLOWER


def test_reject_stale_term_message(SpecCls=Spec):
    """TestRejectStaleTermMessage (§5.1): messages with a stale term do not
    change state."""
    cl = Cluster(n_members=3)
    cl.set_node(0, term=2)
    cl.inject(to=0, frm=1, type=MSG_APP, term=1, index=0, log_term=0)
    cl.step()
    assert cl.get("term", 0) == 2
    assert cl.get("role", 0) == ROLE_FOLLOWER
    assert cl.get("last_index", 0) == 0


def test_start_as_follower():
    """TestStartAsFollower (§5.2)."""
    cl = Cluster(n_members=3)
    assert [cl.get("role", m) for m in range(3)] == [ROLE_FOLLOWER] * 3


def test_leader_bcast_beat():
    """TestLeaderBcastBeat (§5.2): after heartbeat_tick ticks the leader
    sends MsgHeartbeat to every peer."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    assert cl.get("role", 0) == ROLE_LEADER
    cl.drain()
    cl.step(tick=True)  # heartbeat_tick defaults to 1
    hb = [(to, frm) for to, frm, _, t in cl.pending() if t == MSG_HEARTBEAT]
    assert set(hb) == {(1, 0), (2, 0)}


@pytest.mark.parametrize("role", [ROLE_FOLLOWER, ROLE_CANDIDATE])
def test_nonleader_start_election(role):
    """TestFollowerStartElection / TestCandidateStartNewElection (§5.2):
    after election timeout, increment term and send MsgVote to peers."""
    cl = Cluster(n_members=3)
    if role == ROLE_CANDIDATE:
        cl.campaign(0)
        cl.step()
        cl.drain()
    term0 = cl.get("term", 0)
    # force the timeout to fire deterministically
    cl.set_node(0, election_elapsed=cl.get("randomized_timeout", 0) - 1)
    cl.step(tick=True)
    assert cl.get("term", 0) == term0 + 1
    assert cl.get("role", 0) == ROLE_CANDIDATE
    votes = [(to, frm) for to, frm, _, t in cl.pending() if t == MSG_VOTE]
    assert set(votes) == {(1, 0), (2, 0)}


@pytest.mark.parametrize("size,grants,wins", [
    (1, 0, True), (3, 1, True), (3, 0, False), (5, 2, True), (5, 1, False),
])
def test_leader_election_in_one_round_rpc(size, grants, wins):
    """TestLeaderElectionInOneRoundRPC (§5.2): a candidate wins iff it
    gathers a majority in the single vote round."""
    cl = Cluster(n_members=size, spec=Spec(M=size))
    cl.campaign(0)
    cl.step()
    cl.drain()
    term = cl.get("term", 0)
    for g in range(grants):
        cl.inject(to=0, frm=1 + g, type=MSG_VOTE_RESP, term=term, reject=False)
    cl.step()
    want = ROLE_LEADER if wins else ROLE_CANDIDATE
    assert cl.get("role", 0) == want


@pytest.mark.parametrize("vote,frm,granted", [
    (NONE_ID, 1, True), (NONE_ID, 2, True),
    (1, 1, True), (2, 2, True),
    (1, 2, False), (2, 1, False),
])
def test_follower_vote(vote, frm, granted):
    """TestFollowerVote (§5.2): grant iff no vote yet this term or already
    voted for the requester."""
    cl = Cluster(n_members=3)
    cl.set_node(0, term=1, vote=vote)
    cl.inject(to=0, frm=frm, type=MSG_VOTE, term=1, index=0, log_term=0)
    cl.step()
    resp = [
        (to, f) for to, f, _, t in cl.pending() if t == MSG_VOTE_RESP
    ]
    assert resp == [(frm, 0)]
    assert bool(cl.msg_field("reject", to=frm, frm=0)) == (not granted)


@pytest.mark.parametrize("dterm", [0, 1])
def test_candidate_fallback(dterm):
    """TestCandidateFallback (§5.2): a candidate hearing MsgApp at >= its
    term reverts to follower."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.step()
    cl.drain()
    term = cl.get("term", 0)
    cl.inject(to=0, frm=2, type=MSG_APP, term=term + dterm, index=0, log_term=0)
    cl.step()
    assert cl.get("role", 0) == ROLE_FOLLOWER
    assert cl.get("term", 0) == term + dterm
    assert cl.get("lead", 0) == 2


def test_election_timeout_randomized():
    """TestFollower/CandidateElectionTimeoutRandomized (§5.2): timeouts are
    drawn from [T, 2T) and vary across nodes/redraws."""
    et = 10
    cl = Cluster(n_members=5, C=16, spec=Spec(M=5))
    seen = set()
    for c in range(16):
        for m in range(5):
            to = cl.get("randomized_timeout", m, c=c)
            assert et <= to < 2 * et
            seen.add(to)
    assert len(seen) >= et // 2  # spread, not constant


def test_election_timeouts_mostly_nonconflicting():
    """TestFollowersElectionTimeoutNonconflict flavor: the randomized draw
    keeps simultaneous campaigns rare (conflict rate well under 50%)."""
    C = 16
    cl = Cluster(n_members=5, C=C, spec=Spec(M=5))
    conflicts = 0
    for c in range(C):
        tos = [cl.get("randomized_timeout", m, c=c) for m in range(5)]
        if min(tos) == sorted(tos)[1]:
            conflicts += 1
    assert conflicts / C < 0.5


# ---------------------------------------------------------------------------
# §5.3 / §5.4 log matching & vote safety (message-level)
# ---------------------------------------------------------------------------

def test_vote_request_carries_log_position():
    """TestVoteRequest (§5.4.1): MsgVote carries the candidate's lastIndex
    and lastLogTerm."""
    cl = Cluster(n_members=3)
    cl.inject(
        to=0, frm=1, type=MSG_APP, term=2, index=0, log_term=0,
        ent_len=1, ent_term=[2, 0, 0, 0], ent_data=[9, 0, 0, 0],
        ent_type=[0, 0, 0, 0],
    )
    cl.step()
    cl.drain()
    assert cl.get("last_index", 0) == 1
    cl.campaign(0)
    cl.step()
    votes = [(to, f) for to, f, _, t in cl.pending() if t == MSG_VOTE]
    assert set(votes) == {(1, 0), (2, 0)}
    for to, _ in votes:
        assert cl.msg_field("index", to=to, frm=0) == 1
        assert cl.msg_field("log_term", to=to, frm=0) == 2


@pytest.mark.parametrize("my_lt,my_li,cand_lt,cand_li,reject", [
    # candidate log more up-to-date -> grant
    (1, 1, 2, 1, False), (1, 1, 2, 2, False), (1, 1, 1, 2, False),
    # equal -> grant
    (1, 1, 1, 1, False),
    # voter more up-to-date -> reject
    (2, 1, 1, 1, True), (2, 1, 1, 2, True), (1, 2, 1, 1, True),
])
def test_voter_up_to_date_check(my_lt, my_li, cand_lt, cand_li, reject):
    """TestVoter (§5.4.1): grant only to candidates whose log is at least as
    up-to-date (raftLog.isUpToDate, log.go:313)."""
    cl = Cluster(n_members=2, spec=Spec(M=2))
    ents_t = [0, 0, 0, 0]
    for i in range(my_li):
        ents_t[i] = my_lt if i == my_li - 1 else 1
    cl.inject(
        to=0, frm=1, type=MSG_APP, term=my_lt, index=0, log_term=0,
        ent_len=my_li, ent_term=ents_t, ent_data=[0, 0, 0, 0],
        ent_type=[0, 0, 0, 0],
    )
    cl.step()
    cl.drain()
    assert cl.get("last_index", 0) == my_li
    cl.inject(
        to=0, frm=1, type=MSG_VOTE, term=max(my_lt, cand_lt) + 1,
        index=cand_li, log_term=cand_lt,
    )
    cl.step()
    assert bool(cl.msg_field("reject", to=1, frm=0)) == reject


def test_follower_check_msg_app():
    """TestFollowerCheckMsgApp (§5.3): a follower rejects MsgApp whose
    prev(index,term) doesn't match its log, with a hint."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 1)
    cl.stabilize()
    # follower 1 has [empty@t1, 1@t1]; MsgApp claiming prev=(5, t1) -> reject
    cl.inject(to=1, frm=0, type=MSG_APP, term=1, index=5, log_term=1)
    cl.step()
    resps = [
        (to, f) for to, f, _, t in cl.pending() if t == MSG_APP_RESP and to == 0
    ]
    assert (0, 1) in resps
    assert bool(cl.msg_field("reject", to=0, frm=1))
    assert cl.msg_field("reject_hint", to=0, frm=1) == 2  # its lastIndex


def test_follower_append_entries_overwrites_conflict():
    """TestFollowerAppendEntries (§5.3): conflicting suffix is deleted and
    the leader's entries appended."""
    cl = Cluster(n_members=2, spec=Spec(M=2))
    # build local log [t1, t2] via two appends from a fake leader
    cl.inject(
        to=0, frm=1, type=MSG_APP, term=2, index=0, log_term=0,
        ent_len=2, ent_term=[1, 2, 0, 0], ent_data=[10, 20, 0, 0],
        ent_type=[0, 0, 0, 0],
    )
    cl.step()
    cl.drain()
    assert cl.log_entries(0) == [(1, 10), (2, 20)]
    # conflicting append at index 2 with term 3
    cl.inject(
        to=0, frm=1, type=MSG_APP, term=3, index=1, log_term=1,
        ent_len=1, ent_term=[3, 0, 0, 0], ent_data=[30, 0, 0, 0],
        ent_type=[0, 0, 0, 0],
    )
    cl.step()
    assert cl.log_entries(0) == [(1, 10), (3, 30)]


def test_leader_acknowledge_commit():
    """TestLeaderAcknowledgeCommit (§5.3): the entry commits once a quorum
    of followers acked it; lone leader commits immediately."""
    for size, acks, committed in [(1, 0, True), (3, 0, False), (3, 1, True),
                                  (5, 1, False), (5, 2, True)]:
        cl = Cluster(n_members=size, spec=Spec(M=size))
        cl.campaign(0)
        cl.stabilize()
        base = cl.get("commit", 0)
        cl.drain()
        cl.propose(0, 3)
        cl.step()
        cl.drain()  # swallow the MsgApps: no real follower acks
        term = cl.get("term", 0)
        li = cl.get("last_index", 0)
        for a in range(acks):
            cl.inject(
                to=0, frm=1 + a, type=MSG_APP_RESP, term=term, index=li,
                reject=False,
            )
        cl.step()
        got = cl.get("commit", 0) >= base + 1
        assert got == committed, (size, acks)


def test_follower_commit_entry():
    """TestFollowerCommitEntry (§5.3): a follower commits (and applies) at
    the leader's commit index."""
    cl = Cluster(n_members=3)
    cl.inject(
        to=0, frm=1, type=MSG_APP, term=1, index=0, log_term=0,
        ent_len=1, ent_term=[1, 0, 0, 0], ent_data=[77, 0, 0, 0],
        ent_type=[0, 0, 0, 0], commit=1,
    )
    cl.step()
    assert cl.get("commit", 0) == 1
    assert cl.get("applied", 0) == 1
    assert cl.log_entries(0) == [(1, 77)]
