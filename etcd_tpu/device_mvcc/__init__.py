"""Device-resident MVCC apply plane.

Re-expresses the host MVCC apply path (etcd_tpu/server/mvcc.py) as
batched JAX tensors riding the same ``[clusters x members]`` fleet as the
consensus engine: a fixed-key-space revision store with vmapped txn
apply, compaction as a masked scatter with ErrCompacted/ErrFutureRev
status lanes, a shared canonical digest, and device-side watch-delta
extraction — so a committed entry becomes a *served write* without
leaving the chip (ROADMAP: "Device-resident apply plane").

Modules:
  scheme  — canonical key/value/word codec + the shared digest fold
            (pure python; both planes import it)
  state   — KVSpec / KVState pytree (clusters-minor, engine layout)
  apply   — apply_word / apply_words / read_at / kv_digest /
            extract_deltas (the jnp kernels)
  facade  — DevicePlane, the imperative per-lane host surface kvserver's
            DeviceBackedStore sits on
  fuzz    — differential schedule generator + host replay (shared by
            tests/test_device_mvcc.py and chaos_run.py's APPLY tier)

Engine integration: models/engine.py build_kv_round consumes committed
entry words straight from the apply frontier, host-apply vs device-apply
selected by a runtime operand (one trace serves both).
"""
from etcd_tpu.device_mvcc.state import KVSpec, KVState, init_kv  # noqa: F401
from etcd_tpu.device_mvcc.apply import (  # noqa: F401
    WatchDelta,
    apply_word,
    apply_words,
    extract_deltas,
    kv_digest,
    read_at,
)
from etcd_tpu.device_mvcc.facade import DevicePlane  # noqa: F401
