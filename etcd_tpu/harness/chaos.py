"""Functional chaos tier: randomized faults + on-device invariant checkers.

The reference's functional tester (tests/functional/tester/cluster.go:43-65)
loops rounds of inject -> stress -> recover -> check over a live cluster,
with fault cases like BLACKHOLE/DELAY_PEER_PORT_TX_RX (rpcpb enum) injected
by an L4 proxy (pkg/proxy/server.go:92-127), SIGTERM/SIGKILL process kills
with restart (tester/case_sigterm.go + the snapshot cases) and a KV_HASH
checker (tester/checker_kv_hash.go) asserting every member converges to the
same state hash.

The TPU-native equivalent runs the whole loop ON DEVICE at fleet scale:

  * drop faults: per-round Bernoulli keep-masks (the blackhole case);
  * partition faults: rolling per-group bisections re-sampled every epoch
    (SIGQUIT/blackhole-quorum analogs), healed between epochs;
  * delay/reorder faults (rafttest/network.go:122-144 delay semantics):
    messages divert into a held buffer with probability p and deliver a
    round late — arriving after younger messages, which exercises
    reordering;
  * crash–restart faults (the SIGKILL cases): per-round Bernoulli crash
    masks wipe each hit node's volatile state and in-flight traffic,
    keeping only its modeled durable state — HardState term/vote, the
    snapshot, and the log prefix up to a per-node ``stable`` index that
    lags last_index by one lockstep round (fsync lag; entries past it are
    LOST). The node stays down for a configurable number of rounds, then
    restarts as a follower with a fresh randomized election timeout and
    re-derives applied state by replaying its durable log from the
    snapshot. See utils/config.py CrashConfig and the durability
    classification table in models/state.py.
  * membership-change faults (the tester's member add/remove cases):
    encoded conf-change proposals — add/remove voter, add learner,
    promote, auto-joint two-delta words — injected at node 0 with
    per-round Bernoulli probability ``member_p``, sampled from an i32
    palette that rides as a RUNTIME operand (one trace serves every
    mix). Leader-side proposal-guard outcomes and applied-config
    transitions are counted in CrashMetrics;
  * targeted crash scheduling: instead of spreading the crash budget
    Bernoulli-uniformly, the scheduler detects the snapshot-install
    window (MsgSnap in flight / leader pre-ack in PR_SNAPSHOT) and the
    membership-sensitive window (joint config / committed-but-unapplied
    conf change) per node-round and concentrates the SAME expected crash
    budget there (engine.snapshot_window_mask / member_window_mask +
    targeted_crash_probs);
  * checkers, evaluated every round as tensor reductions and accumulated
    as violation counters so only a handful of scalars ever cross to the
    host:
      - election safety: at most one leader per (group, term);
      - state-machine safety (KV_HASH): equal applied index => equal
        applied hash, for every member pair;
      - commit monotonicity: no node's commit index ever regresses
        (crash rounds are exempt for the crashed nodes — commit-only
        advances are never fsync'd, so a restart legally regresses it);
      - leader completeness, CONFIG-AWARE: no index the group has ever
        committed may become erasable by an election under the group's
        live (possibly joint) configuration — per half, the non-holders
        must never form a quorum on their own (see
        check_recovery_invariants for the intersection-bar form and the
        config-blind broken variant);
      - log matching across restart: every TRACKED member that can still
        read the tracked set's minimum commit index agrees on its term
        (members outside the live config abstain — a removed voter's
        stale cursor must not pin the probe, and a never-added slot
        would hold it at zero forever);
      - term monotonicity on the persisted HardState: term never moves
        backwards, crash or not (term/vote changes fsync before any
        message reflecting them is sent).

Everything (fault sampling, stepping, checking) lives in one lax.scan —
no host round-trips during a chaos epoch.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from etcd_tpu.models.engine import (
    build_round,
    crash_restart_fleet,
    empty_inbox,
    init_fleet,
    member_window_mask,
    snapshot_window_mask,
    wipe_crashed_traffic,
)
from etcd_tpu.models.blackbox import (
    DEFAULT_WINDOW,
    EventRing,
    VIOLATION_BIT_NAMES,
    blackbox_update,
    forensics_report,
    init_blackbox,
)
from etcd_tpu.models.metrics import (
    CrashMetrics,
    crash_metrics_report,
    zero_crash_metrics,
)
from etcd_tpu.models.state import NodeState
from etcd_tpu.models.telemetry import (
    DEFAULT_BUCKETS,
    flight_record,
    init_telemetry,
    telemetry_report,
    telemetry_update,
)
from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    ENTRY_CONF_CHANGE,
    INT32_MAX,
    Msg,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import (
    CrashConfig,
    MemberChaosConfig,
    RaftConfig,
)


class Violations(struct.PyTreeNode):
    """Safety-violation counters (i32 scalars)."""

    multi_leader: jnp.ndarray     # >1 leader at one (group, term)
    hash_mismatch: jnp.ndarray    # equal applied, different hash
    commit_regress: jnp.ndarray   # commit index moved backwards
    # crash-recovery invariants (checked when crash faults are enabled;
    # stay 0 in the network-only programs, which don't evaluate them)
    lost_commit: jnp.ndarray      # committed index held by < quorum
    log_divergence: jnp.ndarray   # term disagreement at the commit frontier
    term_regress: jnp.ndarray     # persisted HardState term moved backwards


def zero_violations() -> Violations:
    z = jnp.int32(0)
    return Violations(multi_leader=z, hash_mismatch=z, commit_regress=z,
                      lost_commit=z, log_divergence=z, term_regress=z)


class BlackBox(struct.PyTreeNode):
    """Scan-carried forensics plane (harness side of models/blackbox.py):
    the per-group event ring plus the per-group violation bookkeeping
    the on-violation extraction reduces over. ``viol_groups`` is a [C]
    i32 bitmask over VIOLATION_BIT_NAMES (bit order ==
    VIOLATION_KEYS); ``viol_round`` is the round a group FIRST violated
    (-1 = never) — the ring freezes there, aviation-style, so the
    preserved window is the W rounds leading INTO the violation."""

    ring: EventRing
    viol_groups: jnp.ndarray  # [C] i32 violation-kind bitmask
    viol_round: jnp.ndarray   # [C] i32 first-violation round (-1 none)


def empty_blackbox(spec: Spec, state: NodeState,
                   window: int = DEFAULT_WINDOW) -> BlackBox:
    C = state.term.shape[-1]
    return BlackBox(
        ring=init_blackbox(spec, state, window=window),
        viol_groups=jnp.zeros((C,), jnp.int32),
        viol_round=jnp.full((C,), -1, jnp.int32),
    )


def check_invariants(state: NodeState, prev_commit: jnp.ndarray,
                     viol: Violations, exempt=None, with_masks: bool = False):
    """One round's checker pass: pure reductions over [M, C] leaves.

    ``exempt`` ([M, C] bool or None) excludes nodes from the
    commit-monotonicity check — the crash tier passes this round's crash
    mask, because capping the persisted commit at the durable log is a
    legal regression (MustSync never covers commit-only advances).

    ``with_masks`` additionally returns the PER-GROUP [C] bool masks
    (multi_leader, hash_mismatch, commit_regress) the forensics plane
    accumulates — derived from the very same intermediates the counters
    sum, so the counters stay bit-identical with masks on or off."""
    M = state.role.shape[0]
    is_lead = state.role == ROLE_LEADER            # [M, C]
    term = state.term
    # pairwise i<j comparisons over the tiny member axis
    iu, ju = jnp.triu_indices(M, k=1)
    both_lead = is_lead[iu] & is_lead[ju] & (term[iu] == term[ju])
    same_applied = state.applied[iu] == state.applied[ju]
    diff_hash = state.applied_hash[iu] != state.applied_hash[ju]
    hash_mm = same_applied & diff_hash
    regress = state.commit < prev_commit
    if exempt is not None:
        regress = regress & ~exempt
    viol = viol.replace(
        multi_leader=viol.multi_leader + both_lead.sum().astype(jnp.int32),
        hash_mismatch=viol.hash_mismatch + hash_mm.sum().astype(jnp.int32),
        commit_regress=viol.commit_regress + regress.sum().astype(jnp.int32),
    )
    if not with_masks:
        return viol
    return viol, (both_lead.any(axis=0), hash_mm.any(axis=0),
                  regress.any(axis=0))


def refresh_ref_config(state: NodeState, crash: "CrashState") -> "CrashState":
    """Adopt the newest APPLIED configuration as each group's reference
    config for the recovery checkers.

    Conf changes are log entries, so the member with the highest applied
    index holds the newest applied config (equal applied => equal entries
    => equal config, the same argument as the KV_HASH checker). The carry
    is keyed by a config EPOCH (``ref_applied``, the applied index the
    reference was captured at): a crash rewinds the crashed node's
    applied view to its snapshot's ConfState, and a round where every
    up-to-date member is down must NOT regress the checker to a stale
    config — the conf entries are still in the durable logs and will
    re-apply, so the newest-ever applied config stays authoritative
    across the outage.
    """
    best = state.applied.max(axis=0)                       # [C]
    is_best = state.applied == best[None, :]               # [M, C]
    # lowest-id tie-break makes `first` a one-hot selector
    first = is_best & (jnp.cumsum(is_best, axis=0) == 1)

    def pick(mask):  # [M(node), M(id), C] -> the best node's [M(id), C]
        return (first[:, None, :] & mask).any(axis=0)

    tracked = (state.voters | state.voters_out | state.learners
               | state.learners_next)
    adopt = (best >= crash.ref_applied)[None, :]           # [1, C]
    return crash.replace(
        ref_voters=jnp.where(adopt, pick(state.voters), crash.ref_voters),
        ref_voters_out=jnp.where(
            adopt, pick(state.voters_out), crash.ref_voters_out),
        ref_tracked=jnp.where(adopt, pick(tracked), crash.ref_tracked),
        ref_applied=jnp.maximum(crash.ref_applied, best),
    )


def check_recovery_invariants(
    spec: Spec, state: NodeState, crash: "CrashState", viol: Violations,
    config_aware, with_masks: bool = False,
):
    """Config-aware crash-recovery checkers (ISSUE 3 + ISSUE 5), as
    per-round tensor reductions; returns (viol, crash) with the
    watermark / term-baseline / reference-config carries refreshed.

    Leader completeness is evaluated against the group's live — possibly
    joint — configuration (refresh_ref_config), not a static full-member
    majority: a committed index is LOST iff a candidate missing it could
    still win an election, i.e. iff in the incoming half (and, when
    joint, ALSO in the outgoing half — joint elections must win both,
    quorum/joint.go:49-68) the durable non-holders form a majority on
    their own. For an all-voter odd-M config this reduces exactly to the
    old ``holders < M//2 + 1`` bar; for even-sized halves the
    intersection bar is one looser (2 holders of 4 voters already
    intersect every 3-vote quorum), and removed voters simply drop out
    of both halves instead of counting as missing holders.

    ``config_aware`` is a RUNTIME operand: False selects the deliberately
    config-blind variant — the pre-ISSUE-5 static full-member majority
    with every member slot tracked — which MUST fire on a remove-voter
    schedule the config-aware checker accepts (the proof the rework is
    live, mirroring the persist-nothing durability mode).

    ``with_masks`` additionally returns the per-group [C] bool masks
    (lost_commit, log_divergence, term_regress) for the forensics
    plane, derived from the same intermediates the counters sum, so the
    counters stay bit-identical with masks on or off.
    """
    M = spec.M
    crash = refresh_ref_config(state, crash)
    # term monotonicity on the persisted HardState: term/vote fsync
    # before any message reflecting them leaves the node, so nothing —
    # crash included — may move a node's term backwards
    t_reg_mask = state.term < crash.prev_term                    # [M, C]
    t_reg = t_reg_mask.sum().astype(jnp.int32)

    # leader completeness: every index the group has ever committed must
    # stay election-safe under the reference config (last_index covers
    # snapshot holders: last_index >= snap_index always)
    wm = jnp.maximum(crash.watermark, state.commit.max(axis=0))  # [C]
    holders = state.last_index >= wm[None, :]                    # [M, C]

    def electable_without(half):
        """Could a candidate missing wm win this majority half? Yes iff
        the half's non-holders reach its quorum by themselves (a holder
        never grants to a candidate whose log misses wm)."""
        nv = half.sum(axis=0).astype(jnp.int32)                  # [C]
        non = (half & ~holders).sum(axis=0).astype(jnp.int32)    # [C]
        return (nv > 0) & (non >= nv // 2 + 1)

    out_empty = ~crash.ref_voters_out.any(axis=0)                # [C]
    erasable = electable_without(crash.ref_voters) & (
        out_empty | electable_without(crash.ref_voters_out))
    # config-blind variant: static majority of ALL M member slots
    blind = holders.sum(axis=0) < (M // 2 + 1)
    lost_mask = jnp.where(config_aware, erasable, blind) & (wm > 0)
    lost = lost_mask.sum().astype(jnp.int32)

    # log matching across restart, probed at the TRACKED members'
    # committed frontier: all tracked members that can still read the
    # tracked min-commit agree on its term. Untracked members abstain
    # (a removed voter's stale commit must not pin the probe; a
    # never-added slot would hold it at 0 forever); members compacted
    # past it abstain; snapshot-boundary holders answer with snap_term
    # (same rule as ops/log.py term_at).
    tracked = jnp.where(config_aware, crash.ref_tracked,
                        jnp.ones_like(crash.ref_tracked))
    mc = jnp.where(tracked, state.commit, INT32_MAX).min(axis=0)  # [C]
    L = state.log_term.shape[1]
    oh = jnp.arange(L, dtype=jnp.int32)[:, None] == (mc - 1) % L  # [L, C]
    t_ring = (state.log_term * oh[None, :, :]).sum(axis=1)        # [M, C]
    t_mc = jnp.where(mc[None, :] == state.snap_index, state.snap_term, t_ring)
    can_read = tracked & (mc[None, :] >= state.snap_index) & (
        mc[None, :] > 0) & (mc[None, :] < INT32_MAX)
    iu, ju = jnp.triu_indices(M, k=1)
    diverged = (t_mc[iu] != t_mc[ju]) & can_read[iu] & can_read[ju]

    viol = viol.replace(
        term_regress=viol.term_regress + t_reg,
        lost_commit=viol.lost_commit + lost,
        log_divergence=viol.log_divergence
        + diverged.sum().astype(jnp.int32),
    )
    crash = crash.replace(watermark=wm, prev_term=state.term)
    if not with_masks:
        return viol, crash
    return viol, crash, (lost_mask, diverged.any(axis=0),
                         t_reg_mask.any(axis=0))


def member_palette(spec: Spec, mix: str = "standard") -> jnp.ndarray:
    """The conf-change words the membership tier injects, as an i32[P]
    RUNTIME operand of the epoch program (utils/config.py MEMBER_MIXES).

    Words only ever remove/demote members with id >= 2 — the fsync-lag
    crash model needs >= 2 voters (run_chaos's M >= 2 guard), and the
    device path applies committed changes unconditionally (validation is
    the proposer's job, models/confchange.py), so the palette is where
    the voter floor is enforced. Removing a non-member / re-adding a
    member are deliberate no-op/idempotent words: they exercise the
    guard and apply paths without changing the config.

      * "simple":   single-delta add-voter / remove-voter / add-learner
                    (promotion = add-voter on a learner) per id >= 2;
      * "standard": "simple" plus auto-joint two-delta words (add+add,
                    remove+remove, add+remove, learner+learner) with
                    auto_leave set — the V2 "more than one change =>
                    joint" rule, entering and leaving joint configs;
      * "shrink":   remove-voter words only — the schedule the
                    config-blind checker variant must fire on while the
                    config-aware checker accepts it.
    """
    from etcd_tpu.models.confchange import encode

    ids = list(range(2, spec.M))
    if not ids:
        raise ValueError("member chaos needs spec.M >= 3 (ids 0/1 are the "
                         "never-removed voter floor)")
    if mix == "shrink":
        words = [encode([(CC_REMOVE_NODE, i)]) for i in ids]
    else:
        words = []
        for i in ids:
            words += [
                encode([(CC_ADD_NODE, i)]),
                encode([(CC_REMOVE_NODE, i)]),
                encode([(CC_ADD_LEARNER, i)]),
            ]
        if mix == "standard" and len(ids) >= 2:
            a, b = ids[-2], ids[-1]
            words += [
                encode([(CC_ADD_NODE, a), (CC_ADD_NODE, b)]),
                encode([(CC_REMOVE_NODE, a), (CC_REMOVE_NODE, b)]),
                encode([(CC_ADD_NODE, a), (CC_REMOVE_NODE, b)]),
                encode([(CC_ADD_LEARNER, a), (CC_ADD_LEARNER, b)]),
            ]
    return jnp.asarray(words, jnp.int32)


def targeted_crash_probs(crash_p, snap_win, mem_win, snap_boost,
                         member_boost) -> jnp.ndarray:
    """Per-lane crash probabilities concentrating the SAME expected crash
    budget (crash_p * lanes) on the fault windows.

    Window lanes get ``crash_p * boost`` (snapshot window wins a lane in
    both); the remainder of the budget spreads uniformly over the
    out-of-window lanes. If the boosted windows alone would overspend the
    budget, both tier probabilities scale down so the round's expected
    crash count stays exactly ``crash_p * lanes`` — the equal-budget
    property the targeting acceptance compares against Bernoulli
    scheduling (boosts = 1 reproduce it: every lane gets crash_p).
    All inputs are runtime operands/tensors; shapes [M, C] bool.
    """
    lanes = snap_win.size
    budget = crash_p * lanes
    mem_only = mem_win & ~snap_win
    w_s = snap_win.sum().astype(jnp.float32)
    w_m = mem_only.sum().astype(jnp.float32)
    p_s = jnp.minimum(crash_p * snap_boost, 1.0)
    p_m = jnp.minimum(crash_p * member_boost, 1.0)
    spend = p_s * w_s + p_m * w_m
    scale = jnp.where(spend > budget, budget / jnp.maximum(spend, 1e-9), 1.0)
    p_s = p_s * scale
    p_m = p_m * scale
    rest = jnp.maximum(lanes - w_s - w_m, 1.0)
    p_base = jnp.clip((budget - p_s * w_s - p_m * w_m) / rest, 0.0, 1.0)
    return jnp.where(snap_win, p_s, jnp.where(mem_only, p_m, p_base))


class CrashState(struct.PyTreeNode):
    """Scan-carried crash/recovery bookkeeping (all leaves small next to
    the log).

    ``stable`` is each node's durable log floor: its last_index as of the
    top of the PREVIOUS round. The one-round lag is the modeled fsync
    latency, and it is exactly safe: an acknowledgement emitted in round
    r covers entries appended by end of round r and delivers in round
    r+1, so by the time any peer has observed the ack (top of round r+2)
    those entries are at or below the crash floor — and a crash at round
    r+1 wipes the still-in-flight ack together with the entries.

    The ``ref_*`` leaves carry each group's reference configuration for
    the config-aware recovery checkers: the newest APPLIED config's
    voter / outgoing-voter / tracked-member masks and the applied index
    ("config epoch") they were captured at — kept across crash rewinds
    by refresh_ref_config so a mass outage cannot regress the checker to
    a stale membership view.
    """

    stable: jnp.ndarray     # [M, C] i32 durable log floor
    down: jnp.ndarray       # [M, C] i32 rounds of down-time left (0 = up)
    watermark: jnp.ndarray  # [C] i32 running max committed index
    prev_term: jnp.ndarray  # [M, C] i32 term-monotonicity baseline
    ref_voters: jnp.ndarray      # [M, C] bool reference incoming voters
    ref_voters_out: jnp.ndarray  # [M, C] bool reference outgoing voters
    ref_tracked: jnp.ndarray     # [M, C] bool reference tracked members
    ref_applied: jnp.ndarray     # [C] i32 config epoch (applied index)
    metrics: CrashMetrics


def empty_crash_state(state: NodeState) -> CrashState:
    f2 = jnp.zeros_like(state.last_index, dtype=jnp.bool_)
    base = CrashState(
        stable=state.last_index,
        down=jnp.zeros_like(state.last_index),
        watermark=state.commit.max(axis=0),
        prev_term=state.term,
        ref_voters=f2, ref_voters_out=f2, ref_tracked=f2,
        # epoch -1: the first refresh always adopts the boot config
        ref_applied=jnp.full(state.term.shape[-1:], -1, jnp.int32),
        metrics=zero_crash_metrics(),
    )
    return refresh_ref_config(state, base)


def _bc(spec: Spec, mask, leaf):
    """Broadcast a [from, K*to, C] slot mask to a leaf's shape (ent leaves
    repeat the middle axis per entry — the engine's FLAT storage form)."""
    if leaf.shape[1] != mask.shape[1]:
        return jnp.repeat(mask, spec.E, axis=1)
    return mask


# --------------------------------------------------------- sparse held
# The original held buffer was a SECOND FULL INBOX (17 x [M, K*M, C]
# leaves): at C=1M its while-loop double-buffering alone overflowed HBM
# (measured 17.01G vs the 15.75G budget), capping fault epochs at 524k
# groups. But delay faults are SPARSE — at delay_p=0.05 a sender row
# (K*M = 10 slots) holds ~0.1-0.5 delayed messages a round — so the
# buffer now packs each row's delayed messages into HELD_SLOTS compact
# slots (index + fields), ~3x smaller than the dense plane and with
# tiny [M, H, S, C] one-hot temporaries instead of full-inbox passes.
# Overflow past HELD_SLOTS per row per round DROPS the extra messages —
# legal by the transport contract (etcdserver/raft.go:107-110), and at
# the chaos mixes' traffic (<=2 live slots per row in steady state)
# P(>3 delayed in one row) is negligible.

HELD_SLOTS = 3


class HeldSparse(struct.PyTreeNode):
    """Per-sender-row packed delayed messages: `idx[m, h, c]` is the
    flat slot (0..K*M-1) the h-th held message came from (-1 = empty);
    `msgs` leaves are [M, H(,E packed into H*E), C] in the wire dtype."""

    idx: jnp.ndarray
    msgs: Msg


def empty_held(spec: Spec, C: int, wire_int16: bool) -> HeldSparse:
    # eval_shape: only leaf shapes/dtypes are needed — materializing a
    # real dense inbox here would transiently allocate the very
    # multi-GB buffer this sparse form exists to avoid
    inbox_sds = jax.eval_shape(
        lambda: empty_inbox(spec, C, wire_int16=wire_int16))
    H = HELD_SLOTS

    def shrink(x):
        S = spec.K * spec.M
        e = x.shape[1] // S  # 1, or E for ent leaves
        return jnp.zeros((spec.M, H * e, C), x.dtype)

    return HeldSparse(
        idx=jnp.full((spec.M, H, C), -1, jnp.int32),
        msgs=jax.tree.map(shrink, inbox_sds),
    )


def _pack_held(spec: Spec, out: Msg, dm) -> HeldSparse:
    """Compact this round's delayed slots (mask dm [M, S, C]) into the
    sparse form: per sender row, the h-th delayed slot lands in held
    slot h; extras past HELD_SLOTS drop."""
    S = spec.K * spec.M
    H = HELD_SLOTS
    rank = jnp.cumsum(dm.astype(jnp.int32), axis=1) - 1        # [M, S, C]
    sel = (
        rank[:, None, :, :] == jnp.arange(H, dtype=jnp.int32)[None, :, None, None]
    ) & dm[:, None]                                            # [M, H, S, C]
    taken = sel.any(axis=2)                                    # [M, H, C]
    slot_ids = jnp.arange(S, dtype=jnp.int32)[None, None, :, None]
    idx = jnp.where(taken, (sel * slot_ids).sum(axis=2), -1)

    def pack(x):
        e = x.shape[1] // S
        xr = x.reshape(spec.M, S, e, x.shape[-1])
        f = (sel[:, :, :, None, :] * xr[:, None]).sum(axis=2)  # [M, H, e, C]
        return f.reshape(spec.M, H * e, x.shape[-1]).astype(x.dtype)

    return HeldSparse(idx=idx, msgs=jax.tree.map(pack, out))


def _held_wins(spec: Spec, held: HeldSparse, fresh: Msg) -> Msg:
    """Scatter the sparse held messages back over fresh traffic: a held
    message wins a slot collision (the fresh one drops — legal per the
    transport contract, etcdserver/raft.go:107-110)."""
    S = spec.K * spec.M
    H = HELD_SLOTS
    sel = (
        held.idx[:, :, None, :]
        == jnp.arange(S, dtype=jnp.int32)[None, None, :, None]
    ) & (held.idx >= 0)[:, :, None, :]                         # [M, H, S, C]
    live = sel.any(axis=1)                                     # [M, S, C]

    def un(xh, f):
        e = f.shape[1] // S
        xr = xh.reshape(spec.M, H, e, xh.shape[-1])
        dense = (sel[:, :, :, None, :] * xr[:, :, None]).sum(axis=1)
        dense = dense.reshape(spec.M, S * e, xh.shape[-1]).astype(f.dtype)
        return jnp.where(_bc(spec, live, f), dense, f)

    return jax.tree.map(un, held.msgs, fresh)


def _merge_delayed(spec: Spec, out: Msg, held: HeldSparse,
                   delay_mask) -> tuple[Msg, HeldSparse]:
    """Split this round's traffic by the delay mask and merge in messages
    held from the previous round. Message leaves are in the engine's FLAT
    storage form [from, K*to(*E), C]; `delay_mask` is [from, K*to, C]."""
    new_held = _pack_held(spec, out, delay_mask)
    fresh = out.replace(type=jnp.where(delay_mask, 0, out.type))
    return _held_wins(spec, held, fresh), new_held


def build_chaos_epoch(
    cfg: RaftConfig,
    spec: Spec,
    rounds: int,
    faultless: bool = False,
    partition_period: int = 25,
    tick: bool = True,
    with_delay: bool = True,
    with_crash: bool = False,
    with_member: bool = False,
    with_telemetry: bool = False,
    with_blackbox: bool = False,
):
    """One jitted chaos epoch: `rounds` lockstep rounds of faulted traffic
    with per-round invariant checks.

    Returns fn(state, inbox, held, crash, key, prop_len, prop_data, viol,
    tele, bb, drop_p, delay_p, partition_p, crash_p, down_rounds,
    keep_log, config_aware, member_p, palette, snap_boost, member_boost)
    -> (state, inbox, held, crash, key, viol, tele, bb, commits_delta).
    The fault
    probabilities are RUNTIME operands, not closure constants — one
    traced program serves every fault mix (a full trace costs ~40s of
    single-core time; the suite's chaos configurations used to pay it
    once per mix). The crash knobs ride the same way: ``crash_p``
    (per-node per-round kill probability), ``down_rounds`` (outage
    length) and ``keep_log`` (False = the broken persist-nothing
    durability model) are operands, so the honest and deliberately-broken
    models share one trace — as do ``config_aware`` (False = the broken
    config-blind checker variant), the membership palette/rate and the
    targeting boosts, so one trace serves every membership mix and
    targeting intensity too. The regression
    baseline (prev_commit) starts at the entry state's own commit —
    nothing moves between epochs, so passing it across the boundary
    would merely alias a leaf of the donated state.

    Partitions re-sample every `partition_period` rounds: each group is
    partitioned with probability partition_p into two random sides (links
    across sides drop entirely); other faults stack on top. `faultless`
    selects the structurally-reduced heal program (no sampling, no held
    bookkeeping, no membership injection), which ignores the probability
    operands.

    `with_delay=False` removes the delay/reorder machinery AT TRACE TIME:
    no Bernoulli delay draws, no held-buffer merge, and no held pytree
    in the scan carry. The held buffer is SPARSE (HeldSparse: HELD_SLOTS
    packed messages per sender row) — the round-4 dense form was a full
    second inbox whose double-buffering overflowed HBM at the 1M-group
    configuration (measured 17.01G vs 15.75G), capping delay coverage
    at 524k groups. Callers pass held=None and get None back.

    `with_crash=False` removes the crash–restart machinery AT TRACE TIME
    the same way (no crash sampling, no targeted scheduler). Callers pass
    crash=None and get None back — UNLESS `with_member` is on, which
    keeps the CrashState carry (reference config, watermark, metrics)
    and the recovery checkers alive without any crash sampling; the
    legacy network-fault programs (both flags off) are structurally
    unchanged. With crashes on, the heal program still runs down-timers
    to completion and keeps checking the recovery invariants; only fault
    epochs sample new crashes.

    `with_telemetry` rides a FleetTelemetry carry (models/telemetry.py)
    through every round — per-group lanes + latency histograms updated
    by the same read-only reductions as the checkers, so the state
    trajectory with telemetry on is BIT-IDENTICAL to the trajectory
    with it off (tests/test_telemetry.py proves it against this very
    program). Off, callers pass tele=None and get None back, and the
    traced program is structurally unchanged. The restart/down masks of
    the crash machinery feed the heal-latency histogram; without
    crashes those reduce to carry passthrough at trace time.

    `with_blackbox` rides a BlackBox carry (per-group EventRing +
    violation bookkeeping, models/blackbox.py) the same way: event
    words are computed from the same post-wipe pre/post views and the
    same wire tensors the round produced, the per-round checker passes
    additionally surface their PER-GROUP masks (derived from the exact
    intermediates the counters sum, so the counters stay bit-identical),
    and a group's ring FREEZES at its first violation — the preserved
    window is the W rounds leading into the failure, which is what a
    post-mortem needs. Off, callers pass bb=None and get None back.

    `with_member` adds the membership-change fault class to fault epochs:
    node 0's per-round proposal becomes an encoded conf-change word with
    probability ``member_p``, sampled from the i32[P] ``palette`` operand
    (member_palette), with guard-outcome / applied-transition counters
    accumulated in CrashMetrics. Fault epochs with crashes also route the
    crash budget through targeted_crash_probs over the snapshot-install
    and membership-sensitive windows (boosts of 1 = plain Bernoulli).
    """
    if cfg.packed_state or cfg.compact_wire:
        # the fault machinery addresses the unpacked fleet and the dense
        # [from, K, to] wire directly (crash wipes, held-buffer merges,
        # snapshot-window masks); the diet forms are for the bench/scan
        # paths, the epoch program keeps its memory headroom via donation
        raise ValueError(
            "chaos epochs need the unpacked fleet and the dense wire; "
            "run with packed_state=False and compact_wire=False")
    round_fn = build_round(cfg, spec)
    M = spec.M
    # recovery bookkeeping (CrashState carry + config-aware checkers) is
    # needed by either fault class: crashes lose state, membership
    # changes move the quorum the checkers must count against
    with_recovery = with_crash or with_member

    def epoch(state, inbox, held, crash, key, prop_len, prop_data, viol,
              tele, bb, drop_p, delay_p, partition_p, crash_p, down_rounds,
              keep_log, config_aware, member_p, palette, snap_boost,
              member_boost):
        prev_commit = state.commit
        C = state.term.shape[-1]
        zp = jnp.zeros((M, spec.E, C), jnp.int32)
        z2 = jnp.zeros((M, C), jnp.int32)
        no = jnp.zeros((M, C), jnp.bool_)
        do_tick = jnp.full((M, C), tick, jnp.bool_)
        commit0 = state.commit.sum()
        key, pkey = jax.random.split(key)

        def pre_round(state, inbox, held, crash, key, sample):
            """Top-of-round crash bookkeeping: run down-timers, optionally
            kill fresh nodes (volatile-state wipe to the durable floor),
            silence all down hosts' in-flight traffic, refresh the floor.
            Returns (..., crashed_now, alive, restarted_mask); no-op when
            crashes are compiled out (a member-only program passes its
            CrashState carry through untouched — only post_checks updates
            it)."""
            if not with_crash:
                return state, inbox, held, crash, key, None, None, None
            was_down = crash.down > 0
            down = jnp.maximum(crash.down - 1, 0)
            restarted_mask = was_down & (down == 0)      # [M, C]
            restarted = restarted_mask.sum().astype(jnp.int32)
            if sample:
                key, ck, tk = jax.random.split(key, 3)
                # targeted scheduling: concentrate the SAME expected
                # crash budget on the snapshot-install and membership-
                # sensitive windows (boosts of 1 reproduce the uniform
                # Bernoulli schedule); windows/crashes are counted at
                # sampling instants only, so heal rounds don't dilute
                # the hit-rate comparison
                snap_win = snapshot_window_mask(spec, state, inbox)
                mem_win = member_window_mask(spec, state)
                p_lane = targeted_crash_probs(
                    crash_p, snap_win, mem_win, snap_boost, member_boost)
                hit = jax.random.bernoulli(ck, p_lane) & (down == 0)
                # restart draws a fresh randomized election timeout in
                # [T, 2T), same distribution as boot (models/state.py)
                rand_to = cfg.election_tick + jax.random.randint(
                    tk, (M, C), 0, cfg.election_tick, dtype=jnp.int32)
                state, lost = crash_restart_fleet(
                    spec, state, hit, crash.stable, rand_to,
                    keep_log=keep_log)
                down = jnp.where(hit, down_rounds, down)
                mw = crash.metrics
                crash = crash.replace(metrics=mw.replace(
                    snap_window_lanes=mw.snap_window_lanes
                    + snap_win.sum().astype(jnp.int32),
                    snap_window_crashes=mw.snap_window_crashes
                    + (hit & snap_win).sum().astype(jnp.int32),
                    member_window_lanes=mw.member_window_lanes
                    + mem_win.sum().astype(jnp.int32),
                    member_window_crashes=mw.member_window_crashes
                    + (hit & mem_win).sum().astype(jnp.int32),
                ))
            else:
                hit = jnp.zeros((M, C), jnp.bool_)
                lost = jnp.int32(0)
            # a down host's in-flight traffic is dead every round it is
            # down: the FROM wipe makes fsync-lag entry loss safe (the
            # unsynced entries' acks die with it), the TO wipe models its
            # dead kernel buffers, and re-wiping while down also kills
            # held-buffer messages that resurface mid-outage
            inbox = wipe_crashed_traffic(spec, inbox, down > 0)
            if sample and with_delay:
                # messages a crashed sender emitted in its lost round may
                # also sit delayed in the held buffer — same pre-fsync
                # sends, same wipe
                held = held.replace(
                    idx=jnp.where(hit[:, None, :], -1, held.idx))
            m = crash.metrics
            crash = crash.replace(
                stable=state.last_index,
                down=down,
                metrics=m.replace(
                    crashes_injected=m.crashes_injected
                    + hit.sum().astype(jnp.int32),
                    entries_lost_fsync=m.entries_lost_fsync + lost,
                    restarts_completed=m.restarts_completed + restarted,
                ),
            )
            return (state, inbox, held, crash, key, hit, down == 0,
                    restarted_mask)

        def mask_down(keep, pl, dt, alive):
            """Down nodes neither exchange traffic, tick, nor propose."""
            if not with_crash:
                return keep, pl, dt
            return (keep & alive[:, None, :] & alive[None, :, :],
                    jnp.where(alive, pl, 0), dt & alive)

        def inject_member(state, crash, key, alive):
            """Swap node 0's proposal payload for an encoded conf-change
            word with probability member_p per (round, group), sampled
            from the palette operand, and record the leader-side guard
            outcome (stepLeader refuses a cc while one is pending in
            (applied, pci] or the config is already joint) against the
            group's CURRENT leader — exact when node 0 leads, a one-round
            -skewed estimate when the proposal forwards. A draw landing
            while node 0 is down is discarded BEFORE the counters:
            mask_down zeroes its prop_len, so nothing enters the system
            and counting it would overstate injected proposals."""
            key, kc, kw = jax.random.split(key, 3)
            do_cc = jax.random.bernoulli(kc, member_p, (C,))
            if alive is not None:
                do_cc = do_cc & alive[0]
            P = palette.shape[0]
            pi = jax.random.randint(kw, (C,), 0, P, dtype=jnp.int32)
            sel = pi[None, :] == jnp.arange(P, dtype=jnp.int32)[:, None]
            word = (sel * palette[:, None]).sum(axis=0).astype(jnp.int32)
            pd = prop_data.at[0, 0].set(
                jnp.where(do_cc, word, prop_data[0, 0]))
            pt = zp.at[0, 0].set(
                jnp.where(do_cc, ENTRY_CONF_CHANGE, 0))
            is_lead = state.role == ROLE_LEADER                     # [M, C]
            guard = (state.pending_conf_index > state.applied) \
                | state.voters_out.any(axis=1)
            has_lead = is_lead.any(axis=0)
            refuse = (is_lead & guard).any(axis=0)
            m = crash.metrics
            crash = crash.replace(metrics=m.replace(
                member_changes_proposed=m.member_changes_proposed
                + do_cc.sum().astype(jnp.int32),
                cc_guard_refusals=m.cc_guard_refusals
                + (do_cc & has_lead & refuse).sum().astype(jnp.int32),
                cc_guard_admits=m.cc_guard_admits
                + (do_cc & has_lead & ~refuse).sum().astype(jnp.int32),
            ))
            return key, pd, pt, crash

        def post_checks(pre, state, prev_commit, crash, viol, hit):
            """Per-round checkers + applied-config transition counting.
            ``pre`` is the state AFTER pre_round (so crash rewinds never
            count as transitions) and BEFORE the round step. With the
            forensics plane on, also returns the per-group violation
            bitmask gmask [C] i32 (bit order == VIOLATION_KEYS)."""
            gmask = None
            if with_blackbox:
                viol, masks = check_invariants(state, prev_commit, viol,
                                               exempt=hit, with_masks=True)
                C = state.term.shape[-1]
                gmask = jnp.zeros((C,), jnp.int32)
                for bit, m in enumerate(masks):
                    gmask = gmask | jnp.where(m, 1 << bit, 0)
            else:
                viol = check_invariants(state, prev_commit, viol,
                                        exempt=hit)
            if with_recovery:
                ch = (
                    (pre.voters != state.voters)
                    | (pre.voters_out != state.voters_out)
                    | (pre.learners != state.learners)
                    | (pre.learners_next != state.learners_next)
                ).any(axis=1)                                       # [M, C]
                was_j = pre.voters_out.any(axis=1)
                now_j = state.voters_out.any(axis=1)
                m = crash.metrics
                crash = crash.replace(metrics=m.replace(
                    conf_changes_applied=m.conf_changes_applied
                    + ch.sum().astype(jnp.int32),
                    joint_entered=m.joint_entered
                    + (~was_j & now_j).sum().astype(jnp.int32),
                    joint_left=m.joint_left
                    + (was_j & ~now_j).sum().astype(jnp.int32),
                ))
                if with_blackbox:
                    viol, crash, rmasks = check_recovery_invariants(
                        spec, state, crash, viol, config_aware,
                        with_masks=True)
                    for bit, rm in enumerate(rmasks, start=3):
                        gmask = gmask | jnp.where(rm, 1 << bit, 0)
                else:
                    viol, crash = check_recovery_invariants(
                        spec, state, crash, viol, config_aware)
            return crash, viol, gmask

        def tele_step(tele, pre, state, alive, restarted):
            """Telemetry pass (read-only; compiled out when off). ``pre``
            is the post-wipe pre-round state, so a crash rewind never
            reads as a role/applied transition."""
            if not with_telemetry:
                return tele
            return telemetry_update(
                spec, tele, pre, state,
                restarted=restarted,
                down=None if alive is None else ~alive)

        def bb_step(bb, pre, state, consumed, out, hit, alive, rst, gmask):
            """Forensics pass (read-only; compiled out when off):
            records this round's event words — freezing groups that have
            already violated — then folds the round's per-group checker
            masks into the first-violation bookkeeping. Ordering means a
            group's OWN violation round is still recorded (the write
            gate uses the pre-round viol_round), and its ring holds the
            W rounds ending at that violation."""
            if not with_blackbox:
                return bb
            r = bb.ring.round
            ring = blackbox_update(
                spec, bb.ring, pre, state, inbox=consumed, outbox=out,
                crashed=hit, restarted=rst,
                down=None if alive is None else ~alive,
                write_mask=bb.viol_round < 0)
            fresh = (bb.viol_round < 0) & (gmask != 0)
            return BlackBox(
                ring=ring,
                viol_groups=bb.viol_groups | gmask,
                viol_round=jnp.where(fresh, r, bb.viol_round))

        if faultless:
            # heal program: no fault sampling, no delay bookkeeping. Drain
            # whatever the previous chaos epoch still held by merging it
            # into the entry inbox once (held wins a slot collision, as in
            # _merge_delayed), then run bare rounds with per-round checks.
            if with_delay:
                inbox = _held_wins(spec, held, inbox)
                held = held.replace(
                    idx=jnp.full_like(held.idx, -1),
                    msgs=jax.tree.map(jnp.zeros_like, held.msgs),
                )
            keep_all = jnp.ones((M, M, C), jnp.bool_)

            def heal_body(carry, r):
                state, inbox, crash, viol, tele, bb, prev_commit = carry
                state, inbox, _, crash, _, hit, alive, rst = pre_round(
                    state, inbox, None, crash, None, False)
                pre = state
                keep, pl, dt = mask_down(keep_all, prop_len, do_tick, alive)
                state, out = round_fn(
                    state, inbox, pl, prop_data, zp, z2, no, dt, keep
                )
                crash, viol, gmask = post_checks(pre, state, prev_commit,
                                                 crash, viol, hit)
                tele = tele_step(tele, pre, state, alive, rst)
                bb = bb_step(bb, pre, state, inbox, out, hit, alive, rst,
                             gmask)
                return (state, out, crash, viol, tele, bb,
                        state.commit), None

            (state, inbox, crash, viol, tele, bb, prev_commit), _ = \
                jax.lax.scan(
                    heal_body,
                    (state, inbox, crash, viol, tele, bb, prev_commit),
                    jnp.arange(rounds, dtype=jnp.int32),
                )
            return (state, inbox, held, crash, key, viol, tele, bb,
                    state.commit.sum() - commit0)

        def sample_keep(key, r):
            key, kd, kl = jax.random.split(key, 3)
            # rolling partition: drawn from the epoch-stable pkey folded
            # with the period index, so the cut holds for a whole period
            # and re-rolls at the next one
            period = r // partition_period
            kp = jax.random.fold_in(pkey, period)
            side = jax.random.bernoulli(kp, 0.5, (M, C))
            partitioned = jax.random.bernoulli(
                jax.random.fold_in(kp, 1), partition_p, (C,)
            )
            same_side = side[:, None, :] == side[None, :, :]  # [M, M, C]
            keep_part = same_side | ~partitioned[None, None, :]
            keep_drop = jax.random.bernoulli(kd, 1.0 - drop_p, (M, M, C))
            return key, kl, keep_part & keep_drop

        if with_delay:
            def body(carry, r):
                state, inbox, held, crash, key, viol, tele, bb, \
                    prev_commit = carry
                state, inbox, held, crash, key, hit, alive, rst = pre_round(
                    state, inbox, held, crash, key, True)
                pre = state
                if with_member:
                    key, pd, pt, crash = inject_member(state, crash, key,
                                                       alive)
                else:
                    pd, pt = prop_data, zp
                key, kl, keep = sample_keep(key, r)
                keep, pl, dt = mask_down(keep, prop_len, do_tick, alive)
                state, out = round_fn(
                    state, inbox, pl, pd, pt, z2, no, dt, keep
                )
                delay = jax.random.bernoulli(
                    kl, delay_p, (M, spec.K * M, C)
                ) & (out.type != 0)
                nxt, held2 = _merge_delayed(spec, out, held, delay)
                crash, viol, gmask = post_checks(pre, state, prev_commit,
                                                 crash, viol, hit)
                tele = tele_step(tele, pre, state, alive, rst)
                # `out` (pre-delay-split) is the honest send side; the
                # wiped `inbox` is what this round actually consumed
                bb = bb_step(bb, pre, state, inbox, out, hit, alive, rst,
                             gmask)
                return (state, nxt, held2, crash, key, viol, tele, bb,
                        state.commit), None

            (state, inbox, held, crash, key, viol, tele, bb,
             prev_commit), _ = jax.lax.scan(
                body,
                (state, inbox, held, crash, key, viol, tele, bb,
                 prev_commit),
                jnp.arange(rounds, dtype=jnp.int32),
            )
        else:
            def body(carry, r):
                state, inbox, crash, key, viol, tele, bb, prev_commit = \
                    carry
                state, inbox, _, crash, key, hit, alive, rst = pre_round(
                    state, inbox, None, crash, key, True)
                pre = state
                if with_member:
                    key, pd, pt, crash = inject_member(state, crash, key,
                                                       alive)
                else:
                    pd, pt = prop_data, zp
                key, _, keep = sample_keep(key, r)
                keep, pl, dt = mask_down(keep, prop_len, do_tick, alive)
                state, out = round_fn(
                    state, inbox, pl, pd, pt, z2, no, dt, keep
                )
                crash, viol, gmask = post_checks(pre, state, prev_commit,
                                                 crash, viol, hit)
                tele = tele_step(tele, pre, state, alive, rst)
                bb = bb_step(bb, pre, state, inbox, out, hit, alive, rst,
                             gmask)
                return (state, out, crash, key, viol, tele, bb,
                        state.commit), None

            (state, inbox, crash, key, viol, tele, bb, prev_commit), _ = \
                jax.lax.scan(
                    body, (state, inbox, crash, key, viol, tele, bb,
                           prev_commit),
                    jnp.arange(rounds, dtype=jnp.int32),
                )
        return state, inbox, held, crash, key, viol, tele, bb, \
            state.commit.sum() - commit0

    return epoch


def epoch_donate_argnums(with_delay: bool, with_telemetry: bool,
                         with_blackbox: bool, backend: str) -> tuple[int, ...]:
    """The epoch program's donation set, as a pure function of the
    program structure and backend — the single source of truth shared by
    ``_epoch_program`` and the donation auditor
    (etcd_tpu/analysis/audit.py), so the audited contract can never
    drift from the executed one.

    Donation of the fleet-sized carries (state/inbox/held) is
    accelerator-only: large-C runs that compile fine otherwise die at
    runtime allocation from double-buffering, while host runs don't need
    the memory and keep maximum runtime portability. Donating on CPU was
    TRIED (round 6, with the engine/mesh donation work) and REVERTED:
    empty_crash_state aliases state leaves by reference
    (stable=state.last_index, prev_term=state.term), and the XLA CPU
    runtime rejects a buffer that is both donated (inside state, arg 0)
    and passed live (inside CrashState, arg 3) in one Execute —
    `f(donate(a), a)` — which the member-tier heal handoff hits. The
    TPU runtime tolerates the alias (the 262k–1M chaos evidence runs all
    donated); donation safety for external callers is covered by
    tests/test_donation.py against the engine/mesh builders."""
    if backend == "cpu":
        return ()
    # held (arg 2) is None (no buffers) when the delay machinery is
    # compiled out — donating it is at best a no-op and has crashed
    # the tunneled TPU worker at fleet scale. CrashState (arg 3) is
    # a few [M, C] planes — not worth the same None-donation hazard.
    donate = (0, 1, 2) if with_delay else (0, 1)
    if with_telemetry:
        # the telemetry carry (arg 8) holds fleet-scaled leaves
        # (birth_ring [L, C], cand_since/heal_since [M, C]) and is
        # exclusively threaded — the pre-call pytree is dead once
        # the epoch returns (flight_record reads the returned one),
        # so it joins the anti-double-buffering list. Only when the
        # plane is on: tele=None is the same None-donation hazard
        # as held.
        donate = donate + (8,)
    if with_blackbox:
        # same story for the black-box carry (arg 9): the ring leaf
        # is [W, M, C] — fleet-scaled — and exclusively threaded;
        # gate on the plane being on to avoid the None-donation
        # hazard above.
        donate = donate + (9,)
    return donate


@functools.lru_cache(maxsize=32)
def _epoch_program(cfg: RaftConfig, spec: Spec, rounds: int,
                   faultless: bool, with_delay: bool = True,
                   with_crash: bool = False, with_member: bool = False,
                   with_telemetry: bool = False,
                   with_blackbox: bool = False):
    """One jitted epoch program per (cfg, spec, rounds, structure),
    shared across every run_chaos call and fault mix (probabilities are
    operands). The donation set is epoch_donate_argnums — see its
    docstring for the accelerator-only rationale and the CrashState
    alias hazard."""
    donate = epoch_donate_argnums(with_delay, with_telemetry,
                                  with_blackbox, jax.default_backend())
    return jax.jit(
        build_chaos_epoch(cfg, spec, rounds, faultless=faultless,
                          with_delay=with_delay, with_crash=with_crash,
                          with_member=with_member,
                          with_telemetry=with_telemetry,
                          with_blackbox=with_blackbox),
        donate_argnums=donate,
    )


# lint: allow-def(host-sync) -- the host driver: epoch orchestration + report path, outside the traced epoch
def run_chaos(
    spec: Spec,
    cfg: RaftConfig,
    C: int,
    rounds: int = 200,
    epoch_len: int = 50,
    heal_len: int = 25,
    seed: int = 0,
    drop_p: float = 0.02,
    delay_p: float = 0.05,
    partition_p: float = 0.1,
    crash_p: float = 0.0,
    crash: CrashConfig | None = None,
    member_p: float = 0.0,
    member: MemberChaosConfig | None = None,
    config_aware: bool = True,
    propose: bool = True,
    sync_dispatch: bool = False,
    telemetry: bool = False,
    telemetry_buckets: int = DEFAULT_BUCKETS,
    telemetry_every: int = 1,
    blackbox: bool = False,
    blackbox_window: int = DEFAULT_WINDOW,
    blackbox_k: int = 4,
) -> dict:
    """The tester's round loop (tester/cluster_run.go): alternate fault
    epochs and heal epochs, then verify recovery — every group ends with
    a leader and fresh commits. Returns the violation counts + liveness
    stats; raises nothing (the caller asserts).

    ``crash_p`` > 0 enables crash–restart faults (per-node per-round kill
    probability during fault epochs) with the durability model described
    by ``crash`` (default CrashConfig: 3-round outages, fsync-lag entry
    loss); crash_p=0 compiles the whole crash machinery out.

    ``member_p`` > 0 enables membership-change faults: node 0's proposal
    becomes an encoded conf-change word with this probability per
    (round, group) during fault epochs, drawn from the palette named by
    ``member.mix`` (member_palette); ``member.initial_voters`` boots each
    group with a partial voter set so adds have room. The crash boosts in
    ``member`` route the crash budget through the targeted scheduler
    (snapshot-install / membership windows). ``config_aware=False``
    selects the deliberately-broken config-blind recovery checkers (a
    runtime operand — it shares the traced programs with the honest
    mode, like the persist-nothing durability knob).

    ``telemetry=True`` rides the FleetTelemetry plane through every
    epoch and turns the run into a FLIGHT RECORDER: the report gains a
    ``timeline`` array with one row per epoch (cumulative latency
    histograms + per-group lane totals + violation/crash counters at
    that epoch boundary — telemetry.flight_record) and a ``telemetry``
    summary with p50/p99 latencies, so a failing soak is diagnosable
    post-hoc epoch by epoch instead of from one end-state blob. State
    trajectories are bit-identical with telemetry on or off.
    ``telemetry_every=N`` decimates the flight recorder to every Nth
    epoch boundary (plus the final row) so multi-hour soaks don't grow
    the timeline without bound.

    ``blackbox=True`` rides the EventRing plane (models/blackbox.py)
    through every epoch: each group keeps a [W, M] ring of bit-packed
    per-round event words that FREEZES at that group's first violation,
    so the preserved window ends at the offending round. After the run
    the first ``blackbox_k`` violating group ids are reduced ON DEVICE
    and only those groups' rings cross PCIe ([W, M, k], never
    [W, M, C]); the report gains a ``forensics`` section with decoded
    per-round per-member timelines (blackbox.forensics_report). State
    trajectories are bit-identical with the ring on or off.
    """
    with_crash = crash_p > 0
    with_member = member_p > 0
    if (with_crash or with_member) and spec.M < 2:
        # a singleton commits its own append in the same round, before
        # the modeled fsync completes — the one shape where losing the
        # unsynced suffix can erase a committed entry without any
        # observable ack to wipe
        raise ValueError("crash faults require M >= 2 (fsync-lag model)")
    if with_member and not propose:
        # membership faults ride node 0's proposal stream; without it
        # the injection would only ever increment counters
        raise ValueError("membership chaos requires propose=True")
    if with_member and cfg.wire_int16:
        # conf-change words use bits 16-20 (confchange.py layout) and
        # ride MsgProp/MsgApp ent_data across the wire — the int16 wire
        # silently truncates them (the 81d0b1e bug class, this time by
        # construction rather than by accident)
        raise ValueError(
            "membership chaos words exceed the int16 wire (conf-change "
            "bits 16-20); run with wire_int16=False")
    crash_cfg = (crash or CrashConfig()) if with_crash else None
    # the member config also carries the crash-boost knobs, which apply
    # to pure crash runs (snapshot-window targeting needs no membership
    # faults); the palette/injection side is gated on member_p > 0
    member_cfg = member or MemberChaosConfig()
    iv = member_cfg.initial_voters
    if iv > spec.M:
        # would silently collapse to the all-voters boot, leaving the
        # add-voter/add-learner palette words no free slots
        raise ValueError(
            f"initial_voters={iv} exceeds the member count M={spec.M}")
    voters = None if iv == 0 else jnp.arange(spec.M, dtype=jnp.int32) < iv
    state = init_fleet(spec, C, voters=voters,
                       election_tick=cfg.election_tick, seed=seed)
    inbox = empty_inbox(spec, C, wire_int16=cfg.wire_int16)
    # delay/reorder faults carry a SPARSE held buffer (HELD_SLOTS packed
    # messages per sender row — see HeldSparse); delay_p=0 still drops
    # the whole machinery at trace time
    with_delay = delay_p > 0
    with_recovery = with_crash or with_member
    held = empty_held(spec, C, cfg.wire_int16) if with_delay else None
    crash_state = empty_crash_state(state) if with_recovery else None
    key = jax.random.PRNGKey(seed)
    M = spec.M
    prop_len = jnp.zeros((M, C), jnp.int32)
    prop_data = jnp.zeros((M, spec.E, C), jnp.int32)
    if propose:
        # one proposal per group per round at node 0; when node 0 is not
        # the leader the proposal forwards to it (stepFollower MsgProp),
        # so stress keeps flowing wherever leadership lands
        prop_len = prop_len.at[0].set(1)
        prop_data = prop_data.at[0, 0].set(7)

    tele = (init_telemetry(spec, state, buckets=telemetry_buckets)
            if telemetry else None)
    if telemetry_every < 1:
        raise ValueError(f"telemetry_every must be >= 1, got "
                         f"{telemetry_every}")
    bb = empty_blackbox(spec, state, window=blackbox_window) \
        if blackbox else None
    chaos = _epoch_program(cfg, spec, epoch_len, False, with_delay,
                           with_crash, with_member, telemetry, blackbox)
    heal = _epoch_program(cfg, spec, heal_len, True, with_delay, with_crash,
                          with_member, telemetry, blackbox)
    dp = jnp.float32(drop_p)
    lp = jnp.float32(delay_p)
    pp = jnp.float32(partition_p)
    cp = jnp.float32(crash_p)
    dr = jnp.int32(crash_cfg.down_rounds if with_crash else 1)
    kl = jnp.bool_(crash_cfg.durability == "stable" if with_crash else True)
    ca = jnp.bool_(config_aware)
    mp = jnp.float32(member_p)
    palette = (member_palette(spec, member_cfg.mix) if with_member
               else jnp.zeros((1,), jnp.int32))
    sb = jnp.float32(member_cfg.snap_crash_boost)
    mb = jnp.float32(member_cfg.member_crash_boost)
    z = jnp.float32(0.0)

    def _sync(x):
        # marginal-HBM probe (sync_dispatch): block between epoch
        # dispatches so the donated buffers of the finished program are
        # released before the next executable's workspace is allocated —
        # async dispatch enqueues both and the allocator sees the sum
        if sync_dispatch:
            jax.block_until_ready(x)

    viol = zero_violations()
    commits = []
    timeline = []
    rec = {"i": 0, "pending": None}

    def record(kind):
        # one small host transfer per epoch boundary: the flight
        # recorder's cumulative snapshot (never inside the scan).
        # telemetry_every decimates multi-hour soaks — skipped rows
        # remember their kind so the final boundary is never dropped
        # (the counters are cumulative; the last row carries the run's
        # end state).
        if not telemetry:
            return
        i = rec["i"]
        rec["i"] = i + 1
        if i % telemetry_every:
            rec["pending"] = kind
            return
        rec["pending"] = None
        timeline.append(flight_record(
            tele, viol,
            crash_state.metrics if with_recovery else None,
            kind=kind))

    done = 0
    fault_rounds = 0
    while done < rounds:
        state, inbox, held, crash_state, key, viol, tele, bb, dc = chaos(
            state, inbox, held, crash_state, key, prop_len, prop_data, viol,
            tele, bb, dp, lp, pp, cp, dr, kl, ca, mp, palette, sb, mb
        )
        _sync(viol.multi_leader)
        done += epoch_len
        fault_rounds += epoch_len
        record("fault")
        state, inbox, held, crash_state, key, viol, tele, bb, dh = heal(
            state, inbox, held, crash_state, key, prop_len, prop_data, viol,
            tele, bb, z, z, z, z, dr, kl, ca, z, palette, sb, mb
        )
        _sync(viol.multi_leader)
        done += heal_len
        record("heal")
        commits.append((int(dc), int(dh)))

    # recovery check (the tester's WaitHealth loop, tester/cluster.go):
    # keep healing in bounded increments until every group has a leader —
    # a group whose randomized election timeout just fired may need more
    # than one heal epoch to converge
    def leaders() -> int:
        return int(((state.role == ROLE_LEADER).sum(axis=0) > 0).sum())

    for _ in range(6):
        if leaders() == C:
            break
        state, inbox, held, crash_state, key, viol, tele, bb, dh = heal(
            state, inbox, held, crash_state, key, prop_len, prop_data, viol,
            tele, bb, z, z, z, z, dr, kl, ca, z, palette, sb, mb
        )
        done += heal_len
        record("heal")
        commits.append((0, int(dh)))
    if telemetry and rec["pending"]:
        # the run ended on a decimated boundary — flush the final row
        timeline.append(flight_record(
            tele, viol,
            crash_state.metrics if with_recovery else None,
            kind=rec["pending"]))
    has_leader = leaders()
    v = jax.device_get(viol)
    rep = {
        "groups": C,
        "rounds": done,
        "multi_leader": int(v.multi_leader),
        "hash_mismatch": int(v.hash_mismatch),
        "commit_regress": int(v.commit_regress),
        "lost_commit": int(v.lost_commit),
        "log_divergence": int(v.log_divergence),
        "term_regress": int(v.term_regress),
        "groups_with_leader_after_heal": has_leader,
        "heal_commits_last_epoch": commits[-1][1],
        "epoch_commits": commits,
    }
    if with_crash:
        rep["crash_p"] = crash_p
        rep["crash_down_rounds"] = crash_cfg.down_rounds
        rep["crash_durability"] = crash_cfg.durability
        rep["snap_crash_boost"] = member_cfg.snap_crash_boost
        rep["member_crash_boost"] = member_cfg.member_crash_boost
    if with_member:
        rep["member_p"] = member_p
        rep["member_mix"] = member_cfg.mix
        rep["initial_voters"] = member_cfg.initial_voters
    if telemetry:
        try:
            rep["telemetry"] = telemetry_report(tele)
        except OverflowError:
            # an i32 counter wrapped (realistic only for very long soaks
            # at very large C, e.g. commit_sum ~ C*latency per round) —
            # a multi-hour run must still emit its report; the timeline
            # rows carry per-row `wrapped` flags for the same reason
            rep["telemetry"] = {"wrapped": True,
                                "rounds": int(jax.device_get(tele.round))}
        rep["timeline"] = timeline
    if blackbox:
        # device-side reduction to the first-K offending group ids;
        # only those groups' rings ([W, M, k]) cross PCIe — see
        # blackbox.gather_forensics
        rep["forensics"] = forensics_report(
            bb.ring, bb.viol_groups, bb.viol_round, k=blackbox_k)
    if with_recovery:
        rep["config_aware"] = config_aware
        rep.update(crash_metrics_report(crash_state.metrics))
        if with_crash:
            # the uniform-Bernoulli window-hit baseline for the targeting
            # acceptance: the fraction of crash-sampled lanes that were
            # in-window (windows are counted at sampling instants only)
            sampled = M * C * fault_rounds
            rep["snap_window_lane_frac"] = round(
                rep["snap_window_lanes"] / max(sampled, 1), 6)
            rep["member_window_lane_frac"] = round(
                rep["member_window_lanes"] / max(sampled, 1), 6)
    return rep


VIOLATION_KEYS = (
    "multi_leader", "hash_mismatch", "commit_regress",
    "lost_commit", "log_divergence", "term_regress",
)
# the black-box gmask encodes each violation kind at the bit position of
# its key here; blackbox.py keeps its own literal copy to avoid a
# models -> harness import — this pins the two in lockstep
assert VIOLATION_KEYS == VIOLATION_BIT_NAMES


def summarize_chaos(rep: dict, *, rounds: int, epoch_len: int,
                    heal_len: int, liveness_frac: float = 0.2) -> dict:
    """Pure post-processing of a run_chaos report: the safety verdict,
    the tester-style recovery bar, and the fault-epoch liveness floor.
    Lives here (not in chaos_run.py) so it is unit-testable and every
    driver computes the gates the same way.

    The liveness floor guards fault epochs themselves (VERDICT r3 Weak
    #4: heal-time recovery alone would let a wedge-everything regression
    pass): a fraction of the fault-free throughput (1 commit/group/
    round), defaulted for the standard mix; harsher mixes must set the
    fraction consciously (heavy partitions legally starve minority
    sides). WaitHealth extensions append (0, dh) rows to epoch_commits
    that are NOT fault epochs and must not inflate the floor, hence the
    reconstruction from the requested round budget.
    """
    safe = all(rep.get(k, 0) == 0 for k in VIOLATION_KEYS)
    recovered = (
        rep["groups_with_leader_after_heal"] == rep["groups"]
        and rep["heal_commits_last_epoch"] > 0
    )
    faulted = sum(dc for dc, _ in rep["epoch_commits"])
    # fault epochs = the while-loop iterations of run_chaos (epoch_len +
    # heal_len rounds per iteration)
    faulted_rounds = -(-rounds // (epoch_len + heal_len)) * epoch_len
    floor = int(liveness_frac * rep["groups"] * faulted_rounds)
    return {
        "safe": safe,
        "recovered": recovered,
        "faulted_commits": faulted,
        "faulted_liveness_floor": floor,
        "lively": faulted >= floor,
    }
