"""Offline inspection tools — tools/etcd-dump-db and tools/etcd-dump-logs
analogs.

`dump-db` walks a backend file's buckets/keys (the bbolt inspector:
tools/etcd-dump-db/backend.go — list buckets, iterate a bucket, decode the
key bucket's revision records); `dump-logs` prints a WAL directory's
records in order (tools/etcd-dump-logs/main.go — metadata, hardstates,
snapshots, entries with type/term/index).

Usage:
    python -m etcd_tpu.dump db list-bucket <file.db>
    python -m etcd_tpu.dump db iterate-bucket <file.db> <bucket> [--decode]
    python -m etcd_tpu.dump logs <wal-dir>
"""
from __future__ import annotations

import argparse
import json
import sys


def dump_db_buckets(path: str) -> list[str]:
    from etcd_tpu.storage.backend import Backend

    be = Backend(path)
    try:
        return sorted(be.data.keys())
    finally:
        be.close()


def dump_db_bucket(path: str, bucket: str, decode: bool = False):
    """Yield (key, value-summary) pairs; with decode, revision records in the
    key bucket pretty-print like dump-db's --decode keyDecoder."""
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    be = Backend(path)
    try:
        for k, v in sorted(be.data.get(bucket, {}).items()):
            if decode and bucket == schema.KEY_BUCKET:
                main, sub = schema.bytes_to_rev(k)
                kv, tomb = schema._dec_kv(v)
                yield (
                    f"rev={{{main}/{sub}}}",
                    {
                        "key": kv.key.decode("latin1"),
                        "value": kv.value.decode("latin1"),
                        "create_revision": kv.create_revision,
                        "mod_revision": kv.mod_revision,
                        "version": kv.version,
                        "lease": kv.lease,
                        "tombstone": tomb,
                    },
                )
            else:
                yield (repr(k), f"{len(v)} bytes")
    finally:
        be.close()


def dump_logs(wal_dir: str) -> dict:
    """Replay a WAL directory and summarize its records
    (etcd-dump-logs: WAL metadata + snapshot + hardstate + entries)."""
    from etcd_tpu.storage.wal import WAL

    w = WAL(wal_dir)
    metadata, hardstate, entries, snapshot = w.read_all()
    w.close()
    return {
        "metadata": metadata.decode("latin1") if metadata else "",
        "snapshot": snapshot,
        "hardstate": hardstate,
        "entry_count": len(entries),
        "entries": [
            {
                "index": e["index"],
                "term": e["term"],
                "type": "conf-change" if e.get("type") else "normal",
                "data": e["data"],
            }
            for e in entries
        ],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etcd-dump-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    db = sub.add_parser("db")
    dsub = db.add_subparsers(dest="db_cmd", required=True)
    lb = dsub.add_parser("list-bucket")
    lb.add_argument("path")
    ib = dsub.add_parser("iterate-bucket")
    ib.add_argument("path")
    ib.add_argument("bucket")
    ib.add_argument("--decode", action="store_true")

    lg = sub.add_parser("logs")
    lg.add_argument("wal_dir")

    args = p.parse_args(argv)
    if args.cmd == "db":
        if args.db_cmd == "list-bucket":
            for b in dump_db_buckets(args.path):
                print(b)
        else:
            for k, v in dump_db_bucket(args.path, args.bucket, args.decode):
                print(f"{k} -> {json.dumps(v) if isinstance(v, dict) else v}")
    else:
        print(json.dumps(dump_logs(args.wal_dir), indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
