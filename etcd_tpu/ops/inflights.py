"""Inflights: the per-follower sliding window of in-flight MsgApps.

Re-expression of the reference's ring buffer (raft/tracker/inflights.go:22-132)
as fixed [M, W] tensors on the leader: `ends[d]` holds the last-entry indexes
of in-flight appends to destination d in a ring window [start, start+count).
Because appends are sent in increasing index order the ring is sorted, so
FreeLE is a masked prefix count.

All ops are vectorized over the destination axis and gated by a mask.
"""
from __future__ import annotations

import jax.numpy as jnp

from etcd_tpu.models.state import NodeState
from etcd_tpu.types import Spec


def _ends(spec: Spec, n: NodeState) -> jnp.ndarray:
    """[M, W] view of the flat ends buffer (free reshape)."""
    return n.infl_ends.reshape(spec.M, spec.W)


def _valid(spec: Spec, n: NodeState) -> jnp.ndarray:
    """[M, W] bool: which ring positions hold live ends."""
    w = jnp.arange(spec.W, dtype=jnp.int32)[None, :]
    rel = (w - n.infl_start[:, None]) % spec.W
    return rel < n.infl_count[:, None]


def add(spec: Spec, n: NodeState, mask: jnp.ndarray, end: jnp.ndarray) -> NodeState:
    """Inflights.Add (inflights.go:56-75) for destinations in `mask`."""
    pos = (n.infl_start + n.infl_count) % spec.W
    w = jnp.arange(spec.W, dtype=jnp.int32)[None, :]
    do = mask & (n.infl_count < spec.W)
    sel = do[:, None] & (w == pos[:, None])
    ends = jnp.where(sel, end[:, None] if end.ndim else end, _ends(spec, n))
    return n.replace(
        infl_ends=ends.reshape(-1),
        infl_count=n.infl_count + do.astype(jnp.int32),
    )


def free_le(spec: Spec, n: NodeState, mask: jnp.ndarray, idx: jnp.ndarray) -> NodeState:
    """Inflights.FreeLE (inflights.go:95-122): pop the (sorted) prefix <= idx."""
    freed = (
        (_valid(spec, n) & (_ends(spec, n) <= idx)).sum(axis=-1).astype(jnp.int32)
    )
    freed = jnp.where(mask, freed, 0)
    return n.replace(
        infl_start=(n.infl_start + freed) % spec.W,
        infl_count=n.infl_count - freed,
    )


def free_first_one(spec: Spec, n: NodeState, mask: jnp.ndarray) -> NodeState:
    """Inflights.FreeFirstOne (inflights.go:126-132)."""
    do = mask & (n.infl_count > 0)
    return n.replace(
        infl_start=jnp.where(do, (n.infl_start + 1) % spec.W, n.infl_start),
        infl_count=n.infl_count - do.astype(jnp.int32),
    )


def reset(n: NodeState, mask: jnp.ndarray) -> NodeState:
    """Inflights.reset (via Progress.ResetState, tracker/progress.go:84-90)."""
    z = jnp.zeros_like(n.infl_count)
    return n.replace(
        infl_start=jnp.where(mask, z, n.infl_start),
        infl_count=jnp.where(mask, z, n.infl_count),
    )


def full(max_inflight: int, n: NodeState) -> jnp.ndarray:
    """Inflights.Full (inflights.go:78-81): [M] bool."""
    return n.infl_count >= max_inflight
