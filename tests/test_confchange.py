"""Membership changes — analogs of the reference's confchange suite:
confchange/confchange.go Simple/EnterJoint/LeaveJoint semantics,
confchange/testdata/{simple_*,joint_*}.txt scenarios, raft.go's
one-unapplied-change-at-a-time guard (raft.go:1034-1071) and the
auto-leave rule (raft.go:554-570), plus learner promotion
(server.go:1341-1474's raft-level substrate).
"""
import numpy as np

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.models import confchange as cc
from etcd_tpu.types import (
    CC_ADD_LEARNER,
    CC_ADD_NODE,
    CC_REMOVE_NODE,
    ROLE_LEADER,
    Spec,
)


def masks(cl, m, c=0):
    s = cl.s
    return (
        np.asarray(s.voters[m, ..., c]).tolist(),
        np.asarray(s.voters_out[m, ..., c]).tolist(),
        np.asarray(s.learners[m, ..., c]).tolist(),
        np.asarray(s.learners_next[m, ..., c]).tolist(),
    )


def make3of4():
    """4-slot fleet, members 0-2 voters, slot 3 empty (the joiner)."""
    cl = Cluster(n_members=4, voters=[True, True, True, False])
    cl.campaign(0)
    cl.stabilize()
    assert cl.leader() == 0
    return cl


def test_simple_add_node():
    """simple add (confchange.go:130-147): new voter joins, gets the full
    log, and counts toward quorum."""
    cl = make3of4()
    cl.propose_conf_change(0, cc.encode([(CC_ADD_NODE, 3)]))
    cl.stabilize()
    for m in range(4):
        v, vo, l, ln = masks(cl, m)
        assert v == [True] * 4, (m, v)
        assert vo == [False] * 4 and l == [False] * 4 and ln == [False] * 4
    # the joiner caught up and applied everything
    assert cl.commits().tolist() == [2] * 4
    cl.propose(0, 77)
    cl.stabilize()
    assert cl.commits().tolist() == [3] * 4
    assert cl.log_entries(3)[-1] == (1, 77)


def test_simple_remove_follower():
    """simple remove: quorum shrinks; remaining pair still commits."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose_conf_change(0, cc.encode([(CC_REMOVE_NODE, 2)]))
    cl.stabilize()
    v, _, _, _ = masks(cl, 0)
    assert v == [True, True, False]
    # removed node no longer receives appends; 0+1 alone commit
    cl.isolate(2)
    cl.propose(0, 5)
    cl.stabilize()
    assert cl.commits().tolist()[:2] == [3, 3]


def test_add_learner_then_promote():
    """learner gets replication but no vote weight; promotion via
    simple add-node (the raft substrate of PromoteMember)."""
    cl = make3of4()
    cl.propose_conf_change(0, cc.encode([(CC_ADD_LEARNER, 3)]))
    cl.stabilize()
    v, _, l, _ = masks(cl, 0)
    assert v == [True, True, True, False]
    assert l == [False, False, False, True]
    cl.propose(0, 42)
    cl.stabilize()
    # learner replicated + applied but is not a voter
    assert cl.commits().tolist() == [3] * 4
    assert cl.log_entries(3)[-1] == (1, 42)
    # promote
    cl.propose_conf_change(0, cc.encode([(CC_ADD_NODE, 3)]))
    cl.stabilize()
    v, _, l, _ = masks(cl, 0)
    assert v == [True] * 4 and l == [False] * 4


def test_joint_two_changes_auto_leave():
    """>1 change forces joint consensus with auto-leave
    (confchange_v2_add_double_auto.txt): outgoing set populated while
    joint, then an empty cc entry leaves automatically."""
    cl = Cluster(
        n_members=5, voters=[True, True, True, False, False], spec=Spec(M=5)
    )
    cl.campaign(0)
    cl.stabilize()
    cl.propose_conf_change(
        0, cc.encode([(CC_ADD_NODE, 3), (CC_ADD_NODE, 4)], auto_leave=True)
    )
    cl.stabilize()
    # the auto-leave entry is appended at apply time WITHOUT an immediate
    # broadcast (advance(), raft.go:554-570) — like the reference it rides
    # the next triggered send, so tick a heartbeat round to carry it
    cl.stabilize(tick=True)
    cl.stabilize(tick=True)
    for m in range(5):
        v, vo, l, ln = masks(cl, m)
        assert v == [True] * 5, (m, v)
        assert vo == [False] * 5, (m, vo)  # left the joint config
    cl.propose(0, 9)
    cl.stabilize()
    assert min(cl.commits()) == max(cl.commits())


def test_joint_demotion_stages_learner_next():
    """demoting a voter inside a joint config stages it in LearnersNext
    until LeaveJoint (confchange.go:166-230; joint_learners_next.txt)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    # joint: remove 2 as voter, re-add as learner, no auto-leave
    cl.propose_conf_change(
        0,
        cc.encode(
            [(CC_ADD_LEARNER, 2), (CC_ADD_NODE, 1)],
            enter_joint=True,
            auto_leave=False,
        ),
    )
    cl.stabilize()
    v, vo, l, ln = masks(cl, 0)
    assert v == [True, True, False]
    assert vo == [True, True, True]          # outgoing keeps old voters
    assert ln == [False, False, True]        # staged, not yet a learner
    assert l == [False, False, False]
    # explicit leave
    cl.propose_conf_change(0, cc.encode_leave_joint())
    cl.stabilize()
    v, vo, l, ln = masks(cl, 0)
    assert v == [True, True, False]
    assert vo == [False, False, False]
    assert l == [False, False, True]         # LearnersNext applied
    assert ln == [False, False, False]


def test_joint_quorum_needs_both_majorities():
    """while joint, commit requires a majority of BOTH incoming and
    outgoing configs (quorum/joint.go:49-75)."""
    cl = Cluster(
        n_members=5, voters=[True, True, True, False, False], spec=Spec(M=5)
    )
    cl.campaign(0)
    cl.stabilize()
    cl.propose_conf_change(
        0,
        cc.encode(
            [(CC_ADD_NODE, 3), (CC_ADD_NODE, 4)],
            enter_joint=True,
            auto_leave=False,
        ),
    )
    cl.stabilize()
    v, vo, _, _ = masks(cl, 0)
    assert v == [True] * 5 and vo == [True, True, True, False, False]
    # cut off the two joiners: old majority {0,1,2} still commits (3/5 new
    # majority AND 3/3 old majority both satisfied)
    cl.isolate(3)
    cl.isolate(4)
    base = int(cl.commits()[0])
    cl.propose(0, 1)
    cl.stabilize()
    assert int(cl.commits()[0]) == base + 1
    # now ALSO cut 2: {0,1} is a new-config majority (2 of... no: new config
    # has 5 voters; {0,1} is not a majority) — nothing commits
    cl.isolate(2)
    cl.propose(0, 2)
    cl.stabilize()
    assert int(cl.commits()[0]) == base + 1


def test_one_unapplied_conf_change_at_a_time():
    """a second cc proposed while one is pending is demoted to an empty
    entry (raft.go:1034-1071 pendingConfIndex guard)."""
    cl = make3of4()
    cl.isolate(1)  # stall commit progress? no — {0,2} still commit. Instead:
    cl.recover()
    # propose two ccs in the same round at the leader: second must be refused
    cl.propose_conf_change(0, cc.encode([(CC_ADD_NODE, 3)]))
    cl.propose_conf_change(0, cc.encode([(CC_REMOVE_NODE, 2)]))
    cl.stabilize()
    v, _, _, _ = masks(cl, 0)
    assert v == [True, True, True, True]  # first applied, second blanked


def test_remove_leader_self_then_new_election():
    """leader removing itself: entry commits, then the remaining pair can
    elect (raft.go removes no special case; promotable() gates re-election)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose_conf_change(0, cc.encode([(CC_REMOVE_NODE, 0)]))
    cl.stabilize()
    v, _, _, _ = masks(cl, 1)
    assert v == [False, True, True]
    cl.campaign(1)
    cl.stabilize()
    assert 1 in cl.leaders() or 2 in cl.leaders()
    cl.propose(1, 3)
    cl.stabilize()
    assert int(cl.commits()[1]) >= 3


def test_batched_conf_change_divergence():
    """different clusters in one batch apply different conf changes."""
    cl = Cluster(n_members=4, C=2, voters=[True, True, True, False])
    cl.campaign(0, c=0)
    cl.campaign(0, c=1)
    cl.stabilize()
    cl.propose_conf_change(0, cc.encode([(CC_ADD_NODE, 3)]), c=0)
    cl.propose_conf_change(0, cc.encode([(CC_REMOVE_NODE, 2)]), c=1)
    cl.stabilize()
    v0, _, _, _ = masks(cl, 0, c=0)
    v1, _, _, _ = masks(cl, 0, c=1)
    assert v0 == [True, True, True, True]
    assert v1 == [True, True, False, False]
