"""etcdutl analog: offline admin over a data directory.

The reference's etcdutl operates directly on files with no server
running (etcdutl/etcdutl: snapshot status/restore, defrag, hashkv).
Commands here work on the backend files etcd_tpu writes
(<data-dir>/member<N>.db) and the snapshot blobs etcdctl saves.

Usage:
    python -m etcd_tpu.etcdutl snapshot status snap.db
    python -m etcd_tpu.etcdutl snapshot restore snap.db --data-dir D [--members 3]
    python -m etcd_tpu.etcdutl hashkv --data-dir D --member 0
    python -m etcd_tpu.etcdutl defrag --data-dir D
    python -m etcd_tpu.etcdutl status --data-dir D
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import pickle
import sys


def _member_paths(data_dir: str) -> list[str]:
    return sorted(glob.glob(os.path.join(data_dir, "member*.db")))


def _load(path: str):
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    be = Backend(path)
    meta = schema.load_applied_meta(be) or {}
    store = schema.load_mvcc(
        be,
        max_rev=meta.get("current_rev"),
        compact_rev=meta.get("compact_rev", 0),
    )
    return be, meta, store


class _DataOnlyUnpickler(pickle.Unpickler):
    """Snapshot files travel between machines, so the loader must not be a
    code-execution vector: member snapshots are pure data (dict/list/tuple/
    bytes/str/int/bool/None — see kvserver.member_snapshot), and any GLOBAL
    opcode in the stream is rejected outright."""

    def find_class(self, module, name):
        raise pickle.UnpicklingError(
            f"snapshot file contains non-data object {module}.{name}; "
            "refusing to load"
        )


def load_snapshot(path: str) -> dict:
    """Read a snapshot file written by `etcdctl snapshot save` (the pickled
    member snapshot the gateway streams, server/v3rpc.py
    maintenance_snapshot)."""
    with open(path, "rb") as f:
        return _DataOnlyUnpickler(f).load()


def cmd_snapshot_status(args) -> int:
    snap = load_snapshot(args.path)
    kv = snap.get("kv", {})
    print(json.dumps({
        "applied_index": snap.get("applied_index"),
        "revision": kv.get("current_rev"),
        "compact_revision": kv.get("compact_rev"),
        "total_key_revisions": len(kv.get("revs", [])),
        "alarms": snap.get("alarms", []),
    }))
    return 0


def restore_snapshot(path: str, data_dir: str, members: int = 3) -> int:
    """etcdutl snapshot restore (etcdutl/etcdutl/snapshot_command.go:81,122):
    rewrite a fresh data dir whose every member backend holds the
    snapshot's applied state at a uniform consistent index. Returns the
    restored consistent index. The restored cluster boots via
    EtcdCluster.boot_from_disk (the fresh-WAL-with-snapshot-marker boot of
    the reference's restore)."""
    from etcd_tpu.server.mvcc import MVCCStore
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    snap = load_snapshot(path)
    idx = int(snap["applied_index"])
    store = MVCCStore.from_snapshot(snap["kv"])
    os.makedirs(data_dir, exist_ok=True)
    for m in range(members):
        be = Backend(os.path.join(data_dir, f"member{m}.db"), fresh=True)
        schema.persist_mvcc_delta(be, store, 0)
        schema.save_applied_meta(
            be,
            index=idx,
            term=int(snap.get("term", 1)) or 1,
            store=store,
            lease_snap=snap.get("lease"),
            auth_snap=snap.get("auth"),
            alarms=snap.get("alarms", []),
        )
        be.commit()
        be.close()
    return idx


def cmd_snapshot_restore(args) -> int:
    idx = restore_snapshot(args.path, args.data_dir, args.members)
    print(json.dumps({
        "restored": args.data_dir,
        "members": args.members,
        "consistent_index": idx,
    }))
    return 0


def cmd_hashkv(args) -> int:
    path = os.path.join(args.data_dir, f"member{args.member}.db")
    _, meta, store = _load(path)
    print(json.dumps({
        "member": args.member,
        "hash": store.hash_kv(),
        "revision": store.current_rev,
        "consistent_index": meta.get("consistent_index", 0),
    }))
    return 0


def cmd_defrag(args) -> int:
    for path in _member_paths(args.data_dir):
        from etcd_tpu.storage.backend import Backend

        be = Backend(path)
        before = be.size()
        be.defrag()
        be.close()
        print(f"{os.path.basename(path)}: {before} -> {be.size()} bytes")
    return 0


def cmd_backup(args) -> int:
    """etcdutl backup (etcdutl/etcdutl/backup_command.go): offline copy
    of a data dir to a fresh directory. Like the reference, this is a
    REWRITE rather than a file copy: each member backend is loaded to
    its last committed point (dropping any torn tail) and re-serialized
    cleanly, so the backup is always openable. The manifest records
    per-member consistent index / revision / hash for later integrity
    checks."""
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    paths = _member_paths(args.data_dir)
    if not paths:
        print(f"no member backends under {args.data_dir}",
              file=sys.stderr)
        return 1
    os.makedirs(args.backup_dir, exist_ok=True)
    leftover = _member_paths(args.backup_dir)
    if leftover:
        # stale member files would silently mix with this backup and
        # boot as one inconsistent cluster — refuse
        print(f"backup dir {args.backup_dir} already contains "
              f"{len(leftover)} member backend(s); use an empty dir",
              file=sys.stderr)
        return 1
    # stage into a scratch subdir and move files up only once ALL
    # members serialized: a mid-loop failure (torn source, disk full)
    # must never leave a bootable-looking partial backup behind
    stage = os.path.join(args.backup_dir, ".partial")
    os.makedirs(stage, exist_ok=True)
    for name in os.listdir(stage):  # wipe a previous failed attempt
        os.remove(os.path.join(stage, name))
    manifest = []
    for path in paths:
        be, meta, store = _load(path)
        dst = os.path.join(stage, os.path.basename(path))
        out = Backend(dst, fresh=True)
        schema.persist_mvcc_delta(out, store, 0)
        schema.save_applied_meta(
            out,
            index=meta.get("consistent_index", 0),
            term=meta.get("term", 0),
            store=store,
            lease_snap=meta.get("lease"),
            auth_snap=meta.get("auth"),
            alarms=meta.get("alarms", []),
            cluster_version=meta.get("cluster_version"),
            downgrade=meta.get("downgrade"),
            v2=meta.get("v2"),
        )
        sv = schema.get_storage_version(be)
        if sv is not None:
            schema.set_storage_version(out, sv)
        out.commit()
        out.close()
        be.close()
        manifest.append({
            "member": os.path.basename(path),
            "consistent_index": meta.get("consistent_index", 0),
            "revision": store.current_rev,
            "hash": store.hash_kv(),
        })
    with open(os.path.join(stage, "backup_manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    for name in os.listdir(stage):
        os.rename(os.path.join(stage, name),
                  os.path.join(args.backup_dir, name))
    os.rmdir(stage)
    print(json.dumps({"backed_up": len(manifest),
                      "backup_dir": args.backup_dir}))
    return 0


def cmd_migrate(args) -> int:
    """etcdutl migrate (etcdutl/etcdutl/migrate_command.go): move a data
    dir's storage schema to --target-version ("X.Y"). Upgrading to 3.6
    writes the storage-version field; downgrading to 3.5 removes it —
    refused while 3.6-only content exists (an active downgrade job)
    unless --force, mirroring schema.Migrate's unknown-field check."""
    from etcd_tpu.storage import schema

    target = args.target_version
    if target.count(".") != 1:
        print(f'wrong target version format, expected "X.Y", '
              f'got {target!r}', file=sys.stderr)
        return 1
    if target not in (schema.MIN_STORAGE_VERSION,
                      schema.CURRENT_STORAGE_VERSION):
        print(f"unsupported target storage version {target!r} "
              f"(supported: {schema.MIN_STORAGE_VERSION}, "
              f"{schema.CURRENT_STORAGE_VERSION})", file=sys.stderr)
        return 1
    paths = _member_paths(args.data_dir)
    if not paths:
        print(f"no member backends under {args.data_dir}",
              file=sys.stderr)
        return 1
    from etcd_tpu.storage.backend import Backend

    # phase 1: validate EVERY member before mutating any — a mid-loop
    # failure must not leave the dir at mixed storage versions
    loaded = []
    for path in paths:
        # meta + the version field only — no need to replay the full
        # MVCC history just to flip one meta record
        be = Backend(path)
        meta = schema.load_applied_meta(be) or {}
        if target == schema.MIN_STORAGE_VERSION and \
                (meta.get("downgrade") or {}).get("enabled") and \
                not args.force:
            print(f"{os.path.basename(path)}: active downgrade "
                  f"record is not understood by {target}; cancel it "
                  "or pass --force", file=sys.stderr)
            for b, _ in loaded:
                b.close()
            be.close()
            return 1
        loaded.append((be, path))
    # phase 2: apply
    results = []
    for be, path in loaded:
        current = schema.get_storage_version(be) or \
            schema.MIN_STORAGE_VERSION
        if current != target:
            schema.set_storage_version(be, target)
            be.commit()
        be.close()
        results.append({"member": os.path.basename(path),
                        "version": target,
                        "changed": current != target})
    print(json.dumps(results, indent=2))
    return 0


def cmd_status(args) -> int:
    out = []
    for path in _member_paths(args.data_dir):
        be, meta, store = _load(path)
        out.append({
            "member": os.path.basename(path),
            "size": be.size(),
            "size_in_use": be.size_in_use(),
            "consistent_index": meta.get("consistent_index", 0),
            "term": meta.get("term", 0),
            "revision": store.current_rev,
            "compact_revision": store.compact_rev,
            "keys": len(store.index),
        })
    print(json.dumps(out, indent=2))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etcdutl-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sn = sub.add_parser("snapshot")
    ssub = sn.add_subparsers(dest="snap_cmd", required=True)
    st = ssub.add_parser("status")
    st.add_argument("path")
    rs = ssub.add_parser("restore")
    rs.add_argument("path")
    rs.add_argument("--data-dir", required=True)
    rs.add_argument("--members", type=int, default=3)

    h = sub.add_parser("hashkv")
    h.add_argument("--data-dir", required=True)
    h.add_argument("--member", type=int, default=0)

    d = sub.add_parser("defrag")
    d.add_argument("--data-dir", required=True)

    s = sub.add_parser("status")
    s.add_argument("--data-dir", required=True)

    b = sub.add_parser("backup")
    b.add_argument("--data-dir", required=True)
    b.add_argument("--backup-dir", required=True)

    m = sub.add_parser("migrate")
    m.add_argument("--data-dir", required=True)
    m.add_argument("--target-version", required=True)
    m.add_argument("--force", action="store_true")

    args = p.parse_args(argv)
    if args.cmd == "snapshot":
        if args.snap_cmd == "restore":
            return cmd_snapshot_restore(args)
        return cmd_snapshot_status(args)
    if args.cmd == "hashkv":
        return cmd_hashkv(args)
    if args.cmd == "defrag":
        return cmd_defrag(args)
    if args.cmd == "backup":
        return cmd_backup(args)
    if args.cmd == "migrate":
        return cmd_migrate(args)
    return cmd_status(args)


if __name__ == "__main__":
    from etcd_tpu.utils.cache import entrypoint_platform_setup

    entrypoint_platform_setup()
    sys.exit(main())
