#!/bin/bash
# Full-suite run with wall-clock + RSS telemetry (single-core VM: run alone).
cd /root/repo
T0=$(date +%s)
# -m 'not slow': the full-scale tiers (e.g. the 262k-group crash-chaos
# run) are explicit TPU invocations, not suite members on this VM
python -m pytest tests/ -q -m 'not slow' > suite_run.log 2>&1 &
PYT=$!
( while kill -0 $PYT 2>/dev/null; do
    ps -o rss= -p $PYT
    sleep 15
  done ) > suite_rss.log 2>/dev/null &
wait $PYT
RC=$?
echo "WALL_SECONDS=$(( $(date +%s) - T0 )) RC=$RC" >> suite_run.log

# Optional crash-chaos smoke (SUITE_CHAOS=1): a small chaos_run.py pass
# with crash faults on, exercising the driver + summarize gates end to
# end. Scale evidence runs use chaos_run.py directly on TPU
# (CHAOS_C=262144 CHAOS_CRASH=0.01).
if [ "${SUITE_CHAOS:-0}" != "0" ]; then
  CHAOS_C=${CHAOS_C:-256} CHAOS_ROUNDS=${CHAOS_ROUNDS:-75} \
  CHAOS_CRASH=${CHAOS_CRASH:-0.02} CHAOS_LEASE=${CHAOS_LEASE:-0} \
    python chaos_run.py > chaos_crash_smoke.json 2> chaos_crash_smoke.err
  CRC=$?
  echo "CHAOS_SMOKE_RC=$CRC" >> suite_run.log
  [ $RC -eq 0 ] && RC=$CRC
fi
exit $RC
