"""Mirror syncer — client/v3/mirror/syncer.go parity: paginated base sync
pinned at one revision, then watch-driven incremental updates, against a
second in-process cluster (the make-mirror e2e of
etcdctl/ctlv3/command/make_mirror_command.go).
"""
import pytest

from etcd_tpu.client import Client
from etcd_tpu.mirror import Mirror, Syncer, make_mirror
from etcd_tpu.server.kvserver import EtcdCluster


@pytest.fixture(scope="module")
def clusters():
    src = EtcdCluster()
    src.ensure_leader()
    dst = EtcdCluster()
    dst.ensure_leader()
    return Client(src), Client(dst)


def test_sync_base_paginates_at_pinned_rev(clusters):
    src, _ = clusters
    for i in range(7):
        src.put(b"base/%02d" % i, b"v%d" % i)
    s = Syncer(src, prefix=b"base/")
    pages = list(s.sync_base(batch_limit=3))
    assert [len(p) for p in pages] == [3, 3, 1]
    keys = [kv.key for p in pages for kv in p]
    assert keys == [b"base/%02d" % i for i in range(7)]
    assert s.rev > 0
    # writes after the pinned revision are invisible to a re-run base sync
    src.put(b"base/99", b"late")
    pages2 = list(Syncer(src, prefix=b"base/", rev=s.rev).sync_base(3))
    assert [kv.key for p in pages2 for kv in p] == keys


def test_sync_updates_requires_base(clusters):
    src, _ = clusters
    with pytest.raises(RuntimeError):
        Syncer(src, prefix=b"x/").sync_updates()


def test_make_mirror_end_to_end(clusters):
    src, dst = clusters
    for i in range(5):
        src.put(b"m/%d" % i, b"v%d" % i)
    src.put(b"other/1", b"out-of-scope")

    mirror = make_mirror(src, dst, prefix=b"m/", batch_limit=2)
    assert mirror.base_keys == 5
    got = dst.get_prefix(b"m/")
    assert [(kv.key, kv.value) for kv in got["kvs"]] == [
        (b"m/%d" % i, b"v%d" % i) for i in range(5)
    ]
    # out-of-prefix keys are not mirrored
    assert dst.get(b"other/1") is None

    # incremental: puts, overwrites and deletes flow through the watch
    src.put(b"m/5", b"new")
    src.put(b"m/0", b"v0b")
    src.delete(b"m/3")
    n = mirror.pump()
    assert n == 3
    assert dst.get(b"m/5").value == b"new"
    assert dst.get(b"m/0").value == b"v0b"
    assert dst.get(b"m/3") is None
    # idempotent pump when idle
    assert mirror.pump() == 0


def test_mirror_whole_keyspace(clusters):
    src, dst = clusters
    src.put(b"a-root", b"1")
    mirror = make_mirror(src, dst)  # no prefix: entire keyspace
    assert dst.get(b"a-root").value == b"1"
    src.put(b"z-root", b"2")
    mirror.pump()
    assert dst.get(b"z-root").value == b"2"
