"""clientv3 leasing + ordering sub-package tests.

Leasing (client/v3/leasing): acquisition on Get, cache-served owned reads,
owner write-through with cache refresh, cross-client revocation, dead-owner
claim breaking, and txn invalidation — the integration arcs of the
reference's leasing tests (client/v3/leasing/kv_test.go TestLeasingGet /
TestLeasingInterval / TestLeasingPutGet / TestLeasingRev).

Ordering (client/v3/ordering): revision-monotonic reads with the
endpoint-switching violation closure and ErrNoGreaterRev exhaustion
(kv_test.go TestDetectKvOrderViolation / util_test.go).
"""
from __future__ import annotations

import pytest

from etcd_tpu.client import Client
from etcd_tpu.concurrency import Session
from etcd_tpu.leasing import REVOKE, LeasingKV
from etcd_tpu.ordering import ErrNoGreaterRev, OrderingKV, switch_endpoint_closure
from etcd_tpu.server.kvserver import EtcdCluster


@pytest.fixture()
def ec():
    return EtcdCluster(n_members=3)


def test_leasing_get_acquires_and_caches(ec):
    cl = Client(ec)
    cl.put(b"abc", b"123")
    lkv = LeasingKV(cl, b"lease/")
    assert lkv.get(b"abc").value == b"123"
    # the leasing key exists, bound to the session lease
    lk = cl.get(b"lease/abc")
    assert lk is not None
    assert b"abc" in lkv.owned
    # cached read serves without touching the server's revision
    rev0 = int(cl.get_range(b"abc")["header"].revision)
    assert lkv.get(b"abc").value == b"123"
    assert int(cl.get_range(b"abc")["header"].revision) == rev0


def test_leasing_get_absent_key_cached(ec):
    cl = Client(ec)
    lkv = LeasingKV(cl, b"lease/")
    assert lkv.get(b"nope") is None
    assert lkv.get(b"nope") is None  # negative cache hit
    assert b"nope" in lkv.owned


def test_leasing_owner_put_refreshes_cache(ec):
    cl = Client(ec)
    cl.put(b"k", b"v1")
    lkv = LeasingKV(cl, b"lease/")
    kv1 = lkv.get(b"k")
    lkv.put(b"k", b"v2")
    kv2 = lkv.get(b"k")
    assert kv2.value == b"v2"
    assert kv2.version == kv1.version + 1
    assert kv2.mod_revision > kv1.mod_revision
    # the cache matches the server state
    assert cl.get(b"k").value == b"v2"


def test_leasing_revocation_between_clients(ec):
    cl = Client(ec)
    cl.put(b"abc", b"123")
    lkv1 = LeasingKV(cl, b"lease/")
    lkv2 = LeasingKV(cl, b"lease/")
    assert lkv1.get(b"abc").value == b"123"
    # lkv2's write must revoke lkv1's claim (doc.go:36-44)
    lkv2.put(b"abc", b"456")
    assert b"abc" not in lkv1.owned, "owner did not relinquish"
    assert cl.get(b"lease/abc") is None, "leasing key not cleaned up"
    # lkv1 re-reads through a fresh acquisition and sees the new value
    assert lkv1.get(b"abc").value == b"456"


def test_leasing_dead_owner_claim_broken(ec):
    cl = Client(ec)
    cl.put(b"k", b"v1")
    session1 = Session(cl, ttl=60)
    lkv1 = LeasingKV(cl, b"lease/", session=session1)
    assert lkv1.get(b"k").value == b"v1"
    # simulate a dead owner: drop it from the registry by deleting the
    # object, so no pump ever answers the revoke request
    del lkv1
    lkv2 = LeasingKV(cl, b"lease/")
    lkv2.put(b"k", b"v2")
    assert cl.get(b"k").value == b"v2"
    assert cl.get(b"lease/k") is None


def test_leasing_session_close_releases_claims(ec):
    cl = Client(ec)
    cl.put(b"k", b"v1")
    lkv1 = LeasingKV(cl, b"lease/")
    lkv1.get(b"k")
    lkv1.close()
    assert cl.get(b"lease/k") is None
    # a second client acquires without any revocation dance
    lkv2 = LeasingKV(cl, b"lease/")
    assert lkv2.get(b"k").value == b"v1"
    assert b"k" in lkv2.owned


def test_leasing_txn_invalidates_and_revokes(ec):
    cl = Client(ec)
    cl.put(b"a", b"1")
    cl.put(b"b", b"2")
    lkv1 = LeasingKV(cl, b"lease/")
    lkv2 = LeasingKV(cl, b"lease/")
    lkv1.get(b"a")          # lkv1 owns a
    lkv2.get(b"b")          # lkv2 owns b
    # lkv1 txn writes both: own cache for a invalidated, b revoked from lkv2
    res = (
        lkv1.txn()
        .if_(cl.compare_value(b"a", "=", b"1"))
        .then(Op_put(b"a", b"10"), Op_put(b"b", b"20"))
        .commit()
    )
    assert res["succeeded"]
    assert b"b" not in lkv2.owned
    assert lkv1.get(b"a").value == b"10"
    assert lkv2.get(b"b").value == b"20"


def Op_put(key: bytes, value: bytes):
    from etcd_tpu.server.kvserver import Op

    return Op("put", key, value)


def test_leasing_namespaced_client(ec):
    cl = Client(ec, namespace=b"ns/")
    cl.put(b"k", b"v")
    lkv = LeasingKV(cl, b"lease/")
    kv = lkv.get(b"k")
    assert kv.key == b"k" and kv.value == b"v"  # namespace stripped
    assert lkv.get(b"k").key == b"k"            # cached copy too
    # the leasing key lives inside the namespace
    assert Client(ec).get(b"ns/lease/k") is not None


def test_ordering_monotonic_reads_rotate_members(ec):
    cl = Client(ec)
    cl.put(b"k", b"v1")
    okv = OrderingKV(cl, member=0)
    assert okv.get(b"k").value == b"v1"
    high = okv.prev_rev
    assert high > 0
    # pin the reader to a member and rewind the client's view: a stale
    # serializable read must trigger rotation, not a stale answer
    okv.prev_rev = high + 5
    with pytest.raises(ErrNoGreaterRev):
        okv.get(b"k")
    # after catching up, reads flow again
    for _ in range(6):
        cl.put(b"k", b"v2")
    okv2 = OrderingKV(cl, member=0)
    okv2.prev_rev = high + 5
    assert okv2.get(b"k").value == b"v2"


def test_ordering_violation_closure_counts(ec):
    closure = switch_endpoint_closure(3)
    cl = Client(ec)
    okv = OrderingKV(cl, member=0, on_violation=closure)
    members = []
    # 5*n violations pass (rotating members), then the closure gives up —
    # util.go:36's `count > 5*len(endpoints)` admits one extra increment
    with pytest.raises(ErrNoGreaterRev):
        for _ in range(20):
            closure(okv, 99)
            members.append(okv.member)
    assert len(members) == 16
    assert set(members) == {0, 1, 2}  # rotated through every member


def test_ordering_observes_writes(ec):
    cl = Client(ec)
    okv = OrderingKV(cl)
    okv.put(b"k", b"v")
    assert okv.prev_rev > 0
    r1 = okv.prev_rev
    okv.txn().then(Op_put(b"k", b"v2")).commit()
    assert okv.prev_rev > r1
