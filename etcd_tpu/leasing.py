"""clientv3/leasing parity: serve linearizable reads from a local cache by
owning per-key leasing keys (client/v3/leasing/kv.go, cache.go, doc.go).

Protocol (doc.go:14-46): a Get on key ``k`` tries to acquire the leasing
key ``<pfx>/k`` bound to the client's session lease; while owned, reads of
``k`` are served from the local cache and writes go through ownership-
guarded txns that refresh the cache. Another client writing ``k`` first
requests revocation by overwriting ``<pfx>/k`` with a revoke marker; the
owner answers by deleting the leasing key (relinquishing), which unblocks
the writer. Session-lease expiry deletes every leasing key the owner held,
releasing its claims wholesale.

In-process adaptation: the reference owner reacts from a background
watch goroutine (kv.go:70-78 monitorSession + leases watcher). Here each
``LeasingKV`` drains its watch in ``pump()``, and a writer waiting on a
revocation pumps every sibling LeasingKV registered on the same cluster —
the synchronous analog of goroutine scheduling, matching the repo's
step-and-recheck concurrency idiom (concurrency.py Mutex.lock). A dead
owner (closed process, no pump) is broken by the same fallback the
reference gets from lease expiry: the writer deletes the leasing key
itself once the owner's claim is stale.
"""
from __future__ import annotations

import dataclasses
import weakref

from etcd_tpu.client import Client
from etcd_tpu.concurrency import ConcurrencyError, Session
from etcd_tpu.server.kvserver import Op

REVOKE = b"REVOKE"  # revoke-request marker (the reference bumps a rev
# counter in the leasing key value, leasing/txn.go:33-58; a marker value
# carries the same one-bit "please relinquish" signal)

class _Registry:
    """Every LeasingKV on one EtcdCluster, so a blocked writer can run its
    siblings' watch loops (see module docstring). Keyed by a weak
    reference to the cluster itself: a collected cluster drops its whole
    entry, and ids are never reused across live objects."""
    def __init__(self):
        self.by_cluster: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )

    def add(self, kv: "LeasingKV") -> None:
        self.by_cluster.setdefault(kv.c.ec, []).append(weakref.ref(kv))

    def siblings(self, kv: "LeasingKV"):
        refs = self.by_cluster.get(kv.c.ec, [])
        live, out = [], []
        for r in refs:
            o = r()
            if o is not None:
                live.append(r)
                out.append(o)
        refs[:] = live
        return out


_registry = _Registry()


class LeasingKV:
    """leasingKV (leasing/kv.go:33-56) over the in-process client."""

    def __init__(self, client: Client, pfx: bytes,
                 session: Session | None = None, ttl: int = 60):
        self.c = client
        self.pfx = pfx.rstrip(b"/") + b"/"
        self.session = session or Session(client, ttl)
        # key -> leasing-key create_revision (our ownership proof)
        self.owned: dict[bytes, int] = {}
        # key -> cached KeyValue | None (None caches "key absent")
        self.cache: dict[bytes, object] = {}
        self.watch = client.watch_prefix(self.pfx)
        _registry.add(self)

    def close(self) -> None:
        """Close(): relinquish everything (kv.go:81-84)."""
        for key in list(self.owned):
            self._relinquish(key)
        self.session.close()

    # -- ownership bookkeeping --------------------------------------------
    def _lkey(self, key: bytes) -> bytes:
        return self.pfx + key

    def _relinquish(self, key: bytes) -> None:
        crev = self.owned.pop(key, None)
        self.cache.pop(key, None)
        if crev is None:
            return
        c = self.c
        # delete only our own claim: a newer claimant's leasing key has a
        # different create revision
        c.txn().if_(c.compare_create(self._lkey(key), "=", crev)).then(
            Op("delete", self._lkey(key))
        ).commit()

    def pump(self) -> None:
        """Drain the leasing-key watch: answer revoke requests on keys we
        own and drop claims whose leasing key was deleted out from under
        us (lease expiry / forced break). The in-process analog of the
        reference's background watcher (leasing/kv.go:360-420)."""
        for ev in self.watch.events():
            key = ev.kv.key[len(self.pfx):]
            if key not in self.owned:
                continue
            if ev.type == "put" and ev.kv.value == REVOKE:
                self._relinquish(key)
            elif ev.type == "delete":
                # our claim is gone (expiry or a writer broke it)
                self.owned.pop(key, None)
                self.cache.pop(key, None)

    # -- reads --------------------------------------------------------------
    def get(self, key: bytes, rev: int = 0, serializable: bool = False):
        """Get (kv.go:85-87 -> get): serve owned keys from the cache;
        otherwise acquire the leasing key and cache the read. Historical
        and serializable reads pass through uncached (leasing/kv.go:136-
        141 skips acquisition for non-current reads)."""
        if rev or serializable:
            return self.c.get(key, rev=rev, serializable=serializable)
        self.pump()
        if key in self.owned and key in self.cache:
            return self.cache[key]
        c = self.c
        res = (
            c.txn()
            .if_(c.compare_create(self._lkey(key), "=", 0))
            .then(
                Op("put", self._lkey(key), b"", lease=self.session.lease_id),
                Op("range", key),
            )
            .else_(Op("range", key))
            .commit()
        )
        if res["succeeded"]:
            # strip the client namespace and copy, as Client.get does —
            # txn range payloads come back as the store's own raw kvs
            kvs = self.c._strip(res["responses"][1][1])
            kv = kvs[0] if kvs else None
            self.owned[key] = int(res["rev"])
            self.cache[key] = kv
            return kv
        kvs = self.c._strip(res["responses"][0][1])
        return kvs[0] if kvs else None

    # -- writes -------------------------------------------------------------
    def _wait_revoke(self, key: bytes, max_rounds: int = 200) -> None:
        """Overwrite the leasing key with the revoke marker and wait for
        the owner to relinquish (leasing/txn.go:33-58 + waitSession).
        Pumps every sibling LeasingKV between cluster steps; if the owner
        never answers, break its claim the way lease expiry would."""
        c = self.c
        lkey = self._lkey(key)
        cur = c.get(lkey)
        if cur is None:
            return
        c.put(lkey, REVOKE, lease=0)
        for _ in range(max_rounds):
            for kv in _registry.siblings(self):
                if kv is not self:
                    kv.pump()
            if c.get(lkey) is None:
                return
            c.ec.step()
        # dead owner: no pump will ever answer; expire the claim for it
        c.delete(lkey)

    def put(self, key: bytes, value: bytes, **kw):
        self.pump()
        c = self.c
        if key in self.owned:
            # ownership-guarded write-through + cache refresh (kv.go:
            # put's txn asserts the leasing key is still ours)
            res = (
                c.txn()
                .if_(c.compare_create(self._lkey(key), "=", self.owned[key]))
                .then(Op("put", key, value, **kw))
                .commit()
            )
            if res["succeeded"]:
                mod = int(res["rev"])
                if key in self.cache:
                    # _fresh_kv only when the cache says "key absent"
                    # (entry is None); an unknown entry (e.g. txn()
                    # invalidated it) stays unpopulated — fabricating
                    # create_revision/version=1 for a pre-existing key
                    # would poison later cached gets. get() reads
                    # through on a missing entry.
                    prev = self.cache[key]
                    self.cache[key] = dataclasses.replace(
                        prev, value=value, mod_revision=mod,
                        version=prev.version + 1,
                    ) if prev is not None else _fresh_kv(key, value, mod)
                return res
            # lost the claim mid-flight: a NEW claimant may own the key
            # now, so fall through to the full revoke protocol — a bare
            # write would leave that owner serving its stale cache
            self.owned.pop(key, None)
            self.cache.pop(key, None)
        self._wait_revoke(key)
        return c.put(key, value, **kw)

    def delete(self, key: bytes, **kw):
        self.pump()
        c = self.c
        if key in self.owned:
            res = (
                c.txn()
                .if_(c.compare_create(self._lkey(key), "=", self.owned[key]))
                .then(Op("delete", key, **kw))
                .commit()
            )
            if res["succeeded"]:
                self.cache[key] = None
                return res
            self.owned.pop(key, None)
            self.cache.pop(key, None)
        self._wait_revoke(key)
        return c.delete(key, **kw)

    def txn(self):
        """Txn: revoke other claims on written keys, invalidate our own
        cache for them, then pass through (a simplification of
        leasing/txn.go's server-side evaluation: correctness is kept by
        invalidation, locality of cached txns is not)."""
        builder = self.c.txn()
        orig_commit = builder.commit

        def commit():
            self.pump()
            written = {
                op.key for op in (builder._success + builder._failure)
                if op.type in ("put", "delete")
            }
            for key in written:
                if key in self.owned:
                    self.cache.pop(key, None)
                else:
                    self._wait_revoke(key)
            return orig_commit()

        builder.commit = commit
        return builder


def _fresh_kv(key: bytes, value: bytes, rev: int):
    from etcd_tpu.server.mvcc import KeyValue

    return KeyValue(key=key, value=value, create_revision=rev,
                    mod_revision=rev, version=1)
