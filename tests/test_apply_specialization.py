"""RaftConfig.entry_classes: trace-time removal of the conf-change
apply block from the A-slot apply scan (plus the auto-leave pass and
leave-entry append). Equivalence contract: while only ENTRY_NORMAL
entries commit and the fleet never enters a joint configuration, the
("normal",)-only program reproduces the full program bit-for-bit — the
dropped block was a pure masked no-op replayed on every apply slot."""
import dataclasses

import numpy as np
import jax

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.types import (
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
CFG = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                 inbox_bound=4, coalesce_commit_refresh=True)
C = 4


def _elect(full):
    M, E = SPEC.M, SPEC.E
    state = init_fleet(SPEC, C, seed=0, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, C)
    z2 = np.zeros((M, C), np.int32)
    zp = np.zeros((M, E, C), np.int32)
    no = np.zeros((M, C), bool)
    keep = np.ones((M, M, C), bool)
    hup = no.copy()
    hup[0, :] = True
    state, inbox = full(state, inbox, z2, zp, zp, z2, hup, no, keep)
    for _ in range(12):
        state, inbox = full(state, inbox, z2, zp, zp, z2, no, no, keep)
    assert (np.asarray(state.role)[0] == ROLE_LEADER).all()
    return state, inbox, (z2, zp, no, keep)


def _run_pair(a, b, state0, inbox0, z2, zp, no, keep, rounds=10):
    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = 7
    ptype = zp.copy()
    ptype[0, 0, :] = ENTRY_NORMAL
    sa, ia = state0, inbox0
    sb, ib = state0, inbox0
    for _ in range(rounds):
        sa, ia = a(sa, ia, plen, pdata, ptype, z2, no, no, keep)
        sb, ib = b(sb, ib, plen, pdata, ptype, z2, no, no, keep)
    assert int(np.asarray(sa.commit).min()) >= 8  # really replicating
    for name in sa.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        ), f"state.{name}"
    for name in ia.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(ia, name)), np.asarray(getattr(ib, name))
        ), f"inbox.{name}"


def test_normal_only_apply_program_is_bit_identical():
    full = jax.jit(build_round(CFG, SPEC))
    lean = jax.jit(build_round(
        dataclasses.replace(CFG, entry_classes=("normal",)), SPEC))
    state0, inbox0, (z2, zp, no, keep) = _elect(full)
    _run_pair(full, lean, state0, inbox0, z2, zp, no, keep)


def test_full_bench_stack_with_apply_specialization():
    """entry_classes composes with the whole bench ladder
    (local_steps + message_classes + deferred_emit)."""
    from etcd_tpu.types import MSG_APP, MSG_APP_RESP, MSG_PROP

    full = jax.jit(build_round(CFG, SPEC))
    steady = jax.jit(build_round(
        dataclasses.replace(
            CFG,
            local_steps=("prop",),
            message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP),
            deferred_emit=True,
            entry_classes=("normal",),
        ), SPEC))
    state0, inbox0, (z2, zp, no, keep) = _elect(full)
    _run_pair(full, steady, state0, inbox0, z2, zp, no, keep)


def test_conf_change_still_applies_in_full_program():
    """Sanity guard for the gate itself: the FULL program (default
    entry_classes=None) still applies a committed conf change — i.e.
    the specialization is opt-in, not a silent behavior change."""
    from etcd_tpu.models import confchange as ccmod
    from etcd_tpu.types import CC_REMOVE_NODE

    full = jax.jit(build_round(CFG, SPEC))
    state, inbox, (z2, zp, no, keep) = _elect(full)
    # remove voter 4 via a single change through consensus
    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = ccmod.encode([(CC_REMOVE_NODE, 4)])
    ptype = zp.copy()
    ptype[0, 0, :] = ENTRY_CONF_CHANGE
    state, inbox = full(state, inbox, plen, pdata, ptype, z2, no, no,
                        keep)
    for _ in range(6):
        state, inbox = full(state, inbox, z2, zp, zp, z2, no, no, keep)
    assert not np.asarray(state.voters)[0, 4].any()  # applied on leader
