"""RaftConfig.packed_state / compact_wire: the fleet memory diet.

Equivalence contract (the tentpole's proof obligation): the FULL round
program carried in packed storage (bit-packed int32 lanes + int16 index
planes, models/state.py PackedFleet) and/or with the compacted wire
([bound, to, C] instead of the dense [from, K*to, C]) reproduces the
dense program BIT-FOR-BIT over a scenario that exercises elections,
replication, partitions, read-index waves and ticks — the
tests/test_mesh_equivalence.py scenario style. The chunked packed
program additionally proves the pack/unpack is chunk-local-safe (the
sliced carry is the packed form).

Guard rails: every NodeState field must be classified in the pack plan
(like the crash-durability table), and the bytes/group budget keeps a
future leaf addition from silently re-inflating the resident fleet.
"""
import dataclasses

import numpy as np
import jax
import pytest

from etcd_tpu.models.engine import (
    build_round,
    empty_inbox,
    inbox_bytes_per_group,
    init_fleet,
)
from etcd_tpu.models.state import (
    NodeState,
    pack_fleet,
    pack_plan,
    state_bytes_per_group,
    unpack_fleet,
)
from etcd_tpu.types import ENTRY_NORMAL, ROLE_LEADER, Spec
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=3, L=16, E=1, K=2, W=2, R=2, A=2)
CFG = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2,
                 inbox_bound=4)
C = 16
ROUNDS = 48


def _inputs(r: int):
    """Elections at r=0, proposals on even rounds, a partition window
    long enough that the L=16 ring compacts past the laggard (snapshot
    fallback), one read-index wave, ticks every 3rd round."""
    M, E = SPEC.M, SPEC.E
    hup = np.zeros((M, C), bool)
    if r == 0:
        for c in range(C):
            hup[c % M, c] = True
    plen = np.zeros((M, C), np.int32)
    pdata = np.zeros((M, E, C), np.int32)
    ptype = np.zeros((M, E, C), np.int32)
    if 2 <= r < ROUNDS - 10:
        plen[0, :] = 1
        pdata[0, 0, :] = r * 64 + np.arange(C)
        ptype[0, 0, :] = ENTRY_NORMAL
    ri = np.zeros((M, C), np.int32)
    if r == 24:
        ri[0, :] = 7
    keep = np.ones((M, M, C), bool)
    if 8 <= r < 18:
        keep[1, :, 4:8] = False
        keep[:, 1, 4:8] = False
    tick = np.full((M, C), r % 3 == 0 or r >= ROUNDS - 8, bool)
    return plen, pdata, ptype, ri, hup, tick, keep


def _run(cfg, unpack=False, compact=False):
    round_fn = jax.jit(build_round(cfg, SPEC))
    state = init_fleet(SPEC, C, seed=0, election_tick=cfg.election_tick)
    if cfg.packed_state:
        state = pack_fleet(SPEC, state)
    inbox = empty_inbox(
        SPEC, C, compact_bound=cfg.inbox_bound if cfg.compact_wire else 0)
    states = []
    for r in range(ROUNDS):
        state, inbox = round_fn(state, inbox, *_inputs(r))
        states.append(unpack_fleet(SPEC, state) if cfg.packed_state
                      else state)
    return states


def _assert_trajectories_equal(ref, got, label):
    for r, (a, b) in enumerate(zip(ref, got)):
        for name in NodeState.__dataclass_fields__:
            assert np.array_equal(
                np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
            ), f"{label}: state.{name} diverged at round {r}"


@pytest.fixture(scope="module")
def dense_run():
    states = _run(CFG)
    last = states[-1]
    # the proof only matters if the scenario is rich: steady leaders,
    # deep replication, ring compaction past the partitioned laggard
    role = np.asarray(last.role)
    assert ((role == ROLE_LEADER).sum(axis=0) == 1).all()
    assert (np.asarray(last.snap_index) > 0).any(), "no ring compaction"
    assert int(np.asarray(last.commit).min()) >= 8
    return states


def test_packed_program_is_bit_identical(dense_run):
    got = _run(dataclasses.replace(CFG, packed_state=True))
    _assert_trajectories_equal(dense_run, got, "packed")


def test_packed_chunked_program_is_bit_identical(dense_run):
    """fleet_chunks slices the PACKED carry; unpack/repack happen inside
    the chunk body, so unpacked temps stay chunk-local — and the math
    must not change."""
    got = _run(dataclasses.replace(CFG, packed_state=True, fleet_chunks=2))
    _assert_trajectories_equal(dense_run, got, "packed+chunked")


def test_compact_wire_program_is_bit_identical(dense_run):
    """The boundary-compacted [B, to, C] wire carry is the same messages
    in the same order as scan-entry compaction of the dense carry."""
    got = _run(dataclasses.replace(CFG, compact_wire=True))
    _assert_trajectories_equal(dense_run, got, "compact_wire")


def test_pack_roundtrip_is_exact():
    st = init_fleet(SPEC, 8, seed=3)
    rt = unpack_fleet(SPEC, pack_fleet(SPEC, st))
    for name in NodeState.__dataclass_fields__:
        a, b = np.asarray(getattr(st, name)), np.asarray(getattr(rt, name))
        assert a.dtype == b.dtype, name
        assert np.array_equal(a, b), name


def test_unpack_field_matches_full_unpack():
    """The single-field probe (bench's commit read at scale) must agree
    with the full unpack for every field class: bits, narrow, wide and
    the rng passthrough."""
    from etcd_tpu.models.state import unpack_field

    pk = pack_fleet(SPEC, init_fleet(SPEC, 8, seed=3))
    full = unpack_fleet(SPEC, pk)
    for name in ("commit", "applied_hash", "role", "voters", "log_type",
                 "rng_key"):
        assert np.array_equal(
            np.asarray(unpack_field(SPEC, pk, name)),
            np.asarray(getattr(full, name))), name
    with pytest.raises(KeyError):
        unpack_field(SPEC, pk, "not_a_field")


def test_pack_plan_covers_every_field():
    """A NodeState leaf added without a pack-plan row must fail loudly
    (same enforcement as the crash-durability table): pack_plan raises on
    any coverage gap, so building it IS the check — for several Specs."""
    for spec in (SPEC, Spec(), Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)):
        pack_plan(spec)


def test_packed_timer_lane_validation():
    with pytest.raises(ValueError, match="timer lanes"):
        build_round(
            RaftConfig(election_tick=600, packed_state=True), SPEC)


def test_bytes_per_group_budget():
    """The regression guard: the bench geometry's resident bytes/group,
    computed from the actual leaf dtypes/shapes. A new NodeState or Msg
    leaf that re-inflates the diet past budget fails here instead of
    silently resurrecting the fleet-chunk loop."""
    bench = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    up = state_bytes_per_group(bench)
    pk = state_bytes_per_group(bench, packed=True)
    assert pk <= 1300, f"packed state grew to {pk} B/group"
    assert up / pk >= 2.2, f"state diet ratio fell to {up / pk:.2f}"

    wire_dense = inbox_bytes_per_group(bench, wire_int16=True)
    wire_compact = inbox_bytes_per_group(bench, wire_int16=True,
                                         compact_bound=bench.M - 1)
    assert wire_compact <= 700, f"compact wire grew to {wire_compact}"

    # the headline: total resident bytes/group, diet vs the dense int16
    # fleet (PROFILE.md round-5 census form)
    dense_total = up + wire_dense
    diet_total = pk + wire_compact
    assert dense_total / diet_total >= 2.0, (
        f"fleet diet ratio fell below 2x: {dense_total}/{diet_total}")
