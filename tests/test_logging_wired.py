"""The raft.Logger analog (utils/logging.py) is wired through the host
layers: server events (crash, snapshot install, quota), storage recovery
(torn WAL tail) and embed lifecycle route through the process-wide logger
(raft/logger.go:24-66 + zap_raft.go bridge).
"""
import pytest

from etcd_tpu.utils.logging import (
    DefaultLogger,
    DiscardLogger,
    Logger,
    get_logger,
    set_logger,
)


class CaptureLogger(Logger):
    def __init__(self):
        self.records: list[tuple[str, str]] = []

    def _rec(self, level, fmt, args):
        self.records.append((level, fmt % args if args else fmt))

    def debug(self, fmt, *a):
        self._rec("debug", fmt, a)

    def info(self, fmt, *a):
        self._rec("info", fmt, a)

    def warning(self, fmt, *a):
        self._rec("warning", fmt, a)

    def error(self, fmt, *a):
        self._rec("error", fmt, a)


@pytest.fixture
def capture():
    cap = CaptureLogger()
    old = get_logger()
    set_logger(cap)
    yield cap
    set_logger(old)


def test_set_get_logger_roundtrip(capture):
    assert get_logger() is capture
    assert isinstance(DefaultLogger(), Logger)
    DiscardLogger().warning("dropped %d", 1)  # no-op, no raise


def test_server_crash_and_snapshot_install_log(capture):
    from etcd_tpu.server.kvserver import EtcdCluster

    ec = EtcdCluster()
    ec.ensure_leader()
    ec.put(b"k", b"v")
    ec.stabilize()
    ec.crash_member(1)
    assert any("member 1 crashed" in msg
               for lvl, msg in capture.records if lvl == "warning")
    for i in range(8):
        ec.put(b"g/%d" % i, b"x")
    ec.stabilize()
    ec.restart_member_from_disk(1)
    ec.stabilize()
    assert any("installing peer snapshot on member 1" in msg
               for lvl, msg in capture.records if lvl == "info")


def test_wal_torn_tail_repair_logs(capture, tmp_path):
    from etcd_tpu.storage.wal import WAL

    w = WAL(str(tmp_path / "wal"))
    w.save(hardstate={"term": 1, "vote": 0, "commit": 0},
           entries=[{"index": 1, "term": 1, "data": 7, "type": 0}])
    w.close()
    # tear the tail: chop bytes off the last segment
    import glob
    import os

    seg = sorted(glob.glob(str(tmp_path / "wal" / "*")))[-1]
    size = os.path.getsize(seg)
    with open(seg, "ab") as f:
        f.truncate(size - 3)
    w2 = WAL(str(tmp_path / "wal"))
    w2.read_all()
    assert any("torn wal tail" in msg
               for lvl, msg in capture.records if lvl == "warning")
