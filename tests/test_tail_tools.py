"""Minor-tail components: request tracing (pkg/traceutil), dump tools
(tools/etcd-dump-db, etcd-dump-logs), the L4 tcpproxy gateway
(server/proxy/tcpproxy) and DNS SRV discovery (client/pkg/srv).
"""
import json
import socket
import threading

import pytest


# ---------------------------------------------------------------------------
# traceutil
# ---------------------------------------------------------------------------

def test_trace_steps_and_format():
    import time

    from etcd_tpu.utils.trace import Field, Trace

    t = Trace("put", Field("size", 3))
    t.step("proposed")
    time.sleep(0.01)
    t.step("applied", Field("rev", 7))
    out = t.format()
    assert "put" in out and "{size:3; }" in out
    assert "step proposed" in out and "step applied {rev:7; }" in out
    assert t.duration() >= 0.01


def test_trace_log_threshold(monkeypatch):
    import time

    from etcd_tpu.utils import logging as lg
    from etcd_tpu.utils.trace import Trace

    records = []

    class Cap(lg.Logger):
        def debug(self, f, *a): pass
        def info(self, f, *a): pass
        def warning(self, f, *a): records.append(f % a)
        def error(self, f, *a): pass

    old = lg.get_logger()
    lg.set_logger(Cap())
    try:
        fast = Trace("fast")
        assert not fast.log_if_long(10.0)
        slow = Trace("slow")
        time.sleep(0.02)
        assert slow.log_if_long(0.01)
        assert records and "slow" in records[0]
        assert not Trace.todo().log_if_long(0.0)  # TODO trace never logs
    finally:
        lg.set_logger(old)


def test_trace_add_field_replaces():
    from etcd_tpu.utils.trace import Field, Trace

    t = Trace("x", Field("k", 1))
    t.add_field(Field("k", 2), Field("j", 3))
    assert {f.key: f.value for f in t.fields} == {"k": 2, "j": 3}


# ---------------------------------------------------------------------------
# dump tools
# ---------------------------------------------------------------------------

def test_dump_db_and_logs(tmp_path, capsys):
    from etcd_tpu import dump
    from etcd_tpu.server.mvcc import MVCCStore
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend
    from etcd_tpu.storage.wal import WAL

    # build a small backend with two revisions
    db = str(tmp_path / "m.db")
    be = Backend(db, fresh=True)
    st = MVCCStore()
    txn = st.write_txn()
    txn.put(b"a", b"1")
    txn.end()
    txn = st.write_txn()
    txn.delete_range(b"a")
    txn.end()
    schema.persist_mvcc_delta(be, st, 0)
    schema.save_applied_meta(be, index=2, term=1, store=st, lease_snap=None,
                             auth_snap=None, alarms=[])
    be.commit()
    be.close()

    assert dump.main(["db", "list-bucket", db]) == 0
    buckets = capsys.readouterr().out.split()
    assert "key" in buckets and "meta" in buckets

    assert dump.main(["db", "iterate-bucket", db, "key", "--decode"]) == 0
    out = capsys.readouterr().out
    assert "rev={2/0}" in out and "rev={3/0}" in out
    assert '"tombstone": true' in out

    # WAL dump
    wdir = str(tmp_path / "wal")
    w = WAL(wdir, metadata=b"meta-1")
    w.save_snapshot(0, 0)
    w.save({"term": 1, "vote": 0, "commit": 0},
           [{"index": 1, "term": 1, "data": 11, "type": 0},
            {"index": 2, "term": 1, "data": 22, "type": 1}])
    w.close()
    assert dump.main(["logs", wdir]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["metadata"] == "meta-1"
    assert rep["snapshot"] == {"index": 0, "term": 0}
    assert rep["entry_count"] == 2
    assert rep["entries"][1]["type"] == "conf-change"
    assert rep["hardstate"]["term"] == 1


# ---------------------------------------------------------------------------
# tcpproxy
# ---------------------------------------------------------------------------

def _echo_server():
    """A TCP backend that answers b'pong:' + payload once per connection."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def loop():
        srv.settimeout(5)
        try:
            while True:
                conn, _ = srv.accept()
                data = conn.recv(1024)
                conn.sendall(b"pong:" + data)
                conn.close()
        except OSError:
            pass

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return srv, port


def test_tcpproxy_forwards_and_fails_over():
    from etcd_tpu.tcpproxy import TCPProxy

    srv, port = _echo_server()
    # first endpoint is dead: proxy must inactivate it and fail over
    dead = socket.create_server(("127.0.0.1", 0))
    dead_port = dead.getsockname()[1]
    dead.close()

    proxy = TCPProxy([("127.0.0.1", dead_port), ("127.0.0.1", port)],
                     monitor_interval=0.2).start()
    try:
        for _ in range(2):  # round-robin across picks, both land on live
            with socket.create_connection((proxy.host, proxy.port),
                                          timeout=5) as c:
                c.sendall(b"hi")
                c.settimeout(5)
                assert c.recv(1024) == b"pong:hi"
        assert not proxy.remotes[0].is_active()
        assert proxy.remotes[1].is_active()
    finally:
        proxy.stop()
        srv.close()


# ---------------------------------------------------------------------------
# srv discovery
# ---------------------------------------------------------------------------

def test_srv_get_cluster_and_client():
    from etcd_tpu.srv import SRVRecord, StaticResolver, get_client, get_cluster

    res = StaticResolver({
        ("etcd-server", "tcp", "example.com"): [
            SRVRecord("m0.example.com.", 2380),
            SRVRecord("m1.example.com.", 2380),
            SRVRecord("m2.example.com.", 2380),
        ],
        ("etcd-client", "tcp", "example.com"): [
            SRVRecord("c0.example.com.", 2379),
        ],
        ("etcd-client-ssl", "tcp", "example.com"): [
            SRVRecord("s0.example.com.", 2379),
        ],
    })
    parts = get_cluster(
        res, "http", "etcd-server", "me", "example.com",
        apurls=["http://m1.example.com:2380"],
    )
    assert parts == [
        "0=http://m0.example.com:2380",
        "me=http://m1.example.com:2380",
        "1=http://m2.example.com:2380",
    ]
    cl = get_client(res, "etcd-client", "example.com")
    assert cl["endpoints"] == [
        "https://s0.example.com:2379",
        "http://c0.example.com:2379",
    ]
    with pytest.raises(LookupError):
        get_cluster(res, "http", "nope", "x", "example.com", [])


def test_etcdutl_backup_and_migrate(tmp_path, capsys):
    """etcdutl backup rewrites a loadable copy with a manifest;
    migrate moves the storage-version field both ways with the
    3.6-only-content guard (backup_command.go / migrate_command.go)."""
    import json as _json

    from etcd_tpu import etcdutl
    from etcd_tpu.server.kvserver import EtcdCluster
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    d = str(tmp_path / "data")
    ec = EtcdCluster(n_members=3, data_dir=d)
    ec.ensure_leader()
    ec.put(b"/bk/a", b"1")
    ec.put(b"/bk/b", b"2")
    ec.sync_for_shutdown()

    # backup: copies load cleanly and the manifest matches the source
    bdir = str(tmp_path / "bk")
    assert etcdutl.main(["backup", "--data-dir", d,
                         "--backup-dir", bdir]) == 0
    capsys.readouterr()
    manifest = _json.load(open(f"{bdir}/backup_manifest.json"))
    assert len(manifest) == 3
    assert len({m["hash"] for m in manifest}) == 1  # members agree
    # the backup boots as a working cluster
    ec2 = EtcdCluster.boot_from_disk(bdir, n_members=3, uniform=False)
    ec2.ensure_leader()
    assert ec2.range(b"/bk/a")["kvs"][0].value == b"1"

    # migrate: 3.5 (absent field) -> 3.6 -> back down to 3.5
    assert etcdutl.main(["migrate", "--data-dir", d,
                         "--target-version", "3.6"]) == 0
    out = _json.loads(capsys.readouterr().out)
    assert all(r["changed"] for r in out)
    be = Backend(f"{d}/member0.db")
    assert schema.get_storage_version(be) == "3.6"
    be.close()
    assert etcdutl.main(["migrate", "--data-dir", d,
                         "--target-version", "3.5"]) == 0
    capsys.readouterr()
    be = Backend(f"{d}/member0.db")
    assert schema.get_storage_version(be) is None
    be.close()
    # bad version strings are refused
    assert etcdutl.main(["migrate", "--data-dir", d,
                         "--target-version", "bogus"]) == 1
    assert etcdutl.main(["migrate", "--data-dir", d,
                         "--target-version", "9.9"]) == 1


def test_etcdutl_migrate_downgrade_guard(tmp_path, capsys):
    """An active downgrade record is 3.6-only content: migrating down
    is refused without --force."""
    from etcd_tpu import etcdutl
    from etcd_tpu.server.kvserver import EtcdCluster
    from etcd_tpu.server.version import DowngradeInfo

    d = str(tmp_path / "data")
    ec = EtcdCluster(n_members=1, data_dir=d)
    ec.ensure_leader()
    # plant an active downgrade job BEFORE the persist-triggering write
    ec.members[0].downgrade = DowngradeInfo("3.5.0", True)
    ec.put(b"/k", b"v")
    ec.sync_for_shutdown()
    assert etcdutl.main(["migrate", "--data-dir", d,
                         "--target-version", "3.6"]) == 0
    capsys.readouterr()
    assert etcdutl.main(["migrate", "--data-dir", d,
                         "--target-version", "3.5"]) == 1
    assert "downgrade" in capsys.readouterr().err
    assert etcdutl.main(["migrate", "--data-dir", d,
                         "--target-version", "3.5", "--force"]) == 0
