"""Leader-election behavior, mirroring raft_paper_test.go §5.2 scenarios
(TestLeaderElectionInOneRoundRPC, TestFollowerVote, vote split/recovery) and
raft_test.go's TestLeaderElection, via the lockstep Cluster harness."""
import numpy as np

from etcd_tpu.harness.cluster import Cluster
from etcd_tpu.types import (
    NONE_ID,
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    Spec,
)
from etcd_tpu.utils.config import RaftConfig


def test_single_node_becomes_leader():
    cl = Cluster(n_members=1, spec=Spec(M=1))
    cl.campaign(0)
    cl.stabilize()
    assert cl.leader() == 0
    assert cl.terms()[0] == 1


def test_three_node_election():
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    assert cl.leader() == 0
    assert cl.roles().tolist() == [ROLE_LEADER, ROLE_FOLLOWER, ROLE_FOLLOWER]
    assert cl.terms().tolist() == [1, 1, 1]
    # every node learned the leader
    assert cl.leaf("lead").tolist() == [0, 0, 0]


def test_five_node_election():
    cl = Cluster(n_members=5, spec=Spec(M=5))
    cl.campaign(2)
    cl.stabilize()
    assert cl.leader() == 2


def test_leader_appends_empty_entry_on_election():
    """§5.2/§5.4: a new leader appends a no-op entry at its term; it commits
    once a quorum acks (TestLeaderCommitEntry analog)."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    # empty entry at index 1 replicated + committed everywhere
    assert cl.commits().tolist() == [1, 1, 1]
    for m in range(3):
        assert cl.log_entries(m) == [(1, 0)]


def test_follower_votes_at_most_once_per_term():
    """§5.2: a follower grants at most one vote per term (TestFollowerVote)."""
    cl = Cluster(n_members=3)
    # both 0 and 1 campaign in the same round -> both reach term 1; node 2
    # grants only one vote. Nobody can win a 2-of-3 quorum this round other
    # than via node 2's single vote.
    cl.campaign(0)
    cl.campaign(1)
    cl.stabilize()
    leaders = [m for m in range(3) if cl.roles()[m] == ROLE_LEADER]
    assert len(leaders) <= 1
    votes = cl.leaf("vote")
    # node 2 voted for exactly one of the candidates in term 1
    assert votes[2] in (0, 1)


def test_candidate_with_stale_log_rejected():
    """§5.4.1 (TestVoter/TestLeaderElectionInOneRoundRPC reject cases): a
    candidate with a shorter log cannot win."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    cl.propose(0, 42)
    cl.stabilize()
    assert cl.commits().tolist() == [2, 2, 2]
    # isolate the leader; its log stays the longest
    cl.isolate(0)
    # node 1 and 2 both have the entries; either can win
    cl.campaign(1)
    cl.stabilize()
    assert cl.leader() in (1, 2)

    # now create a cluster where candidate 1 has a stale log: cut 1 off
    # before the proposal instead
    cl2 = Cluster(n_members=3)
    cl2.campaign(0)
    cl2.stabilize()
    cl2.isolate(1)
    cl2.propose(0, 7)
    cl2.stabilize()
    cl2.recover()
    cl2.isolate(0)
    cl2.campaign(1)  # stale log: misses index 2
    cl2.stabilize()
    # 2 must reject 1's vote: 1 cannot become leader
    roles = cl2.roles()
    assert roles[1] != ROLE_LEADER


def test_term_bump_reverts_candidate_to_follower():
    """§5.1: any message with a higher term converts the node to follower."""
    cl = Cluster(n_members=3)
    cl.campaign(0)
    cl.stabilize()
    assert cl.terms().tolist() == [1, 1, 1]
    # partition leader 0 away; 1 campaigns to term 2
    cl.isolate(0)
    cl.campaign(1)
    cl.stabilize()
    assert cl.leader(0) in (1, 2) or cl.roles()[1] == ROLE_CANDIDATE
    cl.recover()
    # old leader hears the new term and steps down
    cl.stabilize(tick=True)
    assert cl.roles()[0] != ROLE_LEADER or cl.terms()[0] >= 2


def test_batched_independent_elections():
    """Two clusters advance independently in the same batch."""
    cl = Cluster(n_members=3, C=2)
    cl.campaign(0, c=0)
    cl.campaign(2, c=1)
    cl.stabilize()
    assert cl.leader(0) == 0
    assert cl.leader(1) == 2
