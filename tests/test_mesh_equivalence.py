"""Sharded-vs-unsharded equivalence + cross-shard invariants.

The reference tests its transport in-process (tests/integration/
cluster.go:126-205 wires members through real rafthttp). The TPU analog's
transport is the mesh sharding of the clusters axis (parallel/mesh.py) —
so the suite must prove that the SAME fleet, stepped through the same
scenario (elections, faults, snapshot catch-up), produces bit-identical
trajectories on 1 device and on the 8-device virtual mesh, in both the
sharding-constraint and the shard_map forms. A sharding bug (wrong axis,
accidental cross-shard leakage, shard-dependent reduction) breaks these
asserts, not just the driver's dryrun."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.parallel.mesh import (
    build_global_invariants,
    build_shard_map_round,
    build_sharded_round,
    make_fleet_mesh,
    shard_fleet,
)
from etcd_tpu.types import ENTRY_NORMAL, ROLE_LEADER, Spec
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=3, L=16, E=1, K=2, W=2, R=2, A=2)
CFG = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=2)
C = 64
ROUNDS = 56


def _inputs(r: int):
    """Per-round inputs: hups at r=0 (member c%M), one proposal per round
    from member 0, ticks every 3rd round (every-round heartbeats would
    compete with appends for the K=2 outbox slots and throttle
    replication to drop-retry speed), and an isolate-member-1 fault on
    clusters [16, 32) for rounds 8..17 — long enough that the ring (L=16)
    compacts past the laggard and heal needs MsgSnap."""
    M, E = SPEC.M, SPEC.E
    hup = np.zeros((M, C), bool)
    if r == 0:
        for c in range(C):
            hup[c % M, c] = True
    plen = np.zeros((M, C), np.int32)
    pdata = np.zeros((M, E, C), np.int32)
    ptype = np.zeros((M, E, C), np.int32)
    if 2 <= r < ROUNDS - 10 and r % 2 == 0:  # quiescing tail at the end
        plen[0, :] = 1
        pdata[0, 0, :] = r * 64 + np.arange(C)
        ptype[0, 0, :] = ENTRY_NORMAL
    ri = np.zeros((M, C), np.int32)
    if r == 20:
        ri[0, :] = 7  # one read-index wave
    keep = np.ones((M, M, C), bool)
    if 8 <= r < 18:
        keep[1, :, 16:32] = False
        keep[:, 1, 16:32] = False
    # quiescing tail ticks every round so heartbeats flush the final
    # commit index to every member
    tick = np.full((M, C), r % 3 == 0 or r >= ROUNDS - 10, bool)
    return plen, pdata, ptype, ri, hup, tick, keep


def _run(round_fn, place=None):
    state = init_fleet(SPEC, C, seed=0, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, C)
    if place is not None:
        state, inbox = place(state, inbox)
    commits = []
    for r in range(ROUNDS):
        plen, pdata, ptype, ri, hup, tick, keep = _inputs(r)
        state, inbox = round_fn(
            state, inbox, plen, pdata, ptype, ri, hup, tick, keep
        )
        commits.append(np.asarray(state.commit).copy())
    return state, inbox, commits


@pytest.fixture(scope="module")
def runs():
    mesh = make_fleet_mesh(8)
    un = _run(jax.jit(build_round(CFG, SPEC)))
    sh = _run(
        build_sharded_round(CFG, SPEC, mesh),
        place=lambda s, i: shard_fleet(mesh, s, i),
    )
    sm = _run(
        build_shard_map_round(CFG, SPEC, mesh),
        place=lambda s, i: shard_fleet(mesh, s, i),
    )
    return un, sh, sm


def test_scenario_is_rich(runs):
    """The equivalence proof only matters if the scenario actually
    exercised elections, replication, faults and snapshot fallback."""
    state, _, commits = runs[0]
    role = np.asarray(state.role)
    assert ((role == ROLE_LEADER).sum(axis=0) == 1).all(), "no steady leader"
    assert (np.asarray(state.snap_index) > 0).any(), "no ring compaction"
    assert (commits[-1] >= 8).all(), "replication too shallow"
    # the faulted block healed: every member converged to within ONE entry
    # of its own cluster's commit front (exact convergence needs fresh
    # appends — heartbeats carry min(match, commit), so the final commit
    # advance rides the next append, as in the reference; clusters are NOT
    # mutually comparable — per-cluster PRNG streams differ)
    spread = commits[-1].max(axis=0) - commits[-1].min(axis=0)
    assert (spread <= 1).all(), "faulted members did not catch up"


def test_sharded_constraint_form_is_bit_identical(runs):
    (s0, i0, c0), (s1, i1, c1), _ = runs
    for r, (a, b) in enumerate(zip(c0, c1)):
        assert np.array_equal(a, b), f"commit diverged at round {r}"
    for name in s0.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(s0, name)), np.asarray(getattr(s1, name))
        ), f"state.{name}"
    for name in i0.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(i0, name)), np.asarray(getattr(i1, name))
        ), f"inbox.{name}"


def test_shard_map_form_is_bit_identical(runs):
    (s0, i0, c0), _, (s2, i2, c2) = runs
    for r, (a, b) in enumerate(zip(c0, c2)):
        assert np.array_equal(a, b), f"commit diverged at round {r}"
    for name in s0.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(s0, name)), np.asarray(getattr(s2, name))
        ), f"state.{name}"
    for name in i0.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(i0, name)), np.asarray(getattr(i2, name))
        ), f"inbox.{name}"


def test_global_invariants_psum_across_shards(runs):
    """The cross-shard checker: clean fleet counts zero; corrupting
    clusters on DIFFERENT devices is summed by the psum, so violations
    can't hide inside a shard."""
    mesh = make_fleet_mesh(8)
    check = build_global_invariants(CFG, SPEC, mesh)
    state, _, commits = runs[1]
    prev = jnp.asarray(commits[-1])
    v = check(state, prev)
    assert int(v.multi_leader) == 0
    assert int(v.hash_mismatch) == 0
    assert int(v.commit_regress) == 0
    # forge a second leader in the leader's term in clusters 3 (shard 0)
    # and 40 (shard 5)
    role = np.array(state.role)  # writable copies
    term = np.array(state.term)
    for c in (3, 40):
        lead = int(np.argmax(role[:, c] == ROLE_LEADER))
        other = (lead + 1) % SPEC.M
        role[other, c] = ROLE_LEADER
        term[other, c] = term[lead, c]
    bad = state.replace(role=jnp.asarray(role), term=jnp.asarray(term))
    v2 = check(shard_fleet(mesh, bad), prev)
    assert int(v2.multi_leader) == 2
    # commit regression is counted per node: claim every commit went up
    v3 = check(state, prev + 1)
    assert int(v3.commit_regress) == SPEC.M * C


# ----------------------------------------------------- 2-D (DCN x ICI)

def test_2d_mesh_form_is_bit_identical(runs):
    """SURVEY §2.3's second axis: the same scenario through a
    (dcn=2, ici=4) mesh — outer splits ride DCN, inner ICI — must be
    bit-identical to the single-device run."""
    from etcd_tpu.parallel.mesh import make_fleet_mesh_2d

    mesh = make_fleet_mesh_2d(2, 4)
    (s0, i0, c0) = runs[0]
    s2, i2, c2 = _run(
        build_shard_map_round(CFG, SPEC, mesh),
        place=lambda s, i: shard_fleet(mesh, s, i),
    )
    for r, (a, b) in enumerate(zip(c0, c2)):
        assert np.array_equal(a, b), f"commit diverged at round {r}"
    for name in s0.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(s0, name)), np.asarray(getattr(s2, name))
        ), f"state.{name}"
    for name in i0.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(i0, name)), np.asarray(getattr(i2, name))
        ), f"inbox.{name}"


def test_2d_mesh_global_invariants_psum(runs):
    """The invariant psum reduces over ICI then DCN and still catches
    violations planted in different 2-D shards."""
    from etcd_tpu.parallel.mesh import make_fleet_mesh_2d

    mesh = make_fleet_mesh_2d(2, 4)
    check = build_global_invariants(CFG, SPEC, mesh)
    state, _, commits = runs[0]
    prev = jnp.asarray(commits[-1])
    v = check(*shard_fleet(mesh, state, prev))
    assert int(v.multi_leader) == 0
    role = np.array(state.role)
    term = np.array(state.term)
    # clusters 1 and 60 land on different DCN rows of the (2, 4) mesh
    for c in (1, 60):
        lead = int(np.argmax(role[:, c] == ROLE_LEADER))
        other = (lead + 1) % SPEC.M
        role[other, c] = ROLE_LEADER
        term[other, c] = term[lead, c]
    bad = state.replace(role=jnp.asarray(role), term=jnp.asarray(term))
    v = check(*shard_fleet(mesh, bad, prev))
    assert int(v.multi_leader) == 2
