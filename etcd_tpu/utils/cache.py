"""Shared persistent-compile-cache setup for entrypoints.

Every entrypoint (bench, chaos_run, test conftest, CLIs) wants the same
thing: the repo-root ``.jax_cache`` directory with zero-threshold
persistence. Entries are machine-specific XLA AOT code — see the
conftest note about wiping the cache after a machine/jaxlib change.
"""
from __future__ import annotations

import os


def configure_compile_cache(root: str | None = None) -> str:
    """Point jax's persistent compilation cache at <repo>/.jax_cache
    (created if needed) and drop the size/time thresholds. Returns the
    cache dir."""
    import jax

    if root is None:
        import etcd_tpu

        root = os.path.dirname(os.path.dirname(os.path.abspath(
            etcd_tpu.__file__
        )))
    cache = os.path.join(root, ".jax_cache")
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    return cache


def entrypoint_platform_setup(force_cpu: bool = False) -> None:
    """The shared CLI-entrypoint preamble (etcdmain / chaos_lease /
    localtester): honor JAX_PLATFORMS — this environment's
    sitecustomize re-pins the accelerator platform at interpreter
    start, overriding the env var, so it must be re-applied AFTER jax
    imports — and point at the persistent compile cache. `force_cpu`
    pins cpu outright for host-tier tools whose C=1 steps would
    otherwise dispatch over an accelerator tunnel per op."""
    import jax

    if force_cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    configure_compile_cache()
