"""pkg/adt interval tree + the auth unified-range permission cache.

adt: interval semantics (affine INF end, point intervals), insert/
delete/find/visit/intersects, and the union-coverage query
(interval_tree.go Contains over unified ranges).

auth: the range_perm_cache parity case the old per-permission check got
wrong — a request spanning two ABUTTING grants must pass, because the
reference checks against merged ranges (range_perm_cache.go:104-120).
"""
import pytest

from etcd_tpu.server.auth import (
    READ,
    READWRITE,
    WRITE,
    AuthStore,
    ErrPermissionDenied,
    Permission,
)
from etcd_tpu.utils import adt


def test_interval_basics():
    ivl = adt.Interval(b"a", b"c")
    assert adt.point(b"k") == adt.Interval(b"k", b"k\x00")
    with pytest.raises(ValueError):
        adt.Interval(b"c", b"a")
    inf = adt.Interval(b"a", adt.INF)
    assert inf.end is adt.INF
    assert ivl.begin == b"a"


def test_tree_insert_find_delete_visit():
    t = adt.IntervalTree()
    t.insert(adt.Interval(b"a", b"c"), 1)
    t.insert(adt.Interval(b"b", b"d"), 2)
    t.insert(adt.Interval(b"x", adt.INF), 3)
    assert len(t) == 3
    assert t.find(adt.Interval(b"b", b"d")) == 2
    assert t.find(adt.Interval(b"b", b"e")) is None
    seen = []
    t.visit(adt.Interval(b"b", b"c"), lambda s, v: seen.append(v))
    assert sorted(seen) == [1, 2]
    assert t.intersects(adt.point(b"zzz"))  # inside [x, INF)
    assert not t.intersects(adt.Interval(b"d", b"e"))
    assert t.delete(adt.Interval(b"a", b"c"))
    assert not t.delete(adt.Interval(b"a", b"c"))
    assert len(t) == 2


def test_union_coverage():
    t = adt.IntervalTree()
    t.insert(adt.Interval(b"a", b"c"))
    t.insert(adt.Interval(b"c", b"e"))   # abutting
    t.insert(adt.Interval(b"f", b"h"))   # gap at [e, f)
    assert t.contains(adt.Interval(b"a", b"e"))      # spans the merge
    assert t.contains(adt.Interval(b"b", b"d"))
    assert not t.contains(adt.Interval(b"a", b"g"))  # crosses the gap
    assert not t.contains(adt.Interval(b"e", b"f"))
    assert t.union() == [adt.Interval(b"a", b"e"), adt.Interval(b"f", b"h")]
    t.insert(adt.Interval(b"e", b"f"))
    assert t.contains(adt.Interval(b"a", b"h"))      # gap closed


def _store_with(perms):
    a = AuthStore()
    a.user_add("root", "pw")
    a.role_add("root")
    a.user_grant_role("root", "root")
    a.user_add("u", "pw")
    a.role_add("r")
    for p in perms:
        a.role_grant_permission("r", p)
    a.user_grant_role("u", "r")
    a.auth_enable()
    return a


def test_auth_unified_ranges_allow_spanning_request():
    a = _store_with([
        Permission(READ, b"a", b"c"),
        Permission(READ, b"c", b"e"),
    ])
    # the reference merges [a,c)+[c,e) -> [a,e): the spanning range reads
    a.check_user("u", b"a", b"e", write=False)
    a.check_user("u", b"b", None, write=False)
    with pytest.raises(ErrPermissionDenied):
        a.check_user("u", b"a", b"f", write=False)
    with pytest.raises(ErrPermissionDenied):
        a.check_user("u", b"a", b"c", write=True)  # READ grant only


def test_auth_perm_cache_invalidates_on_revision():
    a = _store_with([Permission(READWRITE, b"k", None)])
    a.check_user("u", b"k", None, write=True)
    a.role_revoke_permission("r", b"k", None)
    with pytest.raises(ErrPermissionDenied):
        a.check_user("u", b"k", None, write=True)


def test_auth_open_ended_and_write_grants():
    a = _store_with([
        Permission(WRITE, b"w", b"\x00"),   # [w, INF)
        Permission(READ, b"r", None),       # point
    ])
    a.check_user("u", b"zzz", None, write=True)
    a.check_user("u", b"w", b"\x00", write=True)
    a.check_user("u", b"r", None, write=False)
    with pytest.raises(ErrPermissionDenied):
        a.check_user("u", b"zzz", None, write=False)  # WRITE-only grant
