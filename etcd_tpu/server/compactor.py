"""Auto-compaction: periodic and revision modes.

The reference's v3compactor (server/etcdserver/api/v3compactor) runs one
of two policies behind the ``--auto-compaction-mode`` flag:
  * periodic: every interval, compact to the revision observed one
    retention window ago (periodic.go's revolving sample wheel);
  * revision: every 5 minutes, compact to (current - retention)
    revisions (revision.go).

Here the compactor is tick-driven (the host tick loop is the clock) and
proposes the same replicated ``compact`` request a client would.
"""
from __future__ import annotations

from etcd_tpu.server.kvserver import EtcdCluster, ServerError


class Compactor:
    def __init__(self, ec: EtcdCluster, mode: str = "off",
                 retention: int = 0, interval_ticks: int = 10):
        """mode: "off" | "periodic" (retention = ticks of history kept)
        | "revision" (retention = revisions kept)."""
        if mode not in ("off", "periodic", "revision"):
            raise ValueError(f"unknown auto-compaction mode {mode}")
        self.ec = ec
        self.mode = mode
        self.retention = retention
        self.interval = max(interval_ticks, 1)
        self._ticks = 0
        self._samples: list[tuple[int, int]] = []  # (tick, rev)
        self.last_compacted = 0

    def tick(self) -> None:
        if self.mode == "off" or self.retention <= 0:
            return
        self._ticks += 1
        if self._ticks % self.interval:
            return
        try:
            lead = self.ec.leader()
            if lead < 0:
                return
            rev = self.ec.members[lead].store.kv.current_rev
            if self.mode == "revision":
                target = rev - self.retention
            else:  # periodic: compact to the revision seen `retention`
                # ticks ago (the sample wheel)
                self._samples.append((self._ticks, rev))
                cutoff = self._ticks - self.retention
                old = [r for t, r in self._samples if t <= cutoff]
                self._samples = [
                    (t, r) for t, r in self._samples if t > cutoff
                ]
                target = old[-1] if old else 0
            if target > self.last_compacted:
                self.ec.compact(target)
                self.last_compacted = target
        except ServerError:
            pass  # no quorum right now; retry next interval
