"""WAL record codec binding: C++ fast path + pure-Python fallback.

The framing matches native/walcodec.cpp (and mirrors the reference's
wal/encoder.go:124 record layout): ``u32 len | u8 type | u32 crc | payload |
pad8`` with a chained CRC32 so decode stops at the first torn/corrupt frame
(wal/repair.go behavior). The shared object is built on first use with g++
(this image has no pybind11; ctypes over a C ABI is the bridge).
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import zlib

_HEADER = struct.Struct("<IBI")  # len, type, crc


def _pad8(n: int) -> int:
    return (n + 7) & ~7


HEADER_SIZE = _HEADER.size


def first_frame_bytes_needed(header: bytes) -> int | None:
    """Total on-disk size of a frame whose first ``HEADER_SIZE`` bytes are
    ``header`` (None if the header itself is short) — lets a caller probe
    a segment's first frame without reading the whole file."""
    if len(header) < _HEADER.size:
        return None
    return _HEADER.size + _pad8(_HEADER.unpack_from(header, 0)[0])


def tail_chains_cleanly(buf, off: int) -> bool:
    """Whether the bytes at ``off`` parse as one or more COMPLETE frames
    whose chained crcs are self-consistent (the first frame's stored crc
    taken as the chain seed — the chain up to here is broken, so it
    cannot be verified absolutely) and end exactly at EOF. That is the
    signature of real fsync'd records surviving PAST a corrupt frame
    (bit rot), as opposed to the unstructured junk a torn append leaves;
    WAL.read_all uses it to keep mid-segment rot loud while still
    repairing a genuinely torn tail."""
    n = len(buf)
    if off >= n:
        return False
    crc = None
    while off < n:
        if n - off < _HEADER.size:
            return False
        plen, _, want = _HEADER.unpack_from(buf, off)
        total = _HEADER.size + _pad8(plen)
        if n - off < total:
            return False
        if crc is not None:
            payload = bytes(buf[off + _HEADER.size:off + _HEADER.size + plen])
            if zlib.crc32(payload, crc) != want:
                return False
        crc = want
        off += total
    return True


def frame_is_incomplete(buf, off: int) -> bool:
    """Whether the bytes at ``off`` cannot hold a complete frame — the
    buffer ends mid-record, the signature of a torn append (segments are
    plain appends, never preallocated, so a crash tears at EOF). A
    COMPLETE frame that fails its CRC is the other way decode returns
    None, and means bit rot on durable bytes, not a tear — the caller
    (WAL.read_all) uses the distinction to keep mid-log corruption loud.
    """
    remaining = len(buf) - off
    if remaining < _HEADER.size:
        return True
    plen = _HEADER.unpack_from(buf, off)[0]
    return remaining < _HEADER.size + _pad8(plen)


class _PyCodec:
    """Fallback codec (identical framing)."""

    @staticmethod
    def encode(rtype: int, payload: bytes, crc: int) -> tuple[bytes, int]:
        crc = zlib.crc32(payload, crc)
        frame = _HEADER.pack(len(payload), rtype, crc) + payload
        frame += b"\x00" * (_pad8(len(payload)) - len(payload))
        return frame, crc

    @staticmethod
    def decode(buf: memoryview, off: int, crc: int):
        """(consumed, rtype, payload, crc) or None on torn/corrupt frame."""
        if len(buf) - off < _HEADER.size:
            return None
        plen, rtype, want_crc = _HEADER.unpack_from(buf, off)
        total = _HEADER.size + _pad8(plen)
        if len(buf) - off < total:
            return None
        payload = bytes(buf[off + _HEADER.size : off + _HEADER.size + plen])
        crc = zlib.crc32(payload, crc)
        if crc != want_crc:
            return None
        return total, rtype, payload, crc


class _NativeCodec:
    def __init__(self, lib: ctypes.CDLL):
        self.lib = lib
        lib.wal_encode.restype = ctypes.c_uint64
        lib.wal_decode.restype = ctypes.c_uint64
        lib.wal_frame_size.restype = ctypes.c_uint64
        lib.wal_crc32.restype = ctypes.c_uint32

    def encode(self, rtype: int, payload: bytes, crc: int) -> tuple[bytes, int]:
        out = ctypes.create_string_buffer(
            int(self.lib.wal_frame_size(ctypes.c_uint64(len(payload))))
        )
        crc_io = ctypes.c_uint32(crc)
        n = self.lib.wal_encode(
            ctypes.c_uint8(rtype), payload, ctypes.c_uint64(len(payload)),
            ctypes.byref(crc_io), out,
        )
        return out.raw[: int(n)], crc_io.value

    def decode(self, buf, off: int, crc: int):
        """Zero-copy: pass base+off into the C ABI directly (a per-record
        bytes(buf[off:]) copy would make segment replay O(n^2))."""
        if not isinstance(buf, bytes):
            buf = bytes(buf)  # memoryview callers pay one conversion
        base = ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p).value
        crc_io = ctypes.c_uint32(crc)
        ty = ctypes.c_uint8()
        poff = ctypes.c_uint64()
        plen = ctypes.c_uint64()
        n = self.lib.wal_decode(
            ctypes.c_void_p(base + off), ctypes.c_uint64(len(buf) - off),
            ctypes.byref(crc_io), ctypes.byref(ty), ctypes.byref(poff),
            ctypes.byref(plen),
        )
        if n == 0:
            return None
        payload = buf[off + poff.value : off + poff.value + plen.value]
        return int(n), ty.value, payload, crc_io.value


def _build_native():
    src = os.path.join(os.path.dirname(__file__), "..", "..", "native", "walcodec.cpp")
    src = os.path.abspath(src)
    if not os.path.exists(src):
        return None
    so = os.path.join(os.path.dirname(src), "libwalcodec.so")
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", so, src],
                check=True, capture_output=True, timeout=120,
            )
        except Exception:
            return None
    try:
        return _NativeCodec(ctypes.CDLL(so))
    except OSError:
        return None


_codec = None


def get_codec():
    global _codec
    if _codec is None:
        _codec = _build_native() or _PyCodec()
    return _codec


def is_native() -> bool:
    return isinstance(get_codec(), _NativeCodec)
