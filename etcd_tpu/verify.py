"""Offline data-dir invariant checker (server/verify/verify.go:50,92).

The reference's verify package cross-checks a stopped member's WAL
against its backend: the backend's consistent index must fall inside the
WAL's entry range, and internal cursors must agree. Here the checks run
over the backend files the TPU runtime writes:

  per member:
    * the record log replays cleanly (CRC chain; a torn tail is repaired
      on open, anything else is corruption);
    * an applied-meta record exists and its cursors are coherent
      (current_rev >= compact_rev, consistent index >= 0);
    * every persisted revision <= current_rev has intact keyIndex
      generations (load_mvcc replays them; a gap raises).
  across members:
    * any two members whose persisted state reached the same revision
      must agree on hash_kv — the offline form of the KV_HASH checker.

Usage:
    python -m etcd_tpu.verify --data-dir D
"""
from __future__ import annotations

import argparse
import glob
import os
import sys


class VerifyError(Exception):
    pass


def verify_member(path: str) -> dict:
    from etcd_tpu.storage import schema
    from etcd_tpu.storage.backend import Backend

    be = Backend(path)
    meta = schema.load_applied_meta(be)
    if meta is None:
        # an empty/new backend is legal (no applies yet)
        return {"path": path, "consistent_index": 0, "revision": 1,
                "hash": None}
    ci = meta["consistent_index"]
    if ci < 0:
        raise VerifyError(f"{path}: negative consistent index {ci}")
    if meta["current_rev"] < meta["compact_rev"]:
        raise VerifyError(
            f"{path}: current_rev {meta['current_rev']} < compact_rev "
            f"{meta['compact_rev']}"
        )
    try:
        store = schema.load_mvcc(
            be, max_rev=meta["current_rev"],
            compact_rev=meta["compact_rev"],
        )
    except Exception as e:
        raise VerifyError(f"{path}: revision replay failed: {e}") from e
    return {
        "path": path,
        "consistent_index": ci,
        "term": meta["term"],
        "revision": store.current_rev,
        "hash": store.hash_kv(),
    }


def verify_data_dir(data_dir: str) -> list[dict]:
    reports = []
    for path in sorted(glob.glob(os.path.join(data_dir, "member*.db"))):
        reports.append(verify_member(path))
    # cross-member: equal revision => equal hash (KV_HASH, offline)
    by_rev: dict[int, tuple[str, int]] = {}
    for r in reports:
        if r["hash"] is None:
            continue
        seen = by_rev.get(r["revision"])
        if seen is not None and seen[1] != r["hash"]:
            raise VerifyError(
                f"hash divergence at revision {r['revision']}: "
                f"{seen[0]}={seen[1]} vs {r['path']}={r['hash']}"
            )
        by_rev[r["revision"]] = (r["path"], r["hash"])
    return reports


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="etcd-tpu-verify")
    p.add_argument("--data-dir", required=True)
    args = p.parse_args(argv)
    try:
        reports = verify_data_dir(args.data_dir)
    except VerifyError as e:
        print(f"VERIFY FAILED: {e}", file=sys.stderr)
        return 1
    for r in reports:
        print(r)
    print(f"verified {len(reports)} member backend(s): OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
