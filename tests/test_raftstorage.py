"""MemoryStorage contract tests — transliterated from raft/storage_test.go
(TestStorageTerm/Entries/LastIndex/FirstIndex/Compact/Append/
ApplySnapshot/CreateSnapshot) with the reference's error taxonomy
(raft/storage.go:24-38).

Mapping note: the reference keeps a dummy entry at ents[0] marking the
snapshot boundary ({Index:3, Term:3} in its shared fixture); our
MemoryStorage stores that boundary in the snapshot metadata instead, so
the fixture here is snap=(index 3, term 3) + real entries from 4 on.
Member ids are 0-based.
"""
import pytest

from etcd_tpu.storage.raftstorage import (
    ConfState,
    Entry,
    ErrCompacted,
    ErrSnapOutOfDate,
    ErrUnavailable,
    MemoryStorage,
    Snapshot,
    SnapshotMeta,
)

E4, E5, E6 = Entry(4, 4), Entry(5, 5), Entry(6, 6)


def make(ents=(E4, E5)):
    s = MemoryStorage()
    s.apply_snapshot(Snapshot(meta=SnapshotMeta(index=3, term=3)))
    s.ents = list(ents)
    return s


# -- TestStorageTerm ---------------------------------------------------------
@pytest.mark.parametrize(
    "i,want,err",
    [
        (2, 0, ErrCompacted),
        (3, 3, None),  # snapshot boundary (the reference's dummy entry)
        (4, 4, None),
        (5, 5, None),
        (6, 0, ErrUnavailable),
    ],
)
def test_storage_term(i, want, err):
    s = make()
    if err:
        with pytest.raises(err):
            s.term(i)
    else:
        assert s.term(i) == want


# -- TestStorageEntries ------------------------------------------------------
@pytest.mark.parametrize(
    "lo,hi,maxe,want,err",
    [
        (2, 6, None, None, ErrCompacted),
        (3, 4, None, None, ErrCompacted),
        (4, 5, None, [E4], None),
        (4, 6, None, [E4, E5], None),
        (4, 7, None, [E4, E5, E6], None),
        (4, 8, None, None, ErrUnavailable),
        (4, 7, 1, [E4], None),
        (4, 7, 2, [E4, E5], None),
    ],
)
def test_storage_entries(lo, hi, maxe, want, err):
    s = make((E4, E5, E6))
    if err:
        with pytest.raises(err):
            s.entries(lo, hi, maxe)
    else:
        assert s.entries(lo, hi, maxe) == want


# -- TestStorageLastIndex / TestStorageFirstIndex ----------------------------
def test_storage_first_last_index():
    s = make()
    assert s.first_index() == 4
    assert s.last_index() == 5
    s.append([Entry(6, 5)])
    assert s.last_index() == 6
    s.compact(4)
    assert s.first_index() == 5
    assert s.last_index() == 6


# -- TestStorageCompact ------------------------------------------------------
@pytest.mark.parametrize(
    "i,windex,wterm,wlen,err",
    [
        (2, 3, 3, 3, ErrCompacted),
        (3, 3, 3, 3, ErrCompacted),
        (4, 4, 4, 2, None),
        (5, 5, 5, 1, None),
    ],
)
def test_storage_compact(i, windex, wterm, wlen, err):
    s = make()
    if err:
        with pytest.raises(err):
            s.compact(i)
    else:
        s.compact(i)
        # windex/wterm describe the truncation boundary (the reference's
        # dummy entry); wlen counts the dummy, so real entries are wlen-1
        assert s.first_index() == windex + 1
        assert s.term(windex) == wterm
        assert len(s.ents) == wlen - 1
        # the retained snapshot is untouched by compaction
        assert s.snap.meta.index == 3


# -- TestStorageAppend -------------------------------------------------------
@pytest.mark.parametrize(
    "ents,want",
    [
        # all compacted away: no-op
        ([Entry(1, 1), Entry(2, 2)], [E4, E5]),
        # overlap incl. the compacted boundary: prefix truncated away
        ([Entry(3, 3), Entry(4, 4), Entry(5, 5)], [E4, E5]),
        # conflict overwrite
        ([Entry(3, 3), Entry(4, 6), Entry(5, 6)],
         [Entry(4, 6), Entry(5, 6)]),
        # extend past the end
        ([Entry(3, 3), Entry(4, 4), Entry(5, 5), Entry(6, 5)],
         [E4, E5, Entry(6, 5)]),
        # overwrite mid-log truncates the tail
        ([Entry(4, 5)], [Entry(4, 5)]),
        ([Entry(5, 8)], [E4, Entry(5, 8)]),
    ],
)
def test_storage_append(ents, want):
    s = make()
    s.append(ents)
    assert s.ents == want


def test_storage_append_gap_raises():
    s = make()
    with pytest.raises(ErrUnavailable):
        s.append([Entry(8, 5)])


# -- TestStorageApplySnapshot ------------------------------------------------
def test_storage_apply_snapshot():
    cs = ConfState(voters=(0, 1, 2))
    s = MemoryStorage()
    s.apply_snapshot(
        Snapshot(meta=SnapshotMeta(index=4, term=4, conf_state=cs))
    )
    assert s.first_index() == 5 and s.last_index() == 4
    # out-of-date snapshot is refused
    with pytest.raises(ErrSnapOutOfDate):
        s.apply_snapshot(
            Snapshot(meta=SnapshotMeta(index=3, term=3, conf_state=cs))
        )


# -- TestStorageCreateSnapshot -----------------------------------------------
def test_storage_create_snapshot():
    cs = ConfState(voters=(0, 1, 2))
    s = make()
    snap = s.create_snapshot(4, cs, data=(7,))
    assert snap.meta.index == 4 and snap.meta.term == 4
    assert snap.meta.conf_state == cs and snap.data == (7,)
    # entries retained until an explicit compact (matching the reference)
    assert s.last_index() == 5 and len(s.ents) == 2
    with pytest.raises(ErrSnapOutOfDate):
        s.create_snapshot(3, cs)
    with pytest.raises(ErrUnavailable):
        s.create_snapshot(9, cs)
