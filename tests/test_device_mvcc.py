"""Device-resident MVCC apply plane: differential fuzz + integration.

The equivalence contract (ISSUE 7 / ROADMAP "Device-resident apply
plane"): the device revision store (etcd_tpu/device_mvcc) applied over a
committed word stream must be indistinguishable — under the shared
canonical digest, the revision cursors, the per-key latest records and
the compaction-boundary errors — from the host ``MVCCStore`` replaying
the same schedule.  The fuzz harness (device_mvcc/fuzz.py) runs each
GROUP of the batched store as its own randomized schedule, so one device
dispatch checks hundreds of schedules; the 4096-group acceptance shape
rides behind the ``slow`` marker (tier-1 stays fast), with the fast tier
covering the same code paths at small shapes.

Also covered here: the engine integration (build_kv_round consuming the
apply frontier; one trace serving host-apply and device-apply via the
do_apply operand), the kvserver device plane (DeviceBackedStore facade:
puts/txns/compaction/watch/hash through a real EtcdCluster), the watch
delta fan-out, and the APPLY_* knob validation exit codes of bench.py
and chaos_run.py.
"""
from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from etcd_tpu.device_mvcc import (
    KVSpec,
    apply_words,
    init_kv,
    kv_digest,
    read_at,
    scheme,
)
from etcd_tpu.device_mvcc.apply import _record_mix
from etcd_tpu.device_mvcc.fuzz import differential_run, gen_schedules, host_replay

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- codec


def test_word_codec_roundtrip():
    rng = np.random.default_rng(0)
    for _ in range(200):
        kid = int(rng.integers(scheme.MAX_KEYS + 1))
        val = int(rng.integers(scheme.MAX_VAL + 1))
        lease = int(rng.integers(scheme.MAX_LEASE + 1))
        w = scheme.encode_put(kid, val, lease, cont=bool(rng.integers(2)))
        d = scheme.decode(w)
        assert (d["kind"], d["key"], d["val"], d["lease"]) == (
            scheme.KIND_PUT, kid, val, lease)
        lo = int(rng.integers(scheme.MAX_KEYS + 1))
        hi = int(rng.integers(lo, (1 << scheme.HI_BITS)))
        d = scheme.decode(scheme.encode_delete_range(lo, hi))
        assert (d["kind"], d["lo"], d["hi"]) == (scheme.KIND_DELETE, lo, hi)
        rev = int(rng.integers(scheme.MAX_COMPACT_REV + 1))
        d = scheme.decode(scheme.encode_compact(rev))
        assert (d["kind"], d["rev"]) == (scheme.KIND_COMPACT, rev)
    # words stay positive int32 (and off the int16 wire by design)
    assert scheme.encode_put(scheme.MAX_KEYS, scheme.MAX_VAL,
                             scheme.MAX_LEASE) < 2 ** 31
    with pytest.raises(ValueError):
        scheme.encode_put(scheme.MAX_KEYS + 1, 0)
    with pytest.raises(ValueError):
        scheme.encode_compact(scheme.MAX_COMPACT_REV + 1)


def test_canonical_key_value_codecs():
    for kid in (0, 7, 511):
        assert scheme.key_id(scheme.key_bytes(kid)) == kid
    for v in (0, 1, 4095):
        assert scheme.decode_value(scheme.encode_value(v)) == v
    with pytest.raises(ValueError):
        scheme.key_id(b"not-canonical")
    with pytest.raises(ValueError):
        scheme.decode_value(b"zzz")


def test_record_mix_cross_check():
    """The python fold (scheme.record_mix, the host half) and the jnp
    fold (apply._record_mix, the device half) must be bit-congruent —
    this is what makes 'the same digest' literal."""
    rng = np.random.default_rng(1)
    n = 64
    key = rng.integers(0, 512, n).astype(np.int32)
    mod = rng.integers(0, 1 << 24, n).astype(np.int32)
    create = rng.integers(0, 1 << 24, n).astype(np.int32)
    version = rng.integers(0, 1 << 16, n).astype(np.int32)
    vword = rng.integers(0, 4096, n).astype(np.int32)
    lease = rng.integers(0, 16, n).astype(np.int32)
    tomb = rng.integers(0, 2, n).astype(bool)
    dev = np.asarray(_record_mix(
        jnp.asarray(key), jnp.asarray(mod), jnp.asarray(create),
        jnp.asarray(version), jnp.asarray(vword), jnp.asarray(lease),
        jnp.asarray(tomb),
    ))
    for i in range(n):
        assert int(dev[i]) == scheme.record_mix(
            int(key[i]), int(mod[i]), int(create[i]), int(version[i]),
            int(vword[i]), int(lease[i]), bool(tomb[i]))


# ------------------------------------------------------- differential fuzz


def test_differential_fuzz_fast():
    """128 independent randomized schedules (puts incl. multi-op CONT
    txns, point/interval/to-end deletes, valid + boundary-violating
    compactions) — full parity on digest, cursors, error lanes and
    per-key records."""
    rep = differential_run(KVSpec(keys=16), groups=128, ops=60, seed=2)
    assert rep["parity_ok"], rep


def test_differential_fuzz_wide_keyspace():
    rep = differential_run(KVSpec(keys=64), groups=32, ops=80, seed=3)
    assert rep["parity_ok"], rep


def test_fuzz_exercises_all_op_classes():
    """The generator must actually cover tombstones, compactions and
    multi-op txns, or the parity gates above prove less than claimed."""
    kvspec = KVSpec(keys=16)
    words = gen_schedules(kvspec, 64, 60, seed=2)
    kinds = words & 3
    assert (kinds == scheme.KIND_PUT).any()
    assert (kinds == scheme.KIND_DELETE).any()
    assert (kinds == scheme.KIND_COMPACT).any()
    assert ((words & scheme.CONT_BIT) != 0).any()
    # and the error lanes actually fire somewhere in the batch
    st = apply_words(kvspec, init_kv(kvspec, 64), words)
    assert int(np.asarray(st.err_compacted).sum()) > 0
    assert int(np.asarray(st.err_future).sum()) > 0
    # tombstones survive until compaction in at least some group
    assert bool(np.asarray(st.tomb).any())


@pytest.mark.slow
def test_differential_fuzz_acceptance_4096():
    """The acceptance-scale shape: >=100 randomized schedules at >=4096
    groups (every group IS a distinct schedule; all 4096 host-replayed),
    compaction + tombstones included — hash_kv parity via the shared
    canonical digest."""
    rep = differential_run(KVSpec(keys=64), groups=4096, ops=120, seed=8)
    assert rep["checked"] == 4096
    assert rep["parity_ok"], rep


# ----------------------------------------------------- targeted semantics


def _one_lane(kvspec, words):
    st = apply_words(kvspec, init_kv(kvspec, 1),
                     np.asarray(words, np.int32)[:, None])
    return jax.tree.map(np.asarray, st)


def test_multi_op_txn_revision_semantics():
    """CONT words share one revision main (WriteTxn semantics): two puts
    in one txn bump version twice at one revision; delete-then-put in a
    txn opens a fresh generation at the same main."""
    kvspec = KVSpec(keys=8)
    st = _one_lane(kvspec, [
        scheme.encode_put(1, 10),                       # rev 2
        scheme.encode_put(1, 11),                       # rev 3
        scheme.encode_put(1, 12, cont=False),           # rev 4 op 1
        scheme.encode_put(1, 13, cont=True),            # rev 4 op 2
        scheme.encode_delete_range(1, 2, cont=False),   # rev 5 op 1
        scheme.encode_put(1, 14, cont=True),            # rev 5 op 2
    ])
    assert int(st.current_rev[0]) == 5
    assert int(st.mod[1, 0]) == 5
    assert int(st.create[1, 0]) == 5      # fresh generation post-tombstone
    assert int(st.version[1, 0]) == 1
    assert not bool(st.tomb[1, 0])
    # the same schedule through the host store agrees record-for-record
    store, _, _ = host_replay(kvspec, np.asarray([
        scheme.encode_put(1, 10), scheme.encode_put(1, 11),
        scheme.encode_put(1, 12), scheme.encode_put(1, 13, cont=True),
        scheme.encode_delete_range(1, 2), scheme.encode_put(1, 14, cont=True),
    ], np.int32))
    assert store.current_rev == 5
    kvs, _, _ = store.range(scheme.key_bytes(1))
    assert (kvs[0].mod_revision, kvs[0].create_revision, kvs[0].version) == (
        5, 5, 1)


def test_cont_after_compact_opens_fresh_txn():
    """A compact closes the open txn (txn_main lane resets), so a CONT
    word right after it — or as the first word ever — opens a fresh txn
    instead of binding a stale/zero main (review finding: the guard
    lives in apply_word, not in every word producer)."""
    kvspec = KVSpec(keys=8)
    words = np.asarray([
        scheme.encode_put(0, 1),               # rev 2
        scheme.encode_compact(2),              # closes the txn
        scheme.encode_put(1, 2, cont=True),    # must open rev 3, not rev 2
    ], np.int32)
    st = _one_lane(kvspec, words)
    assert int(st.current_rev[0]) == 3
    assert int(st.mod[1, 0]) == 3
    store, _, _ = host_replay(kvspec, words)
    assert scheme.store_latest_digest(store, 8) == int(
        np.asarray(kv_digest(kvspec, apply_words(
            kvspec, init_kv(kvspec, 1), words[:, None])))[0])
    # first-ever word carrying CONT: no open txn -> fresh main, and the
    # revision cursor never regresses below the boot value
    st = _one_lane(kvspec, [scheme.encode_put(0, 1, cont=True)])
    assert int(st.current_rev[0]) == 2
    assert int(st.mod[0, 0]) == 2


def test_device_txn_rejects_out_of_space_key():
    """A canonical key beyond the configured key space must fail BEFORE
    dispatch — no phantom revision on the device lane (review finding)."""
    from etcd_tpu.device_mvcc import DevicePlane
    from etcd_tpu.server.mvcc import DeviceBackedStore

    store = DeviceBackedStore(DevicePlane(KVSpec(keys=8)))
    txn = store.write_txn()
    with pytest.raises(ValueError, match="key space"):
        txn.put(scheme.key_bytes(20), scheme.encode_value(1))
    assert store.current_rev == 1          # nothing stamped
    assert store.plane.records(0) == {}


def test_device_snapshot_preserves_multi_key_revisions():
    """Records sharing one revision main (multi-op txn, multi-key
    delete-range) must all survive to_snapshot/restore — the (mod, sub)
    keying collision of the first facade cut (review finding)."""
    from etcd_tpu.device_mvcc import DevicePlane
    from etcd_tpu.server.mvcc import DeviceBackedStore, MVCCStore

    store = DeviceBackedStore(DevicePlane(KVSpec(keys=8)))
    txn = store.write_txn()
    txn.put(scheme.key_bytes(2), scheme.encode_value(5))
    txn.put(scheme.key_bytes(3), scheme.encode_value(6))
    txn.end()
    assert len(store.revs) == 2            # distinct (mod, sub) keys
    host = MVCCStore.from_snapshot(store.to_snapshot())
    kvs, _, _ = host.range(scheme.key_bytes(2))
    assert kvs[0].key == scheme.key_bytes(2)
    assert kvs[0].value == scheme.encode_value(5)
    kvs, _, _ = host.range(scheme.key_bytes(3))
    assert kvs[0].value == scheme.encode_value(6)


def test_compaction_boundary_errors_and_gc():
    kvspec = KVSpec(keys=8)
    st = _one_lane(kvspec, [
        scheme.encode_put(0, 1),            # rev 2
        scheme.encode_put(1, 2),            # rev 3
        scheme.encode_delete_range(0, 1),   # rev 4 (tombstone key 0)
        scheme.encode_compact(9),           # > current -> ErrFutureRev
        scheme.encode_compact(3),           # ok; tombstone at 4 survives
        scheme.encode_compact(3),           # <= compact_rev -> ErrCompacted
        scheme.encode_compact(4),           # ok; tombstoned key 0 drops
    ])
    assert int(st.err_future[0]) == 1
    assert int(st.err_compacted[0]) == 1
    assert int(st.compact_rev[0]) == 4
    assert not bool(st.present[0, 0])      # whole key compacted away
    assert bool(st.present[1, 0])          # live key keeps its record


def test_read_at_window_semantics():
    """read_at mirrors _check_rev's window errors; a key modified past
    the requested rev is flagged unservable (the latest-only contract),
    never served wrong."""
    kvspec = KVSpec(keys=4)
    words = [scheme.encode_put(0, 1),   # rev 2
             scheme.encode_put(1, 2),   # rev 3
             scheme.encode_put(0, 3),   # rev 4
             scheme.encode_compact(3)]
    st = apply_words(kvspec, init_kv(kvspec, 1),
                     np.asarray(words, np.int32)[:, None])
    vis, unserv, err_f, err_c = jax.tree.map(
        np.asarray, read_at(kvspec, st, 3))
    assert not err_f[0] and not err_c[0]
    assert bool(vis[1, 0]) and not bool(vis[0, 0])
    assert bool(unserv[0, 0])            # key 0 moved at rev 4
    _, _, err_f, _ = jax.tree.map(np.asarray, read_at(kvspec, st, 99))
    assert bool(err_f[0])
    _, _, _, err_c = jax.tree.map(np.asarray, read_at(kvspec, st, 2))
    assert bool(err_c[0])                # below the compaction floor
    vis, unserv, err_f, err_c = jax.tree.map(
        np.asarray, read_at(kvspec, st, 0))  # current: always exact
    assert not err_f[0] and not err_c[0] and not unserv.any()
    assert bool(vis[0, 0]) and bool(vis[1, 0])


def test_watch_delta_extraction_parity():
    """Per-round device deltas, fanned out through the host converter,
    agree with a host watcher's view of the same schedule — up to the
    documented revision-coalescing (one event per key per round carrying
    the newest record)."""
    from etcd_tpu.device_mvcc.apply import extract_deltas
    from etcd_tpu.server.mvcc import MVCCStore
    from etcd_tpu.server.watch import WatchableStore, events_from_delta

    kvspec = KVSpec(keys=8)
    roundwords = [
        [scheme.encode_put(0, 1), scheme.encode_put(1, 2)],
        [scheme.encode_put(0, 3), scheme.encode_delete_range(1, 2)],
        [scheme.encode_put(2, 4, lease=3)],
    ]
    st = init_kv(kvspec, 1)
    ws = WatchableStore(MVCCStore())
    w = ws.watch(scheme.key_bytes(0), b"\x00")
    # the documented fan-out bridge: device deltas feed a host watcher
    # group via notify() directly
    dev_ws = WatchableStore(MVCCStore())
    dev_w = dev_ws.watch(scheme.key_bytes(0), b"\x00")
    dev_last: dict[bytes, tuple] = {}
    for words in roundwords:
        floor = st.current_rev
        st = apply_words(kvspec, st, np.asarray(words, np.int32)[:, None])
        delta = extract_deltas(kvspec, floor, st)
        evs = events_from_delta(delta, 0)
        dev_ws.notify(evs)
        for typ, kv, _prev in evs:
            dev_last[kv.key] = (typ, kv.mod_revision, kv.value, kv.version,
                                kv.lease)
        for word in words:
            op = scheme.decode(word)
            txn = ws.kv.write_txn()
            if op["kind"] == scheme.KIND_PUT:
                txn.put(scheme.key_bytes(op["key"]),
                        scheme.encode_value(op["val"]), op["lease"])
            else:
                txn.delete_range(scheme.key_bytes(op["lo"]))
            txn.end()
            ws.notify(txn.events)
    host_last: dict[bytes, tuple] = {}
    for ev in ws.take_events(w.id):
        host_last[ev.kv.key] = (ev.type, ev.kv.mod_revision, ev.kv.value,
                                ev.kv.version, ev.kv.lease)
    assert dev_last == host_last
    assert dev_last[scheme.key_bytes(1)][0] == "delete"
    assert dev_last[scheme.key_bytes(2)][4] == 3  # lease rides the delta
    # the notified watcher buffered every delta event with the right types
    got = dev_ws.take_events(dev_w.id)
    assert [(e.type, e.kv.key) for e in got] == [
        ("put", scheme.key_bytes(0)), ("put", scheme.key_bytes(1)),
        ("put", scheme.key_bytes(0)), ("delete", scheme.key_bytes(1)),
        ("put", scheme.key_bytes(2)),
    ]


# ------------------------------------------------------ engine integration


def test_engine_kv_round_frontier_and_modes():
    """build_kv_round consumes the apply frontier: proposals become
    applied revisions + watch deltas without leaving the device, the
    digest matches a host replay of the same words, and do_apply=False
    is an identity on the KV fleet (one trace, both apply modes)."""
    from etcd_tpu.models.engine import (
        _jitted_kv_round,
        empty_inbox,
        init_fleet,
    )
    from etcd_tpu.server.watch import events_from_delta
    from etcd_tpu.types import Spec
    from etcd_tpu.utils.config import RaftConfig

    spec = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=4, coalesce_commit_refresh=True,
                     wire_int16=False)
    kvspec = KVSpec(keys=16)
    C, M, E = 4, spec.M, spec.E
    rnd = _jitted_kv_round(cfg, spec, kvspec, 0)
    z2 = jnp.zeros((M, C), jnp.int32)
    zp = jnp.zeros((M, E, C), jnp.int32)
    no_hup = jnp.zeros((M, C), jnp.bool_)
    no_tick = jnp.zeros((M, C), jnp.bool_)
    keep = jnp.ones((M, M, C), jnp.bool_)
    on = jnp.ones((C,), jnp.bool_)
    state = init_fleet(spec, C, seed=0)
    inbox = empty_inbox(spec, C)
    kv = init_kv(kvspec, C)
    state, inbox, kv, _ = rnd(state, inbox, kv, on, z2, zp, zp, z2,
                              no_hup.at[0].set(True), no_tick, keep)
    for _ in range(16):
        state, inbox, kv, _ = rnd(state, inbox, kv, on, z2, zp, zp, z2,
                                  no_hup, no_tick, keep)
        if int((state.role == 3).sum()) == C:
            break
    assert int((state.role == 3).sum()) == C
    words = [scheme.encode_put(r % 16, 100 + r, r % 4) for r in range(10)]
    events = 0
    for r in range(14):
        pl = z2.at[0].set(1) if r < 10 else z2
        pd = zp.at[0, 0].set(words[r]) if r < 10 else zp
        state, inbox, kv, delta = rnd(state, inbox, kv, on, pl, pd, zp, z2,
                                      no_hup, no_tick, keep)
        events += len(events_from_delta(delta, 0))
    assert events == 10                      # every write surfaced exactly once
    assert int(np.asarray(kv.skipped).sum()) == 0
    assert (np.asarray(kv.applied_idx) == np.asarray(state.applied[0])).all()
    store, _, _ = host_replay(kvspec, np.asarray(words, np.int32))
    want = scheme.store_latest_digest(store, 16)
    assert all(int(d) == want for d in np.asarray(kv_digest(kvspec, kv)))
    # host-apply mode: same trace, operand off -> KV fleet untouched
    before = int(np.asarray(kv.current_rev[0]))
    off = jnp.zeros((C,), jnp.bool_)
    state, inbox, kv2, _ = rnd(
        state, inbox, kv, off, z2.at[0].set(1),
        zp.at[0, 0].set(scheme.encode_put(0, 9)), zp, z2, no_hup, no_tick,
        keep,
    )
    assert int(np.asarray(kv2.current_rev[0])) == before
    assert not bool(np.asarray(kv2.mod != kv.mod).any())


def test_engine_kv_round_freezes_on_snapshot_install():
    """A bound member that installs a peer snapshot keeps old ring bytes
    under new cursors; the plane must detect the install (applied jump >
    Spec.A — ring apply can never exceed A per round) and FREEZE the
    lane (sticky desynced) instead of replaying stale words."""
    from etcd_tpu.models.engine import (
        _jitted_kv_round,
        empty_inbox,
        init_fleet,
    )
    from etcd_tpu.types import Spec
    from etcd_tpu.utils.config import RaftConfig

    spec = Spec(M=3, L=16, E=1, K=2, W=4, R=2, A=2)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                     inbox_bound=2, coalesce_commit_refresh=True,
                     wire_int16=False)
    kvspec = KVSpec(keys=16)
    C, M, E = 1, spec.M, spec.E
    rnd = _jitted_kv_round(cfg, spec, kvspec, 2)  # bind the SLOW follower
    z2 = jnp.zeros((M, C), jnp.int32)
    zp = jnp.zeros((M, E, C), jnp.int32)
    no_hup = jnp.zeros((M, C), jnp.bool_)
    no_tick = jnp.zeros((M, C), jnp.bool_)
    full = jnp.ones((M, M, C), jnp.bool_)
    cut2 = full.at[:, 2].set(False).at[2, :].set(False)
    on = jnp.ones((C,), jnp.bool_)
    state = init_fleet(spec, C, seed=0)
    inbox = empty_inbox(spec, C)
    kv = init_kv(kvspec, C)
    state, inbox, kv, _ = rnd(state, inbox, kv, on, z2, zp, zp, z2,
                              no_hup.at[0].set(True), no_tick, cut2)
    for _ in range(12):
        state, inbox, kv, _ = rnd(state, inbox, kv, on, z2, zp, zp, z2,
                                  no_hup, no_tick, cut2)
        if int(state.role[0, 0]) == 3:
            break
    # leader runs far ahead while member 2 is cut: the ring compacts and
    # member 2 can only catch up via MsgSnap
    for r in range(20):
        pl = z2.at[0].set(1)
        pd = zp.at[0, 0].set(scheme.encode_put(r % 16, r))
        state, inbox, kv, _ = rnd(state, inbox, kv, on, pl, pd, zp, z2,
                                  no_hup, no_tick, cut2)
    assert int(state.snap_index[0, 0]) > 0     # leader compacted its ring
    assert int(state.applied[2, 0]) == 0
    all_tick = jnp.ones((M, C), jnp.bool_)
    for r in range(40):                        # heal under ticks: the
        # leader's heartbeat un-pauses the probe, walks member 2's
        # next_idx below the compacted ring, and ships MsgSnap
        state, inbox, kv, delta = rnd(state, inbox, kv, on, z2, zp, zp, z2,
                                      no_hup, all_tick, full)
        if bool(np.asarray(kv.desynced[0])):
            break
    assert int(np.asarray(state.applied[2, 0])) > spec.A  # install happened
    assert bool(np.asarray(kv.desynced[0]))
    # frozen, not corrupted: nothing was ever replayed into the lane
    assert int(np.asarray(kv.current_rev[0])) == 1
    assert not bool(np.asarray(kv.present).any())
    assert not bool(np.asarray(delta.mask).any())


def test_engine_kv_round_rejects_int16_wire():
    from etcd_tpu.models.engine import build_kv_round
    from etcd_tpu.types import Spec
    from etcd_tpu.utils.config import RaftConfig

    with pytest.raises(ValueError, match="int32 wire"):
        build_kv_round(RaftConfig(wire_int16=True), Spec(), KVSpec(keys=8))


# ----------------------------------------------------- kvserver facade


def _mk_clusters():
    from etcd_tpu.server.kvserver import EtcdCluster

    dev = EtcdCluster(n_members=3, apply_plane="device", kv_keys=16)
    host = EtcdCluster(n_members=3)
    return dev, host


def test_kvserver_device_plane_parity():
    """The same client workload through a device-plane EtcdCluster and a
    host-plane one: identical responses, identical canonical digests,
    watch events flowing from the device lanes."""
    from etcd_tpu.server.kvserver import Compare, Op

    dev, host = _mk_clusters()
    w = dev.watch(0, scheme.key_bytes(0), b"\x00")
    for ec in (dev, host):
        ec.put(scheme.key_bytes(1), scheme.encode_value(42))
        ec.put(scheme.key_bytes(0), scheme.encode_value(7), lease=0)
        ec.put(scheme.key_bytes(1), scheme.encode_value(43))
        ec.delete_range(scheme.key_bytes(1))
        ec.txn(
            compare=[Compare(scheme.key_bytes(0), "version", "=", 1)],
            success=[Op("put", scheme.key_bytes(2), scheme.encode_value(5)),
                     Op("range", scheme.key_bytes(0))],
        )
        ec.compact(3)
        ec.stabilize()
    rd = dev.range(scheme.key_bytes(0), b"\x00")
    rh = host.range(scheme.key_bytes(0), b"\x00")
    assert [(kv.key, kv.value, kv.mod_revision, kv.create_revision,
             kv.version) for kv in rd["kvs"]] == [
        (kv.key, kv.value, kv.mod_revision, kv.create_revision, kv.version)
        for kv in rh["kvs"]]
    assert rd["rev"] == rh["rev"]
    # one digest, both planes: device lanes vs host hash_kv_latest
    want = host.members[0].store.kv.hash_kv_latest(16)
    assert all(dev.hash_kv(m) == want for m in range(3))
    dev.corruption_check()
    evs = dev.watch_events(0, w.id)
    assert [e.type for e in evs] == ["put", "put", "put", "delete", "put"]
    # compaction-boundary errors surface as the host exceptions
    from etcd_tpu.server.mvcc import ErrCompacted, ErrFutureRev

    with pytest.raises(ErrCompacted):
        dev.compact(2)
    with pytest.raises(ErrFutureRev):
        dev.compact(99)
    with pytest.raises(ErrFutureRev):
        dev.range(scheme.key_bytes(0), rev=99)


def test_kvserver_device_plane_crash_recovery():
    """A crashed device-plane member recovers through the peer-snapshot
    path: its lane is reloaded from a donor and digests re-converge."""
    from etcd_tpu.server.kvserver import EtcdCluster

    dev = EtcdCluster(n_members=3, apply_plane="device", kv_keys=16)
    for i in range(4):
        dev.put(scheme.key_bytes(i % 3), scheme.encode_value(i))
    dev.stabilize()
    want = dev.hash_kv(0)
    dev.crash_member(2)
    dev.put(scheme.key_bytes(3), scheme.encode_value(9))
    dev.restart_member_from_disk(2)
    dev.stabilize()
    assert not dev.members[2].crashed
    assert dev.hash_kv(2) == dev.hash_kv(0) != want
    dev.corruption_check()


# ------------------------------------------------- knob validation (exit 2)


@pytest.mark.parametrize("script,env_extra,needle", [
    ("bench.py", {"APPLY_MODE": "bogus"}, "APPLY_MODE"),
    ("bench.py", {"APPLY_MODE": "device", "APPLY_KEYS": "4096"},
     "APPLY_KEYS"),
    ("chaos_run.py", {"APPLY_KEYS": "-1"}, "APPLY_KEYS"),
    ("chaos_run.py", {"APPLY_KEYS": "64", "APPLY_OPS": "0"}, "APPLY_OPS"),
    # the headline-bench knobs ride the same validator now (they used to
    # be raw int() casts that died with a bare ValueError traceback)
    ("bench.py", {"BENCH_CHUNKS": "zero"}, "BENCH_CHUNKS"),
    ("bench.py", {"BENCH_CHUNKS": "0"}, "BENCH_CHUNKS"),
    ("bench.py", {"BENCH_C": "-8"}, "BENCH_C"),
    ("bench.py", {"APPLY_MODE": "device", "BENCH_CHUNKS": "1.5"},
     "BENCH_CHUNKS"),
    ("bench.py", {"BENCH_PACKED": "yes"}, "BENCH_PACKED"),
])
def test_apply_knob_validation_exits_2(script, env_extra, needle):
    """Bad APPLY_* values exit 2 with a pointed message before any device
    work — the chaos_run knob-validation contract extended to the apply
    plane."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **env_extra}
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, script)],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 2, (out.returncode, out.stdout, out.stderr)
    assert needle in out.stderr
    assert not out.stdout.strip()
