"""The batched Raft state machine: one pure step function per node.

This file re-expresses the reference's role machines — ``raft.Step``
(raft/raft.go:847-987), ``stepLeader`` (991-1372), ``stepCandidate``
(1376-1419), ``stepFollower`` (1421-1473), the ``become*`` transitions
(686-758), ``tickElection``/``tickHeartbeat`` (645-684) and the
Ready/Advance apply cycle — as straight-line masked tensor updates over a
:class:`NodeState`. Every helper is written for ONE node (scalars, [M] peer
arrays, [L] log ring) and batched by ``jax.vmap`` over the member and
cluster axes; data-dependent Go control flow becomes ``jnp.where`` masks so
the whole round jits into one fused XLA program.

Message processing is a ``lax.scan`` over the (statically bounded)
per-round sequence [hup, inbox(M*K or inbox_bound), prop, read-index].
A straight-line unroll was measured and removed: the per-step
optimization barriers it needed to bound peak HBM shattered the round
into ~13k unfusable ops (fixed per-op overhead dominated on TPU), and the
unrolled XLA CPU compile was pathological (>6GB RSS at C=1). The scan
runs the same masked math one while-iteration per slot; throughput comes
from batch scale C, and the serial slot count from inbox compaction.

Deviations from the reference, all intentional and documented inline:
  * The application is fused: committed entries (and snapshots/conf
    changes) apply eagerly inside the round, up to Spec.A entries per round
    (MaxCommittedSizePerReady pagination, raft.go:149-151).
  * MsgHup is a first-class message; internal campaign triggers
    (MsgTimeoutNow, a pre-candidate winning its pre-vote) emit MsgHup to
    self, arriving next round — a legal async schedule.
  * Ticks run at the START of a round, before message delivery.
  * After the auto-leave proposal (advance(), raft.go:554-570) we
    bcastAppend immediately rather than waiting for the next trigger.
  * Byte quotas (MaxSizePerMsg, MaxUncommittedEntriesSize) are entry
    counts: payloads are fixed-width words on device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from etcd_tpu.models import confchange as ccmod
from etcd_tpu.models.state import (
    NodeState,
    in_config_self,
    is_joint,
    is_learner_self,
)
from etcd_tpu.ops import inflights as infl
from etcd_tpu.ops import log as logops
from etcd_tpu.ops import quorum
from etcd_tpu.ops.outbox import (
    Outbox,
    bcast,
    emit,
    emit_one,
    empty_outbox,
    make_msg,
    record_sent_commit,
)
from etcd_tpu.types import (
    CAMPAIGN_FORCE,
    CAMPAIGN_NONE,
    CAMPAIGN_TRANSFER,
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    MSG_APP,
    MSG_APP_RESP,
    MSG_HEARTBEAT,
    MSG_HEARTBEAT_RESP,
    MSG_HUP,
    MSG_NONE,
    MSG_PRE_VOTE,
    MSG_PRE_VOTE_RESP,
    MSG_PROP,
    MSG_READ_INDEX,
    MSG_READ_INDEX_RESP,
    MSG_SNAP,
    MSG_SNAP_STATUS,
    MSG_TIMEOUT_NOW,
    MSG_TRANSFER_LEADER,
    MSG_UNREACHABLE,
    MSG_VOTE,
    MSG_VOTE_RESP,
    Msg,
    NONE_ID,
    PR_PROBE,
    PR_REPLICATE,
    PR_SNAPSHOT,
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    ROLE_PRE_CANDIDATE,
    Spec,
    VOTE_LOST,
    VOTE_WON,
    pack_mask,
    unpack_mask,
)
from etcd_tpu.utils.config import RaftConfig
from etcd_tpu.utils.tree import tree_where

# ---------------------------------------------------------------------------
# small helpers
# ---------------------------------------------------------------------------


def onehot_sel(vec: jnp.ndarray, i: jnp.ndarray) -> jnp.ndarray:
    """vec[i] for a traced scalar i without an HLO gather — same one-hot
    contraction as :func:`etcd_tpu.ops.log.ring_read` (single audited
    implementation; this is just the domain-named alias used for [M] peer
    vectors)."""
    return logops.ring_read(vec, i)


def _ids(spec: Spec) -> jnp.ndarray:
    return jnp.arange(spec.M, dtype=jnp.int32)


def _self_hot(spec: Spec, n: NodeState) -> jnp.ndarray:
    return _ids(spec) == n.nid


def _progress_ids(n: NodeState) -> jnp.ndarray:
    """[M] mask of ids with a Progress entry (voters + outgoing + learners)."""
    return n.voters | n.voters_out | n.learners


def _voter_union(n: NodeState) -> jnp.ndarray:
    return n.voters | n.voters_out


def promotable(spec: Spec, n: NodeState) -> jnp.ndarray:
    """raft.promotable (raft.go:1618-1621); pending-snapshot is impossible
    here because snapshots apply eagerly on restore."""
    return in_config_self(n) & ~is_learner_self(n)


def _mix_hash(h, idx, term, data):
    """Rolling hash chain over applied entries (KV_HASH checker analog)."""
    h = h * jnp.int32(1000003) + idx * jnp.int32(-1640531527)
    h = h ^ (term * jnp.int32(40503) + data * jnp.int32(69069) + 1)
    return h.astype(jnp.int32)


# ---------------------------------------------------------------------------
# state transitions (raft.go:590-758)
# ---------------------------------------------------------------------------


def reset_state(cfg: RaftConfig, spec: Spec, n: NodeState, term) -> NodeState:
    """raft.reset (raft.go:590-619)."""
    sh = _self_hot(spec, n)
    fM = jnp.zeros((spec.M,), jnp.bool_)
    changed = n.term != term
    key, sub = jax.random.split(n.rng_key)
    rand_to = cfg.election_tick + jax.random.randint(
        sub, (), 0, cfg.election_tick, dtype=jnp.int32
    )
    z = jnp.int32(0)
    n = n.replace(
        term=jnp.asarray(term, jnp.int32),
        vote=jnp.where(changed, NONE_ID, n.vote),
        lead=jnp.int32(NONE_ID),
        election_elapsed=z,
        heartbeat_elapsed=z,
        randomized_timeout=rand_to,
        rng_key=key,
        lead_transferee=jnp.int32(NONE_ID),
        votes_responded=fM,
        votes_granted=fM,
        match=jnp.where(sh, n.last_index, 0),
        next_idx=jnp.zeros((spec.M,), jnp.int32) + n.last_index + 1,
        pr_state=jnp.full((spec.M,), PR_PROBE, jnp.int32),
        probe_sent=fM,
        pending_snapshot=jnp.zeros((spec.M,), jnp.int32),
        recent_active=fM,
        pending_conf_index=z,
        uncommitted_size=z,
        ro_count=z,
        ro_pend_count=z,
    )
    return infl.reset(n, jnp.ones((spec.M,), jnp.bool_))


def become_follower_state(cfg, spec, n: NodeState, term, lead) -> NodeState:
    """raft.becomeFollower (raft.go:686-693)."""
    n = reset_state(cfg, spec, n, term)
    return n.replace(lead=jnp.asarray(lead, jnp.int32), role=jnp.int32(ROLE_FOLLOWER))


def become_candidate_state(cfg, spec, n: NodeState) -> NodeState:
    """raft.becomeCandidate (raft.go:695-706)."""
    n = reset_state(cfg, spec, n, n.term + 1)
    return n.replace(vote=n.nid, role=jnp.int32(ROLE_CANDIDATE))


def become_pre_candidate_state(cfg, spec, n: NodeState) -> NodeState:
    """raft.becomePreCandidate (raft.go:708-722): votes reset, lead cleared,
    but term/vote/timers untouched."""
    fM = jnp.zeros((spec.M,), jnp.bool_)
    return n.replace(
        votes_responded=fM,
        votes_granted=fM,
        lead=jnp.int32(NONE_ID),
        role=jnp.int32(ROLE_PRE_CANDIDATE),
    )


def record_vote(spec, n: NodeState, vid, granted) -> NodeState:
    """ProgressTracker.RecordVote (tracker/tracker.go:259-264): first
    response from a peer wins."""
    hot = _ids(spec) == vid
    fresh = hot & ~n.votes_responded
    return n.replace(
        votes_responded=n.votes_responded | hot,
        votes_granted=jnp.where(fresh, granted, n.votes_granted),
    )


def tally_votes(n: NodeState) -> jnp.ndarray:
    """ProgressTracker.TallyVotes → joint vote result."""
    return quorum.joint_vote_result(
        n.voters, n.voters_out, n.votes_responded, n.votes_granted
    )


def maybe_commit_state(cfg, spec, n: NodeState):
    """raft.maybeCommit (raft.go:585-588): quorum match index, committed only
    if its term is the current term (log.go:325-331). Returns (n, advanced)."""
    mci = quorum.joint_committed_index(n.voters, n.voters_out, n.match)
    t, ok = logops.term_at(spec, n, mci)
    adv = (mci > n.commit) & ok & (t == n.term)
    return n.replace(commit=jnp.where(adv, mci, n.commit)), adv


def append_entries_state(
    cfg,
    spec,
    n: NodeState,
    p_len,
    ent_data,
    ent_type,
    enable,
    count_quota: bool = True,
):
    """raft.appendEntry (raft.go:621-642): assign term/index, enforce the
    uncommitted-size quota (entry-count based) and ring capacity, update the
    leader's own progress, try to commit. Returns (n, accepted)."""
    add = jnp.asarray(p_len, jnp.int32)
    over = (
        (n.uncommitted_size > 0)
        & (add > 0)
        & (n.uncommitted_size + add > cfg.max_uncommitted_entries)
        if count_quota
        else jnp.bool_(False)
    )
    cap_over = (n.last_index + add - n.snap_index) > spec.L
    accepted = enable & ~over & ~cap_over
    terms = jnp.zeros((spec.E,), jnp.int32) + n.term
    n2 = logops.append_span(
        spec, n, n.last_index, add, terms, ent_data, ent_type, accepted
    )
    sh = _self_hot(spec, n)
    n2 = n2.replace(
        uncommitted_size=n2.uncommitted_size
        + jnp.where(accepted & count_quota, add, 0),
        match=jnp.where(sh, jnp.maximum(n2.match, n2.last_index), n2.match),
        next_idx=jnp.where(
            sh, jnp.maximum(n2.next_idx, n2.last_index + 1), n2.next_idx
        ),
    )
    n3, _ = maybe_commit_state(cfg, spec, n2)
    return tree_where(accepted, n3, n), accepted


def become_leader_state(cfg, spec, n: NodeState) -> NodeState:
    """raft.becomeLeader (raft.go:724-758)."""
    n = reset_state(cfg, spec, n, n.term)
    sh = _self_hot(spec, n)
    n = n.replace(
        lead=n.nid,
        role=jnp.int32(ROLE_LEADER),
        pr_state=jnp.where(sh, PR_REPLICATE, n.pr_state),
        next_idx=jnp.where(sh, n.match + 1, n.next_idx),
        pending_conf_index=n.last_index,
    )
    # append the empty entry at the new term; exempt from the quota
    # (raft.go:747-756) and un-refusable by construction.
    zE = jnp.zeros((spec.E,), jnp.int32)
    n, _ = append_entries_state(
        cfg, spec, n, 1, zE, zE, jnp.bool_(True), count_quota=False
    )
    return n


# ---------------------------------------------------------------------------
# sending (raft.go:421-541)
# ---------------------------------------------------------------------------


def _is_paused(cfg, n: NodeState) -> jnp.ndarray:
    """Progress.IsPaused (tracker/progress.go:201-212), [M]."""
    return jnp.where(
        n.pr_state == PR_PROBE,
        n.probe_sent,
        jnp.where(
            n.pr_state == PR_REPLICATE,
            infl.full(cfg.max_inflight, n),
            True,  # PR_SNAPSHOT
        ),
    )


def maybe_send_append(
    cfg, spec, n: NodeState, ob: Outbox, dest_mask, send_if_empty
) -> tuple[NodeState, Outbox]:
    """raft.maybeSendAppend vectorized over destinations (raft.go:432-492).

    dest_mask: [M] bool (self is always excluded). send_if_empty: scalar or
    [M] bool. Falls back to MsgSnap when the needed entries are compacted.
    """
    send_if_empty = jnp.asarray(send_if_empty, jnp.bool_)
    ids = _ids(spec)
    mask = dest_mask & (ids != n.nid) & ~_is_paused(cfg, n)

    prev = n.next_idx - 1  # [M]
    needs_snap = prev < n.snap_index
    t_prev = jnp.where(
        prev == n.snap_index,
        n.snap_term,
        logops.ring_read(n.log_term, logops.slot(spec, prev)),
    )
    offs = jnp.arange(spec.E, dtype=jnp.int32)[None, :]
    idxs = n.next_idx[:, None] + offs  # [M, E]
    valid = (idxs <= n.last_index) & (idxs > n.snap_index)
    s = logops.slot(spec, idxs)
    e_term = jnp.where(valid, logops.ring_read(n.log_term, s), 0)
    e_data = jnp.where(valid, logops.ring_read(n.log_data, s), 0)
    e_type = jnp.where(valid, logops.ring_read(n.log_type, s), 0)
    ln = jnp.clip(n.last_index - n.next_idx + 1, 0, spec.E).astype(jnp.int32)

    empty = ln == 0
    send_app = mask & ~needs_snap & ~(empty & ~send_if_empty)
    send_snap = mask & needs_snap & n.recent_active

    base = make_msg(spec)
    app = bcast(spec, base).replace(
        type=jnp.where(send_app, MSG_APP, MSG_NONE),
        term=jnp.broadcast_to(n.term, (spec.M,)),
        frm=jnp.broadcast_to(n.nid, (spec.M,)),
        index=prev,
        log_term=t_prev,
        commit=jnp.broadcast_to(n.commit, (spec.M,)),
        ent_len=ln,
        ent_term=e_term,
        ent_data=e_data,
        ent_type=e_type,
    )
    ob = emit(spec, ob, send_app, app,
              fields=("index", "log_term", "commit", "ent_len",
                      "ent_term", "ent_data", "ent_type"))
    ob = record_sent_commit(ob, send_app, n.commit)

    has_ents = send_app & (ln > 0)
    repl = n.pr_state == PR_REPLICATE
    probe = n.pr_state == PR_PROBE
    last_sent = prev + ln
    n = n.replace(
        next_idx=jnp.where(has_ents & repl, last_sent + 1, n.next_idx),
        probe_sent=n.probe_sent | (has_ents & probe),
    )
    n = infl.add(spec, n, has_ents & repl, last_sent)

    # The snapshot sent is the freshest applied state — index `applied`,
    # the rolling applied hash, and the applied config — not the last
    # compaction point. This mirrors the reference harness's "you get the
    # most recent snapshot" semantics (rafttest's snapshotOverride,
    # interaction_env_handler_add_nodes.go:39-58) and catches the
    # follower up as far as possible in one message.
    t_app, _ = logops.term_at(spec, n, n.applied)
    # the 32-bit applied hash rides split across commit (low 16 bits,
    # bit-exact through the int16 wire's truncate/sign-extend round trip)
    # and reject_hint (arithmetic >>16: a value in [-32768, 32767], exact
    # in int16) — a whole hash in `commit` alone is silently truncated by
    # RaftConfig.wire_int16 and corrupts every restored follower's hash
    # chain (found by the chaos tier's KV_HASH checker)
    snap = bcast(spec, base).replace(
        type=jnp.where(send_snap, MSG_SNAP, MSG_NONE),
        term=jnp.broadcast_to(n.term, (spec.M,)),
        frm=jnp.broadcast_to(n.nid, (spec.M,)),
        index=jnp.broadcast_to(n.applied, (spec.M,)),
        log_term=jnp.broadcast_to(t_app, (spec.M,)),
        commit=jnp.broadcast_to(n.applied_hash, (spec.M,)),
        reject_hint=jnp.broadcast_to(n.applied_hash >> 16, (spec.M,)),
        reject=jnp.broadcast_to(n.auto_leave, (spec.M,)),
        c_voters=jnp.broadcast_to(pack_mask(n.voters), (spec.M,)),
        c_voters_out=jnp.broadcast_to(pack_mask(n.voters_out), (spec.M,)),
        c_learners=jnp.broadcast_to(pack_mask(n.learners), (spec.M,)),
        c_learners_next=jnp.broadcast_to(
            pack_mask(n.learners_next), (spec.M,)
        ),
    )
    ob = emit(spec, ob, send_snap, snap,
              fields=("index", "log_term", "commit", "reject_hint",
                      "c_voters", "c_voters_out", "c_learners",
                      "c_learners_next"))
    ob = record_sent_commit(ob, send_snap, n.commit)
    n = n.replace(
        pr_state=jnp.where(send_snap, PR_SNAPSHOT, n.pr_state),
        pending_snapshot=jnp.where(send_snap, n.applied, n.pending_snapshot),
    )
    return n, ob


def bcast_append(cfg, spec, n, ob, enable) -> tuple[NodeState, Outbox]:
    """raft.bcastAppend (raft.go:515-522)."""
    return maybe_send_append(cfg, spec, n, ob, _progress_ids(n) & enable, True)


def _ro_last_ctx(n: NodeState) -> jnp.ndarray:
    """readOnly.lastPendingRequestCtx (read_only.go:115-121); 0 if none."""
    has = n.ro_count > 0
    return jnp.where(has, onehot_sel(n.ro_ctx, jnp.maximum(n.ro_count - 1, 0)), 0)


def bcast_heartbeat(cfg, spec, n, ob, ctx, enable) -> tuple[NodeState, Outbox]:
    """raft.bcastHeartbeat (raft.go:525-541): commit per dest is
    min(match, committed) (raft.go:495-511)."""
    to = _progress_ids(n) & (_ids(spec) != n.nid) & enable
    msg = bcast(spec, make_msg(spec)).replace(
        type=jnp.where(to, MSG_HEARTBEAT, MSG_NONE),
        term=jnp.broadcast_to(n.term, (spec.M,)),
        frm=jnp.broadcast_to(n.nid, (spec.M,)),
        commit=jnp.minimum(n.match, n.commit),
        context=jnp.broadcast_to(jnp.asarray(ctx, jnp.int32), (spec.M,)),
    )
    ob = emit(spec, ob, to, msg, fields=("commit",))
    ob = record_sent_commit(ob, to, jnp.minimum(n.match, n.commit))
    return n, ob


# ---------------------------------------------------------------------------
# campaigning (raft.go:760-845); traced ONCE per round via the MsgHup handler
# ---------------------------------------------------------------------------


def campaign(cfg, spec, n: NodeState, ob: Outbox, kind, enable):
    """raft.campaign (raft.go:785-835) with a dynamic CAMPAIGN_* kind.

    kind CAMPAIGN_NONE runs the pre-vote phase first when cfg.pre_vote; an
    instant pre-vote win (single voter) falls through to the real election
    in the same call, mirroring the reference's recursion.
    """
    kind = jnp.asarray(kind, jnp.int32)
    if cfg.pre_vote:
        pre = enable & (kind == CAMPAIGN_NONE)
        npre = become_pre_candidate_state(cfg, spec, n)
        npre = record_vote(spec, npre, npre.nid, jnp.bool_(True))
        won_pre = tally_votes(npre) == VOTE_WON
        to = pre & ~won_pre & _voter_union(npre) & (_ids(spec) != npre.nid)
        lt = logops.last_term(spec, npre)
        msg = bcast(spec, make_msg(spec)).replace(
            type=jnp.where(to, MSG_PRE_VOTE, MSG_NONE),
            term=jnp.broadcast_to(npre.term + 1, (spec.M,)),
            frm=jnp.broadcast_to(npre.nid, (spec.M,)),
            index=jnp.broadcast_to(npre.last_index, (spec.M,)),
            log_term=jnp.broadcast_to(lt, (spec.M,)),
        )
        ob = emit(spec, ob, to, msg, fields=("index", "log_term"))
        n = tree_where(pre, npre, n)
        do_real = enable & jnp.where(pre, won_pre, True)
    else:
        do_real = enable

    nr = become_candidate_state(cfg, spec, n)
    nr = record_vote(spec, nr, nr.nid, jnp.bool_(True))
    won = tally_votes(nr) == VOTE_WON
    to = do_real & ~won & _voter_union(nr) & (_ids(spec) != nr.nid)
    lt = logops.last_term(spec, nr)
    msg = bcast(spec, make_msg(spec)).replace(
        type=jnp.where(to, MSG_VOTE, MSG_NONE),
        term=jnp.broadcast_to(nr.term, (spec.M,)),
        frm=jnp.broadcast_to(nr.nid, (spec.M,)),
        index=jnp.broadcast_to(nr.last_index, (spec.M,)),
        log_term=jnp.broadcast_to(lt, (spec.M,)),
        context=jnp.broadcast_to(
            jnp.where(kind == CAMPAIGN_TRANSFER, CAMPAIGN_TRANSFER, 0), (spec.M,)
        ),
    )
    ob = emit(spec, ob, to, msg, fields=("index", "log_term"))
    nr = tree_where(won, become_leader_state(cfg, spec, nr), nr)
    n = tree_where(do_real, nr, n)
    return n, ob


def hup(cfg, spec, n, ob, kind, enable):
    """raft.hup (raft.go:760-781): guard against campaigning as leader, when
    unpromotable, or with an unapplied conf change in (applied, committed]."""
    pend = logops.count_pending_conf(spec, n, n.applied, n.commit)
    ok = (
        enable
        & (n.role != ROLE_LEADER)
        & promotable(spec, n)
        & ~((pend > 0) & (n.commit > n.applied))
    )
    return campaign(cfg, spec, n, ob, kind, ok)


def _emit_hup_to_self(spec, n, ob, kind, enable):
    """Queue a MsgHup to self for the next round (used by MsgTimeoutNow and
    by a pre-candidate that won its pre-vote round)."""
    return emit_one(
        spec,
        ob,
        n.nid,
        make_msg(spec, type=MSG_HUP, frm=n.nid, context=kind),
        enable,
        fields=(),
    )


# ---------------------------------------------------------------------------
# read-only queue (raft/read_only.go, re-keyed by integer ctx)
# ---------------------------------------------------------------------------


def _rs_push(spec, n: NodeState, ctx, index, enable) -> NodeState:
    """Surface a ReadState to the local application (raft.go:249)."""
    pos = jnp.minimum(n.rs_count, spec.R - 1)
    can = enable & (n.rs_count < spec.R)
    sel = jnp.arange(spec.R, dtype=jnp.int32) == pos
    return n.replace(
        rs_ctx=jnp.where(sel & can, ctx, n.rs_ctx),
        rs_index=jnp.where(sel & can, index, n.rs_index),
        rs_count=n.rs_count + can.astype(jnp.int32),
    )


def _ro_add_request(spec, n: NodeState, ctx, frm, enable) -> NodeState:
    """readOnly.addRequest (read_only.go:39-59); dup ctx is a no-op."""
    dup = ((n.ro_ctx == ctx) & (jnp.arange(spec.R) < n.ro_count)).any()
    can = enable & ~dup & (n.ro_count < spec.R)
    pos = jnp.minimum(n.ro_count, spec.R - 1)
    sel = jnp.arange(spec.R, dtype=jnp.int32) == pos
    acks = n.ro_acks.reshape(spec.R, spec.M)
    return n.replace(
        ro_ctx=jnp.where(sel & can, ctx, n.ro_ctx),
        ro_index=jnp.where(sel & can, n.commit, n.ro_index),
        ro_from=jnp.where(sel & can, frm, n.ro_from),
        ro_acks=jnp.where((sel & can)[:, None], False, acks).reshape(-1),
        ro_count=n.ro_count + can.astype(jnp.int32),
    )


def _ro_recv_ack(spec, n: NodeState, frm, ctx, enable):
    """readOnly.recvAck (read_only.go:61-70). Returns (n, found, acks_row)."""
    in_q = jnp.arange(spec.R) < n.ro_count
    slot_hot = (n.ro_ctx == ctx) & in_q
    found = enable & slot_hot.any()
    fhot = _ids(spec) == frm
    acks_v = n.ro_acks.reshape(spec.R, spec.M)
    acks = acks_v | (slot_hot[:, None] & fhot[None, :] & enable)
    row = jnp.where(slot_hot[:, None], acks, False).any(axis=0)
    return n.replace(ro_acks=acks.reshape(-1)), found, row


def _ro_advance_emit(cfg, spec, n: NodeState, ob: Outbox, ctx, enable):
    """readOnly.advance (read_only.go:72-101) + the response fan-out of
    stepLeader MsgHeartbeatResp (raft.go:1304-1309)."""
    in_q = jnp.arange(spec.R) < n.ro_count
    slot_hot = (n.ro_ctx == ctx) & in_q
    found = enable & slot_hot.any()
    pos = jnp.argmax(slot_hot).astype(jnp.int32)
    released = (jnp.arange(spec.R) <= pos) & in_q & found
    for r in range(spec.R):
        req_from = n.ro_from[r]
        local = (req_from == NONE_ID) | (req_from == n.nid)
        n = _rs_push(spec, n, n.ro_ctx[r], n.ro_index[r], released[r] & local)
        ob = emit_one(
            spec,
            ob,
            req_from,
            make_msg(
                spec,
                type=MSG_READ_INDEX_RESP,
                term=n.term,
                frm=n.nid,
                index=n.ro_index[r],
                context=n.ro_ctx[r],
            ),
            released[r] & ~local,
            fields=("index",),
        )
    shift = jnp.where(found, pos + 1, 0)

    def roll(a):
        return logops.roll_left(a, shift)
    return (
        n.replace(
            ro_ctx=roll(n.ro_ctx),
            ro_index=roll(n.ro_index),
            ro_from=roll(n.ro_from),
            ro_acks=roll(n.ro_acks.reshape(spec.R, spec.M)).reshape(-1),
            ro_count=n.ro_count - shift,
        ),
        ob,
    )


def _committed_in_term(spec, n: NodeState) -> jnp.ndarray:
    """raft.committedEntryInCurrentTerm (raft.go:1731-1733)."""
    t, _ = logops.term_at(spec, n, n.commit)
    return t == n.term


def _is_singleton(spec, n: NodeState) -> jnp.ndarray:
    """ProgressTracker.IsSingleton: exactly one joint voter == self."""
    vu = _voter_union(n)
    return (vu.sum() == 1) & (vu & _self_hot(spec, n)).any()


def _send_read_index_response(cfg, spec, n, ob, ctx, frm, enable):
    """sendMsgReadIndexResponse (raft.go:1827-1843)."""
    if cfg.read_only_lease_based:
        local = (frm == NONE_ID) | (frm == n.nid)
        n = _rs_push(spec, n, ctx, n.commit, enable & local)
        ob = emit_one(
            spec,
            ob,
            frm,
            make_msg(
                spec,
                type=MSG_READ_INDEX_RESP,
                term=n.term,
                frm=n.nid,
                index=n.commit,
                context=ctx,
            ),
            enable & ~local,
            fields=("index",),
        )
        return n, ob
    n = _ro_add_request(spec, n, ctx, frm, enable)
    n, _, _ = _ro_recv_ack(spec, n, n.nid, ctx, enable)
    return bcast_heartbeat(cfg, spec, n, ob, ctx, enable)


def _release_pending_read_index(cfg, spec, n, ob, enable):
    """releasePendingReadIndexMessages (raft.go:1813-1825)."""
    ok = enable & _committed_in_term(spec, n)
    for r in range(spec.R):
        has = ok & (r < n.ro_pend_count)
        n, ob = _send_read_index_response(
            cfg, spec, n, ob, n.ro_pend_ctx[r], n.ro_pend_from[r], has
        )
    return n.replace(ro_pend_count=jnp.where(ok, 0, n.ro_pend_count)), ob


# ---------------------------------------------------------------------------
# message handlers (raft.go:1475-1529)
# ---------------------------------------------------------------------------


def _pend_reply(spec, ob: Outbox, to, enable, term, index, reject,
                hint, logterm) -> Outbox:
    """Record a MsgAppResp intent in the deferred accumulator
    (last-writer-wins per destination; see PendingWire)."""
    p = ob.pend
    hot = (jnp.arange(spec.M, dtype=jnp.int32) == to) & enable
    p = p.replace(
        rep_any=p.rep_any | hot,
        rep_term=jnp.where(hot, term, p.rep_term),
        rep_index=jnp.where(hot, index, p.rep_index),
        rep_reject=jnp.where(hot, reject, p.rep_reject),
        rep_hint=jnp.where(hot, hint, p.rep_hint),
        rep_logterm=jnp.where(hot, logterm, p.rep_logterm),
    )
    return ob.replace(pend=p)


def handle_append_entries(cfg, spec, n, ob, m: Msg, enable):
    """raft.handleAppendEntries (raft.go:1475-1511)."""
    below = m.index < n.commit
    commit0 = n.commit  # the below-commit reply carries pre-append commit
    if not cfg.deferred_emit:
        ob = emit_one(
            spec,
            ob,
            m.frm,
            make_msg(spec, type=MSG_APP_RESP, term=n.term, frm=n.nid,
                     index=n.commit),
            enable & below,
            fields=("index",),
        )
    en = enable & ~below
    # ring-capacity partial accept: entries past snap_index + L can't be
    # stored; accept the storable prefix (size-limited appends are legal).
    eff_len = jnp.clip(n.snap_index + spec.L - m.index, 0, m.ent_len)
    n, lastnewi, ok = logops.maybe_append(
        spec, n, m.index, m.log_term, m.commit, eff_len, m.ent_term, m.ent_data,
        m.ent_type, en,
    )
    hint_index = jnp.minimum(m.index, n.last_index)
    hint_index = logops.find_conflict_by_term(spec, n, hint_index, m.log_term)
    hint_term, _ = logops.term_at(spec, n, hint_index)
    if cfg.deferred_emit:
        # one recorded reply covers the three exclusive cases
        rej = en & ~ok
        idx = jnp.where(below, commit0, jnp.where(ok, lastnewi, m.index))
        ob = _pend_reply(spec, ob, m.frm, enable, n.term, idx, rej,
                         jnp.where(rej, hint_index, 0),
                         jnp.where(rej, hint_term, 0))
        return n, ob
    ob = emit_one(
        spec,
        ob,
        m.frm,
        make_msg(spec, type=MSG_APP_RESP, term=n.term, frm=n.nid, index=lastnewi),
        en & ok,
        fields=("index",),
    )
    ob = emit_one(
        spec,
        ob,
        m.frm,
        make_msg(
            spec,
            type=MSG_APP_RESP,
            term=n.term,
            frm=n.nid,
            index=m.index,
            reject=True,
            reject_hint=hint_index,
            log_term=hint_term,
        ),
        en & ~ok,
        fields=("index", "reject_hint", "log_term"),
    )
    return n, ob


def handle_heartbeat(cfg, spec, n, ob, m: Msg, enable):
    """raft.handleHeartbeat (raft.go:1513-1516)."""
    n = tree_where(enable, logops.commit_to(n, m.commit), n)
    ob = emit_one(
        spec,
        ob,
        m.frm,
        make_msg(
            spec, type=MSG_HEARTBEAT_RESP, term=n.term, frm=n.nid, context=m.context
        ),
        enable,
        fields=(),
    )
    return n, ob


def handle_snapshot(cfg, spec, n, ob, m: Msg, enable):
    """raft.handleSnapshot + restore (raft.go:1518-1614). The snapshot is
    applied eagerly: log reset to (sindex, sterm), state-machine hash and
    config adopted from the message."""
    sindex, sterm = m.index, m.log_term
    stale = sindex <= n.commit

    mv = unpack_mask(m.c_voters, spec.M)
    mvo = unpack_mask(m.c_voters_out, spec.M)
    ml = unpack_mask(m.c_learners, spec.M)
    mln = unpack_mask(m.c_learners_next, spec.M)
    sh = _self_hot(spec, n)
    in_cs = ((mv | mvo | ml) & sh).any()

    fast_fwd = logops.match_term(spec, n, sindex, sterm)
    follower = n.role == ROLE_FOLLOWER
    do_restore = enable & ~stale & follower & in_cs & ~fast_fwd
    do_fast = enable & ~stale & follower & in_cs & fast_fwd

    n = tree_where(do_fast, logops.commit_to(n, sindex), n)

    # reassemble the split applied hash (see the MsgSnap emit site): low
    # 16 bits from commit, high 16 from reject_hint — exact under both
    # the int32 and the int16 wire
    shash = ((m.reject_hint << 16) | (m.commit & 0xFFFF)).astype(jnp.int32)

    restored = n.replace(
        last_index=sindex,
        commit=sindex,
        applied=sindex,
        applied_hash=shash,
        snap_index=sindex,
        snap_term=sterm,
        snap_hash=shash,
        snap_voters=mv,
        snap_voters_out=mvo,
        snap_learners=ml,
        snap_learners_next=mln,
        snap_auto_leave=m.reject,
        voters=mv,
        voters_out=mvo,
        learners=ml,
        learners_next=mln,
        auto_leave=m.reject,
    )
    n = tree_where(do_restore, restored, n)

    ob = emit_one(
        spec,
        ob,
        m.frm,
        make_msg(
            spec,
            type=MSG_APP_RESP,
            term=n.term,
            frm=n.nid,
            index=jnp.where(do_restore, n.last_index, n.commit),
        ),
        enable & follower,
        fields=("index",),
    )
    return n, ob


# ---------------------------------------------------------------------------
# role step functions
# ---------------------------------------------------------------------------


def _handles(cfg: RaftConfig, *types) -> bool:
    """Trace-time: does this program handle any of these message types?
    See RaftConfig.message_classes — None handles everything; a declared
    tuple drops the other handler blocks from the compiled step."""
    return cfg.message_classes is None or any(
        t in cfg.message_classes for t in types
    )


def _step_leader(cfg, spec, n: NodeState, ob: Outbox, m: Msg, en):
    """stepLeader (raft/raft.go:991-1372), minus MsgBeat/MsgCheckQuorum
    (fired directly from tick here)."""
    ids = _ids(spec)
    frm_c = jnp.clip(m.frm, 0, spec.M - 1)
    fhot = ids == m.frm

    # ---- MsgProp (raft.go:1019-1077)
    if _handles(cfg, MSG_PROP):
        is_prop = en & (m.type == MSG_PROP)
        drop = (
            ~in_config_self(n)
            | (n.lead_transferee != NONE_ID)
            | (m.ent_len == 0)
        )
        doprop = is_prop & ~drop
        # conf-change entry guards; refused ccs are blanked to empty normal
        already_joint = is_joint(n)
        pend = n.pending_conf_index > n.applied
        e_type = m.ent_type
        e_data = m.ent_data
        new_pci = n.pending_conf_index
        for e in range(spec.E):
            valid = doprop & (e < m.ent_len)
            is_cc = valid & (e_type[e] == ENTRY_CONF_CHANGE)
            wants_leave = ccmod.is_leave_joint(e_data[e])
            refused = pend | (already_joint & ~wants_leave) | (~already_joint & wants_leave)
            keep = is_cc & ~refused
            e_type = e_type.at[e].set(jnp.where(is_cc & refused, ENTRY_NORMAL, e_type[e]))
            e_data = e_data.at[e].set(jnp.where(is_cc & refused, 0, e_data[e]))
            new_pci = jnp.where(keep, n.last_index + e + 1, new_pci)
            pend = pend | keep
        n = n.replace(pending_conf_index=jnp.where(doprop, new_pci, n.pending_conf_index))
        n, accepted = append_entries_state(cfg, spec, n, m.ent_len, e_data, e_type, doprop)
        if cfg.deferred_emit:
            dest = _progress_ids(n) & (doprop & accepted)
            p = ob.pend
            ob = ob.replace(pend=p.replace(
                send_dest=p.send_dest | dest,
                send_nonempty=p.send_nonempty | dest,
            ))
        else:
            n, ob = bcast_append(cfg, spec, n, ob, doprop & accepted)

    # ---- MsgReadIndex (raft.go:1078-1097)
    if _handles(cfg, MSG_READ_INDEX):
        is_ri = en & (m.type == MSG_READ_INDEX)
        singleton = _is_singleton(spec, n)
        local = (m.frm == NONE_ID) | (m.frm == n.nid)
        n = _rs_push(spec, n, m.context, n.commit, is_ri & singleton & local)
        ob = emit_one(
            spec,
            ob,
            m.frm,
            make_msg(
                spec, type=MSG_READ_INDEX_RESP, term=n.term, frm=n.nid,
                index=n.commit, context=m.context,
            ),
            is_ri & singleton & ~local,
            fields=("index",),
        )
        cit = _committed_in_term(spec, n)
        # defer until first commit at this term (raft.go:1087-1092)
        defer = is_ri & ~singleton & ~cit
        can_defer = defer & (n.ro_pend_count < spec.R)
        pos = jnp.minimum(n.ro_pend_count, spec.R - 1)
        sel = jnp.arange(spec.R, dtype=jnp.int32) == pos
        n = n.replace(
            ro_pend_ctx=jnp.where(sel & can_defer, m.context, n.ro_pend_ctx),
            ro_pend_from=jnp.where(sel & can_defer, m.frm, n.ro_pend_from),
            ro_pend_count=n.ro_pend_count + can_defer.astype(jnp.int32),
        )
        n, ob = _send_read_index_response(
            cfg, spec, n, ob, m.context, m.frm, is_ri & ~singleton & cit
        )

    # ---- messages requiring a Progress entry for m.frm (raft.go:1099-1104)
    has_pr = onehot_sel(_progress_ids(n), frm_c) & (m.frm >= 0)

    if _handles(cfg, MSG_APP_RESP):
        # ---- MsgAppResp (raft.go:1106-1283)
        is_ar = en & (m.type == MSG_APP_RESP) & has_pr
        n = n.replace(recent_active=n.recent_active | (fhot & is_ar))
        match_f = onehot_sel(n.match, frm_c)
        next_f = onehot_sel(n.next_idx, frm_c)
        repl_f = onehot_sel(n.pr_state, frm_c) == PR_REPLICATE

        # reject path (raft.go:1109-1236)
        rej = is_ar & m.reject
        next_probe = jnp.where(
            m.log_term > 0,
            logops.find_conflict_by_term(spec, n, m.reject_hint, m.log_term),
            m.reject_hint,
        )
        dec_repl = rej & repl_f & (m.index > match_f)
        dec_probe = rej & ~repl_f & (next_f - 1 == m.index)
        new_next = jnp.where(
            dec_repl,
            match_f + 1,
            jnp.maximum(jnp.minimum(m.index, next_probe + 1), 1),
        )
        decremented = dec_repl | dec_probe
        n = n.replace(
            next_idx=jnp.where(fhot & decremented, new_next, n.next_idx),
            probe_sent=jnp.where(fhot & dec_probe, False, n.probe_sent),
            pr_state=jnp.where(fhot & dec_repl, PR_PROBE, n.pr_state),
            pending_snapshot=jnp.where(fhot & dec_repl, 0, n.pending_snapshot),
        )
        n = infl.reset(n, fhot & dec_repl)

        # accept path (raft.go:1237-1282)
        acc = is_ar & ~m.reject
        old_paused_f = onehot_sel(_is_paused(cfg, n), frm_c)
        updated = acc & (m.index > match_f)
        n = n.replace(
            match=jnp.where(fhot & updated, m.index, n.match),
            next_idx=jnp.where(fhot & acc, jnp.maximum(n.next_idx, m.index + 1), n.next_idx),
            probe_sent=jnp.where(fhot & updated, False, n.probe_sent),
        )
        state_f = onehot_sel(n.pr_state, frm_c)
        new_match = onehot_sel(n.match, frm_c)
        to_repl = updated & (
            (state_f == PR_PROBE)
            | ((state_f == PR_SNAPSHOT) & (new_match >= onehot_sel(n.pending_snapshot, frm_c)))
        )
        n = n.replace(
            pr_state=jnp.where(fhot & to_repl, PR_REPLICATE, n.pr_state),
            next_idx=jnp.where(fhot & to_repl, new_match + 1, n.next_idx),
            pending_snapshot=jnp.where(fhot & to_repl, 0, n.pending_snapshot),
        )
        n = infl.reset(n, fhot & to_repl)
        n = infl.free_le(spec, n, fhot & updated & (state_f == PR_REPLICATE), m.index)
        n2, committed_adv = maybe_commit_state(cfg, spec, n)
        committed_adv = committed_adv & updated
        n = tree_where(committed_adv, n2, n)
        if _handles(cfg, MSG_READ_INDEX):
            # the pending-read queue only fills while handling
            # MsgReadIndex; a program whose classes exclude it can never
            # have entries to release, so the R-deep masked release pass
            # drops at trace time with the other dead handler blocks
            n, ob = _release_pending_read_index(cfg, spec, n, ob,
                                                committed_adv)

        # merged send: commit-advance broadcast (raft.go:1259-1263) OR
        # refresh/drain to the acking follower (1264-1276) OR the reject-path
        # re-probe (1230-1236); one maybe_send_append inlining covers all three.
        if cfg.coalesce_commit_refresh:
            # commit-advance broadcast deferred to node_round's end-of-round
            # flush (see RaftConfig.coalesce_commit_refresh)
            send_dest = fhot & (updated | decremented)
            send_nonempty = decremented | old_paused_f
        else:
            send_dest = jnp.where(
                committed_adv, _progress_ids(n), fhot & (updated | decremented)
            )
            send_nonempty = committed_adv | decremented | old_paused_f
        if cfg.deferred_emit:
            # accumulate; node_round's flush runs ONE merged
            # maybe_send_append over the union after the scan
            p = ob.pend
            ob = ob.replace(pend=p.replace(
                send_dest=p.send_dest | send_dest,
                send_nonempty=p.send_nonempty | (send_dest & send_nonempty),
            ))
        else:
            n, ob = maybe_send_append(cfg, spec, n, ob, send_dest,
                                      send_nonempty)

        if not cfg.deferred_emit or _handles(cfg, MSG_TRANSFER_LEADER):
            # leadership transfer (raft.go:1278-1281); under deferred_emit
            # a transfer can only be in flight if MsgTransferLeader is a
            # handled class (see RaftConfig.deferred_emit preconditions)
            xfer = updated & (m.frm == n.lead_transferee) & \
                (onehot_sel(n.match, frm_c) == n.last_index)
            ob = emit_one(
                spec,
                ob,
                m.frm,
                make_msg(spec, type=MSG_TIMEOUT_NOW, term=n.term, frm=n.nid),
                xfer,
                fields=(),
            )

    if _handles(cfg, MSG_HEARTBEAT_RESP):
        # ---- MsgHeartbeatResp (raft.go:1284-1309)
        is_hr = en & (m.type == MSG_HEARTBEAT_RESP) & has_pr
        n = n.replace(
            recent_active=n.recent_active | (fhot & is_hr),
            probe_sent=jnp.where(fhot & is_hr, False, n.probe_sent),
        )
        n = infl.free_first_one(
            spec,
            n,
            fhot
            & is_hr
            & (onehot_sel(n.pr_state, frm_c) == PR_REPLICATE)
            & onehot_sel(infl.full(cfg.max_inflight, n), frm_c),
        )
        n, ob = maybe_send_append(
            cfg, spec, n, ob, fhot & is_hr & (onehot_sel(n.match, frm_c) < n.last_index), True
        )
        if not cfg.read_only_lease_based:
            hr_ctx = is_hr & (m.context != 0)
            n, found, row = _ro_recv_ack(spec, n, m.frm, m.context, hr_ctx)
            won = (
                quorum.joint_vote_result(n.voters, n.voters_out, row, row) == VOTE_WON
            )
            n, ob = _ro_advance_emit(cfg, spec, n, ob, m.context, found & won)

    if _handles(cfg, MSG_SNAP_STATUS):
        # ---- MsgSnapStatus (raft.go:1310-1331)
        is_ss = en & (m.type == MSG_SNAP_STATUS) & has_pr & (
            onehot_sel(n.pr_state, frm_c) == PR_SNAPSHOT
        )
        pend_f = jnp.where(m.reject, 0, onehot_sel(n.pending_snapshot, frm_c))
        probe_next = jnp.maximum(onehot_sel(n.match, frm_c) + 1, pend_f + 1)
        n = n.replace(
            pr_state=jnp.where(fhot & is_ss, PR_PROBE, n.pr_state),
            next_idx=jnp.where(fhot & is_ss, probe_next, n.next_idx),
            pending_snapshot=jnp.where(fhot & is_ss, 0, n.pending_snapshot),
            probe_sent=jnp.where(fhot & is_ss, True, n.probe_sent),
        )
        n = infl.reset(n, fhot & is_ss)

    if _handles(cfg, MSG_UNREACHABLE):
        # ---- MsgUnreachable (raft.go:1332-1338)
        is_un = en & (m.type == MSG_UNREACHABLE) & has_pr & (
            onehot_sel(n.pr_state, frm_c) == PR_REPLICATE
        )
        n = n.replace(
            pr_state=jnp.where(fhot & is_un, PR_PROBE, n.pr_state),
            next_idx=jnp.where(fhot & is_un, onehot_sel(n.match, frm_c) + 1, n.next_idx),
            pending_snapshot=jnp.where(fhot & is_un, 0, n.pending_snapshot),
            probe_sent=jnp.where(fhot & is_un, False, n.probe_sent),
        )
        n = infl.reset(n, fhot & is_un)

    if _handles(cfg, MSG_TRANSFER_LEADER):
        # ---- MsgTransferLeader (raft.go:1339-1369)
        is_tl = en & (m.type == MSG_TRANSFER_LEADER) & has_pr
        ignore = onehot_sel(n.learners, frm_c) | (m.frm == n.nid) | (n.lead_transferee == m.frm)
        do_tl = is_tl & ~ignore
        n = n.replace(
            election_elapsed=jnp.where(do_tl, 0, n.election_elapsed),
            lead_transferee=jnp.where(do_tl, m.frm, n.lead_transferee),
        )
        up_to_date = onehot_sel(n.match, frm_c) == n.last_index
        ob = emit_one(
            spec,
            ob,
            m.frm,
            make_msg(spec, type=MSG_TIMEOUT_NOW, term=n.term, frm=n.nid),
            do_tl & up_to_date,
            fields=(),
        )
        n, ob = maybe_send_append(cfg, spec, n, ob, fhot & do_tl & ~up_to_date, True)
    return n, ob


def _step_candidate(cfg, spec, n, ob, m: Msg, en):
    """stepCandidate (raft/raft.go:1376-1419). MsgApp/Heartbeat/Snap are
    handled by the demote-first rewrite in process_message (the candidate has
    already become a follower by the time dispatch runs), so only the vote
    responses remain here."""
    if not _handles(cfg, MSG_VOTE_RESP, MSG_PRE_VOTE_RESP):
        return n, ob  # only vote responses are handled here (see docstring)
    pre = n.role == ROLE_PRE_CANDIDATE
    my_resp = jnp.where(pre, MSG_PRE_VOTE_RESP, MSG_VOTE_RESP)
    is_vr = en & (m.type == my_resp)
    res_before = tally_votes(n)
    n = tree_where(is_vr, record_vote(spec, n, m.frm, ~m.reject), n)
    res = tally_votes(n)
    # only the response that *transitions* the tally acts: the reference
    # changes role immediately so later stale responses are ignored; our
    # pre-candidate stays in role until the MsgHup hop lands, so dedup here.
    won = is_vr & (res == VOTE_WON) & (res_before != VOTE_WON)
    lost = is_vr & (res == VOTE_LOST) & (res_before != VOTE_LOST)
    # pre-candidate winning runs the real election next round via MsgHup
    # (the reference recurses into campaign(), raft.go:1403-1405)
    ob = _emit_hup_to_self(spec, n, ob, CAMPAIGN_FORCE, won & pre)
    # candidate winning becomes leader and broadcasts (raft.go:1406-1408)
    won_real = won & ~pre
    n = tree_where(won_real, become_leader_state(cfg, spec, n), n)
    n, ob = bcast_append(cfg, spec, n, ob, won_real)
    # losing reverts to follower at the current term (raft.go:1410-1413)
    n = tree_where(
        lost, become_follower_state(cfg, spec, n, n.term, jnp.int32(NONE_ID)), n
    )
    # MsgProp dropped (raft.go:1387-1389); MsgTimeoutNow ignored (1415-1416)
    return n, ob


def _step_follower(cfg, spec, n, ob, m: Msg, en):
    """stepFollower (raft/raft.go:1421-1473)."""
    # MsgProp: forward to the leader if known (raft.go:1423-1432)
    if _handles(cfg, MSG_PROP):
        is_prop = en & (m.type == MSG_PROP)
        fwd_ok = (n.lead != NONE_ID) & (not cfg.disable_proposal_forwarding)
        if cfg.deferred_emit:
            # record the forward intent; the flush emits one MsgProp per
            # destination (an earlier same-round forward to the same
            # leader is superseded — proposals are drop-legal)
            p = ob.pend
            hot = (jnp.arange(spec.M, dtype=jnp.int32) == n.lead) & \
                (is_prop & fwd_ok)
            ob = ob.replace(pend=p.replace(
                fwd_any=p.fwd_any | hot,
                fwd_len=jnp.where(hot, m.ent_len, p.fwd_len),
                fwd_data=jnp.where(hot[:, None], m.ent_data[None, :],
                                   p.fwd_data),
                fwd_type=jnp.where(hot[:, None], m.ent_type[None, :],
                                   p.fwd_type),
            ))
        else:
            ob = emit_one(
                spec, ob, n.lead, m.replace(frm=n.nid, term=jnp.int32(0)),
                is_prop & fwd_ok,
            )

    # MsgApp/MsgHeartbeat/MsgSnap from the leader (raft.go:1433-1444)
    lead_msg = en & (
        (m.type == MSG_APP) | (m.type == MSG_HEARTBEAT) | (m.type == MSG_SNAP)
    )
    n = n.replace(
        election_elapsed=jnp.where(lead_msg, 0, n.election_elapsed),
        lead=jnp.where(lead_msg, m.frm, n.lead),
    )
    if _handles(cfg, MSG_APP):
        n, ob = handle_append_entries(cfg, spec, n, ob, m, lead_msg & (m.type == MSG_APP))
    if _handles(cfg, MSG_HEARTBEAT):
        n, ob = handle_heartbeat(cfg, spec, n, ob, m, lead_msg & (m.type == MSG_HEARTBEAT))
    if _handles(cfg, MSG_SNAP):
        n, ob = handle_snapshot(cfg, spec, n, ob, m, lead_msg & (m.type == MSG_SNAP))

    # MsgTransferLeader / MsgReadIndex forwarded to the leader (1445-1451, 1458-1464)
    if _handles(cfg, MSG_TRANSFER_LEADER, MSG_READ_INDEX):
        fwd = en & (
            (m.type == MSG_TRANSFER_LEADER) | (m.type == MSG_READ_INDEX)
        ) & (n.lead != NONE_ID)
        ob = emit_one(spec, ob, n.lead, m, fwd)

    # MsgTimeoutNow: campaign immediately, no pre-vote (raft.go:1452-1457)
    if _handles(cfg, MSG_TIMEOUT_NOW):
        ob = _emit_hup_to_self(
            spec, n, ob, CAMPAIGN_TRANSFER, en & (m.type == MSG_TIMEOUT_NOW)
        )

    # MsgReadIndexResp -> local ReadState (raft.go:1465-1471)
    if _handles(cfg, MSG_READ_INDEX_RESP):
        n = _rs_push(
            spec, n, m.context, m.index, en & (m.type == MSG_READ_INDEX_RESP)
        )
    return n, ob


# ---------------------------------------------------------------------------
# Step: term gate + dispatch (raft.go:847-987)
# ---------------------------------------------------------------------------


def process_message(cfg: RaftConfig, spec: Spec, n: NodeState, ob: Outbox, m: Msg):
    active = m.type != MSG_NONE
    local = m.term == 0  # MsgProp / MsgHup / forwarded MsgReadIndex / empty
    higher = active & ~local & (m.term > n.term)
    lower = active & ~local & (m.term < n.term)

    vote_like = (m.type == MSG_VOTE) | (m.type == MSG_PRE_VOTE)
    force = m.context == CAMPAIGN_TRANSFER
    in_lease = (
        cfg.check_quorum
        & (n.lead != NONE_ID)
        & (n.election_elapsed < cfg.election_tick)
    )
    drop_lease = higher & vote_like & ~force & in_lease

    keep_term = (m.type == MSG_PRE_VOTE) | (
        (m.type == MSG_PRE_VOTE_RESP) & ~m.reject
    )
    do_bf = higher & ~drop_lease & ~keep_term
    from_is_lead = (
        (m.type == MSG_APP) | (m.type == MSG_HEARTBEAT) | (m.type == MSG_SNAP)
    )
    nbf = become_follower_state(
        cfg, spec, n, m.term, jnp.where(from_is_lead, m.frm, NONE_ID)
    )
    n = tree_where(do_bf, nbf, n)

    # lower-term handling consumes the message (raft.go:883-919)
    if _handles(cfg, MSG_HEARTBEAT, MSG_APP):
        lt_push = (
            lower
            & (cfg.check_quorum or cfg.pre_vote)
            & ((m.type == MSG_HEARTBEAT) | (m.type == MSG_APP))
        )
        if cfg.deferred_emit:
            ob = _pend_reply(spec, ob, m.frm, lt_push, n.term,
                             jnp.int32(0), jnp.zeros((), jnp.bool_),
                             jnp.int32(0), jnp.int32(0))
        else:
            ob = emit_one(
                spec,
                ob,
                m.frm,
                make_msg(spec, type=MSG_APP_RESP, term=n.term, frm=n.nid),
                lt_push,
                fields=(),
            )
    if _handles(cfg, MSG_PRE_VOTE):
        lt_prevote = lower & (m.type == MSG_PRE_VOTE)
        ob = emit_one(
            spec,
            ob,
            m.frm,
            make_msg(spec, type=MSG_PRE_VOTE_RESP, term=n.term, frm=n.nid, reject=True),
            lt_prevote,
            fields=(),
        )
    proceed = active & ~drop_lease & ~lower

    # ---- MsgHup (raft.go:923-928); the single campaign() inlining
    if _handles(cfg, MSG_HUP):
        n, ob = hup(cfg, spec, n, ob, m.context, proceed & (m.type == MSG_HUP))

    # ---- Msg{Pre,}Vote for any role (raft.go:930-978)
    if _handles(cfg, MSG_VOTE, MSG_PRE_VOTE):
        is_vreq = proceed & vote_like
        can_vote = (
            (n.vote == m.frm)
            | ((n.vote == NONE_ID) & (n.lead == NONE_ID))
            | ((m.type == MSG_PRE_VOTE) & (m.term > n.term))
        )
        utd = logops.is_up_to_date(spec, n, m.index, m.log_term)
        grant = is_vreq & can_vote & utd
        resp_type = jnp.where(m.type == MSG_VOTE, MSG_VOTE_RESP, MSG_PRE_VOTE_RESP)
        ob = emit_one(
            spec,
            ob,
            m.frm,
            make_msg(spec, frm=n.nid).replace(
                type=resp_type,
                term=jnp.where(grant, m.term, n.term),
                reject=~grant,
            ),
            is_vreq,
            fields=(),
        )
        real_grant = grant & (m.type == MSG_VOTE)
        n = n.replace(
            election_elapsed=jnp.where(real_grant, 0, n.election_elapsed),
            vote=jnp.where(real_grant, m.frm, n.vote),
        )

    # ---- candidates seeing a current leader demote first (raft.go:1390-1398)
    rest = proceed & ~vote_like & (m.type != MSG_HUP)
    cand = (n.role == ROLE_CANDIDATE) | (n.role == ROLE_PRE_CANDIDATE)
    demote = rest & cand & from_is_lead
    n = tree_where(demote, become_follower_state(cfg, spec, n, m.term, m.frm), n)

    # ---- role dispatch
    n, ob = _step_leader(cfg, spec, n, ob, m, rest & (n.role == ROLE_LEADER))
    n, ob = _step_candidate(
        cfg,
        spec,
        n,
        ob,
        m,
        rest & ((n.role == ROLE_CANDIDATE) | (n.role == ROLE_PRE_CANDIDATE)),
    )
    n, ob = _step_follower(cfg, spec, n, ob, m, rest & (n.role == ROLE_FOLLOWER))
    return n, ob


# ---------------------------------------------------------------------------
# tick (raft.go:645-684); returns an election-fire flag instead of
# campaigning inline — the campaign runs through the round's MsgHup slot.
# ---------------------------------------------------------------------------


def tick_timers(cfg: RaftConfig, spec: Spec, n: NodeState, ob: Outbox, enable):
    is_lead = n.role == ROLE_LEADER

    # tickElection for followers/candidates (raft.go:645-654)
    ee = n.election_elapsed + 1
    fire = enable & ~is_lead & promotable(spec, n) & (ee >= n.randomized_timeout)
    n = n.replace(
        election_elapsed=jnp.where(
            enable & ~is_lead, jnp.where(fire, 0, ee), n.election_elapsed
        )
    )

    # tickHeartbeat for leaders (raft.go:657-684)
    ee2 = n.election_elapsed + 1
    et_fire = enable & is_lead & (ee2 >= cfg.election_tick)
    n = n.replace(
        election_elapsed=jnp.where(
            enable & is_lead, jnp.where(et_fire, 0, ee2), n.election_elapsed
        )
    )
    if cfg.check_quorum:
        # MsgCheckQuorum step (raft.go:997-1018)
        sh = _self_hot(spec, n)
        granted = n.recent_active | sh
        qa = (
            quorum.joint_vote_result(
                n.voters, n.voters_out, _progress_ids(n) | sh, granted
            )
            == VOTE_WON
        )
        step_down = et_fire & ~qa
        n = tree_where(
            step_down,
            become_follower_state(cfg, spec, n, n.term, jnp.int32(NONE_ID)),
            n,
        )
        still = et_fire & ~step_down
        n = n.replace(
            recent_active=jnp.where(still, sh & n.recent_active, n.recent_active)
        )
    # abort unfinished transfer after an election timeout (raft.go:668-671)
    n = n.replace(
        lead_transferee=jnp.where(
            et_fire & (n.role == ROLE_LEADER), NONE_ID, n.lead_transferee
        )
    )

    he = n.heartbeat_elapsed + 1
    hb_fire = enable & (n.role == ROLE_LEADER) & (he >= cfg.heartbeat_tick)
    n = n.replace(
        heartbeat_elapsed=jnp.where(
            enable & (n.role == ROLE_LEADER),
            jnp.where(hb_fire, 0, he),
            n.heartbeat_elapsed,
        )
    )
    n, ob = bcast_heartbeat(cfg, spec, n, ob, _ro_last_ctx(n), hb_fire)
    return n, ob, fire


# ---------------------------------------------------------------------------
# apply cycle (Ready/Advance analog)
# ---------------------------------------------------------------------------


def apply_round(cfg: RaftConfig, spec: Spec, n: NodeState, ob: Outbox):
    """Apply up to Spec.A committed entries: conf changes take effect
    (raft.go:1623-1700), the state-machine hash advances, auto-leave fires
    (raft.go:554-570), and the ring compacts at the applied cursor when near
    capacity (the triggerSnapshot analog, server.go:1088-1104)."""

    # Trace-time specialization (RaftConfig.entry_classes): when the
    # program declares it never commits conf-change entries, the
    # apply_conf_change mask algebra, the auto-leave pass and the
    # leave-entry append below are statically dead and drop out — in a
    # masked-SPMD step dead code costs like live code, and this block
    # replays on all Spec.A serial slots.
    handle_cc = cfg.entry_classes is None or \
        "conf_change" in cfg.entry_classes

    def body(carry, _):
        n, ob = carry
        idx = n.applied + 1
        can = idx <= n.commit
        s = logops.slot(spec, idx)
        e_term = logops.ring_read(n.log_term, s)
        e_data = logops.ring_read(n.log_data, s)
        if handle_cc:
            e_type = logops.ring_read(n.log_type, s)
            is_cc = can & (e_type == ENTRY_CONF_CHANGE)
            n, ob = ccmod.apply_conf_change(cfg, spec, n, ob, e_data, is_cc)
        n = n.replace(
            applied=jnp.where(can, idx, n.applied),
            applied_hash=jnp.where(
                can, _mix_hash(n.applied_hash, idx, e_term, e_data), n.applied_hash
            ),
            uncommitted_size=jnp.where(
                can & (n.role == ROLE_LEADER),
                jnp.maximum(n.uncommitted_size - 1, 0),
                n.uncommitted_size,
            ),
        )
        return (n, ob), None

    (n, ob), _ = jax.lax.scan(body, (n, ob), None, length=spec.A)

    if handle_cc:
        # auto-leave joint config (advance(), raft.go:554-570) — only
        # reachable through committed conf changes, so it specializes
        # away with them
        al = (
            (n.role == ROLE_LEADER)
            & n.auto_leave
            & is_joint(n)
            & (n.applied >= n.pending_conf_index)
        )
        zE = jnp.zeros((spec.E,), jnp.int32)
        leave_data = zE.at[0].set(ccmod.encode_leave_joint())
        leave_type = zE.at[0].set(ENTRY_CONF_CHANGE)
        n, acc = append_entries_state(
            cfg, spec, n, 1, leave_data, leave_type, al, count_quota=False
        )
        n = n.replace(
            pending_conf_index=jnp.where(al & acc, n.last_index, n.pending_conf_index)
        )
        # NB: append only — no immediate bcast. The reference's advance()
        # (raft.go:554-570) appends the leave entry without broadcasting;
        # followers pick it up from the next triggered send.

    # compaction: snapshot at the applied cursor when the ring is nearly full
    occ = n.last_index - n.snap_index
    do_c = (occ > spec.L - 2 * spec.E) & (n.applied > n.snap_index)
    t_app, _ = logops.term_at(spec, n, n.applied)
    compacted = n.replace(
        snap_index=n.applied,
        snap_term=t_app,
        snap_hash=n.applied_hash,
        snap_voters=n.voters,
        snap_voters_out=n.voters_out,
        snap_learners=n.learners,
        snap_learners_next=n.learners_next,
        snap_auto_leave=n.auto_leave,
    )
    n = tree_where(do_c, compacted, n)
    return n, ob


# ---------------------------------------------------------------------------
# whole round for one node
# ---------------------------------------------------------------------------


def compact_inbox(spec: Spec, flat: Msg, bound: int) -> Msg:
    """Compact a node's flattened inbox [S=M*K, ...] to its first `bound`
    nonempty slots (original order kept); later messages are dropped.

    The slot->slot routing is a one-hot contraction (sel[b, s] = slot s is
    the b-th nonempty), not a gather: at fleet shapes the [B, S] plane is
    tiny next to C and the multiply-sum fuses into the reduction, while a
    batched gather materializes per-(node, cluster) index tensors.
    See RaftConfig.inbox_bound for the drop-legality argument."""
    S = flat.type.shape[0]
    B = min(bound, S)
    if B >= S:
        return flat
    nonempty = flat.type != MSG_NONE                       # [S]
    rank = jnp.cumsum(nonempty.astype(jnp.int32)) - 1      # [S]
    sel = (
        rank[None, :] == jnp.arange(B, dtype=jnp.int32)[:, None]
    ) & nonempty[None, :]                                  # [B, S]

    def take(x):
        s = sel.reshape(sel.shape + (1,) * (x.ndim - 1))
        if x.dtype == jnp.bool_:
            return (s & x[None]).any(axis=1)
        return (s.astype(x.dtype) * x[None]).sum(axis=1)

    return jax.tree.map(take, flat)


def _flush_deferred(cfg, spec, n: NodeState, ob: Outbox):
    """Materialize the PendingWire intents accumulated during the message
    scan: ONE AppResp emit + ONE proposal-forward emit + ONE merged
    maybe_send_append (the post-scan merge of PROFILE.md's emission
    restructure). Runs once per round, outside the scan carry."""
    p = ob.pend
    base = bcast(spec, make_msg(spec))
    rep = base.replace(
        type=jnp.where(p.rep_any, MSG_APP_RESP, MSG_NONE),
        term=p.rep_term,
        frm=jnp.broadcast_to(n.nid, (spec.M,)),
        index=p.rep_index,
        reject=p.rep_reject,
        reject_hint=p.rep_hint,
        log_term=p.rep_logterm,
    )
    ob = emit(spec, ob, p.rep_any, rep,
              fields=("index", "reject_hint", "log_term"))
    fwd = base.replace(
        type=jnp.where(p.fwd_any, MSG_PROP, MSG_NONE),
        frm=jnp.broadcast_to(n.nid, (spec.M,)),
        ent_len=p.fwd_len,
        ent_data=p.fwd_data,
        ent_type=p.fwd_type,
    )
    ob = emit(spec, ob, p.fwd_any, fwd,
              fields=("ent_len", "ent_data", "ent_type"))
    n, ob = maybe_send_append(cfg, spec, n, ob, p.send_dest,
                              p.send_nonempty)
    return n, ob


def node_round(
    cfg: RaftConfig,
    spec: Spec,
    n: NodeState,
    inbox: Msg,  # leaves [M(from), K, ...]; pre-compacted [B, ...] under
                 # cfg.compact_wire (the engine moved the per-receiver
                 # compaction to the round boundary)
    prop_len,    # i32 scalar: entries proposed locally this round
    prop_data,   # i32[E]
    prop_type,   # i32[E]
    ri_ctx,      # i32 scalar: nonzero => inject a MsgReadIndex with this ctx
    do_hup,      # bool scalar: inject MsgHup (campaign)
    do_tick,     # bool scalar
):
    """One lockstep round for one node: tick -> [hup, inbox..., prop,
    read-index] message scan -> apply. Returns (state, outbox)."""
    ob = empty_outbox(spec, deferred=cfg.deferred_emit)
    if "tick" in cfg.local_steps:
        n, ob, fire = tick_timers(
            cfg, spec, n, ob, jnp.asarray(do_tick, jnp.bool_)
        )
    else:
        # never-ticking program (bench steady loop): tick_timers is a
        # pure masked no-op when do_tick is all-False — dropped at trace
        # time (RaftConfig.local_steps)
        fire = jnp.zeros_like(jnp.asarray(do_tick, jnp.bool_))
    commit0 = n.commit  # round-start commit, for the coalesced flush below

    # Each local step below is one full masked pass over node state; the
    # cfg.local_steps tuple drops statically-dead ones from perf programs
    # (see RaftConfig.local_steps for the soundness argument).
    do_hup_step = "hup" in cfg.local_steps
    do_prop_step = "prop" in cfg.local_steps
    do_ri_step = "read_index" in cfg.local_steps
    hup_msg = make_msg(spec, frm=n.nid).replace(
        type=jnp.where(do_hup | fire, MSG_HUP, MSG_NONE),
        context=jnp.int32(CAMPAIGN_NONE),
    )
    prop_msg = make_msg(spec, frm=n.nid).replace(
        type=jnp.where(prop_len > 0, MSG_PROP, MSG_NONE),
        ent_len=jnp.asarray(prop_len, jnp.int32),
        ent_data=prop_data,
        ent_type=prop_type,
    )
    ri_msg = make_msg(spec, frm=n.nid).replace(
        type=jnp.where(ri_ctx != 0, MSG_READ_INDEX, MSG_NONE),
        context=jnp.asarray(ri_ctx, jnp.int32),
    )

    # NB: the inbox is scanned DIRECTLY (its [K, M] leading axes reshape
    # to one slot axis for free) and the three synthesized local messages
    # run as separate inlined steps. Stacking everything into one `seq`
    # tensor with jnp.concatenate materialized multi-GB padded temps at
    # fleet C (XLA placed the tiny E axis minor: 5x65536x2x5x1 ->
    # 2.5GB x3 in the C=65536 compile report); slicing the inbox in
    # place has no such copy.
    if do_hup_step:
        n, ob = process_message(cfg, spec, n, ob, hup_msg)

    if cfg.compact_wire:
        # the engine compacted this inbox at the previous round's
        # boundary (engine.compact_wire_carry): leaves are already the
        # first-`inbox_bound` nonempty slots in delivery order
        flat = inbox
    else:
        flat = jax.tree.map(
            lambda x: x.reshape((spec.M * spec.K,) + x.shape[2:]), inbox
        )
        if cfg.inbox_bound:
            flat = compact_inbox(spec, flat, cfg.inbox_bound)
    # Scan the message slots. A straight-line unroll was tried (rounds 1-3)
    # and removed: on TPU the per-step optimization barriers it needed to
    # bound peak HBM shattered the round into ~13k unfusable ops whose fixed
    # per-op overhead dominated (bench.py history), and on XLA CPU the
    # unrolled compile was pathological (>6GB compile RSS even at C=1,
    # SIGSEGV in the full suite). The scan form runs the same math with one
    # while iteration per slot; the throughput lever is batch scale C.
    if cfg.sparse_outbox:
        # the dense outbox leaves the scan carry entirely (the completion
        # of PROFILE.md's emission restructure): under the validated
        # message classes every reachable in-scan handler records
        # PendingWire intents, so the carry is (NodeState, PendingWire)
        # and the [K, M] planes are only written by the post-scan merge.
        # `ob` is closed over as a scan constant; its msgs/counts are
        # provably untouched inside the body (RaftConfig.sparse_outbox).
        def body(carry, m):
            nn, pend = carry
            nn, oo = process_message(cfg, spec, nn, ob.replace(pend=pend), m)
            return (nn, oo.pend), None

        (n, pend), _ = jax.lax.scan(body, (n, ob.pend), flat)
        ob = ob.replace(pend=pend)
    else:
        def body(carry, m):
            nn, oo = carry
            nn, oo = process_message(cfg, spec, nn, oo, m)
            return (nn, oo), None

        (n, ob), _ = jax.lax.scan(body, (n, ob), flat)

    if do_prop_step:
        n, ob = process_message(cfg, spec, n, ob, prop_msg)
    if do_ri_step:
        n, ob = process_message(cfg, spec, n, ob, ri_msg)

    if cfg.deferred_emit:
        n, ob = _flush_deferred(cfg, spec, n, ob)

    if cfg.coalesce_commit_refresh:
        # End-of-round commit flush, replacing the per-ack bcastAppend
        # suppressed in _step_leader: if this round advanced the leader's
        # commit, send one (possibly empty) append to every follower whose
        # messages this round (if any) carried a now-stale commit — e.g. a
        # round-start heartbeat emitted before the acks advanced commit.
        # sent_commit tracks the best commit each dest already received.
        stale = ob.sent_commit < jnp.minimum(n.match, n.commit)
        refresh = (
            (n.role == ROLE_LEADER) & (n.commit > commit0)
            & _progress_ids(n) & ((ob.counts == 0) | stale)
        )
        n, ob = maybe_send_append(cfg, spec, n, ob, refresh, True)

    n, ob = apply_round(cfg, spec, n, ob)
    return n, ob
