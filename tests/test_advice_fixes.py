"""Regression tests for the round-1 advisor findings: deterministic auth
applies, WAL open-for-append, watcher-overflow revision rollback, and the
peer-snapshot path when the device log compacts past a member's host-applied
cursor."""
import numpy as np
import pytest

from etcd_tpu.server.auth import AuthStore
from etcd_tpu.server.kvserver import EtcdCluster, ErrCorrupt
from etcd_tpu.server.mvcc import MVCCStore
from etcd_tpu.server.watch import WatchableStore, Watcher
from etcd_tpu.storage.wal import WAL


# ---------------------------------------------------------------- auth salt
def test_auth_apply_is_deterministic_across_members():
    """user_add/change_password hash at propose time and replicate
    (salt, hash), so every member holds identical auth state
    (auth/store.go stores the hash carried in the AuthUserAdd request)."""
    srv = EtcdCluster(n_members=3)
    srv.ensure_leader()
    srv.auth_request("auth_user_add", name="alice", password="secret")
    srv.auth_request("auth_user_change_password", name="alice",
                     password="rotated")
    srv.stabilize()
    users = [srv.members[m].auth.users["alice"] for m in range(3)]
    assert users[0].salt == users[1].salt == users[2].salt
    assert users[0].pw_hash == users[1].pw_hash == users[2].pw_hash
    # and the replicated hash actually verifies the password
    srv.auth_request("auth_role_add", name="r")
    srv.auth_request("auth_user_grant_role", name="alice", role="r")
    assert srv.members[0].auth.users["alice"].pw_hash


def test_auth_store_restore_roundtrip():
    a = AuthStore()
    a.user_add("root", "pw")
    a.role_add("root")
    a.user_grant_role("root", "root")
    a.auth_enable()
    b = AuthStore()
    b.restore(a.to_snapshot())
    assert b.enabled and b.revision == a.revision
    assert b.users["root"].pw_hash == a.users["root"].pw_hash
    assert b.users["root"].roles == {"root"}


# ---------------------------------------------------------------- WAL open
def test_wal_open_existing_then_save(tmp_path):
    """WAL(dir); wal.save(...) on a pre-existing log appends at the tail
    (wal.go Open reads to tail before the WAL is appendable)."""
    d = str(tmp_path / "wal")
    w = WAL(d, metadata=b"node1")
    w.save({"term": 1, "vote": 0, "commit": 0}, [{"index": 1, "term": 1}])
    w.close()
    w2 = WAL(d)  # no explicit read_all
    w2.save({"term": 1, "vote": 0, "commit": 1}, [{"index": 2, "term": 1}])
    w2.close()
    meta, hs, ents, snap = WAL(d).read_all()
    assert meta == b"node1"
    assert [e["index"] for e in ents] == [1, 2]
    assert hs["commit"] == 1


def test_wal_metadata_survives_segment_cut(tmp_path, monkeypatch):
    """Segments created by cut carry the metadata record, so metadata
    survives release_to() dropping the first segment (wal.go cut)."""
    import etcd_tpu.storage.wal as walmod

    monkeypatch.setattr(walmod, "SEGMENT_BYTES", 256)
    d = str(tmp_path / "wal")
    w = WAL(d, metadata=b"m0")
    for i in range(1, 40):
        w.save({"term": 1, "vote": 0, "commit": i},
               [{"index": i, "term": 1, "data": b"x" * 32}])
    w.save_snapshot(30, 1)
    assert len(w._segments()) > 1
    w.release_to(30)
    w.close()
    meta, _, _, _ = WAL(d).read_all()
    assert meta == b"m0"


# ------------------------------------------------------------- watch victim
def test_watch_overflow_no_duplicate_events(monkeypatch):
    """A synced watcher overflowing mid-revision rolls back to the revision
    boundary: after catch-up the client sees every event exactly once."""
    monkeypatch.setattr(Watcher, "MAX_BUFFER", 3)
    ws = WatchableStore()
    w = ws.watch(b"k", range_end=b"\x00")
    # txn 1: two ops at one revision (fills buffer to 2)
    txn = ws.kv.write_txn()
    txn.put(b"k1", b"a")
    txn.put(b"k2", b"b")
    txn.end()
    ws.notify(txn.events)
    # txn 2: two ops at one revision; second op overflows MAX_BUFFER=3
    txn = ws.kv.write_txn()
    txn.put(b"k3", b"c")
    txn.put(b"k4", b"d")
    txn.end()
    ws.notify(txn.events)
    assert w.victim
    got = [e.kv.key for e in ws.take_events(w.id)]
    # catch-up must deliver the whole second revision exactly once
    while ws.sync_watchers() == 0 and (w.victim or w.id in ws.unsynced):
        pass
    got += [e.kv.key for e in ws.take_events(w.id)]
    assert got == [b"k1", b"k2", b"k3", b"k4"]


# ------------------------------------------------- peer snapshot install
def test_member_snapshot_restore_roundtrip():
    srv = EtcdCluster(n_members=3)
    srv.ensure_leader()
    srv.put(b"a", b"1")
    srv.put(b"b", b"2")
    srv.lease_grant(7, 30)
    srv.stabilize()
    snap = srv.member_snapshot(0)
    # wipe member 2 and restore from member 0's snapshot
    srv.restore_member(2, snap)
    assert srv.members[2].applied_index == srv.members[0].applied_index
    assert srv.hash_kv(2) == srv.hash_kv(0)
    assert 7 in srv.members[2].lessor.leases


def test_pump_gap_installs_peer_snapshot_or_fails_loudly():
    srv = EtcdCluster(n_members=3)
    srv.ensure_leader()
    for i in range(4):
        srv.put(b"k%d" % i, b"v%d" % i)
    srv.stabilize()
    # simulate a member whose host apply fell behind a device snapshot
    ms = srv.members[2]
    ms.store.restore(MVCCStore())
    ms.lessor.restore({})
    ms.applied_index = 0
    srv._install_peer_snapshot(2, ms, need=srv.members[0].applied_index)
    assert srv.hash_kv(2) == srv.hash_kv(0)
    assert ms.applied_index == srv.members[0].applied_index
    # no donor far enough -> loud failure, not silent divergence
    with pytest.raises(ErrCorrupt):
        srv._install_peer_snapshot(2, ms, need=10**9)
