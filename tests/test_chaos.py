"""Functional chaos tier tests (tester/cluster.go:43-65 inject->stress->
recover->check loop, KV_HASH checker, delay faults of
rafttest/network.go:122-144 / pkg/proxy).

The default test runs a modest fleet on the CPU mesh; the BASELINE
config #3/#5 scale runs (100k / 1M groups) execute the same code path
and are gated behind SCALE_TESTS=1 (minutes of runtime; exercised on TPU
via chaos_run.py — see CHAOS_r*.json evidence files).
"""
import os

import pytest

from etcd_tpu.harness.chaos import run_chaos
from etcd_tpu.types import Spec
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=5, L=32, E=2, K=4, W=2, R=2, A=4)
CFG = RaftConfig(pre_vote=True, check_quorum=True)


def assert_safe(rep):
    assert rep["multi_leader"] == 0, rep
    assert rep["hash_mismatch"] == 0, rep
    assert rep["commit_regress"] == 0, rep


def test_chaos_small_fleet_under_faults():
    # CPU smoke geometry: scan execution costs minutes per 100 rounds at
    # C=256 on the 1-core test VM, and the real scale/duration coverage
    # runs on TPU (chaos_run.py -> CHAOS_r03.json: 524k groups x 200
    # rounds); this tier proves the code path + checkers, not the scale
    rep = run_chaos(
        SPEC, CFG, C=64, rounds=75, epoch_len=25, heal_len=25, seed=1,
        drop_p=0.03, delay_p=0.08, partition_p=0.2,
    )
    assert_safe(rep)
    # recovery: every group has a leader after the final heal epoch and
    # the healed fleet commits (liveness bar, tests/functional/README)
    assert rep["groups_with_leader_after_heal"] == rep["groups"]
    assert rep["heal_commits_last_epoch"] > 0
    # faults didn't freeze the fleet: chaos epochs still commit somewhere
    assert sum(dc for dc, _ in rep["epoch_commits"]) > 0


def test_chaos_delay_free_program():
    """delay_p=0 compiles the delay machinery OUT (no held buffer in the
    scan carry — the structure the 1M-group TPU tier depends on); its
    5-element carry and held=None plumbing must hold up in-suite, not
    only in multi-hour TPU runs."""
    rep = run_chaos(
        SPEC, CFG, C=64, rounds=50, epoch_len=25, heal_len=25, seed=4,
        drop_p=0.05, delay_p=0.0, partition_p=0.2,
    )
    assert_safe(rep)
    assert rep["groups_with_leader_after_heal"] == rep["groups"]
    assert rep["heal_commits_last_epoch"] > 0
    assert sum(dc for dc, _ in rep["epoch_commits"]) > 0


def test_chaos_heavy_partitions_stay_safe():
    """Aggressive partitions + drops: liveness may suffer, safety must
    not."""
    rep = run_chaos(
        SPEC, CFG, C=64, rounds=50, epoch_len=25, heal_len=25, seed=7,
        drop_p=0.15, delay_p=0.15, partition_p=0.6,
    )
    assert_safe(rep)
    assert rep["groups_with_leader_after_heal"] == rep["groups"]


def test_lease_chaos_expiry_under_faults():
    """Host-layer lease tier (tester/stresser_lease.go +
    checker_lease_expire.go analogs): kept-alive leases survive a faulted
    epoch, abandoned and short-TTL leases expire WITH their keys revoked
    through consensus."""
    from etcd_tpu.harness.chaos_lease import run_lease_chaos

    rep = run_lease_chaos(
        n_members=3, n_leases=4, ttl=8, short_ttl=1,
        fault_rounds=12, drop_p=0.2, seed=5,
    )
    assert rep["lease_violations"] == [], rep
    assert rep["lease_keepalives_ok"] > 0
    # r5 gates: bounded indeterminacy (<=1 of kept) AND a request
    # failure rate the retrying stresser sustains (<=20%); the tier
    # FAILS rather than excusing itself (r4 verdict Weak #3)
    assert rep["lease_gate_failures"] == [], rep
    assert rep["lease_mid_epoch_short_granted"], rep


def test_runner_chaos_election_exclusion():
    """Election runners under faults (tester/stresser_runner.go analog):
    mutual exclusion holds, elections make progress after heal."""
    from etcd_tpu.harness.chaos_lease import run_runner_chaos

    rep = run_runner_chaos(n_members=3, n_runners=2, fault_rounds=8,
                           drop_p=0.15, seed=2)
    assert rep["runner_exclusion_violations"] == 0
    assert rep["runner_final_progress"]


@pytest.mark.skipif(
    not os.environ.get("SCALE_TESTS"),
    reason="BASELINE #3 scale run: set SCALE_TESTS=1 (minutes; meant for TPU)",
)
def test_chaos_100k_groups():
    rep = run_chaos(
        SPEC, CFG, C=100_000, rounds=200, epoch_len=50, heal_len=25,
        seed=3, drop_p=0.02, delay_p=0.05, partition_p=0.1,
    )
    assert_safe(rep)
    assert rep["groups_with_leader_after_heal"] == rep["groups"]
    assert rep["heal_commits_last_epoch"] > 0
