"""RawNode: the synchronous per-group driver contract over device kernels.

The reference's ``RawNode`` (raft/rawnode.go:34-241) is the thread-unsafe
API every etcd server drives: mutate the state machine via Campaign /
Propose / Step / Tick, then harvest pending work as an immutable ``Ready``
batch (raft/node.go:52-90), persist/send/apply it, and ``Advance``. This
module provides the same contract backed by the TPU engine's kernels: a
RawNode owns one *lane* of the fleet — a single-node :class:`NodeState`
pytree stepped by the very same ``process_message`` / ``tick_timers`` /
``apply_round`` functions that ``node_round`` fuses for the batched fleet
(etcd_tpu/models/raft.py), jitted here at batch=1.

Ready/Advance accounting mirrors rawnode.go:125-179: prev Soft/HardState
are remembered at Ready() (acceptReady) and committed at Advance();
MustSync follows node.go:586-593 (term or vote changed, or new entries).

Differences from the reference (deliberate):
  * Snapshots restore eagerly inside the step (see
    models/raft.py handle_snapshot); Ready still surfaces the snapshot so
    the application can persist it, but the in-memory log has already
    adopted it.
  * Conf changes are applied by the engine at apply time (inside
    ``apply_round``) rather than via an explicit ApplyConfChange call;
    Advance() therefore both advances the applied cursor and performs the
    config switch, and `last_conf_states` reports switches for drivers
    that want the reference's return value.
"""
# lint: allow-module(host-sync) -- RawNode is the synchronous per-group host
# adapter by contract (jitted at batch=1, driven step-by-step); every Ready()
# harvest is a deliberate host round-trip, not a traced-round regression.
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from etcd_tpu.models import raft as raftmod
from etcd_tpu.models.state import NodeState, init_node
from etcd_tpu.ops import log as logops
from etcd_tpu.ops.outbox import Outbox, empty_outbox, make_msg
from etcd_tpu.storage.raftstorage import (
    ConfState,
    Entry,
    HardState,
    Snapshot,
    SnapshotMeta,
    Storage,
)
from etcd_tpu.types import (
    ENT_FIELDS,
    CAMPAIGN_NONE,
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    MSG_HUP,
    MSG_NONE,
    MSG_PROP,
    MSG_SNAP,
    NONE_ID,
    PR_PROBE,
    PR_REPLICATE,
    PR_SNAPSHOT,
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    ROLE_PRE_CANDIDATE,
    Msg,
    Spec,
    pack_mask,
)
from etcd_tpu.utils.config import RaftConfig

ROLE_NAMES = {
    ROLE_FOLLOWER: "StateFollower",
    ROLE_PRE_CANDIDATE: "StatePreCandidate",
    ROLE_CANDIDATE: "StateCandidate",
    ROLE_LEADER: "StateLeader",
}

# IsResponseMsg (raft/util.go:47-50)
_RESPONSE_TYPES = {
    2, 4, 7, 9, 15,  # AppResp, VoteResp, HeartbeatResp, PreVoteResp, Unreachable
}


class ErrStepLocalMsg(Exception):
    """raft: cannot step raft local message (rawnode.go:70-72)."""


class ErrStepPeerNotFound(Exception):
    """raft: cannot step as peer not found (rawnode.go:74-78)."""
PR_NAMES = {PR_PROBE: "StateProbe", PR_REPLICATE: "StateReplicate",
            PR_SNAPSHOT: "StateSnapshot"}


@dataclasses.dataclass
class HostMsg:
    """Host-side message record (raftpb.Message analog with explicit to)."""

    type: int
    to: int
    frm: int
    term: int = 0
    index: int = 0
    log_term: int = 0
    commit: int = 0
    reject: bool = False
    reject_hint: int = 0
    context: int = 0
    entries: tuple[Entry, ...] = ()
    snapshot: Snapshot | None = None  # MsgSnap only


@dataclasses.dataclass
class SoftState:
    lead: int
    role: int  # ROLE_*


@dataclasses.dataclass
class ReadState:
    index: int
    request_ctx: int


@dataclasses.dataclass
class Ready:
    """The pending-work batch (raft/node.go:52-90)."""

    soft_state: SoftState | None = None
    hard_state: HardState | None = None  # None == unchanged (empty)
    read_states: list[ReadState] = dataclasses.field(default_factory=list)
    entries: list[Entry] = dataclasses.field(default_factory=list)
    snapshot: Snapshot | None = None
    committed_entries: list[Entry] = dataclasses.field(default_factory=list)
    messages: list[HostMsg] = dataclasses.field(default_factory=list)
    must_sync: bool = False

    # Advance bookkeeping (acceptReady cursors)
    _commit_bound: int = 0


@dataclasses.dataclass
class Progress:
    """tracker.Progress snapshot (tracker/progress.go:30-80)."""

    match: int
    next: int
    state: int  # PR_*
    is_learner: bool
    paused: bool
    pending_snapshot: int
    recent_active: bool
    inflight: int
    inflight_full: bool

    def __str__(self) -> str:
        out = f"{PR_NAMES[self.state]} match={self.match} next={self.next}"
        if self.is_learner:
            out += " learner"
        if self.paused:
            out += " paused"
        if self.pending_snapshot > 0:
            out += f" pendingSnap={self.pending_snapshot}"
        if not self.recent_active:
            out += " inactive"
        if self.inflight > 0:
            out += f" inflight={self.inflight}"
            if self.inflight_full:
                out += "[full]"
        return out


@dataclasses.dataclass
class Status:
    """raft.Status/BasicStatus (raft/status.go:26-76)."""

    id: int
    hard_state: HardState
    soft_state: SoftState
    applied: int
    progress: dict[int, Progress]
    conf_state: ConfState


@functools.lru_cache(maxsize=32)
def _kernels(cfg: RaftConfig, spec: Spec):
    """Jitted single-lane kernels shared by every RawNode with this
    (cfg, spec)."""

    def step_msg(n: NodeState, m: Msg):
        ob = empty_outbox(spec)
        return raftmod.process_message(cfg, spec, n, ob, m)

    def tick(n: NodeState):
        ob = empty_outbox(spec)
        n, ob, fire = raftmod.tick_timers(cfg, spec, n, ob, jnp.bool_(True))
        # tickElection runs the campaign synchronously (raft.go:645-654)
        hup = make_msg(spec, frm=n.nid).replace(
            type=jnp.where(fire, MSG_HUP, MSG_NONE),
            context=jnp.int32(CAMPAIGN_NONE),
        )
        n, ob = raftmod.process_message(cfg, spec, n, ob, hup)
        return n, ob

    def apply_some(n: NodeState):
        ob = empty_outbox(spec)
        return raftmod.apply_round(cfg, spec, n, ob)

    return jax.jit(step_msg), jax.jit(tick), jax.jit(apply_some)


def host_to_device_msg(spec: Spec, hm: HostMsg) -> Msg:
    """HostMsg -> device Msg (the inbox slot format, etcd_tpu/types.py)."""
    ents = hm.entries[: spec.E]
    eT = np.zeros((spec.E,), np.int32)
    eD = np.zeros((spec.E,), np.int32)
    eY = np.zeros((spec.E,), np.int32)
    for j, e in enumerate(ents):
        eT[j], eD[j], eY[j] = e.term, e.data, e.type
    kw = dict(
        type=hm.type, term=hm.term, frm=hm.frm, index=hm.index,
        log_term=hm.log_term, commit=hm.commit, reject=hm.reject,
        reject_hint=hm.reject_hint, context=hm.context, ent_len=len(ents),
    )
    if hm.snapshot is not None:
        meta = hm.snapshot.meta
        v, vo, l, ln_ = meta.conf_state.masks(spec.M)
        kw.update(
            # app_hash split across commit/reject_hint, matching the
            # device MsgSnap emit (models/raft.py maybe_send_append)
            index=meta.index, log_term=meta.term, commit=meta.app_hash,
            reject_hint=(meta.app_hash >> 16) & 0xFFFF,
            reject=meta.conf_state.auto_leave,
            c_voters=pack_mask(jnp.asarray(v)),
            c_voters_out=pack_mask(jnp.asarray(vo)),
            c_learners=pack_mask(jnp.asarray(l)),
            c_learners_next=pack_mask(jnp.asarray(ln_)),
        )
    m = make_msg(spec, **kw)
    return m.replace(
        ent_term=jnp.asarray(eT), ent_data=jnp.asarray(eD),
        ent_type=jnp.asarray(eY),
    )


def outbox_to_host(spec: Spec, ob: Outbox) -> list[HostMsg]:
    """Harvest a device Outbox (leaves [K, M(dest), ...]) into HostMsgs,
    destination-major then slot order (the reference emits per-peer in
    sorted-id order via tracker.Visit, tracker/tracker.go:191-213, so
    this matches)."""
    counts = np.asarray(ob.counts)
    if counts.sum() == 0:
        return []
    K, M, E = spec.K, spec.M, spec.E

    def get(name):  # flat [K*M(*E)] -> [K, M, (E)] view
        a = np.asarray(getattr(ob.msgs, name))
        if name in ENT_FIELDS:
            return a.reshape(K, M, E)
        return a.reshape(K, M)

    f = {k: get(k) for k in (
        "type", "term", "frm", "index", "log_term", "commit", "reject",
        "reject_hint", "context", "ent_len", "ent_term", "ent_data",
        "ent_type", "c_voters", "c_voters_out", "c_learners",
        "c_learners_next")}
    out: list[HostMsg] = []
    for to in range(spec.M):
        for k in range(int(counts[to])):
            t = int(f["type"][k, to])
            if t == MSG_NONE:
                continue
            ents: tuple[Entry, ...] = ()
            if int(f["ent_len"][k, to]) > 0:
                base = int(f["index"][k, to])
                ents = tuple(
                    Entry(
                        index=base + 1 + j,
                        term=int(f["ent_term"][k, to, j]),
                        type=int(f["ent_type"][k, to, j]),
                        data=int(f["ent_data"][k, to, j]),
                    )
                    for j in range(int(f["ent_len"][k, to]))
                )
            snap = None
            if t == MSG_SNAP:
                ub = lambda w: [bool((int(w) >> i) & 1) for i in range(spec.M)]
                cs = ConfState.from_masks(
                    ub(f["c_voters"][k, to]),
                    ub(f["c_voters_out"][k, to]),
                    ub(f["c_learners"][k, to]),
                    ub(f["c_learners_next"][k, to]),
                    bool(f["reject"][k, to]),
                )
                # reassemble the split app hash (device MsgSnap wire
                # format, models/raft.py maybe_send_append)
                raw = (
                    (int(f["reject_hint"][k, to]) << 16)
                    | (int(f["commit"][k, to]) & 0xFFFF)
                ) & 0xFFFFFFFF
                snap = Snapshot(
                    meta=SnapshotMeta(
                        index=int(f["index"][k, to]),
                        term=int(f["log_term"][k, to]),
                        conf_state=cs,
                        app_hash=raw - (1 << 32) if raw >= 1 << 31 else raw,
                    )
                )
            out.append(
                HostMsg(
                    type=t, to=to, frm=int(f["frm"][k, to]),
                    term=int(f["term"][k, to]),
                    index=0 if t == MSG_SNAP else int(f["index"][k, to]),
                    log_term=0 if t == MSG_SNAP else int(f["log_term"][k, to]),
                    commit=0 if t == MSG_SNAP else int(f["commit"][k, to]),
                    reject=False if t == MSG_SNAP else bool(f["reject"][k, to]),
                    reject_hint=0 if t == MSG_SNAP
                    else int(f["reject_hint"][k, to]),
                    context=int(f["context"][k, to]),
                    entries=ents,
                    snapshot=snap,
                )
            )
    return out


class RawNode:
    """Single-group driver with Ready/Advance accounting
    (raft/rawnode.go:34-241), state stepped by the fleet kernels."""

    def __init__(
        self,
        cfg: RaftConfig,
        spec: Spec,
        storage: Storage,
        nid: int,
        applied: int | None = None,
        seed: int = 0,
    ):
        self.cfg, self.spec, self.storage = cfg, spec, storage
        self.nid = nid
        self._step_k, self._tick_k, self._apply_k = _kernels(cfg, spec)
        self.n = self._boot(storage, nid, applied, seed)
        self._pending_msgs: list[HostMsg] = []
        self._pending_snap: Snapshot | None = None
        self._stable_to = int(self.n.last_index)
        # stable-entry cache: what the application has persisted so far.
        # The device ring is truncate-and-append (maybe_append) like the
        # reference's unstable log (log_unstable.go:121-156); when a new
        # leader overwrites a stable suffix, Ready must re-emit it, so we
        # diff the ring against this cache after every step.
        self._stable_ents: dict[int, tuple[int, int, int]] = {
            e.index: (e.term, e.type, e.data)
            for e in self.ring_entries(
                int(self.n.snap_index) + 1, self._stable_to + 1
            )
        }
        self.prev_hs = self._hard_state()
        self.prev_ss = self._soft_state()
        self._rs_seen = 0
        self.last_conf_states: list[ConfState] = []

    # -- boot (newRaft, raft.go:318-370) ------------------------------------
    def _boot(self, storage: Storage, nid, applied, seed) -> NodeState:
        spec, cfg = self.spec, self.cfg
        hs, cs = storage.initial_state()
        snap = storage.snapshot()
        v, vo, l, ln_ = cs.masks(spec.M)
        n = init_node(
            spec, nid, jnp.asarray(v), jnp.asarray(l), seed=seed,
            election_tick=cfg.election_tick,
        )
        first, last = storage.first_index(), storage.last_index()
        # the ring base is the storage's truncation point, which can sit
        # past the retained snapshot (MemoryStorage.Compact moves only the
        # offset); the device collapses both to one snapshot cursor
        si = first - 1
        s_term = storage.term(si) if si > 0 else 0
        L = spec.L
        if last - si > L:
            raise ValueError(
                f"storage holds {last - si} entries > ring capacity {L}"
            )
        lt = np.zeros((L,), np.int32)
        ld = np.zeros((L,), np.int32)
        ly = np.zeros((L,), np.int32)
        for e in storage.entries(first, last + 1):
            s = (e.index - 1) % L
            lt[s], ld[s], ly[s] = e.term, e.data, e.type
        applied = max(applied if applied is not None else 0, si)
        return n.replace(
            term=jnp.int32(hs.term),
            vote=jnp.int32(hs.vote),
            commit=jnp.int32(max(hs.commit, si)),
            applied=jnp.int32(applied),
            last_index=jnp.int32(last),
            snap_index=jnp.int32(si),
            snap_term=jnp.int32(s_term),
            snap_hash=jnp.int32(snap.meta.app_hash),
            applied_hash=jnp.int32(snap.meta.app_hash),
            log_term=jnp.asarray(lt),
            log_data=jnp.asarray(ld),
            log_type=jnp.asarray(ly),
            voters=jnp.asarray(v), voters_out=jnp.asarray(vo),
            learners=jnp.asarray(l), learners_next=jnp.asarray(ln_),
            auto_leave=jnp.bool_(cs.auto_leave),
            snap_voters=jnp.asarray(v), snap_voters_out=jnp.asarray(vo),
            snap_learners=jnp.asarray(l),
            snap_learners_next=jnp.asarray(ln_),
            snap_auto_leave=jnp.bool_(cs.auto_leave),
        )

    # -- state readers -------------------------------------------------------
    def _hard_state(self) -> HardState:
        n = self.n
        return HardState(int(n.term), int(n.vote), int(n.commit))

    def _soft_state(self) -> SoftState:
        n = self.n
        return SoftState(int(n.lead), int(n.role))

    def ring_entries(self, lo: int, hi: int) -> list[Entry]:
        """Entries [lo, hi) read from the device ring."""
        n, L = self.n, self.spec.L
        lt = np.asarray(n.log_term)
        ld = np.asarray(n.log_data)
        ly = np.asarray(n.log_type)
        out = []
        for i in range(lo, hi):
            s = (i - 1) % L
            out.append(Entry(index=i, term=int(lt[s]), type=int(ly[s]),
                             data=int(ld[s])))
        return out

    # -- mutators ------------------------------------------------------------
    def _run_msg(self, hm: HostMsg) -> None:
        pre_snap = int(self.n.snap_index)
        m = host_to_device_msg(self.spec, hm)
        self.n, ob = self._step_k(self.n, m)
        self._harvest(ob)
        post_snap = int(self.n.snap_index)
        if hm.type == MSG_SNAP and post_snap > pre_snap and hm.snapshot:
            # eager restore happened: surface it in the next Ready and track
            # the stable cursor jump (the ring was reset to the snapshot)
            self._pending_snap = hm.snapshot
            self._stable_to = post_snap
            self._stable_ents = {}
        else:
            self._roll_back_overwritten()

    def _roll_back_overwritten(self) -> None:
        """If the step truncate-overwrote already-stable entries
        (handleAppendEntries conflict path, models/raft.py), move the
        stable cursor back so Ready re-emits the new suffix — the analog
        of unstable.truncateAndAppend moving its offset down."""
        n = self.n
        last = int(n.last_index)
        if last < self._stable_to:
            self._stable_to = last
            for j in [j for j in self._stable_ents if j > last]:
                del self._stable_ents[j]
        if not self._stable_ents:
            return
        lo = max(int(n.snap_index) + 1, min(self._stable_ents))
        for e in self.ring_entries(lo, min(self._stable_to, last) + 1):
            want = self._stable_ents.get(e.index)
            if want is not None and want != (e.term, e.type, e.data):
                self._stable_to = e.index - 1
                for j in [j for j in self._stable_ents if j >= e.index]:
                    del self._stable_ents[j]
                break

    def _harvest(self, ob: Outbox) -> None:
        self._pending_msgs.extend(outbox_to_host(self.spec, ob))

    def tick(self) -> None:
        self.n, ob = self._tick_k(self.n)
        self._harvest(ob)

    def campaign(self) -> None:
        self._run_msg(HostMsg(type=MSG_HUP, to=self.nid, frm=self.nid,
                              context=CAMPAIGN_NONE))

    def propose(self, data_word: int) -> bool:
        """Returns False if the proposal was dropped (ErrProposalDropped)."""
        before = (int(self.n.last_index), len(self._pending_msgs))
        self._run_msg(
            HostMsg(
                type=MSG_PROP, to=self.nid, frm=self.nid,
                entries=(Entry(index=0, term=0, type=ENTRY_NORMAL,
                               data=data_word),),
            )
        )
        return self._prop_accepted(before)

    def propose_conf_change(self, cc_word: int) -> bool:
        before = (int(self.n.last_index), len(self._pending_msgs))
        self._run_msg(
            HostMsg(
                type=MSG_PROP, to=self.nid, frm=self.nid,
                entries=(Entry(index=0, term=0, type=ENTRY_CONF_CHANGE,
                               data=cc_word),),
            )
        )
        return self._prop_accepted(before)

    def _prop_accepted(self, before) -> bool:
        last0, msgs0 = before
        appended = int(self.n.last_index) > last0
        forwarded = any(
            m.type == MSG_PROP for m in self._pending_msgs[msgs0:]
        )
        return appended or forwarded

    def step(self, hm: HostMsg) -> None:
        """Feed an external message (Step, rawnode.go:70-79): local message
        types are refused, and response messages from peers outside the
        tracked progress set raise ErrStepPeerNotFound."""
        if hm.type in (MSG_HUP, MSG_PROP):
            raise ErrStepLocalMsg("raft: cannot step raft local message")
        if hm.type in _RESPONSE_TYPES and 0 <= hm.frm < self.spec.M:
            tracked = np.asarray(
                self.n.voters | self.n.voters_out | self.n.learners
                | self.n.learners_next
            )
            if not tracked[hm.frm]:
                raise ErrStepPeerNotFound(
                    "raft: cannot step as peer not found"
                )
        self._run_msg(hm)

    def read_index(self, ctx: int) -> None:
        from etcd_tpu.types import MSG_READ_INDEX

        self._run_msg(HostMsg(type=MSG_READ_INDEX, to=self.nid, frm=self.nid,
                              context=ctx))

    # -- Ready/Advance (rawnode.go:125-179) ----------------------------------
    def has_ready(self) -> bool:
        n = self.n
        if self._pending_msgs or self._pending_snap:
            return True
        if int(n.last_index) > self._stable_to:
            return True
        if self._hard_state() != self.prev_hs:
            return True
        if self._soft_state() != self.prev_ss:
            return True
        if int(n.commit) > int(n.applied):
            return True
        if int(n.rs_count) > 0:
            return True
        return False

    def ready(self) -> Ready:
        """Harvest pending work and accept it (Ready + acceptReady)."""
        n = self.n
        rd = Ready()
        ss = self._soft_state()
        if ss != self.prev_ss:
            rd.soft_state = ss
        hs = self._hard_state()
        if hs != self.prev_hs:
            rd.hard_state = hs
        rs_count = int(n.rs_count)
        if rs_count > 0:
            ctxs = np.asarray(n.rs_ctx)[:rs_count]
            idxs = np.asarray(n.rs_index)[:rs_count]
            rd.read_states = [
                ReadState(index=int(i), request_ctx=int(c))
                for c, i in zip(ctxs, idxs)
            ]
            self.n = self.n.replace(rs_count=jnp.int32(0))
        last = int(n.last_index)
        if last > self._stable_to:
            rd.entries = self.ring_entries(self._stable_to + 1, last + 1)
        rd.snapshot = self._pending_snap
        applied, commit = int(n.applied), int(n.commit)
        if commit > applied:
            rd.committed_entries = self.ring_entries(applied + 1, commit + 1)
        rd.messages = self._pending_msgs
        rd.must_sync = bool(
            hs.term != self.prev_hs.term
            or hs.vote != self.prev_hs.vote
            or rd.entries
        )
        rd._commit_bound = commit
        # acceptReady
        self._pending_msgs = []
        self._pending_snap = None
        self.prev_ss = ss
        self.prev_hs = hs
        self._stable_to = last
        for e in rd.entries:
            self._stable_ents[e.index] = (e.term, e.type, e.data)
        snap_i = int(n.snap_index)
        for j in [j for j in self._stable_ents if j <= snap_i]:
            del self._stable_ents[j]
        return rd

    def advance(self, rd: Ready) -> None:
        """Apply the accepted committed entries; conf changes take effect
        on-device (apply_round) and are reported via last_conf_states."""
        self.last_conf_states = []
        while int(self.n.applied) < rd._commit_bound:
            pre = self._conf_tuple()
            self.n, ob = self._apply_k(self.n)
            self._harvest(ob)
            post = self._conf_tuple()
            if post != pre:
                self.last_conf_states.append(self.conf_state())

    def _conf_tuple(self):
        n = self.n
        return (
            tuple(np.asarray(n.voters).tolist()),
            tuple(np.asarray(n.voters_out).tolist()),
            tuple(np.asarray(n.learners).tolist()),
            tuple(np.asarray(n.learners_next).tolist()),
        )

    def compact_to(self, index: int) -> None:
        """Advance the device lane's snapshot cursor to `index` — the lane
        analog of MemoryStorage.Compact (raft/storage.go:208-233): entries
        <= index become unreachable and further sends below it fall back
        to MsgSnap (maybeSendAppend, raft.go:446-469)."""
        n = self.n
        if index <= int(n.snap_index):
            return
        if index > int(n.applied):
            raise ValueError(
                f"cannot compact beyond applied index {int(n.applied)}"
            )
        term = self.ring_entries(index, index + 1)[0].term
        # the applied hash at `index` equals the current hash only when
        # applied == index; otherwise the snapshot hash stays at the last
        # known point (the chain cannot be rewound)
        snap_hash = (
            int(n.applied_hash) if int(n.applied) == index
            else int(n.snap_hash)
        )
        self.n = n.replace(
            snap_index=jnp.int32(index),
            snap_term=jnp.int32(term),
            snap_hash=jnp.int32(snap_hash),
            snap_voters=n.voters, snap_voters_out=n.voters_out,
            snap_learners=n.learners, snap_learners_next=n.learners_next,
            snap_auto_leave=n.auto_leave,
        )

    def conf_state(self) -> ConfState:
        n = self.n
        return ConfState.from_masks(
            np.asarray(n.voters), np.asarray(n.voters_out),
            np.asarray(n.learners), np.asarray(n.learners_next),
            bool(n.auto_leave),
        )

    # -- status (raft/status.go:26-76) ---------------------------------------
    def status(self) -> Status:
        n, cfg, spec = self.n, self.cfg, self.spec
        progress: dict[int, Progress] = {}
        if int(n.role) == ROLE_LEADER:
            match = np.asarray(n.match)
            nxt = np.asarray(n.next_idx)
            prs = np.asarray(n.pr_state)
            probe_sent = np.asarray(n.probe_sent)
            psnap = np.asarray(n.pending_snapshot)
            ract = np.asarray(n.recent_active)
            icnt = np.asarray(n.infl_count)
            learners = np.asarray(n.learners | n.learners_next)
            tracked = np.asarray(
                n.voters | n.voters_out | n.learners | n.learners_next
            )
            for i in range(spec.M):
                if not tracked[i]:
                    continue
                st = int(prs[i])
                full = int(icnt[i]) >= cfg.max_inflight
                paused = (
                    bool(probe_sent[i]) if st == PR_PROBE
                    else full if st == PR_REPLICATE
                    else True
                )
                progress[i] = Progress(
                    match=int(match[i]), next=int(nxt[i]), state=st,
                    is_learner=bool(learners[i]), paused=paused,
                    pending_snapshot=int(psnap[i]),
                    recent_active=bool(ract[i]),
                    inflight=int(icnt[i]), inflight_full=full,
                )
        return Status(
            id=self.nid,
            hard_state=self._hard_state(),
            soft_state=self._soft_state(),
            applied=int(self.n.applied),
            progress=progress,
            conf_state=self.conf_state(),
        )


class DeviceLaneStorage(Storage):
    """Storage view over a live RawNode's device lane — what the device
    ring itself would answer (InitialState/Entries/Term/.../Snapshot),
    with the reference error taxonomy (raft/storage.go:24-72)."""

    def __init__(self, rn: RawNode):
        self.rn = rn

    def initial_state(self):
        return self.rn._hard_state(), self.rn.conf_state()

    def first_index(self) -> int:
        return int(self.rn.n.snap_index) + 1

    def last_index(self) -> int:
        return int(self.rn.n.last_index)

    def entries(self, lo, hi, max_entries=None):
        from etcd_tpu.storage.raftstorage import ErrCompacted, ErrUnavailable

        if lo < self.first_index():
            raise ErrCompacted(lo)
        if hi > self.last_index() + 1:
            raise ErrUnavailable(hi)
        out = self.rn.ring_entries(lo, hi)
        if max_entries is not None:
            out = out[:max_entries]
        return out

    def term(self, i) -> int:
        from etcd_tpu.storage.raftstorage import ErrCompacted, ErrUnavailable

        n = self.rn.n
        if i == int(n.snap_index):
            return int(n.snap_term)
        if i < int(n.snap_index):
            raise ErrCompacted(i)
        if i > int(n.last_index):
            raise ErrUnavailable(i)
        return self.rn.ring_entries(i, i + 1)[0].term

    def snapshot(self) -> Snapshot:
        n = self.rn.n
        return Snapshot(
            meta=SnapshotMeta(
                index=int(n.snap_index), term=int(n.snap_term),
                conf_state=ConfState.from_masks(
                    np.asarray(n.snap_voters), np.asarray(n.snap_voters_out),
                    np.asarray(n.snap_learners),
                    np.asarray(n.snap_learners_next),
                    bool(n.snap_auto_leave),
                ),
                app_hash=int(n.snap_hash),
            )
        )
