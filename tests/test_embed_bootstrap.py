"""embed cold-start selection tree (bootstrap.go:51-99): new vs existing
vs restart-from-disk vs force-new-cluster, selected from on-disk state +
config flags. Data on disk always wins: an embed restart RESUMES the
cluster (the reference never wipes a data dir), absent members catch up
from peers, and force_new_cluster rebuilds a one-member cluster for
disaster recovery (bootstrap.go:327-341).
"""
from __future__ import annotations

import os

import pytest

from etcd_tpu.client import Client
from etcd_tpu.embed import Config, start_etcd


def _cfg(tmp_path, **kw):
    return Config(
        data_dir=str(tmp_path / "data"), auto_tick=False, cluster_size=3,
        **kw,
    )


def test_new_then_restart_resumes_data(tmp_path):
    e = start_etcd(_cfg(tmp_path))
    cl = Client(e.server)
    cl.put(b"k", b"v1")
    rev = int(cl.get_range(b"k")["header"].revision)
    e.close()

    # same dir, second incarnation: haveWAL wins -> restart from disk
    e2 = start_etcd(_cfg(tmp_path))
    cl2 = Client(e2.server)
    kv = cl2.get(b"k")
    assert kv is not None and kv.value == b"v1", "restart wiped the data dir"
    assert int(cl2.get_range(b"k")["header"].revision) >= rev
    cl2.put(b"k", b"v2")  # still writable
    assert cl2.get(b"k").value == b"v2"
    e2.close()


def test_existing_without_data_refuses(tmp_path):
    with pytest.raises(ValueError, match="nothing to join"):
        start_etcd(_cfg(tmp_path, initial_cluster_state="existing"))
    # and entirely without a data dir
    with pytest.raises(ValueError, match="nothing to join"):
        start_etcd(Config(auto_tick=False,
                          initial_cluster_state="existing"))


def test_absent_member_catches_up_from_peers(tmp_path):
    e = start_etcd(_cfg(tmp_path))
    cl = Client(e.server)
    for i in range(5):
        cl.put(b"k%d" % i, b"v%d" % i)
    e.close()

    # lose one member's data file; the restart boots it empty and
    # installs a peer snapshot (bootstrapExistingClusterNoWAL analog)
    os.remove(os.path.join(str(tmp_path / "data"), "member2.db"))
    e2 = start_etcd(_cfg(tmp_path, initial_cluster_state="existing"))
    e2.server.corruption_check()  # every member at one hash
    cl2 = Client(e2.server)
    assert cl2.get(b"k4").value == b"v4"
    e2.close()


def test_force_new_cluster_single_member(tmp_path):
    e = start_etcd(_cfg(tmp_path))
    Client(e.server).put(b"k", b"v1")
    e.close()

    e2 = start_etcd(_cfg(tmp_path, force_new_cluster=True))
    assert len(e2.server.members) == 1
    cl2 = Client(e2.server)
    assert cl2.get(b"k").value == b"v1"
    cl2.put(b"k2", b"v2")  # one-member cluster commits alone
    assert cl2.get(b"k2").value == b"v2"
    e2.close()


def test_force_new_cluster_survives_member0_loss(tmp_path):
    """Disaster case: member 0's file is gone; recovery must come from a
    surviving member's data, never a silently-empty cluster."""
    e = start_etcd(_cfg(tmp_path))
    Client(e.server).put(b"k", b"v1")
    e.close()

    os.remove(os.path.join(str(tmp_path / "data"), "member0.db"))
    e2 = start_etcd(_cfg(tmp_path, force_new_cluster=True))
    assert len(e2.server.members) == 1
    kv = Client(e2.server).get(b"k")
    assert kv is not None and kv.value == b"v1", (
        "force_new_cluster discarded surviving member data"
    )
    e2.close()


def test_validate_rejects_bad_flags(tmp_path):
    with pytest.raises(ValueError, match="initial cluster state"):
        Config(initial_cluster_state="maybe").validate()
    with pytest.raises(ValueError, match="force_new_cluster"):
        Config(force_new_cluster=True).validate()
