"""Unit tests for utils/trace.py — the host half of the request-tracing
tentpole (ISSUE 15). The reference's pkg/traceutil has its own table
tests (trace_test.go); these cover the same surface: step ordering,
TODO inertness, AddField set-or-replace, and the threshold dump rule.
"""
import json
import time

from etcd_tpu.utils.logging import DiscardLogger, get_logger, set_logger
from etcd_tpu.utils.trace import Field, Trace


class _CaptureLogger(DiscardLogger):
    def __init__(self):
        self.lines = []

    def warning(self, fmt, *args):
        self.lines.append(fmt % args if args else fmt)


def test_step_ordering_and_format():
    t = Trace("put", Field("member", 0))
    t.step("proposed through raft", Field("word", 7))
    t.step("applied; result ready")
    t.step("backends fsynced")
    msgs = [m for _, m, _ in t.steps]
    assert msgs == ["proposed through raft", "applied; result ready",
                    "backends fsynced"]
    # timestamps are monotone non-decreasing (perf_counter)
    stamps = [ts for ts, _, _ in t.steps]
    assert stamps == sorted(stamps)
    out = t.format()
    assert "put" in out.splitlines()[0]
    assert "member:0" in out
    for m in msgs:
        assert m in out
    # per-step fields render next to their step line
    assert "word:7" in out


def test_todo_is_inert():
    t = Trace.todo()
    t.step("never recorded")
    t.add_field(Field("k", "v"))
    assert t.is_empty
    assert t.steps == []
    # an inert trace never dumps, whatever the threshold
    cap = _CaptureLogger()
    old = get_logger()
    set_logger(cap)
    try:
        assert t.log_if_long(0.0) is False
    finally:
        set_logger(old)
    assert cap.lines == []


def test_add_field_set_or_replace():
    t = Trace("range")
    t.add_field(Field("serializable", False))
    t.add_field(Field("count", 3))
    # replace by key, preserving position; new keys append
    t.add_field(Field("serializable", True), Field("limit", 10))
    assert [(f.key, f.value) for f in t.fields] == [
        ("serializable", True), ("count", 3), ("limit", 10)]


def test_threshold_dump_fires_only_past_cutoff():
    cap = _CaptureLogger()
    old = get_logger()
    set_logger(cap)
    try:
        t = Trace("put")
        t.step("fast path")
        # far below any sane threshold: no dump
        assert t.log_if_long(60.0) is False
        assert cap.lines == []
        # past the cutoff: dumps exactly once per call, returns True
        time.sleep(0.01)
        assert t.log_if_long(0.005) is True
        assert len(cap.lines) == 1
        assert "fast path" in cap.lines[0]
    finally:
        set_logger(old)


def test_to_span_shape_and_json_safety():
    t = Trace("txn", Field("rpc", "kv_txn"), Field("blob", b"\x00bytes"))
    t.step("proposed through raft", Field("word", 1))
    t.step("applied; result ready")
    span = t.to_span()
    assert span["op"] == "txn"
    assert span["dur"] >= 0
    # step offsets are relative to the span start and monotone
    offs = [st["ts"] for st in span["steps"]]
    assert offs == sorted(offs) and all(o >= 0 for o in offs)
    assert [st["msg"] for st in span["steps"]] == [
        "proposed through raft", "applied; result ready"]
    assert span["steps"][0]["fields"] == {"word": 1}
    # non-primitive field values are coerced so the span survives
    # json.dumps (the Chrome trace exporter feeds these straight in)
    assert isinstance(span["fields"]["blob"], str)
    json.dumps(span)
