"""RaftConfig.local_steps: trace-time removal of statically-dead local
message passes (bench steady program). Equivalence contract: with no hups,
no ticks and no read-index inputs, the ("prop",)-only program must
reproduce the full program bit-for-bit — the dropped steps were pure
masked no-ops, each costing a full pass over fleet state."""
import dataclasses

import numpy as np
import jax

from etcd_tpu.models.engine import build_round, empty_inbox, init_fleet
from etcd_tpu.types import ENTRY_NORMAL, ROLE_LEADER, Spec
from etcd_tpu.utils.config import RaftConfig

SPEC = Spec(M=5, L=16, E=1, K=2, W=4, R=2, A=2)
CFG = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=4,
                 inbox_bound=4, coalesce_commit_refresh=True)
C = 4


def _elect(full):
    M, E = SPEC.M, SPEC.E
    state = init_fleet(SPEC, C, seed=0, election_tick=CFG.election_tick)
    inbox = empty_inbox(SPEC, C)
    z2 = np.zeros((M, C), np.int32)
    zp = np.zeros((M, E, C), np.int32)
    no = np.zeros((M, C), bool)
    keep = np.ones((M, M, C), bool)
    hup = no.copy()
    hup[0, :] = True
    state, inbox = full(state, inbox, z2, zp, zp, z2, hup, no, keep)
    for _ in range(12):
        state, inbox = full(state, inbox, z2, zp, zp, z2, no, no, keep)
    assert (np.asarray(state.role)[0] == ROLE_LEADER).all()
    return state, inbox, (z2, zp, no, keep)


def test_prop_only_program_is_bit_identical_in_steady_state():
    full = jax.jit(build_round(CFG, SPEC))
    steady = jax.jit(
        build_round(dataclasses.replace(CFG, local_steps=("prop",)), SPEC)
    )
    state0, inbox0, (z2, zp, no, keep) = _elect(full)
    _assert_equiv(full, steady, state0, inbox0, z2, zp, no, keep)


def test_declared_classes_program_is_bit_identical_in_steady_state():
    """The bench steady program (local_steps=("prop",) AND
    message_classes={App, AppResp, Prop}) against live steady traffic."""
    from etcd_tpu.types import MSG_APP, MSG_APP_RESP, MSG_PROP

    full = jax.jit(build_round(CFG, SPEC))
    steady = jax.jit(
        build_round(
            dataclasses.replace(
                CFG,
                local_steps=("prop",),
                message_classes=(MSG_APP, MSG_APP_RESP, MSG_PROP),
            ),
            SPEC,
        )
    )
    state0, inbox0, (z2, zp, no, keep) = _elect(full)
    _assert_equiv(full, steady, state0, inbox0, z2, zp, no, keep)


def _assert_equiv(full, steady, state0, inbox0, z2, zp, no, keep):

    plen = z2.copy()
    plen[0, :] = 1
    pdata = zp.copy()
    pdata[0, 0, :] = 7
    ptype = zp.copy()
    ptype[0, 0, :] = ENTRY_NORMAL

    sa, ia = state0, inbox0
    sb, ib = state0, inbox0
    for r in range(10):
        sa, ia = full(sa, ia, plen, pdata, ptype, z2, no, no, keep)
        sb, ib = steady(sb, ib, plen, pdata, ptype, z2, no, no, keep)
    assert int(np.asarray(sa.commit).min()) >= 8  # really replicating
    for name in sa.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        ), f"state.{name}"
    for name in ia.__dataclass_fields__:
        assert np.array_equal(
            np.asarray(getattr(ia, name)), np.asarray(getattr(ib, name))
        ), f"inbox.{name}"
