"""Static-analysis plane tests (etcd_tpu/analysis — ISSUE 19).

Three tiers:

  * lint-rule unit tests over seeded source fixtures (tmp files), plus
    the repo-clean gate: the real tree lints to zero findings;
  * auditor seeded-violation tests over toy jitted programs — every
    auditor must FIRE on its violation class (reintroduced PR-9-style
    double-donation, jaxpr divergence on an operand change, a host
    callback in the round body, a cross-shard psum) and stay quiet on
    the clean form;
  * acceptance: the real chaos epoch holds the one-trace contract
    across >= 3 runtime-operand variants, the real sharded round
    compiles to zero cross-shard collectives, and the CLI's exit-code
    contract (0 clean / 1 findings / 2 bad knob) subprocess-checks.
"""
from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from etcd_tpu.analysis import audit as A
from etcd_tpu.analysis import lint as L
from etcd_tpu.analysis.programs import (
    ProgramInstance,
    get_program,
    sharded_program,
)

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# lint rules over seeded fixtures
# ---------------------------------------------------------------------------

def _lint(tmp_path: Path, rel: str, src: str, rules=None):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return L.lint_file(p, tmp_path, rules)


def _rules(findings):
    return sorted({f.rule for f in findings})


def test_env_knob_fires_on_raw_reads(tmp_path):
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        import os
        a = os.environ["MY_KNOB"]
        b = os.environ.get("OTHER_KNOB", "1")
        c = os.getenv("THIRD_KNOB")
        """, rules=("env-knob",))
    assert len(finds) == 3 and _rules(finds) == ["env-knob"]
    assert all("utils.knobs" in f.message for f in finds)


def test_env_knob_allowlist_and_presence_checks_legal(tmp_path):
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        import os
        p = os.environ.get("JAX_PLATFORMS")
        f = os.environ["XLA_FLAGS"]
        present = "MY_KNOB" in os.environ
        child = dict(os.environ, MY_KNOB="1")
        os.environ["MY_KNOB"] = "1"
        """, rules=("env-knob",))
    assert finds == []


def test_host_sync_fires_only_in_traced_modules(tmp_path):
    src = """\
        import numpy as np
        def f(x):
            n = x.sum().item()
            a = np.asarray(x)
            return int(x.max())
        """
    inside = _lint(tmp_path, "etcd_tpu/models/x.py", src,
                   rules=("host-sync",))
    outside = _lint(tmp_path, "etcd_tpu/server/x.py", src,
                    rules=("host-sync",))
    assert len(inside) == 3 and _rules(inside) == ["host-sync"]
    assert outside == []


def test_debug_print_fires(tmp_path):
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        import jax
        def f(x):
            jax.debug.print("x = {}", x)
            breakpoint()
            return x
        """, rules=("debug-print",))
    assert len(finds) == 2 and _rules(finds) == ["debug-print"]


def test_undefined_name_fires_on_dangling_name(tmp_path):
    # the PR-9 `margs` class: live only under a gated branch, bound
    # nowhere — a NameError waiting for the right env
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        import os
        def f(flag):
            if flag:
                return margs
            return 0
        """, rules=("undefined-name",))
    assert [f.rule for f in finds] == ["undefined-name"]
    assert "margs" in finds[0].message


def test_undefined_name_resolves_forward_refs(tmp_path):
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        def f():
            return helper() + later
        def helper():
            return 1
        later = 2
        """, rules=("undefined-name",))
    assert finds == []


def test_dead_knob_fires_for_undocumented_and_unused(tmp_path):
    finds = _lint(tmp_path, "bench.py", '''\
        """Docstring mentions BENCH_GOOD only."""
        from etcd_tpu.utils.knobs import env_int
        good = env_int("bench", "BENCH_GOOD", "1")
        dead = env_int("bench", "BENCH_MYSTERY", "1")
        print(good)
        ''', rules=("dead-knob",))
    msgs = [f.message for f in finds]
    assert any("BENCH_MYSTERY" in m and "not documented" in m for m in msgs)
    assert any("never used" in m for m in msgs)


def test_suppression_with_reason_suppresses(tmp_path):
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        import os
        a = os.environ["K"]  # lint: allow(env-knob) -- fixture reason
        """, rules=("env-knob",))
    assert finds == []


def test_suppression_without_reason_is_a_finding(tmp_path):
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        import os
        a = os.environ["K"]  # lint: allow(env-knob)
        """, rules=("env-knob",))
    # the unjustified suppression is itself a finding AND does not
    # suppress — both rules fire
    assert _rules(finds) == ["env-knob", "suppression"]
    assert any("justification" in f.message for f in finds)


def test_suppression_allow_def_covers_whole_body(tmp_path):
    finds = _lint(tmp_path, "etcd_tpu/x.py", """\
        import os
        # lint: allow-def(env-knob) -- fixture: host edge
        def f():
            a = os.environ["K1"]
            return os.environ["K2"]
        b = os.environ["K3"]
        """, rules=("env-knob",))
    assert len(finds) == 1 and "K3" in finds[0].message


def test_repo_lints_clean():
    """The gate the CLI enforces: the current tree carries zero lint
    findings (every host edge / platform read is either restructured or
    justified at the use site)."""
    findings = L.run_lint(REPO)
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# widths auditor (pure table cross-check; no tracing)
# ---------------------------------------------------------------------------

def test_widths_clean_on_real_tables():
    assert A.audit_widths() == []


def test_widths_seeded_violations_fire():
    from etcd_tpu.models import state as st
    from etcd_tpu.types import MSG_SNAP

    # a field dropped from the durability partition breaks coverage
    durable = tuple(f for f in st.DURABLE_FIELDS if f != "term")
    finds = A.audit_widths(durable=durable)
    assert any("term" in f.message for f in finds), finds

    # a field in two classes breaks disjointness
    finds = A.audit_widths(
        capped=tuple(st.CAPPED_FIELDS) + (st.DURABLE_FIELDS[0],))
    assert any("disjoint" in f.message or "classes" in f.message
               for f in finds), finds

    # wide-row drift: an expected wide field the pack plan doesn't have
    finds = A.audit_widths(
        wide_expected=("applied_hash", "snap_hash", "log_data",
                       "not_a_field"))
    assert finds, "expected a wide-set mismatch finding"

    # wire-split registry naming a field Msg doesn't carry
    finds = A.audit_widths(wire_split={("bogus_field", MSG_SNAP)})
    assert any("bogus_field" in f.message for f in finds), finds


# ---------------------------------------------------------------------------
# auditor seeded violations over toy programs
# ---------------------------------------------------------------------------

def _toy(fn, donate, base, variants=(), expected=1, **kw):
    return ProgramInstance(
        name="toy", jitted=jax.jit(fn, donate_argnums=donate),
        donate=donate, C=4, base=tuple(base), variants=tuple(variants),
        expected_outputs=expected, **kw)


def test_donation_double_donation_fires():
    """The PR-9 crash class, reintroduced: one buffer at two donated
    positions aliases two live results into one allocation."""
    x = jnp.zeros((4,), jnp.float32)

    def fn(a, b):
        return a + 1, b * 2

    tp = A.TracedProgram(_toy(fn, (0, 1), (x, x), expected=2))
    finds = A.audit_donation(tp)
    assert any("donated positions" in f.message for f in finds), finds


def test_donation_completeness_fires_and_justification_clears():
    x = jnp.zeros((4,), jnp.float32)
    y = jnp.ones((4,), jnp.float32)

    def fn(a, b):
        return a + 1, b * 2

    inst = _toy(fn, (0,), (x, y), expected=2)
    finds = A.audit_donation(A.TracedProgram(inst))
    assert any("not donated" in f.message for f in finds), finds

    ok = dataclasses.replace(
        inst, undonated_ok={1: "fixture: caller re-reads the buffer"})
    assert A.audit_donation(A.TracedProgram(ok)) == []


def test_donation_not_carried_and_alias_validity_fire():
    x = jnp.zeros((4,), jnp.float32)

    def fn(a):
        return a.sum()

    finds = A.audit_donation(A.TracedProgram(_toy(fn, (0,), (x,))))
    assert any("can never alias" in f.message for f in finds), finds
    assert any("no remaining output slot" in f.message for f in finds), finds


def test_donation_live_alias_fires_and_allowlist_clears():
    x = jnp.zeros((4,), jnp.float32)

    def fn(a, b):
        return a + 1, b.sum()

    inst = _toy(fn, (0,), (x, x), expected=2)
    finds = A.audit_donation(A.TracedProgram(inst))
    assert any("shares a buffer with live arg" in f.message
               for f in finds), finds

    # arg 1 also reads as a carried fleet-scaled arg (its aval matches
    # the a+1 output), so the clean form needs both justifications
    ok = dataclasses.replace(
        inst, live_alias_ok={(0, 1): "fixture: backend tolerates it"},
        undonated_ok={1: "fixture: caller re-reads the buffer"})
    assert A.audit_donation(A.TracedProgram(ok)) == []


def test_one_trace_clean_on_value_variants():
    x = jnp.zeros((4,), jnp.float32)

    def fn(a, k):
        return a * k

    inst = _toy(fn, (), (x, jnp.float32(2.0)),
                variants=[(f"k{v}", (x, jnp.float32(v)))
                          for v in (3.0, 4.0, 5.0)])
    assert A.audit_one_trace(A.TracedProgram(inst)) == []


def test_one_trace_divergence_on_operand_change_fires():
    """Seeded jaxpr divergence: a variant whose operand change leaks
    into the trace (here a shape change standing in for any retrace)
    must fire — the one-trace contract is bit-identity."""
    x = jnp.zeros((4,), jnp.float32)

    def fn(a, k):
        return a * k

    inst = _toy(fn, (), (x, jnp.float32(2.0)),
                variants=[("k3", (x, jnp.float32(3.0))),
                          ("leak", (x, jnp.full((4,), 4.0, jnp.float32)))])
    finds = A.audit_one_trace(A.TracedProgram(inst))
    assert any(f.rule == "audit-one-trace" and "leak" in f.message
               for f in finds), finds


def test_one_trace_requires_three_operand_sets():
    x = jnp.zeros((4,), jnp.float32)
    inst = _toy(lambda a: a + 1, (), (x,), variants=[("only", (x,))])
    finds = A.audit_one_trace(A.TracedProgram(inst))
    assert any("fewer than 3 operand sets" in f.message for f in finds)


def test_transfers_host_callback_fires():
    x = jnp.zeros((4,), jnp.float32)

    def fn(a):
        jax.debug.print("a0 = {}", a[0])
        return a + 1

    finds = A.audit_transfers(A.TracedProgram(_toy(fn, (), (x,))))
    assert any("host primitive" in f.message for f in finds), finds


def test_transfers_output_arity_bound_fires():
    x = jnp.zeros((4,), jnp.float32)

    def fn(a):
        return a + 1, a * 2  # one more result than declared

    finds = A.audit_transfers(A.TracedProgram(_toy(fn, (), (x,),
                                                   expected=1)))
    assert any("declared bound" in f.message for f in finds), finds


def test_collectives_toy_psum_fires():
    """A shard_map psum over the fleet axis IS cross-shard traffic; the
    auditor must see the all-reduce in the post-SPMD HLO."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = Mesh(devs[:2], ("c",))
    fn = shard_map(lambda a: jax.lax.psum(a, "c"), mesh=mesh,
                   in_specs=P("c"), out_specs=P())
    x = jnp.arange(8, dtype=jnp.float32)
    inst = dataclasses.replace(_toy(jax.jit(fn), (), (x,)), mesh=mesh)
    # ProgramInstance.jitted must be the jitted fn itself
    finds = A.audit_collectives(A.TracedProgram(inst))
    assert any("all-reduce" in f.message for f in finds), finds


def test_collectives_skips_unsharded_programs():
    x = jnp.zeros((4,), jnp.float32)
    assert A.audit_collectives(
        A.TracedProgram(_toy(lambda a: a + 1, (), (x,)))) == []


# ---------------------------------------------------------------------------
# acceptance: the real programs hold their contracts
# ---------------------------------------------------------------------------

def test_bare_round_full_audit_clean():
    tp = A.TracedProgram(get_program("bare_round"))
    finds = (A.audit_donation(tp) + A.audit_one_trace(tp)
             + A.audit_transfers(tp))
    assert finds == [], "\n".join(str(f) for f in finds)


def test_bare_round_seeded_internal_alias_fires():
    """Reintroduce the PR-9 bug shape on the REAL round program: two
    leaves of the donated state carry sharing one buffer."""
    prog = get_program("bare_round")
    state = prog.base[0]
    seeded = dataclasses.replace(
        prog, base=(state.replace(commit=state.term),) + prog.base[1:])
    finds = A.audit_donation(A.TracedProgram(seeded))
    assert any("donated positions" in f.message for f in finds), finds


def test_chaos_epoch_one_trace_across_variants():
    """THE one-trace acceptance gate: the full chaos epoch (delay +
    crash + membership + telemetry + blackbox) lowers bit-identically
    across the base operand set and >= 3 runtime-value variants
    (crash-heavy, palette-roll, broken-models)."""
    prog = get_program("chaos_epoch")
    assert len(prog.variants) >= 3
    tp = A.TracedProgram(prog)
    finds = A.audit_one_trace(tp) + A.audit_donation(tp)
    assert finds == [], "\n".join(str(f) for f in finds)


def test_sharded_round_zero_cross_shard_collectives():
    """THE collectives acceptance gate: the steady-state sharded round
    compiles (post-SPMD) to zero cross-shard collectives — clusters are
    independent, so any collective is sharding-rule drift
    (MULTICHIP_SCALING_r05, machine-checked). Runs at a reduced Spec so
    the XLA optimization pass fits the test budget; the CLI audits the
    full bench geometry."""
    from etcd_tpu.types import Spec
    from etcd_tpu.utils.config import RaftConfig

    spec = Spec(M=3, L=4, E=1, K=1, W=1, R=1, A=1)
    cfg = RaftConfig(pre_vote=True, check_quorum=True, max_inflight=1)
    prog = sharded_program("small_sharded", False, spec=spec, cfg=cfg,
                           C=16)
    finds = A.audit_collectives(A.TracedProgram(prog))
    assert finds == [], "\n".join(str(f) for f in finds)


@pytest.mark.slow
def test_registry_sharded_rounds_full_geometry_clean():
    """Full bench-geometry sharded + shard_map rounds: zero cross-shard
    collectives. Minutes of XLA compile cold; rides the persistent
    compile cache when warm (tests/conftest.py)."""
    for name in ("sharded_round", "shard_map_round"):
        tp = A.TracedProgram(get_program(name))
        finds = A.audit_collectives(tp)
        assert finds == [], "\n".join(str(f) for f in finds)


# ---------------------------------------------------------------------------
# CLI + driver preflight exit-code contracts (subprocess)
# ---------------------------------------------------------------------------

def _run_cli(env_over, args=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_over)
    return subprocess.run(
        [sys.executable, "-m", "etcd_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)


def test_cli_bad_knob_exits_2():
    out = _run_cli({"ANALYSIS_RULES": "not-a-rule"})
    assert out.returncode == 2, (out.returncode, out.stderr)
    assert "ANALYSIS_RULES" in out.stderr


def test_cli_rejects_arguments():
    out = _run_cli({}, args=("--flag",))
    assert out.returncode == 2, (out.returncode, out.stderr)


def test_cli_lint_tier_clean_exits_0():
    out = _run_cli({"ANALYSIS_AUDIT": "0"})
    assert out.returncode == 0, (out.returncode, out.stdout, out.stderr)
    assert not out.stdout.strip()
    assert "0 finding(s)" in out.stderr


def test_cli_seeded_violation_exits_1(tmp_path):
    # ANALYSIS_PATHS targets must live under the repo root; park the
    # fixture there and remove it after
    seeded = REPO / "_analysis_seed_fixture_tmp.py"
    seeded.write_text('import os\nx = os.environ["SEEDED_KNOB"]\n')
    try:
        out = _run_cli({"ANALYSIS_AUDIT": "0",
                        "ANALYSIS_PATHS": seeded.name})
        assert out.returncode == 1, (out.returncode, out.stdout, out.stderr)
        assert "SEEDED_KNOB" in out.stdout and "env-knob" in out.stdout
    finally:
        seeded.unlink()


def test_cli_missing_path_exits_2():
    out = _run_cli({"ANALYSIS_PATHS": "no/such/file.py"})
    assert out.returncode == 2, (out.returncode, out.stderr)


def test_cli_widths_audit_tier_exits_0():
    # the cheapest audit tier: no program tracing, just the table
    # cross-check — run_smoke.sh's analysis step uses this shape
    out = _run_cli({"ANALYSIS_LINT": "0", "ANALYSIS_AUDITORS": "widths",
                    "ANALYSIS_PROGRAMS": "bare_round"})
    assert out.returncode == 0, (out.returncode, out.stdout, out.stderr)


def test_drivers_reject_unknown_arguments():
    for script in ("bench.py", "chaos_run.py"):
        out = subprocess.run(
            [sys.executable, str(REPO / script), "--not-a-flag"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
        assert out.returncode == 2, (script, out.returncode, out.stderr)
        assert "--preflight" in out.stderr


@pytest.mark.slow
def test_chaos_run_preflight_passes():
    """chaos_run --preflight audits the exact epoch program the knobs
    select and exits through the normal run (clean contract)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", CHAOS_C="64",
               CHAOS_ROUNDS="2", CHAOS_LEASE="0")
    out = subprocess.run(
        [sys.executable, str(REPO / "chaos_run.py"), "--preflight"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=580)
    assert out.returncode == 0, (out.returncode, out.stderr[-800:])
    assert "# preflight ok" in out.stderr
