"""Log-ring kernels vs a Python oracle of raftLog semantics.

Covers the behaviors of raft/log_test.go (findConflict, maybeAppend,
term/commitTo, isUpToDate) and the findConflictByTerm probe optimization
(raft/log.go:147-168), over randomized ring states including compacted
prefixes. All queries for a test are stacked and evaluated in ONE jitted
vmap call (host dispatch is the bottleneck in CI).
"""
import random

import jax
import jax.numpy as jnp
import numpy as np

from etcd_tpu.models.state import init_node
from etcd_tpu.ops import log as logops
from etcd_tpu.types import Spec

SPEC = Spec(M=3, L=16, E=4)


def mk_node(terms, snap_index=0, snap_term=0, commit=0):
    n = init_node(SPEC, 0, jnp.ones((SPEC.M,), jnp.bool_))
    lt = np.zeros((SPEC.L,), np.int32)
    ld = np.zeros((SPEC.L,), np.int32)
    for i, t in enumerate(terms):
        idx = snap_index + 1 + i
        lt[(idx - 1) % SPEC.L] = t
        ld[(idx - 1) % SPEC.L] = idx * 100 + t
    return n.replace(
        log_term=jnp.asarray(lt),
        log_data=jnp.asarray(ld),
        last_index=jnp.int32(snap_index + len(terms)),
        snap_index=jnp.int32(snap_index),
        snap_term=jnp.int32(snap_term),
        commit=jnp.int32(commit),
        applied=jnp.int32(snap_index),
    )


def stack(nodes):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *nodes)


class OracleLog:
    def __init__(self, terms, snap_index=0, snap_term=0, commit=0):
        self.terms = dict((snap_index + 1 + i, t) for i, t in enumerate(terms))
        self.snap_index, self.snap_term = snap_index, snap_term
        self.last = snap_index + len(terms)
        self.commit = commit

    def term(self, i):
        if i == self.snap_index:
            return self.snap_term, True
        if i in self.terms:
            return self.terms[i], True
        return 0, False

    def match_term(self, i, t):
        got, ok = self.term(i)
        return ok and got == t

    def find_conflict_by_term(self, index, term):
        if index > self.last:
            return index
        i = index
        while True:
            t, ok = self.term(i)
            if not ok and i < self.snap_index:
                t, ok = 0, True  # below dummy: reference returns (0, nil)
            if (ok and t <= term) or not ok:
                return i
            i -= 1

    def maybe_append(self, index, log_term, committed, ents):
        if not self.match_term(index, log_term):
            return 0, False
        lastnewi = index + len(ents)
        ci = 0
        for off, t in enumerate(ents):
            if not self.match_term(index + 1 + off, t):
                ci = index + 1 + off
                break
        if ci != 0:
            for off in range(ci - index - 1, len(ents)):
                self.terms[index + 1 + off] = ents[off]
            self.last = lastnewi
            for i in list(self.terms):
                if i > self.last:
                    del self.terms[i]
        self.commit = max(self.commit, min(committed, lastnewi))
        return lastnewi, True


def rand_log(rng):
    snap_index = rng.randrange(0, 5)
    snap_term = rng.randrange(0, 3)
    nlen = rng.randrange(0, 8)
    terms = []
    t = max(snap_term, 1)
    for _ in range(nlen):
        t += rng.randrange(0, 2)
        terms.append(t)
    commit = snap_index + rng.randrange(0, nlen + 1)
    return terms, snap_index, snap_term, commit


def host_window(n2, i):
    """Read entry terms of node state row i from numpy arrays."""
    last = int(n2.last_index[i])
    snap = int(n2.snap_index[i])
    lt = np.asarray(n2.log_term[i])
    return {j: int(lt[(j - 1) % SPEC.L]) for j in range(snap + 1, last + 1)}


def test_term_at_and_conflict_by_term():
    rng = random.Random(10)
    nodes, idxs, cterms, oracles = [], [], [], []
    for _ in range(40):
        terms, si, st, cm = rand_log(rng)
        o = OracleLog(terms, si, st, cm)
        n = mk_node(terms, si, st, cm)
        for i in range(0, si + len(terms) + 3):
            for t in range(0, 5):
                nodes.append(n)
                idxs.append(i)
                cterms.append(t)
                oracles.append(o)
    ns = stack(nodes)
    idxs_a = jnp.asarray(idxs, jnp.int32)
    ct_a = jnp.asarray(cterms, jnp.int32)

    t_got, ok_got = jax.jit(jax.vmap(lambda n, i: logops.term_at(SPEC, n, i)))(
        ns, idxs_a
    )
    fc_got = jax.jit(
        jax.vmap(lambda n, i, t: logops.find_conflict_by_term(SPEC, n, i, t))
    )(ns, idxs_a, ct_a)
    t_got, ok_got, fc_got = map(np.asarray, (t_got, ok_got, fc_got))

    for k, o in enumerate(oracles):
        ot, ook = o.term(idxs[k])
        assert bool(ok_got[k]) == ook, (k, idxs[k])
        if ook:
            assert t_got[k] == ot
        want = o.find_conflict_by_term(idxs[k], cterms[k])
        assert fc_got[k] == want, (k, idxs[k], cterms[k], fc_got[k], want)


def test_is_up_to_date():
    n = mk_node([1, 1, 2])
    cases = [(3, 2, True), (4, 2, True), (1, 3, True), (2, 2, False), (9, 1, False)]
    got = np.asarray(
        jax.vmap(lambda i, t: logops.is_up_to_date(SPEC, n, i, t))(
            jnp.asarray([c[0] for c in cases], jnp.int32),
            jnp.asarray([c[1] for c in cases], jnp.int32),
        )
    )
    assert got.tolist() == [c[2] for c in cases]


def test_maybe_append_random():
    rng = random.Random(12)
    nodes, args, oracles = [], [], []
    for _ in range(200):
        terms, si, st, cm = rand_log(rng)
        o = OracleLog(terms, si, st, cm)
        base = si + rng.randrange(0, len(terms) + 2)
        bt, _ = o.term(base)
        if rng.random() < 0.3:
            bt = rng.randrange(0, 4)
        elen = rng.randrange(0, SPEC.E + 1)
        ents, t = [], max(bt, 1)
        for _ in range(elen):
            t += rng.randrange(0, 2)
            ents.append(t)
        committed = rng.randrange(0, si + len(terms) + elen + 2)
        et = np.zeros((SPEC.E,), np.int32)
        et[:elen] = ents
        nodes.append(mk_node(terms, si, st, cm))
        args.append((base, bt, committed, elen, et, ents))
        oracles.append(o)

    ns = stack(nodes)
    base_a = jnp.asarray([a[0] for a in args], jnp.int32)
    bt_a = jnp.asarray([a[1] for a in args], jnp.int32)
    cm_a = jnp.asarray([a[2] for a in args], jnp.int32)
    ln_a = jnp.asarray([a[3] for a in args], jnp.int32)
    et_a = jnp.asarray(np.stack([a[4] for a in args]))

    fn = jax.jit(
        jax.vmap(
            lambda n, i, lt, cm, ln, et: logops.maybe_append(
                SPEC, n, i, lt, cm, ln, et, et * 0 + 7, et * 0, jnp.bool_(True)
            )
        )
    )
    n2, lastnewi, ok = fn(ns, base_a, bt_a, cm_a, ln_a, et_a)
    lastnewi, ok = np.asarray(lastnewi), np.asarray(ok)
    n2 = jax.tree.map(np.asarray, n2)

    for k, o in enumerate(oracles):
        base, bt, committed, elen, _, ents = args[k]
        want_last, want_ok = o.maybe_append(base, bt, committed, ents)
        assert bool(ok[k]) == want_ok, (k, args[k])
        if want_ok:
            assert lastnewi[k] == want_last
            assert int(n2.commit[k]) == o.commit
            assert int(n2.last_index[k]) == o.last
            assert host_window(n2, k) == o.terms, (k, args[k])


def test_count_pending_conf():
    from etcd_tpu.types import ENTRY_CONF_CHANGE

    n = mk_node([1, 1, 1, 2, 2], 0, 0, 4)
    n = n.replace(log_type=n.log_type.at[2].set(ENTRY_CONF_CHANGE))  # index 3
    assert int(logops.count_pending_conf(SPEC, n, jnp.int32(0), jnp.int32(4))) == 1
    assert int(logops.count_pending_conf(SPEC, n, jnp.int32(3), jnp.int32(5))) == 0
